"""Turbulence closures: Smagorinsky LES and Wilcox k-omega URANS.

Reference parity: the turbulence half of P22 (SURVEY.md §2.2 "newer
physics" — the reference's two-equation URANS integrator and wall-model
stack). Two closures:

- :func:`eddy_viscosity_smagorinsky` — the algebraic LES model
  ``nu_t = (Cs Delta)^2 |S|``: one fused elementwise pass over the
  strain-rate magnitude the stencil library already provides. Composes
  with any variable-viscosity integrator (``mu_eff = mu + rho nu_t``).
- :class:`KOmegaModel` — Wilcox (1988) two-equation k-omega transport,
  built ON the existing semi-implicit machinery: advection by the
  resolved velocity (upwind), variable-diffusivity diffusion
  (``nu + sigma nu_t``, explicit), production from the resolved strain
  rate, and POINTWISE-IMPLICIT dissipation (``-beta* k omega`` /
  ``-beta omega^2``), which is what makes the stiff near-wall
  sink terms unconditionally stable without a coupled solve — the
  TPU-first replacement for the reference's PETSc-implicit source
  handling.

Both keep every field cell-centered and fused-elementwise; nothing here
introduces a new solver seam.

Oracles (tests/test_turbulence.py): rigid rotation produces zero eddy
viscosity; nu_t scales as Delta^2; homogeneous decay of (k, omega)
matches the closed-form ODE solution; an under-resolved high-Re
Taylor-Green run is energy-decaying and bounded WITH the LES term.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils

Vel = Tuple[jnp.ndarray, ...]


# ---------------------------------------------------------------------------
# Smagorinsky LES
# ---------------------------------------------------------------------------

def eddy_viscosity_smagorinsky(u: Vel, dx: Sequence[float],
                               cs: float = 0.17,
                               wall_axes=None) -> jnp.ndarray:
    """Cell-centered LES eddy viscosity ``nu_t = (Cs Delta)^2 |S|``
    with ``Delta = (prod dx)^(1/dim)`` and ``|S| = sqrt(2 E:E)``.
    ``wall_axes`` switches the boundary strain layers to one-sided
    differences (no cross-wall wrap)."""
    dim = len(u)
    delta = math.prod(float(h) for h in dx) ** (1.0 / dim)
    S = stencils.strain_rate_magnitude_cc(u, dx, wall_axes=wall_axes)
    return (cs * delta) ** 2 * S



def _vc_step_with_extra_viscosity(vc, state, dt: float,
                                  mu_extra: jnp.ndarray):
    """Take one VC step with ``viscosity(phi) + mu_extra``.

    Single point of the (non-reentrant) bound-method override both
    closure drivers use: the patch lives only for the duration of this
    call (trace time under jit), and the try/finally restore keeps the
    shared integrator clean even if the step throws. Do not interleave
    two models over one integrator instance from different threads.
    """
    orig = vc.viscosity
    vc.viscosity = lambda phi: orig(phi) + mu_extra
    try:
        return vc.step(state, dt)
    finally:
        vc.viscosity = orig


class SmagorinskyINS:
    """Single-phase LES: the VC momentum machinery with
    ``mu_eff = mu + rho nu_t(u)`` refreshed from the resolved field
    every step. Constant density keeps the projection exact (FFT)."""

    def __init__(self, grid: StaggeredGrid, mu: float, rho: float = 1.0,
                 cs: float = 0.17, convective_op_type: str = "upwind",
                 wall_axes=None, dtype=jnp.float32):
        from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator

        self.grid = grid
        self.mu = float(mu)
        self.rho = float(rho)
        self.cs = float(cs)
        self.dtype = dtype
        # wall_axes: physical no-slip walls via the VC wall machinery
        # (wall-bounded LES channel/duct). The Smagorinsky nu_t strain
        # uses one-sided boundary-layer differences on wall axes.
        walls = wall_axes is not None and any(wall_axes)
        self._vc = INSVCStaggeredIntegrator(
            grid, rho0=rho, rho1=rho, mu0=mu, mu1=mu,
            convective_op_type=convective_op_type,
            reinit_interval=0, precond="mg" if walls else "fft",
            wall_axes=wall_axes, dtype=dtype)

    def initialize(self, u0: Optional[Vel] = None):
        st = self._vc.initialize(jnp.zeros(self.grid.n,
                                           dtype=self.dtype),
                                 u0_arrays=u0)
        return st

    def step(self, state, dt: float):
        """One LES step: freeze ``mu_eff`` from the current resolved
        field, then take the VC step with that viscosity."""
        mu_t = self.rho * eddy_viscosity_smagorinsky(
            state.u, self.grid.dx, self.cs,
            wall_axes=self._vc.wall_axes)
        return _vc_step_with_extra_viscosity(self._vc, state, dt, mu_t)


class TwoLevelSmagorinskyINS:
    """LES in a REFINED WINDOW (round 5, VERDICT item 3b — AMR x P22):
    the composite two-level INS core advances both levels with an
    explicit Smagorinsky eddy-stress force per level, each level's
    nu_t = (Cs Delta_level)^2 |S| from its OWN resolved strain and
    filter width (the standard grid-filter convention, so the window
    carries a smaller filter scale exactly as the reference's
    turbulence modules do when composed with
    ``IBHierarchyIntegrator``-style refinement [U]).

    Coarse-level force: the periodic VC stress divergence
    (INSVCStaggeredIntegrator._viscous_force). Fine-level force: the
    ghost-extended box twins (amr_ins.box_strain_magnitude /
    box_eddy_viscous_force — pinned exactly equal to the periodic
    operator on wrap-filled ghosts). Molecular viscosity stays in the
    composite core's semi-implicit treatment.
    """

    def __init__(self, grid: StaggeredGrid, box, mu: float,
                 rho: float = 1.0, cs: float = 0.17,
                 convective: bool = True, proj_tol: float = 1e-9,
                 proj_m: int = 24, proj_restarts: int = 8):
        from ibamr_tpu.amr_ins import TwoLevelINS
        from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator

        self.core = TwoLevelINS(grid, box, rho=rho, mu=mu,
                                convective=convective,
                                proj_tol=proj_tol, proj_m=proj_m,
                                proj_restarts=proj_restarts)
        self.grid = grid
        self.box = box
        self.rho = float(rho)
        self.cs = float(cs)
        # periodic coarse-level stress machinery (mu passed per call)
        self._vc = INSVCStaggeredIntegrator(grid, rho0=rho, rho1=rho,
                                            mu0=mu, mu1=mu,
                                            reinit_interval=0,
                                            precond="fft")

    def initialize(self, uc):
        return self.core.initialize(uc)

    def _eddy_forces(self, state):
        from ibamr_tpu.amr_ins import (box_eddy_viscous_force,
                                       box_strain_magnitude,
                                       fill_fine_ghosts_mac)

        g = self.grid
        dim = g.dim
        # coarse: periodic machinery at the coarse filter width
        mu_t_c = self.rho * eddy_viscosity_smagorinsky(
            state.uc, g.dx, self.cs)
        f_c = self._vc._viscous_force(state.uc, mu_t_c)
        # fine: ghost-extended box machinery at the fine filter width
        G = 3
        dx_f = self.core.dx_f
        uext = fill_fine_ghosts_mac(state.uf, state.uc, self.box,
                                    ghost=G)
        S = box_strain_magnitude(uext, dx_f, G, self.box.fine_n)
        delta_f = math.prod(float(h) for h in dx_f) ** (1.0 / dim)
        mu_ext = self.rho * (self.cs * delta_f) ** 2 * S
        f_f = box_eddy_viscous_force(uext, mu_ext, dx_f, G,
                                     self.box.fine_n)
        return f_c, f_f

    def step(self, state, dt: float):
        f_c, f_f = self._eddy_forces(state)
        return self.core.step(state, dt, f_c=f_c, f_f=f_f)

    def stable_dt(self, state, cfl: float = 0.5):
        """Advisory dt bound including the EXPLICIT eddy viscosity the
        class adds: the core's limit uses molecular mu only, and the
        fine level's eddy-diffusion limit rho dx_f^2/(2 dim mu_eff)
        binds whenever mu_t >> mu (code-review round 5)."""
        import jax.numpy as jnp

        from ibamr_tpu.amr_ins import (box_strain_magnitude,
                                       fill_fine_ghosts_mac)

        base = self.core.stable_dt(state, cfl)
        dim = self.grid.dim
        mu = self.core.mu
        out = base
        # coarse-level eddy limit
        mu_t_c = self.rho * eddy_viscosity_smagorinsky(
            state.uc, self.grid.dx, self.cs)
        mu_eff_c = mu + jnp.max(mu_t_c)
        out = jnp.minimum(out, self.rho * min(self.grid.dx) ** 2
                          / (2.0 * dim * mu_eff_c))
        # fine-level eddy limit
        G = 3
        dx_f = self.core.dx_f
        uext = fill_fine_ghosts_mac(state.uf, state.uc, self.box,
                                    ghost=G)
        S = box_strain_magnitude(uext, dx_f, G, self.box.fine_n)
        delta_f = math.prod(float(h) for h in dx_f) ** (1.0 / dim)
        mu_eff_f = mu + self.rho * (self.cs * delta_f) ** 2 * jnp.max(S)
        return jnp.minimum(out, self.rho * min(dx_f) ** 2
                           / (2.0 * dim * mu_eff_f))

    def max_divergence(self, state):
        return self.core.max_divergence(state)


# ---------------------------------------------------------------------------
# Wilcox k-omega
# ---------------------------------------------------------------------------

class KOmegaState(NamedTuple):
    k: jnp.ndarray        # turbulent kinetic energy (cell-centered)
    omega: jnp.ndarray    # specific dissipation rate


class KOmegaModel:
    """Wilcox (1988) k-omega closure on periodic cell-centered fields.

    ``advance`` takes one dt of both transport equations given the
    resolved MAC velocity:

      dk/dt + u.grad k  = P_k - beta* k omega
                          + div((nu + sigma* nu_t) grad k)
      dw/dt + u.grad w  = alpha (w/k) P_k - beta w^2
                          + div((nu + sigma nu_t) grad w)

    with ``nu_t = k/omega`` and ``P_k = nu_t |S|^2`` (production
    limited to ``c_lim beta* k omega`` — the standard realizability
    clip). Advection is upwind via the existing convective machinery;
    the sink terms are pointwise IMPLICIT:

      k^{n+1} = k* / (1 + dt beta* omega^n)
      w^{n+1} = w* / (1 + dt beta w^n)

    so arbitrarily stiff dissipation never bounds dt.
    """

    alpha: float = 5.0 / 9.0
    beta: float = 3.0 / 40.0
    beta_star: float = 9.0 / 100.0
    sigma: float = 0.5
    sigma_star: float = 0.5

    def __init__(self, grid: StaggeredGrid, nu: float,
                 prod_limit: float = 10.0, k_min: float = 1e-12,
                 omega_min: float = 1e-8, wall_axes=None):
        self.grid = grid
        self.nu = float(nu)
        self.prod_limit = float(prod_limit)
        self.k_min = float(k_min)
        self.omega_min = float(omega_min)
        # wall_axes[d]: no-slip walls on both sides of axis d (round
        # 4 — the wall-bounded transport the reference runs). Wall
        # treatment: k = 0 Dirichlet (one-sided half-cell diffusive
        # wall flux), omega = the Wilcox smooth-wall asymptote
        # 6 nu/(beta d^2) IMPOSED on the two near-wall layers (the
        # same rows the wall-resolved channel uses), and advective
        # wall fluxes vanish identically under the pinned-face
        # velocity convention.
        self.wall_axes = (tuple(bool(w) for w in wall_axes)
                          if wall_axes is not None
                          else (False,) * grid.dim)

    def nu_t(self, st: KOmegaState) -> jnp.ndarray:
        return st.k / jnp.maximum(st.omega, self.omega_min)

    def _adv(self, q: jnp.ndarray, u: Vel, dx) -> jnp.ndarray:
        """First-order upwind advection of a cell-centered scalar by
        the MAC velocity (flux form, periodic)."""
        flux_div = jnp.zeros_like(q)
        for d in range(len(u)):
            uf = u[d]
            q_up = jnp.where(uf > 0.0, jnp.roll(q, 1, d), q)
            flux = uf * q_up
            flux_div = flux_div + (jnp.roll(flux, -1, d) - flux) / dx[d]
        return flux_div

    def _diff(self, q: jnp.ndarray, D: jnp.ndarray, dx,
              wall_dirichlet=None) -> jnp.ndarray:
        """div(D grad q) with arithmetic face diffusivity; periodic on
        non-wall axes. On wall axes the wall-face flux is assembled
        one-sided (CONCATENATION — the lo/hi wall fluxes differ, so the
        periodic-wrap trick cannot carry them): ``wall_dirichlet``
        gives the wall value (half-cell gradient against it, e.g. k=0);
        None means zero-flux (used for omega, whose wall rows are
        imposed anyway)."""

        take = stencils.axis_slice

        out = jnp.zeros_like(q)
        for d in range(q.ndim):
            Df = 0.5 * (D + jnp.roll(D, 1, d))
            grad = (q - jnp.roll(q, 1, d)) / dx[d]
            flux = Df * grad
            if self.wall_axes[d]:
                n = q.shape[d]
                interior = take(flux, d, 1, n)
                if wall_dirichlet is None:
                    f_lo = jnp.zeros_like(take(flux, d, 0, 1))
                    f_hi = f_lo
                else:
                    wv = wall_dirichlet
                    f_lo = (take(D, d, 0, 1)
                            * 2.0 * (take(q, d, 0, 1) - wv) / dx[d])
                    f_hi = (take(D, d, n - 1, n)
                            * 2.0 * (wv - take(q, d, n - 1, n)) / dx[d])
                full = jnp.concatenate([f_lo, interior, f_hi], axis=d)
                out = out + (take(full, d, 1, n + 1)
                             - take(full, d, 0, n)) / dx[d]
            else:
                out = out + (jnp.roll(flux, -1, d) - flux) / dx[d]
        return out

    def _impose_omega_walls(self, w: jnp.ndarray) -> jnp.ndarray:
        """Overwrite the two near-wall layers of every wall axis with
        the Wilcox smooth-wall asymptote omega = 6 nu/(beta d^2)."""
        if not any(self.wall_axes):
            return w
        for d, is_wall in enumerate(self.wall_axes):
            if not is_wall:
                continue
            h = self.grid.dx[d]
            for layer in (0, 1):
                dist = (layer + 0.5) * h
                val = 6.0 * self.nu / (self.beta * dist * dist)
                idx = [slice(None)] * w.ndim
                idx[d] = slice(layer, layer + 1)
                w = w.at[tuple(idx)].set(val)
                idx[d] = slice(w.shape[d] - 1 - layer,
                               w.shape[d] - layer)
                w = w.at[tuple(idx)].set(val)
        return w

    def advance(self, st: KOmegaState, u: Vel, dt: float) -> KOmegaState:
        dx = self.grid.dx
        k = jnp.maximum(st.k, self.k_min)
        w = jnp.maximum(st.omega, self.omega_min)
        nu_t = k / w
        # wall-aware strain: one-sided boundary-layer differences on
        # wall axes so production never sees cross-wall wrapped velocity
        # gradients — consistent with the one-sided wall diffusion and
        # channel_komega's one-sided production (ADVICE round 4)
        S2 = stencils.strain_rate_magnitude_cc(
            u, dx, wall_axes=self.wall_axes) ** 2
        P_k = jnp.minimum(nu_t * S2,
                          self.prod_limit * self.beta_star * k * w)

        k_star = (k + dt * (P_k - self._adv(k, u, dx)
                            + self._diff(k, self.nu
                                         + self.sigma_star * nu_t, dx,
                                         wall_dirichlet=0.0)))
        w_star = (w + dt * (self.alpha * (w / k) * P_k
                            - self._adv(w, u, dx)
                            + self._diff(w, self.nu
                                         + self.sigma * nu_t, dx)))
        # pointwise-implicit sinks (unconditionally stable)
        k_new = k_star / (1.0 + dt * self.beta_star * w)
        w_new = self._impose_omega_walls(
            w_star / (1.0 + dt * self.beta * w))
        return KOmegaState(k=jnp.maximum(k_new, self.k_min),
                           omega=jnp.maximum(w_new, self.omega_min))


class KOmegaINS:
    """URANS driver: resolved INS (VC machinery, constant density) with
    ``mu_eff = mu + rho nu_t`` from a co-advanced k-omega pair — the
    analog of the reference's two-equation turbulence hierarchy
    integrator, as one jittable composite step."""

    def __init__(self, grid: StaggeredGrid, mu: float, rho: float = 1.0,
                 convective_op_type: str = "upwind",
                 wall_axes=None, dtype=jnp.float32):
        from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator

        self.grid = grid
        self.mu = float(mu)
        self.rho = float(rho)
        self.dtype = dtype
        # wall_axes: wall-bounded URANS (round 4) — no-slip momentum
        # walls via the VC wall machinery, k = 0 / omega-asymptote
        # walls in the transport model
        walls = wall_axes is not None and any(wall_axes)
        self.model = KOmegaModel(grid, nu=mu / rho,
                                 wall_axes=wall_axes)
        self._vc = INSVCStaggeredIntegrator(
            grid, rho0=rho, rho1=rho, mu0=mu, mu1=mu,
            convective_op_type=convective_op_type,
            reinit_interval=0, precond="mg" if walls else "fft",
            wall_axes=wall_axes, dtype=dtype)

    def initialize(self, u0: Optional[Vel] = None,
                   k0: float = 1e-4, omega0: float = 1.0):
        ins = self._vc.initialize(jnp.zeros(self.grid.n,
                                            dtype=self.dtype),
                                  u0_arrays=u0)
        turb = KOmegaState(
            k=jnp.full(self.grid.n, k0, dtype=self.dtype),
            omega=jnp.full(self.grid.n, omega0, dtype=self.dtype))
        return ins, turb

    def step(self, ins_state, turb: KOmegaState, dt: float):
        mu_t = self.rho * self.model.nu_t(turb)
        ins_new = _vc_step_with_extra_viscosity(self._vc, ins_state,
                                                dt, mu_t)
        turb_new = self.model.advance(turb, ins_new.u, dt)
        return ins_new, turb_new


# ---------------------------------------------------------------------------
# Wall-resolved k-omega channel (the wall-bounded URANS validation case)
# ---------------------------------------------------------------------------

class ChannelProfile(NamedTuple):
    """Steady fully-developed channel solution in plus units."""
    y_plus: jnp.ndarray      # cell-center wall distances
    u_plus: jnp.ndarray      # mean velocity / u_tau
    k_plus: jnp.ndarray      # TKE / u_tau^2
    omega_plus: jnp.ndarray  # omega nu / u_tau^2
    nu_t_plus: jnp.ndarray   # eddy viscosity / nu


def _stretched_faces(re_tau: float, n: int, dy0: float) -> "jnp.ndarray":
    """Geometric face distribution on [0, re_tau] with first spacing
    ``dy0`` (host-side: solves the stretching ratio by bisection)."""
    import numpy as np

    def span(r):
        if abs(r - 1.0) < 1e-12:
            return n * dy0
        return dy0 * (r ** n - 1.0) / (r - 1.0)

    lo, hi = 1.0, 1.5
    while span(hi) < re_tau:
        hi *= 1.02
    for _ in range(100):
        mid = 0.5 * (lo + hi)
        if span(mid) < re_tau:
            lo = mid
        else:
            hi = mid
    r = 0.5 * (lo + hi)
    dys = dy0 * r ** np.arange(n)
    faces = np.concatenate([[0.0], np.cumsum(dys)])
    faces *= re_tau / faces[-1]
    return jnp.asarray(faces)


def channel_komega(re_tau: float = 590.0, n: int = 96,
                   dy0_plus: float = 0.4, iters: int = 40000,
                   cfl: float = 0.3) -> ChannelProfile:
    """Wall-RESOLVED Wilcox k-omega solution of the fully-developed
    turbulent channel — the wall-bounded validation the reference runs
    its URANS stack against (SURVEY.md P22 [U]; VERDICT round 3, weak
    #5: 'no wall-bounded channel/log-law case').

    Everything is nondimensionalized in plus units (nu = 1, u_tau = 1,
    half-height = re_tau): the steady momentum balance is

        d/dy[(1 + nu_t) du/dy] = -1/re_tau ,

    i.e. total stress (1+nu_t) du/dy = 1 - y/re_tau, with the k/omega
    transport of :class:`KOmegaModel` (same constants, same pointwise-
    implicit sinks) reduced to 1D on a geometrically-stretched grid
    resolving y+ ~ dy0_plus at the wall. Boundary conditions: u = 0 and
    k = 0 at the wall via odd-reflection ghosts, the Wilcox smooth-wall
    asymptote omega = 6 nu / (beta y^2) IMPOSED on the two near-wall
    cells, and symmetry (even reflection) at the centerline. Marched to
    steady state with LOCAL pseudo-time steps (diffusive CFL per cell —
    the standard steady-RANS accelerator); the whole march is one
    lax.fori_loop of fused 1D ops.

    Returns the :class:`ChannelProfile` whose u+ the tests pin against
    u+ = y+ in the viscous sublayer and the log law
    u+ = ln(y+)/0.41 + 5.0 in the inertial layer.
    """
    alpha = KOmegaModel.alpha
    beta = KOmegaModel.beta
    beta_star = KOmegaModel.beta_star
    sigma = KOmegaModel.sigma
    sigma_star = KOmegaModel.sigma_star

    faces = _stretched_faces(re_tau, n, dy0_plus)
    yc = 0.5 * (faces[1:] + faces[:-1])
    dyc = faces[1:] - faces[:-1]               # cell widths
    dyf = yc[1:] - yc[:-1]                     # center-to-center

    omega_wall = 6.0 / (beta * yc ** 2)        # smooth-wall asymptote

    def interior_flux(q, D_face):
        """Fluxes D dq/dy at the n-1 interior faces."""
        return D_face * (q[1:] - q[:-1]) / dyf

    def div_flux(flux_int, flux_wall, flux_top):
        full = jnp.concatenate([jnp.asarray([flux_wall]), flux_int,
                                jnp.asarray([flux_top])])
        return (full[1:] - full[:-1]) / dyc

    def face_mean(D):
        return 0.5 * (D[1:] + D[:-1])

    def body(_, st):
        u, k, w = st
        w = jnp.maximum(w, 1e-10)
        k = jnp.maximum(k, 0.0)
        nu_t = k / w
        # momentum: D = 1 + nu_t; wall flux from the u=0 Dirichlet
        # (half-cell one-sided), symmetry flux 0 at the top
        Du = 1.0 + nu_t
        fw_u = Du[0] * (u[0] - 0.0) / (yc[0] - 0.0)
        lap_u = div_flux(interior_flux(u, face_mean(Du)), fw_u, 0.0)
        # production uses the cell-centered gradient (one-sided at the
        # wall cell, central elsewhere)
        g_int = (u[2:] - u[:-2]) / (yc[2:] - yc[:-2])
        g0 = u[0] / yc[0]
        gN = (u[-1] - u[-2]) / dyf[-1]
        grad_u = jnp.concatenate([jnp.asarray([g0]), g_int,
                                  jnp.asarray([gN])])
        P = jnp.minimum(nu_t * grad_u ** 2,
                        10.0 * beta_star * k * w)

        Dk = 1.0 + sigma_star * nu_t
        fw_k = Dk[0] * (k[0] - 0.0) / yc[0]        # k = 0 at the wall
        lap_k = div_flux(interior_flux(k, face_mean(Dk)), fw_k, 0.0)

        Dw = 1.0 + sigma * nu_t
        # omega's wall rows are IMPOSED; no wall flux needed
        lap_w = div_flux(interior_flux(w, face_mean(Dw)), 0.0, 0.0)

        # local pseudo-time steps (diffusive CFL)
        dt_u = cfl * dyc ** 2 / Du
        dt_s = cfl * dyc ** 2 / jnp.maximum(Dk, Dw)

        u_new = u + dt_u * (lap_u + 1.0 / re_tau)
        k_star = k + dt_s * (P + lap_k)
        w_star = w + dt_s * (alpha * (w / jnp.maximum(k, 1e-12)) * P
                             + lap_w)
        k_new = k_star / (1.0 + dt_s * beta_star * w)
        w_new = w_star / (1.0 + dt_s * beta * w)
        # impose the smooth-wall omega asymptote on the 2 wall cells
        w_new = w_new.at[:2].set(omega_wall[:2])
        return (u_new, jnp.maximum(k_new, 0.0),
                jnp.maximum(w_new, 1e-10))

    # initial guess: log-law-ish u, modest k, the wall asymptote for w
    u0 = jnp.minimum(yc, jnp.log(jnp.maximum(yc, 1.0)) / 0.41 + 5.0)
    k0 = 0.1 * jnp.ones_like(yc)
    w0 = omega_wall
    u, k, w = jax.lax.fori_loop(0, iters, body, (u0, k0, w0))
    return ChannelProfile(y_plus=yc, u_plus=u, k_plus=k, omega_plus=w,
                          nu_t_plus=k / jnp.maximum(w, 1e-10))
