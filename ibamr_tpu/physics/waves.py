"""Wave generation and damping (relaxation) zones for two-phase INS.

Reference parity: the numerical-wave-tank half of P22 (SURVEY.md §2.2
"newer physics" — ``FirstOrderStokesWaveGenerator``,
``SecondOrderStokesWaveGenerator``, ``IrregularWaveGenerator``,
``WaveGenerationFunctions`` / ``WaveDampingFunctions``): waves enter the
domain through a GENERATION zone where the solution is relaxed toward an
analytic incident-wave state, and leave through a DAMPING zone relaxed
toward still water, so the working region sees clean periodic waves with
no reflections.

TPU-first redesign: the relaxation method is a pure post-step blend

    q <- (1 - w(x)) q + w(x) q_target,      w in [0, 1]

with the waves2Foam exponential ramp for w — one fused elementwise pass
per field per step, nothing implicit, jit/scan-native, and identical
under GSPMD sharding (w is a static field). Targets come from Stokes
wave theory evaluated lazily at (x, z, t); irregular seas are a
superposition of linear components (vmapped, MXU-batched).

Level-set convention matches ``physics.level_set`` /
``integrators.ins_vc``: phi < 0 is phase 0 (water), phi > 0 phase 1
(air), so phi_target = z - elevation(x, t).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid

Vel = Tuple[jnp.ndarray, ...]


# ---------------------------------------------------------------------------
# Stokes wave targets
# ---------------------------------------------------------------------------

class StokesWave(NamedTuple):
    """One incident wave train (x-propagating).

    ``order=2`` adds the second-order Stokes corrections (bound
    harmonic); dispersion uses the finite-depth linear relation
    ``omega^2 = g k tanh(k depth)``.
    """
    amplitude: float          # linear amplitude a (H/2)
    wavelength: float
    depth: float              # still-water depth
    still_level: float        # z of the undisturbed free surface
    gravity: float = 9.81
    order: int = 1
    phase: float = 0.0

    @property
    def k(self) -> float:
        return 2.0 * math.pi / self.wavelength

    @property
    def omega(self) -> float:
        return math.sqrt(self.gravity * self.k
                         * math.tanh(self.k * self.depth))

    def scaled(self, s) -> "StokesWave":
        """Amplitude-scaled copy (the soft-start hook; works traced)."""
        return self._replace(amplitude=self.amplitude * s)

    def elevation(self, x: jnp.ndarray, t) -> jnp.ndarray:
        """Free-surface elevation about ``still_level``."""
        k, om, a = self.k, self.omega, self.amplitude
        th = k * x - om * t + self.phase
        eta = a * jnp.cos(th)
        if self.order >= 2:
            kd = k * self.depth
            coth = 1.0 / math.tanh(kd)
            eta = eta + (a * a * k * coth / 4.0
                         * (3.0 * coth * coth - 1.0)
                         * jnp.cos(2.0 * th))
        return eta

    def velocity(self, x: jnp.ndarray, z: jnp.ndarray, t,
                 component: int) -> jnp.ndarray:
        """Water-particle velocity (0: horizontal, 1: vertical) from
        finite-depth Stokes theory, evaluated at height z (clipped to
        the water column so the exponential tail stays bounded)."""
        k, om, a = self.k, self.omega, self.amplitude
        g0 = self.gravity
        th = k * x - om * t + self.phase
        zz = jnp.clip(z - self.still_level, -self.depth,
                      2.0 * self.amplitude)
        kd = k * self.depth
        # cosh/sinh ratios, numerically stable form
        ch = jnp.cosh(k * (zz + self.depth)) / math.cosh(kd)
        sh = jnp.sinh(k * (zz + self.depth)) / math.cosh(kd)
        if component == 0:
            u = a * g0 * k / om * ch * jnp.cos(th)
        else:
            u = a * g0 * k / om * sh * jnp.sin(th)
        if self.order >= 2:
            c2 = 0.75 * a * a * om * k
            sh4 = math.sinh(kd) ** 4
            ch2 = jnp.cosh(2.0 * k * (zz + self.depth)) / sh4
            sh2 = jnp.sinh(2.0 * k * (zz + self.depth)) / sh4
            if component == 0:
                u = u + c2 * ch2 * jnp.cos(2.0 * th)
            else:
                u = u + c2 * sh2 * jnp.sin(2.0 * th)
        return u


class IrregularSea(NamedTuple):
    """Superposition of linear components (the IrregularWaveGenerator
    analog): arrays of per-component amplitude/wavelength/phase over a
    shared depth/still level. All evaluations are ONE broadcast sum over
    a leading component axis (no Python loop, trace-safe, MXU/VPU
    batched)."""
    amplitudes: jnp.ndarray
    wavelengths: jnp.ndarray
    phases: jnp.ndarray
    depth: float
    still_level: float
    gravity: float = 9.81

    def _karr(self, ndim: int):
        """Per-component (k, omega, a, phase) reshaped to broadcast
        against an ndim-dimensional field on a leading axis."""
        shp = (-1,) + (1,) * ndim
        k = (2.0 * math.pi / jnp.asarray(self.wavelengths)).reshape(shp)
        om = jnp.sqrt(self.gravity * k * jnp.tanh(k * self.depth))
        a = jnp.asarray(self.amplitudes).reshape(shp)
        ph = jnp.asarray(self.phases).reshape(shp)
        return k, om, a, ph

    @property
    def omega(self) -> float:
        """Smallest component frequency (longest period) — what soft
        starts and probe windows should be sized against."""
        import numpy as np
        k = 2.0 * math.pi / np.asarray(self.wavelengths)
        return float(np.sqrt(self.gravity * k
                             * np.tanh(k * self.depth)).min())

    def scaled(self, s) -> "IrregularSea":
        return self._replace(amplitudes=jnp.asarray(self.amplitudes)
                             * s)

    def elevation(self, x: jnp.ndarray, t) -> jnp.ndarray:
        x = jnp.asarray(x)
        k, om, a, ph = self._karr(x.ndim)
        th = k * x[None] - om * t + ph
        return jnp.sum(a * jnp.cos(th), axis=0)

    def velocity(self, x, z, t, component: int) -> jnp.ndarray:
        x = jnp.asarray(x)
        z = jnp.asarray(z)
        k, om, a, ph = self._karr(max(x.ndim, z.ndim))
        th = k * x[None] - om * t + ph
        zz = jnp.clip(z - self.still_level, -self.depth,
                      2.0 * jnp.max(jnp.asarray(self.amplitudes)))
        kd = k * self.depth
        ch = jnp.cosh(k * (zz[None] + self.depth)) / jnp.cosh(kd)
        sh = jnp.sinh(k * (zz[None] + self.depth)) / jnp.cosh(kd)
        if component == 0:
            comp = ch * jnp.cos(th)
        else:
            comp = sh * jnp.sin(th)
        return jnp.sum(a * self.gravity * k / om * comp, axis=0)


# ---------------------------------------------------------------------------
# relaxation zones
# ---------------------------------------------------------------------------

def _ramp(sigma: jnp.ndarray) -> jnp.ndarray:
    """waves2Foam exponential relaxation weight: 1 at the outer end of
    the zone (sigma=1), 0 at the inner (working-region) end (sigma=0),
    smooth at both."""
    s = jnp.clip(sigma, 0.0, 1.0)
    return (jnp.exp(s ** 3.5) - 1.0) / (math.e - 1.0)


class RelaxationZone(NamedTuple):
    """Static relaxation weights on cells and faces.

    ``strength`` rescales the blend per step; targets are blended as
    q <- (1-w) q + w q_target with w = strength * ramp.
    """
    w_cc: jnp.ndarray         # (n...) cell weight
    w_face: Vel               # per-component face weights
    kind: str                 # "generation" | "damping"


def make_zone(grid: StaggeredGrid, x_start: float, x_end: float,
              kind: str, outer: str, strength: float = 1.0,
              dtype=jnp.float32) -> RelaxationZone:
    """Build a zone over ``[x_start, x_end]`` along axis 0. ``outer``
    names which side touches the domain boundary ("lo" for a left
    generation zone, "hi" for a right damping beach)."""
    assert kind in ("generation", "damping")
    assert outer in ("lo", "hi")
    width = float(x_end) - float(x_start)

    def weight_at(x):
        sigma = (x - x_start) / width
        if outer == "lo":
            sigma = 1.0 - sigma
        return strength * _ramp(sigma) * ((x >= x_start) & (x <= x_end))

    # staggering convention delegated to grid.py's 1-D helpers
    shape = (grid.n[0],) + (1,) * (grid.dim - 1)
    xc = grid.cell_coords_1d(0, dtype)
    w_cc = weight_at(xc).reshape(shape).astype(dtype) \
        * jnp.ones(grid.n, dtype=dtype)
    w_face = []
    for d in range(grid.dim):
        xf = (grid.face_coords_1d(0, dtype) if d == 0
              else grid.cell_coords_1d(0, dtype))
        w_face.append(weight_at(xf).reshape(shape).astype(dtype)
                      * jnp.ones(grid.n, dtype=dtype))
    return RelaxationZone(w_cc=w_cc, w_face=tuple(w_face), kind=kind)


def cell_coords(grid: StaggeredGrid, dtype=jnp.float32):
    """FULL-shape cell-center coordinates (some callers hand these
    straight to ``initialize`` as phi0, which needs the full grid
    shape); staggering convention delegated to grid.py."""
    return tuple(jnp.broadcast_to(c, grid.n)
                 for c in grid.cell_centers(dtype))


def _face_coords(grid: StaggeredGrid, d: int, dtype=jnp.float32):
    return tuple(jnp.broadcast_to(c, grid.n)
                 for c in grid.face_centers(d, dtype))


def wave_targets(grid: StaggeredGrid, wave, t, dtype=jnp.float32):
    """(phi_target, u_target) of the incident wave state at time t.
    phi = z - (still_level + elevation); velocities from wave theory in
    the water, 0 in the air phase (the blend only matters in a band
    around the interface and below)."""
    zax = grid.dim - 1
    cc = cell_coords(grid, dtype)
    eta = wave.elevation(cc[0], t)
    phi_t = cc[zax] - (wave.still_level + eta)
    from ibamr_tpu.physics.level_set import heaviside
    eps = 1.5 * grid.dx[zax]
    u_t = []
    for d in range(grid.dim):
        fc = _face_coords(grid, d, dtype)
        if d == 0 or d == zax:
            comp = 0 if d == 0 else 1
            uf = wave.velocity(fc[0], fc[zax], t, comp)
            eta_f = wave.elevation(fc[0], t)
            # taper by the SMOOTH water fraction (waves2Foam's
            # alpha-weighted target): a sharp air cutoff would inject
            # an O(u_wave) shear/divergence spike at the interface on
            # every relaxation blend, which destabilizes the 1000:1
            # density interface (round-3 calibration)
            phi_f = fc[zax] - (wave.still_level + eta_f)
            water = 1.0 - heaviside(phi_f, eps)
            u_t.append((uf * water).astype(dtype))
        else:
            u_t.append(jnp.zeros(grid.n, dtype=dtype))
    return phi_t.astype(dtype), tuple(u_t)


def still_targets(grid: StaggeredGrid, still_level: float,
                  dtype=jnp.float32):
    """Still-water targets for a damping beach."""
    zax = grid.dim - 1
    cc = cell_coords(grid, dtype)
    phi_t = (cc[zax] - still_level).astype(dtype)
    return phi_t, tuple(jnp.zeros(grid.n, dtype=dtype)
                        for _ in range(grid.dim))


def apply_zone(phi: jnp.ndarray, u: Vel, zone: RelaxationZone,
               phi_target: jnp.ndarray, u_target: Vel):
    """One relaxation blend of (phi, u) toward the targets."""
    phi_new = phi + zone.w_cc * (phi_target - phi)
    u_new = tuple(ud + wf * (ut - ud)
                  for ud, wf, ut in zip(u, zone.w_face, u_target))
    return phi_new, u_new


class WaveTank:
    """Convenience driver: a two-phase VC integrator plus a generation
    zone at the left and a damping beach at the right (the standard NWT
    layout). ``step`` = integrator step -> generation blend -> damping
    blend; fully jittable/scannable.

    ``floor``/``lid`` add Brinkman-penalized solid slabs at the bottom
    and top of the (periodic) domain: the density jump at the vertical
    wrap — water at z_lo wrapping onto air at z_up, heavy-over-light —
    is Rayleigh–Taylor unstable once a wave perturbs it; clamping the
    velocity inside the slabs pins that interface exactly the way a
    physical tank bottom and lid do (same composition the reference
    builds from wall BCs + its wave zones).
    """

    def __init__(self, integ, wave, gen_zone: RelaxationZone,
                 damp_zone: Optional[RelaxationZone] = None,
                 floor: float = 0.0, lid: float = 0.0,
                 end_wall: float = 0.0, eta_solid: float = 1e-3,
                 ramp_time: Optional[float] = None):
        self.integ = integ
        self.wave = wave
        self.gen = gen_zone
        self.damp = damp_zone
        # soft start (waves2Foam Tsoft): an impulsively started
        # generation zone radiates a breaking transient several times
        # the target amplitude; ramp the incident amplitude over ~two
        # periods by default
        if ramp_time is None:
            ramp_time = 2.0 * 2.0 * math.pi / wave.omega
        self.ramp_time = float(ramp_time)
        g = integ.grid
        zax = g.dim - 1
        self._solid = None
        if floor > 0.0 or lid > 0.0 or end_wall > 0.0:
            z_floor = g.x_lo[zax] + floor
            z_lid = g.x_up[zax] - lid
            x_wall = g.x_up[0] - end_wall
            chi = []
            for d in range(g.dim):
                fc = _face_coords(g, d, integ.dtype)
                zf = fc[zax]
                solid = jnp.zeros(g.n, dtype=integ.dtype)
                if floor > 0.0:
                    solid = jnp.maximum(solid, (zf < z_floor).astype(
                        integ.dtype))
                if lid > 0.0:
                    solid = jnp.maximum(solid, (zf > z_lid).astype(
                        integ.dtype))
                if end_wall > 0.0:
                    # a solid slab at the x-wrap: the tank gets physical
                    # end walls, killing the resonant pumping of the
                    # domain's free periodic mode (an x-periodic tank is
                    # a resonator — the generation zone drives it to
                    # breaking; a real NWT is wall-bounded)
                    solid = jnp.maximum(solid, (fc[0] > x_wall).astype(
                        integ.dtype))
                chi.append(solid)
            self._solid = tuple(chi)
        self.eta_solid = float(eta_solid)

    def step(self, state, dt: float):
        g = self.integ.grid
        st = self.integ.step(state, dt)
        t_new = st.t
        phi, u = st.phi, st.u
        if self.ramp_time > 0.0:
            r = jnp.clip(t_new / self.ramp_time, 0.0, 1.0)
            soft = 0.5 * (1.0 - jnp.cos(math.pi * r))
            wv = self.wave.scaled(soft)
        else:
            wv = self.wave
        phi_t, u_t = wave_targets(g, wv, t_new,
                                  dtype=self.integ.dtype)
        if self._solid is not None:
            # never ask the relaxation to drive flow inside the solid
            # slabs — the penalty clamp would fight it every step and
            # the residual shear feeds the wrap-plane RT instability
            u_t = tuple(ut * (1.0 - chi)
                        for ut, chi in zip(u_t, self._solid))
        phi, u = apply_zone(phi, u, self.gen, phi_t, u_t)
        # the conservative integrator transports rho as its OWN state:
        # relax it toward the density of the target interface, or zone
        # blending desynchronizes rho from phi and buoyancy blows up
        rho = getattr(st, "rho", None)
        if rho is not None:
            rho = rho + self.gen.w_cc * (self.integ.density(phi_t) - rho)
        if self.damp is not None:
            phi_s, u_s = still_targets(g, self.wave.still_level,
                                       dtype=self.integ.dtype)
            phi, u = apply_zone(phi, u, self.damp, phi_s, u_s)
            if rho is not None:
                rho = rho + self.damp.w_cc * (self.integ.density(phi_s)
                                              - rho)
        if self._solid is not None:
            # diagonal implicit Brinkman clamp (physics.brinkman) + a
            # VC re-projection to keep div u = 0
            u = tuple(ud / (1.0 + dt * chi / self.eta_solid)
                      for ud, chi in zip(u, self._solid))
            rho_cc = self.integ.density(phi) if rho is None else rho
            # match the integrator's own projection convention: the
            # conservative form projects with ARITHMETIC face densities
            # (its momentum telescoping identity needs it), the plain
            # form with harmonic (ins_vc.project_vc docstring)
            rule = "arithmetic" if rho is not None else "harmonic"
            u, _ = self.integ.project_vc(u, rho_cc, dt, face_rule=rule)
        wall_axes = getattr(self.integ, "wall_axes", None)
        if wall_axes is not None and any(wall_axes):
            # a wall-bounded integrator (the PHYSICAL floor/end-wall
            # alternative to the Brinkman slabs): re-pin the wall-normal
            # faces the zone blending may have touched
            u = tuple(self.integ._pin_normal(c, d)
                      for d, c in enumerate(u))
        st = st._replace(phi=phi, u=u)
        if rho is not None:
            st = st._replace(rho=rho)
        return st

    def elevation_probe(self, state, x_index: int) -> jnp.ndarray:
        """Free-surface height above still level at one x column (from
        the level set's zero crossing via the smoothed indicator)."""
        g = self.integ.grid
        zax = g.dim - 1
        dz = g.dx[zax]
        col = state.phi[x_index] if g.dim == 2 else \
            state.phi[x_index, g.n[1] // 2]
        from ibamr_tpu.physics.level_set import heaviside
        water = 1.0 - heaviside(col, 1.5 * dz)
        h = jnp.sum(water) * dz
        return g.x_lo[zax] + h - self.wave.still_level
