"""Level-set machinery: signed-distance maintenance + interface calculus.

Reference parity: ``src/level_set/`` (P22, SURVEY.md §2.2 —
``RelaxationLSMethod``, ``FastSweepingLSMethod``, ``LevelSetUtilities``).
The reference maintains signed-distance functions for interface-capturing
(multiphase flow, Brinkman penalization) with two reinitialization
engines; both are rebuilt TPU-first:

- :func:`reinitialize` — the RelaxationLSMethod analog: pseudo-time
  relaxation of |grad phi| -> 1 (Sussman-Smereka-Osher) with Godunov
  upwinding and the Russo-Smereka subcell fix pinning the zero level.
  A fixed iteration count under ``lax.fori_loop`` — fully jittable.
- :func:`fast_sweeping_distance` — the FastSweepingLSMethod analog:
  the reference's Gauss-Seidel ordered sweeps are inherently serial, so
  the rebuild runs the SAME Eikonal update as Jacobi iterations
  (whole-array rolls): each iteration propagates the solution one cell,
  like one sweep front, but every cell updates in parallel on the VPU.

Interface calculus (LevelSetUtilities analog): smoothed Heaviside/delta,
phase volume, curvature — the ingredients the multiphase integrator
(:mod:`ibamr_tpu.integrators.ins_vc`) consumes.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid


# -- smoothed interface functions -------------------------------------------

def heaviside(phi: jnp.ndarray, eps: float) -> jnp.ndarray:
    """Smoothed Heaviside H_eps(phi) over a band of half-width eps."""
    core = 0.5 * (1.0 + phi / eps
                  + jnp.sin(math.pi * phi / eps) / math.pi)
    return jnp.where(phi < -eps, 0.0, jnp.where(phi > eps, 1.0, core))


def delta(phi: jnp.ndarray, eps: float) -> jnp.ndarray:
    """Smoothed interface delta (derivative of :func:`heaviside`)."""
    core = 0.5 / eps * (1.0 + jnp.cos(math.pi * phi / eps))
    return jnp.where(jnp.abs(phi) > eps, 0.0, core)


def phase_volume(phi: jnp.ndarray, grid: StaggeredGrid,
                 eps: float) -> jnp.ndarray:
    """Volume of the phi < 0 phase (smoothed)."""
    return jnp.sum(1.0 - heaviside(phi, eps)) * grid.cell_volume


def _central_grad(phi: jnp.ndarray, d: int, dx_d: float,
                  wall: bool) -> jnp.ndarray:
    """Delegates to the shared ops.stencils.central_grad."""
    from ibamr_tpu.ops.stencils import central_grad

    return central_grad(phi, d, dx_d, wall)


def gradient_norm(phi: jnp.ndarray, dx: Sequence[float],
                  wall_axes=None) -> jnp.ndarray:
    """|grad phi| with central differences (diagnostic); one-sided at
    walls when ``wall_axes`` marks an axis wall-bounded."""
    if wall_axes is None:
        wall_axes = (False,) * phi.ndim
    out = jnp.zeros_like(phi)
    for d in range(phi.ndim):
        g = _central_grad(phi, d, dx[d], wall_axes[d])
        out = out + g * g
    return jnp.sqrt(out)


def curvature(phi: jnp.ndarray, dx: Sequence[float],
              wall_axes=None) -> jnp.ndarray:
    """Interface curvature kappa = div(grad phi / |grad phi|);
    one-sided wall differences when ``wall_axes`` is given."""
    dim = phi.ndim
    if wall_axes is None:
        wall_axes = (False,) * dim
    grads = [_central_grad(phi, d, dx[d], wall_axes[d])
             for d in range(dim)]
    mag = jnp.sqrt(sum(g * g for g in grads) + 1e-12)
    kap = jnp.zeros_like(phi)
    for d in range(dim):
        nd = grads[d] / mag
        kap = kap + _central_grad(nd, d, dx[d], wall_axes[d])
    return kap


# -- Godunov Hamiltonian -----------------------------------------------------

def _godunov_grad_mag(phi: jnp.ndarray, dx: Sequence[float],
                      sgn: jnp.ndarray,
                      wall_axes=None) -> jnp.ndarray:
    """Godunov-upwinded |grad phi| for the reinitialization equation.
    ``wall_axes[d]`` zeroes the cross-wall (wrap) one-sided differences
    of axis d — the even-reflection ghost for a walled domain."""
    dim = phi.ndim
    if wall_axes is None:
        wall_axes = (False,) * dim
    acc = jnp.zeros_like(phi)
    for d in range(dim):
        dm = (phi - jnp.roll(phi, 1, d)) / dx[d]     # backward
        dp = (jnp.roll(phi, -1, d) - phi) / dx[d]    # forward
        if wall_axes[d]:
            from ibamr_tpu.ops.stencils import wall_boundary_masks

            is_lo, is_hi = wall_boundary_masks(phi.shape, d)
            dm = jnp.where(is_lo, 0.0, dm)
            dp = jnp.where(is_hi, 0.0, dp)
        # moving outward from the interface: use the upwind choice
        a = jnp.where(sgn >= 0,
                      jnp.maximum(jnp.maximum(dm, 0.0) ** 2,
                                  jnp.minimum(dp, 0.0) ** 2),
                      jnp.maximum(jnp.minimum(dm, 0.0) ** 2,
                                  jnp.maximum(dp, 0.0) ** 2))
        acc = acc + a
    return jnp.sqrt(acc)


def _interface_cells(phi: jnp.ndarray, wall_axes=None) -> jnp.ndarray:
    """Mask of cells whose stencil straddles the zero level. With
    ``wall_axes``, cross-wall (wrap) sign changes are NOT interface
    cells — e.g. a pool's floor row against the air above the domain
    top must not be relaxed by the subcell fix."""
    if wall_axes is None:
        wall_axes = (False,) * phi.ndim
    near = jnp.zeros_like(phi, dtype=bool)
    for d in range(phi.ndim):
        lo = phi * jnp.roll(phi, 1, d) < 0.0
        hi = phi * jnp.roll(phi, -1, d) < 0.0
        if wall_axes[d]:
            from ibamr_tpu.ops.stencils import wall_boundary_masks

            is_lo, is_hi = wall_boundary_masks(phi.shape, d)
            lo = lo & ~is_lo
            hi = hi & ~is_hi
        near = near | lo | hi
    return near


def reinitialize(phi: jnp.ndarray, dx: Sequence[float],
                 iters: int = 40,
                 dtau: float = None,
                 wall_axes=None) -> jnp.ndarray:
    """Relaxation reinitialization toward a signed-distance function.

    d phi / d tau = S(phi_0) (1 - |grad phi|), Godunov upwinding, with
    the Russo-Smereka subcell fix in interface cells: there the update
    drives phi toward (D * sgn) where D is the subcell distance estimate
    phi_0 / |grad phi_0|, so the zero level set does not drift.
    ``wall_axes`` marks wall-bounded axes (even-reflection differences
    at the walls instead of the periodic wrap).
    """
    h = min(dx)
    if dtau is None:
        dtau = 0.5 * h
    phi0 = phi
    sgn = phi0 / jnp.sqrt(phi0 * phi0 + h * h)      # smoothed (far field)
    sgn_hard = jnp.where(phi0 >= 0.0, 1.0, -1.0)    # true sign (subcell fix)
    near = _interface_cells(phi0, wall_axes=wall_axes)
    g0 = jnp.maximum(gradient_norm(phi0, dx, wall_axes=wall_axes), 1e-8)
    D = phi0 / g0                                   # subcell distance

    def body(_, p):
        gm = _godunov_grad_mag(p, dx, sgn, wall_axes=wall_axes)
        upd_far = p + dtau * sgn * (1.0 - gm)
        # Russo-Smereka: relax interface cells to the frozen subcell
        # distance. The TRUE sign is essential here — the smoothed sgn
        # would rescale the fixed point to D/sgn (round-2 fix).
        upd_near = p - dtau / h * (sgn_hard * jnp.abs(p) - D)
        return jnp.where(near, upd_near, upd_far)

    return jax.lax.fori_loop(0, iters, body, phi)


def fast_sweeping_distance(phi: jnp.ndarray, dx: Sequence[float],
                           iters: int = None) -> jnp.ndarray:
    """Signed distance by Jacobi-iterated Eikonal updates.

    The FastSweepingLSMethod analog: the frozen interface band keeps its
    subcell distances (phi / |grad phi|); every other cell repeatedly
    applies the upwind Eikonal update  u = min_neighbors + solve of
    sum_d ((u - a_d)/h_d)^2 = 1  until the front has swept the domain
    (``iters`` defaults to the max grid extent, one cell per pass —
    each Jacobi pass is one whole-array VPU kernel instead of the
    reference's serial Gauss-Seidel sweeps).
    """
    dim = phi.ndim
    if iters is None:
        iters = int(max(phi.shape))
    near = _interface_cells(phi)
    g0 = jnp.maximum(gradient_norm(phi, dx), 1e-8)
    d_band = jnp.abs(phi) / g0
    sgn = jnp.where(phi >= 0, 1.0, -1.0)
    big = float(sum(n * h for n, h in zip(phi.shape, dx)))
    u0 = jnp.where(near, d_band, big)

    def eikonal_update(u):
        # per-axis upwind neighbor values
        mins = [jnp.minimum(jnp.roll(u, 1, d), jnp.roll(u, -1, d))
                for d in range(dim)]
        if dim == 2:
            a = jnp.minimum(mins[0], mins[1])
            b = jnp.maximum(mins[0], mins[1])
            h = dx[0]     # assume near-isotropic spacing
            one_d = a + h
            disc = 2.0 * h * h - (b - a) ** 2
            two_d = 0.5 * (a + b + jnp.sqrt(jnp.maximum(disc, 0.0)))
            cand = jnp.where(one_d <= b, one_d, two_d)
        else:
            s = jnp.sort(jnp.stack(mins, axis=-1), axis=-1)
            h = dx[0]
            a, b, c = s[..., 0], s[..., 1], s[..., 2]
            u1 = a + h
            disc2 = 2.0 * h * h - (b - a) ** 2
            u2 = 0.5 * (a + b + jnp.sqrt(jnp.maximum(disc2, 0.0)))
            sum3 = a + b + c
            disc3 = sum3 ** 2 - 3.0 * (a * a + b * b + c * c - h * h)
            u3 = (sum3 + jnp.sqrt(jnp.maximum(disc3, 0.0))) / 3.0
            cand = jnp.where(u1 <= b, u1, jnp.where(u2 <= c, u2, u3))
        return jnp.minimum(u, cand)

    def body(_, u):
        u = eikonal_update(u)
        return jnp.where(near, d_band, u)

    u = jax.lax.fori_loop(0, iters, body, u0)
    return sgn * u
