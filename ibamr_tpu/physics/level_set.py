"""Level-set machinery: signed-distance maintenance + interface calculus.

Reference parity: ``src/level_set/`` (P22, SURVEY.md §2.2 —
``RelaxationLSMethod``, ``FastSweepingLSMethod``, ``LevelSetUtilities``).
The reference maintains signed-distance functions for interface-capturing
(multiphase flow, Brinkman penalization) with two reinitialization
engines; both are rebuilt TPU-first:

- :func:`reinitialize` — the RelaxationLSMethod analog: pseudo-time
  relaxation of |grad phi| -> 1 (Sussman-Smereka-Osher) with Godunov
  upwinding and the Russo-Smereka subcell fix pinning the zero level.
  A fixed iteration count under ``lax.fori_loop`` — fully jittable.
- :func:`fast_sweeping_distance` — the FastSweepingLSMethod analog:
  directional sweeps that keep the reference's Gauss-Seidel causality
  ALONG the swept axis (a ``lax.scan`` over slices — information
  crosses the whole axis in one pass) while updating each transverse
  slice as one parallel VPU op; a handful of alternating rounds
  replaces the reference's serial 2^dim orderings.

Interface calculus (LevelSetUtilities analog): smoothed Heaviside/delta,
phase volume, curvature — the ingredients the multiphase integrator
(:mod:`ibamr_tpu.integrators.ins_vc`) consumes.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid


# -- smoothed interface functions -------------------------------------------

def heaviside(phi: jnp.ndarray, eps: float) -> jnp.ndarray:
    """Smoothed Heaviside H_eps(phi) over a band of half-width eps."""
    core = 0.5 * (1.0 + phi / eps
                  + jnp.sin(math.pi * phi / eps) / math.pi)
    return jnp.where(phi < -eps, 0.0, jnp.where(phi > eps, 1.0, core))


def delta(phi: jnp.ndarray, eps: float) -> jnp.ndarray:
    """Smoothed interface delta (derivative of :func:`heaviside`)."""
    core = 0.5 / eps * (1.0 + jnp.cos(math.pi * phi / eps))
    return jnp.where(jnp.abs(phi) > eps, 0.0, core)


def phase_volume(phi: jnp.ndarray, grid: StaggeredGrid,
                 eps: float) -> jnp.ndarray:
    """Volume of the phi < 0 phase (smoothed)."""
    return jnp.sum(1.0 - heaviside(phi, eps)) * grid.cell_volume


def _central_grad(phi: jnp.ndarray, d: int, dx_d: float,
                  wall: bool) -> jnp.ndarray:
    """Delegates to the shared ops.stencils.central_grad."""
    from ibamr_tpu.ops.stencils import central_grad

    return central_grad(phi, d, dx_d, wall)


def gradient_norm(phi: jnp.ndarray, dx: Sequence[float],
                  wall_axes=None) -> jnp.ndarray:
    """|grad phi| with central differences (diagnostic); one-sided at
    walls when ``wall_axes`` marks an axis wall-bounded."""
    if wall_axes is None:
        wall_axes = (False,) * phi.ndim
    out = jnp.zeros_like(phi)
    for d in range(phi.ndim):
        g = _central_grad(phi, d, dx[d], wall_axes[d])
        out = out + g * g
    return jnp.sqrt(out)


def curvature(phi: jnp.ndarray, dx: Sequence[float],
              wall_axes=None) -> jnp.ndarray:
    """Interface curvature kappa = div(grad phi / |grad phi|);
    one-sided wall differences when ``wall_axes`` is given."""
    dim = phi.ndim
    if wall_axes is None:
        wall_axes = (False,) * dim
    grads = [_central_grad(phi, d, dx[d], wall_axes[d])
             for d in range(dim)]
    mag = jnp.sqrt(sum(g * g for g in grads) + 1e-12)
    kap = jnp.zeros_like(phi)
    for d in range(dim):
        nd = grads[d] / mag
        kap = kap + _central_grad(nd, d, dx[d], wall_axes[d])
    return kap


# -- Godunov Hamiltonian -----------------------------------------------------

def _godunov_grad_mag(phi: jnp.ndarray, dx: Sequence[float],
                      sgn: jnp.ndarray,
                      wall_axes=None) -> jnp.ndarray:
    """Godunov-upwinded |grad phi| for the reinitialization equation.
    ``wall_axes[d]`` zeroes the cross-wall (wrap) one-sided differences
    of axis d — the even-reflection ghost for a walled domain."""
    dim = phi.ndim
    if wall_axes is None:
        wall_axes = (False,) * dim
    acc = jnp.zeros_like(phi)
    for d in range(dim):
        dm = (phi - jnp.roll(phi, 1, d)) / dx[d]     # backward
        dp = (jnp.roll(phi, -1, d) - phi) / dx[d]    # forward
        if wall_axes[d]:
            from ibamr_tpu.ops.stencils import wall_boundary_masks

            is_lo, is_hi = wall_boundary_masks(phi.shape, d)
            dm = jnp.where(is_lo, 0.0, dm)
            dp = jnp.where(is_hi, 0.0, dp)
        # moving outward from the interface: use the upwind choice
        a = jnp.where(sgn >= 0,
                      jnp.maximum(jnp.maximum(dm, 0.0) ** 2,
                                  jnp.minimum(dp, 0.0) ** 2),
                      jnp.maximum(jnp.minimum(dm, 0.0) ** 2,
                                  jnp.maximum(dp, 0.0) ** 2))
        acc = acc + a
    return jnp.sqrt(acc)


def _interface_cells(phi: jnp.ndarray, wall_axes=None) -> jnp.ndarray:
    """Mask of cells whose stencil straddles the zero level. With
    ``wall_axes``, cross-wall (wrap) sign changes are NOT interface
    cells — e.g. a pool's floor row against the air above the domain
    top must not be relaxed by the subcell fix."""
    if wall_axes is None:
        wall_axes = (False,) * phi.ndim
    near = jnp.zeros_like(phi, dtype=bool)
    for d in range(phi.ndim):
        lo = phi * jnp.roll(phi, 1, d) < 0.0
        hi = phi * jnp.roll(phi, -1, d) < 0.0
        if wall_axes[d]:
            from ibamr_tpu.ops.stencils import wall_boundary_masks

            is_lo, is_hi = wall_boundary_masks(phi.shape, d)
            lo = lo & ~is_lo
            hi = hi & ~is_hi
        near = near | lo | hi
    return near


def reinitialize(phi: jnp.ndarray, dx: Sequence[float],
                 iters: int = 40,
                 dtau: float = None,
                 wall_axes=None) -> jnp.ndarray:
    """Relaxation reinitialization toward a signed-distance function.

    d phi / d tau = S(phi_0) (1 - |grad phi|), Godunov upwinding, with
    the Russo-Smereka subcell fix in interface cells: there the update
    drives phi toward (D * sgn) where D is the subcell distance estimate
    phi_0 / |grad phi_0|, so the zero level set does not drift.
    ``wall_axes`` marks wall-bounded axes (even-reflection differences
    at the walls instead of the periodic wrap).
    """
    h = min(dx)
    if dtau is None:
        dtau = 0.5 * h
    phi0 = phi
    sgn = phi0 / jnp.sqrt(phi0 * phi0 + h * h)      # smoothed (far field)
    sgn_hard = jnp.where(phi0 >= 0.0, 1.0, -1.0)    # true sign (subcell fix)
    near = _interface_cells(phi0, wall_axes=wall_axes)
    g0 = jnp.maximum(gradient_norm(phi0, dx, wall_axes=wall_axes), 1e-8)
    D = phi0 / g0                                   # subcell distance

    def body(_, p):
        gm = _godunov_grad_mag(p, dx, sgn, wall_axes=wall_axes)
        upd_far = p + dtau * sgn * (1.0 - gm)
        # Russo-Smereka: relax interface cells to the frozen subcell
        # distance. The TRUE sign is essential here — the smoothed sgn
        # would rescale the fixed point to D/sgn (round-2 fix).
        upd_near = p - dtau / h * (sgn_hard * jnp.abs(p) - D)
        return jnp.where(near, upd_near, upd_far)

    return jax.lax.fori_loop(0, iters, body, phi)


def _eikonal_solve(mins, h: float) -> jnp.ndarray:
    """Upwind Eikonal solve sum_d ((u - a_d)/h)^2 = 1 from per-axis
    neighbor minima ``mins`` (near-isotropic spacing h, the same
    assumption as the reference's FastSweepingLSMethod update)."""
    dim = len(mins)
    if dim == 2:
        a = jnp.minimum(mins[0], mins[1])
        b = jnp.maximum(mins[0], mins[1])
        one_d = a + h
        disc = 2.0 * h * h - (b - a) ** 2
        two_d = 0.5 * (a + b + jnp.sqrt(jnp.maximum(disc, 0.0)))
        return jnp.where(one_d <= b, one_d, two_d)
    s = jnp.sort(jnp.stack(mins, axis=-1), axis=-1)
    a, b, c = s[..., 0], s[..., 1], s[..., 2]
    u1 = a + h
    disc2 = 2.0 * h * h - (b - a) ** 2
    u2 = 0.5 * (a + b + jnp.sqrt(jnp.maximum(disc2, 0.0)))
    sum3 = a + b + c
    disc3 = sum3 ** 2 - 3.0 * (a * a + b * b + c * c - h * h)
    u3 = (sum3 + jnp.sqrt(jnp.maximum(disc3, 0.0))) / 3.0
    return jnp.where(u1 <= b, u1, jnp.where(u2 <= c, u2, u3))


def fast_sweeping_distance(phi: jnp.ndarray, dx: Sequence[float],
                           iters: int = None,
                           sweeps: int = 4,
                           wall_axes=None) -> jnp.ndarray:
    """Signed distance by FAST SWEEPING (Zhao 2004): the
    ``FastSweepingLSMethod`` analog (SURVEY.md P22,
    ``src/level_set/FastSweepingLSMethod.cpp`` [U]).

    The frozen interface band keeps its subcell distances
    (phi/|grad phi|); outside it, directional sweeps propagate the
    upwind Eikonal update. The TPU-native formulation keeps the
    reference's Gauss-Seidel causality ALONG the swept axis (a
    ``lax.scan`` over slices: slice i sees slice i-1's already-updated
    values) while updating each transverse slice as one parallel VPU
    op — a sweep carries information across the whole axis in ONE
    pass. The transverse axes are lagged (the price of
    slice-parallelism vs the reference's strictly causal serial
    orderings), so diagonal characteristics converge over the
    alternating passes geometrically (~2x error reduction per round)
    rather than in exactly 2^dim orderings: ``sweeps`` = 4 rounds of
    the 2*dim directional passes reach O(h) accuracy at every grid
    size tested (32-128), and the pass count stays ~an order of
    magnitude below the O(n) pseudo-time iterations the relaxation
    PDE needs — pinned by tests/test_physics_p22.py.

    ``iters`` is accepted for backward compatibility and ignored (the
    sweep count does not scale with the grid); passing it warns.
    ``wall_axes`` marks wall-bounded axes: no distance information
    crosses a wall (no wrap in the interface detection, the transverse
    minima, or the sweep seed) — the same convention as
    :func:`reinitialize`.
    """
    if iters is not None:
        import warnings

        warnings.warn(
            "fast_sweeping_distance(iters=...) is ignored: the "
            "directional-sweep solver's cost is set by `sweeps` "
            "(grid-size independent), not a Jacobi iteration count",
            DeprecationWarning, stacklevel=2)
    dim = phi.ndim
    if wall_axes is None:
        wall_axes = (False,) * dim
    wall_axes = tuple(bool(w) for w in wall_axes)
    h = float(dx[0])
    near = _interface_cells(phi, wall_axes=wall_axes)
    g0 = jnp.maximum(gradient_norm(phi, dx, wall_axes=wall_axes), 1e-8)
    d_band = jnp.abs(phi) / g0
    sgn = jnp.where(phi >= 0, 1.0, -1.0)
    big = float(sum(n * hh for n, hh in zip(phi.shape, dx)))
    u0 = jnp.where(near, d_band, big)

    def sweep_axis(u, d, forward):
        """One directional pass along axis d: scan over slices;
        within a slice the d-axis upwind value is the carry (already
        updated — Gauss-Seidel) min the lagged downstream neighbor;
        transverse neighbor minima are lagged (Jacobi), one whole
        slice per scan step."""
        from ibamr_tpu.ops.stencils import wall_boundary_masks

        um = jnp.moveaxis(u, d, 0)
        nm = jnp.moveaxis(near, d, 0)
        bm = jnp.moveaxis(d_band, d, 0)
        if not forward:
            um, nm, bm = um[::-1], nm[::-1], bm[::-1]
        # lagged downstream neighbor (next slice); on a wall axis the
        # last slice has none (big), elsewhere periodic wrap
        down = jnp.roll(um, -1, 0)
        if wall_axes[d]:
            down = down.at[-1].set(big)
        # lagged transverse mins per slice; wall axes exclude the
        # wrapped neighbor (one-sided at the boundary rows)
        tmins = []
        trans = [a for a in range(dim) if a != d]
        for k, a in enumerate(trans):
            ax = k + 1                      # axis of um after moveaxis
            lo_n = jnp.roll(um, 1, ax)
            hi_n = jnp.roll(um, -1, ax)
            if wall_axes[a]:
                is_lo, is_hi = wall_boundary_masks(um.shape, ax)
                lo_n = jnp.where(is_lo, big, lo_n)
                hi_n = jnp.where(is_hi, big, hi_n)
            tmins.append(jnp.minimum(lo_n, hi_n))

        def step(carry, inp):
            u_sl, n_sl, b_sl, down_sl, *t_sl = inp
            a_d = jnp.minimum(carry, down_sl)
            cand = _eikonal_solve([a_d] + list(t_sl), h)
            new = jnp.minimum(u_sl, cand)
            new = jnp.where(n_sl, b_sl, new)
            return new, new

        # seed: the opposite face's (old) slice on periodic axes;
        # nothing beyond a wall
        seed = jnp.full_like(um[-1], big) if wall_axes[d] else um[-1]
        _, um_new = jax.lax.scan(step, seed,
                                 (um, nm, bm, down, *tmins))
        if not forward:
            um_new = um_new[::-1]
        return jnp.moveaxis(um_new, 0, d)

    u = u0
    for _ in range(int(sweeps)):
        for d in range(dim):
            u = sweep_axis(u, d, True)
            u = sweep_axis(u, d, False)
    return sgn * u
