"""Viscoelastic (complex) fluids: Oldroyd-B conformation-tensor transport.

Reference parity: ``src/complex_fluids/`` (P22, SURVEY.md §2.2 —
``CFINSForcing``, ``CFUpperConvectiveOperator``). The polymeric phase is
a symmetric conformation tensor C(x) evolved by the upper-convected
derivative with linear (Oldroyd-B) relaxation:

    dC/dt + u . grad C = grad_u C + C grad_u^T + (1/lambda)(I - C)

and feeds back on the fluid through the polymer stress
``tau = (mu_p / lambda)(C - I)``, whose divergence enters the INS step
as a body force — exactly the role CFINSForcing plays for the
reference's INS integrators.

TPU-first: C is stored as its ``dim*(dim+1)/2`` unique components in one
(..., nc) cell-centered array; transport is the Godunov advector per
component; the stretching/relaxation source is a fused batched 2x2/3x3
tensor contraction. Everything is jittable and sharding-compatible
(roll-based stencils only).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils
from ibamr_tpu.ops.godunov import advect

Vel = Tuple[jnp.ndarray, ...]

_PAIRS = {2: ((0, 0), (0, 1), (1, 1)),
          3: ((0, 0), (0, 1), (0, 2), (1, 1), (1, 2), (2, 2))}


def n_components(dim: int) -> int:
    return dim * (dim + 1) // 2


def identity_conformation(grid: StaggeredGrid,
                          dtype=jnp.float32) -> jnp.ndarray:
    """Equilibrium conformation field C = I -> (*n, nc)."""
    dim = grid.dim
    nc = n_components(dim)
    C = jnp.zeros(grid.n + (nc,), dtype=dtype)
    for k, (i, j) in enumerate(_PAIRS[dim]):
        if i == j:
            C = C.at[..., k].set(1.0)
    return C


def pack(Cfull: jnp.ndarray) -> jnp.ndarray:
    """(..., dim, dim) symmetric -> (..., nc) unique components."""
    dim = Cfull.shape[-1]
    return jnp.stack([Cfull[..., i, j] for (i, j) in _PAIRS[dim]], axis=-1)


def unpack(C: jnp.ndarray, dim: int) -> jnp.ndarray:
    """(..., nc) -> (..., dim, dim) symmetric."""
    out = jnp.zeros(C.shape[:-1] + (dim, dim), dtype=C.dtype)
    for k, (i, j) in enumerate(_PAIRS[dim]):
        out = out.at[..., i, j].set(C[..., k])
        if i != j:
            out = out.at[..., j, i].set(C[..., k])
    return out


def velocity_gradient_cc(u: Vel, dx: Sequence[float],
                         wall_axes=None) -> jnp.ndarray:
    """Cell-centered grad_u[i, j] = du_i/dx_j from MAC velocity.
    ``wall_axes[j]`` replaces the periodic wrap along axis j with
    plain one-sided differences at the boundary cells (the
    face-to-center averages themselves stay exact under the
    pinned-face storage)."""
    from ibamr_tpu.ops.stencils import central_grad

    dim = len(u)
    if wall_axes is None:
        wall_axes = (False,) * dim
    cc = stencils.fc_to_cc(u)
    rows = []
    for i in range(dim):
        cols = [central_grad(cc[i], j, dx[j], wall_axes[j])
                for j in range(dim)]
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)          # (..., i, j)


def oldroyd_b_source(C: jnp.ndarray, grad_u: jnp.ndarray,
                     lam: float) -> jnp.ndarray:
    """Stretching + relaxation RHS in packed components:
    grad_u C + C grad_u^T + (I - C)/lambda."""
    dim = grad_u.shape[-1]
    Cf = unpack(C, dim)
    GC = jnp.einsum("...ik,...kj->...ij", grad_u, Cf)
    S = GC + jnp.swapaxes(GC, -1, -2)
    S = S + (jnp.eye(dim, dtype=C.dtype) - Cf) / lam
    return pack(S)


def polymer_stress(C: jnp.ndarray, mu_p: float, lam: float,
                   dim: int) -> jnp.ndarray:
    """tau = (mu_p / lambda)(C - I), packed."""
    I = pack(jnp.broadcast_to(jnp.eye(dim, dtype=C.dtype),
                              C.shape[:-1] + (dim, dim)))
    return (mu_p / lam) * (C - I)


def stress_divergence_mac(tau: jnp.ndarray, grid: StaggeredGrid,
                          wall_axes=None) -> Vel:
    """MAC body force f_d = sum_j d_j tau_dj from the packed cell-
    centered stress: face-normal derivative via backward difference to
    the face, transverse via centered difference shifted to the face.
    ``wall_axes``: one-sided transverse differences at wall layers and
    pinned (zeroed) wall-normal output faces — the forcing consistent
    with the no-slip wall momentum rows."""
    from ibamr_tpu.integrators.ins_walls import pin_normal
    from ibamr_tpu.ops.stencils import central_grad

    dim = grid.dim
    dx = grid.dx
    if wall_axes is None:
        wall_axes = (False,) * dim
    tf = unpack(tau, dim)
    out = []
    for d in range(dim):
        acc = None
        for j in range(dim):
            t = tf[..., d, j]
            if j == d:
                # wrap row lands on the pinned wall face (masked below)
                g = (t - jnp.roll(t, 1, d)) / dx[d]
            else:
                g = central_grad(t, j, dx[j], wall_axes[j])
                g = 0.5 * (g + jnp.roll(g, 1, d))
            acc = g if acc is None else acc + g
        out.append(pin_normal(acc, d, wall_axes))
    return tuple(out)


class OldroydB:
    """CFINSForcing analog: owns (mu_p, lambda), advances C, returns the
    polymer body force for the INS step."""

    def __init__(self, grid: StaggeredGrid, mu_p: float, lam: float,
                 wall_axes=None, dtype=jnp.float32):
        self.grid = grid
        self.mu_p = float(mu_p)
        self.lam = float(lam)
        # wall_axes: no-slip walls on the flagged axes (round 4 — the
        # wall-bounded viscoelastic channel): conformation advection,
        # velocity gradients, and the stress divergence all switch to
        # their wall-aware forms
        self.wall_axes = (tuple(bool(w) for w in wall_axes)
                          if wall_axes is not None
                          else (False,) * grid.dim)
        self.dtype = dtype

    def initialize(self) -> jnp.ndarray:
        return identity_conformation(self.grid, dtype=self.dtype)

    def step(self, C: jnp.ndarray, u: Vel, dt: float) -> jnp.ndarray:
        """Advect each packed component (Godunov) then apply the
        stretching/relaxation source (explicit Euler)."""
        dx = self.grid.dx
        wa = self.wall_axes
        Cadv = jnp.stack([advect(C[..., k], u, dx, dt, wall_axes=wa)
                          for k in range(C.shape[-1])], axis=-1)
        gu = velocity_gradient_cc(u, dx, wall_axes=wa)
        return Cadv + dt * oldroyd_b_source(Cadv, gu, self.lam)

    def body_force(self, C: jnp.ndarray) -> Vel:
        tau = polymer_stress(C, self.mu_p, self.lam, self.grid.dim)
        return stress_divergence_mac(tau, self.grid,
                                     wall_axes=self.wall_axes)
