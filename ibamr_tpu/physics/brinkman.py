"""Brinkman penalization: porous/rigid obstacles as a volume penalty.

Reference parity: the Brinkman penalization half of P22 (SURVEY.md §2.2
"newer physics" — ``BrinkmanPenalizationRigidBodyDynamics``,
``BrinkmanAdvDiffBcHelper``): solid bodies are represented by an
indicator field chi on the FLUID grid and a permeability eta; inside the
body the momentum equation gains -(chi/eta)(u - u_b), driving the fluid
velocity to the body velocity u_b without any boundary-conforming mesh
or Lagrangian markers.

TPU-first redesign: instead of assembling the penalty into a
variable-coefficient implicit solve (the reference's PETSc path — which
would forfeit our exact spectral Helmholtz/projection solvers), the
penalty is a pointwise DIAGONAL implicit split step:

    u  <-  (u + (dt chi/eta) u_b) / (1 + dt chi/eta)

followed by one extra exact projection to restore div u = 0. The update
is unconditionally stable for ANY eta (the stiff limit eta -> 0 just
clamps u -> u_b), costs one fused elementwise pass plus one FFT round
trip, and keeps every solver seam stock. Free bodies advance by
Newton--Euler with the hydrodynamic force measured as the momentum the
penalty removes from the fluid — discretely exact, no surface
quadrature.

Bodies are analytic signed-distance functions evaluated fresh each step
at the body's current center/orientation (functional state, jit-native;
no stored masks to regrid).
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.physics.level_set import heaviside

Vel = Tuple[jnp.ndarray, ...]


def face_coords(grid: StaggeredGrid, d: int,
                dtype=jnp.float32) -> Tuple[jnp.ndarray, ...]:
    """Broadcastable coordinates of component-d face centers — thin
    wrapper over ``StaggeredGrid.face_centers`` so the staggering
    convention lives in exactly one place (grid.py)."""
    return grid.face_centers(d, dtype)


class RigidBodyState(NamedTuple):
    """Dynamic state of one penalized rigid body."""
    center: jnp.ndarray   # (dim,)
    U: jnp.ndarray        # (dim,) translational velocity
    theta: jnp.ndarray    # scalar orientation (2D) — 0.0 if unused
    omega: jnp.ndarray    # scalar angular velocity (2D) — 0.0 if unused


class BrinkmanBody:
    """One penalized body: an analytic SDF (negative inside) evaluated
    in BODY frame, plus permeability and (for free bodies) inertia.

    ``sdf(x_body) -> phi`` gets coordinates already translated (and, in
    2D, rotated) into the body frame, so one lambda describes the shape
    for every position/orientation.
    """

    def __init__(self, sdf: Callable[[Sequence[jnp.ndarray]], jnp.ndarray],
                 eta: float = 1e-3, smear_cells: float = 1.0,
                 density: Optional[float] = None,
                 volume: Optional[float] = None,
                 moment: Optional[float] = None):
        self.sdf = sdf
        self.eta = float(eta)
        self.smear_cells = float(smear_cells)
        self.density = density      # None -> prescribed-motion body
        self.volume = volume        # needed for free-body gravity
        self.moment = moment

    def chi(self, grid: StaggeredGrid, d: int,
            st: RigidBodyState) -> jnp.ndarray:
        """Indicator (smoothed Heaviside of -sdf) on the d-faces."""
        xs = face_coords(grid, d, st.center.dtype)
        xb = [x - st.center[a] for a, x in enumerate(xs)]
        if grid.dim == 2:
            c, s = jnp.cos(-st.theta), jnp.sin(-st.theta)
            xb = [c * xb[0] - s * xb[1], s * xb[0] + c * xb[1]]
        phi = self.sdf(xb)
        eps = self.smear_cells * max(grid.dx)
        return 1.0 - heaviside(phi, eps)   # 1 inside the body

    def body_velocity(self, grid: StaggeredGrid, d: int,
                      st: RigidBodyState) -> jnp.ndarray:
        """Rigid velocity of the body material at the d-faces."""
        xs = face_coords(grid, d, st.center.dtype)
        v = jnp.full_like(xs[0], st.U[d])
        if grid.dim == 2:
            r = (xs[0] - st.center[0], xs[1] - st.center[1])
            v = v + (-st.omega * r[1] if d == 0 else st.omega * r[0])
        return v


def penalize(u: Vel, grid: StaggeredGrid, dt: float,
             bodies: Sequence[BrinkmanBody],
             states: Sequence[RigidBodyState]) -> Tuple[Vel, list]:
    """Diagonal implicit penalty update; returns the new velocity and,
    per body, the momentum/angular impulse the fluid LOST to it (the
    hydrodynamic force/torque on the body is +impulse/dt)."""
    dim = grid.dim
    unew = list(u)
    impulses = []
    vol = math.prod(grid.dx)
    for body, st in zip(bodies, states):
        dP = []
        torque_impulse = jnp.zeros((), dtype=u[0].dtype)
        for d in range(dim):
            chi = body.chi(grid, d, st)
            ub = body.body_velocity(grid, d, st)
            a = dt * chi / body.eta
            before = unew[d]
            after = (before + a * ub) / (1.0 + a)
            unew[d] = after
            dP.append(jnp.sum(before - after) * vol)
            if dim == 2:
                xs = face_coords(grid, d, st.center.dtype)
                r = (xs[0] - st.center[0], xs[1] - st.center[1])
                arm = -r[1] if d == 0 else r[0]
                # angular momentum the fluid LOST, same convention as
                # dP (round-3 review: a double negation here inverted
                # the torque and anti-damped free rotation)
                torque_impulse = torque_impulse + jnp.sum(
                    arm * (before - after)) * vol
        impulses.append((jnp.stack(dP), torque_impulse))
    return tuple(unew), impulses


class BrinkmanPenalization:
    """Penalization operator bound to one INS integrator: wraps its step
    with penalty + re-projection, and advances FREE bodies by
    Newton--Euler using the measured penalty impulse (the analog of the
    reference's ``BrinkmanPenalizationRigidBodyDynamics``).

    Prescribed bodies (``density=None``) keep whatever ``U``/``omega``
    their state carries; free bodies integrate

        m dV/dt = F_hydro + (m - m_displaced) g,
        I domega/dt = T_hydro.
    """

    def __init__(self, ins, bodies: Sequence[BrinkmanBody],
                 gravity: Optional[Sequence[float]] = None):
        self.ins = ins
        self.bodies = list(bodies)
        self.gravity = (None if gravity is None
                        else jnp.asarray(gravity, dtype=ins.dtype))

    def step(self, ins_state, body_states: Sequence[RigidBodyState],
             dt: float, f: Optional[Vel] = None):
        """One coupled step: INS advance -> implicit penalty ->
        re-projection -> Newton--Euler body update."""
        g = self.ins.grid
        st1 = self.ins.step(ins_state, dt, f=f)
        u_pen, impulses = penalize(st1.u, g, dt, self.bodies, body_states)
        # restore incompressibility (chi varies in space, so the
        # pointwise clamp injects divergence near the body surface)
        u_div0, _ = self.ins.project(u_pen, g.dx)
        st1 = st1._replace(u=u_div0)

        new_states = []
        rho_f = float(self.ins.rho)
        for body, bst, (dP, dL) in zip(self.bodies, body_states,
                                       impulses):
            if body.density is None:
                new_states.append(bst._replace(
                    center=bst.center + dt * bst.U,
                    theta=bst.theta + dt * bst.omega))
                continue
            m_body = body.density * body.volume
            m_disp = rho_f * body.volume
            F = rho_f * dP / dt
            U_new = bst.U + dt / m_body * F
            if self.gravity is not None:
                U_new = U_new + dt * (m_body - m_disp) / m_body \
                    * self.gravity
            if body.moment is not None:
                # angular impulse dL is already time-integrated:
                # delta_omega = rho_f dL / I
                om_new = bst.omega + rho_f * dL / body.moment
            else:
                om_new = bst.omega
            new_states.append(RigidBodyState(
                center=bst.center + dt * U_new, U=U_new,
                theta=bst.theta + dt * om_new, omega=om_new))
        return st1, new_states, impulses


def make_cylinder_sdf(radius: float):
    """SDF of a circle/cylinder of given radius about the body origin
    (2D: disc; 3D: sphere)."""
    def sdf(xb):
        r2 = sum(x * x for x in xb)
        return jnp.sqrt(r2) - radius
    return sdf


def make_box_sdf(half_widths: Sequence[float]):
    """SDF of an axis-aligned box with the given half-widths."""
    hw = tuple(float(h) for h in half_widths)

    def sdf(xb):
        q = [jnp.abs(x) - h for x, h in zip(xb, hw)]
        outside = jnp.sqrt(sum(jnp.maximum(c, 0.0) ** 2 for c in q))
        m = q[0]
        for c in q[1:]:
            m = jnp.maximum(m, c)          # broadcasting max (coords
        inside = jnp.minimum(0.0, m)       # may be (n,1)/(1,n) shaped)
        return outside + inside
    return sdf
