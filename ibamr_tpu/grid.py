"""Staggered (MAC) Cartesian grid geometry.

Reference parity: SAMRAI ``CartesianGridGeometry`` + cell/side-centered patch
data (SURVEY.md L1) collapsed into one static-geometry object. TPU-first
redesign: geometry is *static metadata* (shapes, spacings) hashable for jit;
field data are plain ``jnp`` arrays carried in the state pytree, so one
compiled step function serves the whole run (SURVEY.md §7.1 pillar 1).

Conventions (uniform level, periodic unless stated otherwise):
- ``n = (n_0, ..., n_{d-1})`` cells; ``dx_d = (x_up_d - x_lo_d) / n_d``.
- Cell-centered field: shape ``n``; cell ``i`` center at
  ``x_lo + (i + 1/2) * dx``.
- Face-centered velocity component ``d``: shape ``n`` with ``u_d[i]`` living
  on the *lower* face of cell ``i`` in direction ``d`` (position
  ``x_lo_d + i_d * dx_d``). Under periodicity every component has exactly
  ``prod(n)`` faces, so all MAC components share one static shape — the key
  simplification that keeps XLA shapes uniform.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Tuple

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class StaggeredGrid:
    """Static MAC-grid geometry for a single uniform level."""

    n: Tuple[int, ...]
    x_lo: Tuple[float, ...]
    x_up: Tuple[float, ...]

    def __post_init__(self):
        assert len(self.n) == len(self.x_lo) == len(self.x_up)
        assert all(nd >= 2 for nd in self.n), "need >=2 cells per dim"
        object.__setattr__(self, "n", tuple(int(v) for v in self.n))
        object.__setattr__(self, "x_lo", tuple(float(v) for v in self.x_lo))
        object.__setattr__(self, "x_up", tuple(float(v) for v in self.x_up))

    # -- derived geometry ---------------------------------------------------
    @property
    def dim(self) -> int:
        return len(self.n)

    @property
    def dx(self) -> Tuple[float, ...]:
        return tuple((hi - lo) / nd
                     for lo, hi, nd in zip(self.x_lo, self.x_up, self.n))

    @property
    def lengths(self) -> Tuple[float, ...]:
        return tuple(hi - lo for lo, hi in zip(self.x_lo, self.x_up))

    @property
    def cell_volume(self) -> float:
        return math.prod(self.dx)

    @property
    def num_cells(self) -> int:
        return math.prod(self.n)

    # -- coordinates --------------------------------------------------------
    def cell_coords_1d(self, axis: int, dtype=jnp.float32) -> jnp.ndarray:
        """Cell-center coordinates along one axis, shape (n[axis],)."""
        d = self.dx[axis]
        return self.x_lo[axis] + (jnp.arange(self.n[axis], dtype=dtype) + 0.5) * d

    def face_coords_1d(self, axis: int, dtype=jnp.float32) -> jnp.ndarray:
        """Lower-face coordinates along one axis, shape (n[axis],)."""
        d = self.dx[axis]
        return self.x_lo[axis] + jnp.arange(self.n[axis], dtype=dtype) * d

    def _bcast(self, coords_1d, axis: int) -> jnp.ndarray:
        shape = [1] * self.dim
        shape[axis] = self.n[axis]
        return coords_1d.reshape(shape)

    def cell_centers(self, dtype=jnp.float32) -> Tuple[jnp.ndarray, ...]:
        """Broadcastable cell-center coordinate arrays, one per axis."""
        return tuple(self._bcast(self.cell_coords_1d(a, dtype), a)
                     for a in range(self.dim))

    def face_centers(self, comp: int, dtype=jnp.float32) -> Tuple[jnp.ndarray, ...]:
        """Broadcastable coordinates of velocity-component ``comp`` faces:
        face coordinate along axis ``comp``, cell-center along the others."""
        out = []
        for a in range(self.dim):
            c = (self.face_coords_1d(a, dtype) if a == comp
                 else self.cell_coords_1d(a, dtype))
            out.append(self._bcast(c, a))
        return tuple(out)

    # -- conversions --------------------------------------------------------
    def position_to_index(self, x: jnp.ndarray) -> jnp.ndarray:
        """Continuous cell index of physical position(s) x (..., dim):
        cell i contains [i, i+1) in these units."""
        lo = jnp.asarray(self.x_lo, dtype=x.dtype)
        dx = jnp.asarray(self.dx, dtype=x.dtype)
        return (x - lo) / dx
