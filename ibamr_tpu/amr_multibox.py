"""Multi-box dynamic AMR: tag clustering into K fine windows.

Reference parity: ``BergerRigoutsos`` box clustering + ``LoadBalancer``
(SURVEY.md §3.4, L1) — the reference clusters arbitrary tag sets into
MANY boxes per level, so a structure that splits (or two separate
structures) each get their own refinement. Round 2's dynamic AMR
(:mod:`ibamr_tpu.amr_dynamic`) fits exactly ONE moving window; this
module generalizes it to a static POOL of K fixed-shape windows over
the same coarse level.

TPU-first split of labor (SURVEY.md §7.1 pillar 1, §7.3 hard-part #3):

- the jitted composite step advances all K windows with STATIC shapes —
  a Python-unrolled loop over the pool (K is small and static), each
  window reusing the single-window machinery (traced-origin ghost
  fills, restriction, refluxing);
- CLUSTERING runs on host between jitted segments (exactly where the
  reference runs BergerRigoutsos, §3.4): connected-component labeling
  of the tag field, greedy component->box assignment (largest first),
  pairwise-overlap separation (fixed-shape boxes are nudged apart along
  the cheapest axis), and nearest-origin matching to the PREVIOUS boxes
  so surviving fine data is copied across the right overlap.

Windows must stay pairwise separated by >= GAP coarse cells — not
merely disjoint: each window's reflux corrections land on the coarse
cells just OUTSIDE it, which must not be covered (and overwritten) by
another window's restriction. Same-level box-box coupling goes through
the coarse level — accurate for the well-separated-features regime
this targets, conservative always under the separation invariant (the
composite integral telescopes per window; clustering enforces the gap
or falls back/raises).
"""

from __future__ import annotations

from itertools import permutations
from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ibamr_tpu.amr_dynamic import (AMRState, DynamicTwoLevelAdvDiff,
                                   prolong_cc_conservative, copy_overlap,
                                   restrict_into_coarse, tag_gradient)
from ibamr_tpu.grid import StaggeredGrid

Array = jnp.ndarray


# --------------------------------------------------------------------------
# host-side clustering (the BergerRigoutsos analog)
# --------------------------------------------------------------------------

def connected_components(tags: np.ndarray) -> List[np.ndarray]:
    """Label face-connected components of a boolean tag array (host
    numpy BFS; periodic wrap handled by index modulo). Returns one
    (n_cells, dim) index array per component, largest first."""
    tags = np.asarray(tags, dtype=bool)
    shape = tags.shape
    dim = tags.ndim
    seen = np.zeros(shape, dtype=bool)
    comps = []
    for start in zip(*np.nonzero(tags & ~seen)):
        if seen[start]:
            continue
        stack = [start]
        seen[start] = True
        cells = []
        while stack:
            c = stack.pop()
            cells.append(c)
            for d in range(dim):
                for s in (-1, 1):
                    nb = list(c)
                    nb[d] = (nb[d] + s) % shape[d]
                    nb = tuple(nb)
                    if tags[nb] and not seen[nb]:
                        seen[nb] = True
                        stack.append(nb)
        comps.append(np.asarray(cells))
    comps.sort(key=len, reverse=True)
    return comps


def _center_box(cells: np.ndarray, shape: Tuple[int, ...],
                box_shape: Tuple[int, ...], clearance: int) -> np.ndarray:
    """Fixed-shape box origin centering one component (circular mean per
    axis, clipped to clearance) — the per-component fit_box_origin."""
    dim = len(shape)
    lo = np.zeros(dim, dtype=np.int64)
    for d in range(dim):
        n = shape[d]
        th = 2.0 * np.pi * cells[:, d] / n
        center = np.mod(np.arctan2(np.sin(th).sum(), np.cos(th).sum())
                        / (2.0 * np.pi) * n + 0.5, n)
        lo[d] = int(np.clip(round(center - box_shape[d] / 2.0),
                            clearance, n - box_shape[d] - clearance))
    return lo


GAP = 1   # minimum coarse-cell gap between windows: each window's
# reflux neighbor cells must stay UNCOVERED by every other window, or a
# later window's restriction overwrites an earlier window's flux
# correction and conservation breaks (touching boxes are NOT allowed)


def _separate(los: List[np.ndarray], box_shape, shape, clearance,
              max_rounds: int = 8) -> List[np.ndarray]:
    """Nudge too-close fixed-shape boxes apart: per violating pair,
    shift the LATER (smaller-component) box along the axis needing the
    smallest displacement, keeping >= GAP cells between boxes."""
    los = [lo.copy() for lo in los]
    for _ in range(max_rounds):
        moved = False
        for j in range(1, len(los)):
            for i in range(j):
                ov = [min(los[i][d] + box_shape[d],
                          los[j][d] + box_shape[d])
                      - max(los[i][d], los[j][d])
                      for d in range(len(shape))]
                if all(o > -GAP for o in ov):
                    d = int(np.argmin(ov))
                    if los[j][d] >= los[i][d]:
                        cand = los[i][d] + box_shape[d] + GAP
                    else:
                        cand = los[i][d] - box_shape[d] - GAP
                    los[j][d] = int(np.clip(
                        cand, clearance,
                        shape[d] - box_shape[d] - clearance))
                    moved = True
        if not moved:
            return los
    # separation failed (features too clustered for disjoint boxes of
    # this shape) — caller keeps the previous layout
    return []


def cluster_boxes(tags: np.ndarray, K: int, box_shape: Tuple[int, ...],
                  clearance: int = 2,
                  prev: Optional[np.ndarray] = None) -> np.ndarray:
    """Cluster the tag field into K fixed-shape box origins, pairwise
    separated by >= GAP cells (host side). The K largest components get
    a box each; smaller components stay unrefined on the coarse level
    (size box_shape to cover what must be refined). With fewer
    components than K, the extra boxes shadow the largest component
    (stacked beside it, separated). With ``prev`` given, boxes are
    matched to the previous origins (exact min-cost permutation for
    K <= 6, greedy nearest-pair beyond) so window identity — and
    therefore the regrid overlap copy — follows the FEATURE, not the
    list order. Returns (K, dim) int64 origins; falls back to ``prev``
    when separation is impossible, and raises when it is impossible
    with no ``prev`` to fall back to (features too clustered for K
    disjoint boxes of this shape — overlapping windows would silently
    break conservation)."""
    shape = tags.shape
    comps = connected_components(tags)
    if not comps:
        if prev is not None:
            return np.asarray(prev, dtype=np.int64)
        mid = np.asarray([(s - b) // 2 for s, b in zip(shape, box_shape)],
                         dtype=np.int64)
        los = _separate([mid.copy() for _ in range(K)], box_shape,
                        shape, clearance)
        if not los:
            raise ValueError(
                f"cannot place {K} disjoint {box_shape} windows in a "
                f"{shape} domain with clearance {clearance}")
        return np.stack(los).astype(np.int64)

    los = [_center_box(c, shape, box_shape, clearance)
           for c in comps[:K]]
    while len(los) < K:
        los.append(los[0].copy())     # shadow the largest component
    sep = _separate(los, box_shape, shape, clearance)
    if not sep:
        if prev is not None:
            return np.asarray(prev, dtype=np.int64)
        raise ValueError(
            f"features too clustered for {K} disjoint {box_shape} "
            f"windows (domain {shape}, clearance {clearance}); use a "
            "larger box_shape or fewer windows")
    los = np.stack(sep)

    if prev is not None:
        prev = np.asarray(prev)
        if K <= 6:
            best, best_cost = None, None
            for perm in permutations(range(K)):
                cost = sum(np.abs(los[p] - prev[k]).sum()
                           for k, p in enumerate(perm))
                if best_cost is None or cost < best_cost:
                    best, best_cost = perm, cost
            order = best
        else:
            # greedy globally-nearest pairing (O(K^3) worst case)
            remaining = set(range(K))
            order = [None] * K
            for _ in range(K):
                bi = bj = None
                bcost = None
                for k in range(K):
                    if order[k] is not None:
                        continue
                    for p in remaining:
                        cost = np.abs(los[p] - prev[k]).sum()
                        if bcost is None or cost < bcost:
                            bi, bj, bcost = k, p, cost
                order[bi] = bj
                remaining.discard(bj)
        los = np.stack([los[p] for p in order])
    return los.astype(np.int64)


# --------------------------------------------------------------------------
# the K-window integrator
# --------------------------------------------------------------------------

class MultiBoxState(NamedTuple):
    Qc: Array          # coarse level (periodic)
    Qf: Array          # (K, *fine_shape) window pool
    lo: Array          # (K, dim) int32 window origins


class MultiBoxDynamicAdvDiff:
    """K-window moving-refinement advection-diffusion: the composite
    step is jitted with all origins traced; clustering is host-side
    between jitted chunks (``advance_regridding``)."""

    def __init__(self, grid: StaggeredGrid, box_shape: Tuple[int, ...],
                 K: int, kappa: float = 0.0, scheme: str = "centered",
                 u_fn: Optional[Callable] = None,
                 tag_threshold: float = 0.05, ratio: int = 2,
                 clearance: int = 2, dtype=jnp.float64):
        self.K = int(K)
        # all per-window machinery is the single-window integrator's
        self.win = DynamicTwoLevelAdvDiff(
            grid, box_shape, kappa=kappa, scheme=scheme, u_fn=u_fn,
            tag_threshold=tag_threshold, ratio=ratio,
            clearance=clearance, dtype=dtype)
        self.grid = grid
        self.ratio = ratio
        # compiled once; recompiles only per distinct chunk length
        self._jit_advance = jax.jit(self.advance, static_argnums=2)

    # -- jitted composite step ------------------------------------------
    def step(self, state: MultiBoxState, dt: float) -> MultiBoxState:
        win = self.win
        grid = self.grid
        dim = grid.dim
        Qc, Qf, lo = state

        Fc, Qc_new = win._coarse_advance(Qc, dt)

        # ALL windows read the pristine coarse predictor before ANY
        # writeback (Jacobi ordering): at the minimum separation a
        # window's quadratic ghost stencil can reach the gap cell a
        # neighbor's reflux writes, and a read-after-write interleave
        # would make the result depend on the box index order. The
        # read-then-write order is box-order-independent and is what
        # the device-parallel placement (make_sharded_multibox_step)
        # computes, so the two paths stay equal at every separation.
        subs = [win._fine_substeps(Qc, Qc_new, Qf[k], lo[k], dt)
                for k in range(self.K)]        # static pool: unrolled
        for k in range(self.K):
            Qf_k, acc_lo, acc_hi = subs[k]
            Qc_new = win._restrict_and_reflux(
                Qc_new, Qf_k, lo[k], Fc, acc_lo, acc_hi, dt)
        return MultiBoxState(Qc=Qc_new,
                             Qf=jnp.stack([s[0] for s in subs]),
                             lo=lo)

    def advance(self, state: MultiBoxState, dt: float,
                num_steps: int) -> MultiBoxState:
        def body(s, _):
            return self.step(s, dt), None

        out, _ = lax.scan(body, state, None, length=num_steps)
        return out

    # -- host-side regrid ------------------------------------------------
    def regrid_state(self, state: MultiBoxState) -> MultiBoxState:
        """Re-cluster the tags and move the window pool (host side):
        sync coarse under every old window, cluster, prolong each new
        window, copy surviving fine data from the IDENTITY-matched old
        window."""
        win = self.win
        r = self.ratio
        Qc, Qf, lo = state
        lo_np = np.asarray(lo)
        for k in range(self.K):
            Qc = restrict_into_coarse(Qc, Qf[k], lo[k], r)
        tags = np.asarray(tag_gradient(Qc, self.grid,
                                       win.tag_threshold))
        lo_new = cluster_boxes(tags, self.K, win.box_shape,
                               win.clearance, prev=lo_np)
        Qf_out = []
        for k in range(self.K):
            lo_k = jnp.asarray(lo_new[k], dtype=jnp.int32)
            Qf_k = prolong_cc_conservative(Qc, lo_k, win.box_shape, r)
            Qf_k = copy_overlap(Qf_k, Qf[k], lo_k, lo[k], r)
            Qf_out.append(Qf_k)
        return MultiBoxState(Qc=Qc, Qf=jnp.stack(Qf_out),
                             lo=jnp.asarray(lo_new, dtype=jnp.int32))

    def advance_regridding(self, state: MultiBoxState, dt: float,
                           num_steps: int, regrid_interval: int = 5
                           ) -> MultiBoxState:
        """Host-side regrid cadence around jitted advance chunks (the
        reference's regrid loop shape, §3.4)."""
        done = 0
        while done < num_steps:
            state = self.regrid_state(state)
            n = min(regrid_interval, num_steps - done)
            state = self._jit_advance(state, dt, n)
            done += n
        return state

    # -- setup / diagnostics --------------------------------------------
    def initialize(self, fn) -> MultiBoxState:
        """Evaluate ``fn(coords)->array`` on the coarse level, cluster
        the initial tags, exact-sample each window."""
        win = self.win
        Qc = jnp.asarray(fn(self.grid.cell_centers(win.dtype)),
                         dtype=win.dtype)
        Qc = jnp.broadcast_to(Qc, self.grid.n)
        tags = np.asarray(tag_gradient(Qc, self.grid,
                                       win.tag_threshold))
        lo = cluster_boxes(tags, self.K, win.box_shape, win.clearance)
        Qf = []
        for k in range(self.K):
            lo_k = jnp.asarray(lo[k], dtype=jnp.int32)
            coords = win._fine_cell_coords(lo_k)
            Qf_k = jnp.broadcast_to(
                jnp.asarray(fn(coords), dtype=win.dtype),
                win.fine_shape)
            Qf.append(Qf_k)
        return MultiBoxState(Qc=Qc, Qf=jnp.stack(Qf),
                             lo=jnp.asarray(lo, dtype=jnp.int32))

    def total(self, state: MultiBoxState) -> Array:
        """Composite conserved integral (uncovered coarse + windows)."""
        win = self.win
        vol_c = self.grid.cell_volume
        vol_f = vol_c / (self.ratio ** self.grid.dim)
        covered = jnp.zeros(self.grid.n, dtype=bool)
        ones = jnp.ones(win.box_shape, dtype=bool)
        acc = jnp.asarray(0.0, dtype=state.Qc.dtype)
        for k in range(self.K):
            covered = lax.dynamic_update_slice(covered, ones,
                                               tuple(state.lo[k]))
            acc = acc + jnp.sum(state.Qf[k]) * vol_f
        return acc + jnp.sum(jnp.where(covered, 0.0, state.Qc)) * vol_c
