"""Merge per-process ledger shards into one fleet view (PR 15).

A pod run writes one ledger PER PROCESS — each host opens
``RunLedger(dir, proc=jax.process_index())`` and appends to its own
``ledger-<proc>.jsonl`` shard (:func:`ibamr_tpu.obs.bus.shard_path`),
because O_APPEND atomicity is a per-file, per-host guarantee and a
shared file over NFS is exactly the torn-interleaved-bytes failure the
bus was designed to rule out. The ``run_id`` — a digest of the flight
recorder fingerprint, identical on every host of the same run — is the
cross-shard join key.

This module is the read side: collect the shards of a directory, check
they belong to one run, and interleave them into a single record
stream a fleet summary can walk. Merge order is ``(seq, proc)`` — seq
is each process's own monotonic counter and proc breaks ties — NOT
wall-clock ``t``, so the merge is deterministic under host clock skew
(the per-record ``t`` stays available for staleness display). Each
shard is read with the bus's torn-tail-tolerant :func:`read_ledger`,
so a SIGKILL mid-write on one host costs at most that host's final
line, never the merge.

Counters are cumulative PER PROCESS (last-snapshot-wins within one
shard), so a fleet rollup must never sum the same proc's snapshots
across time or fold two procs into one key. :func:`fleet_counters`
takes the LAST ``counters`` record of each proc and namespaces every
metric key with a ``proc="<p>"`` label (the exporter's label-splice),
which makes the merged registry safe to export: per-proc series stay
distinct, and cross-proc totals are an explicit reader-side sum.

Host-side, stdlib-only, offline — usable on a machine that never ran
the job.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

from ibamr_tpu.obs.bus import read_ledger

__all__ = ["find_shards", "merge_ledgers", "fleet_counters",
           "fleet_prometheus_text"]

_SHARD_RE = re.compile(r"^ledger-([A-Za-z0-9_.-]+)\.jsonl$")


def find_shards(path: str) -> Dict[str, str]:
    """``{proc: shard_path}`` for one run directory.

    ``ledger-<proc>.jsonl`` files are the shards; a bare
    ``ledger.jsonl`` (a single-process run, proc never set) is
    accepted as proc ``"0"`` when no shard already claims that name —
    so every tool that grew ``--fleet`` still reads yesterday's solo
    layout. A file path is treated as a single shard (proc parsed
    from its name when it matches, else ``"0"``)."""
    if os.path.isfile(path):
        m = _SHARD_RE.match(os.path.basename(path))
        return {m.group(1) if m else "0": path}
    shards: Dict[str, str] = {}
    try:
        names = sorted(os.listdir(path))
    except OSError:
        return {}
    for name in names:
        m = _SHARD_RE.match(name)
        if m:
            shards[m.group(1)] = os.path.join(path, name)
    solo = os.path.join(path, "ledger.jsonl")
    if os.path.exists(solo) and "0" not in shards:
        shards["0"] = solo
    return shards


def merge_ledgers(path_or_shards,
                  allow_mixed_run_ids: bool = False) -> dict:
    """Interleave the ledger shards of one run.

    ``path_or_shards`` is a run directory / shard file (routed through
    :func:`find_shards`) or an explicit ``{proc: path}`` map. Returns::

        {"run_id": ...,            # the common run identity (or None)
         "procs": [...],           # sorted proc ids with >= 1 record
         "records": [...],         # all records, sorted (seq, proc),
                                   #   each stamped with its "proc"
         "per_proc": {proc: {"path", "records", "last_seq", "last_t",
                             "run_id"}}}

    Shards whose ``run_id`` disagrees raise ``ValueError`` — merging
    two different runs silently is how a fleet dashboard lies —
    unless ``allow_mixed_run_ids`` (then ``run_id`` is the first
    shard's and the per-proc table shows each shard's own). Records
    from a shard written without ``proc=`` (yesterday's solo writer)
    are stamped with the proc inferred from the filename, so
    downstream grouping never needs a fallback path."""
    shards = (dict(path_or_shards) if isinstance(path_or_shards, dict)
              else find_shards(path_or_shards))
    records: List[dict] = []
    per_proc: Dict[str, dict] = {}
    run_id: Optional[str] = None
    for proc in sorted(shards):
        recs = read_ledger(shards[proc])
        proc_run: Optional[str] = None
        for r in recs:
            if "proc" not in r:
                r = dict(r, proc=proc)
            records.append(r)
            if proc_run is None and r.get("run_id"):
                proc_run = str(r["run_id"])
        per_proc[proc] = {
            "path": shards[proc],
            "records": len(recs),
            "last_seq": max((r["seq"] for r in recs), default=None),
            "last_t": max((r["t"] for r in recs
                           if isinstance(r.get("t"), (int, float))),
                          default=None),
            "run_id": proc_run,
        }
        if proc_run is not None:
            if run_id is None:
                run_id = proc_run
            elif proc_run != run_id and not allow_mixed_run_ids:
                raise ValueError(
                    f"ledger shards disagree on run_id: proc {proc!r} "
                    f"has {proc_run}, earlier shards have {run_id} — "
                    f"not one run (pass allow_mixed_run_ids=True to "
                    f"merge anyway)")
    records.sort(key=lambda r: (r["seq"], str(r.get("proc", ""))))
    return {"run_id": run_id,
            "procs": [p for p in sorted(per_proc)
                      if per_proc[p]["records"]],
            "records": records,
            "per_proc": per_proc}


def fleet_counters(merged: dict) -> dict:
    """The merged metric registry: each proc's LAST ``counters``
    record, every key namespaced with a ``proc="<p>"`` label.

    Returns ``{"counters": {...}, "gauges": {...}, "histograms":
    {...}}`` in exactly the shapes :func:`~ibamr_tpu.obs.export.
    prometheus_text` accepts. Cumulative counters stay per-proc series
    — nothing here sums across processes, so a proc that restarted (and
    reset its counters) cannot silently deflate another's totals."""
    from ibamr_tpu.obs.export import _splice_label

    last: Dict[str, dict] = {}
    for r in merged.get("records") or []:
        if r.get("kind") == "counters":
            last[str(r.get("proc", ""))] = r   # (seq, proc) order: last wins
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for proc in sorted(last):
        rec = last[proc]
        label = f'proc="{proc}"'
        for kind in ("counters", "gauges", "histograms"):
            for key, value in (rec.get(kind) or {}).items():
                out[kind][_splice_label(key, label)] = value
    return out


def fleet_prometheus_text(merged: dict) -> str:
    """Prometheus text for a merged fleet ledger (proc-labeled)."""
    from ibamr_tpu.obs.export import prometheus_text

    snap = fleet_counters(merged)
    return prometheus_text(counters=snap["counters"],
                           gauges=snap["gauges"],
                           histograms=snap["histograms"])
