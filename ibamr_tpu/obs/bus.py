"""The telemetry bus: spans, counters/gauges, and the run ledger.

Everything here is HOST-side and rides the run loop's existing
one-transfer-per-chunk sync points. Nothing in this module may insert
a callback, print, or any other host op into traced code — the only
thing a span contributes inside a trace is ``jax.named_scope``
metadata. The ``solo_chunk_telemetry`` / ``fleet_chunk_telemetry``
graph-contract artifacts re-lower the driver's chunk with a live
ledger attached and budget ``host_transfers_in_scan == 0``, so an
accidentally-traced callback regresses loudly in tier-1.

Concurrency model: counter/gauge updates are plain attribute writes on
per-metric instances (GIL-atomic, no lock on the hot path — the
"cheap lock-free increments" contract); the registry lock is taken
only on metric creation and snapshot. Ledger appends serialize one
whole line into a single ``os.write`` on an ``O_APPEND`` fd, so a
SIGKILL between records never tears a line and concurrent writers
never interleave bytes.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import re
import threading
import time
from bisect import bisect_right
from contextlib import contextmanager
from typing import Any, Dict, Optional, Tuple

LEDGER_SCHEMA = 1

# ---------------------------------------------------------------------------
# counters / gauges
# ---------------------------------------------------------------------------

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_OK = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize_name(name: str) -> str:
    name = _NAME_OK.sub("_", str(name))
    return name if name and not name[0].isdigit() else "_" + name


def _render_key(name: str, labels: Tuple[Tuple[str, str], ...]) -> str:
    """Prometheus-style sample key: ``name{k="v",...}`` with labels
    sorted and values escaped — the one rendering used everywhere
    (registry, ledger snapshots, the exporter), so a counter looks the
    same in ``ledger.jsonl`` and on a future ``/metrics`` endpoint."""
    name = _sanitize_name(name)
    if not labels:
        return name
    parts = []
    for k, v in labels:
        k = _LABEL_OK.sub("_", str(k))
        v = (str(v).replace("\\", "\\\\").replace('"', '\\"')
             .replace("\n", "\\n"))
        parts.append(f'{k}="{v}"')
    return name + "{" + ",".join(parts) + "}"


class Counter:
    """Monotonic cumulative counter. ``inc`` is a bare attribute
    update — no lock, no ledger write; the value reaches the ledger
    only via per-chunk snapshots."""

    __slots__ = ("name", "labels", "key", "value")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...]):
        self.name = name
        self.labels = labels
        self.key = _render_key(name, labels)
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Last-write-wins instantaneous value (queue depths, watermarks)."""

    __slots__ = ("name", "labels", "key", "value")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...]):
        self.name = name
        self.labels = labels
        self.key = _render_key(name, labels)
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


# Fixed log-spaced histogram bounds shared by every histogram: six
# buckets per decade over 1e-6 .. 1e3 (sub-microsecond observes through
# ~17-minute walls; anything above lands in the +Inf bucket). One
# process-wide lattice keeps snapshots mergeable and the percentile
# estimator's worst-case error a single bucket ratio (10^(1/6) ~ 1.47x).
HISTOGRAM_BOUNDS: Tuple[float, ...] = tuple(
    10.0 ** (-6.0 + k / 6.0) for k in range(55))


class Histogram:
    """Fixed-bucket latency/size distribution.

    ``observe`` is the hot path and follows the counter contract:
    the bucket index is computed first (the only function call), then
    the bucket count and running sum update as straight-line attribute
    arithmetic — GIL-atomic, no lock, no ledger write. Bucket counts
    are NON-cumulative in memory; the exporter cumulates them into
    Prometheus ``le`` series and :func:`quantiles_from_counts`
    estimates percentiles by interpolating within the target bucket.
    """

    __slots__ = ("name", "labels", "key", "counts", "sum")

    bounds = HISTOGRAM_BOUNDS

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...]):
        self.name = name
        self.labels = labels
        self.key = _render_key(name, labels)
        self.counts = [0] * (len(HISTOGRAM_BOUNDS) + 1)
        self.sum = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect_right(HISTOGRAM_BOUNDS, v)
        self.counts[i] += 1
        self.sum += v

    @property
    def count(self) -> int:
        return sum(self.counts)

    def snapshot(self) -> dict:
        """``{"sum": s, "count": n, "counts": [...]}`` — the per-chunk
        ledger form (raw per-bucket counts, shared bounds implied)."""
        counts = list(self.counts)
        return {"sum": self.sum, "count": sum(counts), "counts": counts}

    def quantile(self, q: float) -> Optional[float]:
        return quantiles_from_counts(self.counts, [q])[0]


def quantiles_from_counts(counts, qs, bounds=HISTOGRAM_BOUNDS):
    """Percentile estimates from per-bucket (non-cumulative) counts.

    For each quantile ``q`` in ``qs``: find the bucket holding the
    ``q``-th ranked observation and interpolate linearly between its
    bounds (the first bucket's lower bound is 0; the +Inf bucket
    reports the last finite bound — the estimator cannot see past it).
    Returns one value per ``q``, ``None`` where the histogram is empty.
    """
    total = sum(counts)
    out = []
    for q in qs:
        if total == 0:
            out.append(None)
            continue
        q = min(max(float(q), 0.0), 1.0)
        rank = q * total
        cum = 0.0
        idx = len(counts) - 1
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank and c:
                idx = i
                break
        if idx >= len(bounds):                 # +Inf bucket
            out.append(float(bounds[-1]))
            continue
        lo = 0.0 if idx == 0 else float(bounds[idx - 1])
        hi = float(bounds[idx])
        below = cum - counts[idx]
        frac = (rank - below) / counts[idx] if counts[idx] else 0.0
        out.append(lo + (hi - lo) * min(max(frac, 0.0), 1.0))
    return out


_REG_LOCK = threading.Lock()
_COUNTERS: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Counter] = {}
_GAUGES: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Gauge] = {}
_HISTOGRAMS: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                  Histogram] = {}
_HELP: Dict[str, str] = {}


def counter(name: str, **labels) -> Counter:
    """The process-wide counter for ``(name, labels)`` (created on
    first use). Cache the returned instance at module level for hot
    paths — ``inc`` on the instance is the lock-free part."""
    key = (name, tuple(sorted((str(k), str(v))
                              for k, v in labels.items())))
    c = _COUNTERS.get(key)
    if c is None:
        with _REG_LOCK:
            c = _COUNTERS.setdefault(key, Counter(name, key[1]))
    return c


def gauge(name: str, **labels) -> Gauge:
    key = (name, tuple(sorted((str(k), str(v))
                              for k, v in labels.items())))
    g = _GAUGES.get(key)
    if g is None:
        with _REG_LOCK:
            g = _GAUGES.setdefault(key, Gauge(name, key[1]))
    return g


def histogram(name: str, **labels) -> Histogram:
    """The process-wide histogram for ``(name, labels)`` — registry
    semantics identical to :func:`counter` (created on first use, lock
    only on creation and snapshot, ``reset_metrics`` zeroes values in
    place so module-cached handles stay live). Cache the returned
    instance on hot paths; ``observe`` is the lock-free part."""
    key = (name, tuple(sorted((str(k), str(v))
                              for k, v in labels.items())))
    h = _HISTOGRAMS.get(key)
    if h is None:
        with _REG_LOCK:
            h = _HISTOGRAMS.setdefault(key, Histogram(name, key[1]))
    return h


def peek_gauge(name: str, **labels) -> Optional[float]:
    """The gauge's value WITHOUT creating it — ``None`` when no
    subsystem ever touched that metric. Lets an observer (the watchdog
    heartbeat) report serving fields only on runs that actually serve,
    keeping the solo heartbeat schema untouched."""
    key = (name, tuple(sorted((str(k), str(v))
                              for k, v in labels.items())))
    g = _GAUGES.get(key)
    return None if g is None else g.value


def describe(name: str, text: str) -> None:
    """Register the ``# HELP`` line for a metric family (by bare
    name). Subsystems call this next to the ``counter()``/
    ``histogram()`` creation; the exporter falls back to a generic
    line for families nobody described."""
    with _REG_LOCK:
        _HELP[_sanitize_name(name)] = str(text)


def help_for(name: str) -> Optional[str]:
    with _REG_LOCK:
        return _HELP.get(_sanitize_name(name))


def metrics_snapshot() -> dict:
    """``{"counters": {key: value}, "gauges": {key: value},
    "histograms": {key: {sum, count, counts}}}`` with
    Prometheus-rendered keys. The instant snapshot written into the
    ledger at every chunk boundary and serialized by the exporter."""
    with _REG_LOCK:
        return {
            "counters": {c.key: c.value for c in _COUNTERS.values()},
            "gauges": {g.key: g.value for g in _GAUGES.values()},
            "histograms": {h.key: h.snapshot()
                           for h in _HISTOGRAMS.values()},
        }


def reset_metrics() -> None:
    """Zero every metric WITHOUT dropping the instances: subsystems
    cache ``counter(...)`` returns at module level, and clearing the
    registry would silently orphan those live handles (they would keep
    counting into objects no snapshot ever reads). Test harness use."""
    with _REG_LOCK:
        for c in _COUNTERS.values():
            c.value = 0
        for g in _GAUGES.values():
            g.value = 0.0
        for h in _HISTOGRAMS.values():
            for i in range(len(h.counts)):
                h.counts[i] = 0
            h.sum = 0.0


def iter_metrics():
    """Yield ``(kind, name, labels, key, value)`` for the exporter.
    Histogram values are their :meth:`Histogram.snapshot` dicts."""
    with _REG_LOCK:
        items = ([("counter", c, c.value) for c in _COUNTERS.values()]
                 + [("gauge", g, g.value) for g in _GAUGES.values()]
                 + [("histogram", h, h.snapshot())
                    for h in _HISTOGRAMS.values()])
    for kind, m, value in items:
        yield kind, m.name, m.labels, m.key, value


# ---------------------------------------------------------------------------
# the run ledger
# ---------------------------------------------------------------------------

def _jsonable(v: Any) -> Any:
    """Strict-JSON coercion: numpy scalars/arrays to Python, non-finite
    floats to ``None`` (a ledger line must parse under any strict
    reader — the same bug class satellite 1 fixes in MetricsLogger)."""
    if isinstance(v, float):
        return v if math.isfinite(v) else None
    if isinstance(v, (str, int, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if hasattr(v, "item") and getattr(v, "ndim", None) in (0, None):
        try:
            return _jsonable(v.item())
        except Exception:
            pass
    if hasattr(v, "tolist"):
        try:
            return _jsonable(v.tolist())
        except Exception:
            pass
    return str(v)


def run_id_from_fingerprint(fingerprint: Optional[dict]) -> str:
    """The run identity: a stable digest of the flight-recorder
    fingerprint (config digest, integrator spec, engine chain,
    versions, platform — :meth:`FlightRecorder.fingerprint`). The SAME
    fingerprint yields the same ``run_id``, which is what lets a
    ledger, an incident capsule, a heartbeat, and a ``ckpt_fsck``
    report cross-reference one run."""
    if not fingerprint:
        # no fingerprint available (bare tooling): a random identity
        # still correlates the records of THIS process's ledger
        return hashlib.sha256(os.urandom(16)).hexdigest()[:16]
    blob = json.dumps(_jsonable(fingerprint), sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


_PROC_OK = re.compile(r"[^A-Za-z0-9_.-]")


def shard_path(path: str, proc) -> str:
    """The ledger-shard filename for one process of a multi-process
    run: a directory (or a ``.../ledger.jsonl`` path) becomes
    ``.../ledger-<proc>.jsonl``. Every host of a pod run passes the
    SAME ``path`` and its own ``proc`` (``jax.process_index()``), so
    the shards land side by side for :mod:`ibamr_tpu.obs.merge`."""
    p = _PROC_OK.sub("_", str(proc)) or "0"
    if os.path.isdir(path) or path.endswith(os.sep):
        return os.path.join(path, f"ledger-{p}.jsonl")
    d, base = os.path.split(path)
    root, ext = os.path.splitext(base or "ledger.jsonl")
    return os.path.join(d, f"{root}-{p}{ext or '.jsonl'}")


class RunLedger:
    """Per-run append-only ``ledger.jsonl``.

    Every record is one line: ``{"seq": N, "run_id": ..., "t": ...,
    "kind": ..., ...payload}``. ``seq`` is monotonic per ledger FILE —
    reopening an existing ledger (a resumed run) continues the
    sequence, so cross-references stay unambiguous across restarts.
    Each line lands in a single ``os.write`` on an ``O_APPEND`` fd:
    a kill between records cannot tear a committed line, and
    :func:`read_ledger` tolerates (skips) a torn final line from a
    kill mid-write. ``overhead_s`` accumulates the wall cost of every
    append — the observability bill, kept in-band so the <2% budget is
    enforced, not promised.

    ``proc`` (PR 15) is the process identity of a multi-host run:
    ``None`` (the default) keeps single-process behavior bit-for-bit;
    a process index reroutes the file to :func:`shard_path`'s
    ``ledger-<proc>.jsonl`` and stamps ``proc`` on every record, while
    ``run_id`` — a fingerprint digest, identical on every host of the
    same run — stays the cross-shard join key."""

    def __init__(self, path: str,
                 fingerprint: Optional[dict] = None,
                 run_id: Optional[str] = None,
                 proc: Optional[object] = None):
        self.proc = None if proc is None else str(proc)
        if self.proc is not None:
            path = shard_path(path, self.proc)
        self.path = path
        self.run_id = run_id or run_id_from_fingerprint(fingerprint)
        self.overhead_s = 0.0
        self._lock = threading.Lock()
        d = os.path.dirname(path) or "."
        os.makedirs(d, exist_ok=True)
        self._seq = -1
        if os.path.exists(path):
            for rec in read_ledger(path):
                if rec["seq"] > self._seq:
                    self._seq = rec["seq"]
        self._fd = os.open(path,
                           os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                           0o644)
        self.append("run_start", {
            "schema": LEDGER_SCHEMA,
            "pid": os.getpid(),
            "fingerprint": _jsonable(fingerprint)
            if fingerprint else None})

    @property
    def last_seq(self) -> int:
        return self._seq

    def append(self, kind: str, payload: Optional[dict] = None) -> int:
        """Append one record; returns its ``seq``."""
        t0 = time.perf_counter()
        rec = dict(_jsonable(payload or {}))
        if self.proc is not None and "proc" not in rec:
            rec["proc"] = self.proc
        with self._lock:
            self._seq += 1
            rec.update(seq=self._seq, run_id=self.run_id,
                       t=round(time.time(), 6), kind=str(kind))
            line = (json.dumps(rec) + "\n").encode()
            os.write(self._fd, line)
            seq = self._seq
        self.overhead_s += time.perf_counter() - t0
        return seq

    def close(self) -> None:
        if self._fd is None:
            return
        with self._lock:
            fd, self._fd = self._fd, None
        try:
            os.fsync(fd)
        except OSError:
            pass
        os.close(fd)

    def __enter__(self) -> "RunLedger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_ledger(path: str) -> list:
    """Parse a ledger, SKIPPING any line that does not parse or lacks a
    ``seq`` — a kill mid-write leaves at most one torn final line, and
    a strict reader must never accept it as a record."""
    out = []
    try:
        with open(path, "rb") as f:
            for raw in f:
                try:
                    rec = json.loads(raw)
                except ValueError:
                    continue
                if isinstance(rec, dict) and isinstance(
                        rec.get("seq"), int):
                    out.append(rec)
    except OSError:
        return []
    return out


# ---------------------------------------------------------------------------
# the process-current ledger
# ---------------------------------------------------------------------------

_CURRENT: Optional[RunLedger] = None


def attach(ledger_: RunLedger) -> Optional[RunLedger]:
    """Make ``ledger_`` the process-current sink; returns the previous
    one (caller re-attaches it when nesting)."""
    global _CURRENT
    prev, _CURRENT = _CURRENT, ledger_
    return prev


def detach() -> Optional[RunLedger]:
    global _CURRENT
    prev, _CURRENT = _CURRENT, None
    return prev


def current() -> Optional[RunLedger]:
    return _CURRENT


def last_seq() -> Optional[int]:
    led = _CURRENT
    return led.last_seq if led is not None else None


def emit(kind: str, **payload) -> Optional[int]:
    """Append to the current ledger; ``None`` when none is attached
    (telemetry-off runs pay nothing). Records emitted inside a
    :func:`trace_scope` are stamped with the active trace identity
    unless the payload already carries one."""
    led = _CURRENT
    if led is None:
        return None
    _stamp_trace(payload)
    return led.append(kind, payload)


@contextmanager
def ledger(path: str, fingerprint: Optional[dict] = None,
           run_id: Optional[str] = None,
           proc: Optional[object] = None):
    """Open, attach, and on exit detach + fsync-close a run ledger."""
    led = RunLedger(path, fingerprint=fingerprint, run_id=run_id,
                    proc=proc)
    prev = attach(led)
    try:
        yield led
    finally:
        led.append("run_end", {"overhead_s": round(led.overhead_s, 6)})
        if current() is led:
            detach()
        if prev is not None:
            attach(prev)
        led.close()


# ---------------------------------------------------------------------------
# trace identity: request-scoped correlation across ledger records
# ---------------------------------------------------------------------------

_TLS = threading.local()


def new_trace_id() -> str:
    """Mint a request-scoped trace identity (16 hex). Unlike
    ``run_id`` — a digest of the run fingerprint, the root of the
    trace tree — a trace_id names ONE request's path through the
    process: admission, bucket wait, any compile it paid for, ack,
    cruise chunks, completion or quarantine."""
    return hashlib.sha256(os.urandom(16)).hexdigest()[:16]


def _trace_stack() -> list:
    st = getattr(_TLS, "trace", None)
    if st is None:
        st = _TLS.trace = []
    return st


def current_trace() -> Tuple[str, ...]:
    """The innermost active trace identity — ``()`` outside any
    :func:`trace_scope`. Thread-local: a worker thread doing traced
    work on a request's behalf must enter its own scope (the router
    hands the waiting requests' ids to the background pool build)."""
    st = getattr(_TLS, "trace", None)
    return st[-1] if st else ()


@contextmanager
def trace_scope(*trace_ids):
    """Attribute everything emitted in this block — ledger records via
    :func:`emit`, closing spans, capsule manifests — to the given
    trace id(s). A batch serving several requests carries all their
    ids; ``None`` entries are dropped so callers can pass optional
    ids straight through."""
    ids = tuple(str(t) for t in trace_ids if t)
    st = _trace_stack()
    st.append(ids)
    try:
        yield ids
    finally:
        st.pop()


def _stamp_trace(payload: dict) -> None:
    """Stamp the active trace identity into a ledger payload (single
    id as ``trace_id``, several as ``trace_ids``) unless the caller
    already set one explicitly — explicit beats ambient, so a
    per-lane record can name ITS request inside a batch scope."""
    if "trace_id" in payload or "trace_ids" in payload:
        return
    ids = current_trace()
    if not ids:
        return
    if len(ids) == 1:
        payload["trace_id"] = ids[0]
    else:
        payload["trace_ids"] = list(ids)


def record_trace_ids(rec: dict) -> Tuple[str, ...]:
    """Every trace id a ledger record names (reader-side helper:
    ``tools/obs.py trace`` matches on this)."""
    ids = []
    if rec.get("trace_id"):
        ids.append(str(rec["trace_id"]))
    for t in rec.get("trace_ids") or ():
        ids.append(str(t))
    return tuple(ids)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


@contextmanager
def span(name: str, block_on=None, **attrs):
    """One nested wall-clock span.

    Enters ``jax.named_scope`` with the leaf name so the phase also
    lands in on-chip profiler traces; on exit optionally blocks on
    ``block_on`` (a pytree of arrays — the async-dispatch discipline
    from ``utils/timers.py``) BEFORE reading the clock, then closes
    the span into the current ledger (kind ``span``, with the full
    slash ``path`` so readers rebuild the tree without matching
    open/close pairs). Without an attached ledger the cost is two
    clock reads and a list push/pop."""
    import jax

    st = _stack()
    st.append(str(name))
    path = "/".join(st)
    depth = len(st) - 1
    t0 = time.perf_counter()
    err = None
    try:
        with jax.named_scope(str(name).split("::")[-1].split("/")[-1]):
            yield
    except BaseException as e:
        err = type(e).__name__
        raise
    finally:
        if block_on is not None:
            try:
                jax.block_until_ready(block_on)
            except Exception:
                pass
        dur = time.perf_counter() - t0
        st.pop()
        led = _CURRENT
        if led is not None:
            payload = {"name": str(name), "path": path, "depth": depth,
                       "dur_s": round(dur, 9)}
            if attrs:
                payload["attrs"] = attrs
            if err is not None:
                payload["error"] = err
            _stamp_trace(payload)
            led.append("span", payload)


# ---------------------------------------------------------------------------
# chunk boundaries: counters snapshot + device-memory watermarks
# ---------------------------------------------------------------------------

def sample_memory_watermarks() -> int:
    """Read ``memory_stats()`` from every local device into
    ``device_bytes_in_use`` / ``device_peak_bytes_in_use`` gauges
    (labeled by device id). Returns the number of gauge samples taken;
    0 — a clean no-op — wherever the backend does not report memory
    stats (the CPU backend returns None / raises)."""
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return 0
    sampled = 0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            continue
        if not stats:
            continue
        for src, gname in (("bytes_in_use", "device_bytes_in_use"),
                           ("peak_bytes_in_use",
                            "device_peak_bytes_in_use")):
            if src in stats:
                gauge(gname, device=str(getattr(d, "id", "?"))).set(
                    stats[src])
                sampled += 1
    return sampled


def chunk_boundary(step: Optional[int] = None,
                   chunk_wall_s: Optional[float] = None) -> Optional[int]:
    """Per-chunk telemetry flush, called by the driver at the existing
    post-chunk host sync (the one-transfer-per-chunk point). Samples
    device-memory watermarks, snapshots every counter/gauge, and
    appends ONE ``counters`` record. A no-op returning ``None`` when
    no ledger is attached — an un-instrumented run pays zero."""
    led = _CURRENT
    if led is None:
        return None
    t0 = time.perf_counter()
    sample_memory_watermarks()
    snap = metrics_snapshot()
    extra = time.perf_counter() - t0   # append() accounts for itself
    led.overhead_s += extra
    rec = {
        "step": step,
        "chunk_wall_s": chunk_wall_s,
        "counters": snap["counters"],
        "gauges": snap["gauges"],
        "obs_overhead_s": round(led.overhead_s, 6)}
    if snap["histograms"]:
        rec["histograms"] = snap["histograms"]
    return led.append("counters", rec)
