"""Roofline join: attributed device time x graph-census counts (PR 10).

PR 8's ``graph_census`` counts what a step graph *moves and computes*
(``fft_bytes``, ``dot_flops`` — static, from the jaxpr); PR 10's
``deviceprof`` measures where device time *went* (dynamic, from the
profiler trace). Neither alone answers the question ROADMAP item 3
keeps open — "are the hot loops near the machine's roof, or is there
headroom?" — because bytes without seconds give no bandwidth and
seconds without bytes give no efficiency. This module is the join:

    achieved FFT GB/s   = fft_bytes_per_step / fft_seconds_per_step
    achieved dot GFLOP/s = dot_flops_per_step / dot_seconds_per_step
    achieved comm GB/s  = wire_bytes_per_step / comm_seconds_per_step
    fraction_of_step_accounted = (fft_s + dot_s + comm_s) / total_device_s

    The comm wire-bytes proxy is ``collective_bytes -
    pbroadcast_bytes`` from the PR-15 ``collective_census``:
    ``pbroadcast`` prims are shard_map's replication-tracking
    bookkeeping and lower to no-ops, so counting their avals would
    flatter the interconnect rate.

The census side arrives as the ``census_counts.json`` sidecar
``bench.py`` writes into each ``--profile-stages`` capture dir at
capture time (when the jaxpr is still in hand); the time side is the
``op_classes`` table :func:`deviceprof.attribute_events` tallies from
the trace. ``executions`` (how many step/chunk launches ran under the
capture) normalizes both to per-execution numbers.

Like ``deviceprof``, this is offline and host-side: stdlib only, pure
functions over two dicts. No peak-bandwidth table is hardcoded — the
CPU backend this repo tests on has no meaningful roof, and the TPU
roof belongs in the reader's head (or a future budgets file), not
baked into the artifact. The artifact reports *achieved* rates;
"fraction of roof" is a presentation-layer division.
"""

from __future__ import annotations

from typing import List, Optional

__all__ = ["roofline_join", "census_sidecar", "render_roofline"]


def _get(d: dict, key: str, default=0):
    v = d.get(key, default)
    return v if isinstance(v, (int, float)) else default


def roofline_join(summary: dict, census: dict) -> Optional[dict]:
    """Join one attribution summary with its census sidecar.

    ``summary`` needs ``op_classes`` (``fft_s``/``dot_s``) and
    ``total_device_s``; ``census`` needs the ``fft_census``/
    ``dot_census`` byte/flop counts plus ``executions``. Returns the
    roofline block for ``prof_summary.json``, or None when the join is
    impossible (no executions recorded, or no device time)."""
    execs = _get(census, "executions")
    op_classes = summary.get("op_classes") or {}
    total = _get(summary, "total_device_s")
    if execs <= 0 or total <= 0:
        return None
    fft_s = _get(op_classes, "fft_s")
    dot_s = _get(op_classes, "dot_s")
    comm_s = _get(op_classes, "comm_s")
    fft_bytes = _get(census, "fft_bytes")
    dot_bytes = (_get(census, "dot_lhs_bytes")
                 + _get(census, "dot_rhs_bytes")
                 + _get(census, "dot_out_bytes"))
    dot_flops = _get(census, "dot_flops")
    # wire-bytes proxy: pbroadcast is replication bookkeeping that
    # lowers to no-ops — subtract it so achieved GB/s is honest
    comm_bytes = max(0, _get(census, "collective_bytes")
                     - _get(census, "pbroadcast_bytes"))
    out = {
        "executions": int(execs),
        "device_s_per_execution": round(total / execs, 9),
        "fft": None,
        "dot": None,
        "comm": None,
        # how much of the measured device time the censused op classes
        # explain — low values mean the step is dominated by ops the
        # census does not model (elementwise fusions, copies)
        "fraction_of_step_accounted": round(
            (fft_s + dot_s + comm_s) / total, 6),
    }
    if fft_bytes > 0 and fft_s > 0:
        per_exec_s = fft_s / execs
        out["fft"] = {
            "bytes_per_execution": int(fft_bytes),
            "device_s_per_execution": round(per_exec_s, 9),
            "achieved_gb_per_s": round(fft_bytes / per_exec_s / 1e9, 3),
            "fft_ops": int(_get(census, "fft_ops")),
        }
    if dot_flops > 0 and dot_s > 0:
        per_exec_s = dot_s / execs
        out["dot"] = {
            "flops_per_execution": int(dot_flops),
            "bytes_per_execution": int(dot_bytes),
            "device_s_per_execution": round(per_exec_s, 9),
            "achieved_gflop_per_s": round(
                dot_flops / per_exec_s / 1e9, 3),
            "achieved_gb_per_s": round(dot_bytes / per_exec_s / 1e9, 3)
            if dot_bytes > 0 else None,
            "dot_count": int(_get(census, "dot_count")),
        }
    if comm_bytes > 0 and comm_s > 0:
        per_exec_s = comm_s / execs
        out["comm"] = {
            # per-device wire traffic (shard_map avals are per-shard)
            "bytes_per_execution": int(comm_bytes),
            "device_s_per_execution": round(per_exec_s, 9),
            "achieved_gb_per_s": round(
                comm_bytes / per_exec_s / 1e9, 3),
            "collective_prims": int(_get(census, "collective_prims")),
        }
    return out


def census_sidecar(fn, args, label: str = "",
                   executions: int = 0, **extra) -> dict:
    """Build the ``census_counts.json`` document for one captured
    stage: trace ``fn(*args)`` (trace only — no compile) and run the
    PR-8 byte/flop censuses over the jaxpr. Called by ``bench.py`` at
    capture time, when the step function and its arguments are still
    in hand; everything downstream is offline."""
    import jax

    from ibamr_tpu.analysis.graph_census import (collective_census,
                                                 dot_census, fft_census)

    jaxpr = jax.make_jaxpr(fn)(*args).jaxpr
    out = {"schema": 1, "label": label, "executions": int(executions)}
    out.update(fft_census(jaxpr))
    out.pop("fft_transforms", None)       # shapes, not needed downstream
    out.update(dot_census(jaxpr))
    out.update(collective_census(jaxpr))
    out.update(extra)
    return out


def render_roofline(roofline: Optional[dict]) -> List[str]:
    """Human lines for ``tools/prof.py show``."""
    if not roofline:
        return ["  (no roofline: census sidecar or executions missing)"]
    lines = [
        f"  executions: {roofline.get('executions')}   "
        f"device {roofline.get('device_s_per_execution', 0) * 1e3:.3f} "
        f"ms/execution   "
        f"accounted by fft+dot+comm: "
        f"{100.0 * (roofline.get('fraction_of_step_accounted') or 0):.1f}%"
    ]
    fft = roofline.get("fft")
    if fft:
        lines.append(
            f"  fft: {fft['bytes_per_execution'] / 1e6:.2f} MB/exec in "
            f"{fft['device_s_per_execution'] * 1e3:.3f} ms -> "
            f"{fft['achieved_gb_per_s']:.2f} GB/s achieved "
            f"({fft['fft_ops']} transforms)")
    dot = roofline.get("dot")
    if dot:
        gb = (f", {dot['achieved_gb_per_s']:.2f} GB/s"
              if dot.get("achieved_gb_per_s") else "")
        lines.append(
            f"  dot: {dot['flops_per_execution'] / 1e6:.2f} MFLOP/exec "
            f"in {dot['device_s_per_execution'] * 1e3:.3f} ms -> "
            f"{dot['achieved_gflop_per_s']:.2f} GFLOP/s achieved{gb} "
            f"({dot['dot_count']} contractions)")
    comm = roofline.get("comm")
    if comm:
        lines.append(
            f"  comm: {comm['bytes_per_execution'] / 1e6:.2f} MB/exec "
            f"(per device, wire) in "
            f"{comm['device_s_per_execution'] * 1e3:.3f} ms -> "
            f"{comm['achieved_gb_per_s']:.2f} GB/s achieved "
            f"({comm['collective_prims']} collectives)")
    return lines
