"""One pane of glass: the process-wide telemetry bus (PR 9).

Three primitives, one correlated stream per run:

- **spans** — nested wall-clock phases (:func:`span`), async-dispatch
  aware (``block_on=`` a pytree, the ``utils/timers.py`` discipline)
  and mirrored into ``jax.named_scope`` so phase names land in on-chip
  profiler traces;
- **counters / gauges** — a labeled metric registry (:func:`counter`,
  :func:`gauge`) every subsystem publishes into: spectral-plan cache
  hits, engine fallbacks, checkpoint queue depth, supervisor retries,
  lane triage, replay verdicts, device-memory watermarks;
- **the run ledger** — a per-run append-only ``ledger.jsonl``
  (:class:`RunLedger`): spans close into it, counters snapshot into it
  at every chunk boundary, incidents and heartbeats cross-reference it
  by ``seq``, and every record carries the flight-recorder run
  fingerprint digest as ``run_id``.

The non-negotiable constraint: telemetry adds ZERO host transfers
inside the scanned chunk (pinned by the ``*_telemetry`` graph-contract
artifacts) and <2% warm-chunk wall overhead (self-accounted in
``RunLedger.overhead_s``, pinned like the flight recorder's). All
host-side work rides the existing one-transfer-per-chunk sync points.

PR 10 adds the read-back half: :mod:`ibamr_tpu.obs.deviceprof` parses
``jax.profiler`` captures and attributes device-lane op time back to
span paths (the ledger's ``device_time`` record / ``prof_summary.json``
artifact), and :mod:`ibamr_tpu.obs.roofline` joins that time with the
PR-8 graph-census byte/flop counts into achieved-bandwidth numbers.
Both are offline, stdlib-only, and imported lazily here — attaching a
ledger to a run never pays for the trace parser.

PR 15 adds pod scope: ``RunLedger(..., proc=...)`` routes each process
of a multi-host run to its own ``ledger-<proc>.jsonl`` shard (same
``run_id`` everywhere), :mod:`ibamr_tpu.obs.merge` interleaves the
shards deterministically (``(seq, proc)`` order, torn-tail tolerant,
per-proc counter namespacing), and the device-time attribution grows a
``comm_s`` op-class so collective time is a first-class rollup.

See docs/OBSERVABILITY.md for the ledger schema and the CLI cookbook
(``tools/obs.py summary | tail | compare``,
``tools/prof.py attribute | diff | archive``).
"""

from ibamr_tpu.obs.bus import (  # noqa: F401
    HISTOGRAM_BOUNDS,
    Histogram,
    LEDGER_SCHEMA,
    RunLedger,
    attach,
    chunk_boundary,
    counter,
    current,
    current_trace,
    describe,
    detach,
    emit,
    gauge,
    help_for,
    histogram,
    last_seq,
    ledger,
    metrics_snapshot,
    new_trace_id,
    peek_gauge,
    quantiles_from_counts,
    read_ledger,
    record_trace_ids,
    reset_metrics,
    run_id_from_fingerprint,
    sample_memory_watermarks,
    shard_path,
    span,
    trace_scope,
)
from ibamr_tpu.obs.export import (  # noqa: F401
    prometheus_text,
    write_prometheus,
)
from ibamr_tpu.obs.merge import (  # noqa: F401
    find_shards,
    fleet_counters,
    fleet_prometheus_text,
    merge_ledgers,
)
