"""Device-time attribution: join profiler traces back to spans (PR 10).

PR 9 closed the host half of the observability loop — every phase is a
ledger span, and ``obs.span`` enters ``jax.named_scope`` so device
traces are *annotated* — but nothing ever read a trace back: the
``bench.py --profile-stages`` captures landed as raw
``*.trace.json.gz`` files no tool parsed. This module is the read-back
half. It parses the trace-viewer JSON inside a ``jax.profiler``
capture directory, extracts the device-lane op events, and attributes
each op's time to a span path, producing the per-span
``device_time_s`` table that merges with the host span tree
(``tools/obs.py summary --device``) and the ``prof_summary.json``
artifact ``tools/prof.py diff`` gates perf drift on.

Attribution is LAYERED, because the two backends annotate differently:

1. **scope prefix** — TPU/GPU op events carry the framework op path
   (``tf_op``/``op_name`` args, e.g. ``jit(step)/interp/sin``) whose
   components are exactly the ``jax.named_scope`` names ``obs.span``
   entered; the deepest component matching a known span LEAF wins.
2. **module name** — the CPU (TFRT) backend tags op events only with
   ``{"hlo_module": "jit_chunk", "hlo_op": "fusion.3"}``; the module
   name, normalized (``jit_chunk`` -> ``chunk``), is matched against
   span leaves (so the driver's ``driver/chunk`` span claims every op
   of its compiled chunk), then against an explicit ``module_map``.
3. **module identity** — an op whose module resolves to no span is
   still grouped under its module name (``attributed`` to a named
   home, just not a span) so bench captures with no ledger attached
   remain comparable across revisions.

Anything left — no scope, no module — lands in an EXPLICIT
``unattributed`` breakdown keyed by event name. The invariant
``attributed_s + unattributed_s == total_device_s`` is part of the
summary schema (:func:`validate_summary`), so a parser bug that drops
time fails the schema check instead of silently flattering a capture.

Everything here is offline and host-side: stdlib only, no jax import,
usable on a machine that never saw the accelerator.
"""

from __future__ import annotations

import glob
import gzip
import json
import math
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

PROF_SCHEMA = 1
SUMMARY_NAME = "prof_summary.json"
CENSUS_NAME = "census_counts.json"

# trace-viewer process names that mark an accelerator timeline
_DEVICE_PROC_RE = re.compile(r"/device:|^TPU|^GPU", re.IGNORECASE)
# thread names that are op lanes on TPU/GPU timelines (preferred over
# "XLA Modules"/"Steps" rows, which overlap the op rows and would
# double-count every nanosecond)
_OP_LANE_RE = re.compile(r"XLA Ops|TensorFlow Ops", re.IGNORECASE)
# args keys that can carry a slash-separated framework scope path
_SCOPE_ARG_KEYS = ("tf_op", "op_name", "long_name", "name", "scope")
# op-class buckets for the roofline join: FFT ops, contractions, and
# (PR 15) collectives.  A device op is comm when its HLO opcode is a
# collective (sync or async -start/-done halves) OR its framework scope
# path passes through a ``comm`` component — the named scope the
# parallel layer (fftpar/lagrangian/mesh/norms/krylov) wraps every
# cross-device exchange in — so partitioner-materialized resharding
# that keeps a fused non-collective opcode still lands in ``comm_s``.
_FFT_OP_RE = re.compile(r"(^|[./])i?r?fft", re.IGNORECASE)
_DOT_OP_RE = re.compile(r"(^|[./])(dot|convolution|gemm|matmul)",
                        re.IGNORECASE)
_COMM_OP_RE = re.compile(
    r"(^|[./])(all-reduce|all-gather|all-to-all|collective-permute|"
    r"reduce-scatter|collective-broadcast)(-start|-done)?(\.|$)",
    re.IGNORECASE)
_COMM_SCOPE = "comm"


# ---------------------------------------------------------------------------
# capture-dir / trace-file plumbing
# ---------------------------------------------------------------------------

def find_trace_files(capture_dir: str) -> List[str]:
    """Every trace-viewer JSON in a ``jax.profiler`` capture dir
    (``<dir>/plugins/profile/<ts>/<host>.trace.json.gz`` — one per
    host; plain ``.trace.json`` accepted for hand-built fixtures)."""
    out: List[str] = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        out.extend(glob.glob(os.path.join(capture_dir, pat),
                             recursive=True))
    return sorted(set(out))


def load_trace(path: str) -> dict:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        return json.loads(f.read())


def capture_bytes(capture_dir: str) -> int:
    """Total on-disk bytes of a capture directory."""
    total = 0
    for root, _, files in os.walk(capture_dir):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(root, name))
            except OSError:
                pass
    return total


# ---------------------------------------------------------------------------
# device-lane op events
# ---------------------------------------------------------------------------

def _lane_meta(trace: dict) -> Tuple[Dict[int, str], Dict[tuple, str]]:
    """(pid -> process name, (pid, tid) -> thread name) from the
    trace's metadata ('M') events."""
    procs: Dict[int, str] = {}
    threads: Dict[tuple, str] = {}
    for e in trace.get("traceEvents") or []:
        if e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name":
            procs[e.get("pid")] = str(args.get("name", ""))
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = \
                str(args.get("name", ""))
    return procs, threads


def device_op_events(trace: dict) -> Tuple[List[dict], List[dict]]:
    """(op events, device-lane descriptions) for one trace.

    TPU/GPU timelines: processes named ``/device:*`` — take the
    ``XLA Ops`` threads (falling back to every thread of the device
    process when no lane is labeled), and count every complete ('X')
    event there as device-op time. CPU (TFRT) timelines: there is no
    device process, and the executor's op events are scattered across
    pool threads — an op event is exactly an X event carrying
    ``hlo_op``/``hlo_module`` args, wherever it sits (the python host
    thread's function-trace events carry neither and are excluded).
    """
    procs, threads = _lane_meta(trace)
    dev_pids = {pid for pid, name in procs.items()
                if _DEVICE_PROC_RE.search(name or "")}
    op_lanes = {key for key, name in threads.items()
                if key[0] in dev_pids and _OP_LANE_RE.search(name or "")}
    labeled_pids = {pid for pid, _ in op_lanes}
    events: List[dict] = []
    lane_busy: Dict[tuple, dict] = {}
    for e in trace.get("traceEvents") or []:
        if e.get("ph") != "X":
            continue
        key = (e.get("pid"), e.get("tid"))
        args = e.get("args") or {}
        if key[0] in dev_pids:
            # device process: only labeled op lanes when any exist FOR
            # THIS pid (module/step rows overlap the op rows)
            if key[0] in labeled_pids and key not in op_lanes:
                continue
        elif "hlo_op" not in args and "hlo_module" not in args:
            continue                      # host-side python/runtime event
        events.append(e)
        lane = lane_busy.setdefault(key, {
            "pid": key[0], "tid": key[1],
            "process": procs.get(key[0], ""),
            "thread": threads.get(key, ""),
            "events": 0, "busy_s": 0.0})
        lane["events"] += 1
        lane["busy_s"] += float(e.get("dur") or 0.0) / 1e6
    lanes = sorted(lane_busy.values(),
                   key=lambda d: -(d["busy_s"]))
    for d in lanes:
        d["busy_s"] = round(d["busy_s"], 9)
    return events, lanes


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------

def _norm_component(comp: str) -> str:
    """``jit(step)`` -> ``step``; ``transpose[permutation=...]`` ->
    ``transpose``; named-scope components pass through."""
    comp = comp.split("[")[0].strip()
    m = re.match(r"^(?:p?jit|vmap|scan|while|named)\((.*)\)$", comp)
    if m:
        comp = m.group(1)
    return comp


def _norm_module(module: str) -> str:
    """``jit_chunk`` / ``jit__chunk`` / ``jit_step.7`` -> ``chunk`` /
    ``chunk`` / ``step`` — the wrapped function's name, which is what
    a span leaf can plausibly match."""
    m = re.sub(r"\.\d+$", "", str(module))
    m = re.sub(r"^(?:p?jit_+)", "", m)
    return m.strip("_") or str(module)


def _scope_components(event: dict) -> List[str]:
    """The framework scope path of one op event, as components, or []
    when the event carries none (the CPU backend)."""
    args = event.get("args") or {}
    for key in _SCOPE_ARG_KEYS:
        v = args.get(key)
        if isinstance(v, str) and "/" in v:
            return [c for c in v.split("/") if c]
    name = event.get("name")
    if isinstance(name, str) and "/" in name:
        return [c for c in name.split("/") if c]
    return []


def span_leaf_map(span_paths: Iterable[str]) -> Dict[str, str]:
    """leaf name -> full span path. ``obs.span`` enters
    ``jax.named_scope`` with the LEAF of the span name (everything
    after the last ``/`` and ``::``), so the leaf is the token that can
    appear inside a trace. Ambiguous leaves resolve to the SHALLOWEST
    path (deterministic: sorted by depth then name)."""
    leaf_map: Dict[str, str] = {}
    for path in sorted(set(span_paths),
                       key=lambda p: (p.count("/"), p)):
        leaf = path.split("/")[-1].split("::")[-1]
        leaf_map.setdefault(leaf, path)
    return leaf_map


def _resolve(event: dict, leaf_map: Dict[str, str],
             module_map: Dict[str, str]):
    """(key, via) for one op event — ``via`` in {"scope", "module",
    "module-name"} — or (None, None) when nothing identifies it."""
    comps = _scope_components(event)
    for comp in reversed(comps):
        leaf = _norm_component(comp)
        if leaf in leaf_map:
            return leaf_map[leaf], "scope"
    module = (event.get("args") or {}).get("hlo_module")
    if module:
        if module in module_map:
            return module_map[module], "module"
        norm = _norm_module(module)
        if norm in module_map:
            return module_map[norm], "module"
        if norm in leaf_map:
            return leaf_map[norm], "module"
        return norm, "module-name"
    return None, None


def attribute_events(events: List[dict],
                     span_paths: Iterable[str] = (),
                     module_map: Optional[Dict[str, str]] = None,
                     max_ops: int = 16) -> dict:
    """Attribute device-op events to span paths.

    Returns the core of a :data:`SUMMARY_NAME` document; every second
    of device-lane time lands either in ``spans`` (attributed — via
    scope prefix, module match, or module identity) or in the explicit
    ``unattributed`` breakdown. ``op_classes`` tallies
    FFT/contraction/collective op time for the roofline join; the
    classes partition ``total_device_s`` exactly (``other_s`` is the
    remainder), independent of the span accounting identity."""
    leaf_map = span_leaf_map(span_paths)
    module_map = dict(module_map or {})
    spans: Dict[str, dict] = {}
    unattributed: Dict[str, float] = {}
    total = attributed = 0.0
    fft_s = dot_s = comm_s = 0.0
    for e in events:
        dur = float(e.get("dur") or 0.0) / 1e6
        total += dur
        opname = str((e.get("args") or {}).get("hlo_op")
                     or e.get("name") or "?")
        # comm wins over fft/dot: a collective (or an op inside the
        # parallel layer's ``comm`` named scope) is wire time even when
        # its fused opcode also mentions a compute class
        if _COMM_OP_RE.search(opname) or any(
                _norm_component(c) == _COMM_SCOPE
                for c in _scope_components(e)):
            comm_s += dur
        elif _FFT_OP_RE.search(opname):
            fft_s += dur
        elif _DOT_OP_RE.search(opname):
            dot_s += dur
        key, via = _resolve(e, leaf_map, module_map)
        if key is None:
            unattributed[opname] = unattributed.get(opname, 0.0) + dur
            continue
        attributed += dur
        node = spans.setdefault(key, {"device_s": 0.0, "events": 0,
                                      "via": {}, "ops": {}})
        node["device_s"] += dur
        node["events"] += 1
        node["via"][via] = node["via"].get(via, 0) + 1
        node["ops"][opname] = node["ops"].get(opname, 0.0) + dur
    for node in spans.values():
        node["device_s"] = round(node["device_s"], 9)
        top = sorted(node["ops"].items(), key=lambda kv: -kv[1])
        node["ops"] = {k: round(v, 9) for k, v in top[:max_ops]}
    return {
        "total_device_s": round(total, 9),
        "attributed_s": round(attributed, 9),
        "unattributed_s": round(total - attributed, 9),
        "fraction_attributed": round(attributed / total, 6)
        if total > 0 else 1.0,
        "spans": spans,
        "unattributed": {
            k: round(v, 9)
            for k, v in sorted(unattributed.items(),
                               key=lambda kv: -kv[1])[:max_ops]},
        "op_classes": {"fft_s": round(fft_s, 9),
                       "dot_s": round(dot_s, 9),
                       "comm_s": round(comm_s, 9),
                       "other_s": round(total - fft_s - dot_s
                                        - comm_s, 9)},
    }


def spans_from_ledger(ledger_path: str) -> List[str]:
    """Distinct span paths recorded in a run ledger (the PR-9 host
    side of the join)."""
    from ibamr_tpu.obs.bus import read_ledger

    return sorted({r.get("path") or r.get("name")
                   for r in read_ledger(ledger_path)
                   if r.get("kind") == "span"
                   and (r.get("path") or r.get("name"))})


def attribute_capture(capture_dir: str,
                      span_paths: Iterable[str] = (),
                      module_map: Optional[Dict[str, str]] = None,
                      ledger: Optional[str] = None) -> dict:
    """Parse + attribute every trace file in ``capture_dir`` into one
    :data:`SUMMARY_NAME` document. ``ledger`` (a ``ledger.jsonl`` path
    or its directory) contributes its recorded span paths; the
    ``census_counts.json`` sidecar, when present (bench writes it at
    capture time), is joined into a roofline block."""
    paths = list(span_paths)
    if ledger:
        if os.path.isdir(ledger):
            ledger = os.path.join(ledger, "ledger.jsonl")
        paths.extend(spans_from_ledger(ledger))
    files = find_trace_files(capture_dir)
    events: List[dict] = []
    lanes: List[dict] = []
    for f in files:
        ev, ln = device_op_events(load_trace(f))
        events.extend(ev)
        lanes.extend(ln)
    summary = attribute_events(events, paths, module_map)
    summary.update(schema=PROF_SCHEMA,
                   capture_dir=os.path.abspath(capture_dir),
                   trace_files=len(files), lanes=lanes,
                   capture_bytes=capture_bytes(capture_dir))
    census = read_census(capture_dir)
    summary["census"] = census
    if census:
        from ibamr_tpu.obs.roofline import roofline_join

        summary["roofline"] = roofline_join(summary, census)
    else:
        summary["roofline"] = None
    return summary


# ---------------------------------------------------------------------------
# the summary artifact
# ---------------------------------------------------------------------------

def summary_path(path: str) -> str:
    """A directory means its ``prof_summary.json``."""
    if os.path.isdir(path):
        return os.path.join(path, SUMMARY_NAME)
    return path


def write_summary(capture_dir: str, summary: dict) -> str:
    """Atomically land ``prof_summary.json`` next to the capture."""
    path = os.path.join(capture_dir, SUMMARY_NAME)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(summary, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_summary(path: str) -> dict:
    with open(summary_path(path)) as f:
        return json.load(f)


def read_census(capture_dir_or_path: str) -> Optional[dict]:
    path = capture_dir_or_path
    if os.path.isdir(path):
        path = os.path.join(path, CENSUS_NAME)
    try:
        with open(path) as f:
            data = json.load(f)
        return data if isinstance(data, dict) else None
    except (OSError, ValueError):
        return None


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and math.isfinite(v)


def validate_summary(summary) -> List[str]:
    """Schema check; returns problems ([] = valid).

    This is what makes a malformed ``prof_summary.json`` fail LOUDLY
    (``tools/prof.py check`` exits 2) instead of being archived as
    garbage — including the accounting invariant that attributed plus
    unattributed time reconstructs the device total, so time can never
    be silently dropped by a parser bug."""
    probs: List[str] = []
    if not isinstance(summary, dict):
        return ["summary is not an object"]
    if summary.get("schema") != PROF_SCHEMA:
        probs.append(f"schema != {PROF_SCHEMA}: "
                     f"{summary.get('schema')!r}")
    for key in ("total_device_s", "attributed_s", "unattributed_s"):
        v = summary.get(key)
        if not _num(v) or v < 0:
            probs.append(f"{key} not a finite non-negative number: "
                         f"{v!r}")
    frac = summary.get("fraction_attributed")
    if not _num(frac) or not (0.0 <= frac <= 1.0):
        probs.append(f"fraction_attributed outside [0, 1]: {frac!r}")
    spans = summary.get("spans")
    if not isinstance(spans, dict):
        probs.append("spans is not an object")
        spans = {}
    span_sum = 0.0
    for key, node in spans.items():
        dv = node.get("device_s") if isinstance(node, dict) else node
        if not _num(dv) or dv < 0:
            probs.append(f"spans[{key!r}].device_s invalid: {dv!r}")
        else:
            span_sum += dv
    if not isinstance(summary.get("unattributed"), dict):
        probs.append("unattributed breakdown missing")
    if not probs:
        total = summary["total_device_s"]
        tol = max(1e-6, 1e-4 * total)
        if abs(summary["attributed_s"] + summary["unattributed_s"]
               - total) > tol:
            probs.append("attributed_s + unattributed_s != "
                         "total_device_s (time dropped)")
        if abs(span_sum - summary["attributed_s"]) > tol:
            probs.append("sum(spans.device_s) != attributed_s")
    return probs


def compact_summary(summary: dict) -> dict:
    """The embeddable slice (bench JSON ``profiles[*].summary``): the
    tables a diff needs, without per-lane/per-op detail."""
    return {
        "schema": summary.get("schema"),
        "total_device_s": summary.get("total_device_s"),
        "attributed_s": summary.get("attributed_s"),
        "unattributed_s": summary.get("unattributed_s"),
        "fraction_attributed": summary.get("fraction_attributed"),
        "spans": {k: {"device_s": (v.get("device_s")
                                   if isinstance(v, dict) else v)}
                  for k, v in (summary.get("spans") or {}).items()},
        "unattributed": summary.get("unattributed") or {},
        "op_classes": summary.get("op_classes"),
        "census": {k: v for k, v in (summary.get("census") or {}).items()
                   if k in ("label", "n", "executions")} or None,
        "roofline": summary.get("roofline"),
    }


# ---------------------------------------------------------------------------
# pruning (relay_watch archive step)
# ---------------------------------------------------------------------------

_RAW_SUFFIXES = (".trace.json.gz", ".trace.json", ".xplane.pb",
                 ".memory_profile.json.gz", ".overview_page.pb",
                 ".input_pipeline.pb", ".tensorflow_stats.pb",
                 ".kernel_stats.pb", ".hlo_proto.pb")


def prune_raw_traces(capture_dir: str) -> int:
    """Delete the raw multi-MB profiler outputs under ``capture_dir``
    (the ``plugins/profile`` tree), keeping the compact
    ``prof_summary.json`` / ``census_counts.json``. Returns bytes
    freed. Callers MUST validate the summary first — ``tools/prof.py
    archive`` refuses to prune when :func:`validate_summary` fails."""
    freed = 0
    for root, dirs, files in os.walk(capture_dir, topdown=False):
        for name in files:
            if not name.endswith(_RAW_SUFFIXES):
                continue
            path = os.path.join(root, name)
            try:
                freed += os.path.getsize(path)
                os.unlink(path)
            except OSError:
                pass
        for d in dirs:
            try:
                os.rmdir(os.path.join(root, d))   # only if now empty
            except OSError:
                pass
    return freed
