"""Prometheus text-exposition snapshot exporter.

The warm-pool server (docs/SERVING.md) needs a ``/metrics`` endpoint;
everything before it needs the same serialization for artifacts:
:func:`prometheus_text` renders the live registry (or ``counters`` /
``gauges`` / ``histograms`` dicts lifted from a ledger record) in the
Prometheus text format — ``# HELP``/``# TYPE`` headers, sanitized
metric names, escaped label values, and the full cumulative
``_bucket{le=...}`` / ``_sum`` / ``_count`` series per histogram —
and :func:`write_prometheus` lands it atomically so a scraper never
reads a torn file.
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

from ibamr_tpu.obs.bus import HISTOGRAM_BOUNDS, help_for, iter_metrics


def _base_name(key: str) -> str:
    return key.split("{", 1)[0]


def _splice_label(key: str, label: str) -> str:
    """Insert one pre-rendered ``name="value"`` pair into a rendered
    metric key, preserving any labels the key already carries."""
    if "{" in key:
        base, rest = key.split("{", 1)
        return f"{base}{{{label},{rest}"
    return f"{key}{{{label}}}"


def _fmt_value(value: float) -> str:
    v = float(value)
    return repr(int(v)) if v == int(v) else repr(v)


def _fmt_bound(b: float) -> str:
    return f"{b:.6g}"


def _histogram_lines(key: str, snap: dict, lines: list) -> None:
    """Expand one histogram snapshot into the cumulative Prometheus
    series: ``<base>_bucket{le=...}``, ``<base>_sum``, ``<base>_count``."""
    counts = snap.get("counts") or []
    bounds = list(HISTOGRAM_BOUNDS)[: max(len(counts) - 1, 0)]
    cum = 0
    for b, c in zip(bounds, counts):
        cum += int(c)
        le = _splice_label(key, f'le="{_fmt_bound(b)}"')
        base, rest = le.split("{", 1)
        lines.append(f"{base}_bucket{{{rest} {cum}")
    cum = sum(int(c) for c in counts)
    le = _splice_label(key, 'le="+Inf"')
    base, rest = le.split("{", 1)
    lines.append(f"{base}_bucket{{{rest} {cum}")
    if "{" in key:
        base, rest = key.split("{", 1)
        lines.append(f"{base}_sum{{{rest} {_fmt_value(snap.get('sum', 0.0))}")
        lines.append(f"{base}_count{{{rest} {cum}")
    else:
        lines.append(f"{key}_sum {_fmt_value(snap.get('sum', 0.0))}")
        lines.append(f"{key}_count {cum}")


def prometheus_text(counters: Optional[dict] = None,
                    gauges: Optional[dict] = None,
                    histograms: Optional[dict] = None) -> str:
    """Render metrics in the Prometheus text exposition format.

    With no arguments, serializes the LIVE registry. Passing
    ``counters``/``gauges``/``histograms`` dicts (rendered-key ->
    value/snapshot, exactly what a ledger ``counters`` record holds)
    renders a historical snapshot instead — ``tools/obs.py`` uses this
    to export from a ledger of a finished run. Histogram values are
    snapshot dicts ``{"sum", "count", "counts"}``; they expand into
    cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``."""
    samples = []            # (kind, base_name, key, value)
    if counters is None and gauges is None and histograms is None:
        for kind, _name, _labels, key, value in iter_metrics():
            samples.append((kind, _base_name(key), key, value))
    else:
        for key, value in (counters or {}).items():
            samples.append(("counter", _base_name(key), key, value))
        for key, value in (gauges or {}).items():
            samples.append(("gauge", _base_name(key), key, value))
        for key, snap in (histograms or {}).items():
            samples.append(("histogram", _base_name(key), key, snap))

    lines = []
    seen_type = set()
    # group by (kind, base name); stable sort keeps families together
    for kind, base, key, value in sorted(samples, key=lambda s: s[:3]):
        if (kind, base) not in seen_type:
            seen_type.add((kind, base))
            help_text = help_for(base)
            if help_text:
                lines.append(f"# HELP {base} {help_text}")
            lines.append(f"# TYPE {base} {kind}")
        if kind == "histogram":
            _histogram_lines(key, value, lines)
        else:
            lines.append(f"{key} {_fmt_value(value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def write_prometheus(path: str, counters: Optional[dict] = None,
                     gauges: Optional[dict] = None,
                     histograms: Optional[dict] = None) -> str:
    """Atomically write :func:`prometheus_text` to ``path`` (temp +
    ``os.replace``, the repo-wide torn-read discipline)."""
    text = prometheus_text(counters=counters, gauges=gauges,
                           histograms=histograms)
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".metrics-", suffix=".tmp", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path
