"""Prometheus text-exposition snapshot exporter.

The future warm-pool server (ROADMAP item 1) needs a ``/metrics``
endpoint; everything before it needs the same serialization for
artifacts: :func:`prometheus_text` renders the live registry (or a
``counters`` record lifted from a ledger) in the Prometheus text
format — ``# TYPE`` headers, sanitized metric names, escaped label
values — and :func:`write_prometheus` lands it atomically so a
scraper never reads a torn file.
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

from ibamr_tpu.obs.bus import iter_metrics


def _base_name(key: str) -> str:
    return key.split("{", 1)[0]


def prometheus_text(counters: Optional[dict] = None,
                    gauges: Optional[dict] = None) -> str:
    """Render metrics in the Prometheus text exposition format.

    With no arguments, serializes the LIVE registry. Passing
    ``counters``/``gauges`` dicts (rendered-key -> value, exactly what
    a ledger ``counters`` record holds) renders a historical snapshot
    instead — ``tools/obs.py`` uses this to export from a ledger of a
    finished run."""
    samples = []            # (kind, base_name, key, value)
    if counters is None and gauges is None:
        for kind, _name, _labels, key, value in iter_metrics():
            samples.append((kind, _base_name(key), key, value))
    else:
        for key, value in (counters or {}).items():
            samples.append(("counter", _base_name(key), key, value))
        for key, value in (gauges or {}).items():
            samples.append(("gauge", _base_name(key), key, value))

    lines = []
    seen_type = set()
    # group by (kind, base name); stable sort keeps families together
    for kind, base, key, value in sorted(samples):
        if (kind, base) not in seen_type:
            seen_type.add((kind, base))
            lines.append(f"# TYPE {base} {kind}")
        v = float(value)
        text = repr(int(v)) if v == int(v) else repr(v)
        lines.append(f"{key} {text}")
    return "\n".join(lines) + ("\n" if lines else "")


def write_prometheus(path: str, counters: Optional[dict] = None,
                     gauges: Optional[dict] = None) -> str:
    """Atomically write :func:`prometheus_text` to ``path`` (temp +
    ``os.replace``, the repo-wide torn-read discipline)."""
    text = prometheus_text(counters=counters, gauges=gauges)
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".metrics-", suffix=".tmp", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path
