"""Two-level FAC (Fast Adaptive Composite) multigrid preconditioner.

Reference parity: ``FACPreconditioner`` + ``CCPoissonPointRelaxationFACOperator``
(T8, SURVEY.md §2.1) — the V-cycle over AMR levels that smooths on the
refined patch, solves a full-domain coarse correction (with the fine
residual restricted underneath the patch — the defining FAC move), and
interpolates the correction back through the coarse-fine interface.

TPU-first shape: the fine patch is one dense box array, smoothing is
masked red-black half-sweeps (whole-array stencils, no point loops), the
coarse "bottom solve" is a :class:`~ibamr_tpu.solvers.multigrid.PoissonMultigrid`
V-cycle (the hypre-level-solver analog), and the CF interpolation reuses
the quadratic ghost machinery of :mod:`ibamr_tpu.amr`. The whole cycle is
traceable, so it rides inside the jitted FGMRES solve of
:class:`ibamr_tpu.amr_ins.CompositeProjection` as a drop-in ``M``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.amr import (FineBox, fill_fine_ghosts, prolong_cc,
                           restrict_cc)
from ibamr_tpu.amr_ins import _box_cc_laplacian as _box_lap
from ibamr_tpu.bc import DomainBC
from ibamr_tpu.solvers.multigrid import (PoissonMultigrid,
                                         checkerboard_masks)

Array = jnp.ndarray


def _smooth_patch(box: FineBox, dx_f, diag_f, masks, box_sl,
                  e: Array, r: Array, e_parent: Optional[Array],
                  sweeps: int) -> Array:
    """Masked red-black relaxation of lap e = r on one patch level.
    ``e_parent`` supplies CF ghosts (None = homogeneous zero ghosts).
    Shared by the two-level and L-level FAC classes."""
    fine_n = box.fine_n

    def ghosted(e):
        if e_parent is None:
            pad = [(1, 1)] * e.ndim
            return jnp.pad(e, pad)
        e_eff = e_parent.at[box_sl].set(restrict_cc(e))
        return fill_fine_ghosts(e, e_eff, box, ghost=1)

    def sweep(_, e):
        for mask in masks:
            lap = _box_lap(ghosted(e), dx_f, fine_n)
            e = e + jnp.where(mask, (r - lap) / diag_f, 0.0)
        return e

    return jax.lax.fori_loop(0, sweeps, sweep, e)


class FACCompositePoisson:
    """FAC preconditioner for the two-level composite Poisson system of
    :class:`ibamr_tpu.amr_ins.CompositeProjection` (residual pytree
    ``(r_coarse, r_fine_box)``; covered coarse rows are decoupled
    identity rows at Laplacian-diagonal scale).

    ``precondition`` applies one FAC V(nu,nu)-cycle:

    1. red-black smoothing of the patch correction (zero CF ghosts);
    2. full-domain coarse MG V-cycle on the composite residual — the
       covered region carries the RESTRICTED FINE residual;
    3. CF interpolation of the coarse correction onto the patch;
    4. post-smoothing with live CF ghosts from the coarse correction.
    """

    def __init__(self, coarse_shape, bc: DomainBC, dx, box: FineBox,
                 nu: int = 2, mg: Optional[PoissonMultigrid] = None,
                 dtype=jnp.float64):
        self.box = box
        self.bc = bc
        self.dx = tuple(float(h) for h in dx)
        self.dx_f = tuple(h / box.ratio for h in self.dx)
        self.nu = int(nu)
        dim = len(coarse_shape)
        self.box_sl = tuple(slice(box.lo[a], box.hi[a])
                            for a in range(dim))
        covered = np.zeros(tuple(coarse_shape), dtype=bool)
        covered[tuple(np.s_[box.lo[a]:box.hi[a]]
                      for a in range(dim))] = True
        self._covered = jnp.asarray(covered)
        self.mg_c = mg if mg is not None else PoissonMultigrid(
            coarse_shape, bc, self.dx,
            dtype=jax.dtypes.canonicalize_dtype(dtype))
        self._diag_c = sum(2.0 / h ** 2 for h in self.dx)
        self._diag_f = sum(-2.0 / h ** 2 for h in self.dx_f)
        self._masks = checkerboard_masks(box.fine_n)

    def _smooth_fine(self, e_f: Array, r_f: Array,
                     e_c: Optional[Array], sweeps: int) -> Array:
        return _smooth_patch(self.box, self.dx_f, self._diag_f,
                             self._masks, self.box_sl, e_f, r_f, e_c,
                             sweeps)

    def precondition(self, r: Tuple[Array, Array]
                     ) -> Tuple[Array, Array]:
        r_c, r_f = r
        # 1. patch pre-smoothing (zero ghosts: correction quantity)
        e_f = self._smooth_fine(jnp.zeros_like(r_f), r_f, None, self.nu)
        # 2. composite residual on the coarse level: restricted fine
        #    residual underneath the patch — the FAC signature
        pad = [(1, 1)] * e_f.ndim
        res_f = r_f - _box_lap(jnp.pad(e_f, pad), self.dx_f,
                               self.box.fine_n)
        rr_c = r_c.at[self.box_sl].set(restrict_cc(res_f))
        if self.mg_c.has_nullspace:
            rr_c = rr_c - jnp.mean(rr_c)
        e_c = self.mg_c.vcycle(jnp.zeros_like(rr_c), rr_c)
        if self.mg_c.has_nullspace:
            e_c = e_c - jnp.mean(e_c)
        # 3. correction transfer: CF interpolation onto the patch
        e_f = e_f + prolong_cc(e_c, self.box)
        # 4. post-smoothing with live CF ghosts
        e_f = self._smooth_fine(e_f, r_f, e_c, self.nu)
        # covered coarse rows are decoupled -diag*phi identity rows
        e_c_out = jnp.where(self._covered, -r_c / self._diag_c, e_c)
        return (e_c_out, e_f)


class FACMultilevelPoisson:
    """L-level FAC V-cycle for the composite Poisson system of
    :class:`ibamr_tpu.amr_ins_multilevel.MultiLevelCompositeProjection`
    (residual pytree ``(r_0, ..., r_{L-1})``, one nested box per level)
    — the arbitrary-depth generalization of the two-level
    :class:`FACCompositePoisson` (reference FACPreconditioner over a
    full hierarchy, SURVEY.md T8).

    One V(nu,nu)-cycle:

    - DOWN, finest to level 1: red-black pre-smoothing of each patch
      correction (zero CF ghosts), then the defining FAC move — the
      parent's rhs carries the RESTRICTED child residual underneath the
      patch;
    - BOTTOM: full-domain multigrid V-cycle on level 0's composite
      residual;
    - UP, level 1 to finest: CF-interpolate the parent correction onto
      the patch, post-smooth with live CF ghosts.

    ``levels`` come from ``build_hierarchy`` (level 0 periodic root).
    """

    def __init__(self, levels, nu: int = 2,
                 mg: Optional[PoissonMultigrid] = None,
                 dtype=jnp.float64):
        self.levels = list(levels)
        self.L = len(self.levels)
        self.nu = int(nu)
        root = self.levels[0].grid
        dim = root.dim
        self.mg_c = mg if mg is not None else PoissonMultigrid(
            tuple(root.n), DomainBC.periodic(dim), root.dx,
            dtype=jax.dtypes.canonicalize_dtype(dtype))
        self.dx = [spec.grid.dx for spec in self.levels]
        self.diag = [sum(-2.0 / h ** 2 for h in spec.grid.dx)
                     for spec in self.levels]
        self.box_sl = []
        self.masks = []
        self.covered = []     # per level l < L-1: child-box mask
        for l in range(1, self.L):
            box = self.levels[l].box
            self.box_sl.append(tuple(slice(box.lo[a], box.hi[a])
                                     for a in range(dim)))
            self.masks.append(checkerboard_masks(box.fine_n))
            cov = np.zeros(self.levels[l - 1].grid.n, dtype=bool)
            cov[self.box_sl[-1]] = True
            self.covered.append(jnp.asarray(cov))

    def _smooth(self, l: int, e: Array, r: Array,
                e_parent: Optional[Array], sweeps: int) -> Array:
        return _smooth_patch(self.levels[l].box, self.dx[l],
                             self.diag[l], self.masks[l - 1],
                             self.box_sl[l - 1], e, r, e_parent, sweeps)

    def precondition(self, rs):
        orig = tuple(rs)   # identity rows echo the ORIGINAL residual;
        # the down pass overwrites covered regions with child residuals
        rs = list(rs)
        es = [None] * self.L

        # DOWN: smooth each patch, push its residual under the parent
        for l in range(self.L - 1, 0, -1):
            e = self._smooth(l, jnp.zeros_like(rs[l]), rs[l], None,
                             self.nu)
            pad = [(1, 1)] * e.ndim
            res = rs[l] - _box_lap(jnp.pad(e, pad), self.dx[l],
                                   self.levels[l].box.fine_n)
            rs[l - 1] = rs[l - 1].at[self.box_sl[l - 1]].set(
                restrict_cc(res))
            es[l] = e

        # BOTTOM: full-domain MG on the root composite residual
        rr = rs[0]
        if self.mg_c.has_nullspace:
            rr = rr - jnp.mean(rr)
        e0 = self.mg_c.vcycle(jnp.zeros_like(rr), rr)
        if self.mg_c.has_nullspace:
            e0 = e0 - jnp.mean(e0)
        es[0] = e0

        # UP: prolong the parent correction, post-smooth w/ live ghosts
        for l in range(1, self.L):
            es[l] = es[l] + prolong_cc(es[l - 1], self.levels[l].box)
            es[l] = self._smooth(l, es[l], rs[l], es[l - 1], self.nu)

        # covered parent rows are decoupled -diag*phi identity rows in
        # the composite operator
        out = []
        for l in range(self.L):
            if l + 1 < self.L:
                out.append(jnp.where(self.covered[l],
                                     orig[l] / self.diag[l], es[l]))
            else:
                out.append(es[l])
        return tuple(out)
