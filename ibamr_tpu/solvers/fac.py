"""Two-level FAC (Fast Adaptive Composite) multigrid preconditioner.

Reference parity: ``FACPreconditioner`` + ``CCPoissonPointRelaxationFACOperator``
(T8, SURVEY.md §2.1) — the V-cycle over AMR levels that smooths on the
refined patch, solves a full-domain coarse correction (with the fine
residual restricted underneath the patch — the defining FAC move), and
interpolates the correction back through the coarse-fine interface.

TPU-first shape: the fine patch is one dense box array, smoothing is
masked red-black half-sweeps (whole-array stencils, no point loops), the
coarse "bottom solve" is a :class:`~ibamr_tpu.solvers.multigrid.PoissonMultigrid`
V-cycle (the hypre-level-solver analog), and the CF interpolation reuses
the quadratic ghost machinery of :mod:`ibamr_tpu.amr`. The whole cycle is
traceable, so it rides inside the jitted FGMRES solve of
:class:`ibamr_tpu.amr_ins.CompositeProjection` as a drop-in ``M``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.amr import (FineBox, fill_fine_ghosts, prolong_cc,
                           restrict_cc)
from ibamr_tpu.amr_ins import _box_cc_laplacian as _box_lap
from ibamr_tpu.bc import DomainBC
from ibamr_tpu.solvers.multigrid import (PoissonMultigrid,
                                         checkerboard_masks)

Array = jnp.ndarray


class FACCompositePoisson:
    """FAC preconditioner for the two-level composite Poisson system of
    :class:`ibamr_tpu.amr_ins.CompositeProjection` (residual pytree
    ``(r_coarse, r_fine_box)``; covered coarse rows are decoupled
    identity rows at Laplacian-diagonal scale).

    ``precondition`` applies one FAC V(nu,nu)-cycle:

    1. red-black smoothing of the patch correction (zero CF ghosts);
    2. full-domain coarse MG V-cycle on the composite residual — the
       covered region carries the RESTRICTED FINE residual;
    3. CF interpolation of the coarse correction onto the patch;
    4. post-smoothing with live CF ghosts from the coarse correction.
    """

    def __init__(self, coarse_shape, bc: DomainBC, dx, box: FineBox,
                 nu: int = 2, mg: Optional[PoissonMultigrid] = None,
                 dtype=jnp.float64):
        self.box = box
        self.bc = bc
        self.dx = tuple(float(h) for h in dx)
        self.dx_f = tuple(h / box.ratio for h in self.dx)
        self.nu = int(nu)
        dim = len(coarse_shape)
        self.box_sl = tuple(slice(box.lo[a], box.hi[a])
                            for a in range(dim))
        covered = np.zeros(tuple(coarse_shape), dtype=bool)
        covered[tuple(np.s_[box.lo[a]:box.hi[a]]
                      for a in range(dim))] = True
        self._covered = jnp.asarray(covered)
        self.mg_c = mg if mg is not None else PoissonMultigrid(
            coarse_shape, bc, self.dx,
            dtype=jax.dtypes.canonicalize_dtype(dtype))
        self._diag_c = sum(2.0 / h ** 2 for h in self.dx)
        self._diag_f = sum(-2.0 / h ** 2 for h in self.dx_f)
        self._masks = checkerboard_masks(box.fine_n)

    def _smooth_fine(self, e_f: Array, r_f: Array,
                     e_c: Optional[Array], sweeps: int) -> Array:
        """Masked red-black relaxation of lap_f e_f = r_f on the patch.
        ``e_c`` supplies CF ghosts (None = homogeneous zero ghosts)."""
        fine_n = self.box.fine_n

        def ghosted(e_f):
            if e_c is None:
                pad = [(1, 1)] * e_f.ndim
                return jnp.pad(e_f, pad)
            e_eff = e_c.at[self.box_sl].set(restrict_cc(e_f))
            return fill_fine_ghosts(e_f, e_eff, self.box, ghost=1)

        def sweep(_, e_f):
            for mask in self._masks:
                lap = _box_lap(ghosted(e_f), self.dx_f, fine_n)
                e_f = e_f + jnp.where(mask, (r_f - lap) / self._diag_f,
                                      0.0)
            return e_f

        return jax.lax.fori_loop(0, sweeps, sweep, e_f)

    def precondition(self, r: Tuple[Array, Array]
                     ) -> Tuple[Array, Array]:
        r_c, r_f = r
        # 1. patch pre-smoothing (zero ghosts: correction quantity)
        e_f = self._smooth_fine(jnp.zeros_like(r_f), r_f, None, self.nu)
        # 2. composite residual on the coarse level: restricted fine
        #    residual underneath the patch — the FAC signature
        pad = [(1, 1)] * e_f.ndim
        res_f = r_f - _box_lap(jnp.pad(e_f, pad), self.dx_f,
                               self.box.fine_n)
        rr_c = r_c.at[self.box_sl].set(restrict_cc(res_f))
        if self.mg_c.has_nullspace:
            rr_c = rr_c - jnp.mean(rr_c)
        e_c = self.mg_c.vcycle(jnp.zeros_like(rr_c), rr_c)
        if self.mg_c.has_nullspace:
            e_c = e_c - jnp.mean(e_c)
        # 3. correction transfer: CF interpolation onto the patch
        e_f = e_f + prolong_cc(e_c, self.box)
        # 4. post-smoothing with live CF ghosts
        e_f = self._smooth_fine(e_f, r_f, e_c, self.nu)
        # covered coarse rows are decoupled -diag*phi identity rows
        e_c_out = jnp.where(self._covered, -r_c / self._diag_c, e_c)
        return (e_c_out, e_f)
