"""Matrix-free Krylov solvers over pytrees, jit/scan-native.

Reference parity: the IBTK operator/solver framework (T6) — matrix-free
``LinearOperator`` + ``PETScKrylovLinearSolver`` (KSP wrappers) — rebuilt
the TPU way: the operator is any pytree->pytree callable; iteration is a
``lax.while_loop`` so the whole solve compiles into the step function; the
global dot products are ``jnp`` reductions that XLA lowers to ``psum``
collectives under sharding (the analog of the reference's MPI-reduced
VecDot, SURVEY.md §2.4).

Solvers: preconditioned conjugate gradient (SPD systems: Poisson/Helmholtz
with general BCs, CIB mobility) and BiCGStab (mildly nonsymmetric systems).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ibamr_tpu.ops.norms import tree_dot  # noqa: E402  (shared primitive)

Pytree = Any
Operator = Callable[[Pytree], Pytree]


def tree_axpy(alpha, x: Pytree, y: Pytree) -> Pytree:
    """alpha * x + y"""
    return jax.tree_util.tree_map(lambda xi, yi: alpha * xi + yi, x, y)


def tree_scale(alpha, x: Pytree) -> Pytree:
    return jax.tree_util.tree_map(lambda xi: alpha * xi, x)


def tree_sub(x: Pytree, y: Pytree) -> Pytree:
    return jax.tree_util.tree_map(lambda xi, yi: xi - yi, x, y)


class SolveResult(NamedTuple):
    x: Pytree
    iters: jnp.ndarray      # iterations taken
    resnorm: jnp.ndarray    # final |r|_2 (unweighted l2)
    converged: jnp.ndarray  # bool


def cg(A: Operator, b: Pytree, x0: Optional[Pytree] = None,
       M: Optional[Operator] = None, tol: float = 1e-6,
       atol: float = 0.0, maxiter: int = 100) -> SolveResult:
    """Preconditioned conjugate gradient for SPD A (matrix-free).

    Stops when |r| <= max(tol*|b|, atol). ``M`` applies the preconditioner
    inverse (M ~ A^{-1}). Fully traceable: usable inside jit/scan.
    """
    if x0 is None:
        x0 = jax.tree_util.tree_map(jnp.zeros_like, b)
    if M is None:
        M = lambda r: r  # noqa: E731

    bnorm = jnp.sqrt(tree_dot(b, b))
    stop = jnp.maximum(tol * bnorm, atol)

    r0 = tree_sub(b, A(x0))
    z0 = M(r0)
    p0 = z0
    rz0 = tree_dot(r0, z0)

    def cond(st):
        x, r, z, p, rz, k = st
        rn = jnp.sqrt(tree_dot(r, r))
        return jnp.logical_and(k < maxiter, rn > stop)

    def body(st):
        x, r, z, p, rz, k = st
        Ap = A(p)
        pAp = tree_dot(p, Ap)
        # guard against breakdown (pAp ~ 0 when r ~ 0)
        alpha = jnp.where(pAp > 0, rz / jnp.where(pAp == 0, 1.0, pAp), 0.0)
        x = tree_axpy(alpha, p, x)
        r = tree_axpy(-alpha, Ap, r)
        z = M(r)
        rz_new = tree_dot(r, z)
        beta = jnp.where(rz > 0, rz_new / jnp.where(rz == 0, 1.0, rz), 0.0)
        p = tree_axpy(beta, p, z)
        return (x, r, z, p, rz_new, k + 1)

    x, r, _, _, _, k = jax.lax.while_loop(
        cond, body, (x0, r0, z0, p0, rz0, jnp.asarray(0)))
    rn = jnp.sqrt(tree_dot(r, r))
    return SolveResult(x=x, iters=k, resnorm=rn, converged=rn <= stop)


def bicgstab(A: Operator, b: Pytree, x0: Optional[Pytree] = None,
             M: Optional[Operator] = None, tol: float = 1e-6,
             atol: float = 0.0, maxiter: int = 200) -> SolveResult:
    """Right-preconditioned BiCGStab for general (nonsymmetric) A."""
    if x0 is None:
        x0 = jax.tree_util.tree_map(jnp.zeros_like, b)
    if M is None:
        M = lambda r: r  # noqa: E731

    bnorm = jnp.sqrt(tree_dot(b, b))
    stop = jnp.maximum(tol * bnorm, atol)

    r0 = tree_sub(b, A(x0))
    rhat = r0
    one = jnp.asarray(1.0, dtype=jnp.result_type(*jax.tree_util.tree_leaves(b)))

    def cond(st):
        x, r, p, v, rho, alpha, omega, k = st
        rn = jnp.sqrt(tree_dot(r, r))
        return jnp.logical_and(k < maxiter, rn > stop)

    def body(st):
        x, r, p, v, rho, alpha, omega, k = st
        rho_new = tree_dot(rhat, r)
        denom = jnp.where(rho * omega == 0, 1.0, rho * omega)
        beta = (rho_new / denom) * (alpha / jnp.where(omega == 0, 1.0, omega))
        p = tree_axpy(beta, tree_axpy(-omega, v, p), r)
        phat = M(p)
        v = A(phat)
        rhv = tree_dot(rhat, v)
        alpha = rho_new / jnp.where(rhv == 0, 1.0, rhv)
        s = tree_axpy(-alpha, v, r)
        shat = M(s)
        t = A(shat)
        tt = tree_dot(t, t)
        omega = tree_dot(t, s) / jnp.where(tt == 0, 1.0, tt)
        x = tree_axpy(alpha, phat, tree_axpy(omega, shat, x))
        r = tree_axpy(-omega, t, s)
        return (x, r, p, v, rho_new, alpha, omega, k + 1)

    zeros = jax.tree_util.tree_map(jnp.zeros_like, b)
    x, r, _, _, _, _, _, k = jax.lax.while_loop(
        cond, body, (x0, r0, zeros, zeros, one, one, one, jnp.asarray(0)))
    rn = jnp.sqrt(tree_dot(r, r))
    return SolveResult(x=x, iters=k, resnorm=rn, converged=rn <= stop)
