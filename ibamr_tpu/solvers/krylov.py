"""Matrix-free Krylov solvers over pytrees, jit/scan-native.

Reference parity: the IBTK operator/solver framework (T6) — matrix-free
``LinearOperator`` + ``PETScKrylovLinearSolver`` (KSP wrappers) — rebuilt
the TPU way: the operator is any pytree->pytree callable; iteration is a
``lax.while_loop`` so the whole solve compiles into the step function; the
global dot products are ``jnp`` reductions that XLA lowers to ``psum``
collectives under sharding (the analog of the reference's MPI-reduced
VecDot, SURVEY.md §2.4).

Solvers: preconditioned conjugate gradient (SPD systems: Poisson/Helmholtz
with general BCs, CIB mobility) and BiCGStab (mildly nonsymmetric systems).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ibamr_tpu.ops.norms import tree_dot, tree_dots  # noqa: E402  (shared)

Pytree = Any
Operator = Callable[[Pytree], Pytree]


def _gnorm(v) -> jnp.ndarray:
    """Global l2 norm under the ``comm`` named scope (the norms.py
    ``_reduce`` discipline): under sharding the reduction lowers to a
    psum, and the scope is what attributes that collective to the comm
    op-class in device profiles instead of ``unattributed``."""
    with jax.named_scope("comm"):
        return jnp.linalg.norm(v)


def tree_axpy(alpha, x: Pytree, y: Pytree) -> Pytree:
    """alpha * x + y"""
    return jax.tree_util.tree_map(lambda xi, yi: alpha * xi + yi, x, y)


def tree_scale(alpha, x: Pytree) -> Pytree:
    return jax.tree_util.tree_map(lambda xi: alpha * xi, x)


def tree_sub(x: Pytree, y: Pytree) -> Pytree:
    return jax.tree_util.tree_map(lambda xi, yi: xi - yi, x, y)


class SolveResult(NamedTuple):
    x: Pytree
    iters: jnp.ndarray      # iterations taken
    resnorm: jnp.ndarray    # final |r|_2 (unweighted l2)
    converged: jnp.ndarray  # bool


def cg(A: Operator, b: Pytree, x0: Optional[Pytree] = None,
       M: Optional[Operator] = None, tol: float = 1e-6,
       atol: float = 0.0, maxiter: int = 100) -> SolveResult:
    """Preconditioned conjugate gradient for SPD A (matrix-free).

    Stops when |r| <= max(tol*|b|, atol). ``M`` applies the preconditioner
    inverse (M ~ A^{-1}). Fully traceable: usable inside jit/scan.
    """
    if x0 is None:
        x0 = jax.tree_util.tree_map(jnp.zeros_like, b)
    if M is None:
        M = lambda r: r  # noqa: E731

    bnorm = jnp.sqrt(tree_dot(b, b))
    stop = jnp.maximum(tol * bnorm, atol)

    r0 = tree_sub(b, A(x0))
    z0 = M(r0)
    p0 = z0
    # one fused reduction for the (r,z)/(r,r) pair — one psum of a
    # (2,) vector under sharding instead of two scalar syncs
    rz0, rn0sq = tree_dots([(r0, z0), (r0, r0)])
    rn0 = jnp.sqrt(rn0sq)

    # Finite-precision divergence guard: when ``tol`` is below the
    # dtype's reachable floor (an f32 solve asked for 1e-9), the
    # recurred residual bottoms out at roundoff and further iterations
    # LOSE conjugacy — the iterate can then wander arbitrarily far
    # (observed: div 1e10 from the VC projection in f32). Track the
    # best iterate seen; stop once the residual has grown far past the
    # best (the run is diverging, not converging); return the BEST
    # iterate when the solve did not converge. Converged solves return
    # the final iterate exactly as before (bitwise-identical path).
    def cond(st):
        x, r, z, p, rz, k, rn, xb, rb = st
        ok = jnp.logical_and(k < maxiter, rn > stop)
        return jnp.logical_and(ok, rn <= 1e4 * rb)

    def body(st):
        x, r, z, p, rz, k, _, xb, rb = st
        Ap = A(p)
        pAp = tree_dot(p, Ap)
        # guard against breakdown (pAp ~ 0 when r ~ 0)
        alpha = jnp.where(pAp > 0, rz / jnp.where(pAp == 0, 1.0, pAp), 0.0)
        x = tree_axpy(alpha, p, x)
        r = tree_axpy(-alpha, Ap, r)
        z = M(r)
        # fused (r,z)/(r,r) reduction: one collective sync per
        # iteration where there were two (values unchanged — each row
        # reduces the same elements in the same order)
        rz_new, rnsq = tree_dots([(r, z), (r, r)])
        beta = jnp.where(rz > 0, rz_new / jnp.where(rz == 0, 1.0, rz), 0.0)
        p = tree_axpy(beta, p, z)
        rn = jnp.sqrt(rnsq)              # carried: cond reuses it
        better = rn < rb
        xb = jax.tree_util.tree_map(
            lambda a_, b_: jnp.where(better, a_, b_), x, xb)
        rb = jnp.minimum(rb, rn)
        return (x, r, z, p, rz_new, k + 1, rn, xb, rb)

    x, r, _, _, _, k, rn, xb, rb = jax.lax.while_loop(
        cond, body, (x0, r0, z0, p0, rz0, jnp.asarray(0), rn0, x0, rn0))
    converged = rn <= stop
    use_best = jnp.logical_and(~converged, rb < rn)
    x = jax.tree_util.tree_map(
        lambda a_, b_: jnp.where(use_best, a_, b_), xb, x)
    rn = jnp.where(use_best, rb, rn)
    return SolveResult(x=x, iters=k, resnorm=rn, converged=converged)


def bicgstab(A: Operator, b: Pytree, x0: Optional[Pytree] = None,
             M: Optional[Operator] = None, tol: float = 1e-6,
             atol: float = 0.0, maxiter: int = 200) -> SolveResult:
    """Right-preconditioned BiCGStab for general (nonsymmetric) A."""
    if x0 is None:
        x0 = jax.tree_util.tree_map(jnp.zeros_like, b)
    if M is None:
        M = lambda r: r  # noqa: E731

    bnorm = jnp.sqrt(tree_dot(b, b))
    stop = jnp.maximum(tol * bnorm, atol)

    r0 = tree_sub(b, A(x0))
    rhat = r0
    one = jnp.asarray(1.0, dtype=jnp.result_type(*jax.tree_util.tree_leaves(b)))
    rn0 = jnp.sqrt(tree_dot(r0, r0))

    # Same finite-precision divergence guard as ``cg`` (round 4), which
    # BiCGStab never received: its recurred residual is even less
    # trustworthy than CG's (the stabilizer omega can all but vanish),
    # so below the dtype floor the iterate wanders while the recurrence
    # reports progress. Carry the best iterate; stop once the residual
    # has grown far past the best; return the BEST iterate only when
    # the solve did not converge — converged solves keep the exact
    # pre-guard path (bitwise-identical result).
    def cond(st):
        x, r, p, v, rho, alpha, omega, k, rn, xb, rb = st
        ok = jnp.logical_and(k < maxiter, rn > stop)
        return jnp.logical_and(ok, rn <= 1e4 * rb)

    def body(st):
        x, r, p, v, rho, alpha, omega, k, _, xb, rb = st
        rho_new = tree_dot(rhat, r)
        denom = jnp.where(rho * omega == 0, 1.0, rho * omega)
        beta = (rho_new / denom) * (alpha / jnp.where(omega == 0, 1.0, omega))
        p = tree_axpy(beta, tree_axpy(-omega, v, p), r)
        phat = M(p)
        v = A(phat)
        rhv = tree_dot(rhat, v)
        alpha = rho_new / jnp.where(rhv == 0, 1.0, rhv)
        s = tree_axpy(-alpha, v, r)
        shat = M(s)
        t = A(shat)
        # fused (t,t)/(t,s) reduction: one collective sync, not two
        tt, ts = tree_dots([(t, t), (t, s)])
        omega = ts / jnp.where(tt == 0, 1.0, tt)
        x = tree_axpy(alpha, phat, tree_axpy(omega, shat, x))
        r = tree_axpy(-omega, t, s)
        rn = jnp.sqrt(tree_dot(r, r))    # carried: cond reuses it
        better = rn < rb
        xb = jax.tree_util.tree_map(
            lambda a_, b_: jnp.where(better, a_, b_), x, xb)
        rb = jnp.minimum(rb, rn)
        return (x, r, p, v, rho_new, alpha, omega, k + 1, rn, xb, rb)

    zeros = jax.tree_util.tree_map(jnp.zeros_like, b)
    x, r, _, _, _, _, _, k, rn, xb, rb = jax.lax.while_loop(
        cond, body, (x0, r0, zeros, zeros, one, one, one,
                     jnp.asarray(0), rn0, x0, rn0))
    converged = rn <= stop
    use_best = jnp.logical_and(~converged, rb < rn)
    x = jax.tree_util.tree_map(
        lambda a_, b_: jnp.where(use_best, a_, b_), xb, x)
    rn = jnp.where(use_best, rb, rn)
    return SolveResult(x=x, iters=k, resnorm=rn, converged=converged)


# ---------------------------------------------------------------------------
# FGMRES + Newton-Krylov (T6 completion: the reference's
# PETScKrylovLinearSolver FGMRES default + PETScNewtonKrylovSolver/SNES
# with matrix-free MFFD Jacobians — SURVEY.md §2.1 T6)
# ---------------------------------------------------------------------------

def _ravel(pytree):
    from jax.flatten_util import ravel_pytree

    flat, unravel = ravel_pytree(pytree)
    return flat, unravel


def _fgmres_flat(Aop, b, x0, Mop, m, tol, atol, restarts):
    """Flexible right-preconditioned GMRES(m) on flat vectors.

    TPU-first formulation: the Krylov basis is one (m+1, n) matrix, so
    orthogonalization is two matmuls per Arnoldi step (all candidate
    dots at once + rank-1 basis combination) instead of a data-dependent
    inner loop — MXU-friendly and fully lax-traceable.
    """
    n = b.shape[0]
    dtype = b.dtype
    bnorm = _gnorm(b)
    stop = jnp.maximum(tol * bnorm, atol)

    def restart_body(carry):
        x, _, it = carry
        r = b - Aop(x)
        beta = _gnorm(r)
        beta_safe = jnp.where(beta == 0, 1.0, beta)
        V0 = jnp.zeros((m + 1, n), dtype=dtype).at[0].set(r / beta_safe)
        Z0 = jnp.zeros((m, n), dtype=dtype)
        H0 = jnp.zeros((m + 1, m), dtype=dtype)

        def arnoldi(j, st):
            V, Z, H = st
            v = V[j]
            z = Mop(v)
            w = Aop(z)
            # classical Gram-Schmidt with reorthogonalization (CGS2):
            # two batched-dot + rank-k-update rounds keep the basis
            # orthogonal to working precision (important in f32) while
            # staying all-matmul for the MXU
            mask = (jnp.arange(m + 1) <= j).astype(dtype)
            dots = (V @ w) * mask
            w = w - V.T @ dots
            dots2 = (V @ w) * mask
            w = w - V.T @ dots2
            wnorm = _gnorm(w)
            H = H.at[:, j].set(dots + dots2).at[j + 1, j].set(wnorm)
            V = V.at[j + 1].set(w / jnp.where(wnorm == 0, 1.0, wnorm))
            Z = Z.at[j].set(z)
            return V, Z, H

        V, Z, H = jax.lax.fori_loop(0, m, arnoldi, (V0, Z0, H0))
        e1 = jnp.zeros(m + 1, dtype=dtype).at[0].set(beta)
        # rcond = raw machine eps, NOT jax's default eps*max(m,n):
        # a strongly-scaled preconditioner (e.g. the Stokes Schur
        # proxy) inflates sigma_max, and the default cutoff then
        # truncates the small-but-essential singular direction --
        # observed as an f32 FGMRES that makes ZERO progress. True
        # breakdown columns (converged early) still fall below eps.
        y, *_ = jnp.linalg.lstsq(H, e1, rcond=float(jnp.finfo(dtype).eps))
        # an exactly-zero restart residual (projecting an already
        # div-free field, the zero-state initialize) makes H all-zero,
        # and lstsq of an all-zero matrix returns NaN here (0/0 in the
        # SVD-based solve); true breakdown columns can do the same. A
        # non-finite y entry carries no descent information — drop it
        # (keeping x unchanged along that direction is exact).
        y = jnp.where(jnp.isfinite(y), y, jnp.zeros_like(y))
        x = x + Z.T @ y
        rn = _gnorm(b - Aop(x))
        return x, rn, it + 1

    def cond(carry):
        _, rn, it = carry
        return jnp.logical_and(it < restarts, rn > stop)

    x, rn, it = jax.lax.while_loop(
        cond, restart_body,
        (x0, jnp.asarray(jnp.inf, dtype=dtype), jnp.asarray(0)))
    return x, rn, it


def fgmres(A: Operator, b: Pytree, x0: Optional[Pytree] = None,
           M: Optional[Operator] = None, m: int = 30,
           tol: float = 1e-6, atol: float = 0.0,
           restarts: int = 10) -> SolveResult:
    """Flexible GMRES(m) over pytrees (general nonsymmetric systems;
    the preconditioner may itself be an inner iteration)."""
    bflat, unravel = _ravel(b)
    if x0 is None:
        x0flat = jnp.zeros_like(bflat)
    else:
        x0flat, _ = _ravel(x0)

    def Aop(v):
        out, _ = _ravel(A(unravel(v)))
        return out

    if M is None:
        Mop = lambda v: v  # noqa: E731
    else:
        def Mop(v):
            out, _ = _ravel(M(unravel(v)))
            return out

    x, rn, it = _fgmres_flat(Aop, bflat, x0flat, Mop, m, tol, atol,
                             restarts)
    bnorm = _gnorm(bflat)
    stop = jnp.maximum(tol * bnorm, atol)
    return SolveResult(x=unravel(x), iters=it, resnorm=rn,
                       converged=rn <= stop)


class NewtonResult(NamedTuple):
    x: Pytree
    iters: jnp.ndarray
    resnorm: jnp.ndarray
    converged: jnp.ndarray


def newton_krylov(F: Operator, x0: Pytree, tol: float = 1e-8,
                  atol: float = 0.0, maxiter: int = 10,
                  inner_m: int = 20, inner_restarts: int = 2,
                  inner_tol: float = 1e-3) -> NewtonResult:
    """Matrix-free Newton-Krylov: solve F(x) = 0 with exact JVP
    Jacobians (jax.jvp — sharper than the reference's MFFD finite
    differencing) and FGMRES inner solves. Fully lax-traceable, so an
    implicit integrator can run it inside jit/scan.
    """
    x0flat, unravel = _ravel(x0)

    def Fflat(v):
        out, _ = _ravel(F(unravel(v)))
        return out

    f0 = Fflat(x0flat)
    fnorm0 = _gnorm(f0)
    stop = jnp.maximum(tol * jnp.maximum(fnorm0, 1e-30), atol)

    def cond(carry):
        _, fnorm, it = carry
        return jnp.logical_and(it < maxiter, fnorm > stop)

    def body(carry):
        x, fnorm, it = carry
        # one primal evaluation yields both the residual and the
        # tangent-only Jacobian map (cheaper than jax.jvp inside the
        # Arnoldi loop, which would re-trace the primal every iteration)
        fx, Jop = jax.linearize(Fflat, x)

        dx, _, _ = _fgmres_flat(Jop, -fx, jnp.zeros_like(x),
                                lambda v: v, inner_m, inner_tol, 0.0,
                                inner_restarts)

        # backtracking line search (the SNES 'bt' analog): halve the
        # step until the residual norm decreases, tracking the BEST
        # candidate seen — when no scale decreases (inexact Jacobian
        # solve, kinked residual) taking the least-bad step keeps the
        # iteration from wandering. All comparisons are written so a
        # NaN/inf trial norm counts as NOT improved (NaN-safe).
        def ls_cond(c):
            s, fn, bs, bfn, tries = c
            improved = fn < fnorm
            return jnp.logical_and(tries < 6,
                                   jnp.logical_not(improved))

        def ls_body(c):
            s, _, bs, bfn, tries = c
            s = s * 0.5
            fn = _gnorm(Fflat(x + s * dx))
            better = fn < bfn                      # False for NaN fn
            bs = jnp.where(better, s, bs)
            bfn = jnp.where(better, fn, bfn)
            return s, fn, bs, bfn, tries + 1

        fn_full = _gnorm(Fflat(x + dx))
        one = jnp.asarray(1.0, dtype=x.dtype)
        full_ok = jnp.isfinite(fn_full)
        bs0 = jnp.where(full_ok, one, one / 64.0)  # NaN full step: tiny
        bfn0 = jnp.where(full_ok, fn_full,
                         jnp.asarray(jnp.inf, dtype=x.dtype))
        _, _, s, fn, _ = jax.lax.while_loop(
            ls_cond, ls_body, (one, fn_full, bs0, bfn0,
                               jnp.asarray(0)))
        x = x + s * dx
        return x, fn, it + 1

    x, fnorm, it = jax.lax.while_loop(
        cond, body, (x0flat, fnorm0, jnp.asarray(0)))
    return NewtonResult(x=unravel(x), iters=it, resnorm=fnorm,
                        converged=fnorm <= stop)
