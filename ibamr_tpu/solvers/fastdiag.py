"""Fast-diagonalization Helmholtz/Poisson solver for wall-bounded boxes.

Reference parity: replaces the FAC-multigrid + hypre solves (T8) for
non-periodic uniform levels — the role CCPoissonSolverManager /
SCPoissonSolverManager solvers play under the projection preconditioner
(P3) when walls are present.

Method (classic "fast diagonalization", Lynch-Rice-Thomas): the discrete
Laplacian with BC-modified end rows is a symmetric tridiagonal per axis;
eigendecompose each non-periodic axis ONCE on host (numpy.eigh) and apply
the orthogonal eigenvector matrices as axis transforms. Periodic axes use
FFT. The operator is then diagonal: solve = fwd transforms -> divide ->
inverse transforms.

TPU-first: the eigenvector transforms are dense (n, n) matmuls batched
over all other axes — they run on the MXU at full throughput, which on
TPU routinely beats a same-size FFT. The solve is exact for the discrete
operator (projection stays div-free to roundoff, as in the periodic FFT
path).

Centerings per axis:
- ``cc``        cell-centered unknowns; walls at faces. Dirichlet ghost
                = 2g - Q1 -> end row (-3, 1)/h^2; Neumann ghost = Q1 ->
                end row (-1, 1)/h^2.
- ``fc_pinned`` face-centered normal component; the lo boundary face is
                slot 0 of the array and is PINNED to the BC value (the
                hi boundary face is the same physical DOF in the
                periodic storage convention and is implicit). Unknowns
                are interior faces 1..n-1: standard Dirichlet-node
                tridiagonal of size n-1.
"""

from __future__ import annotations

import functools
import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.bc import AxisBC, DomainBC, ghost_reflect_coeff
from ibamr_tpu.grid import StaggeredGrid


def laplacian_1d_cc(n: int, h: float, axbc: AxisBC) -> np.ndarray:
    """BC-modified tridiagonal for a cell-centered axis (homogeneous).

    The boundary row uses the Robin reflection of bc._ghost_layers_cc:
    homogeneous ghost = r * interior with r = -(a/2 - b/h)/(a/2 + b/h),
    so the end diagonal is (-2 + r)/h^2 — which reproduces the classic
    -3 (dirichlet, r=-1) and -1 (neumann, r=+1) rows and covers general
    a*Q + b*dQ/dn = g (T9). The modification is diagonal-only, so the
    matrix stays symmetric and eigh applies."""
    A = np.zeros((n, n))
    inv = 1.0 / (h * h)
    for i in range(n):
        A[i, i] = -2.0 * inv
        if i > 0:
            A[i, i - 1] = inv
        if i < n - 1:
            A[i, i + 1] = inv
    for side, i in ((axbc.lo, 0), (axbc.hi, n - 1)):
        if side.kind == "periodic":
            raise ValueError("periodic axis has no 1D matrix")
        r = ghost_reflect_coeff(side, h)
        A[i, i] = (-2.0 + r) * inv
    return A


def laplacian_1d_fc_pinned(n: int, h: float) -> np.ndarray:
    """Interior-face unknowns (1..n-1) with Dirichlet boundary faces:
    standard (n-1)-point Dirichlet-node tridiagonal."""
    m = n - 1
    A = np.zeros((m, m))
    inv = 1.0 / (h * h)
    for i in range(m):
        A[i, i] = -2.0 * inv
        if i > 0:
            A[i, i - 1] = inv
        if i < m - 1:
            A[i, i + 1] = inv
    return A


def _periodic_symbol(n: int, h: float) -> np.ndarray:
    k = np.fft.fftfreq(n)
    return (2.0 * np.cos(2.0 * math.pi * k) - 2.0) / (h * h)


# plan-cached device-resident periodic axis plans: solver
# re-construction (regrids, level rebuilds) stops recomputing the
# symbol / eigendecomposition and every trace captures the SAME
# constants (the 1-D analog of solvers.spectral_plan.get_plan)
@functools.lru_cache(maxsize=64)
def _periodic_fft_plan_impl(n: int, h: float, x64: bool):
    return ("fft", jnp.asarray(_periodic_symbol(n, h)))


def _periodic_fft_plan(n: int, h: float):
    # keyed on the x64 mode: the cached jnp array's dtype follows the
    # mode at BUILD time, and a stale-mode plan would leak f64 (or f32)
    # constants into every later trace (see spectral_plan.plan_key)
    return _periodic_fft_plan_impl(n, h, bool(jax.config.jax_enable_x64))


@functools.lru_cache(maxsize=64)
def _periodic_eig_plan_impl(n: int, h: float, x64: bool):
    lam, V = np.linalg.eigh(laplacian_1d_periodic(n, h))
    return ("eig", jnp.asarray(V), jnp.asarray(lam))


def _periodic_eig_plan(n: int, h: float):
    return _periodic_eig_plan_impl(n, h, bool(jax.config.jax_enable_x64))


def laplacian_1d_periodic(n: int, h: float) -> np.ndarray:
    """Circulant 1D Laplacian (symmetric; its eigh basis is a real
    orthogonal Fourier basis — the dense-transform alternative to the
    FFT plan)."""
    eye = np.eye(n)
    return (-2.0 * eye + np.roll(eye, 1, axis=1)
            + np.roll(eye, -1, axis=1)) / (h * h)


class FastDiagSolver:
    """Separable Helmholtz solve (alpha + beta lap) Q = rhs on one grid,
    for one combination of per-axis (BC, centering)."""

    def __init__(self, grid: StaggeredGrid, bc: DomainBC,
                 centerings: Sequence[str], dense_periodic: bool = False):
        """``dense_periodic``: apply periodic axes as dense real-Fourier
        eigenbasis MATMULS instead of FFTs. Two reasons to choose it:
        (a) the MXU runs same-size dense transforms at full throughput
        and the SPMD partitioner distributes axis matmuls cleanly, and
        (b) XLA's fft thunk rejects the partitioned layouts a sharded
        composite solve produces (CPU "IsMonotonicWithDim0Major"
        RET_CHECK) — matmul transforms have no such restriction."""
        self.grid = grid
        self.bc = bc
        self.centerings = tuple(centerings)
        self.plans = []            # per axis: ("fft", lam) | ("eig", V, lam)
        for d, (axbc, cent) in enumerate(zip(bc.axes, self.centerings)):
            n, h = grid.n[d], grid.dx[d]
            if axbc.periodic and dense_periodic:
                self.plans.append(_periodic_eig_plan(int(n), float(h)))
            elif axbc.periodic:
                self.plans.append(_periodic_fft_plan(int(n), float(h)))
            elif cent == "cc":
                lam, V = np.linalg.eigh(laplacian_1d_cc(n, h, axbc))
                self.plans.append(("eig", jnp.asarray(V), jnp.asarray(lam)))
            elif cent == "fc_pinned":
                lam, V = np.linalg.eigh(laplacian_1d_fc_pinned(n, h))
                self.plans.append(("eig", jnp.asarray(V), jnp.asarray(lam)))
            else:
                raise ValueError(f"unknown centering {cent!r}")

    # -- helpers -------------------------------------------------------------
    def _axis_matmul(self, x: jnp.ndarray, M: jnp.ndarray,
                     axis: int) -> jnp.ndarray:
        """Apply M (m_out, m_in) along ``axis`` of x."""
        moved = jnp.moveaxis(x, axis, -1)
        out = jnp.tensordot(moved, M.astype(moved.dtype), axes=([-1], [1]))
        return jnp.moveaxis(out, -1, axis)

    def _interior(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, list]:
        """Slice off pinned boundary faces; remember which axes."""
        pinned = [d for d, c in enumerate(self.centerings)
                  if c == "fc_pinned" and not self.bc.axes[d].periodic]
        idx = [slice(None)] * x.ndim
        for d in pinned:
            idx[d] = slice(1, None)
        return x[tuple(idx)], pinned

    def solve(self, rhs: jnp.ndarray, alpha, beta,
              zero_nullspace: bool = False) -> jnp.ndarray:
        """Solve (alpha + beta lap) Q = rhs. With alpha == 0 and an
        all-Neumann/periodic problem set ``zero_nullspace`` to project
        out the constant mode (periodic-Poisson compatibility analog)."""
        x, pinned = self._interior(rhs)
        rdt = x.dtype
        cdt = jnp.complex128 if rdt == jnp.float64 else jnp.complex64
        dim = x.ndim

        # forward eig transforms (real), then FFTs (complex)
        for d, plan in enumerate(self.plans):
            if plan[0] == "eig":
                x = self._axis_matmul(x, plan[1].T, d)
        any_fft = any(p[0] == "fft" for p in self.plans)
        if any_fft:
            x = x.astype(cdt)
            for d, plan in enumerate(self.plans):
                if plan[0] == "fft":
                    x = jnp.fft.fft(x, axis=d)

        # diagonal solve
        sym = jnp.zeros((), dtype=rdt)
        for d, plan in enumerate(self.plans):
            lam = plan[1] if plan[0] == "fft" else plan[2]
            shape = [1] * dim
            shape[d] = lam.shape[0]
            sym = sym + lam.reshape(shape).astype(rdt)
        denom = alpha + beta * sym
        if zero_nullspace:
            # eigh-computed nullspace eigenvalues are ~1e-13, never an
            # exact 0 — a strict equality test would divide the constant
            # mode by roundoff (observed: f32 pressures of O(1e6)).
            # Threshold relative to the operator's spectral radius.
            tol = 1e-8 * jnp.max(jnp.abs(sym))
            null = jnp.abs(denom) <= tol
            safe = jnp.where(null, 1.0, denom)
            x = jnp.where(null, 0.0, x / safe)
        else:
            x = x / denom

        # inverse transforms
        if any_fft:
            for d, plan in enumerate(self.plans):
                if plan[0] == "fft":
                    x = jnp.fft.ifft(x, axis=d)
            x = jnp.real(x).astype(rdt)
        for d, plan in enumerate(self.plans):
            if plan[0] == "eig":
                x = self._axis_matmul(x, plan[1], d)

        # re-attach pinned faces as zeros (homogeneous walls)
        for d in pinned:
            pad = [(0, 0)] * dim
            pad[d] = (1, 0)
            x = jnp.pad(x, pad)
        return x
