"""CIB mobility-solver menu: Direct / Krylov / KrylovFreeBody solvers.

Reference parity: the CIB solver stack (P15, SURVEY.md §2.2 —
``DirectMobilitySolver``, ``KrylovMobilitySolver``,
``KrylovFreeBodyMobilitySolver``). The reference assembles dense
approximate mobility matrices (RPY / empirical fits) in Fortran and uses
them directly for small problems or as preconditioners for
PETSc-Krylov solves of the exact (grid-resolved) mobility; the free-body
mobility solver iterates on the body-space Schur complement
``N^{-1} = K^T M^{-1} K`` so force-free bodies (sedimenting spheres,
swimmers) can be advanced without prescribing their motion.

TPU-first redesign:

- The dense approximate mobility is a single ``(N*d, N*d)`` pairwise
  tensor built with broadcasting and factorized by dense Cholesky — both
  MXU-friendly batched ops. 3D uses the Rotne--Prager--Yamakawa tensor
  (SPD for all non-overlapping AND overlapping configurations); 2D uses
  the regularized-Stokeslet blob tensor of Cortez's method (free-space
  2D Stokeslets have the Stokes paradox; the blob form is the standard
  SPD regularization).
- The exact mobility ``M = J L^{-1} S`` (spread -> FFT Stokes -> interp,
  ``integrators/cib.py``) is applied matrix-free; ``KrylovMobilitySolver``
  wraps it in the jit-native preconditioned CG of ``solvers/krylov``
  with the dense Cholesky solve as preconditioner.
- ``KrylovFreeBodyMobilitySolver`` runs FGMRES on the (small) body
  resistance system matrix-free — each application is one inner
  preconditioned mobility solve — preconditioned by the dense
  approximate body mobility ``(K^T Mtilde^{-1} K)^{-1}``, so the outer
  iteration count is independent of marker count.

All solves are shape-static and jittable; nothing here depends on the
marker configuration at trace time.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.integrators.cib import (RigidBodies, n_rigid_modes,
                                       rigid_force_torque, rigid_velocity)
from ibamr_tpu.solvers import krylov


# ---------------------------------------------------------------------------
# dense approximate mobility tensors
# ---------------------------------------------------------------------------

def rpy_mobility_matrix(X: jnp.ndarray, radius: float,
                        mu: float) -> jnp.ndarray:
    """Dense 3D Rotne--Prager--Yamakawa mobility, ``(N*3, N*3)``.

    Self term ``I/(6 pi mu a)``; far field (r > 2a)
    ``(1/(8 pi mu r)) [(1 + 2a^2/(3r^2)) I + (1 - 2a^2/r^2) rhat rhat]``;
    the overlapping correction (r <= 2a)
    ``(1/(6 pi mu a)) [(1 - 9r/(32a)) I + (3r/(32a)) rhat rhat]``
    keeps the matrix SPD for every configuration — the property the
    preconditioner and the direct small-problem solve both rely on.
    """
    a = float(radius)
    N = X.shape[0]
    d = X.shape[1]
    assert d == 3, "rpy_mobility_matrix is the 3D tensor; 2D uses " \
        "blob_mobility_matrix"
    dx = X[:, None, :] - X[None, :, :]          # (N, N, 3)
    r2 = jnp.sum(dx * dx, axis=-1)
    r_true = jnp.sqrt(r2)                       # branch selector (exact)
    # guarded radius keeps every arithmetic path finite: coincident
    # DISTINCT markers (touching bodies) would otherwise put inf/NaN in
    # the unselected far branch and jnp.where propagates NaN*0
    r2g = jnp.where(r2 > 0, r2, 1.0)
    r = jnp.sqrt(r2g)
    rhat = dx / r[..., None]                    # 0 at coincident pairs
    eye = jnp.eye(d, dtype=X.dtype)
    outer = rhat[..., :, None] * rhat[..., None, :]   # (N, N, 3, 3)

    c_far = 1.0 / (8.0 * jnp.pi * mu * r)
    far = c_far[..., None, None] * (
        (1.0 + 2.0 * a * a / (3.0 * r2g))[..., None, None] * eye
        + (1.0 - 2.0 * a * a / r2g)[..., None, None] * outer)

    c0 = 1.0 / (6.0 * jnp.pi * mu * a)
    near = c0 * ((1.0 - 9.0 * r_true / (32.0 * a))[..., None, None] * eye
                 + (3.0 * r_true / (32.0 * a))[..., None, None] * outer)

    # coincident pairs take the near branch, whose r->0 limit is the
    # self-mobility c0*I — the correct RPY continuation
    blocks = jnp.where((r_true < 2.0 * a)[..., None, None], near, far)
    self_block = c0 * eye
    iN = jnp.arange(N)
    blocks = blocks.at[iN, iN].set(self_block)
    return blocks.transpose(0, 2, 1, 3).reshape(N * d, N * d)


def blob_mobility_matrix(X: jnp.ndarray, radius: float,
                         mu: float) -> jnp.ndarray:
    """Dense 2D regularized-Stokeslet (blob) mobility, ``(N*2, N*2)``.

    The 2D free-space Stokeslet has no finite self-mobility (Stokes
    paradox); the blob-regularized tensor of the method of regularized
    Stokeslets, with blob width ``eps = radius``,

      G_ij = (1/(4 pi mu)) [ -delta_ij (ln(R + eps)
                                        - eps (R + 2 eps)/(R (R + eps)))
                             + x_i x_j (R + 2 eps)/(R (R + eps)^2) ],
      R = sqrt(r^2 + eps^2),

    is the convolution of Stokeslets with a positive blob pair, hence
    symmetric positive definite up to the log kernel's conditional
    definiteness; a small diagonal shift (``jitter``) makes the Cholesky
    robust in f32.
    """
    eps = float(radius)
    N = X.shape[0]
    d = X.shape[1]
    assert d == 2, "blob_mobility_matrix is the 2D tensor"
    dx = X[:, None, :] - X[None, :, :]
    r2 = jnp.sum(dx * dx, axis=-1)
    R = jnp.sqrt(r2 + eps * eps)
    eye = jnp.eye(d, dtype=X.dtype)
    c = 1.0 / (4.0 * jnp.pi * mu)
    diag_term = -(jnp.log(R + eps)
                  - eps * (R + 2.0 * eps) / (R * (R + eps)))
    outer = dx[..., :, None] * dx[..., None, :]
    cross = (R + 2.0 * eps) / (R * (R + eps) ** 2)
    blocks = c * (diag_term[..., None, None] * eye
                  + cross[..., None, None] * outer)
    return blocks.transpose(0, 2, 1, 3).reshape(N * d, N * d)


def dense_mobility_matrix(X: jnp.ndarray, radius: float,
                          mu: float) -> jnp.ndarray:
    """Dimension dispatch: RPY in 3D, regularized blob in 2D."""
    return (rpy_mobility_matrix if X.shape[1] == 3
            else blob_mobility_matrix)(X, radius, mu)


# ---------------------------------------------------------------------------
# DirectMobilitySolver
# ---------------------------------------------------------------------------

class DirectMobilitySolver:
    """Dense approximate mobility: assemble, Cholesky-factorize, solve.

    The analog of the reference's ``DirectMobilitySolver`` (P15): exact
    for the model tensor it assembles, approximate for the grid-resolved
    mobility — used standalone for small blobs and as the preconditioner
    inside the Krylov solvers. The factorization is a one-time dense
    cost; every ``solve`` is two triangular solves (MXU batched).
    """

    def __init__(self, X: jnp.ndarray, radius: float, mu: float,
                 jitter: float = 1e-10):
        self.X = X
        self.radius = float(radius)
        self.mu = float(mu)
        self.dim = X.shape[1]
        M = dense_mobility_matrix(X, radius, mu)
        n = M.shape[0]
        scale = jnp.mean(jnp.diag(M))
        self._chol = jnp.linalg.cholesky(
            M + (jitter * scale) * jnp.eye(n, dtype=M.dtype))
        self._M = M

    def matrix(self) -> jnp.ndarray:
        return self._M

    def apply(self, lam: jnp.ndarray) -> jnp.ndarray:
        """Mtilde lam, marker-shaped ``(N, d)`` in and out."""
        v = self._M @ lam.reshape(-1)
        return v.reshape(lam.shape)

    def solve(self, rhs: jnp.ndarray) -> jnp.ndarray:
        """Mtilde^{-1} rhs via the cached Cholesky factor."""
        b = rhs.reshape(-1)
        y = jax.scipy.linalg.solve_triangular(self._chol, b, lower=True)
        x = jax.scipy.linalg.solve_triangular(self._chol.T, y, lower=False)
        return x.reshape(rhs.shape)

    def body_resistance(self, bodies: RigidBodies) -> jnp.ndarray:
        """Dense approximate body resistance ``K^T Mtilde^{-1} K``
        (``(B*nm, B*nm)``, SPD). One triangular solve per rigid mode."""
        nb = bodies.n_bodies
        nm = n_rigid_modes(self.dim)
        eye = jnp.eye(nb * nm, dtype=self.X.dtype).reshape(nb * nm, nb, nm)
        KE = jax.vmap(lambda e: rigid_velocity(self.X, bodies, e))(eye)
        sols = jax.vmap(self.solve)(KE)
        R = jnp.einsum('and,bnd->ab', KE, sols)
        return 0.5 * (R + R.T)


# ---------------------------------------------------------------------------
# KrylovMobilitySolver
# ---------------------------------------------------------------------------

class KrylovMobilitySolver:
    """Preconditioned CG on the exact grid mobility ``M = J L^{-1} S``.

    ``mobility_apply`` is the matrix-free exact operator (one spread +
    Stokes solve + interp per application, e.g.
    ``CIBMethod.mobility_apply``); the dense ``DirectMobilitySolver``
    supplies the preconditioner, collapsing the kernel-regularized
    spectrum so iteration counts stay flat as markers are added — the
    same division of labor as the reference's
    ``KrylovMobilitySolver(DirectMobilitySolver)`` nesting.
    """

    def __init__(self, mobility_apply: Callable[[jnp.ndarray], jnp.ndarray],
                 precond: Optional[DirectMobilitySolver] = None,
                 tol: float = 1e-9, maxiter: int = 500):
        self.mobility_apply = mobility_apply
        self.precond = precond
        self.tol = float(tol)
        self.maxiter = int(maxiter)

    def solve(self, rhs: jnp.ndarray,
              x0: Optional[jnp.ndarray] = None) -> krylov.SolveResult:
        M = self.precond.solve if self.precond is not None else None
        return krylov.cg(self.mobility_apply, rhs, x0=x0, M=M,
                         tol=self.tol, maxiter=self.maxiter)


# ---------------------------------------------------------------------------
# KrylovFreeBodyMobilitySolver
# ---------------------------------------------------------------------------

class FreeBodyResult(NamedTuple):
    U: jnp.ndarray           # (B, nm) rigid motions of the free bodies
    lam: jnp.ndarray         # (N, d) constraint forces realizing them
    converged: jnp.ndarray   # outer FGMRES convergence flag
    resnorm: jnp.ndarray     # outer residual norm
    iters: jnp.ndarray       # outer iterations


class KrylovFreeBodyMobilitySolver:
    """Matrix-free Krylov solve of the body mobility problem
    ``(K^T M^{-1} K) U = F_ext`` for force/torque-driven FREE bodies.

    Each outer application is one inner (preconditioned) mobility solve;
    the outer system is only ``B * n_rigid_modes`` big, FGMRES because
    the inexact inner solves make the operator only approximately
    symmetric. The preconditioner is the INVERSE of the dense
    approximate body resistance from ``DirectMobilitySolver`` — the
    "reusing the dense resistance" composition of the reference's
    ``KrylovFreeBodyMobilitySolver``. Unlike
    ``CIBMethod.resistance_matrix`` (one inner solve per rigid mode,
    6B of them in 3D), the cost here is the handful of outer iterations
    the preconditioner leaves — independent of body count for
    well-separated bodies.
    """

    def __init__(self, mobility_apply: Callable[[jnp.ndarray], jnp.ndarray],
                 bodies: RigidBodies, X: jnp.ndarray, radius: float,
                 mu: float, inner_tol: float = 1e-8,
                 inner_maxiter: int = 500, outer_tol: float = 1e-7,
                 outer_maxiter: int = 40):
        self.bodies = bodies
        self.X = X
        self.dim = X.shape[1]
        # dtype-aware tolerance floors: production marker state is f32
        # (TPU), where 1e-8/1e-7 sit below attainable residuals and the
        # inner CG would burn maxiter then report failure (caught by the
        # round-3 f32 driver verify).
        eps = float(jnp.finfo(X.dtype).eps)
        inner_tol = max(float(inner_tol), 50.0 * eps)
        outer_tol = max(float(outer_tol), 200.0 * eps)
        self.direct = DirectMobilitySolver(X, radius, mu)
        self.inner = KrylovMobilitySolver(mobility_apply,
                                          precond=self.direct,
                                          tol=inner_tol,
                                          maxiter=inner_maxiter)
        self.outer_tol = float(outer_tol)
        self.outer_maxiter = int(outer_maxiter)
        # dense approximate body mobility = preconditioner for the outer
        R_approx = self.direct.body_resistance(bodies)
        self._N_approx = jnp.linalg.inv(R_approx)

    def _resistance_apply(self, U: jnp.ndarray) -> jnp.ndarray:
        """(K^T M^{-1} K) U, flat (B*nm,) in and out."""
        nb = self.bodies.n_bodies
        nm = n_rigid_modes(self.dim)
        rhs = rigid_velocity(self.X, self.bodies, U.reshape(nb, nm))
        res = self.inner.solve(rhs)
        return rigid_force_torque(self.X, self.bodies,
                                  res.x).reshape(-1)

    def solve(self, FT: jnp.ndarray) -> FreeBodyResult:
        """External force/torque ``FT`` (B, nm) -> free rigid motions."""
        nb = self.bodies.n_bodies
        nm = n_rigid_modes(self.dim)
        res = krylov.fgmres(self._resistance_apply, FT.reshape(-1),
                            M=lambda v: self._N_approx @ v,
                            m=min(self.outer_maxiter, nb * nm + 2),
                            tol=self.outer_tol,
                            restarts=2)
        U = res.x.reshape(nb, nm)
        # recover the realizing constraint forces for spreading/diagnostics
        lam = self.inner.solve(
            rigid_velocity(self.X, self.bodies, U)).x
        return FreeBodyResult(U=U, lam=lam, converged=res.converged,
                              resnorm=res.resnorm, iters=res.iters)
