"""Spectral-plan layer: hash-cons-cached symbol tables + the k-space-
resident fused fluid substep.

Round-5 measurement (PERF.md, BENCH_TPU_NUMBERS.json rev 96498b2) put
``fluid_solve`` at 39.3 ms — the dominant flagship phase once the
transfer-side levers landed. The remaining structural waste was not in
the transforms themselves (the fused substep already runs ONE batched
rfftn and ONE batched irfftn) but around them:

- every spectral solve recomputed its symbol tables (`laplacian_symbol`,
  the staggered divergence symbols) per call/trace — regrids and solver
  re-construction paid the rebuild over and over;
- the transform operands were pinned to f32 with no opt-in cheaper
  precision, even though bf16 operand compression is exactly the trade
  the ``packed_bf16`` transfer engine already sells.

A :class:`SpectralPlan` precomputes the tables ONCE per
``(shape, dx, dtype, bc)`` and hash-conses them in a bounded LRU
(:func:`get_plan`), device-resident, so every spectral solve — the
fused substep, Poisson, Helmholtz, the all-periodic saddle solve —
shares one set of constants. The fused :meth:`SpectralPlan.substep`
performs the viscous Helmholtz solve, the staggered Leray projection,
the pressure-increment assembly AND an optional body-force spectral
filter as ONE batched forward rfftn -> diagonal k-space algebra -> ONE
batched inverse irfftn. ``spectral_dtype`` opts into the mixed-precision
transform path: bf16/split-real transform OPERANDS (the real input
batch and the split-real spectral intermediate are rounded through the
storage dtype) with f32 twiddle factors and f32 accumulation inside the
transform — the accuracy contract is tolerance-pinned against the f64
oracle in tests/test_spectral_plan.py, exactly like ``packed_bf16``.

The default-precision path is BITWISE identical to the pre-plan
implementation (same ops in the same order; the cached tables are built
by the same ``fft.laplacian_symbol`` / ``fft._staggered_div_symbols``
calls), so trajectories and restart files are unchanged.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from collections import OrderedDict
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Vel = Tuple[jnp.ndarray, ...]

# Reverse-mode policy for the fused substep (PR 19). The default custom
# VJP treats the Helmholtz/pressure coefficients (alpha, beta,
# pinc_coeffs) and filter_sym as non-differentiated constants — design
# variables flow through the RHS fields, and the cotangent pass is the
# SAME plan with conjugated symbols (one batched rfftn + one batched
# irfftn, zero saved spectra). Set this True to fall back to plain
# autodiff when a caller genuinely needs d/d(alpha) or d/d(beta)
# (e.g. differentiating through an adaptive dt); that path re-derives
# the chain rule through the k-space algebra and is NOT covered by the
# ``grad_substep`` graph budget.
DIFFERENTIATE_COEFFS = False


@contextlib.contextmanager
def plain_autodiff_substep():
    """Trace-scoped opt-out of the fused substep's custom VJP.

    ``jax.custom_vjp`` refuses forward-mode autodiff (jvp/linearize);
    graphs that linearize through the fluid solve — the implicit
    Newton-Krylov residual folds an INS step into every evaluation —
    must trace inside this context, which routes ``substep`` through
    the raw k-space algebra (both autodiff modes supported natively;
    coefficient gradients become available; the ``grad_substep``
    budget does not apply to graphs traced this way)."""
    global DIFFERENTIATE_COEFFS
    prev = DIFFERENTIATE_COEFFS
    DIFFERENTIATE_COEFFS = True
    try:
        yield
    finally:
        DIFFERENTIATE_COEFFS = prev

# -- spectral_dtype normalization -------------------------------------------

_SPECTRAL_DTYPE_ALIASES = {
    None: None, "none": None, "f32": None, "float32": None,
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
    "f64": jnp.float64, "float64": jnp.float64,
}


def canonical_spectral_dtype(spec):
    """Normalize the ``spectral_dtype`` knob: ``None`` (native working
    precision, f32 by convention), ``jnp.bfloat16`` (compressed
    transform operands), or ``jnp.float64`` (escalated: the whole
    substep runs on the f64 twin plan — the precision-escalation chain's
    last link and the shadow audit's reference). Anything else is a
    typo'd input file and raises.

    Note: under a runtime without x64 enabled the f64 request
    canonicalizes to f32 at plan-build time (jax's standard dtype
    demotion) — the knob is then a no-op, not an error."""
    if isinstance(spec, str):
        key = spec.lower()
        if key in _SPECTRAL_DTYPE_ALIASES:
            return _SPECTRAL_DTYPE_ALIASES[key]
        raise ValueError(
            f"spectral_dtype = {spec!r}: expected one of "
            f"{sorted(k for k in _SPECTRAL_DTYPE_ALIASES if k)} or None")
    if spec is None or spec is jnp.bfloat16 or spec is jnp.float64:
        return spec
    if jnp.dtype(spec) == jnp.dtype(jnp.bfloat16):
        return jnp.bfloat16
    if jnp.dtype(spec) == jnp.dtype(jnp.float64):
        return jnp.float64
    raise ValueError(f"spectral_dtype = {spec!r}: only bf16 operand "
                     "compression or f64 escalation is supported "
                     "(None = native precision)")


def _round_real(x: jnp.ndarray, sdtype) -> jnp.ndarray:
    """Round a real transform operand through the storage dtype; the
    transform itself still runs at f32 (f32 twiddle/accumulation)."""
    return x.astype(sdtype).astype(jnp.float32)


def _round_complex(z: jnp.ndarray, sdtype) -> jnp.ndarray:
    """Split-real rounding of a spectral operand: the re/im planes are
    rounded through the storage dtype independently (complex-bf16 does
    not exist as a device type; split-real IS the storage layout)."""
    re = jnp.real(z).astype(sdtype).astype(jnp.float32)
    im = jnp.imag(z).astype(sdtype).astype(jnp.float32)
    return jax.lax.complex(re, im)


# -- the plan ----------------------------------------------------------------

class SpectralPlan:
    """Device-resident spectral symbol tables for one
    ``(shape, dx, dtype, bc)`` and the solves that share them.

    Construct via :func:`get_plan` (the hash-cons cache), not directly —
    direct construction bypasses the LRU and recomputes the tables the
    cache exists to share.
    """

    def __init__(self, shape: Sequence[int], dx: Sequence[float],
                 dtype, bc: str = "periodic"):
        if bc != "periodic":
            raise ValueError(
                f"SpectralPlan bc={bc!r}: only 'periodic' has a "
                "diagonal spectral symbol (walls go through "
                "solvers.fastdiag / solvers.stokes)")
        # table builders live in solvers.fft (the canonical symbol
        # definitions); imported lazily because fft delegates its fused
        # substep back to this module
        from ibamr_tpu.solvers import fft

        self.shape = tuple(int(s) for s in shape)
        self.dx = tuple(float(h) for h in dx)
        self.bc = bc
        self.dim = len(self.shape)
        # batched-transform axes for a leading stack dimension
        self.axes = tuple(range(1, self.dim + 1))
        self.rdtype = jax.dtypes.canonicalize_dtype(dtype)
        self.cdtype = jnp.complex128 if self.rdtype == jnp.float64 \
            else jnp.complex64
        # the tables: discrete-Laplacian symbol on the rfftn grid and
        # the per-axis staggered divergence symbols. Built by the same
        # fft.py code the unplanned solves used, so values are bitwise
        # identical to a per-call rebuild. ensure_compile_time_eval:
        # the first get_plan for a shape often fires INSIDE a jit
        # trace — the tables must come out as concrete device arrays,
        # not tracers, or the hash-cons cache would leak trace-scoped
        # values into every later caller.
        with jax.ensure_compile_time_eval():
            self.sym = fft.laplacian_symbol(self.shape, self.dx,
                                            self.rdtype)
            self.D = fft._staggered_div_symbols(self.shape, self.dx,
                                                self.cdtype)
            if self.rdtype != jnp.float32:
                # pre-materialized f32 views for the bf16 transform
                # path (f32 twiddle/accumulation)
                self._sym_f32 = self.sym.astype(jnp.float32)
                self._D_f32 = tuple(d.astype(jnp.complex64)
                                    for d in self.D)
            else:
                self._sym_f32 = self.sym
                self._D_f32 = self.D

    # -- table views ---------------------------------------------------------
    def _tables(self, f32: bool):
        """(sym, D) at the working precision: the plan's native dtype,
        or the f32 view the bf16 transform path computes in."""
        if not f32:
            return self.sym, self.D
        return self._sym_f32, self._D_f32

    # -- fused substep (the tentpole) ----------------------------------------
    def substep(self, rhs: Vel, alpha, beta,
                pinc_coeffs: Tuple[float, float],
                spectral_dtype=None,
                filter_sym: Optional[jnp.ndarray] = None
                ) -> Tuple[Vel, jnp.ndarray]:
        """K-space-resident fused Stokes substep.

        ONE batched forward rfftn over the stacked MAC rhs, then the
        whole chain as diagonal spectral algebra — Helmholtz inverse
        ``(alpha + beta lap)^{-1}``, optional body-force spectral
        filter ``filter_sym`` (a real symbol multiplied into the rhs
        spectrum: dealiasing masks, Gaussian force smoothing — zero
        extra transforms), staggered Leray projection, and the
        pressure-increment assembly ``p_inc = (a + b lap) phi0`` for
        ``pinc_coeffs = (a, b)`` — then ONE batched inverse irfftn for
        the ``dim + 1`` outputs.

        ``spectral_dtype=jnp.bfloat16`` rounds the transform operands
        (real input batch, split-real spectral intermediate) through
        bf16 while all twiddle factors, k-space tables and accumulation
        stay f32. Returns ``(u_new, p_inc)``; with the default
        precision ``u_new`` is divergence-free to roundoff.
        """
        sdtype = canonical_spectral_dtype(spectral_dtype)
        rdtype = self.rdtype
        if sdtype is jnp.float64:
            # escalated precision: run the WHOLE substep on the f64
            # twin plan (tables, transforms and algebra all at f64) and
            # cast the outputs back to the caller's working dtype. This
            # is the precision-escalation chain's last link and the
            # shadow audit's reference path.
            if rdtype == jnp.float64:
                return self.substep(rhs, alpha, beta, pinc_coeffs,
                                    spectral_dtype=None,
                                    filter_sym=filter_sym)
            plan64 = get_plan(self.shape, self.dx, jnp.float64, self.bc)
            u64, p64 = plan64.substep(
                tuple(c.astype(plan64.rdtype) for c in rhs),
                alpha, beta, pinc_coeffs, spectral_dtype=None,
                filter_sym=filter_sym)
            return (tuple(c.astype(rdtype) for c in u64),
                    p64.astype(rdtype))
        a, b = pinc_coeffs
        sdtype_name = "bf16" if sdtype is jnp.bfloat16 else "none"
        # strongly type concrete coefficients HERE, at trace time: a
        # weak python float crossing the custom_vjp boundary becomes a
        # convert_element_type op per scalar in the compiled graph (the
        # convert budgets pin the substep at its pre-VJP count). Traced
        # coefficients (dt under grad) pass through untouched.
        wdtype = jnp.float32 if sdtype is not None else self.rdtype
        alpha, beta, a, b = (
            v if isinstance(v, jax.core.Tracer) else jnp.asarray(v, wdtype)
            for v in (alpha, beta, a, b))
        if DIFFERENTIATE_COEFFS:
            # opt-out: plain autodiff through the raw math (coefficient
            # cotangents available, gradient cost unbudgeted)
            return _substep_raw(self, sdtype_name, tuple(rhs),
                                alpha, beta, a, b, filter_sym)
        return _substep_core(self, sdtype_name, tuple(rhs),
                             alpha, beta, a, b, filter_sym)

    def kspace_algebra(self, uh: jnp.ndarray, alpha, beta,
                       pinc_coeffs: Tuple[float, float],
                       f32: bool = False,
                       filter_sym: Optional[jnp.ndarray] = None
                       ) -> jnp.ndarray:
        """The diagonal spectral algebra between the substep's two
        transforms: ``uh`` is the stacked forward spectrum of the dim
        MAC components; returns the stacked dim+1 inverse-transform
        operand. Exposed separately so bench.py can time the
        transform-vs-algebra split of the fluid phase."""
        dim = self.dim
        sym, D = self._tables(f32=f32)
        wdtype = jnp.float32 if f32 else self.rdtype
        cdtype = uh.dtype
        if filter_sym is not None:
            uh = uh * filter_sym.astype(wdtype)[None]
        denom = (alpha + beta * sym).astype(wdtype)
        uh = uh / denom[None]
        divh = None
        for d in range(dim):
            t = D[d] * uh[d]
            divh = t if divh is None else divh + t
        sym_safe = jnp.where(sym == 0, 1.0, sym)
        phih = jnp.where(sym == 0, 0.0, divh / sym_safe)
        a, b = pinc_coeffs
        return jnp.stack(
            [uh[d] + jnp.conj(D[d]) * phih for d in range(dim)]
            + [((a + b * sym) * phih).astype(cdtype)])

    def kspace_algebra_adjoint(self, ch: jnp.ndarray, alpha, beta,
                               pinc_coeffs: Tuple[float, float],
                               f32: bool = False,
                               filter_sym: Optional[jnp.ndarray] = None
                               ) -> jnp.ndarray:
        """Conjugate-transpose of :meth:`kspace_algebra`'s block symbol,
        applied to the stacked ``dim + 1`` cotangent spectra ``ch``.

        The substep's spatial map is ``irfftn . diag(M) . rfftn`` for
        the per-mode block symbol ``M(k)``; its real transpose is the
        SAME transform pair around ``M(k)^H``. With ``H = 1/(alpha +
        beta*lam)``, ``P = filter_sym`` and ``D_e`` the staggered
        divergence symbols, the closed form is

            (M^H c)_e = H * P * [ c_e + conj(D_e)/lam *
                                  ( sum_d D_d c_d + (a + b*lam) c_p ) ]

        with the ``1/lam`` term zeroed at k=0 (matching the primal's
        zero-mean pressure convention). Same cached tables, same
        diagonal structure, zero extra transforms — the cotangent pass
        IS the plan."""
        dim = self.dim
        sym, D = self._tables(f32=f32)
        wdtype = jnp.float32 if f32 else self.rdtype
        cdtype = ch.dtype
        a, b = pinc_coeffs
        g = None
        for d in range(dim):
            t = D[d] * ch[d]
            g = t if g is None else g + t
        g = g + ((a + b * sym) * ch[dim]).astype(cdtype)
        sym_safe = jnp.where(sym == 0, 1.0, sym)
        psih = jnp.where(sym == 0, 0.0, g / sym_safe)
        denom = (alpha + beta * sym).astype(wdtype)
        out = jnp.stack([ch[d] + jnp.conj(D[d]) * psih
                         for d in range(dim)]) / denom[None]
        if filter_sym is not None:
            out = out * filter_sym.astype(wdtype)[None]
        return out

    # -- the classic solves, sharing the cached tables -----------------------
    def solve_poisson(self, rhs: jnp.ndarray) -> jnp.ndarray:
        """lap(p) = rhs; zero-mean solution (k=0 mode discarded)."""
        sym = self.sym
        rhat = jnp.fft.rfftn(rhs)
        sym_safe = jnp.where(sym == 0, 1.0, sym)
        phat = jnp.where(sym == 0, 0.0, rhat / sym_safe)
        p = jnp.fft.irfftn(phat, s=self.shape)
        return p.astype(rhs.dtype)

    def solve_helmholtz(self, rhs: jnp.ndarray, alpha, beta) -> jnp.ndarray:
        """(alpha + beta lap) u = rhs (alpha + beta*lam != 0 required)."""
        rhat = jnp.fft.rfftn(rhs)
        uhat = rhat / (alpha + beta * self.sym)
        u = jnp.fft.irfftn(uhat, s=self.shape)
        return u.astype(rhs.dtype)

    def solve_stokes_saddle(self, f_u: Vel, f_p: jnp.ndarray,
                            alpha, mu) -> Tuple[Vel, jnp.ndarray]:
        """Exact periodic saddle-point solve of

            alpha*u - mu*lap(u) + grad(p) = f_u,    -div(u) = f_p

        as one batched spectral pass (the all-periodic collapse of the
        coupled Krylov solve in solvers.stokes): with A = alpha - mu*lam
        and the staggered symbols D_d (gradient -conj(D_d)),

            p_hat = (sum_d D_d f_hat_d + A f_hat_p) / lam     (0 at k=0)
            u_hat_d = (f_hat_d + conj(D_d) p_hat) / A

        Zero modes follow the periodic conventions: p is zero-mean; the
        k=0 velocity mode is f_hat_d(0)/alpha (zeroed when alpha == 0 —
        the steady zero-mean frame). ``alpha`` may be traced.
        """
        dim = self.dim
        rdtype = self.rdtype
        sym, D = self.sym, self.D
        fh = jnp.fft.rfftn(jnp.stack(tuple(f_u) + (f_p,)),
                           axes=self.axes)
        A = (alpha - mu * sym).astype(rdtype)
        divf = None
        for d in range(dim):
            t = D[d] * fh[d]
            divf = t if divf is None else divf + t
        sym_safe = jnp.where(sym == 0, 1.0, sym)
        ph = jnp.where(sym == 0, 0.0, (divf + A * fh[dim]) / sym_safe)
        A_safe = jnp.where(A == 0, 1.0, A)
        uh = jnp.stack(
            [jnp.where(A == 0, 0.0,
                       (fh[d] + jnp.conj(D[d]) * ph) / A_safe)
             for d in range(dim)] + [ph])
        out = jnp.fft.irfftn(uh, s=self.shape, axes=self.axes)
        out = out.astype(rdtype)
        return tuple(out[d] for d in range(dim)), out[dim]


# -- fused-substep reverse mode (PR 19) --------------------------------------
#
# ``_substep_raw`` is the literal substep math (bitwise identical to the
# pre-VJP implementation: same ops, same order). ``_substep_core`` wraps
# it in a ``jax.custom_vjp`` whose backward pass applies the SAME plan
# with conjugated symbols: one batched rfftn over the stacked dim+1
# output cotangents, the diagonal ``kspace_algebra_adjoint``, one
# batched irfftn for the dim RHS cotangents. No spectra are saved from
# the forward pass (residuals are the five scalars + the filter table),
# so a full vjp round trip costs exactly 2x the primal's batched FFT
# calls — the ``grad_substep`` graph budget pins that statically.

def _substep_raw(plan: "SpectralPlan", sdtype_name: str, rhs: Vel,
                 alpha, beta, a, b,
                 filter_sym: Optional[jnp.ndarray]
                 ) -> Tuple[Vel, jnp.ndarray]:
    sdtype = jnp.bfloat16 if sdtype_name == "bf16" else None
    x = jnp.stack(rhs)
    if sdtype is not None:
        # bf16 transform operands, f32 twiddle/accumulation
        x = _round_real(x.astype(jnp.float32), sdtype)
    uh = jnp.fft.rfftn(x, axes=plan.axes)
    outh = plan.kspace_algebra(uh, alpha, beta, (a, b),
                               f32=sdtype is not None,
                               filter_sym=filter_sym)
    if sdtype is not None:
        # split-real compression of the inverse-transform operand
        outh = _round_complex(outh, sdtype)
    out = jnp.fft.irfftn(outh, s=plan.shape, axes=plan.axes)
    out = out.astype(plan.rdtype)
    return tuple(out[d] for d in range(plan.dim)), out[plan.dim]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _substep_core(plan: "SpectralPlan", sdtype_name: str, rhs: Vel,
                  alpha, beta, a, b,
                  filter_sym: Optional[jnp.ndarray]
                  ) -> Tuple[Vel, jnp.ndarray]:
    return _substep_raw(plan, sdtype_name, rhs, alpha, beta, a, b,
                        filter_sym)


def _substep_fwd(plan, sdtype_name, rhs, alpha, beta, a, b, filter_sym):
    out = _substep_raw(plan, sdtype_name, rhs, alpha, beta, a, b,
                       filter_sym)
    # residuals: coefficients only — the adjoint needs no forward
    # activations (the whole point of "adjoint at primal cost")
    return out, (alpha, beta, a, b, filter_sym)


def _substep_bwd(plan, sdtype_name, res, ct):
    alpha, beta, a, b, filter_sym = res
    ct_u, ct_p = ct
    sdtype = jnp.bfloat16 if sdtype_name == "bf16" else None
    c = jnp.stack(tuple(ct_u) + (ct_p,)).astype(
        jnp.float32 if sdtype is not None else plan.rdtype)
    if sdtype is not None:
        # mirror the primal's operand compression on the cotangents so
        # the transposed transforms see the same storage precision
        c = _round_real(c, sdtype)
    ch = jnp.fft.rfftn(c, axes=plan.axes)
    gh = plan.kspace_algebra_adjoint(ch, alpha, beta, (a, b),
                                     f32=sdtype is not None,
                                     filter_sym=filter_sym)
    if sdtype is not None:
        gh = _round_complex(gh, sdtype)
    g = jnp.fft.irfftn(gh, s=plan.shape, axes=plan.axes)
    g = g.astype(plan.rdtype)
    rhs_ct = tuple(g[d] for d in range(plan.dim))
    # alpha/beta/pinc are treated as constants (see
    # DIFFERENTIATE_COEFFS); filter_sym is a precomputed table
    zero = lambda v: None if v is None else jnp.zeros_like(v)  # noqa: E731
    return (rhs_ct, zero(alpha), zero(beta), zero(a), zero(b),
            zero(filter_sym))


_substep_core.defvjp(_substep_fwd, _substep_bwd)


# -- the hash-cons LRU cache -------------------------------------------------

_CACHE_MAXSIZE = 16
_cache: "OrderedDict[tuple, SpectralPlan]" = OrderedDict()
_lock = threading.Lock()
_stats = {"hits": 0, "misses": 0, "evictions": 0}

# telemetry twins (PR 9): the same three events published onto the
# process-wide bus, so a run ledger's per-chunk counter snapshots show
# plan-cache behavior alongside every other subsystem
from ibamr_tpu import obs as _obs  # noqa: E402

_OBS_HITS = _obs.counter("spectral_plan_hits_total")
_OBS_MISSES = _obs.counter("spectral_plan_misses_total")
_OBS_EVICTIONS = _obs.counter("spectral_plan_evictions_total")


def plan_key(shape: Sequence[int], dx: Sequence[float], dtype,
             bc: str = "periodic") -> tuple:
    # the x64 flag is part of the key: table BUILDERS run np/jnp math
    # whose intermediate precision follows the mode, so two same-dtype
    # plans built under different modes differ in the last ulp — enough
    # to break tools/replay.py's bitwise pin when it re-executes a
    # capsule under the recorded mode inside a long-lived process
    return (tuple(int(s) for s in shape),
            tuple(float(h) for h in dx),
            jnp.dtype(jax.dtypes.canonicalize_dtype(dtype)).name,
            bc, bool(jax.config.jax_enable_x64))


def get_plan(shape: Sequence[int], dx: Sequence[float], dtype,
             bc: str = "periodic") -> SpectralPlan:
    """Hash-cons a :class:`SpectralPlan`: one table build per distinct
    ``(shape, dx, dtype, bc)``, LRU-bounded so a regrid loop (moving
    fine windows, level rebuilds) cannot grow the cache without bound.
    Device-resident: repeated jit traces capture the SAME arrays, so
    solver re-construction stops recomputing symbol tables."""
    key = plan_key(shape, dx, dtype, bc)
    with _lock:
        plan = _cache.get(key)
        if plan is not None:
            _stats["hits"] += 1
            _OBS_HITS.inc()
            _cache.move_to_end(key)
            return plan
    # build outside the lock (table construction runs device code)
    plan = SpectralPlan(shape, dx, dtype, bc)
    with _lock:
        # double-checked: a racing builder's plan wins LRU placement
        existing = _cache.get(key)
        if existing is not None:
            _stats["hits"] += 1
            _OBS_HITS.inc()
            _cache.move_to_end(key)
            return existing
        _stats["misses"] += 1
        _OBS_MISSES.inc()
        _cache[key] = plan
        while len(_cache) > _CACHE_MAXSIZE:
            _cache.popitem(last=False)
            _stats["evictions"] += 1
            _OBS_EVICTIONS.inc()
    return plan


def plan_cache_stats() -> dict:
    """{hits, misses, evictions, size, maxsize} — the observable the
    cache-boundedness test pins."""
    with _lock:
        return dict(_stats, size=len(_cache), maxsize=_CACHE_MAXSIZE)


def clear_plan_cache() -> None:
    with _lock:
        _cache.clear()
        for k in _stats:
            _stats[k] = 0


# -- module-level conveniences ----------------------------------------------

def spectral_substep(rhs: Vel, dx: Sequence[float], alpha, beta,
                     pinc_coeffs: Tuple[float, float],
                     spectral_dtype=None,
                     filter_sym: Optional[jnp.ndarray] = None
                     ) -> Tuple[Vel, jnp.ndarray]:
    """Plan-cached fused fluid substep (see
    :meth:`SpectralPlan.substep`); fetches/creates the plan for
    ``rhs[0].shape``."""
    plan = get_plan(rhs[0].shape, dx, rhs[0].dtype)
    return plan.substep(rhs, alpha, beta, pinc_coeffs,
                        spectral_dtype=spectral_dtype,
                        filter_sym=filter_sym)


def gaussian_filter_symbol(shape: Sequence[int], dx: Sequence[float],
                           width: float, dtype=jnp.float32) -> jnp.ndarray:
    """Spectral symbol of a discrete Gaussian smoother of standard
    deviation ``width`` (grid units of length): exp(width^2/2 * lam)
    with lam the discrete-Laplacian symbol (lam <= 0, so this is a pure
    low-pass). Intended as ``filter_sym`` for the fused substep's
    body-force smoothing — it rides the substep's existing transforms."""
    from ibamr_tpu.solvers import fft

    # widest AVAILABLE float (f64 only when x64 is enabled): asking for
    # f64 outright warns and truncates under the production x64-off
    # config (graph-audit first-wave finding)
    wide = jax.dtypes.canonicalize_dtype(jnp.float64)
    lam = fft.laplacian_symbol(shape, dx, wide)
    return jnp.exp(0.5 * float(width) ** 2 * lam).astype(dtype)
