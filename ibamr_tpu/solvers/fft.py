"""Spectral (FFT) solvers for the periodic uniform level.

Reference parity: on periodic uniform grids these replace the whole
FAC-multigrid + hypre stack (T8) and the Poisson/Helmholtz sub-solves of
the staggered Stokes projection preconditioner (P3) — SURVEY.md §3.3 "for
uniform-grid periodic acceptance configs the whole saddle solve collapses
to FFT Poisson projection + FFT Helmholtz".

Key design point: the inverted symbol is that of the **discrete** 2d+1-point
Laplacian, ``lam_k = (2 cos(2 pi k / n) - 2) / h^2`` per axis — NOT the
continuous ``-|k|^2``. Using the discrete symbol makes ``div u`` after
projection zero to machine precision, because FFT-solve(discrete symbol) is
the exact inverse of the stencil operator. The same circulant symbol applies
to cell- and face-centered fields (staggering shifts eigenvectors by a
phase, not eigenvalues), so one solver serves pressure and velocity.

On TPU, jnp.fft lowers to XLA's FFT; under sharding the transform induces
the all-to-all transposes over ICI that are this method's true long-range
communication (SURVEY.md §5.7).
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax.numpy as jnp

Vel = Tuple[jnp.ndarray, ...]


def laplacian_symbol(shape: Sequence[int], dx: Sequence[float],
                     dtype=jnp.float32) -> jnp.ndarray:
    """Symbol (eigenvalues) of the discrete periodic Laplacian on the
    rfftn-truncated spectral grid: sum_d (2 cos(2 pi k_d / n_d) - 2)/h_d^2.
    Shape: rfftn output shape for a real input of ``shape``."""
    dim = len(shape)
    sym = None
    for d in range(dim):
        n = shape[d]
        k = (jnp.fft.rfftfreq(n) if d == dim - 1 else jnp.fft.fftfreq(n))
        lam = (2.0 * jnp.cos(2.0 * math.pi * k) - 2.0) / (dx[d] ** 2)
        lam = lam.astype(dtype)
        bshape = [1] * dim
        bshape[d] = lam.shape[0]
        lam = lam.reshape(bshape)
        sym = lam if sym is None else sym + lam
    return sym


def solve_poisson_periodic(rhs: jnp.ndarray, dx: Sequence[float]) -> jnp.ndarray:
    """Solve lap(p) = rhs on the periodic grid; returns the zero-mean
    solution (rhs mean is projected out — the periodic compatibility
    condition). Symbol tables come from the hash-cons plan cache
    (solvers.spectral_plan), so repeated traces/regrids share them."""
    from ibamr_tpu.solvers import spectral_plan

    plan = spectral_plan.get_plan(rhs.shape, dx, rhs.dtype)
    return plan.solve_poisson(rhs)


def solve_helmholtz_periodic(rhs: jnp.ndarray, dx: Sequence[float],
                             alpha: float, beta: float) -> jnp.ndarray:
    """Solve (alpha + beta * lap) u = rhs on the periodic grid.

    For Crank-Nicolson viscous steps: alpha = rho/dt, beta = -mu/2.
    Requires alpha + beta*lam != 0 for all modes (true for alpha>0, beta<0).
    """
    from ibamr_tpu.solvers import spectral_plan

    plan = spectral_plan.get_plan(rhs.shape, dx, rhs.dtype)
    return plan.solve_helmholtz(rhs, alpha, beta)


def solve_helmholtz_periodic_vel(rhs: Vel, dx: Sequence[float],
                                 alpha: float, beta: float) -> Vel:
    """Component-wise Helmholtz solve for a MAC velocity (same symbol for
    every staggering)."""
    return tuple(solve_helmholtz_periodic(c, dx, alpha, beta) for c in rhs)


def solve_stokes_periodic(f: Vel, dx: Sequence[float],
                          mu: float) -> Tuple[Vel, jnp.ndarray]:
    """Solve steady Stokes  -mu lap(u) + grad(p) = f,  div(u) = 0  on the
    periodic MAC grid; returns (u, p), both zero-mean.

    Reference parity: the CIB formulation's fluid solve (P15) — the
    reference runs its Krylov staggered-Stokes stack; periodically the
    solve is exact in two FFT passes: p from lap(p) = div(f), then each
    velocity component from -mu lap(u_d) = (P f)_d, where P is the
    discrete Leray projection. All operators share the discrete symbol so
    div(u) == 0 to machine precision. The zero-mean convention discards
    any net force (a periodic steady state exists only in the zero-mean
    frame — the standard traction-free convention).
    """
    f_proj, phi = project_divergence_free(f, dx)
    # lap^{-1} zeroes the k=0 mode, so each u component is zero-mean
    u = tuple(-solve_poisson_periodic(c, dx) / mu for c in f_proj)
    return u, phi


def _staggered_div_symbols(shape: Sequence[int], dx: Sequence[float],
                           cdtype) -> Tuple[jnp.ndarray, ...]:
    """Per-axis spectral symbols of the staggered MAC divergence
    D_d = (e^{i theta_d} - 1)/h_d (lower-face storage: div at cell i
    takes u_d[i+1] - u_d[i]). The matching staggered gradient symbol is
    -conj(D_d), and sum_d |D_d|^2 = -laplacian_symbol — the identities
    that make the spectral projection exactly mirror the stencils."""
    dim = len(shape)
    out = []
    for d in range(dim):
        n = shape[d]
        f = (jnp.fft.rfftfreq(n) if d == dim - 1 else jnp.fft.fftfreq(n))
        theta = 2.0 * math.pi * f
        Dd = (jnp.exp(1j * theta) - 1.0) / dx[d]
        bshape = [1] * dim
        bshape[d] = Dd.shape[0]
        out.append(Dd.reshape(bshape).astype(cdtype))
    return tuple(out)


def helmholtz_project_periodic(rhs: Vel, dx: Sequence[float],
                               alpha: float, beta: float,
                               pinc_coeffs: Tuple[float, float],
                               spectral_dtype=None,
                               filter_sym=None) -> Tuple[Vel, jnp.ndarray]:
    """Fused spectral Stokes substep: ONE batched forward rfftn over
    the stacked MAC components, then the Helmholtz inverse, the
    staggered Leray projection, AND the pressure-increment assembly all
    as elementwise spectral arithmetic, then ONE batched inverse irfftn
    for the dim+1 outputs — 2 batched FFT calls total instead of the
    8 single-field transforms + three full-grid stencil passes of the
    unfused helmholtz_vel_solve -> project -> laplacian_cc pipeline
    (the projection-preconditioner collapse of SURVEY.md §3.3 taken to
    its fixed point; HBM traffic is the TPU bottleneck, so fewer
    full-array passes is the whole game).

    Round 6: delegates to the plan-cached k-space-resident substep in
    solvers.spectral_plan — symbol tables are hash-consed per
    ``(shape, dx, dtype)`` so regrids/solver re-construction stop
    recomputing them; ``spectral_dtype="bf16"`` opts into the
    mixed-precision transform path (bf16/split-real operands, f32
    twiddle/accumulation); ``filter_sym`` applies a body-force spectral
    filter inside the same transform pair.

    Returns ``(u_new, p_inc)`` with
    ``u_new = P (alpha + beta lap)^{-1} rhs`` (divergence-free to
    roundoff at full precision) and ``p_inc = (a + b lap) phi0`` for
    ``pinc_coeffs = (a, b)``, ``phi0 = lap^{-1} div u_star``."""
    from ibamr_tpu.solvers import spectral_plan

    plan = spectral_plan.get_plan(rhs[0].shape, dx, rhs[0].dtype)
    return plan.substep(rhs, alpha, beta, pinc_coeffs,
                        spectral_dtype=spectral_dtype,
                        filter_sym=filter_sym)


def project_divergence_free(u: Vel, dx: Sequence[float],
                            q=None) -> Tuple[Vel, jnp.ndarray]:
    """Exact discrete Leray projection: phi = lap^{-1}(div u - q);
    u_proj = u - grad(phi). Returns (u_proj, phi). div(u_proj) == q (0
    when q is None) to machine precision because the FFT inverse matches
    the stencils.

    ``q`` is an optional cell-centered divergence source (internal fluid
    sources/sinks, the IBStandardSourceGen analog P14). A net (mean)
    source has no periodic solution; the Poisson solve discards the k=0
    mode, which IS the compatibility projection the reference enforces
    by balancing sources against sinks."""
    from ibamr_tpu.ops import stencils

    div = stencils.divergence(u, dx)
    if q is not None:
        div = div - q
    phi = solve_poisson_periodic(div, dx)
    g = stencils.gradient(phi, dx)
    return tuple(c - gc for c, gc in zip(u, g)), phi
