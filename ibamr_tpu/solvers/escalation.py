"""Solver non-convergence surfacing + escalation (PR 3 tentpole 2).

Every Krylov solve in the framework returns a ``SolveResult`` with
``iters``/``resnorm``/``converged`` — and until this PR every
integrator caller DISCARDED them: a Stokes solve that stagnated at
resnorm 1e-2 fed its garbage update straight into the next timestep,
and the first visible symptom was a NaN chunks later. This module is
the production answer:

- :func:`record_solve_stats` threads a solve's stats onto its owning
  solver object (``last_solve_stats``) so ``metrics_fn``/bench can log
  them WITHOUT re-running the solve. Eager solves record directly;
  traced solves record through ``jax.debug.callback`` only when the
  owner opted in (``record_stats=True``) — the default adds nothing to
  jitted/sharded paths.
- :func:`escalate_solve` walks a DECLARED fallback chain, mirroring
  PR 2's ``ENGINE_FALLBACKS`` shape: each level names a cheap recipe
  (more FGMRES restarts, a longer Krylov basis, a more accurate inner
  preconditioner — the "tighter inner tol" knob) and the walk stops at
  the first level that converges. Level 0 converging returns its
  result untouched (bitwise the plain solve). Any walk past level 0
  lands a structured ``solver_escalation``/``solver_breakdown``
  incident; an exhausted chain raises :class:`SolverBreakdown`, which
  subclasses ``SimulationDiverged`` so the PR-2 supervisor treats it
  exactly like a divergence (rollback + dt backoff + retry).

Escalation is a HOST-side loop (each attempt re-traces eagerly with
its own static solver geometry), so it lives at the driver/setup level
— inside a jitted step the stats surface via the callback path and the
driver escalates between chunks.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Sequence

from ibamr_tpu.utils.hierarchy_driver import SimulationDiverged


class SolverBreakdown(SimulationDiverged):
    """A solve escalated through its whole declared chain and still did
    not converge. Subclasses :class:`SimulationDiverged` so the
    supervisor's rollback-and-retry fires unchanged (a breakdown at
    large dt is routinely cured by the dt backoff)."""

    kind = "solver_breakdown"

    def __init__(self, context: str, attempts, step: Optional[int] = None):
        self.context = context
        self.attempts = list(attempts)
        self.step = -1 if step is None else step
        self.bad_leaves: list = []
        last = self.attempts[-1] if self.attempts else {}
        RuntimeError.__init__(
            self,
            f"solver breakdown in {context!r}: escalation chain "
            f"exhausted after {len(self.attempts)} attempts "
            f"(last level {last.get('level')!r}, resnorm "
            f"{last.get('resnorm')})")

    def incident_payload(self) -> dict:
        return {"context": self.context, "attempts": self.attempts}


# ---------------------------------------------------------------------------
# stats surfacing
# ---------------------------------------------------------------------------

def _is_tracer(x) -> bool:
    import jax

    return isinstance(x, jax.core.Tracer)


def solve_stats_dict(sol, solver: str = "", level: str = "") -> dict:
    """Host-side dict from an (already concrete) SolveResult-like."""
    rec = {"iters": int(sol.iters), "resnorm": float(sol.resnorm),
           "converged": bool(sol.converged)}
    if solver:
        rec["solver"] = solver
    if level:
        rec["level"] = level
    return rec


def record_solve_stats(sink, sol, solver: str = "",
                       use_callback: bool = False,
                       mirrors: Sequence = ()) -> None:
    """Store ``{iters, resnorm, converged, solver}`` as
    ``sink.last_solve_stats`` (and on every object in ``mirrors``).

    Eager values are stored synchronously. Traced values (the solve is
    running inside jit) are recorded through ``jax.debug.callback``
    when ``use_callback`` is set — fired per execution, host-ordered,
    no added device sync — and silently skipped otherwise, so jitted
    and SPMD-sharded paths pay nothing unless the owner opted in.
    """
    sinks = (sink,) + tuple(m for m in mirrors if m is not None)
    if not any(_is_tracer(v) for v in (sol.iters, sol.resnorm,
                                       sol.converged)):
        rec = solve_stats_dict(sol, solver)
        for s in sinks:
            s.last_solve_stats = rec
        return
    if not use_callback:
        return
    import jax

    def _tap(iters, resnorm, converged):
        rec = {"iters": int(iters), "resnorm": float(resnorm),
               "converged": bool(converged)}
        if solver:
            rec["solver"] = solver
        for s in sinks:
            s.last_solve_stats = rec

    jax.debug.callback(_tap, sol.iters, sol.resnorm, sol.converged)


# ---------------------------------------------------------------------------
# the declared escalation chain (the ENGINE_FALLBACKS shape)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class EscalationLevel:
    """One link of a solve escalation chain. The scales multiply the
    base solve's geometry; ``inner_scale`` deepens whatever inner
    accuracy knob the owner exposes (preconditioner sweeps / inner
    tolerance — the attempt_fn decides what it means)."""

    name: str
    restarts_scale: int = 1
    m_scale: int = 1
    maxiter_scale: int = 1
    inner_scale: int = 1


ESCALATION_LEVELS: Dict[str, EscalationLevel] = {
    "base": EscalationLevel("base"),
    "restarts_x4": EscalationLevel("restarts_x4", restarts_scale=4),
    "deep_x4_inner_x2": EscalationLevel(
        "deep_x4_inner_x2", restarts_scale=4, m_scale=2, inner_scale=2),
}

# name -> next link (None terminates), mirroring ENGINE_FALLBACKS: one
# flat registry, chains derived by walking it, no cycles by inspection
ESCALATION_FALLBACKS: Dict[str, Optional[str]] = {
    "base": "restarts_x4",
    "restarts_x4": "deep_x4_inner_x2",
    "deep_x4_inner_x2": None,
}


def escalation_chain(name: str = "base"):
    """The escalation order starting AT ``name`` (inclusive). Raises
    KeyError for unknown level names."""
    cur: Optional[str] = name
    if cur not in ESCALATION_LEVELS:
        raise KeyError(f"unknown escalation level {name!r}; known: "
                       f"{sorted(ESCALATION_LEVELS)}")
    chain = []
    while cur is not None:
        chain.append(ESCALATION_LEVELS[cur])
        cur = ESCALATION_FALLBACKS[cur]
    return chain


# ---------------------------------------------------------------------------
# precision escalation (PR 5): the spectral_dtype chain + f64 shadow audit
# ---------------------------------------------------------------------------

class PrecisionDrift(SimulationDiverged):
    """The strided f64 shadow audit found the mixed-precision fluid
    substep drifting past its pinned bound: the state is finite and the
    solver converged, but the fast path is lying. Subclasses
    :class:`SimulationDiverged` so the supervisor's rollback machinery
    fires — but the supervisor retries at the NEXT precision level
    (``PRECISION_FALLBACKS``) instead of backing dt off, because the
    cure is precision, not stability."""

    kind = "precision_drift"

    def __init__(self, step: int, *, drift: float, bound: float,
                 spectral_dtype: str, div_drift: Optional[float] = None):
        self.step = step
        self.drift = float(drift)
        self.bound = float(bound)
        self.spectral_dtype = spectral_dtype
        self.div_drift = None if div_drift is None else float(div_drift)
        self.bad_leaves: list = []      # nothing is non-finite
        RuntimeError.__init__(
            self,
            f"precision drift by step {step}: f64 shadow audit measured "
            f"relative substep drift {self.drift:.4g} > bound "
            f"{self.bound:.4g} at spectral_dtype={spectral_dtype!r} — "
            f"the mixed-precision fast path is out of tolerance")

    def incident_payload(self) -> dict:
        return {"drift": self.drift, "bound": self.bound,
                "spectral_dtype": self.spectral_dtype,
                "div_drift": self.div_drift}


# level name -> next link (None terminates): the ENGINE_FALLBACKS /
# ESCALATION_FALLBACKS shape, applied to the spectral_dtype knob. The
# names are exactly the canonical_spectral_dtype aliases, so a level
# name can be assigned straight onto ``integ.spectral_dtype``.
PRECISION_LEVELS = ("bf16", "f32", "f64")
PRECISION_FALLBACKS: Dict[str, Optional[str]] = {
    "bf16": "f32",
    "f32": "f64",
    "f64": None,
}


def precision_level_name(spectral_dtype) -> str:
    """Map a canonical ``spectral_dtype`` knob value (None / jnp.bfloat16
    / jnp.float64 or their string aliases) to its PRECISION_LEVELS name."""
    import jax.numpy as jnp

    from ibamr_tpu.solvers.spectral_plan import canonical_spectral_dtype

    sd = canonical_spectral_dtype(spectral_dtype)
    if sd is None:
        return "f32"
    if sd is jnp.bfloat16:
        return "bf16"
    return "f64"


def precision_chain(name: str = "bf16"):
    """The precision escalation order starting AT ``name`` (inclusive)."""
    if name not in PRECISION_FALLBACKS:
        raise KeyError(f"unknown precision level {name!r}; known: "
                       f"{list(PRECISION_LEVELS)}")
    chain, cur = [], name
    while cur is not None:
        chain.append(cur)
        cur = PRECISION_FALLBACKS[cur]
    return chain


class ShadowAuditor:
    """Strided f64 shadow audit of the fused spectral fluid substep.

    Every ``every`` chunks, :meth:`maybe_audit` re-runs ONE
    representative Stokes substep from the current velocity twice —
    once at the integrator's configured ``spectral_dtype`` and once at
    f64 via the existing :class:`~ibamr_tpu.solvers.spectral_plan
    .SpectralPlan` — and compares the relative velocity drift (and the
    post-projection divergence gap) against pinned bounds. A breach
    raises :class:`PrecisionDrift`, which the supervisor answers with a
    rollback and a retry at the next ``PRECISION_FALLBACKS`` level.

    The audit is strided and OUTSIDE the jitted chunk (one extra
    substep per ``every`` chunks, amortized to noise) so the hot path's
    trace and transfer budget are untouched — pinned by the driver's
    ``trace_counts`` in tests.

    Default ``bound=0.02``: an order of magnitude above the pinned
    natural bf16 substep drift (~3e-3 vs the f64 oracle,
    tests/test_spectral_plan.py), so only a genuinely out-of-tolerance
    fast path trips it.
    """

    def __init__(self, every: int = 8, bound: float = 0.02,
                 div_bound: Optional[float] = None):
        if every < 1:
            raise ValueError("ShadowAuditor.every must be >= 1")
        self.every = every
        self.bound = float(bound)
        self.div_bound = None if div_bound is None else float(div_bound)
        self.chunks_seen = 0
        self.audits = 0
        self.history: list = []
        self.last: Optional[dict] = None

    def params(self) -> dict:
        """JSON-safe audit configuration for the flight-recorder
        fingerprint (what tools/replay.py re-arms the audit from)."""
        return {"every": self.every, "bound": self.bound,
                "div_bound": self.div_bound}

    @staticmethod
    def _fluid_parts(integ, state):
        """(ins-like integrator, ins-like state) — unwraps one IB layer."""
        ins = getattr(integ, "ins", None)
        if ins is not None and hasattr(state, "ins"):
            return ins, state.ins
        return integ, state

    def maybe_audit(self, integ, state, dt, step: int):
        """Called by the driver once per chunk; audits every ``every``-th
        call. Returns the audit record (or None off-cadence)."""
        self.chunks_seen += 1
        if self.chunks_seen % self.every:
            return None
        return self.audit(integ, state, dt, step=step)

    def audit(self, integ, state, dt, step: int):
        """One shadow audit; raises :class:`PrecisionDrift` on breach."""
        import jax.numpy as jnp
        import numpy as np

        from ibamr_tpu.ops import stencils
        from ibamr_tpu.solvers.spectral_plan import get_plan

        fluid, fstate = self._fluid_parts(integ, state)
        sdtype = getattr(fluid, "spectral_dtype", None)
        grid = fluid.grid
        rho = float(getattr(fluid, "rho", 1.0))
        mu = float(getattr(fluid, "mu", 0.0))
        u = fstate.u
        # representative single Stokes substep: backward-Euler viscous
        # solve + Leray projection of rho/dt * u — the exact algebra the
        # fused fast path runs each half-step, fed the live velocity
        alpha = rho / float(dt)
        beta = -0.5 * mu
        rhs = tuple((c * alpha) for c in u)
        plan = get_plan(rhs[0].shape, grid.dx, rhs[0].dtype)
        fast_u, _ = plan.substep(rhs, alpha, beta, (alpha, beta),
                                 spectral_dtype=sdtype)
        plan64 = get_plan(rhs[0].shape, grid.dx, jnp.float64)
        ref_u, _ = plan64.substep(
            tuple(c.astype(plan64.rdtype) for c in rhs),
            alpha, beta, (alpha, beta), spectral_dtype=None)
        scale = max(float(jnp.max(jnp.abs(c))) for c in ref_u)
        scale = max(scale, 1e-30)
        drift = max(
            float(jnp.max(jnp.abs(f.astype(plan64.rdtype)
                                  - r.astype(plan64.rdtype))))
            for f, r in zip(fast_u, ref_u)) / scale
        div_fast = float(jnp.max(jnp.abs(
            stencils.divergence(fast_u, grid.dx))))
        div_ref = float(jnp.max(jnp.abs(
            stencils.divergence(ref_u, grid.dx))))
        div_drift = abs(div_fast - div_ref) / max(scale, 1e-30)
        self.audits += 1
        level = precision_level_name(sdtype)
        rec = {"step": int(step), "spectral_dtype": level,
               "drift": drift, "bound": self.bound,
               "div_drift": div_drift, "div_bound": self.div_bound}
        self.last = rec
        self.history.append(rec)
        breached = (np.isfinite(drift) and drift > self.bound) or \
            (self.div_bound is not None and div_drift > self.div_bound)
        if breached:
            raise PrecisionDrift(step, drift=drift, bound=self.bound,
                                 spectral_dtype=level,
                                 div_drift=div_drift)
        return rec


def escalate_solve(attempt_fn: Callable, *, context: str = "solve",
                   chain=None, on_incident: Optional[Callable] = None,
                   step: Optional[int] = None):
    """Walk the chain until an attempt converges.

    ``attempt_fn(level: EscalationLevel, attempt: int) -> SolveResult``
    runs one EAGER solve at that level's geometry. The first converged
    attempt wins; level 0 converging returns its result with no extra
    work (bitwise the plain solve). Escalations past level 0 are
    reported to ``on_incident`` as one structured record::

        {"event": "solver_escalation"|"solver_breakdown",
         "kind": "solver_breakdown", "context": ...,
         "recovered": bool, "level": <winning level or None>,
         "attempts": [{level, iters, resnorm, converged}, ...]}

    and an exhausted chain raises :class:`SolverBreakdown` carrying the
    same attempts list.
    """
    chain = escalation_chain() if chain is None else list(chain)
    if not chain:
        raise ValueError("escalation chain must have at least one level")
    attempts = []
    for i, level in enumerate(chain):
        sol = attempt_fn(level, i)
        rec = solve_stats_dict(sol, level=level.name)
        attempts.append(rec)
        if rec["converged"]:
            if i > 0 and on_incident is not None:
                on_incident({"event": "solver_escalation",
                             "kind": "solver_breakdown",
                             "context": context, "recovered": True,
                             "level": level.name, "attempts": attempts})
            return sol
    if on_incident is not None:
        on_incident({"event": "solver_breakdown",
                     "kind": "solver_breakdown", "context": context,
                     "recovered": False, "level": None,
                     "attempts": attempts})
    raise SolverBreakdown(context, attempts, step=step)
