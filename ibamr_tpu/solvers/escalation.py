"""Solver non-convergence surfacing + escalation (PR 3 tentpole 2).

Every Krylov solve in the framework returns a ``SolveResult`` with
``iters``/``resnorm``/``converged`` — and until this PR every
integrator caller DISCARDED them: a Stokes solve that stagnated at
resnorm 1e-2 fed its garbage update straight into the next timestep,
and the first visible symptom was a NaN chunks later. This module is
the production answer:

- :func:`record_solve_stats` threads a solve's stats onto its owning
  solver object (``last_solve_stats``) so ``metrics_fn``/bench can log
  them WITHOUT re-running the solve. Eager solves record directly;
  traced solves record through ``jax.debug.callback`` only when the
  owner opted in (``record_stats=True``) — the default adds nothing to
  jitted/sharded paths.
- :func:`escalate_solve` walks a DECLARED fallback chain, mirroring
  PR 2's ``ENGINE_FALLBACKS`` shape: each level names a cheap recipe
  (more FGMRES restarts, a longer Krylov basis, a more accurate inner
  preconditioner — the "tighter inner tol" knob) and the walk stops at
  the first level that converges. Level 0 converging returns its
  result untouched (bitwise the plain solve). Any walk past level 0
  lands a structured ``solver_escalation``/``solver_breakdown``
  incident; an exhausted chain raises :class:`SolverBreakdown`, which
  subclasses ``SimulationDiverged`` so the PR-2 supervisor treats it
  exactly like a divergence (rollback + dt backoff + retry).

Escalation is a HOST-side loop (each attempt re-traces eagerly with
its own static solver geometry), so it lives at the driver/setup level
— inside a jitted step the stats surface via the callback path and the
driver escalates between chunks.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Sequence

from ibamr_tpu.utils.hierarchy_driver import SimulationDiverged


class SolverBreakdown(SimulationDiverged):
    """A solve escalated through its whole declared chain and still did
    not converge. Subclasses :class:`SimulationDiverged` so the
    supervisor's rollback-and-retry fires unchanged (a breakdown at
    large dt is routinely cured by the dt backoff)."""

    kind = "solver_breakdown"

    def __init__(self, context: str, attempts, step: Optional[int] = None):
        self.context = context
        self.attempts = list(attempts)
        self.step = -1 if step is None else step
        self.bad_leaves: list = []
        last = self.attempts[-1] if self.attempts else {}
        RuntimeError.__init__(
            self,
            f"solver breakdown in {context!r}: escalation chain "
            f"exhausted after {len(self.attempts)} attempts "
            f"(last level {last.get('level')!r}, resnorm "
            f"{last.get('resnorm')})")

    def incident_payload(self) -> dict:
        return {"context": self.context, "attempts": self.attempts}


# ---------------------------------------------------------------------------
# stats surfacing
# ---------------------------------------------------------------------------

def _is_tracer(x) -> bool:
    import jax

    return isinstance(x, jax.core.Tracer)


def solve_stats_dict(sol, solver: str = "", level: str = "") -> dict:
    """Host-side dict from an (already concrete) SolveResult-like."""
    rec = {"iters": int(sol.iters), "resnorm": float(sol.resnorm),
           "converged": bool(sol.converged)}
    if solver:
        rec["solver"] = solver
    if level:
        rec["level"] = level
    return rec


def record_solve_stats(sink, sol, solver: str = "",
                       use_callback: bool = False,
                       mirrors: Sequence = ()) -> None:
    """Store ``{iters, resnorm, converged, solver}`` as
    ``sink.last_solve_stats`` (and on every object in ``mirrors``).

    Eager values are stored synchronously. Traced values (the solve is
    running inside jit) are recorded through ``jax.debug.callback``
    when ``use_callback`` is set — fired per execution, host-ordered,
    no added device sync — and silently skipped otherwise, so jitted
    and SPMD-sharded paths pay nothing unless the owner opted in.
    """
    sinks = (sink,) + tuple(m for m in mirrors if m is not None)
    if not any(_is_tracer(v) for v in (sol.iters, sol.resnorm,
                                       sol.converged)):
        rec = solve_stats_dict(sol, solver)
        for s in sinks:
            s.last_solve_stats = rec
        return
    if not use_callback:
        return
    import jax

    def _tap(iters, resnorm, converged):
        rec = {"iters": int(iters), "resnorm": float(resnorm),
               "converged": bool(converged)}
        if solver:
            rec["solver"] = solver
        for s in sinks:
            s.last_solve_stats = rec

    jax.debug.callback(_tap, sol.iters, sol.resnorm, sol.converged)


# ---------------------------------------------------------------------------
# the declared escalation chain (the ENGINE_FALLBACKS shape)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class EscalationLevel:
    """One link of a solve escalation chain. The scales multiply the
    base solve's geometry; ``inner_scale`` deepens whatever inner
    accuracy knob the owner exposes (preconditioner sweeps / inner
    tolerance — the attempt_fn decides what it means)."""

    name: str
    restarts_scale: int = 1
    m_scale: int = 1
    maxiter_scale: int = 1
    inner_scale: int = 1


ESCALATION_LEVELS: Dict[str, EscalationLevel] = {
    "base": EscalationLevel("base"),
    "restarts_x4": EscalationLevel("restarts_x4", restarts_scale=4),
    "deep_x4_inner_x2": EscalationLevel(
        "deep_x4_inner_x2", restarts_scale=4, m_scale=2, inner_scale=2),
}

# name -> next link (None terminates), mirroring ENGINE_FALLBACKS: one
# flat registry, chains derived by walking it, no cycles by inspection
ESCALATION_FALLBACKS: Dict[str, Optional[str]] = {
    "base": "restarts_x4",
    "restarts_x4": "deep_x4_inner_x2",
    "deep_x4_inner_x2": None,
}


def escalation_chain(name: str = "base"):
    """The escalation order starting AT ``name`` (inclusive). Raises
    KeyError for unknown level names."""
    cur: Optional[str] = name
    if cur not in ESCALATION_LEVELS:
        raise KeyError(f"unknown escalation level {name!r}; known: "
                       f"{sorted(ESCALATION_LEVELS)}")
    chain = []
    while cur is not None:
        chain.append(ESCALATION_LEVELS[cur])
        cur = ESCALATION_FALLBACKS[cur]
    return chain


def escalate_solve(attempt_fn: Callable, *, context: str = "solve",
                   chain=None, on_incident: Optional[Callable] = None,
                   step: Optional[int] = None):
    """Walk the chain until an attempt converges.

    ``attempt_fn(level: EscalationLevel, attempt: int) -> SolveResult``
    runs one EAGER solve at that level's geometry. The first converged
    attempt wins; level 0 converging returns its result with no extra
    work (bitwise the plain solve). Escalations past level 0 are
    reported to ``on_incident`` as one structured record::

        {"event": "solver_escalation"|"solver_breakdown",
         "kind": "solver_breakdown", "context": ...,
         "recovered": bool, "level": <winning level or None>,
         "attempts": [{level, iters, resnorm, converged}, ...]}

    and an exhausted chain raises :class:`SolverBreakdown` carrying the
    same attempts list.
    """
    chain = escalation_chain() if chain is None else list(chain)
    if not chain:
        raise ValueError("escalation chain must have at least one level")
    attempts = []
    for i, level in enumerate(chain):
        sol = attempt_fn(level, i)
        rec = solve_stats_dict(sol, level=level.name)
        attempts.append(rec)
        if rec["converged"]:
            if i > 0 and on_incident is not None:
                on_incident({"event": "solver_escalation",
                             "kind": "solver_breakdown",
                             "context": context, "recovered": True,
                             "level": level.name, "attempts": attempts})
            return sol
    if on_incident is not None:
        on_incident({"event": "solver_breakdown",
                     "kind": "solver_breakdown", "context": context,
                     "recovered": False, "level": None,
                     "attempts": attempts})
    raise SolverBreakdown(context, attempts, step=step)
