from ibamr_tpu.solvers import fft, krylov

__all__ = ["fft", "krylov", "mobility"]


def __getattr__(name):
    # mobility imports integrators.cib which imports solvers.fft; lazy
    # load keeps the package import acyclic.
    if name == "mobility":
        import importlib
        return importlib.import_module("ibamr_tpu.solvers.mobility")
    raise AttributeError(name)
