from ibamr_tpu.solvers import fft, krylov

__all__ = ["fft", "krylov"]
