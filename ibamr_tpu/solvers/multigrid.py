"""Geometric multigrid for cell-centered Poisson/Helmholtz with general
Robin boundary conditions, plus a two-level FAC composite preconditioner.

Reference parity: the FAC-multigrid + hypre level-solver stack (T8,
SURVEY.md §2.1) — ``FACPreconditioner`` V-cycles over
``CCPoissonPointRelaxationFACOperator`` (red-black Gauss-Seidel
smoothers, Fortran-kernel level relaxation) with hypre PFMG/SMG bottom
solves (``CCPoissonHypreLevelSolver``) — rebuilt the TPU way:

- **smoothing** is two masked Jacobi half-sweeps per red-black pass:
  the full residual stencil is evaluated once per color and the update
  applied through a checkerboard mask, so each sweep is a handful of
  fused elementwise/stencil ops that XLA pipelines through the VPU (no
  sequential point loop — the reference's F77 ``rbgs`` kernels become
  whole-array ops);
- **boundary conditions** enter through the ghost-fill arithmetic of
  :mod:`ibamr_tpu.bc` and an analytically assembled diagonal (the
  ghost-reflection coefficient folds into the boundary-cell diagonal),
  so the same code path serves Dirichlet/Neumann/Robin/periodic — the
  analog of the reference's RobinBcCoefStrategy-aware smoothers;
- **grid transfer** is full-weighting restriction (2^d block mean) and
  BC-aware piecewise-linear prolongation — strided reshapes, no
  indirection;
- the V-cycle recursion is unrolled at trace time (level shapes are
  static), and the outer iteration is a ``lax.while_loop``, so a whole
  ``solve`` compiles into one XLA computation usable inside jit/scan —
  the analog of a PETSc KSP(richardson)+PCMG solve, minus the host
  round-trips.

Variable-coefficient problems (the reference's
``VCSCViscousOperator``-class systems and ``PoissonSpecifications``
with cell data D) are handled by rediscretized coarse operators: the
cell diffusivity is block-mean coarsened per level and the operator
applied in face-flux (conservative) form on every level.
"""

from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.amr import restrict_cc
from ibamr_tpu.bc import (AxisBC, DomainBC, fill_ghosts_cc,
                          ghost_reflect_coeff)

Array = jnp.ndarray


def checkerboard_masks(shape) -> Tuple[Array, Array]:
    """(red, black) boolean checkerboard masks for red-black sweeps."""
    grids = jnp.meshgrid(*[jnp.arange(s) for s in shape], indexing="ij")
    parity = sum(grids) % 2
    return parity == 0, parity == 1


# ---------------------------------------------------------------------------
# BC utilities
# ---------------------------------------------------------------------------

def homogeneous_bc(bc: DomainBC) -> DomainBC:
    """The same BC kinds with zero boundary data — correction equations
    on coarse levels satisfy the homogeneous version of the fine BCs."""
    axes = []
    for ax in bc.axes:
        axes.append(AxisBC(
            dataclasses.replace(ax.lo, value=0.0),
            dataclasses.replace(ax.hi, value=0.0)))
    return DomainBC(axes=tuple(axes))


def _nullspace(bc: DomainBC) -> bool:
    """True when the Poisson operator has the constant nullspace: every
    axis periodic or pure-Neumann on both sides."""
    for ax in bc.axes:
        if ax.periodic:
            continue
        for s in (ax.lo, ax.hi):
            a, b = s.coeffs()
            if a != 0.0:
                return False
    return True


# ---------------------------------------------------------------------------
# Level operator: alpha*Q + div(D grad Q), D face-averaged from cell data
# (D=None means constant-coefficient beta*lap)
# ---------------------------------------------------------------------------

class _Level(NamedTuple):
    """Static per-level discretization data (closed over by the jitted
    solve — all leaves are arrays or hashable)."""
    shape: Tuple[int, ...]
    dx: Tuple[float, ...]
    diag: Array            # operator diagonal incl. BC corrections
    D_face: Optional[Tuple[Array, ...]]  # face diffusivity per axis, or None


def _face_coeffs(D: Array, bc: DomainBC) -> Tuple[Array, ...]:
    """Arithmetic-mean face diffusivities from cell-centered D, one
    array per axis with shape n + e_d (interior + boundary faces).
    Boundary faces use the one-sided cell value (periodic: wrap mean)."""
    out = []
    for d in range(D.ndim):
        if bc.axes[d].periodic:
            Dm = 0.5 * (D + jnp.roll(D, 1, axis=d))       # face i = mean(i-1, i)
            # append the wrap face at the high end so shape = n+1
            lo = [slice(None)] * D.ndim
            lo[d] = slice(0, 1)
            Df = jnp.concatenate([Dm, Dm[tuple(lo)]], axis=d)
        else:
            pad = [(0, 0)] * D.ndim
            pad[d] = (1, 1)
            Dg = jnp.pad(D, pad, mode="edge")
            sl_lo = [slice(None)] * D.ndim
            sl_hi = [slice(None)] * D.ndim
            sl_lo[d] = slice(0, -1)
            sl_hi[d] = slice(1, None)
            Df = 0.5 * (Dg[tuple(sl_lo)] + Dg[tuple(sl_hi)])
        out.append(Df)
    return tuple(out)


def _apply_op(Q: Array, level: _Level, bc: DomainBC, alpha: float,
              beta: float, bdry_data: Optional[dict] = None) -> Array:
    """alpha*Q + beta*div(grad Q)  (constant coefficient), or
    alpha*Q + beta*div(D grad Q) when the level carries face
    coefficients. Conservative face-flux form so coarse operators stay
    symmetric."""
    dim = Q.ndim
    dx = level.dx
    G = fill_ghosts_cc(Q, bc, dx, bdry_data=bdry_data)
    center = tuple(slice(1, -1) for _ in range(dim))
    out = alpha * Q
    for d in range(dim):
        lo = list(center)
        hi = list(center)
        lo[d] = slice(0, -2)
        hi[d] = slice(2, None)
        if level.D_face is None:
            out = out + beta * (G[tuple(lo)] - 2.0 * Q + G[tuple(hi)]) \
                / dx[d] ** 2
        else:
            Df = level.D_face[d]
            sl_lo = [slice(None)] * dim
            sl_hi = [slice(None)] * dim
            sl_lo[d] = slice(0, -1)
            sl_hi[d] = slice(1, None)
            flux_hi = Df[tuple(sl_hi)] * (G[tuple(hi)] - Q) / dx[d]
            flux_lo = Df[tuple(sl_lo)] * (Q - G[tuple(lo)]) / dx[d]
            out = out + beta * (flux_hi - flux_lo) / dx[d]
    return out


def _assemble_diag(shape, bc: DomainBC, dx, alpha: float, beta: float,
                   D_face, dtype) -> Array:
    """Exact operator diagonal including the ghost-reflection
    contribution at boundary cells (the ghost of a boundary cell is a
    multiple c of the cell itself under homogeneous BCs, so c folds
    into that cell's diagonal)."""
    dim = len(shape)
    if D_face is None:
        diag = jnp.full(shape, alpha + beta * sum(-2.0 / h ** 2
                                                  for h in dx),
                        dtype=dtype)
        for d in range(dim):
            ax = bc.axes[d]
            if ax.periodic:
                continue
            for s, side in ((0, ax.lo), (1, ax.hi)):
                c = ghost_reflect_coeff(side, dx[d])
                idx = [slice(None)] * dim
                idx[d] = slice(0, 1) if s == 0 else slice(-1, None)
                diag = diag.at[tuple(idx)].add(beta * c / dx[d] ** 2)
        return diag
    # variable-coefficient: diag = alpha - beta*(D_hi + D_lo)/h^2 per
    # axis, with boundary-face reflection corrections
    diag = jnp.full(shape, alpha, dtype=dtype)
    for d in range(dim):
        Df = D_face[d]
        sl_lo = [slice(None)] * dim
        sl_hi = [slice(None)] * dim
        sl_lo[d] = slice(0, -1)
        sl_hi[d] = slice(1, None)
        diag = diag - beta * (Df[tuple(sl_lo)] + Df[tuple(sl_hi)]) \
            / dx[d] ** 2
        ax = bc.axes[d]
        if ax.periodic:
            continue
        for s, side in ((0, ax.lo), (1, ax.hi)):
            c = ghost_reflect_coeff(side, dx[d])
            idx = [slice(None)] * dim
            idx[d] = slice(0, 1) if s == 0 else slice(-1, None)
            fidx = [slice(None)] * dim
            fidx[d] = slice(0, 1) if s == 0 else slice(-1, None)
            diag = diag.at[tuple(idx)].add(
                beta * c * Df[tuple(fidx)] / dx[d] ** 2)
    return diag


# ---------------------------------------------------------------------------
# Grid transfer
# ---------------------------------------------------------------------------

def restrict_full_weighting(r: Array) -> Array:
    """2^d block mean — the cell-centered full-weighting restriction
    (shared with the AMR coarsen op: amr.restrict_cc)."""
    return restrict_cc(r, ratio=2)


def _axis_ghost_hom(C: Array, axis: int, ax: AxisBC, h: float) -> Array:
    """Pad ONE axis with one ghost layer under homogeneous BCs."""
    lo_idx = [slice(None)] * C.ndim
    hi_idx = [slice(None)] * C.ndim
    if ax.periodic:
        lo_idx[axis] = slice(-1, None)
        hi_idx[axis] = slice(0, 1)
        lo_g, hi_g = C[tuple(lo_idx)], C[tuple(hi_idx)]
    else:
        lo_idx[axis] = slice(0, 1)
        hi_idx[axis] = slice(-1, None)
        lo_g = ghost_reflect_coeff(ax.lo, h) * C[tuple(lo_idx)]
        hi_g = ghost_reflect_coeff(ax.hi, h) * C[tuple(hi_idx)]
    return jnp.concatenate([lo_g, C, hi_g], axis=axis)


def prolong_linear(C: Array, bc: DomainBC, dx_coarse) -> Array:
    """BC-aware piecewise-linear prolongation (cell-centered, ratio 2):
    child values are the 3/4-1/4 axis-separable interpolants of the
    parent and its neighbor toward the child, with homogeneous-BC ghosts
    beyond walls (correction quantities vanish/reflect there)."""
    out = C
    for d in range(C.ndim):
        G = _axis_ghost_hom(out, d, bc.axes[d], dx_coarse[d])
        sl_c = [slice(None)] * out.ndim
        sl_m = [slice(None)] * out.ndim
        sl_p = [slice(None)] * out.ndim
        sl_c[d] = slice(1, -1)
        sl_m[d] = slice(0, -2)
        sl_p[d] = slice(2, None)
        left = 0.75 * G[tuple(sl_c)] + 0.25 * G[tuple(sl_m)]
        right = 0.75 * G[tuple(sl_c)] + 0.25 * G[tuple(sl_p)]
        stacked = jnp.stack([left, right], axis=d + 1)
        new_shape = list(out.shape)
        new_shape[d] = out.shape[d] * 2
        out = stacked.reshape(new_shape)
    return out


# ---------------------------------------------------------------------------
# The solver
# ---------------------------------------------------------------------------

class MGSolveResult(NamedTuple):
    x: Array
    iters: jnp.ndarray
    resnorm: jnp.ndarray
    converged: jnp.ndarray


class PoissonMultigrid:
    """Geometric-multigrid solver for
    ``alpha*Q + beta*lap(Q) = f``   (D=None), or
    ``alpha*Q + div(D grad Q) = f`` (cell-centered D),
    under the full Robin BC menu of :mod:`ibamr_tpu.bc`.

    Setup is static (level shapes/diagonals precomputed); ``solve`` is
    fully traceable. Matches the role of the reference's
    ``CCPoissonSolverManager`` default (FAC-preconditioned Krylov with
    point-relaxation smoothers) — SURVEY.md §2.1 T8.
    """

    def __init__(self, shape: Sequence[int], bc: DomainBC,
                 dx: Sequence[float], alpha: float = 0.0,
                 beta: float = 1.0, D: Optional[Array] = None,
                 nu_pre: int = 2, nu_post: int = 2,
                 nu_coarse: int = 40, min_cells: int = 4,
                 dtype=jnp.float64):
        self.bc = bc
        self.bc_hom = homogeneous_bc(bc)
        # respect the session's enabled precision (f32 on TPU, f64 in
        # the x64 test env) without requested-dtype truncation warnings
        dtype = jax.dtypes.canonicalize_dtype(dtype)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.nu_pre = nu_pre
        self.nu_post = nu_post
        self.nu_coarse = nu_coarse
        self.has_nullspace = (alpha == 0.0) and _nullspace(bc)

        shape = tuple(int(v) for v in shape)
        dx = tuple(float(v) for v in dx)
        self.levels: List[_Level] = []
        Dl = D
        while True:
            D_face = None if Dl is None else _face_coeffs(Dl, bc)
            diag = _assemble_diag(shape, bc, dx, self.alpha, self.beta,
                                  D_face, dtype)
            self.levels.append(_Level(shape=shape, dx=dx, diag=diag,
                                      D_face=D_face))
            if any(s % 2 != 0 or s // 2 < min_cells for s in shape):
                break
            shape = tuple(s // 2 for s in shape)
            dx = tuple(h * 2.0 for h in dx)
            if Dl is not None:
                Dl = restrict_full_weighting(Dl)
        # red-black checkerboard masks per level
        self._masks = [checkerboard_masks(lv.shape)
                       for lv in self.levels]

    # -- level pieces -------------------------------------------------------
    def _op(self, Q, li: int, bdry_data=None, hom=True):
        bc = self.bc_hom if hom else self.bc
        return _apply_op(Q, self.levels[li], bc, self.alpha, self.beta,
                         bdry_data=bdry_data)

    def _smooth(self, Q, f, li: int, sweeps: int,
                reverse: bool = False):
        """Red-black relaxation; ``reverse`` sweeps black-then-red.
        Post-smoothing in reversed color order makes the V-cycle a
        SYMMETRIC operator — required when the cycle preconditions CG
        (a nonsymmetric M can trip CG's rz>0 breakdown guard)."""
        red, black = self._masks[li]
        diag = self.levels[li].diag
        order = (black, red) if reverse else (red, black)

        def sweep(_, Q):
            for mask in order:
                r = f - self._op(Q, li)
                Q = Q + jnp.where(mask, r / diag, 0.0)
            return Q

        return jax.lax.fori_loop(0, sweeps, sweep, Q)

    def _vcycle(self, Q, f, li: int):
        if li == len(self.levels) - 1:
            # palindromic ordering keeps the bottom solve symmetric too
            half = self.nu_coarse // 2
            Q = self._smooth(Q, f, li, half)
            return self._smooth(Q, f, li, self.nu_coarse - half,
                                reverse=True)
        Q = self._smooth(Q, f, li, self.nu_pre)
        r = f - self._op(Q, li)
        rc = restrict_full_weighting(r)
        ec = self._vcycle(jnp.zeros_like(rc), rc, li + 1)
        Q = Q + prolong_linear(ec, self.bc_hom,
                               self.levels[li + 1].dx)
        return self._smooth(Q, f, li, self.nu_post, reverse=True)

    # -- public API ---------------------------------------------------------
    def vcycle(self, Q: Array, f: Array) -> Array:
        """One homogeneous-BC V-cycle (use as a preconditioner)."""
        return self._vcycle(Q, f, 0)

    def solve(self, f: Array, x0: Optional[Array] = None,
              tol: float = 1e-8, maxiter: int = 50,
              bdry_data: Optional[dict] = None) -> MGSolveResult:
        """V-cycle iteration to ``|r| <= tol*|f|``. Inhomogeneous
        boundary data is folded into the right-hand side once (the ghost
        fill is affine in Q: op_inhom(Q) = op_hom(Q) + bc_terms), so the
        cycle itself runs homogeneous."""
        f = jnp.asarray(f)
        if x0 is None:
            x0 = jnp.zeros_like(f)
        # fold inhomogeneous boundary terms into the rhs:
        zero = jnp.zeros_like(f)
        bc_terms = _apply_op(zero, self.levels[0], self.bc, self.alpha,
                             self.beta, bdry_data=bdry_data)
        f_eff = f - bc_terms
        if self.has_nullspace:
            f_eff = f_eff - jnp.mean(f_eff)
        fnorm = jnp.linalg.norm(f_eff.ravel())
        stop = tol * jnp.maximum(fnorm, 1e-30)

        def cond(carry):
            Q, rn, it = carry
            return jnp.logical_and(it < maxiter, rn > stop)

        def body(carry):
            Q, _, it = carry
            Q = self._vcycle(Q, f_eff, 0)
            if self.has_nullspace:
                Q = Q - jnp.mean(Q)
            rn = jnp.linalg.norm((f_eff - self._op(Q, 0)).ravel())
            return Q, rn, it + 1

        rn0 = jnp.linalg.norm((f_eff - self._op(x0, 0)).ravel())
        Q, rn, it = jax.lax.while_loop(
            cond, body, (x0, rn0, jnp.asarray(0)))
        return MGSolveResult(x=Q, iters=it, resnorm=rn,
                             converged=rn <= stop)
