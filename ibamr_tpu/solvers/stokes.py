"""General staggered-Stokes saddle-point solver: coupled (u, p) Krylov
solve with inflow / no-slip / open (traction-free) boundaries.

Reference parity: the full Krylov half of the staggered Stokes machinery
(P3, SURVEY.md §2.2) — ``StaggeredStokesOperator`` (the coupled
[A G; -D 0] block operator), ``StaggeredStokesSolver`` (FGMRES on the
coupled system), ``StaggeredStokesProjectionPreconditioner`` (velocity
sub-solve + pressure Schur proxy), ``StaggeredStokesPhysicalBoundaryHelper``
/ ``INSProjectionBcCoef`` (normal-traction "open" boundaries and
prescribed-velocity inflows). The FFT/fast-diagonalization paths
(:mod:`ibamr_tpu.solvers.fft`, ``ins_walls``) cover periodic and
homogeneous no-slip domains exactly; THIS module covers everything they
cannot: inhomogeneous normal velocities (inflow) and open outflow
boundaries, on one jit-compiled coupled solve.

TPU-first design
----------------
- Face-complete MAC layout: on a non-periodic axis, that axis's normal
  component stores ALL faces (shape n+1 along its own axis) so boundary
  faces are explicit DOFs: prescribed faces are identity rows, open
  faces are live unknowns with one-sided momentum rows. No indirection:
  rows are selected by static boolean masks, so XLA fuses the row
  dispatch into the stencils.
- The operator is linear-homogeneous (all boundary DATA lives in the
  right-hand side via ghost lifting), so one FGMRES instance serves any
  boundary data — and the preconditioner is automatically consistent.
- Preconditioner: block lower-triangular projection preconditioner —
  ``nu`` red-black sweeps approximate A^{-1} (the velocity Helmholtz
  sub-solve), then a Cahouet–Chabard Schur proxy
  ``S^{-1} ~ alpha * L_p^{-1} - mu * I`` (S = D A^{-1} G is
  negative-definite in both limits) with the pressure Poisson solved by
  one geometric-multigrid V-cycle (Neumann at walls/inflow, Dirichlet
  at open boundaries) — the reference's projection preconditioner
  (Griffith JCP 2009) with hypre level solves replaced by
  :class:`~ibamr_tpu.solvers.multigrid.PoissonMultigrid`.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.bc import (AxisBC, DomainBC, SideBC, DIRICHLET, NEUMANN,
                          periodic_axis)
from ibamr_tpu.solvers.escalation import escalate_solve, record_solve_stats
from ibamr_tpu.solvers.krylov import fgmres
from ibamr_tpu.solvers.multigrid import (PoissonMultigrid,
                                         checkerboard_masks)

Array = jnp.ndarray
Vel = Tuple[Array, ...]

WALL = "wall"        # no-slip / prescribed velocity (value may be 0)
INFLOW = "inflow"    # synonym of wall with nonzero normal data
OPEN = "open"        # traction-free outflow: p = 0, du/dn = 0


@dataclasses.dataclass(frozen=True)
class VelocitySide:
    """One domain side's velocity condition. ``kind``: wall/inflow
    (prescribed velocity — data supplied at solve time) or open."""
    kind: str = WALL

    def __post_init__(self):
        if self.kind not in (WALL, INFLOW, OPEN):
            raise ValueError(f"unknown velocity BC kind {self.kind!r}")

    @property
    def prescribed(self) -> bool:
        return self.kind in (WALL, INFLOW)


@dataclasses.dataclass(frozen=True)
class StokesBC:
    """Per-axis (lo, hi) velocity sides; ``None`` marks a periodic axis."""
    axes: Tuple[Optional[Tuple[VelocitySide, VelocitySide]], ...]

    @property
    def dim(self) -> int:
        return len(self.axes)

    def periodic(self, e: int) -> bool:
        return self.axes[e] is None

    def side(self, e: int, s: int) -> VelocitySide:
        ax = self.axes[e]
        assert ax is not None
        return ax[s]


def channel_bc(dim: int, flow_axis: int = 0) -> StokesBC:
    """Inflow at flow-axis lo, open outflow at hi, no-slip otherwise."""
    axes = []
    for e in range(dim):
        if e == flow_axis:
            axes.append((VelocitySide(INFLOW), VelocitySide(OPEN)))
        else:
            axes.append((VelocitySide(WALL), VelocitySide(WALL)))
    return StokesBC(axes=tuple(axes))


def cavity_bc(dim: int) -> StokesBC:
    return StokesBC(axes=tuple(
        (VelocitySide(WALL), VelocitySide(WALL)) for _ in range(dim)))


class StokesSolveResult(NamedTuple):
    u: Vel
    p: Array
    iters: jnp.ndarray
    resnorm: jnp.ndarray
    converged: jnp.ndarray


class StaggeredStokesSolver:
    """Coupled solve of

        alpha*u - mu*lap(u) + grad(p) = f_u   (momentum, interior+open faces)
        u = data                              (prescribed boundary faces)
        -div(u) = f_p                         (continuity, every cell)

    on the face-complete MAC layout (component d: shape n + e_d on its
    own non-periodic axis). ``bdry`` supplies the boundary data at solve
    time: {(d, e, side): array|scalar} — component d's value on the
    (e, side) boundary (normal data for e == d, tangential for e != d).
    """

    def __init__(self, n: Sequence[int], dx: Sequence[float],
                 bc: StokesBC, alpha: float, mu: float,
                 nu_sweeps: int = 4, tol: float = 1e-8, m: int = 40,
                 restarts: int = 12, dtype=jnp.float64,
                 record_stats: bool = False):
        self.n = tuple(int(v) for v in n)
        self.dx = tuple(float(v) for v in dx)
        self.bc = bc
        self.alpha = float(alpha)
        self.mu = float(mu)
        self.nu_sweeps = int(nu_sweeps)
        self.tol = float(tol)
        self.m = int(m)
        self.restarts = int(restarts)
        # per-solve convergence surfacing: eager solves always record;
        # record_stats=True additionally taps jitted solves through
        # jax.debug.callback (off by default — sharded paths pay nothing)
        self.record_stats = bool(record_stats)
        self.last_solve_stats: Optional[dict] = None
        dim = len(self.n)
        assert bc.dim == dim
        dtype = jax.dtypes.canonicalize_dtype(dtype)
        self.dtype = dtype

        self.has_open = any(
            not bc.periodic(e) and not bc.side(e, s).prescribed
            for e in range(dim) for s in (0, 1))
        # pressure nullspace: constant p when no open boundary anchors it
        self.p_nullspace = not self.has_open

        # component shapes (face-complete on own non-periodic axis)
        self.shapes = []
        for d in range(dim):
            self.shapes.append(tuple(
                self.n[e] + (1 if (e == d and not bc.periodic(e)) else 0)
                for e in range(dim)))

        # prescribed-face masks + operator diagonals per component
        self._masks = []
        self._diags = []
        for d in range(dim):
            mask = np.zeros(self.shapes[d], dtype=bool)
            if not bc.periodic(d):
                if bc.side(d, 0).prescribed:
                    mask[tuple(slice(0, 1) if e == d else slice(None)
                               for e in range(dim))] = True
                if bc.side(d, 1).prescribed:
                    mask[tuple(slice(-1, None) if e == d else slice(None)
                               for e in range(dim))] = True
            self._masks.append(jnp.asarray(mask))
            self._diags.append(self._assemble_diag(d))

        # red-black parity masks per component
        self._rb = [checkerboard_masks(self.shapes[d])
                    for d in range(dim)]

        # pressure Poisson preconditioner: Neumann at prescribed sides,
        # Dirichlet at open sides, periodic elsewhere
        p_axes = []
        for e in range(dim):
            if bc.periodic(e):
                p_axes.append(periodic_axis())
            else:
                sides = []
                for s in (0, 1):
                    if bc.side(e, s).prescribed:
                        sides.append(SideBC(NEUMANN))
                    else:
                        sides.append(SideBC(DIRICHLET))
                p_axes.append(AxisBC(sides[0], sides[1]))
        self.p_bc = DomainBC(axes=tuple(p_axes))
        self.p_mg = PoissonMultigrid(self.n, self.p_bc, self.dx,
                                     dtype=dtype)

        # all-periodic collapse: the saddle operator is exactly diagonal
        # in k-space, so one batched spectral pass replaces the whole
        # FGMRES + multigrid stack (SURVEY.md §3.3 taken to the coupled
        # system). The plan is hash-consed per (n, dx, dtype) in
        # solvers.spectral_plan; set ``self.spectral = None`` to force
        # the Krylov path (e.g. for cross-validation).
        self.spectral = None
        if all(bc.periodic(e) for e in range(dim)):
            from ibamr_tpu.solvers import spectral_plan
            self.spectral = spectral_plan.get_plan(self.n, self.dx,
                                                   dtype)

    # ------------------------------------------------------------------
    # homogeneous linear operator pieces
    # ------------------------------------------------------------------
    def _ghost_pad(self, c: Array, d: int) -> Array:
        """Extend component d by one ghost layer per axis under the
        HOMOGENEOUS BCs (data lives in the rhs):
        - own axis (e == d), non-periodic: boundary faces are DOFs; pad
          edge-mode so open ends see du/dn = 0 and prescribed ends see a
          value never used (identity rows).
        - tangential wall/inflow: odd reflection (ghost = -interior).
        - tangential open: even reflection (ghost = interior).
        - periodic: wrap.
        """
        out = c
        for e in range(c.ndim):
            lo_idx = [slice(None)] * out.ndim
            hi_idx = [slice(None)] * out.ndim
            if self.bc.periodic(e):
                lo_idx[e] = slice(-1, None)
                hi_idx[e] = slice(0, 1)
                lo_g, hi_g = out[tuple(lo_idx)], out[tuple(hi_idx)]
            elif e == d:
                lo_idx[e] = slice(0, 1)
                hi_idx[e] = slice(-1, None)
                lo_g, hi_g = out[tuple(lo_idx)], out[tuple(hi_idx)]
            else:
                lo_idx[e] = slice(0, 1)
                hi_idx[e] = slice(-1, None)
                s_lo = -1.0 if self.bc.side(e, 0).prescribed else 1.0
                s_hi = -1.0 if self.bc.side(e, 1).prescribed else 1.0
                lo_g = s_lo * out[tuple(lo_idx)]
                hi_g = s_hi * out[tuple(hi_idx)]
            out = jnp.concatenate([lo_g, out, hi_g], axis=e)
        return out

    def _lap(self, c: Array, d: int) -> Array:
        G = self._ghost_pad(c, d)
        center = tuple(slice(1, -1) for _ in range(c.ndim))
        acc = jnp.zeros_like(c)
        for e in range(c.ndim):
            lo = list(center)
            hi = list(center)
            lo[e] = slice(0, -2)
            hi[e] = slice(2, None)
            acc = acc + (G[tuple(lo)] - 2.0 * c + G[tuple(hi)]) \
                / self.dx[e] ** 2
        return acc

    def _grad_p(self, p: Array, d: int) -> Array:
        """Pressure gradient on component d's faces. Open boundary
        faces see the homogeneous Dirichlet ghost (p = 0 at the face:
        ghost = -adjacent); prescribed faces get 0 (identity rows)."""
        h = self.dx[d]
        if self.bc.periodic(d):
            return (p - jnp.roll(p, 1, axis=d)) / h
        lo = [slice(None)] * p.ndim
        hi = [slice(None)] * p.ndim
        lo[d] = slice(0, 1)
        hi[d] = slice(-1, None)
        ghost_lo = -p[tuple(lo)] if not self.bc.side(d, 0).prescribed \
            else p[tuple(lo)]
        ghost_hi = -p[tuple(hi)] if not self.bc.side(d, 1).prescribed \
            else p[tuple(hi)]
        ext = jnp.concatenate([ghost_lo, p, ghost_hi], axis=d)
        sl_hi = [slice(None)] * p.ndim
        sl_lo = [slice(None)] * p.ndim
        sl_hi[d] = slice(1, None)
        sl_lo[d] = slice(0, -1)
        g = (ext[tuple(sl_hi)] - ext[tuple(sl_lo)]) / h
        return g

    def divergence(self, u: Vel) -> Array:
        acc = None
        for d, c in enumerate(u):
            h = self.dx[d]
            if self.bc.periodic(d):
                dd = (jnp.roll(c, -1, axis=d) - c) / h
            else:
                sl_hi = [slice(None)] * c.ndim
                sl_lo = [slice(None)] * c.ndim
                sl_hi[d] = slice(1, None)
                sl_lo[d] = slice(0, -1)
                dd = (c[tuple(sl_hi)] - c[tuple(sl_lo)]) / h
            acc = dd if acc is None else acc + dd
        return acc

    def _momentum(self, u: Vel, p: Array, alpha=None) -> Vel:
        alpha = self.alpha if alpha is None else alpha
        out = []
        for d, c in enumerate(u):
            r = alpha * c - self.mu * self._lap(c, d) \
                + self._grad_p(p, d)
            r = jnp.where(self._masks[d], c, r)   # identity rows
            out.append(r)
        return tuple(out)

    def operator(self, x, alpha=None):
        u, p = x
        r_p = -self.divergence(u)
        if self.p_nullspace:
            # rank-one shift pins the constant pressure mode
            r_p = r_p + jnp.mean(p)
        return (self._momentum(u, p, alpha=alpha), r_p)

    # ------------------------------------------------------------------
    # diagonals (for the velocity smoother)
    # ------------------------------------------------------------------
    def _assemble_diag(self, d: int) -> Array:
        """alpha-FREE part of the smoother diagonal (the mu/stencil
        terms + boundary adjustments). The dynamic diagonal is
        ``where(mask, 1, this + alpha)`` — assembled per call so alpha
        may be a traced value (adaptive dt, VERDICT round 4 item 6)."""
        dim = len(self.n)
        base = 2.0 * self.mu * sum(1.0 / h ** 2 for h in self.dx)
        diag = np.full(self.shapes[d], base, dtype=np.float64)
        for e in range(dim):
            if self.bc.periodic(e):
                continue
            if e == d:
                # boundary faces: edge-pad ghost == the face itself
                for s in (0, 1):
                    idx = [slice(None)] * dim
                    idx[e] = slice(0, 1) if s == 0 else slice(-1, None)
                    diag[tuple(idx)] -= self.mu / self.dx[e] ** 2
            else:
                for s in (0, 1):
                    sgn = -1.0 if self.bc.side(e, s).prescribed else 1.0
                    idx = [slice(None)] * dim
                    idx[e] = slice(0, 1) if s == 0 else slice(-1, None)
                    diag[tuple(idx)] -= sgn * self.mu / self.dx[e] ** 2
        return jnp.asarray(diag, dtype=self.dtype)

    def _diag(self, d: int, alpha=None) -> Array:
        """Smoother diagonal at the given (possibly traced) alpha;
        identity rows get 1."""
        alpha = self.alpha if alpha is None else alpha
        return jnp.where(self._masks[d], 1.0, self._diags[d] + alpha)

    # ------------------------------------------------------------------
    # preconditioner
    # ------------------------------------------------------------------
    def _vel_smooth(self, r_u: Vel, alpha=None,
                    nu_sweeps: Optional[int] = None) -> Vel:
        """nu red-black sweeps on alpha*u - mu*lap(u) = r_u from zero
        (the velocity Helmholtz sub-solve of the projection
        preconditioner). ``nu_sweeps`` overrides the construction-time
        sweep count (the escalation path's "tighter inner" knob)."""
        a = self.alpha if alpha is None else alpha
        nu = self.nu_sweeps if nu_sweeps is None else int(nu_sweeps)

        def one_component(d, c0, rhs):
            red, black = self._rb[d]
            diag = self._diag(d, alpha)

            def sweep(_, c):
                for mask in (red, black):
                    Ac = a * c - self.mu * self._lap(c, d)
                    Ac = jnp.where(self._masks[d], c, Ac)
                    c = c + jnp.where(mask, (rhs - Ac) / diag, 0.0)
                return c

            return jax.lax.fori_loop(0, nu, sweep, c0)

        return tuple(one_component(d, jnp.zeros_like(r), r)
                     for d, r in enumerate(r_u))

    def _schur(self, s: Array, alpha=None) -> Array:
        """Cahouet–Chabard Schur proxy: S^{-1} s ~ alpha*L_p^{-1} s - mu*s
        (S = D A^{-1} G with A = alpha - mu*L; the alpha-dominant limit
        gives alpha*L_p^{-1}, the steady limit gives -mu*I since
        D L^{-1} G ~ I). L_p^{-1} is one MG V-cycle. A traced ``alpha``
        always takes the vcycle branch (time stepping has alpha>0);
        only the static alpha==0 steady solve skips it."""
        a = self.alpha if alpha is None else alpha
        out = -self.mu * s
        if alpha is None and self.alpha == 0.0:
            return out
        q = s
        if self.p_nullspace:
            q = q - jnp.mean(q)
        q = self.p_mg.vcycle(jnp.zeros_like(q), q)
        if self.p_nullspace:
            q = q - jnp.mean(q)
        return out + a * q

    def precondition(self, r, alpha=None, nu_sweeps=None):
        r_u, r_p = r
        u1 = self._vel_smooth(r_u, alpha=alpha, nu_sweeps=nu_sweeps)
        s = r_p + self.divergence(u1)
        p1 = self._schur(s, alpha=alpha)
        return (u1, p1)

    # ------------------------------------------------------------------
    # right-hand side assembly (all boundary data enters here)
    # ------------------------------------------------------------------
    def make_rhs(self, f_u: Optional[Vel] = None,
                 f_p: Optional[Array] = None,
                 bdry: Optional[Dict] = None):
        """rhs pytree for ``solve``. ``bdry[(d, e, side)]`` prescribes
        component d on boundary (e, side): normal data when e == d
        (face slab, identity rows), tangential data when e != d (enters
        through the Dirichlet ghost lift 2*mu*V/h^2)."""
        dim = len(self.n)
        bdry = bdry or {}
        ru = []
        for d in range(dim):
            r = jnp.zeros(self.shapes[d], dtype=self.dtype) \
                if f_u is None else jnp.asarray(f_u[d], dtype=self.dtype)
            # tangential ghost lifts FIRST: identity rows are set after,
            # so a lift slab crossing a prescribed boundary face (e.g.
            # the moving-lid corner in a driven cavity) cannot corrupt
            # that face's prescribed value
            for e in range(dim):
                if e == d or self.bc.periodic(e):
                    continue
                for s in (0, 1):
                    if not self.bc.side(e, s).prescribed:
                        continue
                    val = bdry.get((d, e, s), None)
                    if val is None:
                        continue
                    idx = [slice(None)] * dim
                    idx[e] = slice(0, 1) if s == 0 else slice(-1, None)
                    r = r.at[tuple(idx)].add(
                        2.0 * self.mu * jnp.asarray(val, self.dtype)
                        / self.dx[e] ** 2)
            # normal (identity-row) data
            if not self.bc.periodic(d):
                for s in (0, 1):
                    if not self.bc.side(d, s).prescribed:
                        continue
                    val = bdry.get((d, d, s), 0.0)
                    idx = [slice(0, 1) if e == d else slice(None)
                           for e in range(dim)]
                    if s == 1:
                        idx[d] = slice(-1, None)
                    r = r.at[tuple(idx)].set(val)
            ru.append(r)
        rp = jnp.zeros(self.n, dtype=self.dtype) if f_p is None \
            else jnp.asarray(f_p, dtype=self.dtype)
        if self.p_nullspace:
            rp = rp - jnp.mean(rp)
        return (tuple(ru), rp)

    # ------------------------------------------------------------------
    def solve(self, rhs, x0=None, alpha=None, *, m=None, restarts=None,
              nu_sweeps=None) -> StokesSolveResult:
        """``alpha`` overrides the construction-time alpha = rho/dt and
        may be a TRACED scalar — the adaptive-dt path recompiles
        nothing (one compiled step serves every dt; VERDICT round 4
        item 6). ``m``/``restarts``/``nu_sweeps`` override the solve
        geometry (used by :meth:`solve_escalated`; default ``None``
        keeps the construction-time values and the exact pre-override
        trace). Every solve records ``self.last_solve_stats``: eagerly
        when run outside jit, through ``jax.debug.callback`` when the
        solver was built with ``record_stats=True``."""
        if self.spectral is not None:
            return self._solve_spectral(rhs, alpha=alpha)
        if x0 is None:
            x0 = (tuple(jnp.zeros(s, dtype=self.dtype)
                        for s in self.shapes),
                  jnp.zeros(self.n, dtype=self.dtype))
        op = self.operator if alpha is None else \
            (lambda x: self.operator(x, alpha=alpha))
        if alpha is None and nu_sweeps is None:
            M = self.precondition
        else:
            M = lambda r: self.precondition(r, alpha=alpha,  # noqa: E731
                                            nu_sweeps=nu_sweeps)
        sol = fgmres(op, rhs, x0=x0, M=M,
                     m=self.m if m is None else int(m),
                     tol=self.tol,
                     restarts=(self.restarts if restarts is None
                               else int(restarts)))
        record_solve_stats(self, sol, solver="fgmres",
                           use_callback=self.record_stats)
        u, p = sol.x
        if self.p_nullspace:
            p = p - jnp.mean(p)
        return StokesSolveResult(u=u, p=p, iters=sol.iters,
                                 resnorm=sol.resnorm,
                                 converged=sol.converged)

    def _solve_spectral(self, rhs, alpha=None) -> StokesSolveResult:
        """Exact all-periodic saddle solve: one batched spectral pass
        through the hash-consed plan, plus ONE operator apply for an
        honest residual record (same |r|_2 <= tol*|b|_2 convention as
        the FGMRES path, so escalation/vitals plumbing reads it
        unchanged). ``alpha`` may be traced — the adaptive-dt contract
        of :meth:`solve` is preserved."""
        from ibamr_tpu.solvers.krylov import SolveResult

        a = self.alpha if alpha is None else alpha
        ru, rp = rhs
        u, p = self.spectral.solve_stokes_saddle(ru, rp, a, self.mu)
        Au, Ap = self.operator((u, p)) if alpha is None else \
            self.operator((u, p), alpha=alpha)
        rn2 = sum(jnp.sum((c - r) ** 2) for c, r in zip(Au, ru)) \
            + jnp.sum((Ap - rp) ** 2)
        bn2 = sum(jnp.sum(r ** 2) for r in ru) + jnp.sum(rp ** 2)
        resnorm = jnp.sqrt(rn2)
        converged = resnorm <= self.tol * jnp.sqrt(bn2)
        sol = SolveResult(x=(u, p), iters=jnp.asarray(0, jnp.int32),
                          resnorm=resnorm, converged=converged)
        record_solve_stats(self, sol, solver="spectral",
                           use_callback=self.record_stats)
        return StokesSolveResult(u=u, p=p, iters=sol.iters,
                                 resnorm=resnorm, converged=converged)

    def solve_escalated(self, rhs, x0=None, alpha=None, *, chain=None,
                        on_incident=None, step=None,
                        context="StaggeredStokesSolver") \
            -> StokesSolveResult:
        """Host-side escalating solve: walk the declared chain (default
        :data:`ibamr_tpu.solvers.escalation.ESCALATION_FALLBACKS`) until
        an attempt converges — each level scales FGMRES restarts, the
        Krylov basis and the preconditioner sweep depth. Level 0 is the
        plain :meth:`solve` geometry, so a converging base solve is
        bitwise-identical to ``solve``. Raises ``SolverBreakdown``
        after the chain is exhausted; escalations/breakdowns go to
        ``on_incident`` as structured records. Eager-only (each level
        compiles its own solve geometry) — inside jit use plain
        :meth:`solve`."""
        def attempt(level, _i):
            return self.solve(
                rhs, x0=x0, alpha=alpha,
                m=self.m * level.m_scale,
                restarts=self.restarts * level.restarts_scale,
                nu_sweeps=self.nu_sweeps * level.inner_scale)

        return escalate_solve(attempt, context=context, chain=chain,
                              on_incident=on_incident, step=step)
