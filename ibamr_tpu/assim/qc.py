"""Observation quality control: the gate between sensors and the gain.

Real sensor streams drop out (NaN), go stale (a feed that keeps
repeating its last value ages without failing), and spike (electrical
outliers many sigma off the flow). Letting any of those into the
analysis corrupts EVERY lane at once — the one failure mode lane
quarantine cannot contain — so QC screens per channel BEFORE the
update and the analysis only ever sees an (m,) accept mask (shapes
static, zero retraces; see :mod:`ibamr_tpu.assim.enkf`).

Screening order per channel: dropout (non-finite value), stale
(``age_s`` beyond ``max_age_s``), then innovation magnitude
``|y - ybar| > k_sigma * sqrt(HPH + R)`` against the ensemble's own
predicted spread — the classic background check, self-scaling as the
ensemble tightens. Every rejection is a structured ledger record
(kind ``assim_qc_reject``) plus a reason-labeled counter, so
``tools/obs.py summary`` can report rejections by reason and the SLO
gate can pin "every injected bad observation was rejected".
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from ibamr_tpu import obs as _obs

_REJECTS = _obs.counter  # labeled per reason at call time
_obs.describe("assim_qc_rejections_total",
              "observation channels rejected by the QC gate, by reason")
_obs.describe("assim_qc_accepted_total",
              "observation channels accepted into the analysis")


@dataclass
class QCConfig:
    """Gate thresholds. ``k_sigma`` is deliberately loose (4 sigma):
    QC protects against *bad sensors*, not surprising flow — a filter
    that rejects every informative innovation never corrects."""
    k_sigma: float = 4.0
    max_age_s: float = 60.0
    min_accept: int = 1     # fewer accepted channels -> skip analysis


def screen(batch, ybar: np.ndarray, hph: np.ndarray,
           cfg: QCConfig, *, step: int = 0,
           cycle: Optional[int] = None) -> Tuple[np.ndarray, dict]:
    """Per-channel accept mask for one observation batch.

    batch: :class:`~ibamr_tpu.assim.observe.ObservationBatch`;
    ybar: (m,) ensemble-mean predicted obs; hph: (m,) ensemble
    variance of the predicted obs (the diag of H P H^T).

    Returns ``(accept (m,) bool, report)`` where report counts
    rejections by reason. Emits one ledger record per rejection.
    """
    y = np.asarray(batch.values, np.float64)
    r = np.asarray(batch.r, np.float64)
    age = np.asarray(batch.age_s, np.float64)
    ybar = np.asarray(ybar, np.float64)
    hph = np.maximum(np.asarray(hph, np.float64), 0.0)
    m = y.shape[0]
    names = batch.names or tuple(f"ch[{i}]" for i in range(m))
    cyc = batch.cycle if cycle is None else cycle

    accept = np.ones(m, dtype=bool)
    reasons: dict = {"dropout": 0, "stale": 0, "outlier": 0}
    for j in range(m):
        reason = None
        innov = y[j] - ybar[j]
        thresh = cfg.k_sigma * float(np.sqrt(hph[j] + r[j]))
        if not np.isfinite(y[j]):
            reason = "dropout"
        elif age[j] > cfg.max_age_s:
            reason = "stale"
        elif abs(innov) > thresh:
            reason = "outlier"
        if reason is None:
            continue
        accept[j] = False
        reasons[reason] += 1
        _REJECTS("assim_qc_rejections_total", reason=reason).inc()
        _obs.emit("assim_qc_reject",
                  instrument=names[j], reason=reason,
                  cycle=int(cyc), step=int(step),
                  value=(float(y[j]) if np.isfinite(y[j]) else None),
                  innovation=(float(innov) if np.isfinite(innov)
                              else None),
                  threshold=thresh, age_s=float(age[j]))
    n_acc = int(accept.sum())
    _obs.counter("assim_qc_accepted_total").inc(n_acc)
    report = {"accepted": n_acc, "rejected": int(m - n_acc),
              "by_reason": {k: v for k, v in reasons.items() if v}}
    return accept, report
