"""Masked ensemble square-root filter (ESRF/ETKF) analysis.

The analysis is small dense batched linear algebra in ensemble space
(Evensen 1994; ETKF square-root form after Hunt et al. 2007): with B
lanes and m observed channels, everything beyond the two (B, n)
ensemble matmuls is (B, B) or (B, m) — an eigh, a few GEMMs — so the
update between scan chunks costs microseconds next to the chunk.

Robustness contracts, all in-graph (zero retraces):

- **masked statistics** — the (B,) ``alive`` mask weights every
  ensemble moment, so a quarantined lane contributes NOTHING to the
  mean, the anomalies, or the gain, and its own rows pass through the
  analysis bitwise frozen (``jnp.where`` on the lane axis — the PR-7
  lane-freeze idiom). Masked analysis on B lanes with k alive is
  exactly the dense analysis on the k-member ensemble (pinned by
  tests/test_assim.py).
- **masked observations** — the (m,) ``obs_mask`` from the QC gate
  zeroes rejected channels out of the innovation and the gain instead
  of slicing them out, so a cycle with three rejected sensors runs the
  SAME executable as a clean one.
- **multiplicative inflation** — a traced scalar multiplying the
  posterior anomalies (Anderson & Anderson 1999 family). Escalating
  the inflation rung never recompiles, and posterior spread responds
  exactly linearly, which is what makes the collapse -> escalate ->
  cured ladder deterministic.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

try:                       # optional: only the packer needs it
    from jax.flatten_util import ravel_pytree
except Exception:          # pragma: no cover
    ravel_pytree = None

_EPS = 1e-30


class AnalysisDiag(NamedTuple):
    """Scalar diagnostics of one analysis — ONE host transfer reads
    them all post-update (the filter-health sentinels' inputs)."""
    spread_f: jnp.ndarray      # forecast ensemble spread (masked rms)
    spread_a: jnp.ndarray      # analysis ensemble spread
    innov_rms: jnp.ndarray     # rms innovation over accepted channels
    consistency: jnp.ndarray   # innovation chi2 / E[chi2] (~1 healthy)
    n_alive: jnp.ndarray       # effective ensemble size
    n_obs: jnp.ndarray         # accepted channel count


def masked_moments(ens: jnp.ndarray, alive: jnp.ndarray):
    """Mean and anomalies over alive lanes only.

    ens: (B, n); alive: (B,) bool. Returns (mean (n,), anom (B, n) with
    dead rows zeroed, neff scalar)."""
    w = alive.astype(ens.dtype)
    neff = jnp.maximum(jnp.sum(w), 1.0)
    mean = jnp.sum(w[:, None] * ens, axis=0) / neff
    anom = (ens - mean[None, :]) * w[:, None]
    return mean, anom, neff


def masked_spread(ens: jnp.ndarray, alive: jnp.ndarray) -> jnp.ndarray:
    """Scalar ensemble spread: rms of masked anomalies per alive-lane
    degree of freedom."""
    _, anom, neff = masked_moments(ens, alive)
    n = ens.shape[1]
    denom = jnp.maximum(neff - 1.0, 1.0) * n
    return jnp.sqrt(jnp.sum(anom * anom) / denom)


def esrf_analysis(ens: jnp.ndarray, obs_ens: jnp.ndarray,
                  y: jnp.ndarray, r: jnp.ndarray,
                  alive: jnp.ndarray, obs_mask: jnp.ndarray,
                  inflation: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, AnalysisDiag]:
    """One masked ETKF square-root update.

    ens: (B, n) packed state ensemble; obs_ens: (B, m) = H(ens);
    y: (m,) observed values; r: (m,) obs-error variances;
    alive: (B,) lane mask; obs_mask: (m,) QC-accepted mask;
    inflation: scalar posterior multiplicative inflation.

    Returns (analysis ensemble (B, n) with dead lanes frozen, diag).
    """
    B = ens.shape[0]
    dt = ens.dtype
    xbar, Zx, neff = masked_moments(ens, alive)
    ybar, Zy, _ = masked_moments(obs_ens, alive)

    om = obs_mask.astype(dt)
    rinv = om / jnp.asarray(r, dt)                  # rejected -> 0
    d = (jnp.asarray(y, dt) - ybar) * om            # (m,)

    # ensemble-space gain: G = (neff-1) I + Zy R^-1 Zy^T, (B, B)
    C = (Zy * rinv[None, :]) @ Zy.T
    G = (neff - 1.0) * jnp.eye(B, dtype=dt) + C
    lam, Q = jnp.linalg.eigh(G)
    lam = jnp.maximum(lam, jnp.asarray(_EPS, dt))
    wbar = (Q / lam[None, :]) @ (Q.T @ (Zy @ (rinv * d)))   # (B,)
    # symmetric square root: Wa = sqrt(neff-1) G^{-1/2}
    Wa = (Q * jnp.sqrt((neff - 1.0) / lam)[None, :]) @ Q.T  # (B, B)

    mean_shift = wbar @ Zx                          # (n,)
    anom_a = Wa @ Zx                                # (B, n)
    infl = jnp.asarray(inflation, dt)
    ana = xbar[None, :] + mean_shift[None, :] + infl * anom_a
    # dead lanes ride through bitwise frozen (lane-freeze idiom)
    ana = jnp.where(alive[:, None], ana, ens)

    # diagnostics — innovation consistency: E[d_j^2] = HPH_jj + r_j
    m_eff = jnp.maximum(jnp.sum(om), 1.0)
    hph = jnp.sum(Zy * Zy, axis=0) / jnp.maximum(neff - 1.0, 1.0)
    chi2 = jnp.sum(d * d * om / (hph + jnp.asarray(r, dt) + _EPS))
    diag = AnalysisDiag(
        spread_f=masked_spread(ens, alive),
        spread_a=masked_spread(ana, alive),
        innov_rms=jnp.sqrt(jnp.sum(d * d) / m_eff),
        consistency=chi2 / m_eff,
        n_alive=neff,
        n_obs=jnp.sum(om))
    return ana, diag


# ---------------------------------------------------------------------------
# state packing: the assimilated subset of an IBState as a flat vector
# ---------------------------------------------------------------------------

def state_packer(template_state):
    """(pack, unpack, n) for the assimilated subset of an UNBATCHED
    IBState: the MAC velocity components and the pressure.

    ``pack(state) -> (n,)`` and ``unpack(state, vec) -> state`` are
    pure and jittable; ``jax.vmap`` them for the lane-stacked fleet.
    Markers ride along un-assimilated (they are slaved to the velocity
    field through the IB coupling), and ``n_prev``/``t``/``k`` keep
    the lane's own history — the analysis moves the flow, not the
    clock.
    """
    if ravel_pytree is None:   # pragma: no cover
        raise ImportError("jax.flatten_util is required for packing")
    subset = (template_state.ins.u, template_state.ins.p)
    flat0, unravel = ravel_pytree(subset)

    def pack(state):
        v, _ = ravel_pytree((state.ins.u, state.ins.p))
        return v

    def unpack(state, vec):
        u, p = unravel(vec.astype(flat0.dtype))
        return state._replace(ins=state.ins._replace(u=u, p=p))

    return pack, unpack, int(flat0.shape[0])
