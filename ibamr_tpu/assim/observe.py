"""Observation operators for ensemble assimilation (ROADMAP item 5).

The instrument panel IS the observation map: a flow meter or pressure
gauge (:class:`ibamr_tpu.instruments.InstrumentPanel`) is already a
pure, jittable function of the state — interp gathers plus on-device
reductions, no host sync — so H(x) here is nothing more than
``panel.readings`` flattened into a fixed-order vector and ``vmap``-ed
over the lane axis. No separate "forward operator" code path exists to
drift out of sync with what the diagnostics stream reports.

Host-side observation *data* (the y that arrives from real sensors)
rides :class:`ObservationBatch` — plain numpy plus an age stamp, so the
QC gate (:mod:`ibamr_tpu.assim.qc`) can reject dropped / stale /
outlier channels before anything touches the device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.instruments import InstrumentPanel

# fixed channel order: every vector obs is the panel's readings dict
# flattened in this sequence (meters vary fastest)
DEFAULT_CHANNELS: Tuple[str, ...] = ("flux", "mean_pressure")


class ObservationOperator:
    """H: state -> (m,) observation vector, derived from an instrument
    panel. Pure and jittable; ``fleet`` maps it over lane axis 0."""

    def __init__(self, panel: InstrumentPanel,
                 channels: Sequence[str] = DEFAULT_CHANNELS):
        self.panel = panel
        self.channels = tuple(channels)
        self.n_meters = int(panel.meters.idx.shape[0])

    @property
    def n_obs(self) -> int:
        return self.n_meters * len(self.channels)

    def channel_names(self) -> Tuple[str, ...]:
        """One stable name per vector slot, e.g. ``flux[2]`` — the
        instrument identity QC rejections are keyed by."""
        return tuple(f"{c}[{i}]" for c in self.channels
                     for i in range(self.n_meters))

    def __call__(self, state) -> jnp.ndarray:
        """Unbatched IBState -> (m,) observation vector."""
        r = self.panel.readings(state.ins.u, state.ins.p, state.X)
        return jnp.concatenate(
            [jnp.atleast_1d(r[c]) for c in self.channels])

    def fleet(self, fleet_state) -> jnp.ndarray:
        """Lane-stacked state -> (B, m) per-member predicted obs."""
        return jax.vmap(self.__call__)(fleet_state)


@dataclass
class ObservationBatch:
    """One cycle's worth of sensor data, host-side.

    values: (m,) float64 — NaN marks a dropped channel;
    r: (m,) observation-error variances;
    age_s: (m,) seconds since each channel's reading was taken (a
        stale feed shows up as a large age, not a missing value);
    cycle: the assimilation cycle index this batch belongs to.
    """
    values: np.ndarray
    r: np.ndarray
    age_s: np.ndarray
    cycle: int = 0
    names: Optional[Tuple[str, ...]] = None

    def __post_init__(self):
        self.values = np.atleast_1d(np.asarray(self.values, np.float64))
        m = self.values.shape[0]
        self.r = np.broadcast_to(
            np.asarray(self.r, np.float64), (m,)).copy()
        self.age_s = np.broadcast_to(
            np.asarray(self.age_s, np.float64), (m,)).copy()


def synthesize_batches(op: ObservationOperator, truth_states,
                       sigma, *, seed: int = 0,
                       start_cycle: int = 0) -> list:
    """Noisy observation batches from a truth trajectory (twin
    experiment): H(truth) + N(0, sigma^2), R = sigma^2, age 0.

    ``truth_states`` is a sequence of unbatched states, one per cycle.
    Deterministic in ``seed`` so drills and their replays see the same
    sensor stream.
    """
    rng = np.random.default_rng(seed)
    m = op.n_obs
    sig = np.broadcast_to(np.asarray(sigma, np.float64), (m,)).copy()
    names = op.channel_names()
    out = []
    for i, st in enumerate(truth_states):
        clean = np.asarray(op(st), np.float64)
        out.append(ObservationBatch(
            values=clean + sig * rng.standard_normal(m),
            r=sig ** 2, age_s=np.zeros(m),
            cycle=start_cycle + i, names=names))
    return out


def stream_from_list(batches) -> Callable[[int, int], Optional[ObservationBatch]]:
    """An ``obs_source(cycle, step)`` over a precomputed batch list —
    the deterministic source drills wrap with injectors. Cycles past
    the end return None (the filter free-runs)."""
    batches = list(batches)

    def source(cycle: int, step: int):
        return batches[cycle] if 0 <= cycle < len(batches) else None

    return source
