"""The assimilation cycle: observe -> analyze -> advance, supervised.

One :class:`AssimilationCycle` turns the PR-7 lane fleet into a
forecasting service. The forecast leg is the ordinary fleet driver
chunk (vmapped scan, per-lane dt + alive mask); the analysis leg rides
the driver's regrid hook — the one cadence callback whose return value
REPLACES the state — so every ``steps_per_cycle`` steps the masked
ESRF update (:mod:`ibamr_tpu.assim.enkf`) moves all B lanes between
scan chunks, inside the same supervised run loop that already owns
checkpointing, rollback and lane quarantine.

Robustness wiring:

- the analysis executables are AOT-compiled ONCE through the serving
  :class:`~ibamr_tpu.serve.aot_cache.ExecutableCache` (``kind:
  "assim_chunk"``) and keyed on shapes only — quarantine flips the
  (B,) alive mask's *values*, QC flips the (m,) obs mask's values,
  inflation is a traced scalar: zero steady-state compiles, one trace
  signature through every failure mode;
- filter-health sentinels (ensemble-spread collapse, sustained
  innovation-consistency drift) raise :class:`FilterDegraded` — a
  :class:`SimulationDiverged` with ``kind="filter_degraded"`` — so the
  PR-2/3 supervisor rolls the whole cycle back to a verified
  checkpoint and retries with the multiplicative inflation escalated
  one :data:`INFLATION_FALLBACKS` rung (dt untouched: the flow is
  fine, the *filter* was mistuned);
- after every analysis the cycle calls ``HealthProbe.rebaseline()`` —
  an analysis update legitimately moves every lane's functional /
  volume / budget anchors, and without re-anchoring the first
  post-analysis chunk false-positives a WARN streak;
- every cycle runs under its own ``trace_id`` (``assim/cycle`` span),
  emits a terminal ``assim_cycle`` ledger record, and publishes
  forecast-error / spread / consistency gauges on the obs bus. Lost
  cycles are therefore countable from the ledger alone —
  ``tools/slo.py check --assim`` pins them at EXACTLY zero.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu import obs as _obs
from ibamr_tpu.assim import enkf as _enkf
from ibamr_tpu.assim import qc as _qc
from ibamr_tpu.assim.observe import ObservationOperator, stream_from_list
from ibamr_tpu.utils.hierarchy_driver import (HierarchyDriver, RunConfig,
                                              SimulationDiverged)

# the ENGINE_FALLBACKS / PRECISION_FALLBACKS chain shape: each rung
# maps to the next-stronger one; the top rung has no successor (the
# supervisor then falls back to its generic dt-backoff retry, which
# for a filter fault effectively gives up gracefully)
INFLATION_FALLBACKS = {
    1.0: 1.05,
    1.05: 1.1,
    1.1: 1.2,
    1.2: 1.4,
    1.4: 1.7,
}

_obs.describe("assim_cycles_total", "completed assimilation cycles")
_obs.describe("assim_cycles_skipped_total",
              "cycles with no usable observations (analysis skipped)")
_obs.describe("assim_inflation_escalations_total",
              "multiplicative-inflation rungs climbed after rollback")
_obs.describe("assim_analysis_wall_seconds",
              "wall time of one masked ESRF analysis (device + host)")
_obs.describe("assim_forecast_error",
              "rms innovation over QC-accepted channels (forecast "
              "error proxy against live sensors)")
_obs.describe("assim_spread", "masked ensemble spread after analysis")
_obs.describe("assim_consistency",
              "innovation chi2 / expected (healthy ~ 1)")


class FilterDegraded(SimulationDiverged):
    """The FILTER (not the flow) went statistically bad: ensemble
    spread collapsed below the floor, or the innovation-consistency
    ratio drifted out of band for ``sustain`` consecutive cycles.
    Subclassing :class:`SimulationDiverged` reuses the whole PR-2/3
    recovery machinery; ``escalate`` (when set by the cycle) lets the
    supervisor climb the inflation ladder instead of backing off dt.
    """

    kind = "filter_degraded"

    def __init__(self, step: int, reasons, diagnostics: dict,
                 escalate: Optional[Callable] = None):
        self.step = step
        self.reasons = list(reasons)
        self.diagnostics = dict(diagnostics)
        self.escalate = escalate
        self.bad_leaves: list = []      # the state itself is finite
        RuntimeError.__init__(
            self,
            f"filter degraded by step {step}: "
            f"{'; '.join(self.reasons)} (diagnostics "
            f"{self.diagnostics}) — rolling back to retry with "
            f"escalated inflation")

    def incident_payload(self) -> dict:
        return {"reasons": self.reasons,
                "diagnostics": self.diagnostics}


@dataclass
class AssimConfig:
    """Cycle cadence + filter tuning + sentinel thresholds."""
    steps_per_cycle: int = 2
    dt: float = 1e-3
    inflation: float = 1.0              # must sit on the ladder
    spread_floor: float = 0.0           # 0 disables the collapse sentinel
    consistency_ceiling: float = 0.0    # 0 disables the drift sentinel
    sustain: int = 3                    # consecutive bad cycles to fire
    qc: _qc.QCConfig = field(default_factory=_qc.QCConfig)


class AssimilationCycle:
    """A recurring forecasting tenant over a B-lane fleet driver."""

    def __init__(self, integ, obs_op: ObservationOperator, lanes: int,
                 cfg: AssimConfig, *, probe=None, cache=None,
                 recorder=None, fleet_step_wrap=None,
                 restart_interval: Optional[int] = None):
        from ibamr_tpu.serve.aot_cache import get_cache

        self.integ = integ
        self.obs_op = obs_op
        self.lanes = int(lanes)
        self.cfg = cfg
        self.inflation = float(cfg.inflation)
        self.cache = cache if cache is not None else get_cache()
        self.probe = probe
        self.obs_source: Optional[Callable] = None
        self._packer = None
        self._drift_streak = 0
        self._skipped = 0
        self.escalations: list = []

        run_cfg = RunConfig(
            dt=cfg.dt, num_steps=cfg.steps_per_cycle,
            health_interval=cfg.steps_per_cycle,
            restart_interval=(restart_interval
                              if restart_interval is not None
                              else cfg.steps_per_cycle),
            regrid_interval=cfg.steps_per_cycle)
        self.driver = HierarchyDriver(
            integ, run_cfg, lanes=self.lanes,
            regrid_fn=self._analysis_hook, health_probe=probe,
            recorder=recorder, fleet_step_wrap=fleet_step_wrap)

    # -- compiled pieces (kind: assim_chunk) ---------------------------------

    def _packers(self, fleet_state):
        if self._packer is None:
            from ibamr_tpu.utils.lanes import lane_slice
            self._packer = _enkf.state_packer(lane_slice(fleet_state, 0))
        return self._packer

    def _fingerprint(self, piece: str, args) -> tuple:
        from ibamr_tpu.serve.aot_cache import (arg_signature,
                                               step_fingerprint)
        fp = step_fingerprint(self.integ, extra={
            "assim": {"channels": list(self.obs_op.channels),
                      "n_meters": self.obs_op.n_meters,
                      "lanes": self.lanes}})
        extra = {"kind": "assim_chunk", "piece": piece,
                 "args": arg_signature(args)}
        return fp, extra

    def _observe_exec(self, fleet_state, alive):
        """(ybar, hph) of the predicted obs ensemble — QC's inputs."""
        from ibamr_tpu.serve.aot_cache import aot_compile

        def observe(state, alive_m):
            obs_ens = self.obs_op.fleet(state)
            ybar, zy, neff = _enkf.masked_moments(obs_ens, alive_m)
            hph = jnp.sum(zy * zy, axis=0) / jnp.maximum(neff - 1.0, 1.0)
            return ybar, hph

        args = (fleet_state, alive)
        fp, extra = self._fingerprint("observe", args)
        ent = self.cache.get_or_compile(
            fp, lambda: aot_compile(observe, args),
            extra=extra, label="assim_observe")
        return ent.executable

    def _analyze_exec(self, fleet_state, y, r, obs_mask, alive, infl):
        from ibamr_tpu.serve.aot_cache import aot_compile

        pack, unpack, _n = self._packers(fleet_state)

        def analyze(state, y_v, r_v, om, alive_m, lam):
            ens = jax.vmap(pack)(state)
            obs_ens = self.obs_op.fleet(state)
            ana, diag = _enkf.esrf_analysis(
                ens, obs_ens, y_v, r_v, alive_m, om, lam)
            new_state = jax.vmap(unpack)(state, ana)
            return new_state, diag

        args = (fleet_state, y, r, obs_mask, alive, infl)
        fp, extra = self._fingerprint("analyze", args)
        ent = self.cache.get_or_compile(
            fp, lambda: aot_compile(analyze, args),
            extra=extra, label="assim_analyze")
        return ent.executable

    # -- inflation ladder ----------------------------------------------------

    def escalate_inflation(self) -> Optional[tuple]:
        """One rung up :data:`INFLATION_FALLBACKS`; returns (before,
        after) or None at the top. Called by the supervisor on a
        ``filter_degraded`` rollback — no recompile happens (inflation
        is a traced argument), so the retry reruns the same
        executables with a stronger filter."""
        cur = self.inflation
        nxt = next((v for k, v in INFLATION_FALLBACKS.items()
                    if abs(k - cur) < 1e-12), None)
        if nxt is None:
            return None
        self.inflation = float(nxt)
        self._drift_streak = 0
        self.escalations.append((cur, nxt))
        _obs.counter("assim_inflation_escalations_total").inc()
        return (cur, nxt)

    # -- the cycle hook (runs at the driver's regrid cadence) ----------------

    def _analysis_hook(self, state, step: int):
        cfg = self.cfg
        cycle = step // cfg.steps_per_cycle - 1
        batch = (self.obs_source(cycle, step)
                 if self.obs_source is not None else None)
        if batch is None:
            self._skipped += 1
            _obs.counter("assim_cycles_skipped_total").inc()
            return state

        tid = _obs.new_trace_id()
        with _obs.trace_scope(tid):
            with _obs.span("assim/cycle", cycle=int(cycle),
                           step=int(step)):
                return self._run_analysis(state, batch, cycle, step)

    def _run_analysis(self, state, batch, cycle: int, step: int):
        cfg = self.cfg
        alive = jnp.asarray(self.driver.lane_alive)
        t0 = time.perf_counter()

        # observe: ensemble-predicted mean/variance per channel
        with _obs.span("assim/observe"):
            obs_exec = self._observe_exec(state, alive)
            ybar, hph = obs_exec(state, alive)
            ybar = np.asarray(ybar)
            hph = np.asarray(hph)

        # QC gate (host-side; rejections are structured records)
        with _obs.span("assim/qc"):
            accept, qc_report = _qc.screen(
                batch, ybar, hph, cfg.qc, step=step, cycle=cycle)
        if qc_report["accepted"] < cfg.qc.min_accept:
            self._skipped += 1
            _obs.counter("assim_cycles_skipped_total").inc()
            _obs.emit("assim_cycle", cycle=int(cycle), step=int(step),
                      skipped=True, **qc_report)
            return state

        # analyze: masked ESRF update of every alive lane
        dt0 = jax.tree_util.tree_leaves(state)[0].dtype
        y = jnp.nan_to_num(
            jnp.asarray(batch.values, jnp.float64)).astype(dt0)
        r = jnp.asarray(batch.r, jnp.float64).astype(dt0)
        om = jnp.asarray(accept)
        infl = jnp.asarray(self.inflation, dt0)
        with _obs.span("assim/analyze"):
            ana_exec = self._analyze_exec(state, y, r, om, alive, infl)
            new_state, diag = ana_exec(state, y, r, om, alive, infl)
            diag = jax.tree_util.tree_map(
                lambda v: float(np.asarray(v)), diag)
        wall = time.perf_counter() - t0

        # sentinels: the filter's own health
        reasons = []
        if cfg.spread_floor > 0.0 and diag.spread_a < cfg.spread_floor:
            reasons.append(
                f"ensemble spread collapsed: {diag.spread_a:.3e} < "
                f"floor {cfg.spread_floor:.3e}")
        if cfg.consistency_ceiling > 0.0 \
                and diag.consistency > cfg.consistency_ceiling:
            self._drift_streak += 1
            if self._drift_streak >= cfg.sustain:
                reasons.append(
                    f"innovation consistency drifted: "
                    f"{diag.consistency:.2f} > "
                    f"{cfg.consistency_ceiling:.2f} for "
                    f"{self._drift_streak} cycles")
        else:
            self._drift_streak = 0
        if reasons:
            raise FilterDegraded(
                step, reasons,
                {"spread_a": diag.spread_a, "spread_f": diag.spread_f,
                 "consistency": diag.consistency,
                 "inflation": self.inflation,
                 "n_alive": diag.n_alive, "cycle": int(cycle)},
                escalate=self.escalate_inflation)

        # telemetry: gauges + the cycle's terminal ledger record
        _obs.gauge("assim_forecast_error").set(diag.innov_rms)
        _obs.gauge("assim_spread").set(diag.spread_a)
        _obs.gauge("assim_consistency").set(diag.consistency)
        _obs.gauge("assim_inflation").set(self.inflation)
        _obs.histogram("assim_analysis_wall_seconds").observe(wall)
        _obs.counter("assim_cycles_total").inc()
        _obs.emit("assim_cycle", cycle=int(cycle), step=int(step),
                  skipped=False, forecast_error=diag.innov_rms,
                  spread_f=diag.spread_f, spread_a=diag.spread_a,
                  consistency=diag.consistency,
                  inflation=self.inflation,
                  n_alive=int(diag.n_alive), n_obs=int(diag.n_obs),
                  analysis_wall_s=wall, **qc_report)

        # analysis moved every lane: re-anchor the vitals baselines or
        # the next chunk's drift triage false-positives a WARN
        if self.probe is not None:
            self.probe.rebaseline()
        return new_state

    # -- service entry -------------------------------------------------------

    def run(self, state0, batches=None, *, directory: str,
            n_cycles: Optional[int] = None,
            obs_source: Optional[Callable] = None,
            max_retries: int = 3, handle_signals: bool = False,
            recorder=None, **supervisor_kw):
        """Assimilate ``batches`` (one per cycle) into the fleet under
        full supervision; returns the final lane-stacked state. Each
        cycle is forecast (``steps_per_cycle`` driver steps) followed
        by the analysis hook; rollbacks re-fetch the SAME batch for a
        re-run cycle, so retries are deterministic.

        ``obs_source`` overrides the batch list with a callable
        ``(cycle, step) -> ObservationBatch | None`` — the seam the
        fault-injection drills wrap sensor faults around (pass
        ``n_cycles`` alongside, or ``batches`` just for its length)."""
        from ibamr_tpu.utils.supervisor import ResilientDriver

        if batches is not None:
            batches = list(batches)
            if n_cycles is None:
                n_cycles = len(batches)
        if n_cycles is None:
            raise ValueError("run() needs batches or n_cycles")
        self.obs_source = (obs_source if obs_source is not None
                           else stream_from_list(batches or []))
        self.driver.cfg.num_steps = \
            n_cycles * self.cfg.steps_per_cycle
        sup = ResilientDriver(
            self.driver, directory, max_retries=max_retries,
            handle_signals=handle_signals, recorder=recorder,
            **supervisor_kw)
        return sup.run(state0)
