"""Fault-tolerant ensemble data assimilation (ROADMAP item 5).

The PR-7 lane fleet as a statistical object: a masked EnKF/ESRF
analysis (:mod:`~ibamr_tpu.assim.enkf`) updates all B lanes between
scan chunks from instrument-panel observations
(:mod:`~ibamr_tpu.assim.observe`), behind a per-channel QC gate
(:mod:`~ibamr_tpu.assim.qc`), orchestrated by the supervised
:class:`~ibamr_tpu.assim.cycle.AssimilationCycle`. See
docs/RESILIENCE.md ("Filter robustness") for the failure-mode map.
"""

from ibamr_tpu.assim.cycle import (INFLATION_FALLBACKS, AssimConfig,
                                   AssimilationCycle, FilterDegraded)
from ibamr_tpu.assim.enkf import (AnalysisDiag, esrf_analysis,
                                  masked_moments, masked_spread,
                                  state_packer)
from ibamr_tpu.assim.observe import (ObservationBatch,
                                     ObservationOperator,
                                     stream_from_list,
                                     synthesize_batches)
from ibamr_tpu.assim.qc import QCConfig, screen

__all__ = [
    "AnalysisDiag", "AssimConfig", "AssimilationCycle",
    "FilterDegraded", "INFLATION_FALLBACKS", "ObservationBatch",
    "ObservationOperator", "QCConfig", "esrf_analysis",
    "masked_moments", "masked_spread", "screen", "state_packer",
    "stream_from_list", "synthesize_batches",
]
