"""Deterministic open-loop load generator for the warm-pool router
(PR 17, docs/SERVING.md "Traffic & overload").

Every serving number before this module came from a single-family
cold/warm drill; the north star is sustained traffic the server did
not pick. This module supplies that traffic REPRODUCIBLY:

- **arrivals** — :func:`poisson_burst_schedule` draws a seeded Poisson
  process (exponential inter-arrival gaps from
  ``np.random.default_rng(seed)``) with named burst windows where the
  instantaneous rate multiplies by ``burst_factor``. The schedule is a
  pure function of its arguments — virtual timestamps, no wall clock —
  so the same seed replays the same soak bit-for-bit at the schedule
  level.
- **scenario mix** — :data:`SCENARIO_MIX` is heavy-tailed in service
  demand (steps per request), modeled on the repo's example drivers:
  most arrivals are short interactive probes (the ``examples/IB`` /
  ``examples/navier_stokes`` driver scale), a minority are long batch
  campaign chunks (the ``examples/adv_diff`` / ``examples/IBFE``
  sweep scale). Every mix entry shares ONE scenario family (shape,
  physics), so a bounded CPU soak pays exactly one bucket compile and
  then rides the zero-compile warm path — heterogeneous ``steps``/
  ``dt`` are traced arguments and never retrace.
- **open loop** — :func:`run_open_loop` submits each arrival at its
  scheduled (scaled) time from its own thread regardless of earlier
  completions, which is what makes overload REAL: a closed loop would
  politely self-throttle and never exercise admission control. Thread
  count is bounded; saturation is counted, never silently dropped.
- **the soak** — :func:`soak_drill` composes the above against a fresh
  router with committed tenant-class policies and returns the traffic
  summary ``tools/slo.py check --soak`` and ``bench.py --soak``
  evaluate. Chaos (compile storms, killed builds, stragglers) rides on
  top in ``tools.fault_injection.run_soak_smoke``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, replace
from typing import Optional, Sequence

import numpy as np

from ibamr_tpu import obs as _obs
from ibamr_tpu.serve.router import (BucketSpec, ScenarioRequest,
                                    TenantClassPolicy, WarmPoolRouter)

# ---------------------------------------------------------------------------
# scenario mix: heavy-tailed service demand over ONE warm family
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Scenario:
    """One entry of the load mix: a named request template with a
    sampling weight. ``name`` references the example-driver scale the
    entry is modeled on; ``steps`` carries the heavy tail.

    ``family`` (PR 18) optionally overrides the bucket-family fields
    of generated requests (any of ``n_cells``/``n_lat``/``n_lon``/
    ``engine``/``spectral_dtype``/``mu`` as a mapping) — the
    mix-shift soak routes part of the mix onto families the router
    has never compiled. ``None`` (the default) keeps the schedule's
    single shared family exactly as before."""
    name: str
    weight: float
    tenant_class: str
    steps: int
    dt: float = 5e-5
    deadline_s: Optional[float] = None
    family: Optional[tuple] = None      # (("n_lon", 12), ...) mapping


# Heavy-tailed mix (weights sum to 1): ~80% short interactive probes,
# ~20% long batch chunks with 3-8x the service demand — the shape of
# the example-driver population (many small demo probes, few long
# campaign sweeps), restated as one bucket family.
SCENARIO_MIX: Sequence[Scenario] = (
    Scenario("ib/shell_probe", 0.55, "interactive", steps=1),
    Scenario("navier_stokes/cavity_ack", 0.25, "interactive", steps=2),
    Scenario("adv_diff/batch_sweep", 0.15, "batch", steps=4),
    Scenario("ibfe/campaign_chunk", 0.05, "batch", steps=8),
)


@dataclass(frozen=True)
class Arrival:
    """One scheduled submission: virtual time + the request to send."""
    t: float
    scenario: str
    request: ScenarioRequest


def poisson_burst_schedule(seed: int, duration_s: float,
                           rate_rps: float,
                           burst_factor: float = 4.0,
                           burst_start_frac: float = 0.4,
                           burst_len_frac: float = 0.3,
                           mix: Sequence[Scenario] = SCENARIO_MIX,
                           n_cells: int = 8, n_lat: int = 6,
                           n_lon: int = 8,
                           tenants_per_class: int = 2,
                           tenant_prefix: str = "",
                           mix_schedule: Optional[Sequence] = None) -> list:
    """Seeded Poisson arrivals over ``[0, duration_s)`` virtual
    seconds at ``rate_rps``, multiplied by ``burst_factor`` inside the
    burst window (``[start_frac, start_frac + len_frac) * duration``).
    Deterministic: a pure function of the arguments.

    ``mix_schedule`` (PR 18) makes the mix PIECEWISE in virtual time:
    a sequence of ``(start_frac, mix)`` pairs, each active from
    ``start_frac * duration_s`` until the next — the mix-shift soak
    rotates arrivals onto unseen families mid-run this way. ``None``
    (the default) uses ``mix`` throughout, and the rng draw sequence
    is unchanged: single-mix schedules replay bit-for-bit against
    pre-PR-18 seeds."""
    rng = np.random.default_rng(int(seed))
    if mix_schedule is None:
        segments = [(0.0, tuple(mix))]
    else:
        segments = sorted(((float(f), tuple(m))
                           for f, m in mix_schedule),
                          key=lambda seg: seg[0])
        if not segments or segments[0][0] > 0.0:
            segments.insert(0, (0.0, tuple(mix)))
    seg_weights = []
    for _, m in segments:
        w = np.asarray([s.weight for s in m], dtype=float)
        seg_weights.append(w / w.sum())
    b0 = burst_start_frac * duration_s
    b1 = b0 + burst_len_frac * duration_s
    arrivals: list = []
    t = 0.0
    k = 0
    while True:
        rate = rate_rps * (burst_factor if b0 <= t < b1 else 1.0)
        t += float(rng.exponential(1.0 / max(rate, 1e-9)))
        if t >= duration_s:
            break
        active = 0
        for si, (frac, _) in enumerate(segments):
            if t >= frac * duration_s:
                active = si
        seg_mix, weights = segments[active][1], seg_weights[active]
        sc = seg_mix[int(rng.choice(len(seg_mix), p=weights))]
        fam = dict(sc.family) if sc.family else {}
        tenant = (f"{tenant_prefix}{sc.tenant_class}"
                  f"-{k % max(tenants_per_class, 1)}")
        arrivals.append(Arrival(
            t=t, scenario=sc.name,
            request=ScenarioRequest(
                tenant=tenant,
                n_cells=fam.get("n_cells", n_cells),
                n_lat=fam.get("n_lat", n_lat),
                n_lon=fam.get("n_lon", n_lon),
                steps=sc.steps, dt=sc.dt,
                engine=fam.get("engine"),
                spectral_dtype=fam.get("spectral_dtype"),
                mu=fam.get("mu", 0.05),
                tenant_class=sc.tenant_class,
                deadline_s=sc.deadline_s)))
        k += 1
    return arrivals


# ---------------------------------------------------------------------------
# open-loop driver
# ---------------------------------------------------------------------------


def run_open_loop(router: WarmPoolRouter, arrivals: Sequence[Arrival],
                  time_scale: float = 1.0, max_threads: int = 32,
                  join_timeout_s: float = 120.0) -> dict:
    """Fire ``arrivals`` at the router open-loop: each submission at
    ``t * time_scale`` wall seconds after start, from its own bounded
    worker thread, independent of earlier completions. Returns
    ``{"results": [RequestResult...], "wall_s", "overruns",
    "hung_threads"}`` — ``hung_threads > 0`` means a worker failed to
    finish inside ``join_timeout_s`` (the soak drill's deadlock
    tripwire); ``overruns`` counts submissions that could not start on
    schedule because all workers were busy (they still run, late)."""
    results: list = []
    errors: list = []
    lock = threading.Lock()
    gate = threading.Semaphore(int(max_threads))
    overruns = [0]
    t0 = time.perf_counter()

    def fire(arr: Arrival):
        try:
            out = router.serve([arr.request])
            with lock:
                results.extend(out)
        except Exception as e:  # noqa: BLE001 - counted, not fatal
            with lock:
                errors.append(f"{type(e).__name__}: {e}")
        finally:
            gate.release()

    threads = []
    for arr in arrivals:
        delay = arr.t * time_scale - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        if not gate.acquire(blocking=False):
            overruns[0] += 1
            gate.acquire()          # open loop saturated: run late
        th = threading.Thread(target=fire, args=(arr,), daemon=True)
        th.start()
        threads.append(th)
    deadline = time.monotonic() + join_timeout_s
    hung = 0
    for th in threads:
        th.join(max(deadline - time.monotonic(), 0.0))
        if th.is_alive():
            hung += 1
    return {"results": results, "errors": errors,
            "wall_s": time.perf_counter() - t0,
            "overruns": overruns[0], "hung_threads": hung}


def _quantile(values, q):
    if not values:
        return None
    vs = sorted(values)
    import math
    return vs[min(len(vs) - 1, max(0, math.ceil(q * len(vs)) - 1))]


def traffic_summary(results, wall_s: float) -> dict:
    """Per-class traffic rollup of a result list: completed/shed/
    quarantined counts, shed rate, warm first-step and queue-wait
    percentiles — the shape the soak artifact and the bench ``--soak``
    grid carry."""
    total = len(results)
    shed = [r for r in results if r.shed]
    served = [r for r in results if not r.shed]
    by_reason: dict = {}
    for r in shed:
        by_reason[r.shed_reason] = by_reason.get(r.shed_reason, 0) + 1
    classes: dict = {}
    for r in results:
        # RequestResult has no class field; recover it from shed
        # records vs served tenants (tenant names are class-prefixed
        # by the schedule generator)
        cls = r.tenant.rsplit("-", 1)[0]
        c = classes.setdefault(cls, {"submitted": 0, "completed": 0,
                                     "shed": 0, "quarantined": 0,
                                     "retried": 0})
        c["submitted"] += 1
        if r.shed:
            c["shed"] += 1
        else:
            c["completed"] += 1
        if r.quarantined:
            c["quarantined"] += 1
        if r.retries:
            c["retried"] += 1
    warm_first = [r.first_step_s for r in served
                  if not r.cold and r.first_step_s is not None]
    qwaits = [r.queue_wait_s for r in results
              if r.queue_wait_s is not None]
    return {
        "submitted": total,
        "completed": len(served),
        "ok": sum(1 for r in served if r.ok),
        "shed": len(shed),
        "shed_rate": round(len(shed) / total, 4) if total else None,
        "shed_by_reason": by_reason,
        "quarantined": sum(1 for r in results if r.quarantined),
        "retried": sum(1 for r in results if r.retries),
        "requests_per_s": (round(len(served) / wall_s, 3)
                           if wall_s > 0 else None),
        "warm_first_step_p50_s": _round(_quantile(warm_first, 0.5)),
        "warm_first_step_p99_s": _round(_quantile(warm_first, 0.99)),
        "queue_wait_p99_s": _round(_quantile(qwaits, 0.99)),
        "classes": classes,
    }


def _round(v, nd: int = 6):
    return None if v is None else round(float(v), nd)


# ---------------------------------------------------------------------------
# the bounded soak drill (tools/slo.py check --soak, bench.py --soak)
# ---------------------------------------------------------------------------

# Committed soak policies: interactive traffic is slot-bounded with a
# strict-ish deadline and one retry; batch traffic queues deeper and
# waits longer. The drill ships these so the gate measures the SAME
# admission behavior every round.
SOAK_POLICIES = {
    "interactive": TenantClassPolicy(
        max_inflight=4, queue_depth=16, queue_timeout_s=30.0,
        deadline_s=30.0, retry_budget=1),
    "batch": TenantClassPolicy(
        max_inflight=2, queue_depth=8, queue_timeout_s=60.0,
        deadline_s=60.0, retry_budget=1),
    "chaos": TenantClassPolicy(
        max_inflight=2, queue_depth=2, queue_timeout_s=5.0,
        deadline_s=5.0, retry_budget=1),
}


def soak_drill(seed: int = 0, duration_s: float = 6.0,
               rate_rps: float = 6.0, burst_factor: float = 4.0,
               n_cells: int = 8, n_lat: int = 6, n_lon: int = 8,
               lanes: int = 2, cache_dir: Optional[str] = None,
               time_scale: float = 1.0,
               policies: Optional[dict] = None,
               mix: Sequence[Scenario] = SCENARIO_MIX,
               router: Optional[WarmPoolRouter] = None,
               warm: bool = True) -> dict:
    """One bounded deterministic CPU soak: a fresh router (unless one
    is injected) with the committed :data:`SOAK_POLICIES`, pre-warmed,
    driven open-loop by a seeded Poisson + ``burst_factor``x burst
    schedule over the heavy-tailed mix. Returns the traffic summary
    plus config echo; with a ledger attached
    (``obs.ledger(path)``), the soak SLIs are computable from the
    ledger alone (``tools/slo.py soak_slis_from_ledger``)."""
    from ibamr_tpu.serve import aot_cache

    if router is None:
        spec = BucketSpec(n_cells=n_cells, n_lat=n_lat, n_lon=n_lon,
                          lanes=lanes, chunk_steps=2)
        router = WarmPoolRouter(
            [spec], cache=aot_cache.ExecutableCache(directory=cache_dir),
            allow_dynamic=True,
            policies=dict(policies if policies is not None
                          else SOAK_POLICIES))
        if warm:
            with _obs.span("soak/warm"):
                router.warm(spec)
    arrivals = poisson_burst_schedule(
        seed=seed, duration_s=duration_s, rate_rps=rate_rps,
        burst_factor=burst_factor, mix=mix, n_cells=n_cells,
        n_lat=n_lat, n_lon=n_lon)
    with _obs.span("soak/open_loop", arrivals=len(arrivals)):
        run = run_open_loop(router, arrivals, time_scale=time_scale)
    # shed requests can leave bucket builds in flight; drain them so
    # a soak child process exits cleanly (a daemon thread mid-compile
    # at interpreter teardown aborts the process)
    router.drain_builds(timeout_s=60.0)
    out = traffic_summary(run["results"], run["wall_s"])
    out.update({
        "seed": int(seed), "duration_s": duration_s,
        "rate_rps": rate_rps, "burst_factor": burst_factor,
        "arrivals": len(arrivals), "wall_s": round(run["wall_s"], 3),
        "overruns": run["overruns"], "hung_threads": run["hung_threads"],
        "loadgen_errors": run["errors"][:5],
    })
    return out


def chaos_mix(base: Sequence[Scenario] = SCENARIO_MIX,
              novel_families: int = 3) -> list:
    """The chaos tenant's mix: the base mix re-classed to ``chaos``
    plus requests that will land on NOVEL dynamic families (distinct
    ``n_lon``), each a fresh bucket compile — the compile-storm fuel.
    Returned scenarios carry ``steps`` tags the schedule generator
    maps onto distinct families via :func:`chaos_requests`."""
    out = [replace(s, tenant_class="chaos", weight=s.weight * 0.5)
           for s in base]
    for i in range(novel_families):
        out.append(Scenario(f"chaos/novel_family_{i}",
                            weight=0.5 / max(novel_families, 1),
                            tenant_class="chaos", steps=1))
    return out
