"""AOT executable cache: hash-consing whole compiled executables.

At the north star's service scale, compilation IS the latency: the
flagship bench stages pay 91-160 s of ``compile_warmup_s`` against a
~97 ms warm step. This module generalizes the ``SpectralPlan``
hash-cons (``solvers/spectral_plan.py:get_plan``) from FFT symbol
tables to whole compiled step executables, in three layers:

- **in-memory LRU** — :class:`ExecutableCache`: process-local, holds
  live ``jax.stages.Compiled`` objects keyed on the scenario-family
  digest (:func:`cache_key` of the flight-recorder fingerprint: config
  digest, integrator spec, RESOLVED engine, spectral_dtype, mesh, x64
  mode, platform — plus the lowered argument signature, so shape
  families can never collide even under an opaque integrator spec).
- **JAX persistent compilation cache**
  (:func:`enable_persistent_cache`) — the cross-process/cluster layer:
  a miss in a fresh process still re-traces and re-lowers, but XLA's
  backend compile (the expensive part) is served from disk, so a
  scenario family compiles once per cluster ever.
- **manifest sidecars** — one digest-protected ``<dir>/<key>.json``
  per entry: records the fingerprint + compile seconds, letting a
  fresh process distinguish a true cold compile from a
  persistent-cache load. A manifest whose digest does not verify is
  REFUSED — counted, deleted, and the entry recompiled from scratch; a
  poisoned manifest can misattribute an executable to the wrong
  scenario family, so corruption never loads.

Every hit/miss/eviction is twinned onto the telemetry bus
(``aot_cache_*_total`` counters) and, when a run ledger is attached,
emitted as an ``aot_cache`` ledger record with the compile seconds —
the per-run warm-pool efficacy record ``tools/obs.py summary`` renders.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ibamr_tpu import obs as _obs
from ibamr_tpu.utils.flight_recorder import canonicalize

MANIFEST_SCHEMA = 1
_DEFAULT_CAPACITY = 16

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

_HITS = _obs.counter("aot_cache_hits_total")
_MISSES = _obs.counter("aot_cache_misses_total")
_EVICTS = _obs.counter("aot_cache_evictions_total")
_CORRUPT = _obs.counter("aot_cache_corrupt_total")
_WAITS = _obs.counter("aot_cache_inflight_waits_total")
# build-time distribution, split by where the backend compile came
# from: source="compile" (true cold build) vs "persistent" (XLA's disk
# cache served it — the load-time tail the persistent layer exists for)
_H_BUILD = {s: _obs.histogram("aot_cache_build_seconds", source=s)
            for s in ("compile", "persistent")}
_obs.describe("aot_cache_hits_total",
              "In-process executable-cache hits.")
_obs.describe("aot_cache_misses_total",
              "Executable-cache misses (one AOT build each).")
_obs.describe("aot_cache_build_seconds",
              "Executable build wall time on a miss, by "
              "source=compile|persistent.")
_obs.describe("aot_cache_bytes",
              "Estimated bytes of compiled code held by the "
              "executable cache (the brownout watermark input).")
_obs.describe("aot_cache_released_total",
              "Entries explicitly released (elastic pool shrink), "
              "distinct from LRU/bytes-ceiling evictions.")

# fingerprint fields that determine the compiled executable — the
# "scenario family". Everything else in the fingerprint (rng keys,
# injectors, numpy version, ...) is run identity, not compile identity.
KEY_FIELDS = ("config_digest", "integrator", "engine", "spectral_dtype",
              "mesh", "mesh_shape", "x64", "platform", "device_count",
              "jax_version")


def cache_key(fingerprint: dict, extra: Optional[dict] = None) -> str:
    """16-hex scenario-family key: sha256 of the canonicalized stable
    subset (:data:`KEY_FIELDS`) of a flight-recorder fingerprint, plus
    any ``extra`` material (argument signatures, chunk length, lane
    count). Canonicalization makes the key insertion-order invariant —
    pinned by tests/test_fingerprint_canonical.py."""
    material = {k: fingerprint.get(k) for k in KEY_FIELDS}
    if extra:
        material["extra"] = extra
    blob = json.dumps(canonicalize(material), sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def arg_signature(args) -> list:
    """(shape, dtype) per leaf of an argument pytree — cache-key
    material guaranteeing an executable is only ever served to the
    aval family it was lowered for."""
    import jax

    return [[list(getattr(a, "shape", ())),
             str(getattr(a, "dtype", type(a).__name__))]
            for a in jax.tree_util.tree_leaves(args)]


def step_fingerprint(integ, *, spec: Optional[dict] = None,
                     extra: Optional[dict] = None) -> dict:
    """Flight-recorder fingerprint of an integrator outside any driver
    run — the cache's key source. Carries the RESOLVED engine
    (``ib.engine_name``), spectral dtype, x64 mode, platform and device
    count exactly as :meth:`FlightRecorder.fingerprint` defines them."""
    from ibamr_tpu.utils.flight_recorder import FlightRecorder

    rec = FlightRecorder(capacity=1, spec=spec, extra_fingerprint=extra)
    rec.observe(integ=integ)
    return rec.fingerprint()


def enable_persistent_cache(jax=None, directory: Optional[str] = None,
                            min_compile_secs: float = 2.0):
    """Wire JAX's persistent compilation cache — the cross-process
    layer: a scenario family's XLA backend compile happens once per
    cluster ever. Directory: ``directory`` arg, else
    ``$IBAMR_COMPILE_CACHE``, else ``<repo>/.jax_cache``. Returns the
    cache dir, or None when unavailable (never fatal: serving without
    the disk layer is slow, not wrong)."""
    try:
        if jax is None:
            import jax
        d = directory or os.environ.get(
            "IBAMR_COMPILE_CACHE",
            os.path.join(REPO_ROOT, ".jax_cache"))
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
        return d
    except Exception:
        return None


def estimate_executable_bytes(executable) -> int:
    """Best-effort compiled-size estimate for the bytes watermark:
    XLA's ``memory_analysis`` generated-code size when the backend
    exposes it, else the serialized HLO text length (a stable proxy —
    bigger graphs compile to more code). 0 only when the executable
    exposes neither; the watermark degrades to count-only LRU then."""
    try:
        ma = executable.memory_analysis()
        size = int(getattr(ma, "generated_code_size_in_bytes", 0) or 0)
        if size > 0:
            return size
    except Exception:  # noqa: BLE001 - estimate, never fatal
        pass
    try:
        return len(executable.as_text())
    except Exception:  # noqa: BLE001
        return 0


@dataclass
class CacheEntry:
    """One cached executable + its accounting record."""
    key: str
    executable: Any                  # jax.stages.Compiled (opaque here)
    fingerprint: dict = field(default_factory=dict)
    compile_s: float = 0.0
    label: str = ""
    hits: int = 0
    built_at: float = 0.0
    # "compile" = true cold build; "persistent" = a valid manifest
    # pre-existed, so XLA's disk cache served the backend compile
    cold_source: str = "compile"
    # estimated compiled-code bytes (the aot_cache_bytes watermark)
    size_bytes: int = 0


class _InFlight:
    """Build-once latch for concurrent get-or-compile on one key."""

    __slots__ = ("event", "entry", "error")

    def __init__(self):
        self.event = threading.Event()
        self.entry = None
        self.error = None


class ExecutableCache:
    """Hash-cons LRU of compiled executables (the spectral-plan cache
    pattern, generalized). ``get_or_compile`` guarantees at most ONE
    build per key regardless of concurrency: the first caller compiles
    outside the lock, every other caller for that key waits on the
    in-flight latch and shares the published entry."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY,
                 directory: Optional[str] = None,
                 max_bytes: Optional[int] = None):
        if capacity < 1:
            raise ValueError(
                f"ExecutableCache.capacity must be >= 1, got {capacity!r}")
        self.capacity = int(capacity)
        self.directory = directory
        # optional bytes ceiling on ESTIMATED compiled size: evicts
        # LRU-first until under, on top of the count LRU. None (the
        # default) preserves count-only behavior exactly.
        self.max_bytes = None if max_bytes is None else int(max_bytes)
        if directory:
            os.makedirs(directory, exist_ok=True)
        self._entries: "OrderedDict[str, CacheEntry]" = OrderedDict()
        self._inflight: dict = {}
        self._lock = threading.Lock()
        self._stats = {"hits": 0, "misses": 0, "evictions": 0,
                       "corrupt": 0, "inflight_waits": 0,
                       "released": 0, "bytes": 0}

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def keys(self) -> list:
        with self._lock:
            return list(self._entries)

    def get(self, key: str) -> Optional[CacheEntry]:
        """Peek an entry WITHOUT touching stats or LRU order."""
        with self._lock:
            return self._entries.get(key)

    def bytes(self) -> int:
        """Estimated bytes of compiled code currently held."""
        with self._lock:
            return int(self._stats["bytes"])

    def release(self, keys) -> int:
        """Explicitly drop entries (elastic pool shrink): counted as
        ``released``, not evictions, so the LRU-pressure signal stays
        honest. Returns how many entries were actually held."""
        dropped = 0
        with self._lock:
            for key in ([keys] if isinstance(keys, str) else keys):
                ent = self._entries.pop(key, None)
                if ent is None:
                    continue
                dropped += 1
                self._stats["released"] += 1
                self._stats["bytes"] = max(
                    0, self._stats["bytes"] - ent.size_bytes)
                _obs.counter("aot_cache_released_total").inc()
                _obs.emit("aot_cache", event="release", key=key,
                          label=ent.label)
            self._set_bytes_gauge_locked()
        return dropped

    def set_max_bytes(self, max_bytes: Optional[int]) -> int:
        """Adjust the bytes ceiling at runtime (the memory-pressure
        injector's seam) and evict LRU-first until under it. Returns
        how many entries were evicted by the squeeze."""
        with self._lock:
            self.max_bytes = (None if max_bytes is None
                              else int(max_bytes))
            return self._evict_over_limits_locked()

    def _evict_over_limits_locked(self) -> int:
        evicted = 0
        while self._entries and (
                len(self._entries) > self.capacity
                or (self.max_bytes is not None
                    and self._stats["bytes"] > self.max_bytes)):
            old_key, old = self._entries.popitem(last=False)
            self._stats["evictions"] += 1
            self._stats["bytes"] = max(
                0, self._stats["bytes"] - old.size_bytes)
            evicted += 1
            _EVICTS.inc()
            _obs.emit("aot_cache", event="evict", key=old_key,
                      label=old.label)
        self._set_bytes_gauge_locked()
        return evicted

    def _set_bytes_gauge_locked(self) -> None:
        _obs.gauge("aot_cache_bytes").set(float(self._stats["bytes"]))

    def clear(self) -> None:
        """Drop every entry and zero the stats (tests; manifests on
        disk are left alone — they describe the persistent layer)."""
        with self._lock:
            self._entries.clear()
            self._inflight.clear()
            for k in self._stats:
                self._stats[k] = 0
            self._set_bytes_gauge_locked()

    # -- the hash-cons ------------------------------------------------------

    def get_or_compile(self, fingerprint, build: Callable[[], Any], *,
                       extra: Optional[dict] = None,
                       label: str = "") -> CacheEntry:
        """One executable per scenario family. ``fingerprint`` is a
        flight-recorder fingerprint dict (keyed via :func:`cache_key`
        with ``extra``) or a pre-computed key string. ``build()``
        returns the compiled executable (typically
        ``jax.jit(fn).lower(*args).compile()``); it runs OUTSIDE the
        cache lock, under a ``serve/compile`` span."""
        key = (fingerprint if isinstance(fingerprint, str)
               else cache_key(fingerprint, extra=extra))
        while True:
            with self._lock:
                ent = self._entries.get(key)
                if ent is not None:
                    self._entries.move_to_end(key)
                    ent.hits += 1
                    self._stats["hits"] += 1
                    _HITS.inc()
                    _obs.emit("aot_cache", event="hit", key=key,
                              label=label or ent.label)
                    return ent
                flight = self._inflight.get(key)
                if flight is None:
                    flight = _InFlight()
                    self._inflight[key] = flight
                    break                       # we are the builder
                self._stats["inflight_waits"] += 1
            # someone else is compiling this key: wait off-lock, then
            # re-enter — the published entry reads as a hit
            _WAITS.inc()
            flight.event.wait()
            if flight.error is not None:
                raise flight.error

        manifest = self._read_manifest(key)
        t0 = time.perf_counter()
        try:
            with _obs.span("serve/compile", key=key, label=label):
                executable = build()
        except Exception as e:
            with self._lock:
                flight.error = e
                self._inflight.pop(key, None)
            flight.event.set()
            raise
        compile_s = time.perf_counter() - t0
        entry = CacheEntry(
            key=key, executable=executable,
            fingerprint=(canonicalize(fingerprint)
                         if isinstance(fingerprint, dict) else {}),
            compile_s=compile_s, label=label, built_at=time.time(),
            cold_source="persistent" if manifest else "compile",
            size_bytes=estimate_executable_bytes(executable))
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            self._stats["misses"] += 1
            self._stats["bytes"] += entry.size_bytes
            self._evict_over_limits_locked()
            flight.entry = entry
            self._inflight.pop(key, None)
        _MISSES.inc()
        _H_BUILD[entry.cold_source].observe(compile_s)
        _obs.emit("aot_cache", event="miss", key=key, label=label,
                  compile_s=round(compile_s, 3),
                  cold_source=entry.cold_source,
                  size_bytes=entry.size_bytes)
        self._write_manifest(entry)
        flight.event.set()
        return entry

    # -- manifest sidecars --------------------------------------------------

    def manifest_path(self, key: str) -> Optional[str]:
        if not self.directory:
            return None
        return os.path.join(self.directory, f"{key}.json")

    def _write_manifest(self, entry: CacheEntry) -> None:
        path = self.manifest_path(entry.key)
        if path is None:
            return
        body = {"manifest_schema": MANIFEST_SCHEMA, "key": entry.key,
                "fingerprint": entry.fingerprint,
                "compile_s": round(entry.compile_s, 3),
                "built_at": entry.built_at, "label": entry.label}
        blob = json.dumps(canonicalize(body), sort_keys=True)
        doc = {"digest": hashlib.sha256(blob.encode()).hexdigest(),
               "body": body}
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            # a failed sidecar write costs the next process one
            # cold-source misattribution, never correctness
            try:
                os.remove(tmp)
            except OSError:
                pass

    def _read_manifest(self, key: str) -> Optional[dict]:
        """Digest-verified manifest body, or None (absent OR corrupt).
        A mismatched digest is REFUSED — counted, the file deleted, the
        caller recompiles. Corruption never loads."""
        path = self.manifest_path(key)
        if path is None or not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                doc = json.load(f)
            body = doc["body"]
            blob = json.dumps(canonicalize(body), sort_keys=True)
            if (doc.get("digest")
                    != hashlib.sha256(blob.encode()).hexdigest()):
                raise ValueError("manifest digest mismatch")
            if body.get("key") != key:
                raise ValueError("manifest key mismatch")
            if body.get("manifest_schema") != MANIFEST_SCHEMA:
                raise ValueError("unknown manifest schema")
            return body
        except Exception as e:  # noqa: BLE001 - refusal, not death
            with self._lock:
                self._stats["corrupt"] += 1
            _CORRUPT.inc()
            _obs.emit("aot_cache", event="corrupt", key=key,
                      error=f"{type(e).__name__}: {e}")
            try:
                os.remove(path)
            except OSError:
                pass
            return None

    def published_keys(self) -> list:
        """Keys with a VALID manifest on disk (the persistent layer's
        directory listing; corrupt sidecars are excluded and reaped)."""
        if not self.directory:
            return []
        out = []
        for name in sorted(os.listdir(self.directory)):
            if not name.endswith(".json") or name.count(".") != 1:
                continue
            key = name[:-len(".json")]
            if self._read_manifest(key) is not None:
                out.append(key)
        return out


# -- module-default cache (the spectral-plan module-cache idiom) ------------

_default_cache: Optional[ExecutableCache] = None
_default_lock = threading.Lock()


def get_cache() -> ExecutableCache:
    """The process-default executable cache. Manifest sidecars go to
    ``$IBAMR_AOT_CACHE`` when set (memory-only otherwise — the JAX
    persistent cache is wired separately via
    :func:`enable_persistent_cache`)."""
    global _default_cache
    with _default_lock:
        if _default_cache is None:
            _default_cache = ExecutableCache(
                directory=os.environ.get("IBAMR_AOT_CACHE") or None)
        return _default_cache


def executable_cache_stats() -> dict:
    """Hit/miss/eviction counts of the default cache (bench stages
    report per-stage deltas of these as ``cache_hits``/
    ``cache_misses``)."""
    return get_cache().stats()


def clear_executable_cache() -> None:
    """Reset the default cache (tests)."""
    get_cache().clear()


# -- AOT step helpers -------------------------------------------------------

def step_callable(integ, *, donate: bool = True,
                  with_stats: bool = False):
    """The exact python callable + donate_argnums the cache lowers for
    an integrator step. The bench census traces THIS callable (a
    ``jax.stages.Compiled`` cannot be re-traced), so the roofline
    sidecar always describes the same graph the cache serves."""
    base = integ.step_with_stats if with_stats else integ.step
    return base, ((0,) if donate else ())


def aot_compile(fn, args, donate_argnums=()):
    """``jax.jit(fn).lower(*args).compile()`` — the AOT build every
    cache entry holds."""
    import jax

    return jax.jit(fn, donate_argnums=tuple(donate_argnums)) \
        .lower(*args).compile()


def cached_step(integ, state, dt, *, donate: bool = True,
                with_stats: bool = False, spec: Optional[dict] = None,
                extra: Optional[dict] = None,
                cache: Optional[ExecutableCache] = None,
                label: str = ""):
    """Get-or-AOT-compile the integrator step for ``state``'s aval
    family through the executable cache. Returns ``(callable, entry)``
    where the callable has the jitted-step calling convention
    (``new_state = f(state, dt)``, or ``(new_state, stats)`` with
    ``with_stats``)."""
    cache = cache if cache is not None else get_cache()
    fp = step_fingerprint(integ, spec=spec)
    fn, dn = step_callable(integ, donate=donate, with_stats=with_stats)
    key_extra = {"kind": "step", "donate": bool(donate),
                 "with_stats": bool(with_stats),
                 "args": arg_signature((state, dt))}
    if extra:
        key_extra.update(extra)
    entry = cache.get_or_compile(
        fp, lambda: aot_compile(fn, (state, dt), dn),
        extra=key_extra, label=label or "step")
    return entry.executable, entry
