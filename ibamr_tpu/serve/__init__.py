"""Serving layer: AOT executable cache + warm-pool scenario router.

``aot_cache`` generalizes the SpectralPlan hash-cons
(solvers/spectral_plan.py:get_plan) from FFT symbol tables to whole
compiled executables; ``router`` packs scenario requests into
pre-compiled fleet-lane buckets on top of it; ``loadgen`` drives the
router with deterministic open-loop traffic; ``autoscale`` closes the
loop from observed traffic to warm capacity (elastic pools, brownout
degradation, crash-safe restart) and ``capacity`` predicts what that
loop can sustain. See docs/SERVING.md.
"""
