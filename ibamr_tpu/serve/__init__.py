"""Serving layer: AOT executable cache + warm-pool scenario router.

``aot_cache`` generalizes the SpectralPlan hash-cons
(solvers/spectral_plan.py:get_plan) from FFT symbol tables to whole
compiled executables; ``router`` packs scenario requests into
pre-compiled fleet-lane buckets on top of it. See docs/SERVING.md.
"""
