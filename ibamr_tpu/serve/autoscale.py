"""Elastic warm pools: traffic-driven autoscaling, brownout
degradation, and crash-safe router restart (PR 18, docs/SERVING.md
"Elastic pools & brownout").

PR 17 gave the router admission control over a FIXED set of bucket
families; this module closes the loop from observed traffic to warm
capacity, in four legs:

- **traffic-driven scaling** — :class:`MixEstimator` folds the
  router's ``request_admit`` stream into per-family EWMA arrival
  shares over deterministic virtual-time windows (a pure function of
  the ``(family, t)`` stream — no wall clock enters the estimate, so
  the same schedule replays the same decisions).
  :class:`ElasticPoolManager` grows hot families by pre-compiling
  them ASYNCHRONOUSLY through the PR-11 ``ExecutableCache`` build
  threads — serving never stalls on a grow, and a family is routable
  only once its pool is warm — and shrinks cold families under
  hysteresis (min-dwell since last arrival, never a family with a
  batch in flight), releasing their executables and bytes. Every
  decision is a ``pool_scale`` ledger record carrying the reason and
  the mix snapshot that justified it.
- **brownout degradation** — a pressure signal (queue-wait p99 from
  the live ``serve_queue_wait_seconds`` histogram delta + the
  precompile backlog + the executable-cache bytes watermark) moves
  the router through the explicit mode ladder ``healthy -> brownout
  -> shed_batch``: brownout caps batch-class cruise chunks to the
  already-compiled length-1 ack (degraded throughput, ZERO new
  compiles) and defers non-urgent pre-compiles; shed_batch sheds
  batch tenants with ``shed_reason="brownout"`` so interactive p99
  stays in band. Escalation is immediate, de-escalation waits out
  ``mode_min_dwell_s`` — the oscillation guard. Every transition is
  a ``serve_mode`` ledger record and the ``serve_mode`` gauge.
- **crash-safe restart** — :meth:`ElasticPoolManager.save_manifest`
  checkpoints the serving state (live families, tenant policies,
  scale-history digest) to ``serving_manifest.json`` via the PR-2
  atomic-write discipline (tmp + fsync + replace, digest-protected
  like the aot-cache sidecars); :func:`restore_serving_manifest`
  rebuilds a fresh router from it and re-warms the working set with
  BOUNDED concurrency (no cold storm) through the JAX persistent
  compilation cache — the restart drill pins first-warm-serve with
  zero fresh XLA compiles via the cache's ``cold_source`` manifest
  attribution.

The capacity model that predicts what this machinery can sustain
lives in :mod:`ibamr_tpu.serve.capacity`; the composed chaos drill is
``tools.fault_injection.run_elastic_smoke`` (dryrun path 22) and the
ceilings live in ``SLO.json`` ``elastic_slos`` (``tools/slo.py check
--elastic``).
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import threading
import time
from dataclasses import asdict, dataclass, replace
from typing import Callable, Optional, Sequence

from ibamr_tpu import obs as _obs
from ibamr_tpu.serve.router import (BucketSpec, TenantClassPolicy,
                                    WarmPoolRouter)
from ibamr_tpu.utils.checkpoint import _atomic_write

SERVING_MANIFEST_SCHEMA = 1

# The mode ladder, in escalation order. Gauge value = list index, so
# the watchdog heartbeat and SLO gate read modes without string labels.
MODES = ("healthy", "brownout", "shed_batch")

_obs.describe("serve_families_live",
              "Warm pool families currently routable.")
_obs.describe("serve_precompiles_inflight",
              "Async pool builds currently in flight (the precompile "
              "backlog leg of the brownout pressure signal).")
_obs.describe("serve_mode",
              "Degradation mode: 0=healthy, 1=brownout, 2=shed_batch.")
_obs.describe("serve_pool_scale_total",
              "Elastic scaling decisions, by action=grow|warmed|"
              "shrink|deferred.")


@dataclass(frozen=True)
class ScalePolicy:
    """The committed elastic policy: when to grow/shrink and where the
    brownout ladder trips. Enter thresholds sit strictly above exit
    thresholds (the hysteresis dead band), and every dwell is in the
    SAME virtual-time units the estimator observes."""
    # -- mix estimation ----------------------------------------------------
    window_s: float = 0.5          # virtual-time window length
    ewma_alpha: float = 0.5        # per-window EWMA smoothing
    # -- grow / shrink -----------------------------------------------------
    grow_share: float = 0.10       # mix share that makes a family hot
    grow_min_arrivals: int = 2     # arrivals before a grow can trigger
    shrink_share: float = 0.02     # mix share below which a family is cold
    min_dwell_s: float = 3.0       # virtual dwell before a shrink
    # absolute no-arrivals horizon after which a family is cold even
    # if its NORMALIZED share stays high (proportional EWMA decay
    # preserves relative shares when the whole stream goes quiet, so
    # share alone can never expire the last traffic pattern seen)
    idle_evict_s: float = 30.0
    max_live_families: int = 8
    # -- brownout ladder (enter > exit: the dead band) ---------------------
    brownout_queue_p99_s: float = 1.0
    brownout_exit_queue_p99_s: float = 0.25
    brownout_backlog: int = 2      # precompiles in flight
    brownout_exit_backlog: int = 0
    brownout_cache_frac: float = 0.90   # bytes / max_bytes watermark
    brownout_exit_cache_frac: float = 0.70
    shed_queue_p99_s: float = 4.0
    shed_backlog: int = 4
    mode_min_dwell_s: float = 1.0  # de-escalation dwell (virtual s)
    urgent_share: float = 0.20     # brownout still grows above this
    batch_classes: Sequence[str] = ("batch",)
    # -- restart -----------------------------------------------------------
    restore_concurrency: int = 2   # bounded re-warm (no cold storm)


class MixEstimator:
    """Windowed EWMA arrival-mix estimator over DETERMINISTIC virtual
    time: arrivals land in window ``floor(t / window_s)``; when an
    observation crosses a window boundary the completed window's
    per-family shares fold into the EWMA (empty windows decay it
    toward zero). A pure function of the observed ``(family, t)``
    stream — replaying a schedule replays the mix bit-for-bit."""

    def __init__(self, window_s: float = 0.5, alpha: float = 0.5):
        self.window_s = float(window_s)
        self.alpha = float(alpha)
        self._ewma: dict = {}
        self._win_idx: Optional[int] = None
        self._win_counts: dict = {}
        self._totals: dict = {}

    def advance(self, t: float) -> None:
        """Roll the window clock forward to ``t`` WITHOUT an arrival:
        completed windows flush, arrival-free windows decay every
        family toward zero — a family nobody asks for cools at the
        same deterministic rate it heated. Idle ticks call this, so
        shrink decisions do not need traffic to age the mix."""
        idx = int(math.floor(float(t) / self.window_s))
        if self._win_idx is None:
            self._win_idx = idx
            return
        if idx > self._win_idx:
            self._flush()
            for _ in range(idx - self._win_idx - 1):
                self._decay()
            self._win_idx = idx

    def observe(self, family, t: float) -> None:
        self.advance(t)
        # late/out-of-order observations fold into the current window
        self._win_counts[family] = self._win_counts.get(family, 0) + 1
        self._totals[family] = self._totals.get(family, 0) + 1

    def _flush(self) -> None:
        total = sum(self._win_counts.values())
        shares = ({f: c / total for f, c in self._win_counts.items()}
                  if total else {})
        for f in set(self._ewma) | set(shares):
            self._ewma[f] = ((1.0 - self.alpha) * self._ewma.get(f, 0.0)
                             + self.alpha * shares.get(f, 0.0))
        self._win_counts = {}

    def _decay(self) -> None:
        for f in list(self._ewma):
            self._ewma[f] *= (1.0 - self.alpha)

    def mix(self) -> dict:
        """Normalized family -> share, blending the EWMA with the
        current (partial) window so a fresh burst registers before its
        window closes. Families below 1e-6 are dropped."""
        total = sum(self._win_counts.values())
        cur = ({f: c / total for f, c in self._win_counts.items()}
               if total else {})
        raw = {}
        for f in set(self._ewma) | set(cur):
            raw[f] = ((1.0 - self.alpha) * self._ewma.get(f, 0.0)
                      + self.alpha * cur.get(f, 0.0))
        norm = sum(raw.values())
        if norm <= 0:
            return {}
        return {f: v / norm for f, v in raw.items() if v / norm > 1e-6}

    def arrivals(self, family) -> int:
        """Total arrivals ever observed for ``family``."""
        return self._totals.get(family, 0)


def _spec_dict(spec: BucketSpec) -> dict:
    return asdict(spec)


def _scale_digest(events: Sequence[dict]) -> str:
    blob = json.dumps(events, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class ElasticPoolManager:
    """Closes the loop from observed traffic to warm capacity (module
    docstring has the four legs). Attach one manager per router; the
    router calls :meth:`observe_admit` per admitted request and
    consults :meth:`should_shed` / :meth:`cruise_cap` on the
    admission and cruise paths.

    All decision state is guarded by one re-entrant lock; grow builds
    run on the router's async build threads (one watcher thread per
    grow awaits publication and emits the ``warmed`` record), so a
    scaling decision NEVER blocks the admitting request."""

    def __init__(self, router: WarmPoolRouter,
                 policy: Optional[ScalePolicy] = None,
                 manifest_path: Optional[str] = None,
                 pressure_fn: Optional[Callable[[], dict]] = None):
        self.router = router
        self.policy = policy or ScalePolicy()
        self.manifest_path = manifest_path
        # test seam: override the measured pressure signal with a
        # synthetic one (the brownout mode-matrix drill)
        self.pressure_fn = pressure_fn
        self.estimator = MixEstimator(self.policy.window_s,
                                      self.policy.ewma_alpha)
        self._lock = threading.RLock()
        self.mode = "healthy"
        self._mode_since = 0.0
        self._now = 0.0                  # latest virtual time seen
        self.transitions: list = []      # (t, from, to, reason)
        self.scale_events: list = []     # digest material
        self._last_active: dict = {}     # family -> last admit t
        self._grown_at: dict = {}        # family -> warm-publication t
        self._growing: dict = {}         # family -> decision t
        self._deferred: list = []        # (family, t) parked in brownout
        self._watchers: list = []
        # queue-wait baseline = the histogram AS OF construction, so a
        # manager built late in a process (restart drill) measures its
        # own traffic's pressure, not the previous router's history
        snap = _obs.metrics_snapshot()["histograms"].get(
            "serve_queue_wait_seconds")
        self._qwait_counts: Optional[list] = (
            None if snap is None else list(snap["counts"]))
        self._t0 = time.monotonic()
        router.manager = self
        self._set_gauges()

    # -- observation --------------------------------------------------------

    def observe_admit(self, request, t: Optional[float] = None,
                      trace_id: Optional[str] = None) -> None:
        """Fold one admitted request into the mix estimate and run a
        scaling/mode tick. ``t`` is virtual seconds (tests, drills);
        when omitted, monotonic seconds since manager creation — the
        estimator never reads a clock itself."""
        if t is None:
            t = time.monotonic() - self._t0
        family = request.family()
        with self._lock:
            self._now = max(self._now, float(t))
            self.estimator.observe(family, t)
            self._last_active[family] = float(t)
            self._tick_locked(float(t), trace_id)

    def tick(self, t: Optional[float] = None) -> None:
        """Run one scaling/mode tick without an arrival (drain paths,
        tests). Idle traffic still exits brownout this way."""
        if t is None:
            t = time.monotonic() - self._t0
        with self._lock:
            self._now = max(self._now, float(t))
            self.estimator.advance(float(t))
            self._tick_locked(float(t), None)

    def _tick_locked(self, t: float, trace_id: Optional[str]) -> None:
        self._update_mode(t, trace_id)
        mix = self.estimator.mix()
        live = self.router.live_families()
        growing = dict(self._growing)
        # -- grow: hot families not yet routable ---------------------------
        for family, share in sorted(mix.items(), key=lambda kv: -kv[1]):
            if family in live or family in growing:
                continue
            if share < self.policy.grow_share:
                continue
            if (self.estimator.arrivals(family)
                    < self.policy.grow_min_arrivals):
                continue
            seen = self._last_active.get(family)
            if seen is not None and \
                    t - seen >= self.policy.idle_evict_s:
                # a normalized share survives a quiet stream forever
                # (see idle_evict_s) — never grow on stale share alone
                continue
            if (len(live) + len(growing)
                    >= self.policy.max_live_families):
                break
            if (self.mode != "healthy"
                    and share < self.policy.urgent_share):
                # brownout defers non-urgent precompiles; the build
                # fires when the router de-escalates to healthy
                if family not in {f for f, _ in self._deferred}:
                    self._deferred.append((family, t))
                    self._emit_scale("deferred", family, t, "brownout",
                                     mix, trace_id)
                continue
            self._grow(family, t, "mix_shift", mix, trace_id)
            growing[family] = t
        # -- shrink: cold families past their dwell ------------------------
        for family, spec in live.items():
            if len(self.router.live_families()) <= 1:
                break                      # never scale to zero
            if self.router.family_inflight(family):
                continue                   # never the family serving now
            seen = max(self._last_active.get(family, 0.0),
                       self._grown_at.get(family, 0.0))
            if t - seen < self.policy.min_dwell_s:
                continue                   # hysteresis: min-dwell
            idle = (t - seen) >= self.policy.idle_evict_s
            if mix.get(family, 0.0) > self.policy.shrink_share \
                    and not idle:
                continue
            self._shrink(family, spec, t,
                         "idle_family" if idle else "cold_family",
                         mix, trace_id)
        self._set_gauges()

    # -- scaling ------------------------------------------------------------

    def _emit_scale(self, action: str, family, t: float, reason: str,
                    mix: dict, trace_id: Optional[str],
                    **extra) -> None:
        event = dict(action=action, family=str(family),
                     t=round(float(t), 4), reason=reason,
                     mix={str(f): round(s, 4) for f, s in mix.items()},
                     **extra)
        self.scale_events.append(event)
        _obs.counter("serve_pool_scale_total", action=action).inc()
        _obs.emit("pool_scale", trace_id=trace_id or None,
                  families_live=len(self.router.live_families()),
                  **event)

    def _grow(self, family, t: float, reason: str, mix: dict,
              trace_id: Optional[str]) -> None:
        spec = self.router._bucket_for(family,
                                       self.router.default_lanes)
        self._growing[family] = t
        self._emit_scale("grow", family, t, reason, mix, trace_id,
                         lanes=spec.lanes)
        wait = self.router._ensure_pool(
            spec, trace_ids=(trace_id,) if trace_id else ())
        t_wall = time.perf_counter()
        watcher = threading.Thread(
            target=self._await_grow,
            args=(family, spec, wait, t, t_wall, trace_id),
            daemon=True)
        self._watchers.append(watcher)
        watcher.start()

    def _await_grow(self, family, spec, wait, t_decided: float,
                    t_wall: float, trace_id: Optional[str]) -> None:
        """Grow watcher: awaits the async build's publication and
        stamps the family routable. Runs OFF the serving path — a
        failed build just clears the in-flight mark (the next hot
        tick retries)."""
        error = None
        try:
            wait()
        except Exception as e:  # noqa: BLE001 - retried by next tick
            error = f"{type(e).__name__}: {e}"
        warm_s = time.perf_counter() - t_wall
        with self._lock:
            self._growing.pop(family, None)
            if error is None:
                self._grown_at[family] = t_decided
            mix = self.estimator.mix()
            if error is None:
                self._emit_scale("warmed", family, t_decided,
                                 "build_done", mix, trace_id,
                                 warm_s=round(warm_s, 4))
            else:
                self._emit_scale("grow_failed", family, t_decided,
                                 "build_failed", mix, trace_id,
                                 error=error)
            self._set_gauges()

    def _shrink(self, family, spec, t: float, reason: str, mix: dict,
                trace_id: Optional[str]) -> None:
        released = self.router.release_pool(spec)
        # keep _last_active: arrival recency stays true across a
        # shrink, and the grow loop's stale-share guard needs it
        # (popping it would re-grow the family on the next tick)
        self._grown_at.pop(family, None)
        self._emit_scale("shrink", family, t, reason, mix, trace_id,
                         lanes=spec.lanes, released_entries=released)

    def drain(self, timeout_s: float = 60.0) -> int:
        """Join grow watchers + the router's build threads (process
        exit hygiene, same contract as ``router.drain_builds``)."""
        deadline = time.monotonic() + max(float(timeout_s), 0.0)
        alive = 0
        with self._lock:
            watchers = list(self._watchers)
        for w in watchers:
            w.join(max(deadline - time.monotonic(), 0.0))
            alive += int(w.is_alive())
        return alive + self.router.drain_builds(
            max(deadline - time.monotonic(), 0.0))

    # -- brownout ladder ----------------------------------------------------

    def pressure(self) -> dict:
        """The measured pressure signal: queue-wait p99 over the
        histogram DELTA since the last call (recent pressure, not
        process-lifetime), the precompile backlog, and the cache-bytes
        watermark fraction (0 when no ``max_bytes`` ceiling is set)."""
        snap = _obs.metrics_snapshot()["histograms"].get(
            "serve_queue_wait_seconds")
        p99 = 0.0
        if snap is not None:
            counts = list(snap["counts"])
            base = self._qwait_counts
            delta = (counts if base is None else
                     [int(a) - int(b) for a, b in zip(counts, base)])
            self._qwait_counts = counts
            if sum(delta) > 0:
                (p99,) = _obs.quantiles_from_counts(delta, [0.99])
        cache = self.router.cache
        frac = 0.0
        max_bytes = getattr(cache, "max_bytes", None)
        if max_bytes:
            frac = cache.bytes() / float(max_bytes)
        return {"queue_p99_s": float(p99),
                "backlog": self.router.build_backlog(),
                "cache_frac": float(frac)}

    def _target_mode(self, p: dict) -> str:
        pol = self.policy
        if (p["queue_p99_s"] >= pol.shed_queue_p99_s
                or p["backlog"] >= pol.shed_backlog):
            return "shed_batch"
        if (p["queue_p99_s"] >= pol.brownout_queue_p99_s
                or p["backlog"] >= pol.brownout_backlog
                or p["cache_frac"] >= pol.brownout_cache_frac):
            return "brownout"
        if (p["queue_p99_s"] <= pol.brownout_exit_queue_p99_s
                and p["backlog"] <= pol.brownout_exit_backlog
                and p["cache_frac"] <= pol.brownout_exit_cache_frac):
            return "healthy"
        # dead band (between brownout exit and entry): brownout holds,
        # but shed_batch steps down — pressure below the BROWNOUT
        # entry can never justify the harsher mode (monotonicity)
        if self.mode == "shed_batch":
            return "brownout"
        return self.mode          # inside the dead band: hold

    def _update_mode(self, t: float, trace_id: Optional[str]) -> None:
        p = (self.pressure_fn() if self.pressure_fn is not None
             else self.pressure())
        target = self._target_mode(p)
        cur, tgt = MODES.index(self.mode), MODES.index(target)
        if tgt > cur:
            nxt = MODES[cur + 1]       # escalate one rung, immediately
        elif tgt < cur:
            # de-escalation waits out the dwell: the oscillation guard
            if t - self._mode_since < self.policy.mode_min_dwell_s:
                return
            nxt = MODES[cur - 1]
        else:
            return
        prev, self.mode = self.mode, nxt
        self._mode_since = t
        self.transitions.append((round(float(t), 4), prev, nxt))
        _obs.emit("serve_mode", trace_id=trace_id or None,
                  t=round(float(t), 4), mode=nxt, prev=prev,
                  queue_p99_s=round(p["queue_p99_s"], 4),
                  backlog=int(p["backlog"]),
                  cache_frac=round(p["cache_frac"], 4))
        _obs.gauge("serve_mode").set(MODES.index(nxt))
        if nxt == "healthy" and self._deferred:
            deferred, self._deferred = self._deferred, []
            mix = self.estimator.mix()
            for family, _ in deferred:
                if (family not in self.router.live_families()
                        and family not in self._growing
                        and mix.get(family, 0.0)
                        >= self.policy.shrink_share):
                    self._grow(family, t, "deferred_resume", mix,
                               trace_id)

    # -- router consultation seams ------------------------------------------

    def should_shed(self, tenant_class: str) -> bool:
        """True when the current mode sheds this class pre-admission
        (``shed_reason="brownout"``): shed_batch sheds batch tenants;
        interactive traffic is never mode-shed."""
        return (self.mode == "shed_batch"
                and tenant_class in self.policy.batch_classes)

    def cruise_cap(self, tenant_classes: Sequence[str]) -> Optional[int]:
        """Chunk-length cap for a packed batch: under brownout (or
        worse) an all-batch batch cruises on the already-compiled
        length-1 ack chunk — degraded throughput, zero fresh compiles.
        Mixed batches keep full cruise (an interactive member must not
        pay the degradation)."""
        if self.mode == "healthy" or not tenant_classes:
            return None
        if all(c in self.policy.batch_classes for c in tenant_classes):
            return 1
        return None

    def _set_gauges(self) -> None:
        _obs.gauge("serve_families_live").set(
            len(self.router.live_families()))
        _obs.gauge("serve_precompiles_inflight").set(
            self.router.build_backlog())
        _obs.gauge("serve_mode").set(MODES.index(self.mode))

    # -- crash-safe restart --------------------------------------------------

    def manifest(self) -> dict:
        """The serving-state snapshot ``save_manifest`` persists: live
        families (full BucketSpecs), tenant policies, the mode, and a
        digest over the scale-event history (restore proves it resumed
        the same story, not a look-alike)."""
        with self._lock:
            live = self.router.live_specs()
            policies = {cls: asdict(pol) for cls, pol
                        in self.router.admission._policies.items()}
            return {
                "manifest_schema": SERVING_MANIFEST_SCHEMA,
                "families": [_spec_dict(s) for s in live],
                "policies": policies,
                "mode": self.mode,
                "scale_events": len(self.scale_events),
                "scale_digest": _scale_digest(self.scale_events),
                "cache_dir": getattr(self.router.cache, "directory",
                                     None),
                "saved_t": round(self._now, 4),
            }

    def save_manifest(self, path: Optional[str] = None) -> str:
        """Checkpoint the serving state to ``serving_manifest.json``:
        atomic tmp + fsync + replace (PR-2 discipline) with a
        whole-body digest (the aot-cache sidecar discipline) — a torn
        or tampered manifest is refused at restore, never restored
        wrong."""
        path = path or self.manifest_path
        if not path:
            raise ValueError("no manifest path configured")
        body = self.manifest()
        blob = json.dumps(body, sort_keys=True)
        doc = {"digest": hashlib.sha256(blob.encode()).hexdigest(),
               "body": body}
        payload = json.dumps(doc, indent=1, sort_keys=True).encode()
        _atomic_write(path, lambda f: f.write(payload))
        _obs.emit("serving_manifest", path=os.path.basename(path),
                  families=len(body["families"]),
                  scale_digest=body["scale_digest"])
        return path


def read_serving_manifest(path: str) -> dict:
    """Digest-verified manifest body. Raises ``ValueError`` on a torn,
    tampered, or wrong-schema manifest — corruption never restores."""
    with open(path) as f:
        doc = json.load(f)
    body = doc.get("body")
    if body is None:
        raise ValueError("serving manifest has no body")
    blob = json.dumps(body, sort_keys=True)
    if doc.get("digest") != hashlib.sha256(blob.encode()).hexdigest():
        raise ValueError("serving manifest digest mismatch")
    if body.get("manifest_schema") != SERVING_MANIFEST_SCHEMA:
        raise ValueError(
            f"unknown serving manifest schema "
            f"{body.get('manifest_schema')!r}")
    return body


def restore_serving_manifest(path: str, cache=None,
                             policy: Optional[ScalePolicy] = None,
                             concurrency: Optional[int] = None,
                             warm: bool = True):
    """Rebuild a router + manager from a serving manifest and re-warm
    the persisted working set with BOUNDED concurrency (at most
    ``concurrency`` builds in flight — a restart must not cold-storm
    the build executor). Returns ``(router, manager, stats)``; stats
    carries ``fresh_compiles`` (cache entries whose ``cold_source``
    was ``"compile"``) — the restart drill pins this to ZERO when the
    aot-cache manifests and JAX persistent cache survive the crash."""
    from ibamr_tpu.serve import aot_cache

    body = read_serving_manifest(path)
    specs = [BucketSpec(**f) for f in body["families"]]
    policies = {cls: TenantClassPolicy(**p)
                for cls, p in body["policies"].items()}
    if cache is None:
        cache = aot_cache.ExecutableCache(
            directory=body.get("cache_dir"))
    router = WarmPoolRouter(specs, cache=cache, policies=policies)
    manager = ElasticPoolManager(router, policy=policy,
                                 manifest_path=path)
    pol = manager.policy
    width = max(1, int(concurrency if concurrency is not None
                       else pol.restore_concurrency))
    t0 = time.perf_counter()
    errors: list = []
    if warm:
        with _obs.span("serve/restore", families=len(specs),
                       concurrency=width):
            for i in range(0, len(specs), width):
                waits = [router._ensure_pool(s)
                         for s in specs[i:i + width]]
                for w in waits:
                    try:
                        w()
                    except Exception as e:  # noqa: BLE001 - reported
                        errors.append(f"{type(e).__name__}: {e}")
    warm_s = time.perf_counter() - t0
    fresh = persistent = 0
    for key in cache.keys():
        ent = cache.get(key)
        if ent is None:
            continue
        if ent.cold_source == "compile":
            fresh += 1
        else:
            persistent += 1
    stats = {"families": len(specs),
             "warmed": len(router.live_specs()),
             "fresh_compiles": fresh,
             "persistent_loads": persistent,
             "warm_s": round(warm_s, 4),
             "concurrency": width,
             "scale_digest": body["scale_digest"],
             "errors": errors[:5]}
    manager._set_gauges()
    _obs.emit("serving_restore", **stats)
    return router, manager, stats
