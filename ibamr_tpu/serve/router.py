"""Warm-pool scenario router: scenario requests onto pre-compiled
fleet-lane buckets.

Request lifecycle (docs/SERVING.md):

1. **submit** — requests are grouped by scenario family (shape,
   engine, spectral dtype, physics constants baked into the closure).
2. **bucket** — each group is packed into the nearest declared
   ``(family, B)`` bucket: smallest ``B >= group size``; oversize
   groups split across batches; short groups are PADDED to ``B`` with
   copies of their last lane marked not-alive
   (:func:`ibamr_tpu.utils.lanes.pad_lanes`) — the fleet chunk's alive
   mask freezes padding in-graph.
3. **warm / miss** — a warm bucket serves immediately from its
   AOT-compiled lane chunks; a miss compiles ASYNCHRONOUSLY (one
   background build per bucket, published to the shared
   :class:`~ibamr_tpu.serve.aot_cache.ExecutableCache`) while the
   requests wait — the compile lands in the cold requests'
   request-to-first-step latency and nowhere else.
4. **run** — the pre-compiled chunk advances all lanes; per-lane
   finite health quarantines a bad tenant's lane (PR-7 ``jnp.where``
   freeze) without perturbing neighbours. Per-lane dt and the alive
   mask are TRACED arguments: heterogeneous requests never retrace.
5. **account** — every request emits a ``request`` ledger record
   (tenant, family key, bucket, lane, cold/warm, first-step and total
   latency, steps, verdict) plus ``serve_*_total`` counters.

The router runs only chunk lengths it pre-compiled (1 for the
first-step ack, ``chunk_steps`` for cruise), so a warm second request
of the same family performs ZERO compiles — pinned structurally by
``tools/serve.py check`` against SERVE_CONTRACT.json.

Traffic robustness (PR 17, docs/SERVING.md "Traffic & overload"):
step 1 above is now a real admission gate. Every request belongs to a
**tenant class** (``ScenarioRequest.tenant_class``) with a
:class:`TenantClassPolicy`: a bounded inflight-slot pool plus a
bounded wait queue (overflow or timeout SHEDS the request —
``serve_shed_total{reason=...}`` / a terminal ``request_shed`` ledger
record, queue time on ``serve_queue_wait_seconds``), an
admission-to-first-step **deadline budget** (enforced at the two
host-side wait points: the admission queue and the bucket-compile
wait; an ack chunk already in flight is never cancelled), and a
**retry budget** with deterministic jittered backoff for transient
failures (a failed or killed async pool build, a quarantined-lane
landing) so a compile storm cannot amplify itself. A quarantined or
shed request RELEASES its admission slot immediately and wakes one
queued waiter (``serve_slots_reclaimed_total``) — dead lanes return
capacity to waiting requests instead of draining the class dry. Every
admitted ``trace_id`` reaches exactly one terminal record kind
(``request`` or ``request_shed``) even when ``serve`` raises: the
no-lost-request invariant the soak drill
(``tools.fault_injection.run_soak_smoke``) pins.

Elastic pools (PR 18, docs/SERVING.md "Elastic pools & brownout"):
an optional :class:`~ibamr_tpu.serve.autoscale.ElasticPoolManager`
attaches as ``router.manager`` and closes the loop from the admit
stream to warm capacity — grow pre-compiles hot families async (the
family is routable only once warm), shrink releases cold pools via
:meth:`WarmPoolRouter.release_pool` (never a family with a batch in
flight — ``family_inflight``), and the brownout mode ladder caps
batch cruise chunks to the compiled length-1 ack and sheds batch
tenants pre-admission with ``shed_reason="brownout"``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ibamr_tpu import obs as _obs
from ibamr_tpu.serve import aot_cache
from ibamr_tpu.utils import lanes as _lanes

_REQS = _obs.counter("serve_requests_total")
_COLD = _obs.counter("serve_cold_requests_total")
_QUAR = _obs.counter("serve_quarantined_total")
_PADS = _obs.counter("serve_padded_lanes_total")

# Request-latency distributions (PR 14). Handles are module-cached —
# ``reset_metrics`` zeroes values in place, so these stay live.
_H_REQ = {p: _obs.histogram("serve_request_seconds", path=p)
          for p in ("cold", "warm")}
_H_FIRST = {p: _obs.histogram("serve_first_step_seconds", path=p)
            for p in ("cold", "warm")}
_H_WAIT = _obs.histogram("serve_bucket_wait_seconds")
_H_PADFRAC = _obs.histogram("serve_padding_fraction")
_H_QWAIT = _obs.histogram("serve_queue_wait_seconds")
_RECLAIMS = _obs.counter("serve_slots_reclaimed_total")
_obs.describe("serve_requests_total", "Requests completed by the router.")
_obs.describe("serve_cold_requests_total",
              "Requests that paid a bucket compile (cold path).")
_obs.describe("serve_quarantined_total",
              "Requests whose lane was quarantined mid-flight.")
_obs.describe("serve_padded_lanes_total",
              "Dead padding lanes stepped alongside live requests.")
_obs.describe("serve_request_seconds",
              "End-to-end request latency (submit to completion), "
              "by path=cold|warm.")
_obs.describe("serve_first_step_seconds",
              "Request-to-first-step ack latency, by path=cold|warm.")
_obs.describe("serve_bucket_wait_seconds",
              "Wait for the bucket's warm pool (compile time on a miss).")
_obs.describe("serve_padding_fraction",
              "Per-batch fraction of bucket lanes that were padding.")
_obs.describe("serve_requests_inflight",
              "Requests admitted and not yet completed.")
_obs.describe("serve_requests_completed",
              "Requests completed since process start.")
_obs.describe("serve_shed_total",
              "Requests shed by admission control, by reason="
              "queue_full|queue_timeout|deadline_exceeded|"
              "build_failed|no_bucket|router_error|brownout.")
_obs.describe("serve_queue_wait_seconds",
              "Admission-queue wait per request (0 for immediate "
              "admission).")
_obs.describe("serve_retries_total",
              "Retry hops taken for transient failures, by "
              "reason=build_failed|lane_quarantined.")
_obs.describe("serve_slots_reclaimed_total",
              "Admission slots reclaimed from quarantined/shed "
              "requests and handed to queued waiters.")
_obs.describe("serve_requests_queued",
              "Requests currently waiting in an admission queue.")
_obs.describe("serve_requests_shed",
              "Requests shed since process start (cumulative gauge "
              "for the watchdog heartbeat).")


class PoolWaitTimeout(Exception):
    """A request's deadline budget expired while its bucket's warm
    pool was still compiling (the admission-to-first-step timeout)."""


@dataclass(frozen=True)
class TenantClassPolicy:
    """Admission policy for one tenant class (PR 17).

    ``max_inflight`` caps concurrently-admitted requests of the class;
    beyond it, up to ``queue_depth`` requests WAIT (bounded by
    ``queue_timeout_s`` and the per-request deadline) and the rest are
    shed immediately (``queue_full``). ``deadline_s`` is the default
    admission-to-first-step budget (a request's own ``deadline_s``
    wins). ``retry_budget`` bounds jittered-backoff retries of
    transient failures — 0 (the default) preserves the pre-PR-17
    fail-fast behavior exactly."""
    max_inflight: int = 1 << 20
    queue_depth: int = 1 << 20
    queue_timeout_s: float = 120.0
    deadline_s: Optional[float] = None
    retry_budget: int = 0
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 0.5


DEFAULT_POLICY = TenantClassPolicy()


class _ClassState:
    __slots__ = ("inflight", "queued", "cond")

    def __init__(self, lock):
        self.inflight = 0
        self.queued = 0
        self.cond = threading.Condition(lock)


class AdmissionController:
    """Per-tenant-class bounded admission: inflight slots + a bounded
    wait queue, one condition variable per class (shared lock). All
    waits are time-bounded, so admission can never deadlock — the
    worst case is a shed."""

    def __init__(self, policies=None, default: TenantClassPolicy = DEFAULT_POLICY):
        self._policies = dict(policies or {})
        self._default = default
        self._lock = threading.Lock()
        self._classes: dict = {}

    def policy(self, cls: str) -> TenantClassPolicy:
        return self._policies.get(cls, self._default)

    def _state_locked(self, cls: str) -> _ClassState:
        st = self._classes.get(cls)
        if st is None:
            st = self._classes[cls] = _ClassState(self._lock)
        return st

    def admit(self, cls: str, deadline_left: Optional[float] = None):
        """Try to take an inflight slot for ``cls``; queue (bounded)
        when the class is saturated. Returns ``(admitted, wait_s,
        shed_reason)`` — ``shed_reason`` is ``None`` on admission,
        else ``queue_full`` / ``queue_timeout`` /
        ``deadline_exceeded``."""
        pol = self.policy(cls)
        if deadline_left is not None and deadline_left <= 0:
            return False, 0.0, "deadline_exceeded"
        t0 = time.perf_counter()
        with self._lock:
            st = self._state_locked(cls)
            if st.inflight < pol.max_inflight:
                st.inflight += 1
                _H_QWAIT.observe(0.0)
                return True, 0.0, None
            if st.queued >= pol.queue_depth:
                return False, 0.0, "queue_full"
            budget, reason = pol.queue_timeout_s, "queue_timeout"
            if deadline_left is not None and deadline_left < budget:
                budget, reason = deadline_left, "deadline_exceeded"
            st.queued += 1
            gq = _obs.gauge("serve_requests_queued")
            gq.set(gq.value + 1)
            try:
                while st.inflight >= pol.max_inflight:
                    remaining = budget - (time.perf_counter() - t0)
                    if remaining <= 0:
                        wait_s = time.perf_counter() - t0
                        _H_QWAIT.observe(wait_s)
                        return False, wait_s, reason
                    st.cond.wait(min(remaining, 0.25))
                st.inflight += 1
                wait_s = time.perf_counter() - t0
                _H_QWAIT.observe(wait_s)
                return True, wait_s, None
            finally:
                st.queued -= 1
                gq.set(max(gq.value - 1, 0))

    def release(self, cls: str, reclaimed: bool = False) -> None:
        """Return a slot; ``reclaimed=True`` marks a slot freed by a
        quarantined/shed request (the dead lane's capacity handed to a
        waiter — ``serve_slots_reclaimed_total``)."""
        with self._lock:
            st = self._state_locked(cls)
            st.inflight = max(st.inflight - 1, 0)
            if reclaimed:
                _RECLAIMS.inc()
            st.cond.notify()


@dataclass(frozen=True)
class BucketSpec:
    """One warm-pool bucket: a pre-compiled (shape, engine, dtype, B)
    fleet-lane executable family. Family fields select the compiled
    graph; ``lanes`` is the batch capacity; ``chunk_steps`` the cruise
    chunk length (also the quarantine-triage cadence)."""
    n_cells: int
    n_lat: int
    n_lon: int
    lanes: int
    engine: Optional[str] = None            # None = auto -> resolver
    spectral_dtype: Optional[str] = None
    mu: float = 0.05
    dt: float = 5e-5                        # template dt (dt is traced)
    chunk_steps: int = 2

    def family(self):
        return (self.n_cells, self.n_lat, self.n_lon, self.engine,
                self.spectral_dtype, self.mu)


@dataclass
class ScenarioRequest:
    """One tenant's scenario. Family fields select the bucket; value
    fields (``dt``, ``steps``, ``perturb``) are traced arguments or
    host-side loop bounds and never retrace."""
    tenant: str
    n_cells: int
    n_lat: int = 8
    n_lon: int = 16
    steps: int = 3
    dt: float = 5e-5
    engine: Optional[str] = None
    spectral_dtype: Optional[str] = None
    mu: float = 0.05
    # per-lane initial velocity offset amplitude; a non-finite value
    # poisons the lane's state (the quarantine drill in tests)
    perturb: float = 0.0
    # admission class (selects the TenantClassPolicy) and an optional
    # per-request admission-to-first-step deadline overriding the
    # class default (PR 17)
    tenant_class: str = "standard"
    deadline_s: Optional[float] = None

    def family(self):
        return (self.n_cells, self.n_lat, self.n_lon, self.engine,
                self.spectral_dtype, self.mu)


@dataclass
class RequestResult:
    """Per-request accounting (mirrors the ``request`` ledger record)."""
    tenant: str
    ok: bool
    quarantined: bool
    cold: bool
    bucket_lanes: int
    lane: int
    steps_done: int
    first_step_s: float
    total_s: float
    family_key: str
    error: Optional[str] = None
    trace_id: Optional[str] = None
    # traffic accounting (PR 17): shed requests never ran a step;
    # queue_wait_s is the admission-queue time, retries the number of
    # backoff hops taken before this (terminal) outcome
    shed: bool = False
    shed_reason: Optional[str] = None
    retries: int = 0
    queue_wait_s: float = 0.0


class WarmPool:
    """One warm bucket: integrator + template state + the AOT-compiled
    lane chunks (length 1 for the first-step ack, ``chunk_steps`` for
    cruise), all published through the shared executable cache."""

    def __init__(self, spec: BucketSpec, cache):
        import jax.numpy as jnp

        from ibamr_tpu.models.shell3d import build_shell_example
        from ibamr_tpu.utils.hierarchy_driver import (HierarchyDriver,
                                                      RunConfig)

        self.spec = spec
        self.cache = cache
        engine_arg = (None if spec.engine in (None, "auto")
                      else {"scatter": False,
                            "mxu": True}.get(spec.engine, spec.engine))
        self.integ, self.template = build_shell_example(
            n_cells=spec.n_cells, n_lat=spec.n_lat, n_lon=spec.n_lon,
            radius=0.25, aspect=1.2, stiffness=1.0,
            rest_length_factor=0.75, mu=spec.mu,
            use_fast_interaction=engine_arg,
            spectral_dtype=spec.spectral_dtype)
        self.engine = self.integ.ib.engine_name
        cfg = RunConfig(dt=spec.dt, num_steps=spec.chunk_steps,
                        health_interval=spec.chunk_steps)
        self.driver = HierarchyDriver(self.integ, cfg, lanes=spec.lanes)
        self.fingerprint = aot_cache.step_fingerprint(self.integ)
        self.key = aot_cache.cache_key(
            self.fingerprint,
            extra={"kind": "fleet_chunk", "lanes": spec.lanes})
        self._dt_vec = jnp.full((spec.lanes,), spec.dt,
                                dtype=jnp.float32)

    def _template_args(self, live: int = 1):
        stacked, alive = _lanes.pad_lanes([self.template] * live,
                                          self.spec.lanes)
        return stacked, self._dt_vec, alive

    def contract_args(self, length: int = 1, live: int = 1):
        """(fn, args, donate_argnums) of this pool's chunk for the
        graph-contract census (``served_chunk`` in
        analysis/contracts.py) — the serving ack path must lower the
        same in-scan structure as the batch fleet chunk."""
        jitted = self.driver._chunk(length)
        fn = getattr(jitted, "__wrapped__", jitted)
        return fn, self._template_args(live=live), ()

    def ensure_compiled(self) -> None:
        """AOT-compile the ack (length 1) and cruise chunks through
        the cache. Idempotent; this is the whole cost of a bucket
        miss."""
        for length in sorted({1, self.spec.chunk_steps}):
            self.chunk(length)

    def chunk(self, length: int):
        """The compiled fleet chunk of ``length`` steps. EVERY call
        goes through the hash-cons — a warm pool reads as cache hits
        (the ``warm_hits`` contract observable), a cold one as exactly
        one miss per (family, lanes, length)."""
        args = self._template_args(live=self.spec.lanes)
        entry = self.cache.get_or_compile(
            self.fingerprint,
            lambda: self.driver._chunk(length).lower(*args).compile(),
            extra={"kind": "fleet_chunk", "lanes": self.spec.lanes,
                   "length": length,
                   "args": aot_cache.arg_signature(args)},
            label=(f"pool:{self.spec.n_cells}^3"
                   f"x{self.spec.lanes}:len{length}"))
        return entry.executable

    def entry_keys(self) -> list:
        """The cache keys of this pool's ack/cruise chunks, computed
        WITHOUT compiling — the elastic shrink path releases exactly
        these from the shared cache (``router.release_pool``)."""
        sig = aot_cache.arg_signature(
            self._template_args(live=self.spec.lanes))
        return [aot_cache.cache_key(
                    self.fingerprint,
                    extra={"kind": "fleet_chunk",
                           "lanes": self.spec.lanes,
                           "length": length, "args": sig})
                for length in sorted({1, self.spec.chunk_steps})]

    def request_state(self, req: ScenarioRequest):
        """Template state with the request's perturbation applied: a
        per-component constant velocity offset (divergence-free) —
        values only, never shapes/dtypes (the family contract)."""
        import jax.numpy as jnp

        if req.perturb == 0.0:
            return self.template
        st = self.template
        u = tuple(c + jnp.asarray(req.perturb * 1e-3 * (d + 1),
                                  dtype=c.dtype)
                  for d, c in enumerate(st.ins.u))
        return st._replace(ins=st.ins._replace(u=u))


class _PoolBuild:
    __slots__ = ("event", "pool", "error", "thread", "trace_ids")

    def __init__(self, trace_ids=()):
        self.event = threading.Event()
        self.pool = None
        self.error = None
        self.thread = None
        # the cold requests waiting on this build: the background
        # compile's spans and aot_cache records bill to THEIR traces
        self.trace_ids = tuple(t for t in trace_ids if t)


class WarmPoolRouter:
    """Packs scenario requests into warm-pool buckets (module
    docstring has the request lifecycle)."""

    def __init__(self, buckets: Sequence[BucketSpec] = (), cache=None,
                 allow_dynamic: bool = True, default_lanes: int = 2,
                 policies: Optional[dict] = None,
                 default_policy: TenantClassPolicy = DEFAULT_POLICY):
        self.cache = cache if cache is not None else aot_cache.get_cache()
        self._specs = list(buckets)
        self._pools: dict = {}
        self._inflight: dict = {}
        self._serving: dict = {}       # family -> batches in flight
        self._lock = threading.Lock()
        self.allow_dynamic = allow_dynamic
        self.default_lanes = int(default_lanes)
        # per-tenant-class admission control (PR 17); the default
        # policy is permissive (huge slots, no deadline, no retries)
        # so a router built without policies behaves exactly as before
        self.admission = AdmissionController(policies, default_policy)
        # optional elastic pool manager (PR 18): observes admissions,
        # sheds batch tenants in shed_batch mode, caps batch cruise
        # chunks in brownout. None = pre-PR-18 behavior exactly.
        self.manager = None

    # -- pool lifecycle -----------------------------------------------------

    def is_warm(self, spec: BucketSpec) -> bool:
        with self._lock:
            return spec in self._pools

    def live_specs(self) -> list:
        """Specs with a published warm pool (routable families)."""
        with self._lock:
            return list(self._pools)

    def live_families(self) -> dict:
        """family tuple -> BucketSpec for every warm pool."""
        with self._lock:
            return {s.family(): s for s in self._pools}

    def build_backlog(self) -> int:
        """Async pool builds currently in flight (the precompile
        backlog leg of the elastic manager's pressure signal)."""
        with self._lock:
            return len(self._inflight)

    def family_inflight(self, family) -> int:
        """Batches of ``family`` currently being served — the elastic
        manager's never-evict-active guard reads this."""
        with self._lock:
            return self._serving.get(family, 0)

    def release_pool(self, spec: BucketSpec) -> int:
        """Evict a warm pool (elastic shrink): the family stops being
        routable, its spec leaves the declared set, and its compiled
        ack/cruise executables are released from the shared cache.
        Returns how many cache entries were released. A family mid-
        serve must not be released — the manager checks
        :meth:`family_inflight` first (a released pool under a live
        batch would not crash, but the batch's next chunk would pay a
        fresh compile)."""
        with self._lock:
            pool = self._pools.pop(spec, None)
            try:
                self._specs.remove(spec)
            except ValueError:
                pass
        if pool is None:
            return 0
        return self.cache.release(pool.entry_keys())

    def drain_builds(self, timeout_s: float = 60.0) -> int:
        """Join any in-flight pool-build threads (bounded); returns
        how many are still alive after the timeout. A shed request
        leaves its bucket build running (the next arrival gets the
        warm pool), so call this before process exit — a daemon
        thread inside an XLA compile at interpreter teardown aborts
        the whole process."""
        with self._lock:
            threads = [f.thread for f in self._inflight.values()
                       if f.thread is not None]
        deadline = time.monotonic() + max(float(timeout_s), 0.0)
        alive = 0
        for t in threads:
            t.join(max(deadline - time.monotonic(), 0.0))
            alive += int(t.is_alive())
        return alive

    def warm(self, spec: Optional[BucketSpec] = None,
             block: bool = True):
        """Pre-compile bucket(s) (``spec=None`` warms every declared
        bucket). ``block=False`` returns immediately with the builds
        running in the background."""
        specs = [spec] if spec is not None else list(self._specs)
        waits = [self._ensure_pool(s) for s in specs]
        if block:
            return [w() for w in waits]
        return waits

    def _ensure_pool(self, spec: BucketSpec, trace_ids=()):
        """Warm pool for ``spec``, compiled asynchronously on a miss
        (one background build per bucket, published to the shared
        executable cache). Returns a ``wait()`` callable producing the
        pool — a cold request's latency includes this wait; every
        other family keeps serving meanwhile. ``trace_ids`` names the
        requests whose cold path this build is (thread-locals do not
        cross threads, so the identity is handed over explicitly); a
        build already in flight keeps its original attribution."""
        with self._lock:
            pool = self._pools.get(spec)
            if pool is not None:
                return lambda timeout=None: pool
            flight = self._inflight.get(spec)
            if (flight is not None and flight.thread is not None
                    and not flight.thread.is_alive()
                    and not flight.event.is_set()):
                # the build thread died without publishing (killed
                # mid-build): fail the flight over so its waiters see
                # a retryable build error instead of hanging forever,
                # and let a fresh build start
                self._inflight.pop(spec, None)
                flight.error = RuntimeError(
                    "pool build thread died before publishing")
                flight.event.set()
                flight = None
            if flight is None:
                flight = _PoolBuild(trace_ids=trace_ids)
                self._inflight[spec] = flight
                t = threading.Thread(target=self._build_pool,
                                     args=(spec, flight), daemon=True)
                flight.thread = t
                t.start()

        def wait(timeout=None):
            deadline = (None if timeout is None
                        else time.monotonic() + max(float(timeout), 0.0))
            # sliced wait: each slice re-checks the builder thread's
            # liveness, so a killed build fails over instead of
            # deadlocking every waiter (soak invariant: no deadlock)
            while not flight.event.is_set():
                if (deadline is not None
                        and time.monotonic() >= deadline):
                    raise PoolWaitTimeout(
                        f"pool build for {spec.n_cells}^3 "
                        f"x{spec.lanes} exceeded the deadline budget")
                slice_s = (0.25 if deadline is None
                           else min(0.25, max(
                               deadline - time.monotonic(), 0.001)))
                if flight.event.wait(slice_s):
                    break
                th = flight.thread
                if (th is not None and not th.is_alive()
                        and not flight.event.is_set()):
                    with self._lock:
                        if self._inflight.get(spec) is flight:
                            self._inflight.pop(spec, None)
                    flight.error = RuntimeError(
                        "pool build thread died before publishing")
                    flight.event.set()
            if flight.error is not None:
                raise flight.error
            return flight.pool

        return wait

    def _build_pool(self, spec: BucketSpec, flight: _PoolBuild) -> None:
        try:
            with _obs.trace_scope(*flight.trace_ids), \
                    _obs.span("serve/pool_build",
                              lanes=spec.lanes, n=spec.n_cells):
                pool = WarmPool(spec, self.cache)
                pool.ensure_compiled()
            with self._lock:
                self._pools[spec] = pool
                self._inflight.pop(spec, None)
            flight.pool = pool
        except Exception as e:  # noqa: BLE001 - delivered to waiters
            with self._lock:
                self._inflight.pop(spec, None)
            flight.error = e
        finally:
            flight.event.set()

    # -- bucketing ----------------------------------------------------------

    def _bucket_for(self, family, count: int) -> BucketSpec:
        """Nearest bucket: same family, smallest ``lanes >= count``;
        else the largest same-family bucket (the group splits); else a
        dynamic bucket when allowed."""
        with self._lock:
            cands = [s for s in self._specs if s.family() == family]
        if not cands:
            if not self.allow_dynamic:
                raise KeyError(
                    f"no declared bucket for scenario family {family} "
                    f"(allow_dynamic=False)")
            lanes = max(self.default_lanes, count)
            spec = BucketSpec(n_cells=family[0], n_lat=family[1],
                              n_lon=family[2], lanes=lanes,
                              engine=family[3], spectral_dtype=family[4],
                              mu=family[5])
            with self._lock:
                self._specs.append(spec)
            cands = [spec]
        fits = sorted((s for s in cands if s.lanes >= count),
                      key=lambda s: s.lanes)
        return fits[0] if fits else max(cands, key=lambda s: s.lanes)

    # -- serving ------------------------------------------------------------

    def serve(self, requests: Sequence[ScenarioRequest]):
        """Serve a batch of scenario requests; returns one
        :class:`RequestResult` per request, input order preserved.

        Admission mints each request a ``trace_id`` and emits a
        ``request_admit`` ledger record; every record and span the
        request touches downstream carries the id, so
        ``tools/obs.py trace <id>`` rebuilds the full
        admission→completion timeline from the ledger alone. Every
        admitted id reaches exactly one TERMINAL record (``request``
        or ``request_shed``) — even when ``serve`` raises, the
        unserved remainder is shed first (the no-lost-request
        invariant)."""
        g_in = _obs.gauge("serve_requests_inflight")
        g_done = _obs.gauge("serve_requests_completed")
        tids = [_obs.new_trace_id() for _ in requests]
        t_admit = time.perf_counter()
        g_in.set(g_in.value + len(requests))
        for r, tid in zip(requests, tids):
            _obs.emit("request_admit", trace_id=tid, tenant=r.tenant,
                      tenant_class=r.tenant_class,
                      family=str(r.family()), steps=int(r.steps))
        mgr = self.manager
        if mgr is not None:
            # elastic observation (PR 18): fold arrivals into the mix
            # estimate + run a scaling/mode tick. A manager bug must
            # degrade to static routing, never down the router.
            for r, tid in zip(requests, tids):
                try:
                    mgr.observe_admit(r, trace_id=tid)
                except Exception:  # noqa: BLE001 - degrade, don't die
                    _obs.counter("serve_manager_errors_total").inc()
        results: list = [None] * len(requests)
        try:
            groups: dict = {}
            for i, r in enumerate(requests):
                groups.setdefault(r.family(), []).append((i, r))
            for family, members in groups.items():
                pos = 0
                while pos < len(members):
                    spec = self._bucket_for(family, len(members) - pos)
                    batch = members[pos:pos + spec.lanes]
                    pos += len(batch)
                    out = self._admit_and_serve(spec, batch, tids,
                                                t_admit)
                    for (i, _), res in zip(batch, out):
                        results[i] = res
        except BaseException as e:
            reason = ("no_bucket" if isinstance(e, KeyError)
                      else "router_error")
            for i, r in enumerate(requests):
                if results[i] is None:
                    results[i] = self._shed(
                        r, tids[i], reason, 0.0,
                        error=f"{type(e).__name__}: {e}")
            raise
        finally:
            g_in.set(max(g_in.value - len(requests), 0))
        g_done.set(g_done.value + len(requests))
        return results

    # -- admission / shed / retry (PR 17) -----------------------------------

    def _shed(self, req: ScenarioRequest, tid: Optional[str],
              reason: str, queue_wait_s: float, retries: int = 0,
              error: Optional[str] = None) -> RequestResult:
        """Terminal shed: counter + cumulative gauge + the
        ``request_shed`` ledger record (the shed counterpart of the
        ``request`` accounting record)."""
        _obs.counter("serve_shed_total", reason=reason).inc()
        gs = _obs.gauge("serve_requests_shed")
        gs.set(gs.value + 1)
        payload = dict(trace_id=tid or None, tenant=req.tenant,
                       tenant_class=req.tenant_class,
                       family=str(req.family()), reason=reason,
                       queue_wait_s=round(queue_wait_s, 4),
                       retries=int(retries))
        if error:
            payload["error"] = error
        _obs.emit("request_shed", **payload)
        return RequestResult(
            tenant=req.tenant, ok=False, quarantined=False,
            cold=False, bucket_lanes=0, lane=-1, steps_done=0,
            first_step_s=0.0, total_s=0.0,
            family_key=str(req.family()),
            error=error or f"shed ({reason})", trace_id=tid,
            shed=True, shed_reason=reason, retries=int(retries),
            queue_wait_s=queue_wait_s)

    def _deadline_left(self, req: ScenarioRequest,
                       t_admit: float) -> Optional[float]:
        deadline = (req.deadline_s if req.deadline_s is not None
                    else self.admission.policy(req.tenant_class
                                               ).deadline_s)
        if deadline is None:
            return None
        return deadline - (time.perf_counter() - t_admit)

    @staticmethod
    def _backoff_s(pol: TenantClassPolicy, attempt: int,
                   tid: Optional[str]) -> float:
        """Exponential backoff with DETERMINISTIC jitter derived from
        the trace id (no RNG state, replays identically)."""
        base = min(pol.backoff_cap_s,
                   pol.backoff_base_s * (2 ** max(attempt - 1, 0)))
        jitter = (int((tid or "0")[:8], 16) % 1000) / 1000.0
        return base * (0.5 + 0.5 * jitter)

    def _admit_and_serve(self, spec: BucketSpec, batch, tids,
                         t_admit: float):
        """Admission-gate one packed batch, serve the admitted
        members (with retries), and release every admitted slot —
        reclaimed slots (quarantined/shed requests) wake a queued
        waiter so dead lanes return capacity."""
        out: list = [None] * len(batch)
        admitted: list = []
        qwaits: dict = {}
        mgr = self.manager
        for j, (i, r) in enumerate(batch):
            if mgr is not None and mgr.should_shed(r.tenant_class):
                # mode-driven shed (PR 18): shed_batch drops batch
                # tenants BEFORE they take a slot, so interactive p99
                # rides the capacity brownout protects
                out[j] = self._shed(r, tids[i], "brownout", 0.0)
                continue
            ok, wait_s, reason = self.admission.admit(
                r.tenant_class, self._deadline_left(r, t_admit))
            if ok:
                admitted.append(j)
                qwaits[j] = wait_s
            else:
                out[j] = self._shed(r, tids[i], reason, wait_s)
        if not admitted:
            return out
        try:
            self._serve_admitted(spec, batch, tids, t_admit, admitted,
                                 qwaits, out)
        finally:
            for j in admitted:
                res = out[j]
                reclaimed = (isinstance(res, RequestResult)
                             and (res.quarantined or res.shed))
                self.admission.release(batch[j][1].tenant_class,
                                       reclaimed=reclaimed)
        return out

    def _serve_admitted(self, spec: BucketSpec, batch, tids,
                        t_admit: float, admitted, qwaits, out):
        """The retry loop: serve the admitted members, classify
        transient failures (failed/killed pool build, quarantined
        lane), back off and retry within the class budget; everything
        else is terminal. ``out[j]`` is a RequestResult for every
        admitted ``j`` on exit."""
        pending = list(admitted)
        attempt = 0
        while pending:
            reqs = [batch[j][1] for j in pending]
            btids = [tids[batch[j][0]] for j in pending]
            lefts = [self._deadline_left(r, t_admit) for r in reqs]
            bq = [qwaits[j] for j in pending]
            err: Optional[BaseException] = None
            try:
                res = self._serve_batch(spec, reqs, btids, qwaits=bq,
                                        attempt=attempt,
                                        deadline_lefts=lefts)
            except Exception as e:  # noqa: BLE001 - pool build failed
                res, err = None, e
            retry: list = []
            reasons: dict = {}
            for k, j in enumerate(pending):
                i, r = batch[j]
                pol = self.admission.policy(r.tenant_class)
                left = self._deadline_left(r, t_admit)
                can_retry = (attempt < pol.retry_budget
                             and (left is None
                                  or left > self._backoff_s(
                                      pol, attempt + 1, tids[i])))
                if err is not None:
                    if can_retry:
                        retry.append(j)
                        reasons[j] = "build_failed"
                    else:
                        out[j] = self._shed(
                            r, tids[i], "build_failed", qwaits[j],
                            retries=attempt,
                            error=f"{type(err).__name__}: {err}")
                    continue
                rres = res[k]
                if rres.quarantined and can_retry:
                    retry.append(j)
                    reasons[j] = "lane_quarantined"
                else:
                    out[j] = rres
            if retry:
                attempt += 1
                backoff = 0.0
                for j in retry:
                    i, r = batch[j]
                    pol = self.admission.policy(r.tenant_class)
                    b = self._backoff_s(pol, attempt, tids[i])
                    backoff = max(backoff, b)
                    _obs.counter("serve_retries_total",
                                 reason=reasons[j]).inc()
                    _obs.emit("request_retry", trace_id=tids[i],
                              tenant=r.tenant,
                              tenant_class=r.tenant_class,
                              attempt=attempt, reason=reasons[j],
                              backoff_s=round(b, 4))
                time.sleep(backoff)
            pending = retry

    def _serve_batch(self, spec: BucketSpec,
                     reqs: Sequence[ScenarioRequest],
                     tids: Sequence[Optional[str]] = (),
                     qwaits: Sequence[float] = (),
                     attempt: int = 0,
                     deadline_lefts: Sequence[Optional[float]] = ()):
        """Serving-count bookkeeping around :meth:`_serve_batch_run`:
        while a family has a batch in flight the elastic manager's
        shrink path must not release its pool
        (``family_inflight`` — the never-evict-active guard)."""
        family = spec.family()
        with self._lock:
            self._serving[family] = self._serving.get(family, 0) + 1
        try:
            return self._serve_batch_run(spec, reqs, tids, qwaits,
                                         attempt, deadline_lefts)
        finally:
            with self._lock:
                n = self._serving.get(family, 1) - 1
                if n <= 0:
                    self._serving.pop(family, None)
                else:
                    self._serving[family] = n

    def _serve_batch_run(self, spec: BucketSpec,
                         reqs: Sequence[ScenarioRequest],
                         tids: Sequence[Optional[str]] = (),
                         qwaits: Sequence[float] = (),
                         attempt: int = 0,
                         deadline_lefts: Sequence[Optional[float]] = ()):
        import jax.numpy as jnp

        tids = list(tids) or [None] * len(reqs)
        qwaits = list(qwaits) or [0.0] * len(reqs)
        lefts = list(deadline_lefts) or [None] * len(reqs)
        t_submit = time.perf_counter()
        with _obs.trace_scope(*tids), \
                _obs.span("serve/request", lanes=spec.lanes,
                          requests=len(reqs)):
            cold = not self.is_warm(spec)
            wait = self._ensure_pool(spec, trace_ids=tids)
            # the deadline budget binds the pool wait only when every
            # member carries one — the most patient member keeps the
            # build alive for the others
            finite = [x for x in lefts if x is not None]
            budget = (max(finite)
                      if finite and len(finite) == len(reqs) else None)
            with _obs.span("bucket_wait", cold=cold):
                t_wait = time.perf_counter()
                try:
                    pool = wait(budget)    # cold: compile lands here
                except PoolWaitTimeout:
                    # every member's admission-to-first-step budget
                    # expired while the bucket compiled: terminal shed
                    # (a deadline, unlike a failed build, never
                    # retries — the budget is already gone)
                    return [self._shed(r, tids[k],
                                       "deadline_exceeded", qwaits[k],
                                       retries=attempt)
                            for k, r in enumerate(reqs)]
                finally:
                    _H_WAIT.observe(time.perf_counter() - t_wait)
            results: list = [None] * len(reqs)
            elapsed = time.perf_counter() - t_submit
            live_idx = []
            for k, r in enumerate(reqs):
                if lefts[k] is not None and lefts[k] - elapsed <= 0:
                    # admission-to-first-step budget burned in the
                    # bucket wait: shed before spending device time
                    results[k] = self._shed(r, tids[k],
                                            "deadline_exceeded",
                                            qwaits[k], retries=attempt)
                else:
                    live_idx.append(k)
            if not live_idx:
                return results
            sreqs = [reqs[k] for k in live_idx]
            stids = [tids[k] for k in live_idx]
            B = spec.lanes
            pads = B - len(sreqs)
            if pads:
                _PADS.inc(pads)
            _H_PADFRAC.observe(pads / B)
            stacked, _ = _lanes.pad_lanes(
                [pool.request_state(r) for r in sreqs], B)
            dt_vec = jnp.asarray(
                [r.dt for r in sreqs] + [sreqs[-1].dt] * pads,
                dtype=pool._dt_vec.dtype)

            steps_done = np.zeros(B, dtype=int)
            target = np.array([r.steps for r in sreqs] + [0] * pads)
            quarantined = np.zeros(B, dtype=bool)
            alive_host = np.arange(B) < len(sreqs)
            first_step_s = None
            state = stacked
            while True:
                remaining = target - steps_done
                live = alive_host & (remaining > 0)
                if not live.any():
                    break
                # only pre-compiled lengths run (1 and chunk_steps):
                # the warm path performs ZERO compiles by construction
                length = (spec.chunk_steps
                          if first_step_s is not None
                          and int(remaining[live].max())
                          >= spec.chunk_steps
                          else 1)
                # brownout cruise cap (PR 18): an all-batch batch is
                # degraded to the already-compiled length-1 ack chunk
                # — reduced throughput, still zero fresh compiles
                mgr = self.manager
                if mgr is not None and length > 1:
                    cap = mgr.cruise_cap(
                        [r.tenant_class for r in sreqs])
                    if cap is not None:
                        length = min(length, cap)
                run_mask = live & (remaining >= length)
                with _obs.span("ack" if first_step_s is None
                               else "cruise", steps=length):
                    state, health = pool.chunk(length)(
                        state, dt_vec, jnp.asarray(run_mask))
                    h = np.asarray(health)   # one transfer per chunk
                if first_step_s is None:
                    first_step_s = time.perf_counter() - t_submit
                steps_done[run_mask] += length
                newly_bad = run_mask & (h < 0.5)
                for lane in np.nonzero(newly_bad)[0]:
                    if lane >= len(sreqs):
                        continue
                    _obs.emit("lane_quarantine",
                              trace_id=stids[lane] or None,
                              tenant=sreqs[lane].tenant,
                              family=pool.key, lane=int(lane),
                              step=int(steps_done[lane]))
                quarantined |= newly_bad
                alive_host &= ~newly_bad

            total_s = time.perf_counter() - t_submit
            if first_step_s is None:          # zero-step requests
                first_step_s = total_s
            path = "cold" if cold else "warm"
            for lane, r in enumerate(sreqs):
                q = bool(quarantined[lane])
                ok = bool(steps_done[lane] >= r.steps) and not q
                _REQS.inc()
                if cold:
                    _COLD.inc()
                if q:
                    _QUAR.inc()
                _H_REQ[path].observe(total_s)
                _H_FIRST[path].observe(first_step_s)
                qw = qwaits[live_idx[lane]]
                results[live_idx[lane]] = RequestResult(
                    tenant=r.tenant, ok=ok, quarantined=q, cold=cold,
                    bucket_lanes=B, lane=lane,
                    steps_done=int(steps_done[lane]),
                    first_step_s=first_step_s, total_s=total_s,
                    family_key=pool.key, trace_id=stids[lane],
                    error=("lane quarantined (non-finite state)" if q
                           else None),
                    retries=int(attempt), queue_wait_s=qw)
                _obs.emit("request", trace_id=stids[lane] or None,
                          tenant=r.tenant,
                          tenant_class=r.tenant_class,
                          family=pool.key,
                          engine=pool.engine, bucket_lanes=B,
                          lane=lane, cold=cold, ok=ok, quarantined=q,
                          steps=int(steps_done[lane]),
                          first_step_s=round(first_step_s, 4),
                          total_s=round(total_s, 4),
                          queue_wait_s=round(qw, 4),
                          retries=int(attempt))
        return results


def _histogram_delta(before: dict, after: dict) -> dict:
    """Per-key difference of two ``metrics_snapshot()["histograms"]``
    dicts, keeping only keys that saw observes in between — the drill
    reports ITS distribution even when the process served before."""
    out = {}
    for key, snap in after.items():
        b = before.get(key)
        if b is None:
            counts = list(snap["counts"])
            s = float(snap["sum"])
        else:
            counts = [int(a) - int(x)
                      for a, x in zip(snap["counts"], b["counts"])]
            s = float(snap["sum"]) - float(b["sum"])
        n = sum(counts)
        if n > 0:
            out[key] = {"sum": s, "count": n, "counts": counts}
    return out


def cold_warm_drill(n_cells: int = 16, n_lat: int = 8, n_lon: int = 16,
                    lanes: int = 2, steps: int = 3, dt: float = 5e-5,
                    engine: Optional[str] = None,
                    spectral_dtype: Optional[str] = None,
                    cache_dir: Optional[str] = None,
                    warm_requests: int = 1) -> dict:
    """The serving benchmark: one scenario family served twice through
    a FRESH router + FRESH executable cache — request 1 pays the cold
    path (bucket compile on miss), request 2 rides warm. Returns
    request-to-first-step latencies plus compile counts; the serve
    contract (``tools/serve.py check`` vs SERVE_CONTRACT.json) pins
    ``warm_compiles == 0`` and ``warm_new_trace_signatures == 0``
    structurally.

    ``warm_requests > 1`` serves extra warm requests AFTER the
    contract-measured one (its compile/hit accounting is untouched) so
    the warm-path percentiles (``warm_p50_s``/``warm_p99_s``, from the
    ``serve_first_step_seconds{path="warm"}`` histogram delta) rest on
    a real sample; the full per-key histogram delta rides along under
    ``"histograms"`` for ``tools/obs.py compare`` and the SLO gate."""
    cache = aot_cache.ExecutableCache(directory=cache_dir)
    spec = BucketSpec(n_cells=n_cells, n_lat=n_lat, n_lon=n_lon,
                      lanes=lanes, engine=engine,
                      spectral_dtype=spectral_dtype, dt=dt,
                      chunk_steps=max(1, min(2, steps)))
    router = WarmPoolRouter([spec], cache=cache, allow_dynamic=False)

    def one(tag):
        before = cache.stats()
        res = router.serve([ScenarioRequest(
            tenant=tag, n_cells=n_cells, n_lat=n_lat, n_lon=n_lon,
            steps=steps, dt=dt, engine=engine,
            spectral_dtype=spectral_dtype)])[0]
        after = cache.stats()
        return res, {"compiles": after["misses"] - before["misses"],
                     "hits": after["hits"] - before["hits"]}

    hist_before = _obs.metrics_snapshot()["histograms"]
    cold_res, cold_stats = one("drill-cold")
    pool = router._pools[spec]
    sigs_cold = sum(pool.driver.trace_counts.values())
    warm_res, warm_stats = one("drill-warm")
    sigs_warm = sum(pool.driver.trace_counts.values())
    for k in range(max(0, int(warm_requests) - 1)):
        one(f"drill-warm-{k + 2}")
    hist = _histogram_delta(hist_before,
                            _obs.metrics_snapshot()["histograms"])
    warm_first = hist.get('serve_first_step_seconds{path="warm"}')
    warm_p50, warm_p99 = (
        _obs.quantiles_from_counts(warm_first["counts"], [0.5, 0.99])
        if warm_first else (None, None))
    return {
        "n": n_cells, "lanes": lanes, "steps": steps,
        "engine": pool.engine,
        "family_key": cold_res.family_key,
        "cold_first_step_s": round(cold_res.first_step_s, 4),
        "warm_first_step_s": round(warm_res.first_step_s, 4),
        "warm_over_cold": round(
            warm_res.first_step_s / max(cold_res.first_step_s, 1e-9), 6),
        "cold_compiles": cold_stats["compiles"],
        "warm_compiles": warm_stats["compiles"],
        "warm_hits": warm_stats["hits"],
        "warm_new_trace_signatures": sigs_warm - sigs_cold,
        "cold_ok": bool(cold_res.ok), "warm_ok": bool(warm_res.ok),
        "warm_requests": max(1, int(warm_requests)),
        "warm_p50_s": (None if warm_p50 is None
                       else round(warm_p50, 6)),
        "warm_p99_s": (None if warm_p99 is None
                       else round(warm_p99, 6)),
        "histograms": hist,
    }
