"""Serving capacity model: measured chunk cost -> sustainable load.

The elastic manager (:mod:`ibamr_tpu.serve.autoscale`) reacts to
traffic; this module PREDICTS what the reaction can sustain, joining
two things the repo already measures:

- **per-request chunk cost** — ``request`` ledger records carry warm
  ``total_s`` and ``steps`` per family, so a family's per-step warm
  cost (and its lane width) falls straight out of any soak ledger;
- **the scaling policy** — how many lanes serve a family
  concurrently.

The model is a first-order M/M/1-style queueing bound, documented
rather than hidden: with mean service time ``E[S]`` per request and
``c`` effective servers (lanes), sojourn p99 under exponential
assumptions is roughly ``E[S] * ln(100) / (1 - rho)`` — so the
largest utilization meeting ``p99 <= X`` is
``rho_max = 1 - E[S] * ln(100) / X`` (clamped to [0, 0.95]) and the
sustainable arrival rate is ``rho_max * c / E[S]``. Crude, but it is
a CEILING with honest inputs: the elastic smoke checks its healthy
offered rate against this prediction, and ``tools/slo.py check
--elastic`` carries the per-family costs in its artifact.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

LN100 = math.log(100.0)
MAX_UTILIZATION = 0.95


def family_costs_from_records(records: Sequence[dict]) -> dict:
    """Per-family warm cost model from ``request`` ledger records:
    ``{family: {"per_step_s", "mean_service_s", "lanes", "samples"}}``.
    Cold completions are excluded — compile cost is the autoscaler's
    problem (scale-up latency), not steady-state capacity."""
    acc: Dict[str, dict] = {}
    for r in records:
        if r.get("kind") != "request" or r.get("cold"):
            continue
        steps = int(r.get("steps") or 0)
        total = float(r.get("total_s") or 0.0)
        if steps <= 0 or total <= 0.0:
            continue
        fam = str(r.get("family"))
        a = acc.setdefault(fam, {"steps": 0, "total_s": 0.0,
                                 "samples": 0, "lanes": 1})
        a["steps"] += steps
        a["total_s"] += total
        a["samples"] += 1
        a["lanes"] = max(a["lanes"], int(r.get("bucket_lanes") or 1))
    out = {}
    for fam, a in acc.items():
        per_step = a["total_s"] / a["steps"]
        out[fam] = {"per_step_s": round(per_step, 6),
                    "mean_service_s": round(a["total_s"] / a["samples"],
                                            6),
                    "lanes": a["lanes"],
                    "samples": a["samples"]}
    return out


def mix_service_time(costs: dict, mix: Optional[dict] = None,
                     steps_by_family: Optional[dict] = None) -> dict:
    """Mix-weighted mean service time and effective lane count.
    ``mix`` maps family -> share (defaults to sample-weighted shares
    from ``costs``); ``steps_by_family`` overrides the measured mean
    steps with a planned demand profile."""
    if not costs:
        return {"mean_service_s": None, "lanes": 0}
    if mix is None:
        total = sum(c["samples"] for c in costs.values())
        mix = {f: c["samples"] / total for f, c in costs.items()}
    norm = sum(mix.get(f, 0.0) for f in costs)
    if norm <= 0:
        return {"mean_service_s": None, "lanes": 0}
    es = 0.0
    lanes = 0
    for fam, c in costs.items():
        w = mix.get(fam, 0.0) / norm
        if w <= 0:
            continue
        service = (c["per_step_s"] * steps_by_family[fam]
                   if steps_by_family and fam in steps_by_family
                   else c["mean_service_s"])
        es += w * service
        lanes = max(lanes, c["lanes"])
    return {"mean_service_s": es, "lanes": lanes}


def sustainable_rps(costs: dict, p99_ceiling_s: float,
                    mix: Optional[dict] = None,
                    steps_by_family: Optional[dict] = None) -> dict:
    """Predicted sustainable arrival rate keeping sojourn p99 under
    ``p99_ceiling_s`` for the given family mix (module docstring has
    the queueing bound). Returns the full reasoning, not just the
    number: ``{"rps", "utilization", "mean_service_s", "lanes",
    "p99_ceiling_s"}`` — ``rps`` is ``None`` when the model has no
    warm samples or the ceiling is below one service time."""
    st = mix_service_time(costs, mix=mix,
                          steps_by_family=steps_by_family)
    es, lanes = st["mean_service_s"], st["lanes"]
    out = {"rps": None, "utilization": None,
           "mean_service_s": (None if es is None else round(es, 6)),
           "lanes": lanes,
           "p99_ceiling_s": float(p99_ceiling_s)}
    if es is None or es <= 0.0 or p99_ceiling_s <= 0.0:
        return out
    rho = 1.0 - (es * LN100) / float(p99_ceiling_s)
    rho = max(0.0, min(MAX_UTILIZATION, rho))
    if rho <= 0.0:
        out["utilization"] = 0.0
        return out            # one service time already busts the p99
    out["utilization"] = round(rho, 4)
    out["rps"] = round(rho * max(lanes, 1) / es, 3)
    return out


def capacity_report(records: Sequence[dict], p99_ceiling_s: float,
                    mix: Optional[dict] = None) -> dict:
    """One-call capacity artifact from a soak ledger: per-family
    costs + the sustainable-rate prediction (the shape the elastic
    smoke and ``bench.py --elastic`` embed)."""
    costs = family_costs_from_records(records)
    return {"families": costs,
            "prediction": sustainable_rps(costs, p99_ceiling_s,
                                          mix=mix)}
