"""L-level composite incompressible Navier-Stokes (+ IB coupling).

Reference parity: the reference's PRODUCTION configuration — INS on an
arbitrary-depth AMR hierarchy with an FAC-class composite solve
(SURVEY.md §3.3 call stack, T8, P2/P3). Round 2 had the two-level
composite fluid (:mod:`ibamr_tpu.amr_ins`) and L-level hierarchies for
scalars only (:mod:`ibamr_tpu.amr_multilevel`); this module composes
the two: the same per-pair coarse-fine primitives (quadratic CF ghost
fill, coincident-face restriction, interface flux synchronization)
applied recursively over an L-level nested-box hierarchy, with ONE
FGMRES solve of the full L-level composite Poisson system per step.

Scheme (nested ratio-2 boxes, one box per level, shared dt — the
explicit-predictor trade of TwoLevelINS taken hierarchy-wide; dt is
bounded by the FINEST level's viscous/advective limits):

1. explicit convective + viscous predictor per level; each child level
   works on ghost-extended arrays quadratically interpolated from its
   parent at MAC positions (T10). Parent arrays of depth >= 1 are box
   arrays; the interpolation stencils stay interior because every box
   keeps >= 2 cells of clearance inside its parent (build_hierarchy).
2. slave covered regions bottom-up (coincident-face mean restriction).
3. **L-level composite projection**: FGMRES on the pytree
   (phi_0, ..., phi_{L-1}) of the composite Poisson operator — per
   level: covered cells carry the slaving identity, uncovered cells
   the 5/7-point Laplacian with the flux through every CF interface
   face replaced by the transverse mean of the child-side fluxes, and
   child cells the box Laplacian with CF-interpolated ghosts. The
   preconditioner applies an (approximate) per-level inverse: exact
   periodic FFT at the root + fast-diagonalization Dirichlet inverses
   on each box — the L-level generalization of the two-level
   "FAC collapsed to its exact-solver limit"; an external FAC V-cycle
   (:class:`ibamr_tpu.solvers.fac.FACMultilevelPoisson`) can be
   injected instead. FGMRES iteration counts stay level-count
   independent (pinned by tests).
4. correct every level with consistent gradients and synchronize.

The IB coupling (``MultiLevelIBINS``) keeps the structure inside the
FINEST box (the canonical usage: refinement tracks the immersed
boundary): transfers run at finest resolution, and the spread force is
restricted down the hierarchy level by level.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ibamr_tpu.amr import (FineBox, _box_mac_divergence, fill_fine_ghosts,
                           restrict_cc, restrict_mac)
from ibamr_tpu.amr_ins import (_box_cc_laplacian, _box_convective_rate,
                               _box_laplacian, _box_mac_from_periodic,
                               _periodic_from_box_mac,
                               box_mac_gradient_correct,
                               fill_fine_ghosts_mac,
                               interface_flux_correction,
                               scatter_box_mac_to_coarse)
from ibamr_tpu.amr_multilevel import LevelSpec, build_hierarchy
from ibamr_tpu.bc import DomainBC, dirichlet_axis
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils
from ibamr_tpu.ops.convection import convective_rate
from ibamr_tpu.solvers import fft
from ibamr_tpu.solvers.fastdiag import FastDiagSolver
from ibamr_tpu.solvers.krylov import fgmres

Array = jnp.ndarray
Vel = Tuple[Array, ...]


class MultiLevelCompositeProjection:
    """FGMRES solve of the L-level composite Poisson problem.

    ``levels`` come from :func:`ibamr_tpu.amr_multilevel.build_hierarchy`
    (level 0 periodic root; level l >= 1 a nested box in level l-1's
    index space). The solution pytree is a tuple of per-level
    cell-centered arrays.
    """

    def __init__(self, levels: Sequence[LevelSpec], tol: float = 1e-9,
                 m: int = 24, restarts: int = 8, preconditioner=None):
        self.levels = list(levels)
        self.L = len(self.levels)
        if self.L < 2:
            raise ValueError("need at least 2 levels (use the uniform "
                             "integrator for L=1)")
        self._external_precond = preconditioner
        # convergence surfacing (same contract as CompositeProjection):
        # eager projections record the inner FGMRES stats, mirrored
        # onto the FAC object when ``preconditioner`` is a bound method
        self.last_solve_stats = None
        self.record_stats = False
        self.tol = float(tol)
        self.m = int(m)
        self.restarts = int(restarts)
        self.dx = [spec.grid.dx for spec in self.levels]
        self.diag = [sum(2.0 / h ** 2 for h in spec.grid.dx)
                     for spec in self.levels]
        # GSPMD pins (parallel.mesh.make_sharded_multilevel_ib_step):
        # root-level arrays pinned to the spatial sharding, box arrays
        # pinned replicated, at every level crossing — the explicit-pin
        # pattern of the two-level CompositeProjection (wrong values
        # were observed when the partitioner propagated through mixed
        # scatter/gather composites unconstrained). None = unsharded
        # no-ops.
        self.root_sharding = None
        self.box_sharding = None
        # dense-transform twin of the root FFT inverse for the sharded
        # preconditioner path; built host-side by
        # build_dense_root_solver (eigenbasis constants must not be
        # created mid-trace)
        self._root_dense_solver = None

        # per level l < L-1: the region covered by the child box, and
        # the child-box slice in this level's index space
        self.box_sl: List[Tuple[slice, ...]] = []
        self.covered: List[Array] = []
        for l in range(self.L - 1):
            box = self.levels[l + 1].box
            dim = self.levels[l].grid.dim
            sl = tuple(slice(box.lo[a], box.hi[a]) for a in range(dim))
            self.box_sl.append(sl)
            cov = np.zeros(self.levels[l].grid.n, dtype=bool)
            cov[sl] = True
            self.covered.append(jnp.asarray(cov))

        # per-level preconditioner inverses: exact periodic FFT at the
        # root, fast-diagonalization Dirichlet on each box
        self.box_solvers = [
            FastDiagSolver(spec.grid,
                           DomainBC(axes=(dirichlet_axis(),)
                                    * spec.grid.dim),
                           ("cc",) * spec.grid.dim)
            for spec in self.levels[1:]]

    # -- sharding pins ---------------------------------------------------
    def _pin(self, x, l: int):
        """Pin a level-``l`` array: the root to the spatial sharding,
        box levels replicated (boxes are the SMALL levels by design —
        see make_sharded_two_level_ib_step's cost model)."""
        sh = self.root_sharding if l == 0 else self.box_sharding
        if sh is None:
            return x
        return jax.lax.with_sharding_constraint(x, sh)

    def _pin_all(self, xs):
        return tuple(self._pin(x, l) for l, x in enumerate(xs))

    def build_dense_root_solver(self) -> None:
        """Build the dense-periodic root inverse for the sharded
        preconditioner path (XLA's fft thunk rejects the partitioned
        layouts this solve produces). Host-side only."""
        if self._root_dense_solver is None:
            g = self.levels[0].grid
            self._root_dense_solver = FastDiagSolver(
                g, DomainBC.periodic(g.dim), ("cc",) * g.dim,
                dense_periodic=True)

    # -- composite operator ---------------------------------------------
    def _effective(self, phis: Sequence[Array]) -> List[Array]:
        """Top-down effective arrays: each level's covered region holds
        the restriction of the child's effective array."""
        eff = [None] * self.L
        eff[self.L - 1] = phis[self.L - 1]
        for l in range(self.L - 2, -1, -1):
            eff[l] = self._pin(phis[l].at[self.box_sl[l]].set(
                restrict_cc(eff[l + 1])), l)
        return eff

    def _extended(self, eff: Sequence[Array]) -> List[Optional[Array]]:
        """1-ghost extensions of each child level from its parent's
        effective array (None at the root)."""
        exts: List[Optional[Array]] = [None]
        for l in range(1, self.L):
            exts.append(self._pin(
                fill_fine_ghosts(eff[l], eff[l - 1],
                                 self.levels[l].box, ghost=1), l))
        return exts

    def operator(self, phis):
        eff = self._effective(phis)
        exts = self._extended(eff)
        out = []
        for l in range(self.L):
            g = self.levels[l].grid
            if l == 0:
                lap = stencils.laplacian(eff[0], g.dx)
            else:
                lap = _box_cc_laplacian(exts[l], g.dx, g.n)
            if l + 1 < self.L:
                box = self.levels[l + 1].box
                lap = interface_flux_correction(
                    lap, eff[l], exts[l + 1], box, g.dx,
                    self.levels[l + 1].grid.dx)
                lap = jnp.where(self.covered[l],
                                -self.diag[l] * phis[l], lap)
            if l == 0:
                # rank-one shift removes the composite constant
                # nullspace (as in the two-level operator)
                lap = lap + self.diag[0] * jnp.mean(eff[0])
            out.append(self._pin(lap, l))
        return tuple(out)

    def _precondition(self, rs):
        if self._external_precond is not None:
            # pin the external (e.g. FAC V-cycle) output too: the
            # sharded path's invariant is that every level crossing
            # re-constrains the partitioner, external preconditioners
            # included
            return self._pin_all(self._external_precond(rs))
        if self.root_sharding is not None:
            # sharded solve: the root exact inverse runs as dense
            # real-Fourier axis MATMULS (fastdiag dense_periodic) that
            # the SPMD partitioner distributes; XLA's fft thunk rejects
            # the partitioned layouts
            p0 = self._root_dense_solver.solve(rs[0], 0.0, 1.0,
                                               zero_nullspace=True)
        else:
            p0 = fft.solve_poisson_periodic(rs[0], self.dx[0])
        out = [p0]
        for l in range(1, self.L):
            out.append(self.box_solvers[l - 1].solve(rs[l], 0.0, 1.0))
        for l in range(self.L - 1):
            out[l] = jnp.where(self.covered[l],
                               -rs[l] / self.diag[l], out[l])
        return self._pin_all(out)

    # -- projection ------------------------------------------------------
    def project(self, us: Sequence[Vel]) -> Tuple[Tuple[Vel, ...],
                                                  Array]:
        """Make the composite MAC field discretely divergence-free.
        ``us[0]`` is the periodic root field (lower-face layout);
        ``us[l >= 1]`` are box MAC arrays (complete faces). Returns the
        corrected per-level velocities and the FGMRES iteration count
        (diagnostic for the level-independence tests)."""
        divs = []
        for l in range(self.L):
            g = self.levels[l].grid
            if l == 0:
                d = stencils.divergence(us[0], g.dx)
            else:
                d = _box_mac_divergence(us[l], g.dx)
            if l + 1 < self.L:
                d = jnp.where(self.covered[l], 0.0, d)
            divs.append(self._pin(d, l))

        sol = fgmres(self.operator, tuple(divs), M=self._precondition,
                     m=self.m, tol=self.tol, restarts=self.restarts)
        from ibamr_tpu.solvers.escalation import record_solve_stats
        record_solve_stats(
            self, sol, solver="fgmres",
            use_callback=self.record_stats,
            mirrors=(getattr(self._external_precond, "__self__", None),))
        phis = self._pin_all(sol.x)
        eff = self._effective(phis)
        exts = self._extended(eff)

        out: List[Vel] = []
        for l in range(self.L):
            g = self.levels[l].grid
            if l == 0:
                gc = stencils.gradient(eff[0], g.dx)
                out.append(tuple(self._pin(c - gr, l)
                                 for c, gr in zip(us[0], gc)))
            else:
                out.append(tuple(self._pin(c, l) for c in
                                 box_mac_gradient_correct(us[l], exts[l],
                                                          g.dx)))

        # synchronize bottom-up: covered parent faces := restriction
        for l in range(self.L - 2, -1, -1):
            out[l] = tuple(self._pin(c, l) for c in
                           scatter_box_mac_to_coarse(
                               out[l], restrict_mac(out[l + 1]),
                               self.levels[l + 1].box))
        return tuple(out), sol.iters

    def max_divergence(self, us: Sequence[Vel]) -> Array:
        """Max |div| over uncovered cells of every level + the full
        finest level."""
        acc = jnp.asarray(0.0, dtype=us[0][0].dtype)
        for l in range(self.L):
            g = self.levels[l].grid
            if l == 0:
                d = stencils.divergence(us[0], g.dx)
            else:
                d = _box_mac_divergence(us[l], g.dx)
            if l + 1 < self.L:
                d = jnp.where(self.covered[l], 0.0, d)
            acc = jnp.maximum(acc, jnp.max(jnp.abs(d)))
        return acc


# --------------------------------------------------------------------------
# the L-level integrator
# --------------------------------------------------------------------------

class MultiLevelINSState(NamedTuple):
    us: Tuple[Vel, ...]     # per-level MAC fields
    t: Array
    k: Array


class MultiLevelINS:
    """Composite L-level INS: explicit convection + diffusion on every
    level (shared dt), one composite projection per step."""

    GHOST = 2     # MAC predictor ghost width (PPM-free centered/upwind)

    def __init__(self, grid: StaggeredGrid, boxes: Sequence[FineBox],
                 rho: float = 1.0, mu: float = 0.01,
                 convective: bool = True, proj_tol: float = 1e-9,
                 proj_m: int = 24, proj_restarts: int = 8,
                 precond_factory=None):
        self.levels = build_hierarchy(grid, boxes)
        self.L = len(self.levels)
        self.grid = grid
        self.rho = float(rho)
        self.mu = float(mu)
        self.convective = bool(convective)
        # kept so a moving-window regrid can rebuild the preconditioner
        # at the new boxes instead of silently reverting to the default
        # (the ADVICE-round-2 regrid-config-carry contract)
        self.precond_factory = precond_factory
        precond = (precond_factory(self.levels)
                   if precond_factory is not None else None)
        self.proj = MultiLevelCompositeProjection(
            self.levels, tol=proj_tol, m=proj_m, restarts=proj_restarts,
            preconditioner=precond)

    # -- state -----------------------------------------------------------
    def initialize(self, vel_fn=None, dtype=jnp.float64
                   ) -> MultiLevelINSState:
        """Evaluate ``vel_fn(face_coord_arrays) -> component`` on every
        level's MAC faces (zeros when None), then project the composite
        field divergence-free and synchronize."""
        us = []
        for l, spec in enumerate(self.levels):
            g = spec.grid
            comps = []
            for d in range(g.dim):
                shape = tuple(g.n[e] + (1 if (l > 0 and e == d) else 0)
                              for e in range(g.dim))
                if vel_fn is None:
                    comps.append(jnp.zeros(shape, dtype=dtype))
                    continue
                coords = []
                for e in range(g.dim):
                    if e == d:
                        c = g.x_lo[e] + np.arange(shape[e]) * g.dx[e]
                    else:
                        c = g.x_lo[e] + (np.arange(shape[e]) + 0.5) \
                            * g.dx[e]
                    coords.append(c)
                mesh = np.meshgrid(*coords, indexing="ij")
                comps.append(jnp.asarray(vel_fn(d, mesh), dtype=dtype))
            us.append(tuple(comps))
        us, _ = self.proj.project(us)
        return MultiLevelINSState(
            us=tuple(us), t=jnp.zeros((), dtype=dtype),
            k=jnp.zeros((), dtype=jnp.int32))

    # -- one composite step ---------------------------------------------
    def _predict(self, us: Sequence[Vel], dt: float,
                 fs: Optional[Sequence[Optional[Vel]]] = None
                 ) -> List[Vel]:
        rho, mu = self.rho, self.mu
        stars: List[Vel] = []
        for l in range(self.L):
            g = self.levels[l].grid
            if l == 0:
                lap = stencils.laplacian_vel(us[0], g.dx)
                if self.convective:
                    nc = convective_rate(us[0], g.dx, "centered")
                else:
                    nc = tuple(jnp.zeros_like(c) for c in us[0])
            else:
                gext = self.GHOST
                # parent arrays (box layout for l >= 2) feed the MAC CF
                # ghost fill directly: the interpolation stencils stay
                # interior under the >= 2-cell nesting clearance, so
                # the periodic wrap in the index arithmetic never fires
                uext = fill_fine_ghosts_mac(us[l], us[l - 1],
                                            self.levels[l].box,
                                            ghost=gext)
                lap = _box_laplacian(uext, g.dx, gext, g.n)
                if self.convective:
                    nc = _box_convective_rate(uext, g.dx, gext, g.n)
                else:
                    nc = tuple(jnp.zeros_like(c) for c in lap)
            comps = []
            for d in range(g.dim):
                rhs = -nc[d] + (mu * lap[d]) / rho
                if fs is not None and fs[l] is not None:
                    rhs = rhs + fs[l][d] / rho
                comps.append(us[l][d] + dt * rhs)
            stars.append(tuple(comps))

        # slave covered parent regions bottom-up
        for l in range(self.L - 2, -1, -1):
            stars[l] = scatter_box_mac_to_coarse(
                stars[l], restrict_mac(stars[l + 1]),
                self.levels[l + 1].box)
        return stars

    def step(self, state: MultiLevelINSState, dt: float,
             fs: Optional[Sequence[Optional[Vel]]] = None
             ) -> MultiLevelINSState:
        stars = self._predict(state.us, dt, fs=fs)
        us_new, _ = self.proj.project(stars)
        return MultiLevelINSState(us=tuple(us_new), t=state.t + dt,
                                  k=state.k + 1)

    def max_divergence(self, state: MultiLevelINSState) -> Array:
        return self.proj.max_divergence(state.us)

    def stable_dt(self, state: MultiLevelINSState, cfl: float = 0.5
                  ) -> Array:
        """Advisory explicit-predictor dt bound (see
        TwoLevelINS.stable_dt): the FINEST level's advective CFL and
        viscous limits bind."""
        from ibamr_tpu.amr_ins import level_dt_limit

        out = jnp.asarray(jnp.inf, dtype=state.us[0][0].dtype)
        for spec, us in zip(self.levels, state.us):
            out = jnp.minimum(out, level_dt_limit(
                us, spec.grid.dx, spec.grid.dim, self.rho, self.mu,
                cfl))
        return out


def advance_multilevel(integ: MultiLevelINS, state: MultiLevelINSState,
                       dt: float, num_steps: int) -> MultiLevelINSState:
    def body(s, _):
        return integ.step(s, dt), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out


# --------------------------------------------------------------------------
# IB on the L-level hierarchy (structure inside the finest box)
# --------------------------------------------------------------------------

class MultiLevelIBState(NamedTuple):
    fluid: MultiLevelINSState
    X: Array
    U: Array
    mask: Array


class MultiLevelIBINS:
    """Explicit IB coupling on the L-level composite grid: transfers at
    FINEST resolution; the spread force restricted level by level down
    the hierarchy. The structure must keep delta-support clearance from
    the finest box boundary (proper-nesting analog)."""

    def __init__(self, grid: StaggeredGrid, boxes: Sequence[FineBox], ib,
                 rho: float = 1.0, mu: float = 0.01,
                 convective: bool = True, proj_tol: float = 1e-9,
                 proj_m: int = 24, proj_restarts: int = 8,
                 precond_factory=None):
        self.core = MultiLevelINS(grid, boxes, rho=rho, mu=mu,
                                  convective=convective,
                                  proj_tol=proj_tol, proj_m=proj_m,
                                  proj_restarts=proj_restarts,
                                  precond_factory=precond_factory)
        self.levels = self.core.levels
        self.L = self.core.L
        self.grid = grid
        self.finest_grid = self.levels[-1].grid
        self.ib = ib

    def initialize(self, X0, vel_fn=None) -> MultiLevelIBState:
        X = jnp.asarray(X0)
        fluid = self.core.initialize(vel_fn=vel_fn, dtype=X.dtype)
        return MultiLevelIBState(
            fluid=fluid, X=X, U=jnp.zeros_like(X),
            mask=jnp.ones(X.shape[0], dtype=X.dtype))

    def _interp(self, u_box: Vel, X, mask):
        from ibamr_tpu.ops import interaction

        u_per = _periodic_from_box_mac(u_box, self.finest_grid.n)
        return interaction.interpolate_vel(u_per, self.finest_grid, X,
                                           kernel=self.ib.kernel,
                                           weights=mask)

    def _spread_forces(self, F, X, mask) -> List[Optional[Vel]]:
        """Spread at finest resolution, restrict down the hierarchy.
        Level l < L-1 sees the conservative restriction scattered into
        its (zero elsewhere) force array."""
        from ibamr_tpu.ops import interaction

        f_per = interaction.spread_vel(F, self.finest_grid, X,
                                       kernel=self.ib.kernel,
                                       weights=mask)
        fs: List[Optional[Vel]] = [None] * self.L
        fs[self.L - 1] = _box_mac_from_periodic(f_per)
        for l in range(self.L - 2, -1, -1):
            g = self.levels[l].grid
            dim = g.dim
            zero = tuple(
                jnp.zeros(tuple(g.n[e] + (1 if (l > 0 and e == d) else 0)
                                for e in range(dim)),
                          dtype=f_per[0].dtype)
                for d in range(dim))
            fs[l] = scatter_box_mac_to_coarse(
                zero, restrict_mac(fs[l + 1]), self.levels[l + 1].box)
        return fs

    def step(self, state: MultiLevelIBState, dt: float
             ) -> MultiLevelIBState:
        fluid = state.fluid
        X_n = state.X
        uf = fluid.us[self.L - 1]
        U_n = self._interp(uf, X_n, state.mask)
        X_half = X_n + 0.5 * dt * U_n
        t_half = fluid.t + 0.5 * dt
        F = self.ib.compute_force(X_half, U_n, t_half)
        fs = self._spread_forces(F, X_half, state.mask)
        fluid_new = self.core.step(fluid, dt, fs=fs)
        u_mid = tuple(0.5 * (a + b)
                      for a, b in zip(uf, fluid_new.us[self.L - 1]))
        U_half = self._interp(u_mid, X_half, state.mask)
        X_new = X_n + dt * U_half
        return MultiLevelIBState(fluid=fluid_new, X=X_new, U=U_half,
                                 mask=state.mask)


def advance_multilevel_ib(integ: MultiLevelIBINS,
                          state: MultiLevelIBState, dt: float,
                          num_steps: int) -> MultiLevelIBState:
    def body(s, _):
        return integ.step(s, dt), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out


# --------------------------------------------------------------------------
# moving-window regrid at arbitrary depth (SURVEY.md §3.4 for L levels)
# --------------------------------------------------------------------------

def regrid_multilevel_ib(integ: MultiLevelIBINS, state: MultiLevelIBState,
                         move_threshold: int = 2
                         ) -> Tuple[MultiLevelIBINS, MultiLevelIBState]:
    """Host-side marker-tagged regrid of the WHOLE box chain: every
    level's fixed-shape window is re-centered on the current markers
    (in its own parent's index space, nesting clearance enforced
    level by level — the depth-L generalization of
    :func:`ibamr_tpu.amr_ins.regrid_two_level_ib`). When any window
    moves, the state transfers:

    1. each new window's velocity = divergence-preserving MAC
       prolongation of its (already transferred) parent field (T10);
    2. surviving same-level data copied across the old/new overlap —
       the overlap is computed in PHYSICAL coordinates because a moved
       parent shifts the child's index frame;
    3. covered parent faces re-slaved bottom-up and ONE composite
       projection cleans the prolongation/copy seams.

    Returns (integ, state); both unchanged when no window moved."""
    from ibamr_tpu.amr import prolong_mac_div_preserving
    from ibamr_tpu.amr_ins import _window_lo_from_markers

    old_levels = integ.levels
    L = integ.L
    grid = integ.grid

    new_boxes: List[FineBox] = []
    parent_grid = grid
    moved = False
    for l in range(1, L):
        old = old_levels[l].box
        lo = _window_lo_from_markers(parent_grid, state.X, old.shape)
        if max(abs(a - b) for a, b in zip(lo, old.lo)) < move_threshold \
                and not moved:
            # a moved ANCESTOR forces recomputation below it even if
            # this window's origin is unchanged in the parent frame
            lo = old.lo
        else:
            moved = moved or tuple(lo) != tuple(old.lo)
        new_boxes.append(FineBox(lo=tuple(lo), shape=old.shape,
                                 ratio=old.ratio))
        parent_grid = new_boxes[-1].fine_grid(parent_grid)
    if not moved:
        return integ, state

    core = integ.core
    integ2 = MultiLevelIBINS(grid, new_boxes, integ.ib, rho=core.rho,
                             mu=core.mu, convective=core.convective,
                             proj_tol=core.proj.tol, proj_m=core.proj.m,
                             proj_restarts=core.proj.restarts,
                             precond_factory=core.precond_factory)
    new_levels = integ2.levels

    us_new: List[Vel] = [state.fluid.us[0]]       # root rides along
    for l in range(1, L):
        pg = new_levels[l - 1].grid
        box = new_levels[l].box
        parent = us_new[l - 1]
        if l >= 2:
            # box layout -> periodic layout of the parent window; the
            # wrap images never reach the prolonged region (>= 2-cell
            # nesting clearance vs the 1-cell prolongation stencil)
            from ibamr_tpu.amr_ins import _periodic_from_box_mac
            parent = _periodic_from_box_mac(parent, pg.n)
        uf = list(prolong_mac_div_preserving(parent, pg, box))

        # overlap copy in physical coordinates (integer at this level's
        # resolution: window origins live on the parent lattice)
        og = old_levels[l].grid
        ng = new_levels[l].grid
        dxl = ng.dx
        ov_lo = [max(a, b) for a, b in zip(og.x_lo, ng.x_lo)]
        ov_hi = [min(a, b) for a, b in zip(og.x_up, ng.x_up)]
        if all(h > lo_ + 0.5 * dd
               for lo_, h, dd in zip(ov_lo, ov_hi, dxl)):
            src0 = [int(round((ov_lo[d] - og.x_lo[d]) / dxl[d]))
                    for d in range(grid.dim)]
            dst0 = [int(round((ov_lo[d] - ng.x_lo[d]) / dxl[d]))
                    for d in range(grid.dim)]
            cnt = [int(round((ov_hi[d] - ov_lo[d]) / dxl[d]))
                   for d in range(grid.dim)]
            for d in range(grid.dim):
                src = tuple(slice(src0[e], src0[e] + cnt[e]
                                  + (1 if e == d else 0))
                            for e in range(grid.dim))
                dst = tuple(slice(dst0[e], dst0[e] + cnt[e]
                                  + (1 if e == d else 0))
                            for e in range(grid.dim))
                uf[d] = uf[d].at[dst].set(state.fluid.us[l][d][src])
        us_new.append(tuple(uf))

    # re-slave covered parent faces bottom-up, then clean the seams
    for l in range(L - 2, -1, -1):
        us_new[l] = scatter_box_mac_to_coarse(
            us_new[l], restrict_mac(us_new[l + 1]),
            new_levels[l + 1].box)
    us_p, _ = integ2.core.proj.project(us_new)
    fluid = MultiLevelINSState(us=tuple(us_p), t=state.fluid.t,
                               k=state.fluid.k)
    return integ2, MultiLevelIBState(fluid=fluid, X=state.X, U=state.U,
                                     mask=state.mask)


def advance_multilevel_ib_regridding(integ: MultiLevelIBINS,
                                     state: MultiLevelIBState, dt: float,
                                     num_steps: int,
                                     regrid_interval: int = 20,
                                     on_chunk=None
                                     ) -> Tuple[MultiLevelIBINS,
                                                MultiLevelIBState]:
    """Advance with the whole window chain tracking the structure:
    jitted chunks with host-side regrids between them (the reference's
    regrid cadence, §3.4). A static chain re-traces nothing; a moved
    chain compiles anew at its new static origins."""
    from ibamr_tpu.amr_ins import advance_with_regrids

    return advance_with_regrids(integ, state, dt, num_steps,
                                regrid_interval, advance_multilevel_ib,
                                regrid_multilevel_ib,
                                on_chunk=on_chunk)
