"""ConstraintIB: rigid / prescribed-kinematics bodies by momentum projection.

Reference parity: ``ConstraintIBMethod`` + ``ConstraintIBKinematics``
(P16, SURVEY.md §2.2; Bhalla, Bale, Griffith, Patankar, JCP 250 (2013)
446-476 — the fictitious-domain momentum-projection formulation). Unlike
CIB (P15), no constraint SOLVE happens: after an unconstrained fluid
step, the velocity inside each body is PROJECTED onto rigid modes (plus
any prescribed deformational kinematics) and imposed back on the grid,
followed by a divergence-free projection.

One step:
  1. unconstrained INS step                         -> u*
  2. interpolate u* at body markers                 -> U_i
  3. least-squares rigid projection per body        -> (V_b, W_b)
     (free DOFs keep the projected momentum — that IS momentum
     conservation; prescribed DOFs are overwritten from the kinematics)
  4. constrained marker velocity U_b = K(V,W) + U_def
     (U_def = prescribed deformation velocity with its rigid component
     projected out, so it carries no net momentum)
  5. grid correction u <- u* + S_norm (U_b - U_i), where S_norm is
     delta-spreading NORMALIZED by the spread indicator (a partition of
     unity inside the body) — velocity replacement, not force addition
  6. re-project to the divergence-free space; advance X with U_b.

TPU-first: all of 1-6 is one fused jittable function; per-body
reductions are ``segment_sum`` over the static ``body_id`` array and the
3x3 (or scalar) inertia solves run batched on the MXU.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.cib import (RigidBodies, body_centroids,
                                       n_rigid_modes, rigid_velocity)
from ibamr_tpu.integrators.ins import INSState, INSStaggeredIntegrator
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.delta import Kernel

Vel = Tuple[jnp.ndarray, ...]


class ConstraintIBState(NamedTuple):
    ins: INSState
    X: jnp.ndarray          # (N, dim) marker positions
    U_body: jnp.ndarray     # (B, modes) last rigid motion (diagnostic)


def project_rigid(X: jnp.ndarray, bodies: RigidBodies,
                  U: jnp.ndarray) -> jnp.ndarray:
    """Least-squares projection of marker velocities onto rigid modes
    per body -> (B, n_rigid_modes) = (V, W) about each centroid.

    Equal marker weights (the reference weights by material volume; for
    uniformly seeded bodies these coincide)."""
    N, dim = X.shape
    nb = bodies.n_bodies
    bid = bodies.body_id
    ones = jnp.ones((N, 1), X.dtype)
    cnt = jnp.maximum(jax.ops.segment_sum(ones, bid, num_segments=nb), 1.0)
    V = jax.ops.segment_sum(U, bid, num_segments=nb) / cnt

    cent = body_centroids(X, bodies)
    r = X - cent[bid]
    u_rel = U - V[bid]
    if dim == 2:
        # scalar angular momentum / moment of inertia
        L = jax.ops.segment_sum(r[:, 0] * u_rel[:, 1]
                                - r[:, 1] * u_rel[:, 0],
                                bid, num_segments=nb)
        I = jax.ops.segment_sum(jnp.sum(r * r, axis=1), bid,
                                num_segments=nb)
        W = (L / jnp.maximum(I, 1e-30))[:, None]
        return jnp.concatenate([V, W], axis=1)
    # 3D: solve I W = L with the batched inertia tensor
    L = jax.ops.segment_sum(jnp.cross(r, u_rel), bid, num_segments=nb)
    rr = jax.ops.segment_sum(
        jnp.einsum("ni,nj->nij", r, r), bid, num_segments=nb)
    tr = jnp.trace(rr, axis1=-2, axis2=-1)
    I = tr[:, None, None] * jnp.eye(dim, dtype=X.dtype) - rr
    I = I + 1e-30 * jnp.eye(dim, dtype=X.dtype)
    W = jnp.linalg.solve(I, L[..., None])[..., 0]
    return jnp.concatenate([V, W], axis=1)


class ConstraintIBMethod:
    """Momentum-projection constraint IB coupling (P16).

    ``free``: (B, n_rigid_modes) 0/1 — 1 keeps the momentum-projected
    value (freely moving DOF), 0 takes the prescribed value from
    ``prescribed_fn(t) -> (B, n_rigid_modes)``.
    ``deformation_fn(t, X) -> (N, dim)``: optional prescribed
    deformational velocity (swimming gaits etc.); its rigid component is
    projected out automatically.
    """

    def __init__(self, ins: INSStaggeredIntegrator, bodies: RigidBodies,
                 free=None,
                 prescribed_fn: Optional[Callable] = None,
                 deformation_fn: Optional[Callable] = None,
                 kernel: Kernel = "IB_4",
                 indicator_floor: float = 1e-4,
                 density_ratio=None, gravity=None,
                 virtual_mass: float = 1.0):
        self.ins = ins
        self.bodies = bodies
        dim = ins.grid.dim
        modes = n_rigid_modes(dim)
        if free is None:
            free = jnp.ones((bodies.n_bodies, modes), dtype=ins.dtype)
        self.free = jnp.asarray(free, dtype=ins.dtype)
        self.prescribed_fn = prescribed_fn
        self.deformation_fn = deformation_fn
        self.kernel = kernel
        # spread-indicator threshold below which a cell is treated as
        # outside every body (no correction applied)
        self.indicator_floor = float(indicator_floor)
        # inertial (time-dependent) rigid-body dynamics: per-body
        # density ratio rho_body/rho_fluid (the reference's free-moving
        # ConstraintIB bodies with excess inertia — Bhalla et al. 2013
        # §2.4). ratio == 1 (or None) is the neutrally-buoyant limit
        # where the momentum projection alone IS the dynamics.
        self.density_ratio = None if density_ratio is None else \
            jnp.asarray(density_ratio, dtype=ins.dtype).reshape(-1, 1)
        # virtual-mass stabilization weight (0 = raw explicit
        # Newton-Euler update; 1 = interior-fluid added mass)
        self.virtual_mass = float(virtual_mass)
        if gravity is None:
            self._g_modes = None
        else:
            if self.density_ratio is None:
                raise ValueError(
                    "gravity without density_ratio has no effect: a "
                    "neutrally-buoyant body feels no net gravity; pass "
                    "density_ratio to enable the excess-mass dynamics")
            g = jnp.asarray(gravity, dtype=ins.dtype)
            self._g_modes = jnp.concatenate(
                [g, jnp.zeros(modes - dim, dtype=ins.dtype)])[None, :]

    # -- normalized velocity imposition --------------------------------------
    def _impose(self, u: Vel, X: jnp.ndarray, dU: jnp.ndarray) -> Vel:
        """u + S_norm(dU): delta-spread the velocity correction and
        normalize by the spread indicator so the correction is a
        velocity (partition-of-unity) rather than a force density."""
        grid = self.ins.grid
        out = []
        ones = jnp.ones(X.shape[0], dtype=dU.dtype)
        for d in range(grid.dim):
            num = interaction.spread(dU[:, d], grid, X, centering=d,
                                     kernel=self.kernel)
            den = interaction.spread(ones, grid, X, centering=d,
                                     kernel=self.kernel)
            corr = jnp.where(den > self.indicator_floor, num
                             / jnp.maximum(den, self.indicator_floor), 0.0)
            out.append(u[d] + corr)
        return tuple(out)

    # -- one coupled step -----------------------------------------------------
    def step(self, state: ConstraintIBState,
             dt: float) -> ConstraintIBState:
        ins, grid = self.ins, self.ins.grid
        bodies = self.bodies
        X = state.X

        # 1. unconstrained fluid step
        ins_star = ins.step(state.ins, dt)
        u_star = ins_star.u
        t_new = ins_star.t

        # 2. interpolate at markers
        U_i = interaction.interpolate_vel(u_star, grid, X,
                                          kernel=self.kernel)

        # 3. rigid projection; free DOFs keep it, others prescribed
        U_proj = project_rigid(X, bodies, U_i)
        # 3b. excess-inertia update for density-mismatched free bodies:
        #   V = V_fluid + a * (V_prev + dt g - V_fluid),
        #   a = (s-1)/(s+vm),  s = rho_b/rho_f.
        # The per-step gravity kick a*dt*g is the ADDED-MASS-corrected
        # buoyant acceleration (s-1)g/(s+vm) — for vm = 1 (default)
        # exactly the classical early-time free fall of a 2D cylinder
        # (added mass = displaced mass; use vm = 0.5 for a 3D sphere).
        # |a| < 1 for every s > 0 when vm >= 1, which is the
        # stabilization the raw explicit vm = 0 form (a = (s-1)/s,
        # added-mass unstable for light bodies) lacks. NOTE the map's
        # fixed-point slip vs the projected fluid velocity,
        # D = a/(1-a) dt g = (s-1)/(1+vm) dt g, is an O(dt)
        # operator-splitting artifact, NOT the terminal velocity: the
        # terminal state is wake-drag-limited through the fluid solve
        # (the slip here is ~1e-3 of the resolved velocities).
        # test_constraint_ib_dynamics pins the early-time added-mass
        # trajectory quantitatively (ADVICE round 2).
        if self.density_ratio is not None:
            s = self.density_ratio
            U_prev = state.U_body
            if self._g_modes is not None:
                U_prev = U_prev + dt * self._g_modes
            U_proj = U_proj + (s - 1.0) / (s + self.virtual_mass) \
                * (U_prev - U_proj)
        if self.prescribed_fn is not None:
            U_pres = jnp.asarray(self.prescribed_fn(t_new),
                                 dtype=U_proj.dtype)
            U_body = self.free * U_proj + (1.0 - self.free) * U_pres
        else:
            U_body = U_proj

        # 4. constrained marker velocity
        U_b = rigid_velocity(X, bodies, U_body)
        if self.deformation_fn is not None:
            U_def = self.deformation_fn(t_new, X)
            U_def = U_def - rigid_velocity(
                X, bodies, project_rigid(X, bodies, U_def))
            U_b = U_b + U_def

        # 5. impose on the grid, 6. restore incompressibility
        u_corr = self._impose(u_star, X, U_b - U_i)
        u_new, _ = ins.project(u_corr, grid.dx)
        ins_new = ins_star._replace(u=u_new)

        X_new = X + dt * U_b
        return ConstraintIBState(ins=ins_new, X=X_new, U_body=U_body)

    # -- setup ----------------------------------------------------------------
    def initialize(self, X0, ins_state: Optional[INSState] = None
                   ) -> ConstraintIBState:
        X = jnp.asarray(X0, dtype=self.ins.dtype)
        if ins_state is None:
            ins_state = self.ins.initialize()
        modes = n_rigid_modes(self.ins.grid.dim)
        return ConstraintIBState(
            ins=ins_state, X=X,
            U_body=jnp.zeros((self.bodies.n_bodies, modes),
                             dtype=self.ins.dtype))


def advance_constraint_ib(method: ConstraintIBMethod,
                          state: ConstraintIBState, dt: float,
                          num_steps: int) -> ConstraintIBState:
    """Advance ``num_steps`` under one jitted lax.scan."""
    def body(s, _):
        return method.step(s, dt), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out


def fill_disc(center, radius: float, spacing: float,
              dtype=None) -> jnp.ndarray:
    """Uniformly seeded solid disc of markers (the volumetric body
    sampling ConstraintIB needs, vs CIB's surface-only blobs)."""
    import numpy as np
    n = int(np.ceil(2 * radius / spacing)) + 1
    ax = np.linspace(-radius, radius, n)
    xx, yy = np.meshgrid(ax, ax, indexing="ij")
    keep = xx ** 2 + yy ** 2 <= radius ** 2
    pts = np.stack([xx[keep] + center[0], yy[keep] + center[1]], axis=1)
    return jnp.asarray(pts, dtype=dtype or jnp.float32)
