"""CIB: constrained rigid-body immersed-boundary method in Stokes flow.

Reference parity: the CIB module (P15, SURVEY.md §2.2 —
``CIBMethod``, ``CIBSaddlePointSolver``, ``CIBMobilitySolver``,
``DirectMobilitySolver``, ``KrylovMobilitySolver``; acceptance config
``examples/CIB/ex0``). Rigid bodies are marker blobs; the constraint
formulation solves for Lagrange-multiplier forces ``lambda`` on the
markers such that the flow they induce moves every marker rigidly:

    M lambda = K U        (markers move with the rigid motion U)
    K^T lambda = F_ext    (force/torque balance on free bodies)

where ``M = J L^{-1} S`` is the marker mobility (interp o Stokes-solve o
spread — symmetric positive semi-definite by spread/interp adjointness),
``K`` maps body rigid motions (V, W) to marker velocities, and ``L`` is
the steady Stokes operator.

TPU-first redesign: the reference applies M through its PETSc Krylov
staggered-Stokes stack and assembles dense mobility matrices via Fortran
RPY kernels; here one M application is spread -> two FFT passes -> interp
(exact, SURVEY.md §3.3), M^{-1} is the jit-native CG of
``solvers.krylov``, and the small body-resistance system
``R = K^T M^{-1} K`` (6B x 6B in 3D) is formed by applying M^{-1} to the
rigid basis columns and solved densely on the MXU. All marker state is
fixed-shape ``(N, dim)`` arrays grouped by a static ``body_id``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.delta import Kernel
from ibamr_tpu.solvers import fft, krylov


class RigidBodies(NamedTuple):
    """Static marker->body structure (the analog of CIBMethod's per-body
    LData registration)."""
    body_id: jnp.ndarray     # (N,) int32 body index per marker
    n_bodies: int            # static


def n_rigid_modes(dim: int) -> int:
    """Rigid-motion DOFs per body: translations + rotations."""
    return dim + (1 if dim == 2 else 3)


def body_centroids(X: jnp.ndarray, bodies: RigidBodies) -> jnp.ndarray:
    """(B, dim) mean marker position per body (the tracking point the
    reference calls the center of mass)."""
    nb = bodies.n_bodies
    sums = jax.ops.segment_sum(X, bodies.body_id, num_segments=nb)
    cnt = jax.ops.segment_sum(jnp.ones((X.shape[0], 1), X.dtype),
                              bodies.body_id, num_segments=nb)
    # a body id with no markers (config error) yields a zero centroid
    # rather than NaN-poisoning the whole solve
    return sums / jnp.maximum(cnt, 1.0)


def rigid_velocity(X: jnp.ndarray, bodies: RigidBodies,
                   U: jnp.ndarray) -> jnp.ndarray:
    """K U: marker velocities of rigid motions ``U`` (B, n_rigid_modes)
    = (V, W) per body, about each body's centroid."""
    dim = X.shape[1]
    cent = body_centroids(X, bodies)
    r = X - cent[bodies.body_id]
    V = U[:, :dim][bodies.body_id]
    if dim == 2:
        w = U[:, 2][bodies.body_id]
        rot = jnp.stack([-w * r[:, 1], w * r[:, 0]], axis=-1)
    else:
        W = U[:, 3:6][bodies.body_id]
        rot = jnp.cross(W, r)
    return V + rot


def rigid_force_torque(X: jnp.ndarray, bodies: RigidBodies,
                       lam: jnp.ndarray) -> jnp.ndarray:
    """K^T lambda: net force and torque (about the centroid) per body,
    (B, n_rigid_modes). Exact adjoint of ``rigid_velocity``."""
    dim = X.shape[1]
    nb = bodies.n_bodies
    cent = body_centroids(X, bodies)
    r = X - cent[bodies.body_id]
    F = jax.ops.segment_sum(lam, bodies.body_id, num_segments=nb)
    if dim == 2:
        tau = jax.ops.segment_sum(
            r[:, 0] * lam[:, 1] - r[:, 1] * lam[:, 0],
            bodies.body_id, num_segments=nb)
        return jnp.concatenate([F, tau[:, None]], axis=-1)
    tau = jax.ops.segment_sum(jnp.cross(r, lam), bodies.body_id,
                              num_segments=nb)
    return jnp.concatenate([F, tau], axis=-1)


class MobilityInfo(NamedTuple):
    """Convergence diagnostics of the inner CG mobility solves (the
    analog of the reference's KSP convergence monitoring): callers should
    check ``converged`` before trusting body motions."""
    converged: jnp.ndarray    # bool: all inner solves converged
    max_resnorm: jnp.ndarray  # worst final residual norm
    max_iters: jnp.ndarray    # most iterations taken by any solve


class CIBMethod:
    """Direct mobility solver for rigid bodies in periodic Stokes flow.

    ``solve_mobility``  : given external (F, T) per body -> rigid motions
                          U = N (F, T) with N = R^{-1} (the mobility
                          problem of free bodies).
    ``solve_constraint``: given prescribed rigid motions -> constraint
                          forces lambda and the net (F, T) needed (the
                          prescribed-kinematics problem).
    Both go through ``R = K^T M^{-1} K`` built by ``resistance_matrix``.
    """

    def __init__(self, grid: StaggeredGrid, bodies: RigidBodies,
                 mu: float = 1.0, kernel: Kernel = "IB_4",
                 cg_tol: float = 1e-9, cg_maxiter: int = 500,
                 domain: str = "periodic",
                 stokes_tol: float = 1e-10):
        self.grid = grid
        self.bodies = bodies
        self.mu = float(mu)
        self.kernel = kernel
        self.cg_tol = float(cg_tol)
        self.cg_maxiter = int(cg_maxiter)
        # domain = "periodic": the FFT steady-Stokes fluid solve (the
        # original CIB configuration — zero-mean traction-free frame).
        # domain = "walled": no-slip enclosure — the fluid solve is the
        # coupled saddle FGMRES of solvers.stokes at alpha = 0 (steady)
        # with every side a prescribed u = 0 wall (round 5, VERDICT
        # item 3c: CIB composed with nonperiodic boundaries; the
        # reference gets this by configuring CIBStaggeredStokesSolver
        # over the wall-BC'd INS machinery [U]). Bodies must keep
        # delta-support clearance from the walls (the layout-bridge
        # contract shared with the open-boundary IB coupling).
        if domain not in ("periodic", "walled"):
            raise ValueError(f"unknown CIB domain {domain!r}")
        self.domain = domain
        self._stokes = None
        if domain == "walled":
            from ibamr_tpu.solvers.stokes import (StaggeredStokesSolver,
                                                  cavity_bc)

            self._stokes = StaggeredStokesSolver(
                grid.n, grid.dx, cavity_bc(grid.dim), alpha=0.0,
                mu=self.mu, tol=float(stokes_tol))
        # optional GSPMD hook: applied to the spread force and the
        # solved velocity inside mobility_apply so a sharded wrapper
        # (parallel.mesh.make_sharded_cib_constraint) can keep the
        # grid fields distributed through the nested solves
        self.field_pin = None

    # -- the mobility operator (the hot composition) -------------------------
    def mobility_apply(self, X: jnp.ndarray, lam: jnp.ndarray) -> jnp.ndarray:
        """M lambda = J L^{-1} S lambda — spread marker forces, solve
        steady Stokes, interpolate back. SPD up to the delta-kernel
        regularization (the oracle the tests check). The fluid solve is
        the FFT inverse (periodic) or the walled saddle FGMRES; both
        are self-adjoint on the div-free subspace, so CG stays valid."""
        f = interaction.spread_vel(lam, self.grid, X, kernel=self.kernel)
        if self.field_pin is not None:
            f = tuple(self.field_pin(c) for c in f)
        if self.domain == "walled":
            from ibamr_tpu.ops.stencils import (mac_complete_from_periodic,
                                                mac_periodic_from_complete)

            s = self._stokes
            f_fc = mac_complete_from_periodic(
                tuple(c.astype(s.dtype) for c in f))
            sol = s.solve(s.make_rhs(f_u=f_fc))
            u = mac_periodic_from_complete(
                tuple(c.astype(lam.dtype) for c in sol.u), self.grid.n)
        else:
            u, _ = fft.solve_stokes_periodic(f, self.grid.dx, self.mu)
        if self.field_pin is not None:
            u = tuple(self.field_pin(c) for c in u)
        return interaction.interpolate_vel(u, self.grid, X,
                                           kernel=self.kernel)

    def mobility_solve(self, X: jnp.ndarray,
                       rhs: jnp.ndarray) -> krylov.SolveResult:
        """CG solve M lambda = rhs (rhs: (N, dim) marker velocities)."""
        return krylov.cg(lambda l: self.mobility_apply(X, l), rhs,
                         tol=self.cg_tol, maxiter=self.cg_maxiter)

    # -- dense body-space solves --------------------------------------------
    def _rigid_basis(self, X: jnp.ndarray) -> jnp.ndarray:
        """(B*nm, N, dim): K applied to each unit rigid mode."""
        nb = self.bodies.n_bodies
        nm = n_rigid_modes(self.grid.dim)
        eye = jnp.eye(nb * nm, dtype=X.dtype).reshape(nb * nm, nb, nm)
        return jax.vmap(lambda e: rigid_velocity(X, self.bodies, e))(eye)

    def resistance_matrix(self, X: jnp.ndarray
                          ) -> Tuple[jnp.ndarray, jnp.ndarray, MobilityInfo]:
        """R = K^T M^{-1} K (B*nm square, symmetric positive definite),
        Lam = M^{-1} K (B*nm, N, dim) for reuse, and the CG diagnostics.

        The reference's DirectMobilitySolver assembles dense RPY mobility
        matrices in Fortran; here each column is one CG solve against the
        exact discrete mobility, batched with vmap."""
        KE = self._rigid_basis(X)                     # (Bnm, N, dim)
        res = jax.vmap(lambda b: self.mobility_solve(X, b))(KE)
        Lam = res.x
        info = MobilityInfo(converged=jnp.all(res.converged),
                            max_resnorm=jnp.max(res.resnorm),
                            max_iters=jnp.max(res.iters))
        R = jnp.einsum('and,bnd->ab', KE, Lam)
        # symmetrize (CG tolerance noise)
        return 0.5 * (R + R.T), Lam, info

    def solve_mobility(self, X: jnp.ndarray, FT: jnp.ndarray
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, MobilityInfo]:
        """Free-body mobility problem: external force/torque FT
        (B, nm) -> rigid motions U (B, nm), marker forces lambda, and
        the inner-solve diagnostics."""
        nb = self.bodies.n_bodies
        nm = n_rigid_modes(self.grid.dim)
        R, Lam, info = self.resistance_matrix(X)
        U = jnp.linalg.solve(R, FT.reshape(-1)).reshape(nb, nm)
        lam = jnp.einsum('a,and->nd', U.reshape(-1), Lam)
        return U, lam, info

    def solve_constraint(self, X: jnp.ndarray, U: jnp.ndarray
                         ) -> Tuple[jnp.ndarray, jnp.ndarray, MobilityInfo]:
        """Prescribed-kinematics problem: rigid motions U (B, nm) ->
        constraint forces lambda (N, dim), required net (F, T), and the
        inner-solve diagnostics."""
        rhs = rigid_velocity(X, self.bodies, U)
        res = self.mobility_solve(X, rhs)
        lam = res.x
        FT = rigid_force_torque(X, self.bodies, lam)
        info = MobilityInfo(converged=res.converged,
                            max_resnorm=res.resnorm,
                            max_iters=res.iters)
        return lam, FT, info

    # -- quasi-static time stepping ------------------------------------------
    def step(self, X: jnp.ndarray, FT: jnp.ndarray, dt: float
             ) -> Tuple[jnp.ndarray, jnp.ndarray, MobilityInfo]:
        """Advance free bodies one forward-Euler step under external
        force/torque FT (creeping flow: velocities are instantaneous)."""
        U, _, info = self.solve_mobility(X, FT)
        Xdot = rigid_velocity(X, self.bodies, U)
        return X + dt * Xdot, U, info

    # -- Krylov free-body menu (the KrylovFreeBodyMobilitySolver analog) -----
    def free_body_solver(self, X: jnp.ndarray, radius: float,
                         inner_tol: Optional[float] = None,
                         outer_tol: float = 1e-7):
        """Build a ``KrylovFreeBodyMobilitySolver`` over THIS method's
        exact mobility (P15 menu: outer body-space FGMRES, inner
        preconditioned CG, dense regularized-Stokeslet preconditioners).
        ``radius`` is the marker hydrodynamic radius for the dense
        approximate tensors — the marker spacing (~grid dx) is the
        standard choice."""
        from ibamr_tpu.solvers.mobility import KrylovFreeBodyMobilitySolver
        return KrylovFreeBodyMobilitySolver(
            lambda lam: self.mobility_apply(X, lam), self.bodies, X,
            radius, self.mu,
            inner_tol=self.cg_tol if inner_tol is None else inner_tol,
            inner_maxiter=self.cg_maxiter, outer_tol=outer_tol)

    def step_krylov(self, X: jnp.ndarray, FT: jnp.ndarray, dt: float,
                    radius: float):
        """Forward-Euler free-body step through the Krylov menu: one
        outer body-mobility solve instead of ``n_bodies * n_rigid_modes``
        resistance-column solves — the scalable path for many bodies."""
        solver = self.free_body_solver(X, radius)
        res = solver.solve(FT)
        Xdot = rigid_velocity(X, self.bodies, res.U)
        return X + dt * Xdot, res.U, res


class FreeBodyTrajectory(NamedTuple):
    X: jnp.ndarray           # final marker positions (N, d)
    centroids: jnp.ndarray   # (num_steps, B, d) per-step body centroids
    U: jnp.ndarray           # (num_steps, B, nm) per-step rigid motions


def advance_free_bodies(method: "CIBMethod", X: jnp.ndarray, FT_fn,
                        dt: float, num_steps: int,
                        radius: Optional[float] = None
                        ) -> FreeBodyTrajectory:
    """TIME-DEPENDENT free-body dynamics under the mobility formulation
    (VERDICT round 3, missing #5): integrate body positions with the
    per-step rigid velocities of the body-mobility solve — the
    reference's ``CIBMethod`` advancing force/torque-driven bodies in
    time (SURVEY.md P15 [U]), as opposed to the single quasi-static
    solve of ``solve_mobility``.

    ``FT_fn(t, centroids) -> (B, nm)`` supplies the external
    force/torque each step (constant gravity, position-dependent traps,
    time-ramped loads). Each step is one Krylov body-mobility solve
    (``radius`` given — the scalable path; defaults to the direct
    resistance route otherwise) followed by a forward-Euler rigid
    update of every marker; the whole trajectory is one ``lax.scan``.
    Marker rigidity is exact by construction (positions move with the
    body's rigid modes only), so body shape is preserved to roundoff
    over arbitrarily many steps — the property the trajectory tests
    pin alongside the ConstraintIB cross-check."""
    bodies = method.bodies

    def body(carry, k):
        X, t = carry
        cents = body_centroids(X, bodies)
        FT = FT_fn(t, cents)
        if radius is not None:
            X_new, U, _ = method.step_krylov(X, FT, dt, radius)
        else:
            X_new, U, _ = method.step(X, FT, dt)
        return (X_new, t + dt), (body_centroids(X_new, bodies), U)

    (X_fin, _), (cents, Us) = jax.lax.scan(
        body, (X, jnp.zeros((), dtype=X.dtype)), None,
        length=num_steps)
    return FreeBodyTrajectory(X=X_fin, centroids=cents, U=Us)


def make_disc(center: Sequence[float], radius: float, n_markers: int,
              dtype=jnp.float64) -> jnp.ndarray:
    """Marker ring for a 2D rigid disc boundary (CIB/ex0-style body)."""
    th = jnp.arange(n_markers, dtype=dtype) * (2.0 * jnp.pi / n_markers)
    return jnp.stack([center[0] + radius * jnp.cos(th),
                      center[1] + radius * jnp.sin(th)], axis=-1)


def make_sphere(center: Sequence[float], radius: float, n_lat: int,
                n_lon: int, dtype=jnp.float64) -> jnp.ndarray:
    """Marker shell for a 3D rigid sphere (latitude-longitude rings)."""
    pts = []
    import numpy as np
    for i in range(n_lat):
        phi = np.pi * (i + 0.5) / n_lat
        for j in range(n_lon):
            th = 2.0 * np.pi * j / n_lon
            pts.append([center[0] + radius * np.sin(phi) * np.cos(th),
                        center[1] + radius * np.sin(phi) * np.sin(th),
                        center[2] + radius * np.cos(phi)])
    return jnp.asarray(pts, dtype=dtype)
