"""Advection-diffusion integrator (semi-implicit, cell-centered).

Reference parity: ``AdvDiffSemiImplicitHierarchyIntegrator`` (P19,
SURVEY.md §2.2) — scalar transport

    dQ/dt + div(u Q) = kappa lap(Q) + src

with AB2 extrapolated explicit convection and Crank-Nicolson diffusion,
advected by a (time-dependent) MAC velocity, e.g. the INS integrator's.
Multiple transported quantities ride one state, each with its own
diffusivity and source — the analog of the reference's per-variable
registration (`registerTransportedQuantity`).

TPU-first design: like the INS integrator, the state is a NamedTuple
pytree and ``step`` is pure/jittable; the CN Helmholtz solve is spectral
on the periodic level through an overridable solver seam (swapped for the
pencil-decomposed distributed solver under sharding).

Convective form is conservative: face fluxes u_d * Q|_face with centered
or first-order-upwind face interpolation (the reference's PPM/CUI menu
has these as its lower-order members; PPM is a planned addition).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.solvers import fft

Vel = Tuple[jnp.ndarray, ...]


class AdvDiffState(NamedTuple):
    """State for all transported quantities (tuple-of-arrays, one per
    registered variable)."""
    Q: Tuple[jnp.ndarray, ...]
    n_prev: Tuple[jnp.ndarray, ...]   # previous convective rates (AB2)
    t: jnp.ndarray
    k: jnp.ndarray


class TransportedQuantity(NamedTuple):
    """Per-variable config (reference: registerTransportedQuantity +
    setPhysicalBcCoef). ``bc`` of None means fully periodic; a DomainBC
    with wall axes gets fast-diagonalization diffusion solves and
    ghost-lifted Crank-Nicolson boundary data. Convective wall fluxes
    remain valid because the advection velocity satisfies u.n = 0 at
    walls (the INS no-slip contract)."""
    name: str
    kappa: float = 0.0
    # source(coords, t, Q) -> array, or None
    source: Optional[Callable] = None
    convective_op_type: str = "upwind"   # "centered"|"upwind"|"cui"|"none"
    init: Optional[Callable] = None      # Q0(coords) -> array
    bc: Optional[object] = None          # bc.DomainBC or None
    # spatially-varying boundary data {(axis, side): array} overriding
    # the per-side constants (muParserRobinBcCoefs analog, T9)
    bdry_data: Optional[dict] = None


def convective_flux_divergence(Q: jnp.ndarray, u: Vel,
                               dx: Sequence[float],
                               scheme: str, bc=None,
                               bdry_data=None) -> jnp.ndarray:
    """div(u Q) at cell centers from face fluxes. ``scheme`` selects the
    face value of Q: centered average, upwind donor cell, or CUI.

    With ``bc`` (a :class:`ibamr_tpu.bc.DomainBC`), the face states come
    from a BC-honoring ghost fill (T5) instead of the periodic wrap —
    required for CUI's two-cell reach near walls; the flux DIVERGENCE
    stays the roll form because the advecting normal velocity vanishes
    on wall faces (pinned MAC layout), so the wrapped flux there is the
    exact zero both sides need."""
    from ibamr_tpu.ops.convection import advective_face_value

    dim = Q.ndim
    need_ghosts = bc is not None and not bc.all_periodic
    if need_ghosts:
        from ibamr_tpu import bc as bc_mod

        g = 2
        Qg = bc_mod.fill_ghosts_cc(Q, bc, dx, bdry_data=bdry_data,
                                   width=g)
        interior = [slice(g, g + Q.shape[e]) for e in range(dim)]

        def at(d, s):
            sl = list(interior)
            sl[d] = slice(g + s, g + s + Q.shape[d])
            return Qg[tuple(sl)]
    else:
        def at(d, s):
            return jnp.roll(Q, -s, d) if s else Q

    out = jnp.zeros_like(Q)
    for d in range(dim):
        ud = u[d]
        if need_ghosts and not bc.axes[d].periodic:
            # ENFORCE the pinned-wall layout contract on non-periodic
            # axes: face 0 is the physical boundary face AND (via the
            # roll) the image of the opposite boundary face, so a
            # nonzero boundary-normal velocity there would re-inject
            # the outflow at the inflow end. The BC menu served here is
            # walls (u.n = 0); pin it so a through-flow velocity fails
            # visibly (no boundary transport) instead of wrapping.
            sl = [slice(None)] * dim
            sl[d] = slice(0, 1)
            ud = ud.at[tuple(sl)].set(0.0)
        Qm = at(d, -1)                    # Q[i-1] at lower face i
        if scheme == "cui":
            qf = advective_face_value(Qm, Q, ud, scheme,
                                      Qmm=at(d, -2), Qpp=at(d, 1))
        else:
            qf = advective_face_value(Qm, Q, ud, scheme)
        flux = ud * qf                     # at lower faces of axis d
        out = out + (jnp.roll(flux, -1, d) - flux) / dx[d]
    return out


class AdvDiffSemiImplicitIntegrator:
    """Semi-implicit advection-diffusion on the periodic uniform level."""

    def __init__(self, grid: StaggeredGrid,
                 quantities: Sequence[TransportedQuantity],
                 dtype=jnp.float32):
        self.grid = grid
        self.quantities = tuple(quantities)
        self.dtype = dtype
        # solver seam (cf. INSStaggeredIntegrator): (rhs, dx, alpha, beta)
        self.helmholtz_solve = fft.solve_helmholtz_periodic
        # per-quantity wall solvers (fast diagonalization) where bc given
        self._wall_solvers = []
        for q in self.quantities:
            if q.bc is not None and not q.bc.all_periodic:
                from ibamr_tpu.solvers.fastdiag import FastDiagSolver

                self._wall_solvers.append(
                    FastDiagSolver(grid, q.bc, ("cc",) * grid.dim))
            else:
                self._wall_solvers.append(None)

    # -- state ---------------------------------------------------------------
    def initialize(self, Q0: Optional[Sequence] = None) -> AdvDiffState:
        g = self.grid
        coords = g.cell_centers(self.dtype)
        Qs = []
        for i, q in enumerate(self.quantities):
            if Q0 is not None and Q0[i] is not None:
                arr = jnp.broadcast_to(
                    jnp.asarray(Q0[i], dtype=self.dtype), g.n)
            elif q.init is not None:
                arr = jnp.broadcast_to(
                    jnp.asarray(q.init(coords), dtype=self.dtype), g.n)
            else:
                arr = jnp.zeros(g.n, dtype=self.dtype)
            Qs.append(arr)
        zeros = tuple(jnp.zeros(g.n, dtype=self.dtype)
                      for _ in self.quantities)
        return AdvDiffState(Q=tuple(Qs), n_prev=zeros,
                            t=jnp.asarray(0.0, dtype=self.dtype),
                            k=jnp.asarray(0, dtype=jnp.int32))

    # -- single step (pure, jittable) ----------------------------------------
    def step(self, state: AdvDiffState, dt, u: Optional[Vel] = None,
             sources: Optional[Sequence] = None) -> AdvDiffState:
        """Advance one step. ``u`` is the MAC advection velocity (held
        fixed over the step; pass the INS midpoint velocity for 2nd
        order). ``sources`` optionally overrides per-variable sources
        with precomputed arrays (e.g. an IB-spread marker source)."""
        g = self.grid
        dx = g.dx
        coords = g.cell_centers(self.dtype)
        t_half = state.t + 0.5 * dt

        newQ, newN = [], []
        for i, q in enumerate(self.quantities):
            Q = state.Q[i]
            # AB2 convective extrapolation (Euler on the first step)
            if q.convective_op_type == "none" or u is None:
                n_curr = jnp.zeros_like(Q)
                n_star = n_curr
            else:
                n_curr = convective_flux_divergence(
                    Q, u, dx, q.convective_op_type, bc=q.bc,
                    bdry_data=q.bdry_data)
                c1 = jnp.where(state.k == 0, 1.0, 1.5).astype(self.dtype)
                c2 = jnp.where(state.k == 0, 0.0, -0.5).astype(self.dtype)
                n_star = c1 * n_curr + c2 * state.n_prev[i]

            rhs = Q / dt - n_star
            wall_solver = self._wall_solvers[i]
            if q.kappa != 0.0:
                if wall_solver is not None:
                    from ibamr_tpu import bc as bc_mod
                    # affine lifting: lap_bc(Q) = A Q + b with b the
                    # boundary-data vector = lap_bc(0); CN needs
                    # kappa/2 (A Q^n) + kappa b = kappa/2 lap_bc(Q^n)
                    # + kappa/2 b on the RHS of (1/dt - kappa/2 A).
                    b_vec = bc_mod.laplacian_cc(
                        jnp.zeros_like(Q), q.bc, dx,
                        bdry_data=q.bdry_data)
                    rhs = rhs + 0.5 * q.kappa * (
                        bc_mod.laplacian_cc(Q, q.bc, dx,
                                            bdry_data=q.bdry_data)
                        + b_vec)
                else:
                    from ibamr_tpu.ops import stencils
                    rhs = rhs + 0.5 * q.kappa * stencils.laplacian(Q, dx)
            if sources is not None and sources[i] is not None:
                rhs = rhs + sources[i]
            elif q.source is not None:
                rhs = rhs + q.source(coords, t_half, Q)

            if q.kappa != 0.0:
                if wall_solver is not None:
                    Qn = wall_solver.solve(rhs, 1.0 / dt, -0.5 * q.kappa)
                else:
                    Qn = self.helmholtz_solve(rhs, dx, alpha=1.0 / dt,
                                              beta=-0.5 * q.kappa)
            else:
                Qn = dt * rhs
            newQ.append(Qn)
            newN.append(n_curr)

        return AdvDiffState(Q=tuple(newQ), n_prev=tuple(newN),
                            t=state.t + dt, k=state.k + 1)

    # -- diagnostics ---------------------------------------------------------
    def total(self, state: AdvDiffState, i: int = 0) -> jnp.ndarray:
        """Conserved integral of Q_i (periodic, conservative flux form)."""
        return jnp.sum(state.Q[i]) * self.grid.cell_volume


def advance_adv_diff(integ: AdvDiffSemiImplicitIntegrator,
                     state: AdvDiffState, dt: float, num_steps: int,
                     u: Optional[Vel] = None) -> AdvDiffState:
    """Advance ``num_steps`` fixed-velocity steps under one lax.scan."""
    def body(s, _):
        return integ.step(s, dt, u=u), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out
