"""Implicit IB coupling integrator (stiff structures, large dt).

Reference parity: ``IBImplicitStaggeredHierarchyIntegrator`` (P8,
SURVEY.md §2.2) — the reference couples the structure implicitly by
solving the nonlinear system for the new structure configuration with
SNES (Newton-Krylov, matrix-free MFFD Jacobian) around the staggered
Stokes solve. Explicit IB forces stability timesteps dt ~ 1/sqrt(k) for
spring stiffness k; the implicit midpoint coupling removes that limit.

TPU-first formulation: the unknown is the marker configuration X^{n+1}
alone (the fluid solve is a closed-form FFT/fastdiag map, so it is
folded INTO the residual rather than kept as a separate block — the
collapse of the reference's block saddle system to its exact-solver
limit). The residual of the midpoint rule is

    R(X^{n+1}) = X^{n+1} - X^n - dt * J(X^{mid}) u^{mid}
    X^{mid} = (X^n + X^{n+1})/2
    u^{mid} = (u^n + u^{n+1})/2
    u^{n+1} = INS_step(u^n, f = S(X^{mid}) F(X^{mid}, U^{mid}))

solved by ibamr_tpu.solvers.krylov.newton_krylov (exact JVP through the
whole spread -> solve -> interp graph; FGMRES inner iterations). Every
residual evaluation costs one fluid solve + one spread + one interp —
the same structure as the reference's per-Krylov-iteration cost.
"""

from __future__ import annotations

import contextlib
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.integrators.ib import IBMethod, IBState
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.ops.interaction_packed import plain_autodiff_transfers
from ibamr_tpu.solvers.krylov import newton_krylov
from ibamr_tpu.solvers.spectral_plan import plain_autodiff_substep

Vel = Tuple[jnp.ndarray, ...]


@contextlib.contextmanager
def _forward_diffable_trace():
    """newton_krylov takes exact JVPs (jax.linearize) through the whole
    spread -> solve -> interp residual, and jax.custom_vjp functions
    refuse forward mode — trace the Newton solve with the budgeted
    reverse-mode wrappers swapped for their raw autodiff twins."""
    with plain_autodiff_transfers(), plain_autodiff_substep():
        yield


class IBImplicitIntegrator:
    """Implicit-midpoint IB coupling (P8's implicit variant).

    Same construction surface as IBExplicitIntegrator; extra knobs tune
    the Newton-Krylov solve. ``initialize`` is inherited behaviorally:
    use IBExplicitIntegrator.initialize or build IBState directly.
    """

    def __init__(self, ins: INSStaggeredIntegrator, ib: IBMethod,
                 scheme: str = "midpoint",
                 newton_tol: float = 1e-6, newton_maxiter: int = 8,
                 inner_m: int = 16, inner_restarts: int = 2,
                 inner_tol: float = 1e-3):
        if scheme not in ("midpoint", "backward_euler"):
            raise ValueError(f"unknown implicit IB scheme {scheme!r}")
        self.ins = ins
        self.ib = ib
        # midpoint: 2nd order, A-stable (accuracy at moderate dt);
        # backward_euler: 1st order, L-stable (extreme-stiffness robust)
        self.scheme = scheme
        self.newton_tol = float(newton_tol)
        self.newton_maxiter = int(newton_maxiter)
        self.inner_m = int(inner_m)
        self.inner_restarts = int(inner_restarts)
        self.inner_tol = float(inner_tol)

    def initialize(self, X0, ins_state=None, mask=None) -> IBState:
        from ibamr_tpu.integrators.ib import IBExplicitIntegrator

        return IBExplicitIntegrator(self.ins, self.ib).initialize(
            X0, ins_state=ins_state, mask=mask)

    # -- single step (pure, jittable) ----------------------------------------
    def step(self, state: IBState, dt: float) -> IBState:
        grid = self.ins.grid
        ib = self.ib
        u_n = state.ins.u
        X_n = state.X
        mask = state.mask
        t_half = state.ins.t + 0.5 * dt

        mid = self.scheme == "midpoint"

        def fluid_and_U(X_new):
            """u^{n+1} and the marker advection velocity for a trial
            configuration (one residual evaluation). Midpoint evaluates
            the coupling at (X^n + X^{n+1})/2 and (u^n + u^{n+1})/2;
            backward Euler at X^{n+1}, u^{n+1}."""
            X_c = 0.5 * (X_n + X_new) if mid else X_new
            U_est = (X_new - X_n) / dt           # discrete dX/dt
            t_c = t_half if mid else state.ins.t + dt
            F_c = ib.compute_force(X_c, U_est, t_c)
            f_eul = ib.spread_force(F_c, grid, X_c, mask)
            ins_new = self.ins.step(state.ins, dt, f=f_eul)
            if mid:
                u_c = tuple(0.5 * (a + b)
                            for a, b in zip(u_n, ins_new.u))
            else:
                u_c = ins_new.u
            U_c = ib.interpolate_velocity(u_c, grid, X_c, mask)
            return ins_new, U_c

        def residual(X_new):
            _, U_mid = fluid_and_U(X_new)
            return X_new - X_n - dt * U_mid

        # explicit forward-Euler predictor as the Newton initial guess
        U_n = ib.interpolate_velocity(u_n, grid, X_n, mask)
        X_pred = X_n + dt * U_n

        with _forward_diffable_trace():
            sol = newton_krylov(residual, X_pred, tol=self.newton_tol,
                                maxiter=self.newton_maxiter,
                                inner_m=self.inner_m,
                                inner_restarts=self.inner_restarts,
                                inner_tol=self.inner_tol)
        X_new = sol.x
        ins_new, U_mid = fluid_and_U(X_new)
        return IBState(ins=ins_new, X=X_new, U=U_mid, mask=mask)


def advance_ib_implicit(integ: IBImplicitIntegrator, state: IBState,
                        dt: float, num_steps: int) -> IBState:
    def body(s, _):
        return integ.step(s, dt), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out


class TwoLevelIBImplicit:
    """Implicit-midpoint IB coupling ON THE COMPOSITE TWO-LEVEL
    HIERARCHY (VERDICT round 3, missing #6): the reference's
    ``IBImplicitStaggeredHierarchyIntegrator`` works on the AMR
    hierarchy — stiff structures are exactly the case that wants
    refinement and implicit dt together (SURVEY.md P8 [U]).

    Same TPU-first collapse as the uniform integrator: the unknown is
    X^{n+1} alone, and one residual evaluation folds the WHOLE
    composite step — spread at fine resolution, force restriction to
    the coarse level, the two-level explicit predictor, and the
    composite FGMRES projection — into the Newton-Krylov residual
    graph (forward-mode JVPs differentiate through the projection's
    iteration). The structure lives inside the fine window with
    delta-support clearance, exactly like TwoLevelIBINS.
    """

    def __init__(self, grid, box, ib, rho: float = 1.0,
                 mu: float = 0.01, convective: bool = True,
                 proj_tol: float = 1e-8, proj_m: int = 16,
                 proj_restarts: int = 2,
                 scheme: str = "midpoint",
                 newton_tol: float = 1e-6, newton_maxiter: int = 8,
                 inner_m: int = 12, inner_restarts: int = 2,
                 inner_tol: float = 1e-3, _expl=None):
        from ibamr_tpu.amr_ins import TwoLevelIBINS

        if scheme not in ("midpoint", "backward_euler"):
            raise ValueError(f"unknown implicit IB scheme {scheme!r}")
        # reuse the explicit composite integrator for its core stepping
        # + fine-resolution transfer helpers; only the coupling loop
        # differs. ``_expl`` lets the moving-window regrid adopt the
        # explicit integrator it already rebuilt at the new box instead
        # of paying a second CompositeProjection/FastDiag construction.
        self._expl = _expl if _expl is not None else TwoLevelIBINS(
            grid, box, ib, rho=rho, mu=mu, convective=convective,
            proj_tol=proj_tol, proj_m=proj_m,
            proj_restarts=proj_restarts)
        self.grid = grid
        self.box = box
        self.ib = ib
        self.scheme = scheme
        self.newton_tol = float(newton_tol)
        self.newton_maxiter = int(newton_maxiter)
        self.inner_m = int(inner_m)
        self.inner_restarts = int(inner_restarts)
        self.inner_tol = float(inner_tol)

    def initialize(self, X0, uc=None):
        return self._expl.initialize(X0, uc=uc)

    def step(self, state, dt: float):
        from ibamr_tpu.amr_ins import TwoLevelIBState

        expl = self._expl
        fluid = state.fluid
        X_n = state.X
        mask = state.mask
        mid = self.scheme == "midpoint"
        t_half = fluid.t + 0.5 * dt

        def fluid_and_U(X_new):
            X_c = 0.5 * (X_n + X_new) if mid else X_new
            U_est = (X_new - X_n) / dt
            t_c = t_half if mid else fluid.t + dt
            F_c = self.ib.compute_force(X_c, U_est, t_c)
            # one transfer context per configuration, shared by spread
            # and interp (no redundant bucket prep per residual eval);
            # the two-level spread (incl. the partitioner-safe
            # sharding pins) is the explicit integrator's shared
            # helper, so the pinning cannot drift between paths
            ctx = self.ib.prepare(X_c, mask) \
                if hasattr(self.ib, "prepare") else None
            f_c, f_f = expl._spread_two_level(F_c, X_c, mask, ctx=ctx)
            fluid_new = expl.core.step(fluid, dt, f_c=f_c, f_f=f_f)
            if mid:
                u_c = tuple(0.5 * (a + b)
                            for a, b in zip(fluid.uf, fluid_new.uf))
            else:
                u_c = fluid_new.uf
            U_c = expl._interp(u_c, X_c, mask, ctx=ctx)
            return fluid_new, U_c

        def residual(X_new):
            _, U_mid = fluid_and_U(X_new)
            return X_new - X_n - dt * U_mid

        U_n = expl._interp(fluid.uf, X_n, mask)
        X_pred = X_n + dt * U_n
        with _forward_diffable_trace():
            sol = newton_krylov(residual, X_pred, tol=self.newton_tol,
                                maxiter=self.newton_maxiter,
                                inner_m=self.inner_m,
                                inner_restarts=self.inner_restarts,
                                inner_tol=self.inner_tol)
        X_new = sol.x
        fluid_new, U_mid = fluid_and_U(X_new)
        return TwoLevelIBState(fluid=fluid_new, X=X_new, U=U_mid,
                               mask=mask)


def advance_two_level_ib_implicit(integ: TwoLevelIBImplicit, state,
                                  dt: float, num_steps: int):
    def body(s, _):
        return integ.step(s, dt), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out


def regrid_two_level_ib_implicit(integ: TwoLevelIBImplicit, state,
                                 move_threshold: int = 2):
    """Moving-window regrid for the IMPLICIT composite integrator:
    retag the window from the current markers and rebuild BOTH the
    explicit core (state transfer runs through the explicit machinery,
    amr_ins.regrid_two_level_ib) and the implicit wrapper around the
    new box. Unchanged window returns (integ, state) as-is."""
    from ibamr_tpu.amr_ins import regrid_two_level_ib

    expl2, state2 = regrid_two_level_ib(integ._expl, state,
                                        move_threshold=move_threshold)
    if expl2 is integ._expl:
        return integ, state
    core = expl2.core
    integ2 = TwoLevelIBImplicit(
        integ.grid, expl2.box, integ.ib, rho=core.rho, mu=core.mu,
        convective=core.convective, proj_tol=core.proj.tol,
        proj_m=core.proj.m, proj_restarts=core.proj.restarts,
        scheme=integ.scheme, newton_tol=integ.newton_tol,
        newton_maxiter=integ.newton_maxiter, inner_m=integ.inner_m,
        inner_restarts=integ.inner_restarts,
        inner_tol=integ.inner_tol, _expl=expl2)
    return integ2, state2


def advance_two_level_ib_implicit_regridding(integ: TwoLevelIBImplicit,
                                             state, dt: float,
                                             num_steps: int,
                                             regrid_interval: int = 20,
                                             on_chunk=None):
    """Implicit composite advance with the fine window TRACKING the
    structure (the regrid-cadence driver shared with the explicit
    path): jitted chunks of ``regrid_interval`` implicit steps with
    host-side marker-tagged regrids between them — stiff structures
    get large dt AND a window that follows them."""
    from ibamr_tpu.amr_ins import advance_with_regrids

    return advance_with_regrids(
        integ, state, dt, num_steps, regrid_interval,
        advance_two_level_ib_implicit, regrid_two_level_ib_implicit,
        on_chunk=on_chunk)
