from ibamr_tpu.integrators.ins import INSState, INSStaggeredIntegrator
from ibamr_tpu.integrators.cib import CIBMethod, RigidBodies
from ibamr_tpu.integrators.ibfe import IBFEMethod
from ibamr_tpu.integrators.constraint_ib import (ConstraintIBMethod,
                                                 ConstraintIBState)

__all__ = ["INSState", "INSStaggeredIntegrator", "CIBMethod", "RigidBodies",
           "IBFEMethod", "ConstraintIBMethod", "ConstraintIBState"]

# Heavier integrator families import lazily (keep `import ibamr_tpu`
# light); the module paths are the stable API:
#   ibamr_tpu.integrators.ib           - explicit marker IB (P8/P9)
#   ibamr_tpu.integrators.ib_implicit  - Newton-Krylov implicit IB (P8)
#   ibamr_tpu.integrators.imp          - material points (P18)
#   ibamr_tpu.integrators.ins_walls    - no-slip/moving-lid INS (P2)
#   ibamr_tpu.integrators.ins_open     - inflow/outflow INS (P2/P3)
#   ibamr_tpu.integrators.ins_vc       - two-phase VC INS, both forms (P22)
#   ibamr_tpu.integrators.adv_diff     - transported quantities (P19)
#   ibamr_tpu.integrators.gib          - generalized IB / rods (P12)
#   ibamr_tpu.integrators.penalty_ib   - penalty IB (P14)
