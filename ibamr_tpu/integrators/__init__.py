from ibamr_tpu.integrators.ins import INSState, INSStaggeredIntegrator
from ibamr_tpu.integrators.cib import CIBMethod, RigidBodies

__all__ = ["INSState", "INSStaggeredIntegrator", "CIBMethod", "RigidBodies"]
