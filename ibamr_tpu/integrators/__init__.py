from ibamr_tpu.integrators.ins import INSState, INSStaggeredIntegrator

__all__ = ["INSState", "INSStaggeredIntegrator"]
