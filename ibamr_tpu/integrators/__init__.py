from ibamr_tpu.integrators.ins import INSState, INSStaggeredIntegrator
from ibamr_tpu.integrators.cib import CIBMethod, RigidBodies
from ibamr_tpu.integrators.ibfe import IBFEMethod
from ibamr_tpu.integrators.constraint_ib import (ConstraintIBMethod,
                                                 ConstraintIBState)

__all__ = ["INSState", "INSStaggeredIntegrator", "CIBMethod", "RigidBodies",
           "IBFEMethod", "ConstraintIBMethod", "ConstraintIBState"]
