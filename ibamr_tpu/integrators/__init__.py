from ibamr_tpu.integrators.ins import INSState, INSStaggeredIntegrator
from ibamr_tpu.integrators.cib import CIBMethod, RigidBodies
from ibamr_tpu.integrators.ibfe import IBFEMethod

__all__ = ["INSState", "INSStaggeredIntegrator", "CIBMethod", "RigidBodies",
           "IBFEMethod"]
