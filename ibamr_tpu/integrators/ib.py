"""Immersed-boundary coupling integrator (explicit schemes).

Reference parity (SURVEY.md §3.2): ``IBExplicitHierarchyIntegrator`` (P8)
driving the ``IBStrategy`` contract (P7) implemented by ``IBMethod`` (P9)
with ``LDataManager`` marker data (T1) and ``IBStandardForceGen`` forces
(P11). One midpoint timestep:

  U^n      = J(X^n) u^n                       (interpolateVelocity)
  X^{n+1/2} = X^n + dt/2 U^n                  (forwardEulerStep half)
  F^{n+1/2} = Force(X^{n+1/2}, U^n)           (computeLagrangianForce)
  f         = S(X^{n+1/2}) F^{n+1/2}          (spreadForce)
  u^{n+1}   = INS step with body force f      (fluid solve, §3.3)
  U^{n+1/2} = J(X^{n+1/2}) (u^n + u^{n+1})/2  (interpolateVelocity)
  X^{n+1}   = X^n + dt U^{n+1/2}              (midpointStep)

TPU-first design: the marker set is a fixed-capacity ``(N, dim)`` array
plus an active mask (SURVEY.md §7.1); the entire step — force SoA
evaluation, spread scatter, FFT fluid solve, interp gather — is one pure
jittable function, so ``lax.scan`` runs whole simulations on-device.

The ``IBMethod`` plugin seam survives as a small Python protocol: anything
with ``compute_force(X, U, t)`` can replace the standard force generator
(the analog of registering a custom IBLagrangianForceStrategy).
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSState, INSStaggeredIntegrator
from ibamr_tpu.ops import forces as force_mod
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.delta import Kernel

Vel = Tuple[jnp.ndarray, ...]


class IBState(NamedTuple):
    """Coupled fluid + structure state pytree."""
    ins: INSState
    X: jnp.ndarray       # (N, dim) marker positions
    U: jnp.ndarray       # (N, dim) marker velocities (diagnostic / damping)
    mask: jnp.ndarray    # (N,) 0/1 active-slot mask (fixed-capacity pool)


def check_fast_grid(fast, grid: StaggeredGrid) -> None:
    """A fast transfer engine bakes in its grid at construction;
    calling it against a different grid (a regrid, or the FINE grid of
    a composite hierarchy while the engine was built for the coarse
    one) must fail loudly — a shape-compatible mismatch would transfer
    with the wrong dx/origin silently. Shared by every IBStrategy."""
    eg = getattr(fast, "grid", None)
    if eg is not None and (tuple(eg.n) != tuple(grid.n)
                           or eg.x_lo != grid.x_lo
                           or eg.x_up != grid.x_up):
        # print the full geometry: in the composite-hierarchy mismatch
        # (coarse engine vs fine window) the SHAPES can be identical
        # and only the extents differ
        raise ValueError(
            f"fast engine grid (n={tuple(eg.n)}, x_lo={eg.x_lo}, "
            f"x_up={eg.x_up}) != call grid (n={tuple(grid.n)}, "
            f"x_lo={grid.x_lo}, x_up={grid.x_up}); rebuild the "
            "engine for this grid")


class IBMethod:
    """Classic marker-IB structure container (P9 parity).

    Holds the force specs and the delta kernel choice; provides the
    spread / interpolate / force operations the coupling integrator calls
    through the IBStrategy-shaped interface.
    """

    def __init__(self, specs: force_mod.ForceSpecs,
                 kernel: Kernel = "IB_4",
                 force_fn: Optional[Callable] = None,
                 fast=None):
        self.specs = specs
        self.kernel = kernel
        self.force_fn = force_fn  # optional custom force strategy
        # optional FastInteraction engine (ops.interaction_fast): the
        # bucketed-MXU formulation of spread/interp; None = scatter path
        self.fast = fast
        # RESOLVED engine name (set by factory builders after auto
        # resolution / fallback) — fingerprint and cache-key material;
        # None = derive a label from the engine object's type
        self.engine_name = None

    def compute_force(self, X: jnp.ndarray, U: jnp.ndarray,
                      t) -> jnp.ndarray:
        if self.force_fn is not None:
            return self.force_fn(X, U, t)
        return force_mod.compute_lagrangian_force(X, U, self.specs)

    def prepare(self, X: jnp.ndarray, mask: jnp.ndarray):
        """Per-position transfer context (marker buckets), shared by all
        spread/interp calls at the same X within a step."""
        if self.fast is None:
            return None
        return self.fast.buckets(X, mask)

    def refresh(self, ctx, X: jnp.ndarray, mask: jnp.ndarray):
        """Slot-preserving context refresh at a drifted position (the
        half-step of the midpoint scheme): re-gather the new positions
        into the pack-time layout instead of re-bucketing from scratch
        (exact — engines fall back to a full re-pack under a drift
        bound). Returns ``(ctx, hit)``, or ``(None, None)`` when the
        engine has no refresh path and the caller must re-prepare."""
        if ctx is None or self.fast is None:
            return None, None
        r = getattr(self.fast, "refresh", None)
        if r is None:
            return None, None
        return r(ctx, X, weights=mask)

    def interpolate_velocity(self, u: Vel, grid: StaggeredGrid,
                             X: jnp.ndarray, mask: jnp.ndarray,
                             ctx=None) -> jnp.ndarray:
        if self.fast is not None:
            check_fast_grid(self.fast, grid)
            return self.fast.interpolate_vel(u, X, weights=mask, b=ctx)
        return interaction.interpolate_vel(u, grid, X, kernel=self.kernel,
                                           weights=mask)

    def spread_force(self, F: jnp.ndarray, grid: StaggeredGrid,
                     X: jnp.ndarray, mask: jnp.ndarray,
                     ctx=None) -> Vel:
        if self.fast is not None:
            check_fast_grid(self.fast, grid)
            return self.fast.spread_vel(F, X, weights=mask, b=ctx)
        return interaction.spread_vel(F, grid, X, kernel=self.kernel,
                                      weights=mask)


class IBExplicitIntegrator:
    """Explicit IB coupling of an INS integrator and an IBMethod (P8).

    ``ins`` is any fluid integrator exposing ``grid``, ``dtype``,
    ``initialize()`` and ``step(state, dt, f=...)`` with a state
    carrying ``u`` and ``t`` — the periodic staggered integrator, the
    wall-bounded one, and the MULTIPHASE VC forms all satisfy the seam,
    so capsule-style structures in two-phase flow are the same
    composition (pass ``ins_state=vc.initialize(phi0)`` to
    ``initialize``; pinned by tests/test_vc_ib.py)."""

    def __init__(self, ins: INSStaggeredIntegrator, ib: IBMethod,
                 scheme: str = "midpoint"):
        if scheme not in ("midpoint", "forward_euler"):
            raise ValueError(f"unknown IB time stepping scheme {scheme!r}")
        self.ins = ins
        self.ib = ib
        self.scheme = scheme
        self._jitted_steps = {}

    def jitted_step(self, donate: bool = True, with_stats: bool = False):
        """Compiled step with whole-step buffer donation: the input
        IBState's buffers (velocity, pressure, markers) are reused for
        the output — fields update in place instead of allocating fresh
        full-field HBM buffers each step. Cached per (donate,
        with_stats), so repeated calls share one compiled executable.

        Donation contract: after ``new = f(state, dt)`` the caller's
        ``state`` buffers are DELETED — anyone retaining pre-step state
        (rollback templates, trajectory recorders keeping live arrays)
        must pass ``donate=False``. That includes reverse-mode autodiff:
        a cotangent pass replays the step from saved primals, so a
        donated input under an outer ``grad``/``vjp`` trace is a
        use-after-free the donated executable would hide. The returned
        callable therefore REFUSES (raises, does not silently ignore)
        donation when any input leaf is a tracer — mirroring
        ResilientDriver's forced-off donation, but loudly: the caller
        asked for an optimization the gradient makes unsound, and must
        choose (``donate=False``, or ``RunConfig(remat=...)`` chunks
        which force donation off under grad)."""
        key = (bool(donate), bool(with_stats))
        fn = self._jitted_steps.get(key)
        if fn is None:
            base = self.step_with_stats if with_stats else self.step
            if donate:
                jitted = jax.jit(base, donate_argnums=(0,))

                @functools.wraps(base)
                def fn(state, dt):
                    if any(isinstance(l, jax.core.Tracer)
                           for l in jax.tree_util.tree_leaves(
                               (state, dt))):
                        raise ValueError(
                            "jitted_step(donate=True) called under an "
                            "active trace (grad/vjp/jit): buffer "
                            "donation invalidates the primal values "
                            "the cotangent pass replays from. Use "
                            "jitted_step(donate=False) when "
                            "differentiating (the design loop and "
                            "RunConfig(remat=...) chunks do this "
                            "automatically).")
                    return jitted(state, dt)
                # keep the RAW python step reachable for the graph-
                # contract harness (contracts._unwrap lowers it with
                # its own donate_argnums)
                fn.__wrapped__ = base
            else:
                fn = jax.jit(base)
            self._jitted_steps[key] = fn
        return fn

    # -- state ---------------------------------------------------------------
    def initialize(self, X0, ins_state: Optional[INSState] = None,
                   mask=None) -> IBState:
        dtype = self.ins.dtype
        X = jnp.asarray(X0, dtype=dtype)
        if ins_state is None:
            ins_state = self.ins.initialize()
        if mask is None:
            mask = jnp.ones(X.shape[0], dtype=dtype)
        return IBState(ins=ins_state, X=X,
                       U=jnp.zeros_like(X),
                       mask=jnp.asarray(mask, dtype=dtype))

    # -- single step (pure, jittable) ----------------------------------------
    def step(self, state: IBState, dt: float) -> IBState:
        new_state, _ = self.step_with_stats(state, dt)
        return new_state

    def step_with_stats(self, state: IBState, dt: float):
        """``step`` plus a per-step stats dict: ``refresh_hit`` is a
        traced bool when the transfer engine took the slot-preserving
        half-step refresh path (False = the drift bound forced a full
        re-pack), or None when the engine has no refresh. The stats
        ride beside the state — the IBState pytree is unchanged, so
        checkpoints, sharding specs and lax.scan carriers are
        untouched."""
        grid = self.ins.grid
        ib = self.ib
        u_n = state.ins.u
        X_n = state.X
        # strategies may expose a per-position transfer context (marker
        # buckets for the MXU path) shared across calls at the same X
        prep = getattr(ib, "prepare", None)

        def ctx_at(X):
            return prep(X, state.mask) if prep is not None else None

        # structure prediction to the half step
        ctx_n = ctx_at(X_n)
        U_n = ib.interpolate_velocity(u_n, grid, X_n, state.mask,
                                      ctx=ctx_n)
        refresh_hit = None
        if self.scheme == "midpoint":
            X_half = X_n + 0.5 * dt * U_n
            # half-step context: slot-preserving refresh of ctx_n when
            # the strategy supports it (one bucket_prep per step — the
            # round-5 measured 14.6 ms x2 tax), full re-prepare
            # otherwise
            refresh = getattr(ib, "refresh", None)
            ctx_h = None
            if refresh is not None and ctx_n is not None:
                ctx_h, refresh_hit = refresh(ctx_n, X_half, state.mask)
            if ctx_h is None:
                ctx_h = ctx_at(X_half)
        else:
            X_half = X_n
            ctx_h = ctx_n

        # Lagrangian force at the half step, spread to the grid
        t_half = state.ins.t + 0.5 * dt
        F_half = ib.compute_force(X_half, U_n, t_half)
        f_eul = ib.spread_force(F_half, grid, X_half, state.mask,
                                ctx=ctx_h)

        # fluid solve with the IB body force
        ins_new = self.ins.step(state.ins, dt, f=f_eul)

        # corrector: move markers with the midpoint velocity
        if self.scheme == "midpoint":
            u_half = tuple(0.5 * (a + b) for a, b in zip(u_n, ins_new.u))
            U_half = ib.interpolate_velocity(u_half, grid, X_half,
                                             state.mask, ctx=ctx_h)
            X_new = X_n + dt * U_half
            U_out = U_half
        else:
            X_new = X_n + dt * U_n
            U_out = U_n

        return (IBState(ins=ins_new, X=X_new, U=U_out, mask=state.mask),
                {"refresh_hit": refresh_hit})

    # -- diagnostics ---------------------------------------------------------
    def total_marker_force(self, state: IBState) -> jnp.ndarray:
        F = self.ib.compute_force(state.X, state.U, state.ins.t)
        return jnp.sum(F * state.mask[:, None], axis=0)


def advance_ib(integrator: IBExplicitIntegrator, state: IBState, dt: float,
               num_steps: int) -> IBState:
    """Advance ``num_steps`` under one jitted lax.scan."""
    def body(s, _):
        return integrator.step(s, dt), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out


def polygon_area(X: jnp.ndarray) -> jnp.ndarray:
    """Shoelace area of a closed 2D marker loop (volume-conservation
    diagnostic for the membrane acceptance configs)."""
    x, y = X[:, 0], X[:, 1]
    xn, yn = jnp.roll(x, -1), jnp.roll(y, -1)
    return 0.5 * jnp.abs(jnp.sum(x * yn - xn * y))
