"""IB coupling on inflow/outflow (open-boundary) domains — the
flow-past-an-immersed-structure configuration.

Reference parity: the reference's most-run IB scenarios are external
flows past structures in channels with prescribed inflow and open
outflow (``IBExplicitHierarchyIntegrator`` over the
inflow/outflow-configured ``INSStaggeredHierarchyIntegrator``, SURVEY.md
P2/P8 — flow past a cylinder, flapping filaments, valve leaflets). The
periodic and enclosed IB couplings exist (`integrators.ib`,
`amr_ins`); this module completes the boundary menu by coupling the
marker-cloud IBStrategy seam to
:class:`~ibamr_tpu.integrators.ins_open.INSOpenIntegrator`'s coupled
velocity-pressure solve.

Layout bridge: the open solver stores velocities FACE-COMPLETE (+1 on
the component's own axis); the transfer ops use the periodic lower-face
layout. The structure must keep delta-support clearance from every
domain boundary (markers at a boundary would wrap their stencil), which
makes the conversion exact: interpolation reads the lower faces,
spreading appends a zero upper-boundary face — the same clearance
contract as the fine-window composite path
(`amr_ins._box_mac_from_periodic`).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins_open import INSOpenIntegrator, OpenINSState
from ibamr_tpu.ops.stencils import (mac_complete_from_periodic,
                                    mac_periodic_from_complete)

Vel = Tuple[jnp.ndarray, ...]


class IBOpenState(NamedTuple):
    fluid: OpenINSState
    X: jnp.ndarray
    U: jnp.ndarray
    mask: jnp.ndarray
    # net Lagrangian force actually spread during the last step (at the
    # midpoint configuration X_half, U_n, t+dt/2) — carried so drag/lift
    # diagnostics report the applied force, not a half-step-lagged
    # recomputation; zeros before the first step
    F_net: jnp.ndarray


class IBOpenIntegrator:
    """Explicit midpoint IB coupling over the open-boundary INS step.
    The construction dt on the INS integrator is the default; ``step``
    also takes an explicit (possibly traced) dt — alpha = rho/dt is
    threaded through the saddle solve dynamically, so the CFL-adaptive
    driver loop works on this family.

    ``ib`` is any marker-cloud IBStrategy (IBMethod, IBFEMethod, ...);
    ``x_lo`` places the solver's index box in physical space (default
    origin)."""

    def __init__(self, ins: INSOpenIntegrator, ib,
                 x_lo: Optional[Sequence[float]] = None):
        self.ins = ins
        self.ib = ib
        dim = len(ins.n)
        x_lo = tuple(float(v) for v in (x_lo or (0.0,) * dim))
        x_up = tuple(x_lo[d] + ins.n[d] * ins.dx[d] for d in range(dim))
        self.grid = StaggeredGrid(n=tuple(ins.n), x_lo=x_lo, x_up=x_up)

    # -- layout bridge (shared with the fine-window composite path) ----------
    def _to_lower(self, u: Vel) -> Vel:
        """Face-complete -> periodic lower-face layout (drop the upper
        boundary face; exact under the clearance contract)."""
        return mac_periodic_from_complete(u, self.grid.n)

    def _to_complete(self, f: Vel) -> Vel:
        """Periodic lower-face layout -> face-complete (the duplicated
        wrap face carries zero under the clearance contract — no
        spread force lands on any boundary face)."""
        return mac_complete_from_periodic(f)

    # -- state ---------------------------------------------------------------
    def initialize(self, X0, fluid: Optional[OpenINSState] = None,
                   mask=None) -> IBOpenState:
        if fluid is None:
            fluid = self.ins.initialize()
        # cast markers to the FLUID dtype (same contract as
        # IBExplicitIntegrator.initialize): a mixed-precision carry
        # would either break the scan (f32 markers + f64 fluid) or
        # silently promote the production-f32 step to f64
        dtype = self.ins.solver.dtype
        X = jnp.asarray(X0, dtype=dtype)
        if mask is None:
            mask = jnp.ones(X.shape[0], dtype=dtype)
        return IBOpenState(fluid=fluid, X=X, U=jnp.zeros_like(X),
                           mask=jnp.asarray(mask, dtype=dtype),
                           F_net=jnp.zeros(X.shape[1], dtype=dtype))

    # -- single step (pure, jittable) ----------------------------------------
    def step(self, state: IBOpenState, dt=None) -> IBOpenState:
        """``dt`` may be None (construction dt), a float, or a traced
        scalar — the saddle solve takes alpha = rho/dt dynamically, so
        the CFL-adaptive hierarchy_driver loop works on this family
        (VERDICT round 4 item 6)."""
        dt_arg = dt
        if dt is None:
            dt = self.ins.dt
        grid = self.grid
        ib = self.ib
        fluid = state.fluid
        X_n = state.X
        u_low = self._to_lower(fluid.u)
        U_n = ib.interpolate_velocity(u_low, grid, X_n, state.mask)
        X_half = X_n + 0.5 * dt * U_n
        F = ib.compute_force(X_half, U_n, fluid.t + 0.5 * dt)
        ctx = ib.prepare(X_half, state.mask) \
            if hasattr(ib, "prepare") else None
        f_per = ib.spread_force(F, grid, X_half, state.mask, ctx=ctx)
        fluid_new = self.ins.step(fluid, dt=dt_arg,
                                  f=self._to_complete(f_per))
        u_mid = tuple(0.5 * (a + b)
                      for a, b in zip(u_low,
                                      self._to_lower(fluid_new.u)))
        U_half = ib.interpolate_velocity(u_mid, grid, X_half,
                                         state.mask, ctx=ctx)
        X_new = X_n + dt * U_half
        return IBOpenState(fluid=fluid_new, X=X_new, U=U_half,
                           mask=state.mask,
                           F_net=jnp.sum(F * state.mask[:, None],
                                         axis=0))

    # -- diagnostics ---------------------------------------------------------
    def body_force_on_fluid(self, state: IBOpenState) -> jnp.ndarray:
        """Net structural force applied to the fluid during the LAST
        step (the NEGATIVE of the hydrodynamic force on the body):
        sum of the Lagrangian forces at the spread configuration
        (X_half, U_n, t+dt/2) — e.g. drag = -F_net[flow_axis] for a
        target-point-held body. Before the first step, zero."""
        return state.F_net


    def cfl_dt(self, state: IBOpenState, cfl: float = 0.5) -> float:
        """Advective CFL bound from the fluid field (hierarchy_driver
        contract; the marker velocities ride the same field)."""
        return self.ins.cfl_dt(state.fluid, cfl)


def advance_ib_open(integ: IBOpenIntegrator, state: IBOpenState,
                    num_steps: int) -> IBOpenState:
    def body(s, _):
        return integ.step(s), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out
