"""IBFE: immersed finite-element structure method.

Reference parity: ``IBFEMethod`` (P17) + ``FEDataManager`` (T16,
SURVEY.md §2.2) — the Lagrangian structure is a finite-element solid;
internal forces come from the hyperelastic weak form (PK1 stress), and
fluid-structure coupling spreads/interpolates with the same regularized
delta kernels as the marker IB path.

Coupling schemes, matching the reference's vocabulary:

- ``"nodal"``: spread the weak-form nodal forces from the nodal positions
  and interpolate velocity at the nodes (the reference's nodal-coupling /
  mass-lumped option).
- ``"unified"``: L2-project the nodal force to a force *density*, evaluate
  it at element quadrature points, and spread each quad point's
  ``G(X_q) * w_q dV`` (the reference's default quadrature-point coupling,
  better volume conservation for coarse structural meshes); velocity is
  interpolated at quad points and L2-projected back to nodes.

Both schemes conserve total force exactly (sum of spread point forces ==
sum of nodal forces, by partition of unity of the shape functions).

``IBFEMethod`` implements the same strategy surface as
:class:`ibamr_tpu.integrators.ib.IBMethod` (compute_force /
spread_force / interpolate_velocity), so
:class:`~ibamr_tpu.integrators.ib.IBExplicitIntegrator` drives it
unchanged — the IBStrategy plugin seam (P7) doing its job.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax.numpy as jnp

from ibamr_tpu.fe.fem import (FEAssembly, build_assembly, elastic_energy,
                              nodal_average_from_quads, nodal_forces,
                              quad_positions)
from ibamr_tpu.fe.mesh import FEMesh
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.delta import Kernel

Vel = Tuple[jnp.ndarray, ...]


def _check_fast_engine(fast, kernel) -> None:
    """The engine bakes in its kernel at construction; a mismatch with
    the method's kernel would silently transfer with the wrong delta."""
    if fast is not None and getattr(fast, "kernel", kernel) != kernel:
        raise ValueError(
            f"fast engine kernel {fast.kernel!r} != method kernel "
            f"{kernel!r}")


def _check_fast_grid(fast, grid) -> None:
    """Delegates to the shared engine/grid guard (ib.check_fast_grid),
    so every IBStrategy enforces the same contract."""
    from ibamr_tpu.integrators.ib import check_fast_grid

    check_fast_grid(fast, grid)


class IBFEMethod:
    """FE-structure strategy for the explicit IB coupling integrator.

    The coupled state's ``X`` is the (n_nodes, dim) array of current
    nodal positions; reference-configuration tables live in ``self.asm``.
    """

    def __init__(self, mesh: FEMesh, W: Callable,
                 kernel: Kernel = "IB_4",
                 coupling: str = "unified",
                 damping: float = 0.0,
                 body_force: Optional[Callable] = None,
                 dtype=jnp.float32,
                 fast=None,
                 transfer_level: int = 0):
        if coupling not in ("nodal", "unified"):
            raise ValueError(f"unknown IBFE coupling scheme {coupling!r}")
        # optional transfer engine (FastInteraction / PackedInteraction
        # / Pallas twins): IBFE quadrature/node clouds are ordinary
        # marker clouds to the engines, so the FE coupling rides the
        # same MXU/packed fast paths as the classic IB method; None =
        # XLA scatter/gather (exact for either choice — the engines are
        # roundoff-equal to the scatter oracle, tests pin it)
        _check_fast_engine(fast, kernel)
        self.fast = fast
        self.mesh = mesh
        self.asm: FEAssembly = build_assembly(mesh, dtype=dtype)
        self.W = W
        self.kernel = kernel
        self.coupling = coupling
        self.damping = damping
        self.body_force = body_force  # optional (x, t) -> nodal force
        # transfer tables: the stiffness assembly by default, or a
        # DENSER rule (fem.transfer_quadrature) for the
        # Eulerian<->Lagrangian coupling — the reference's
        # FEDataManager::updateQuadratureRule adapts exactly this rule
        # to the deformed configuration [U]; pick the level host-side
        # from fem.suggest_transfer_level (per regrid cadence)
        from ibamr_tpu.fe.fem import build_transfer_assembly
        self.transfer_level = int(transfer_level)
        if self.transfer_level > 0 and coupling == "nodal":
            raise ValueError(
                "transfer_level applies to the 'unified' "
                "(quadrature-point) coupling only; nodal coupling "
                "transfers at the nodes and has no quadrature rule "
                "to densify")
        self.tasm: FEAssembly = (
            self.asm if self.transfer_level <= 0
            else build_transfer_assembly(mesh, self.transfer_level,
                                         dtype=dtype))
        # static node<->quad transfer weights, hoisted out of the
        # per-step calls (they depend only on the assembly)
        from ibamr_tpu.fe.fem import _node_qp_weights
        self._wwden = _node_qp_weights(self.tasm.elems,
                                       self.tasm.shape,
                                       self.tasm.wdV,
                                       self.tasm.n_nodes)

    # -- IBStrategy surface --------------------------------------------------
    def prepare(self, X: jnp.ndarray, mask: jnp.ndarray):
        """Per-position transfer context for the fast engines: bucket
        ONCE per structural position (nodal cloud, or the quad cloud it
        determines) and reuse across the step's spread+interp calls —
        the same ctx protocol IBMethod exposes."""
        if self.fast is None:
            return None
        if self.coupling == "nodal":
            return self.fast.buckets(X, mask)
        return self.fast.buckets(quad_positions(self.tasm, X))

    def compute_force(self, X: jnp.ndarray, U: jnp.ndarray,
                      t) -> jnp.ndarray:
        F = nodal_forces(self.asm, self.W, X)
        if self.damping:
            F = F - self.damping * U
        if self.body_force is not None:
            F = F + self.body_force(X, t)
        return F

    def interpolate_velocity(self, u: Vel, grid: StaggeredGrid,
                             X: jnp.ndarray, mask: jnp.ndarray,
                             ctx=None) -> jnp.ndarray:
        if self.coupling == "nodal":
            if self.fast is not None:
                _check_fast_grid(self.fast, grid)
                return self.fast.interpolate_vel(u, X, weights=mask,
                                                 b=ctx)
            return interaction.interpolate_vel(u, grid, X,
                                               kernel=self.kernel,
                                               weights=mask)
        xq = quad_positions(self.tasm, X)
        if self.fast is not None:
            _check_fast_grid(self.fast, grid)
            Uq = self.fast.interpolate_vel(u, xq, b=ctx)
        else:
            Uq = interaction.interpolate_vel(u, grid, xq,
                                             kernel=self.kernel)
        # nodal mask honored the same way the nodal path does: inactive
        # slots interpolate to zero (and so do not move)
        out = nodal_average_from_quads(self.tasm.elems,
                                       self.tasm.shape,
                                       self.tasm.wdV,
                                       self.tasm.n_nodes,
                                       Uq, ww_den=self._wwden)
        return out * mask[:, None]

    def spread_force(self, F: jnp.ndarray, grid: StaggeredGrid,
                     X: jnp.ndarray, mask: jnp.ndarray,
                     ctx=None) -> Vel:
        if self.coupling == "nodal":
            if self.fast is not None:
                _check_fast_grid(self.fast, grid)
                return self.fast.spread_vel(F, X, weights=mask, b=ctx)
            return interaction.spread_vel(F, grid, X, kernel=self.kernel,
                                          weights=mask)
        # distribute each nodal force over its quadrature points with
        # per-node-normalized positive shares (exact total-force
        # conservation on every element family; see fem.
        # distribute_to_quads); nodal mask zeroes inactive slots
        from ibamr_tpu.fe.fem import distribute_to_quads
        Fq = distribute_to_quads(self.tasm.elems, self.tasm.shape,
                                 self.tasm.wdV, self.tasm.n_nodes,
                                 F * mask[:, None], ww_den=self._wwden)
        xq = quad_positions(self.tasm, X)
        if self.fast is not None:
            _check_fast_grid(self.fast, grid)
            return self.fast.spread_vel(Fq, xq, b=ctx)
        return interaction.spread_vel(Fq, grid, xq, kernel=self.kernel)

    # -- diagnostics ---------------------------------------------------------
    def energy(self, X: jnp.ndarray):
        return elastic_energy(self.asm, self.W, X)

    def current_volume(self, X: jnp.ndarray):
        """Deformed measure: sum_e |det FF_e| * refvol_e."""
        from ibamr_tpu.fe.fem import deformation_gradients
        FF = deformation_gradients(self.asm, X)      # (E, nq, d, d)
        return jnp.sum(jnp.abs(jnp.linalg.det(FF)) * self.asm.wdV)


class IBFESurfaceMethod:
    """Codim-1 FE strategy (the reference's ``IBFESurfaceMethod``, P17):
    membranes/shells carry in-plane elasticity from ``fe/surface.py``
    and couple at surface quadrature points with AREA weights (or
    nodally) — same IBStrategy seam, so ``IBExplicitIntegrator`` drives
    it unchanged."""

    def __init__(self, mesh, W: Callable, kernel: Kernel = "IB_4",
                 coupling: str = "unified", damping: float = 0.0,
                 body_force: Optional[Callable] = None,
                 dtype=jnp.float32, fast=None):
        from ibamr_tpu.fe.surface import (SurfaceMesh,
                                          build_surface_assembly)

        if coupling not in ("nodal", "unified"):
            raise ValueError(f"unknown IBFE coupling scheme {coupling!r}")
        assert isinstance(mesh, SurfaceMesh)
        _check_fast_engine(fast, kernel)
        self.fast = fast
        self.mesh = mesh
        self.asm = build_surface_assembly(mesh, dtype=dtype)
        self.W = W
        self.kernel = kernel
        self.coupling = coupling
        self.damping = damping
        self.body_force = body_force
        from ibamr_tpu.fe.fem import _node_qp_weights
        self._wwden = _node_qp_weights(self.asm.elems, self.asm.shape,
                                       self.asm.wdA, self.asm.n_nodes)

    # -- IBStrategy surface --------------------------------------------------
    def prepare(self, X: jnp.ndarray, mask: jnp.ndarray):
        """Per-position transfer context (see IBFEMethod.prepare)."""
        from ibamr_tpu.fe.surface import surface_quad_positions

        if self.fast is None:
            return None
        if self.coupling == "nodal":
            return self.fast.buckets(X, mask)
        return self.fast.buckets(surface_quad_positions(self.asm, X))

    def compute_force(self, X: jnp.ndarray, U: jnp.ndarray,
                      t) -> jnp.ndarray:
        from ibamr_tpu.fe.surface import membrane_forces

        F = membrane_forces(self.asm, self.W, X)
        if self.damping:
            F = F - self.damping * U
        if self.body_force is not None:
            F = F + self.body_force(X, t)
        return F

    def interpolate_velocity(self, u: Vel, grid: StaggeredGrid,
                             X: jnp.ndarray, mask: jnp.ndarray,
                             ctx=None) -> jnp.ndarray:
        from ibamr_tpu.fe.fem import nodal_average_from_quads
        from ibamr_tpu.fe.surface import surface_quad_positions

        if self.coupling == "nodal":
            if self.fast is not None:
                _check_fast_grid(self.fast, grid)
                return self.fast.interpolate_vel(u, X, weights=mask,
                                                 b=ctx)
            return interaction.interpolate_vel(u, grid, X,
                                               kernel=self.kernel,
                                               weights=mask)
        xq = surface_quad_positions(self.asm, X)
        if self.fast is not None:
            _check_fast_grid(self.fast, grid)
            Uq = self.fast.interpolate_vel(u, xq, b=ctx)
        else:
            Uq = interaction.interpolate_vel(u, grid, xq,
                                            kernel=self.kernel)
        out = nodal_average_from_quads(self.asm.elems, self.asm.shape,
                                       self.asm.wdA, self.asm.n_nodes,
                                       Uq, ww_den=self._wwden)
        return out * mask[:, None]

    def spread_force(self, F: jnp.ndarray, grid: StaggeredGrid,
                     X: jnp.ndarray, mask: jnp.ndarray,
                     ctx=None) -> Vel:
        from ibamr_tpu.fe.fem import distribute_to_quads
        from ibamr_tpu.fe.surface import surface_quad_positions

        if self.coupling == "nodal":
            if self.fast is not None:
                _check_fast_grid(self.fast, grid)
                return self.fast.spread_vel(F, X, weights=mask, b=ctx)
            return interaction.spread_vel(F, grid, X, kernel=self.kernel,
                                          weights=mask)
        Fq = distribute_to_quads(self.asm.elems, self.asm.shape,
                                 self.asm.wdA, self.asm.n_nodes,
                                 F * mask[:, None], ww_den=self._wwden)
        xq = surface_quad_positions(self.asm, X)
        if self.fast is not None:
            _check_fast_grid(self.fast, grid)
            return self.fast.spread_vel(Fq, xq, b=ctx)
        return interaction.spread_vel(Fq, grid, xq, kernel=self.kernel)

    # -- diagnostics ---------------------------------------------------------
    def energy(self, X: jnp.ndarray):
        from ibamr_tpu.fe.surface import membrane_energy
        return membrane_energy(self.asm, self.W, X)

    def current_area(self, X: jnp.ndarray):
        from ibamr_tpu.fe.surface import current_area
        return current_area(self.asm, X)


class DirectForcingKinematics:
    """Prescribed-kinematics wrapper (the reference's
    ``IBFEDirectForcingKinematics``, P17): drives any FE strategy's
    structure toward a prescribed trajectory with a stiff
    penalty/damping pair

        F_df = kappa (X_target(t) - X) - eta (U - U_target(t)),

    added on top of the wrapped strategy's elastic force. All other
    IBStrategy calls delegate, so the integrator sees one strategy."""

    def __init__(self, base, target_fn: Callable, kappa: float,
                 eta: float = 0.0, target_vel_fn: Optional[Callable] = None):
        self.base = base
        self.target_fn = target_fn
        self.target_vel_fn = target_vel_fn
        self.kappa = float(kappa)
        self.eta = float(eta)

    def compute_force(self, X: jnp.ndarray, U: jnp.ndarray,
                      t) -> jnp.ndarray:
        F = self.base.compute_force(X, U, t)
        Xt = self.target_fn(t)
        F = F + self.kappa * (Xt - X)
        if self.eta:
            Ut = (self.target_vel_fn(t) if self.target_vel_fn is not None
                  else jnp.zeros_like(U))
            F = F - self.eta * (U - Ut)
        # user target functions easily promote dtype (x64 constants);
        # the coupled scan carry must stay in the state's dtype
        return F.astype(X.dtype)

    def interpolate_velocity(self, *a, **kw):
        return self.base.interpolate_velocity(*a, **kw)

    def spread_force(self, *a, **kw):
        return self.base.spread_force(*a, **kw)

    def __getattr__(self, name):
        return getattr(self.base, name)
