"""IBFE: immersed finite-element structure method.

Reference parity: ``IBFEMethod`` (P17) + ``FEDataManager`` (T16,
SURVEY.md §2.2) — the Lagrangian structure is a finite-element solid;
internal forces come from the hyperelastic weak form (PK1 stress), and
fluid-structure coupling spreads/interpolates with the same regularized
delta kernels as the marker IB path.

Coupling schemes, matching the reference's vocabulary:

- ``"nodal"``: spread the weak-form nodal forces from the nodal positions
  and interpolate velocity at the nodes (the reference's nodal-coupling /
  mass-lumped option).
- ``"unified"``: L2-project the nodal force to a force *density*, evaluate
  it at element quadrature points, and spread each quad point's
  ``G(X_q) * w_q dV`` (the reference's default quadrature-point coupling,
  better volume conservation for coarse structural meshes); velocity is
  interpolated at quad points and L2-projected back to nodes.

Both schemes conserve total force exactly (sum of spread point forces ==
sum of nodal forces, by partition of unity of the shape functions).

``IBFEMethod`` implements the same strategy surface as
:class:`ibamr_tpu.integrators.ib.IBMethod` (compute_force /
spread_force / interpolate_velocity), so
:class:`~ibamr_tpu.integrators.ib.IBExplicitIntegrator` drives it
unchanged — the IBStrategy plugin seam (P7) doing its job.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax.numpy as jnp

from ibamr_tpu.fe.fem import (FEAssembly, build_assembly, elastic_energy,
                              l2_project_from_quads, nodal_forces,
                              project_to_quads, quad_positions)
from ibamr_tpu.fe.mesh import FEMesh
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.delta import Kernel

Vel = Tuple[jnp.ndarray, ...]


class IBFEMethod:
    """FE-structure strategy for the explicit IB coupling integrator.

    The coupled state's ``X`` is the (n_nodes, dim) array of current
    nodal positions; reference-configuration tables live in ``self.asm``.
    """

    def __init__(self, mesh: FEMesh, W: Callable,
                 kernel: Kernel = "IB_4",
                 coupling: str = "unified",
                 damping: float = 0.0,
                 body_force: Optional[Callable] = None,
                 dtype=jnp.float32):
        if coupling not in ("nodal", "unified"):
            raise ValueError(f"unknown IBFE coupling scheme {coupling!r}")
        self.mesh = mesh
        self.asm: FEAssembly = build_assembly(mesh, dtype=dtype)
        self.W = W
        self.kernel = kernel
        self.coupling = coupling
        self.damping = damping
        self.body_force = body_force  # optional (x, t) -> nodal force

    # -- IBStrategy surface --------------------------------------------------
    def compute_force(self, X: jnp.ndarray, U: jnp.ndarray,
                      t) -> jnp.ndarray:
        F = nodal_forces(self.asm, self.W, X)
        if self.damping:
            F = F - self.damping * U
        if self.body_force is not None:
            F = F + self.body_force(X, t)
        return F

    def interpolate_velocity(self, u: Vel, grid: StaggeredGrid,
                             X: jnp.ndarray, mask: jnp.ndarray,
                             ctx=None) -> jnp.ndarray:
        if self.coupling == "nodal":
            return interaction.interpolate_vel(u, grid, X,
                                               kernel=self.kernel,
                                               weights=mask)
        xq = quad_positions(self.asm, X)
        Uq = interaction.interpolate_vel(u, grid, xq, kernel=self.kernel)
        # nodal mask honored the same way the nodal path does: inactive
        # slots interpolate to zero (and so do not move)
        return l2_project_from_quads(self.asm, Uq) * mask[:, None]

    def spread_force(self, F: jnp.ndarray, grid: StaggeredGrid,
                     X: jnp.ndarray, mask: jnp.ndarray,
                     ctx=None) -> Vel:
        if self.coupling == "nodal":
            return interaction.spread_vel(F, grid, X, kernel=self.kernel,
                                          weights=mask)
        # force density G = M_lumped^{-1} F at nodes -> quad points,
        # each quad point spreads G(X_q) * (w_q dV); nodal mask zeroes
        # inactive slots' contribution, matching the nodal path
        from ibamr_tpu.fe.fem import safe_lumped_mass
        G = F * mask[:, None] / safe_lumped_mass(self.asm)[:, None]
        Gq = project_to_quads(self.asm, G)
        wq = self.asm.wdV.reshape(-1)
        xq = quad_positions(self.asm, X)
        return interaction.spread_vel(Gq * wq[:, None], grid, xq,
                                      kernel=self.kernel)

    # -- diagnostics ---------------------------------------------------------
    def energy(self, X: jnp.ndarray):
        return elastic_energy(self.asm, self.W, X)

    def current_volume(self, X: jnp.ndarray):
        """Deformed measure: sum_e |det FF_e| * refvol_e."""
        from ibamr_tpu.fe.fem import deformation_gradients
        FF = deformation_gradients(self.asm, X)
        return jnp.sum(jnp.abs(jnp.linalg.det(FF))
                       * jnp.sum(self.asm.wdV, axis=1))
