"""Incompressible Navier-Stokes integrator on the staggered (MAC) grid.

Reference parity: ``INSStaggeredHierarchyIntegrator`` (P2) with its
convective-operator menu (P4) and the staggered Stokes solve (P3) —
SURVEY.md §3.3. On the periodic uniform level the reference's Krylov
saddle-point solve with projection preconditioner collapses to an exact
projection method (the preconditioner IS the exact solver when FFTs invert
the sub-blocks), which is what we implement:

per step (pressure-increment projection, AB2 convection, CN diffusion):
  1. N* = 3/2 N(u^n) - 1/2 N(u^{n-1})          (forward Euler on step 0)
  2. (rho/dt - mu/2 lap) u* = (rho/dt + mu/2 lap) u^n - rho N* + f - grad p^{n-1/2}
  3. lap(phi) = (rho/dt) div(u*)
  4. u^{n+1} = u* - (dt/rho) grad(phi)          (div u^{n+1} == 0 exactly)
  5. p^{n+1/2} = p^{n-1/2} + phi - (mu dt / (2 rho)) lap(phi)

TPU-first design: the state is a NamedTuple pytree; ``step`` is a pure
function of (state, dt, body_force) built once per integrator config and
meant to live inside jit / lax.scan. All solves are FFT (exact, no inner
iteration), so one timestep is a fixed dataflow graph — no data-dependent
control flow anywhere.
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils
from ibamr_tpu.ops.convection import convective_rate
from ibamr_tpu.solvers import fft

Vel = Tuple[jnp.ndarray, ...]


class INSState(NamedTuple):
    """Functional INS state pytree."""
    u: Vel                  # MAC velocity components
    p: jnp.ndarray          # cell-centered pressure (at t^{n-1/2})
    n_prev: Vel             # N(u^{n-1}) for AB2 extrapolation
    t: jnp.ndarray          # scalar time
    k: jnp.ndarray          # step counter (AB2 bootstrap)


class INSStaggeredIntegrator:
    """Projection-method INS integrator on a periodic uniform MAC grid.

    Parameters mirror the reference's input-file vocabulary where sensible:
    ``rho`` (mass density), ``mu`` (dynamic viscosity), and
    ``convective_op_type`` in {"centered", "upwind", "ppm", "cui",
    "none"} (case-insensitive; "ppm" is the reference's default
    operator, "cui" the CBC-limited cubic upwind of the newer menu).
    ``wall_axes`` puts homogeneous no-slip walls on both sides of the
    marked axes; ``wall_tangential[(d, e, side)]`` prescribes component
    d's tangential velocity on the side(0=lo,1=hi) wall of axis e (a
    moving lid).
    """

    def __init__(self, grid: StaggeredGrid, rho: float = 1.0,
                 mu: float = 0.01, convective_op_type: str = "centered",
                 dtype=jnp.float32,
                 wall_axes: Optional[Tuple[bool, ...]] = None,
                 wall_tangential=None,
                 spectral_dtype=None):
        # reference input files spell these uppercase ("PPM", "CENTERED")
        convective_op_type = convective_op_type.lower()
        if convective_op_type not in ("centered", "upwind", "ppm", "cui",
                                      "none"):
            raise ValueError(f"unknown convective_op_type {convective_op_type!r}")
        self.grid = grid
        self.rho = float(rho)
        self.mu = float(mu)
        self.convective_op_type = convective_op_type
        self.dtype = dtype
        self.wall_axes = (tuple(bool(w) for w in wall_axes)
                          if wall_axes is not None
                          else (False,) * grid.dim)
        if len(self.wall_axes) != grid.dim:
            raise ValueError(
                f"wall_axes has {len(self.wall_axes)} entries for a "
                f"{grid.dim}D grid")
        # opt-in mixed-precision spectral transforms (bf16/split-real
        # operands, f32 twiddle/accumulation); only the fused periodic
        # path honors it — walls use fastdiag, where it has no meaning
        from ibamr_tpu.solvers import spectral_plan
        self.spectral_dtype = spectral_plan.canonical_spectral_dtype(
            spectral_dtype)
        if self.spectral_dtype is not None and any(self.wall_axes):
            raise ValueError(
                "spectral_dtype requires the fully-periodic fused "
                f"spectral path; wall_axes={self.wall_axes}")
        self.wall_tangential = dict(wall_tangential or {})
        for key, val in self.wall_tangential.items():
            ok = (isinstance(key, tuple) and len(key) == 3
                  and 0 <= key[0] < grid.dim and 0 <= key[1] < grid.dim
                  and key[0] != key[1] and key[2] in (0, 1)
                  and self.wall_axes[key[1]])
            if not ok:
                raise ValueError(
                    f"wall_tangential key {key!r} must be (component d, "
                    f"wall axis e != d, side in {{0, 1}}) with "
                    f"wall_axes[e] set; wall_axes={self.wall_axes}")
        # Overridable solver seams (the StaggeredStokesSolver plugin
        # interface of the north star): the sharded path swaps these for
        # pencil-decomposed distributed FFT solves (parallel.fftpar); the
        # wall-bounded path (no-slip walls on ``wall_axes``) swaps them
        # for fast-diagonalization solves (solvers.fastdiag).
        self.fused_stokes = None     # set on the periodic path below
        if any(self.wall_axes):
            from ibamr_tpu.integrators import ins_walls

            ops = ins_walls.WallOps(grid, self.wall_axes,
                                    tangential=self.wall_tangential)
            self.helmholtz_vel_solve = ops.helmholtz_vel
            self.project = ops.project
            self.laplacian_vel = ops.laplacian_vel
            self.pressure_gradient = ops.pressure_gradient
            self.laplacian_cc = ops.laplacian_cc
        else:
            # (non-empty wall_tangential with no wall axes is already
            # rejected by the per-key validation above)
            self.helmholtz_vel_solve = fft.solve_helmholtz_periodic_vel
            self.project = fft.project_divergence_free
            self.laplacian_vel = stencils.laplacian_vel
            self.pressure_gradient = stencils.gradient
            self.laplacian_cc = stencils.laplacian
            # fused spectral Stokes substep (Helmholtz + projection +
            # pressure increment in one spectral pass — 7 transforms
            # instead of 8 + three stencil passes). Disabled by the
            # sharded wrapper, which swaps in pencil-FFT seams.
            self.fused_stokes = fft.helmholtz_project_periodic
        # convective operator (P4 menu). Walls or PPM need the
        # ghost-padded path; fully-periodic centered/upwind keep the
        # original roll formulation.
        from ibamr_tpu.ops.convection import convective_rate_bc
        if convective_op_type == "none":
            self._convective = None
        elif any(self.wall_axes) or convective_op_type in ("ppm", "cui"):
            self._convective = partial(
                convective_rate_bc, scheme=convective_op_type,
                wall_axes=self.wall_axes,
                wall_tangential=self.wall_tangential)
        else:
            self._convective = partial(convective_rate,
                                       scheme=convective_op_type)

    # -- state construction -------------------------------------------------
    def initialize(self, u0=None, u0_arrays: Optional[Vel] = None) -> INSState:
        """Build the initial state.

        ``u0`` may be either a sequence of per-component callables
        ``u0[d](coords_tuple, t) -> array`` (e.g. CartGridFunction per
        component), or a single vector-valued callable
        ``u0(coords_tuple, t) -> [array, ...]`` (what ``function_from_db``
        returns); each component is evaluated at its own face centers.
        (A vector callable is invoked once per component — dim calls —
        because each MAC component lives at different coordinates; pass
        per-component callables or arrays to avoid the redundant work.)
        ``u0_arrays`` passes raw MAC arrays directly."""
        g = self.grid
        if u0_arrays is not None:
            u = tuple(jnp.asarray(c, dtype=self.dtype) for c in u0_arrays)
        elif u0 is not None:
            def eval_comp(d):
                coords = g.face_centers(d, self.dtype)
                if callable(u0):
                    val = u0(coords, 0.0)[d]
                else:
                    val = u0[d](coords, 0.0)
                return jnp.broadcast_to(
                    jnp.asarray(val, dtype=self.dtype), g.n)

            u = tuple(eval_comp(d) for d in range(g.dim))
        else:
            u = tuple(jnp.zeros(g.n, dtype=self.dtype) for _ in range(g.dim))
        zero_cc = jnp.zeros(g.n, dtype=self.dtype)
        zeros_vel = tuple(jnp.zeros(g.n, dtype=self.dtype)
                          for _ in range(g.dim))
        return INSState(u=u, p=zero_cc, n_prev=zeros_vel,
                        t=jnp.asarray(0.0, dtype=self.dtype),
                        k=jnp.asarray(0, dtype=jnp.int32))

    # -- single step (pure, jittable) ---------------------------------------
    def step(self, state: INSState, dt: float,
             f: Optional[Vel] = None,
             q: Optional[jnp.ndarray] = None) -> INSState:
        """Advance one timestep. ``f`` is an optional MAC body force
        (e.g. the spread IB force) held fixed over the step; ``q`` is an
        optional cell-centered divergence source (internal fluid
        sources/sinks — the IBStandardSourceGen analog, P14), imposed as
        div u^{n+1} = q by the projection."""
        g = self.grid
        rho, mu = self.rho, self.mu
        dx = g.dx
        u, p = state.u, state.p

        # 1. convective extrapolation (AB2; Euler on the first step)
        if self._convective is None:
            n_star = tuple(jnp.zeros_like(c) for c in u)
            n_curr = n_star
        else:
            n_curr = self._convective(u, dx)
            c1 = jnp.where(state.k == 0, 1.0, 1.5).astype(self.dtype)
            c2 = jnp.where(state.k == 0, 0.0, -0.5).astype(self.dtype)
            n_star = tuple(c1 * a + c2 * b
                           for a, b in zip(n_curr, state.n_prev))

        # 2. semi-implicit viscous solve for u*
        lap_u = self.laplacian_vel(u, dx)
        gp = self.pressure_gradient(p, dx)
        rhs = []
        for d in range(g.dim):
            r = (rho / dt) * u[d] + 0.5 * mu * lap_u[d] \
                - rho * n_star[d] - gp[d]
            if f is not None:
                r = r + f[d]
            rhs.append(r)
        # the fused path is only valid while the solver seams are the
        # stock periodic-FFT ones — a custom helmholtz_vel_solve /
        # project override (pencil solvers, user plugins) must win
        use_fused = (
            self.fused_stokes is not None and q is None
            and self.helmholtz_vel_solve is fft.solve_helmholtz_periodic_vel
            and self.project is fft.project_divergence_free)
        if use_fused:
            # fused spectral path: Helmholtz solve + projection +
            # pressure increment in one spectral round trip.
            # p_inc = (rho/dt) phi0 - (0.5 mu) lap(phi0)
            # spectral_dtype is forwarded only when set, so swapped-in
            # fused_stokes seams keep their plain signature
            extra = ({"spectral_dtype": self.spectral_dtype}
                     if self.spectral_dtype is not None else {})
            u_new, p_inc = self.fused_stokes(
                tuple(rhs), dx, alpha=rho / dt, beta=-0.5 * mu,
                pinc_coeffs=(rho / dt, -0.5 * mu), **extra)
            p_new = p + p_inc
        else:
            u_star = self.helmholtz_vel_solve(
                tuple(rhs), dx, alpha=rho / dt, beta=-0.5 * mu)

            # 3-4. exact projection (phi0 = lap^{-1} div u*;
            # phi = (rho/dt) phi0)
            u_new, phi0 = self.project(u_star, dx, q=q)
            phi = (rho / dt) * phi0

            # 5. pressure update (pressure-increment form w/ viscous
            # correction)
            p_new = p + phi \
                - (0.5 * mu * dt / rho) * self.laplacian_cc(phi, dx)

        return INSState(u=u_new, p=p_new, n_prev=n_curr,
                        t=state.t + dt, k=state.k + 1)

    # -- diagnostics --------------------------------------------------------
    def cfl_dt(self, state: INSState, cfl: float = 0.5) -> float:
        """Largest stable dt by the advective CFL condition (host-side;
        the analog of the reference's global-min dt reduction)."""
        g = self.grid
        umax = max(float(jnp.max(jnp.abs(c))) for c in state.u)
        if umax == 0.0:
            return math.inf
        return cfl * min(g.dx) / umax

    def kinetic_energy(self, state: INSState) -> jnp.ndarray:
        ke = sum(jnp.sum(jnp.square(c)) for c in state.u)
        return 0.5 * self.rho * ke * self.grid.cell_volume

    def max_divergence(self, state: INSState) -> jnp.ndarray:
        return jnp.max(jnp.abs(stencils.divergence(state.u, self.grid.dx)))


def advance(integrator: INSStaggeredIntegrator, state: INSState, dt: float,
            num_steps: int, f: Optional[Vel] = None,
            q: Optional[jnp.ndarray] = None) -> INSState:
    """Advance ``num_steps`` fixed-dt steps under one jitted lax.scan."""
    def body(s, _):
        return integrator.step(s, dt, f, q=q), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out
