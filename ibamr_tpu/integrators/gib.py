"""Generalized IB method: rods with director frames and torque coupling.

Reference parity: ``GeneralizedIBMethod`` + ``IBKirchhoffRodForceGen``
(P12, SURVEY.md §2.2; Lim-Ferent-Wang-Peskin 2008). Beyond classic IB,
each Lagrangian node carries an orthonormal director triad; the rod
model produces torques as well as forces, the fluid exerts angular
velocity on the frames, and the torques enter the fluid as the couple
force density f_N = 1/2 curl( N delta(x - X) ).

One midpoint step (the rotational extension of §3.2):
  U^n     = J u^n,  w^n = 1/2 J curl(u^n)
  X, D at n+1/2 via half-step translation / rotation
  (F, N)  = rod force/torque at the half step  (autodiff of rod energy)
  f       = S F + 1/2 curl(S N)               (spread force + couple)
  fluid step with f;  corrector with midpoint velocities.

3D only (director frames are intrinsically 3D — the reference's rod
machinery likewise compiles for NDIM=3).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSState, INSStaggeredIntegrator
from ibamr_tpu.ops import interaction, stencils
from ibamr_tpu.ops.delta import Kernel
from ibamr_tpu.ops.rods import (RodSpecs, rod_energy, rod_force_torque,
                                rotate_frames)

Vel = Tuple[jnp.ndarray, ...]


class GIBState(NamedTuple):
    ins: INSState
    X: jnp.ndarray       # (N, 3) node positions
    D: jnp.ndarray       # (N, 3, 3) director triads (rows = directors)


def _dcc(f, axis, h):
    return (jnp.roll(f, -1, axis) - jnp.roll(f, 1, axis)) / (2.0 * h)


def _cc_to_face(f, d):
    """Shift a cell-centered array to face centering along axis d."""
    return 0.5 * (f + jnp.roll(f, 1, d))


def couple_force_mac(n_cc: Vel, grid: StaggeredGrid) -> Vel:
    """MAC force of the torque couple 1/2 curl(n) from a cell-centered
    torque density field n."""
    dx = grid.dx
    curl = (
        _dcc(n_cc[2], 1, dx[1]) - _dcc(n_cc[1], 2, dx[2]),
        _dcc(n_cc[0], 2, dx[2]) - _dcc(n_cc[2], 0, dx[0]),
        _dcc(n_cc[1], 0, dx[0]) - _dcc(n_cc[0], 1, dx[1]),
    )
    return tuple(0.5 * _cc_to_face(curl[d], d) for d in range(3))


class GeneralizedIBMethod:
    """Rod-structure coupling integrator (P12)."""

    def __init__(self, ins: INSStaggeredIntegrator, specs: RodSpecs,
                 kernel: Kernel = "IB_4"):
        assert ins.grid.dim == 3, "generalized IB requires a 3D grid"
        self.ins = ins
        self.specs = specs
        self.kernel = kernel

    # -- kinematics ----------------------------------------------------------
    def _marker_velocities(self, u: Vel, X: jnp.ndarray
                           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        grid = self.ins.grid
        U = interaction.interpolate_vel(u, grid, X, kernel=self.kernel)
        w_cc = stencils.curl_3d_cc(u, grid.dx)
        w = jnp.stack([
            interaction.interpolate(w_cc[d], grid, X, centering="cell",
                                    kernel=self.kernel)
            for d in range(3)], axis=-1)
        return U, 0.5 * w

    def _spread_force_torque(self, F: jnp.ndarray, N: jnp.ndarray,
                             X: jnp.ndarray) -> Vel:
        grid = self.ins.grid
        f = interaction.spread_vel(F, grid, X, kernel=self.kernel)
        n_cc = tuple(
            interaction.spread(N[:, d], grid, X, centering="cell",
                               kernel=self.kernel)
            for d in range(3))
        fc = couple_force_mac(n_cc, grid)
        return tuple(a + b for a, b in zip(f, fc))

    # -- one step ------------------------------------------------------------
    def step(self, state: GIBState, dt: float) -> GIBState:
        ins = self.ins
        u_n = state.ins.u
        X_n, D_n = state.X, state.D

        U_n, w_n = self._marker_velocities(u_n, X_n)
        X_half = X_n + 0.5 * dt * U_n
        D_half = rotate_frames(D_n, 0.5 * dt * w_n)

        F, N = rod_force_torque(X_half, D_half, self.specs)
        f = self._spread_force_torque(F, N, X_half)

        ins_new = ins.step(state.ins, dt, f=f)

        u_mid = tuple(0.5 * (a + b) for a, b in zip(u_n, ins_new.u))
        U_half, w_half = self._marker_velocities(u_mid, X_half)
        X_new = X_n + dt * U_half
        D_new = rotate_frames(D_n, dt * w_half)
        return GIBState(ins=ins_new, X=X_new, D=D_new)

    # -- setup / diagnostics --------------------------------------------------
    def initialize(self, X0, D0,
                   ins_state: Optional[INSState] = None) -> GIBState:
        dtype = self.ins.dtype
        if ins_state is None:
            ins_state = self.ins.initialize()
        return GIBState(ins=ins_state,
                        X=jnp.asarray(X0, dtype=dtype),
                        D=jnp.asarray(D0, dtype=dtype))

    def energy(self, state: GIBState):
        return rod_energy(state.X, state.D, self.specs)


def advance_gib(method: GeneralizedIBMethod, state: GIBState, dt: float,
                num_steps: int) -> GIBState:
    def body(s, _):
        return method.step(s, dt), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out
