"""Collocated (cell-centered) INS integrator with approximate projection.

Reference parity: ``INSCollocatedHierarchyIntegrator`` (P5, SURVEY.md
§2.2) — the cell-centered alternative to the staggered integrator (P2):
all velocity components live at cell centers and the projection is
APPROXIMATE (Almgren-Bell-Szymczak style): the Poisson problem is driven
by the divergence of the face-interpolated velocity, the correction is
the cell-centered central gradient, and the residual cell-centered
divergence is O(h^2) rather than roundoff — the documented trade-off of
the collocated discretization in the reference as well.

TPU-first: cell-centered components are plain ``grid.n`` arrays; every
solve reuses the periodic FFT cell-centered Poisson/Helmholtz kernels
(one spectral family instead of the staggered per-component offsets).
"""

from __future__ import annotations


from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils
from ibamr_tpu.solvers import fft

Vel = Tuple[jnp.ndarray, ...]


class CollocatedINSState(NamedTuple):
    u: Vel                 # dim cell-centered components
    p: jnp.ndarray         # cell-centered pressure
    n_prev: Vel            # previous convective rate (AB2)
    t: jnp.ndarray
    k: jnp.ndarray


def _cc_convective_rate(u: Vel, dx, scheme: str) -> Vel:
    """(u . grad) u with cell-centered central or upwind differences."""
    dim = len(u)
    out = []
    for d in range(dim):
        acc = jnp.zeros_like(u[d])
        for a in range(dim):
            if scheme == "centered":
                dd = (jnp.roll(u[d], -1, a) - jnp.roll(u[d], 1, a)) \
                    / (2.0 * dx[a])
            else:  # upwind
                dm = (u[d] - jnp.roll(u[d], 1, a)) / dx[a]
                dp = (jnp.roll(u[d], -1, a) - u[d]) / dx[a]
                dd = jnp.where(u[a] > 0, dm, dp)
            acc = acc + u[a] * dd
        out.append(acc)
    return tuple(out)


class INSCollocatedIntegrator:
    """Cell-centered approximate-projection INS (P5)."""

    def __init__(self, grid: StaggeredGrid, rho: float = 1.0,
                 mu: float = 0.01, convective_op_type: str = "centered",
                 wall_axes=None,
                 dtype=jnp.float32):
        if convective_op_type not in ("centered", "upwind", "none"):
            raise ValueError(
                f"unknown convective_op_type {convective_op_type!r}")
        self.grid = grid
        self.rho = float(rho)
        self.mu = float(mu)
        self.convective_op_type = convective_op_type
        self.dtype = dtype
        # wall_axes[d]: NO-SLIP walls on both sides of axis d (round 5
        # — P5 closure: the collocated family beyond periodic-FFT).
        # Cell-centered unknowns with walls at faces: velocity solves
        # are Dirichlet-at-face fast-diagonalization transforms,
        # the projection Poisson is Neumann, and every explicit
        # stencil sees odd-reflection (velocity) / even-reflection
        # (pressure, phi) ghosts — the same convention as
        # solvers.fastdiag.laplacian_1d_cc, so the implicit and
        # explicit halves of the step share one discrete operator.
        self.wall_axes = (tuple(bool(w) for w in wall_axes)
                          if wall_axes is not None
                          else (False,) * grid.dim)
        self._vel_solver = None
        self._phi_solver = None
        if any(self.wall_axes):
            from ibamr_tpu.bc import (AxisBC, DomainBC, dirichlet_axis,
                                      neumann_axis, periodic_axis)
            from ibamr_tpu.solvers.fastdiag import FastDiagSolver

            vel_bc = DomainBC(axes=tuple(
                dirichlet_axis() if w else periodic_axis()
                for w in self.wall_axes))
            phi_bc = DomainBC(axes=tuple(
                neumann_axis() if w else periodic_axis()
                for w in self.wall_axes))
            self._vel_solver = FastDiagSolver(grid, vel_bc,
                                              ("cc",) * grid.dim)
            self._phi_solver = FastDiagSolver(grid, phi_bc,
                                              ("cc",) * grid.dim)

    # -- wall-aware cell-centered stencils -----------------------------------
    def _ext(self, c: jnp.ndarray, d: int, sign: float) -> jnp.ndarray:
        """One ghost layer along axis d by homogeneous reflection. The
        coefficient comes from bc.ghost_reflect_coeff — the SAME
        single-sourced convention the ghost fill, the
        fast-diagonalization matrices, and the multigrid diagonals use
        — so ``sign`` (-1 velocity Dirichlet, +1 pressure Neumann) is
        validated against it rather than hardcoded twice."""
        from ibamr_tpu.bc import (DIRICHLET, NEUMANN, SideBC,
                                  ghost_reflect_coeff)
        from ibamr_tpu.ops.stencils import axis_slice
        kind = DIRICHLET if sign < 0 else NEUMANN
        r = ghost_reflect_coeff(SideBC(kind), self.grid.dx[d])
        n = c.shape[d]
        lo = r * axis_slice(c, d, 0, 1)
        hi = r * axis_slice(c, d, n - 1, n)
        return jnp.concatenate([lo, c, hi], axis=d)

    def _d_central(self, c, d, sign):
        """Central first derivative along d, wall-aware when flagged."""
        dx = self.grid.dx[d]
        if not self.wall_axes[d]:
            return (jnp.roll(c, -1, d) - jnp.roll(c, 1, d)) / (2.0 * dx)
        from ibamr_tpu.ops.stencils import axis_slice
        e = self._ext(c, d, sign)
        n = c.shape[d]
        return (axis_slice(e, d, 2, n + 2)
                - axis_slice(e, d, 0, n)) / (2.0 * dx)

    def _d_upwind(self, c, d, a, sign):
        dx = self.grid.dx[d]
        if not self.wall_axes[d]:
            dm = (c - jnp.roll(c, 1, d)) / dx
            dp = (jnp.roll(c, -1, d) - c) / dx
        else:
            from ibamr_tpu.ops.stencils import axis_slice
            e = self._ext(c, d, sign)
            n = c.shape[d]
            dm = (c - axis_slice(e, d, 0, n)) / dx
            dp = (axis_slice(e, d, 2, n + 2) - c) / dx
        return jnp.where(a > 0, dm, dp)

    def _lap(self, c, sign):
        g = self.grid
        acc = jnp.zeros_like(c)
        for d in range(g.dim):
            dx = g.dx[d]
            if not self.wall_axes[d]:
                acc = acc + (jnp.roll(c, -1, d) - 2.0 * c
                             + jnp.roll(c, 1, d)) / dx ** 2
            else:
                from ibamr_tpu.ops.stencils import axis_slice
                e = self._ext(c, d, sign)
                n = c.shape[d]
                acc = acc + (axis_slice(e, d, 2, n + 2) - 2.0 * c
                             + axis_slice(e, d, 0, n)) / dx ** 2
        return acc

    # -- state ----------------------------------------------------------------
    def initialize(self, u0=None,
                   u0_arrays: Optional[Vel] = None) -> CollocatedINSState:
        """Build the initial state. Same ``u0`` contract as the
        staggered integrator: per-component callables
        ``u0[d](coords, t) -> array`` or one vector callable
        ``u0(coords, t) -> [array, ...]``, evaluated at t=0 — here all
        components share the cell-center coordinates."""
        g = self.grid
        if u0_arrays is not None:
            u = tuple(jnp.asarray(c, dtype=self.dtype) for c in u0_arrays)
        elif u0 is not None:
            coords = g.cell_centers(self.dtype)
            if callable(u0):
                vals = u0(coords, 0.0)
            else:
                vals = [u0[d](coords, 0.0) for d in range(g.dim)]
            u = tuple(jnp.broadcast_to(
                jnp.asarray(vals[d], dtype=self.dtype), g.n)
                for d in range(g.dim))
        else:
            u = tuple(jnp.zeros(g.n, dtype=self.dtype)
                      for _ in range(g.dim))
        zero = jnp.zeros(g.n, dtype=self.dtype)
        return CollocatedINSState(
            u=u, p=zero,
            n_prev=tuple(jnp.zeros(g.n, dtype=self.dtype)
                         for _ in range(g.dim)),
            t=jnp.zeros((), dtype=self.dtype),
            k=jnp.zeros((), dtype=jnp.int32))

    # -- approximate projection ----------------------------------------------
    def _approx_project(self, u: Vel) -> Tuple[Vel, jnp.ndarray]:
        """ABS approximate projection: MAC divergence of face-averaged
        velocity drives the Poisson solve; cell-centered central
        gradient corrects. Wall axes: the wall face velocity is zero
        (pinned slot), the Poisson problem is Neumann with the
        constant mode projected out, and the correction gradient uses
        even-reflection ghosts."""
        g = self.grid
        dx = g.dx
        # face-normal average: component d onto its lower d-face; on a
        # wall axis the wrap slot IS both wall faces and carries 0
        u_face = []
        for d in range(g.dim):
            uf = 0.5 * (u[d] + jnp.roll(u[d], 1, d))
            if self.wall_axes[d]:
                from ibamr_tpu.integrators.ins_walls import pin_normal
                uf = pin_normal(uf, d, self.wall_axes)
            u_face.append(uf)
        div = stencils.divergence(tuple(u_face), dx)
        if self._phi_solver is not None:
            phi = self._phi_solver.solve(div, alpha=0.0, beta=1.0,
                                         zero_nullspace=True)
        else:
            phi = fft.solve_poisson_periodic(div, dx)
        grad_cc = tuple(self._d_central(phi, d, +1.0)
                        for d in range(g.dim))
        return tuple(c - gc for c, gc in zip(u, grad_cc)), phi

    # -- one step -------------------------------------------------------------
    def step(self, state: CollocatedINSState, dt: float,
             f: Optional[Vel] = None) -> CollocatedINSState:
        g = self.grid
        rho, mu = self.rho, self.mu
        dx = g.dx
        u, p = state.u, state.p

        walls = any(self.wall_axes)
        if self.convective_op_type == "none":
            n_star = tuple(jnp.zeros_like(c) for c in u)
            n_curr = n_star
        else:
            # one loop for both domains: _d_central/_d_upwind dispatch
            # per axis (periodic roll, or odd no-slip ghosts on wall
            # axes), so the periodic path reduces exactly to the old
            # _cc_convective_rate
            out = []
            for d in range(g.dim):
                acc = jnp.zeros_like(u[d])
                for a in range(g.dim):
                    if self.convective_op_type == "centered":
                        dd = self._d_central(u[d], a, -1.0)
                    else:
                        dd = self._d_upwind(u[d], a, u[a], -1.0)
                    acc = acc + u[a] * dd
                out.append(acc)
            n_curr = tuple(out)
            c1 = jnp.where(state.k == 0, 1.0, 1.5).astype(self.dtype)
            c2 = jnp.where(state.k == 0, 0.0, -0.5).astype(self.dtype)
            n_star = tuple(c1 * a + c2 * b
                           for a, b in zip(n_curr, state.n_prev))

        grad_p = tuple(self._d_central(p, d, +1.0)
                       for d in range(g.dim))
        rhs = []
        for d in range(g.dim):
            lap = (self._lap(u[d], -1.0) if walls
                   else stencils.laplacian(u[d], dx))
            r = (rho / dt) * u[d] + 0.5 * mu * lap \
                - rho * n_star[d] - grad_p[d]
            if f is not None:
                r = r + f[d]
            rhs.append(r)
        # cell-centered Helmholtz solve per component: periodic FFT,
        # or the Dirichlet-at-face fastdiag transforms on wall axes
        if self._vel_solver is not None:
            u_star = tuple(
                self._vel_solver.solve(c, alpha=rho / dt,
                                       beta=-0.5 * mu)
                for c in rhs)
        else:
            u_star = tuple(
                fft.solve_helmholtz_periodic(c, dx, alpha=rho / dt,
                                             beta=-0.5 * mu)
                for c in rhs)

        u_new, phi0 = self._approx_project(u_star)
        phi = (rho / dt) * phi0
        p_new = p + phi - (0.5 * mu * dt / rho) * (
            self._lap(phi, +1.0) if walls
            else stencils.laplacian(phi, dx))

        return CollocatedINSState(u=u_new, p=p_new, n_prev=n_curr,
                                  t=state.t + dt, k=state.k + 1)

    # -- diagnostics ----------------------------------------------------------
    def kinetic_energy(self, state: CollocatedINSState) -> jnp.ndarray:
        ke = sum(jnp.sum(jnp.square(c)) for c in state.u)
        return 0.5 * self.rho * ke * self.grid.cell_volume

    def max_divergence(self, state: CollocatedINSState) -> jnp.ndarray:
        """Cell-centered central divergence — O(h^2) small, NOT roundoff
        (approximate projection). Wall axes use the odd-ghost stencil
        (no cross-wall wrap in the diagnostic)."""
        g = self.grid
        div = jnp.zeros(g.n, dtype=state.u[0].dtype)
        for d in range(g.dim):
            div = div + self._d_central(state.u[d], d, -1.0)
        return jnp.max(jnp.abs(div))


def advance_collocated(integ: INSCollocatedIntegrator,
                       state: CollocatedINSState, dt: float,
                       num_steps: int,
                       f: Optional[Vel] = None) -> CollocatedINSState:
    def body(s, _):
        return integ.step(s, dt, f), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out
