"""Collocated (cell-centered) INS integrator with approximate projection.

Reference parity: ``INSCollocatedHierarchyIntegrator`` (P5, SURVEY.md
§2.2) — the cell-centered alternative to the staggered integrator (P2):
all velocity components live at cell centers and the projection is
APPROXIMATE (Almgren-Bell-Szymczak style): the Poisson problem is driven
by the divergence of the face-interpolated velocity, the correction is
the cell-centered central gradient, and the residual cell-centered
divergence is O(h^2) rather than roundoff — the documented trade-off of
the collocated discretization in the reference as well.

TPU-first: cell-centered components are plain ``grid.n`` arrays; every
solve reuses the periodic FFT cell-centered Poisson/Helmholtz kernels
(one spectral family instead of the staggered per-component offsets).
"""

from __future__ import annotations


from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils
from ibamr_tpu.solvers import fft

Vel = Tuple[jnp.ndarray, ...]


class CollocatedINSState(NamedTuple):
    u: Vel                 # dim cell-centered components
    p: jnp.ndarray         # cell-centered pressure
    n_prev: Vel            # previous convective rate (AB2)
    t: jnp.ndarray
    k: jnp.ndarray


def _cc_convective_rate(u: Vel, dx, scheme: str) -> Vel:
    """(u . grad) u with cell-centered central or upwind differences."""
    dim = len(u)
    out = []
    for d in range(dim):
        acc = jnp.zeros_like(u[d])
        for a in range(dim):
            if scheme == "centered":
                dd = (jnp.roll(u[d], -1, a) - jnp.roll(u[d], 1, a)) \
                    / (2.0 * dx[a])
            else:  # upwind
                dm = (u[d] - jnp.roll(u[d], 1, a)) / dx[a]
                dp = (jnp.roll(u[d], -1, a) - u[d]) / dx[a]
                dd = jnp.where(u[a] > 0, dm, dp)
            acc = acc + u[a] * dd
        out.append(acc)
    return tuple(out)


class INSCollocatedIntegrator:
    """Cell-centered approximate-projection INS (P5)."""

    def __init__(self, grid: StaggeredGrid, rho: float = 1.0,
                 mu: float = 0.01, convective_op_type: str = "centered",
                 dtype=jnp.float32):
        if convective_op_type not in ("centered", "upwind", "none"):
            raise ValueError(
                f"unknown convective_op_type {convective_op_type!r}")
        self.grid = grid
        self.rho = float(rho)
        self.mu = float(mu)
        self.convective_op_type = convective_op_type
        self.dtype = dtype

    # -- state ----------------------------------------------------------------
    def initialize(self, u0=None,
                   u0_arrays: Optional[Vel] = None) -> CollocatedINSState:
        """Build the initial state. Same ``u0`` contract as the
        staggered integrator: per-component callables
        ``u0[d](coords, t) -> array`` or one vector callable
        ``u0(coords, t) -> [array, ...]``, evaluated at t=0 — here all
        components share the cell-center coordinates."""
        g = self.grid
        if u0_arrays is not None:
            u = tuple(jnp.asarray(c, dtype=self.dtype) for c in u0_arrays)
        elif u0 is not None:
            coords = g.cell_centers(self.dtype)
            if callable(u0):
                vals = u0(coords, 0.0)
            else:
                vals = [u0[d](coords, 0.0) for d in range(g.dim)]
            u = tuple(jnp.broadcast_to(
                jnp.asarray(vals[d], dtype=self.dtype), g.n)
                for d in range(g.dim))
        else:
            u = tuple(jnp.zeros(g.n, dtype=self.dtype)
                      for _ in range(g.dim))
        zero = jnp.zeros(g.n, dtype=self.dtype)
        return CollocatedINSState(
            u=u, p=zero,
            n_prev=tuple(jnp.zeros(g.n, dtype=self.dtype)
                         for _ in range(g.dim)),
            t=jnp.zeros((), dtype=self.dtype),
            k=jnp.zeros((), dtype=jnp.int32))

    # -- approximate projection ----------------------------------------------
    def _approx_project(self, u: Vel) -> Tuple[Vel, jnp.ndarray]:
        """ABS approximate projection: MAC divergence of face-averaged
        velocity drives the Poisson solve; cell-centered central
        gradient corrects."""
        g = self.grid
        dx = g.dx
        # face-normal average: component d onto its lower d-face
        u_face = tuple(0.5 * (u[d] + jnp.roll(u[d], 1, d))
                       for d in range(g.dim))
        div = stencils.divergence(u_face, dx)
        phi = fft.solve_poisson_periodic(div, dx)
        grad_cc = tuple(
            (jnp.roll(phi, -1, d) - jnp.roll(phi, 1, d)) / (2.0 * dx[d])
            for d in range(g.dim))
        return tuple(c - gc for c, gc in zip(u, grad_cc)), phi

    # -- one step -------------------------------------------------------------
    def step(self, state: CollocatedINSState, dt: float,
             f: Optional[Vel] = None) -> CollocatedINSState:
        g = self.grid
        rho, mu = self.rho, self.mu
        dx = g.dx
        u, p = state.u, state.p

        if self.convective_op_type == "none":
            n_star = tuple(jnp.zeros_like(c) for c in u)
            n_curr = n_star
        else:
            n_curr = _cc_convective_rate(u, dx, self.convective_op_type)
            c1 = jnp.where(state.k == 0, 1.0, 1.5).astype(self.dtype)
            c2 = jnp.where(state.k == 0, 0.0, -0.5).astype(self.dtype)
            n_star = tuple(c1 * a + c2 * b
                           for a, b in zip(n_curr, state.n_prev))

        grad_p = tuple(
            (jnp.roll(p, -1, d) - jnp.roll(p, 1, d)) / (2.0 * dx[d])
            for d in range(g.dim))
        rhs = []
        for d in range(g.dim):
            lap = stencils.laplacian(u[d], dx)
            r = (rho / dt) * u[d] + 0.5 * mu * lap \
                - rho * n_star[d] - grad_p[d]
            if f is not None:
                r = r + f[d]
            rhs.append(r)
        # cell-centered Helmholtz solve per component (periodic FFT)
        u_star = tuple(
            fft.solve_helmholtz_periodic(c, dx, alpha=rho / dt,
                                         beta=-0.5 * mu)
            for c in rhs)

        u_new, phi0 = self._approx_project(u_star)
        phi = (rho / dt) * phi0
        p_new = p + phi - (0.5 * mu * dt / rho) * stencils.laplacian(
            phi, dx)

        return CollocatedINSState(u=u_new, p=p_new, n_prev=n_curr,
                                  t=state.t + dt, k=state.k + 1)

    # -- diagnostics ----------------------------------------------------------
    def kinetic_energy(self, state: CollocatedINSState) -> jnp.ndarray:
        ke = sum(jnp.sum(jnp.square(c)) for c in state.u)
        return 0.5 * self.rho * ke * self.grid.cell_volume

    def max_divergence(self, state: CollocatedINSState) -> jnp.ndarray:
        """Cell-centered central divergence — O(h^2) small, NOT roundoff
        (approximate projection)."""
        g = self.grid
        div = jnp.zeros(g.n, dtype=state.u[0].dtype)
        for d in range(g.dim):
            div = div + (jnp.roll(state.u[d], -1, d)
                         - jnp.roll(state.u[d], 1, d)) / (2.0 * g.dx[d])
        return jnp.max(jnp.abs(div))


def advance_collocated(integ: INSCollocatedIntegrator,
                       state: CollocatedINSState, dt: float,
                       num_steps: int,
                       f: Optional[Vel] = None) -> CollocatedINSState:
    def body(s, _):
        return integ.step(s, dt, f), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out
