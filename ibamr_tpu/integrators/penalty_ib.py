"""Penalty IB: massive immersed boundaries.

Reference parity: ``PenaltyIBMethod`` (P14, SURVEY.md §2.2; Kim &
Peskin's penalty formulation). Each massive marker i carries a shadow
mass point Y_i of mass m_i tethered to the IB marker X_i by a stiff
penalty spring K. The IB markers move with the fluid as usual; the mass
points obey Newton's law with gravity, and the spring transmits inertia
and weight to the fluid:

  F_fluid,i = K (Y_i - X_i)                 (added to the elastic force)
  m_i dV_i/dt = -K (Y_i - X_i) + m_i g     (mass-point ODE, symplectic
  dY_i/dt = V_i                             Euler inside the IB step)

TPU-first: the shadow state (Y, V) are two more fixed-shape arrays in
the coupled pytree; the ODE update fuses into the jitted step.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.integrators.ib import IBExplicitIntegrator, IBMethod, IBState
from ibamr_tpu.integrators.ins import INSState, INSStaggeredIntegrator

Vel = Tuple[jnp.ndarray, ...]


class PenaltyIBState(NamedTuple):
    ib: IBState            # fluid + IB markers
    Y: jnp.ndarray         # (N, dim) mass-point positions
    V: jnp.ndarray         # (N, dim) mass-point velocities


class PenaltyIBIntegrator:
    """IBExplicitIntegrator + massive shadow points (P14).

    ``mass``: (N,) marker masses (0 = massless, spring disabled);
    ``stiffness``: penalty spring constant K; ``gravity``: (dim,) g.
    """

    def __init__(self, ins: INSStaggeredIntegrator, ib: IBMethod,
                 mass, stiffness: float, gravity=None,
                 scheme: str = "midpoint"):
        self.inner = IBExplicitIntegrator(ins, ib, scheme=scheme)
        self.ins = ins
        self.ib = ib
        dtype = ins.dtype
        self.mass = jnp.asarray(mass, dtype=dtype)
        self.K = float(stiffness)
        if gravity is None:
            gravity = (0.0,) * ins.grid.dim
        self.gravity = jnp.asarray(gravity, dtype=dtype)

    def initialize(self, X0, ins_state: Optional[INSState] = None,
                   mask=None) -> PenaltyIBState:
        ib_state = self.inner.initialize(X0, ins_state=ins_state, mask=mask)
        return PenaltyIBState(ib=ib_state, Y=ib_state.X,
                              V=jnp.zeros_like(ib_state.X))

    def step(self, state: PenaltyIBState, dt: float) -> PenaltyIBState:
        ib_state, Y, V = state
        massive = (self.mass > 0.0).astype(Y.dtype)[:, None]

        # penalty spring force on the FLUID markers, added to the
        # registered elastic force through the force_fn seam
        base_force = self.ib.compute_force

        def force_with_penalty(X, U, t):
            return base_force(X, U, t) + self.K * massive * (Y - X)

        ib_penalized = IBMethod(self.ib.specs, kernel=self.ib.kernel,
                                force_fn=force_with_penalty,
                                fast=self.ib.fast)
        stepper = IBExplicitIntegrator(self.ins, ib_penalized,
                                       scheme=self.inner.scheme)
        ib_new = stepper.step(ib_state, dt)

        # symplectic-Euler mass-point update (reaction + gravity);
        # massless slots get acc == 0 via where (a tiny-mass clamp would
        # overflow to inf and 0*inf = NaN under the mask)
        acc = jnp.where(
            self.mass[:, None] > 0.0,
            -self.K * (Y - ib_new.X)
            / jnp.where(self.mass > 0.0, self.mass, 1.0)[:, None]
            + self.gravity, 0.0)
        V_new = massive * (V + dt * acc)
        Y_new = Y + dt * V_new * massive
        return PenaltyIBState(ib=ib_new, Y=Y_new, V=V_new)


def advance_penalty_ib(integ: PenaltyIBIntegrator, state: PenaltyIBState,
                       dt: float, num_steps: int) -> PenaltyIBState:
    def body(s, _):
        return integ.step(s, dt), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out
