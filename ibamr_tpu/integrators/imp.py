"""IMP: immersed material-point method (P18).

Reference parity: ``IMPMethod`` / ``IMPInitializer`` (SURVEY.md §2.2
P18 [vintage]) — immersed structures represented as material points
carrying full continuum-mechanics state (deformation gradient F,
reference volume V0) instead of spring networks: velocity interpolated
from the grid moves the points, the interpolated velocity GRADIENT
evolves F (dF/dt = (grad u) F), and the first-Piola–Kirchhoff stress of
a hyperelastic constitutive law generates the fluid body force in
divergence form f = -sum_p V0_p P(F_p) F_p^T grad(delta_h).

TPU-first shape: points are fixed-capacity (N, ...) arrays with an
active mask (the Lagrangian-pool convention of ``integrators.ib``); the
kernel-gradient transfers are the tensor-product scatter/gather of
:mod:`ibamr_tpu.ops.interaction` with analytic-AD kernel derivatives —
no new primitive, and the whole step jits into one XLA computation.
B-spline kernels (C^1) are the default, as kernel-gradient quality
drives the method.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator, INSState
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.delta import Kernel

Array = jnp.ndarray
Vel = Tuple[Array, ...]


class NeoHookean(NamedTuple):
    """Compressible neo-Hookean: P(F) = mu (F - F^-T) + lam ln(J) F^-T."""
    mu: float
    lam: float

    def pk1(self, F: Array) -> Array:
        Finv = jnp.linalg.inv(F)
        FinvT = jnp.swapaxes(Finv, -1, -2)
        J = jnp.linalg.det(F)
        lnJ = jnp.log(jnp.maximum(J, 1e-12))
        return self.mu * (F - FinvT) \
            + self.lam * lnJ[..., None, None] * FinvT


class IMPState(NamedTuple):
    ins: INSState
    X: Array        # (N, dim) point positions
    F: Array        # (N, dim, dim) deformation gradients
    mask: Array     # (N,) active-slot mask


class IMPMethod:
    """Material-point structure container: volumes, constitutive law,
    kernel choice, and the grid<->point transfer operations."""

    def __init__(self, V0: Array, model: NeoHookean,
                 kernel: Kernel = "BSPLINE_3"):
        self.V0 = jnp.asarray(V0)
        self.model = model
        self.kernel = kernel

    def interpolate_velocity(self, u: Vel, grid: StaggeredGrid,
                             X: Array, mask: Array) -> Array:
        return interaction.interpolate_vel(u, grid, X,
                                           kernel=self.kernel,
                                           weights=mask)

    def velocity_gradient(self, u: Vel, grid: StaggeredGrid,
                          X: Array, mask: Array) -> Array:
        return interaction.interpolate_gradient_vel(
            u, grid, X, kernel=self.kernel, weights=mask)

    def velocity_and_gradient(self, u: Vel, grid: StaggeredGrid,
                              X: Array, mask: Array):
        """Fused (U, grad u) at points — one stencil pass per
        component (the hot transfer path of the IMP step)."""
        return interaction.interpolate_vel_and_gradient(
            u, grid, X, kernel=self.kernel, weights=mask)

    def spread_force(self, F_def: Array, grid: StaggeredGrid,
                     X: Array, mask: Array) -> Vel:
        P = self.model.pk1(F_def)
        PFt = P @ jnp.swapaxes(F_def, -1, -2)
        return interaction.spread_stress(PFt, self.V0, grid, X,
                                         kernel=self.kernel,
                                         weights=mask)


class IMPExplicitIntegrator:
    """Explicit IMP coupling to the periodic staggered INS integrator
    (the P8 explicit pattern of ``IBExplicitIntegrator``, with the
    marker force replaced by material-point stress divergence and the
    structure state extended with F)."""

    def __init__(self, ins: INSStaggeredIntegrator, imp: IMPMethod,
                 scheme: str = "midpoint"):
        if scheme not in ("midpoint", "forward_euler"):
            raise ValueError(f"unknown IMP scheme {scheme!r}")
        self.ins = ins
        self.imp = imp
        self.scheme = scheme

    def initialize(self, X0, ins_state: Optional[INSState] = None,
                   mask=None) -> IMPState:
        dtype = self.ins.dtype
        X = jnp.asarray(X0, dtype=dtype)
        N, dim = X.shape
        if ins_state is None:
            ins_state = self.ins.initialize()
        if mask is None:
            mask = jnp.ones(N, dtype=dtype)
        F = jnp.broadcast_to(jnp.eye(dim, dtype=dtype), (N, dim, dim))
        return IMPState(ins=ins_state, X=X, F=F,
                        mask=jnp.asarray(mask, dtype=dtype))

    def step(self, state: IMPState, dt: float) -> IMPState:
        grid = self.ins.grid
        imp = self.imp
        u_n = state.ins.u
        X_n, F_n = state.X, state.F
        dim = grid.dim
        eye = jnp.eye(dim, dtype=X_n.dtype)

        U_n, G_n = imp.velocity_and_gradient(u_n, grid, X_n, state.mask)

        if self.scheme == "midpoint":
            X_half = X_n + 0.5 * dt * U_n
            F_half = (eye + 0.5 * dt * G_n) @ F_n
        else:
            X_half, F_half = X_n, F_n

        f_eul = imp.spread_force(F_half, grid, X_half, state.mask)
        ins_new = self.ins.step(state.ins, dt, f=f_eul)

        if self.scheme == "midpoint":
            u_half = tuple(0.5 * (a + b) for a, b in zip(u_n, ins_new.u))
            U_half, G_half = imp.velocity_and_gradient(
                u_half, grid, X_half, state.mask)
            X_new = X_n + dt * U_half
            # midpoint rule for dF/dt = G F: the half-step gradient
            # acts on the HALF-step state (F_n + dt*G_half@F_n drops
            # the dt^2 G^2/2 term and degrades F to first order)
            F_new = F_n + dt * G_half @ F_half
        else:
            X_new = X_n + dt * U_n
            F_new = (eye + dt * G_n) @ F_n

        return IMPState(ins=ins_new, X=X_new, F=F_new, mask=state.mask)

    # -- diagnostics ---------------------------------------------------
    def jacobians(self, state: IMPState) -> Array:
        """det(F) per point (volume-change diagnostic; ~1 for nearly
        incompressible motion)."""
        return jnp.linalg.det(state.F)


def material_disc(grid: StaggeredGrid, center, radius: float,
                  points_per_cell: int = 2, dtype=jnp.float64):
    """Uniformly seeded material points filling a disc/ball: positions
    (N, dim) and per-point reference volumes (N,). The IMPInitializer
    analog for the standard test geometry."""
    import numpy as np

    dim = grid.dim
    h = min(grid.dx)
    spacing = h / points_per_cell
    axes = [np.arange(c - radius, c + radius + spacing / 2, spacing)
            for c in center]
    mesh = np.meshgrid(*axes, indexing="ij")
    pts = np.stack([m.ravel() for m in mesh], axis=-1)
    keep = np.sum((pts - np.asarray(center)) ** 2, axis=-1) \
        <= radius ** 2
    pts = pts[keep]
    vol = spacing ** dim
    dtype = jax.dtypes.canonicalize_dtype(dtype)
    return (jnp.asarray(pts, dtype=dtype),
            jnp.full(pts.shape[0], vol, dtype=dtype))
