"""Variable-coefficient (multiphase) INS integrator with level-set
interface capture.

Reference parity: the multiphase pieces of P22 (SURVEY.md §2.2 —
``INSVCStaggeredHierarchyIntegrator`` conservative/non-conservative,
surface-tension / gravity forcing, level-set coupling) in the periodic
TPU-first setting:

- density rho(phi) and viscosity mu(phi) from a smoothed-Heaviside blend
  of the two phases' properties (the level-set coupling);
- explicit AB2 convection + EXPLICIT variable-viscosity stress
  (divergence of 2 mu D(u) — dt limited by the viscous CFL of the
  heavier constraint, the documented trade of the non-conservative
  variant at this stage);
- variable-density projection  div( (1/rho) grad p ) = div(u*)/dt
  (harmonic-density face coefficients) solved matrix-free with CG
  preconditioned by ONE V-cycle of the true variable-coefficient
  multigrid (ratio-robust, ~10 iterations at density ratio 1000 — the
  reference's FAC-preconditioned VC Poisson, T8) or optionally the
  constant-coefficient FFT inverse;
- continuum-surface-force surface tension  f = sigma kappa delta(phi)
  grad phi  and gravity  rho g;
- the level set is advected with the Godunov advector and periodically
  reinitialized (physics.level_set).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils
from ibamr_tpu.ops.convection import convective_rate
from ibamr_tpu.ops.godunov import advect
from ibamr_tpu.physics import level_set as ls
from ibamr_tpu.solvers import fft, krylov

Vel = Tuple[jnp.ndarray, ...]


class VCINSState(NamedTuple):
    u: Vel
    p: jnp.ndarray
    phi: jnp.ndarray         # level set (negative = phase 0)
    n_prev: Vel
    t: jnp.ndarray
    k: jnp.ndarray


def _cc_to_face(f: jnp.ndarray, d: int) -> jnp.ndarray:
    return 0.5 * (f + jnp.roll(f, 1, d))


class INSVCStaggeredIntegrator:
    """Two-phase variable-coefficient INS (P22 multiphase analog)."""

    def __init__(self, grid: StaggeredGrid,
                 rho0: float = 1.0, rho1: float = 1.0,
                 mu0: float = 0.01, mu1: float = 0.01,
                 sigma: float = 0.0,
                 gravity: Optional[Sequence[float]] = None,
                 convective_op_type: str = "upwind",
                 interface_eps: Optional[float] = None,
                 reinit_interval: int = 10,
                 cg_tol: float = 1e-8, cg_maxiter: int = 200,
                 precond: str = "mg",
                 wall_axes: Optional[Sequence[bool]] = None,
                 tangential=None,
                 open_outlet: bool = False,
                 still_level: Optional[float] = None,
                 dtype=jnp.float32):
        self.grid = grid
        self.rho = (float(rho0), float(rho1))
        self.mu = (float(mu0), float(mu1))
        self.sigma = float(sigma)
        self.gravity = (tuple(float(g) for g in gravity)
                        if gravity is not None else (0.0,) * grid.dim)
        self.convective_op_type = convective_op_type
        self.eps = (interface_eps if interface_eps is not None
                    else 1.5 * max(grid.dx))
        self.reinit_interval = int(reinit_interval)
        self.cg_tol = float(cg_tol)
        self.cg_maxiter = int(cg_maxiter)
        # wall_axes[d] puts NO-SLIP physical walls on both sides of
        # axis d (pinned-face storage of integrators.ins_walls: the
        # wall-NORMAL component's slot 0 along d is the lo wall face,
        # pinned to 0; the hi wall face is its periodic-wrap image), the
        # non-periodic half of P22 the reference runs its tanks with
        # (INSVCStaggeredHierarchyIntegrator wall BCs, SURVEY.md §2.2).
        # tangential[(d, e, side)] prescribes component d's tangential
        # velocity on the side (0=lo/1=hi) wall of axis e (moving lid).
        self.wall_axes = (tuple(bool(w) for w in wall_axes)
                          if wall_axes is not None
                          else (False,) * grid.dim)
        self.tangential = dict(tangential or {})
        if precond not in ("fft", "mg"):
            raise ValueError(f"unknown preconditioner {precond!r}")
        if any(self.wall_axes) and precond == "fft":
            raise ValueError(
                "wall-bounded VC-INS requires the 'mg' preconditioner "
                "(the FFT inverse assumes a fully periodic domain)")
        # "fft": exact constant-coefficient inverse (iterations grow
        # with the density ratio); "mg": one V-cycle of the TRUE
        # variable-coefficient operator (ratio-robust — the reference's
        # FAC-preconditioned VC Poisson, SURVEY.md T8/P22)
        self.precond = precond
        self.dtype = dtype
        # open_outlet (round 5, VERDICT item 3a — open-boundary x VC
        # two-phase): axis 0 becomes wall(lo) -> OUTLET(hi). The
        # pinned-face layout's single axis-0 wrap slot stores the
        # OUTLET face (free, pressure-Dirichlet-corrected); the inlet
        # face is an implicit impermeable back wall (the NWT geometry:
        # back wall + generation zone + working region + beach +
        # outlet). Advection/stress stencils still wrap axis 0 — valid
        # under the SANDWICH CONTRACT: a generation zone at the lo end
        # and a damping beach before the outlet keep both sides of the
        # wrap near still water, so wrapped neighbors agree to the
        # relaxation tolerance (the same clearance-style contract the
        # IB layout bridges use). Gravity is referenced to the STILL
        # density profile (rho - rho_still(z)) g, so the still state
        # has p = 0 and the outlet's homogeneous Dirichlet is exact.
        self.open_outlet = bool(open_outlet)
        self.still_level = still_level
        self._rho_still = None
        if self.open_outlet:
            if precond != "mg":
                raise ValueError(
                    "open_outlet requires the 'mg' preconditioner "
                    "(the FFT inverse assumes a periodic domain)")
            if self.wall_axes[0]:
                raise ValueError(
                    "open_outlet replaces axis 0's boundary pair "
                    "(wall lo -> outlet hi); wall_axes[0] must be "
                    "False")
            if still_level is None and any(
                    gv != 0.0 for gv in self.gravity):
                raise ValueError(
                    "open_outlet with gravity needs still_level (the "
                    "still free-surface height referencing the "
                    "hydrostatic profile so outlet p = 0 is exact)")
            if any(gv != 0.0
                   for gv in self.gravity[:grid.dim - 1]):
                raise ValueError(
                    "open_outlet supports gravity along the LAST axis "
                    "only (the still hydrostatic reference is a "
                    "z-profile; a transverse gravity component would "
                    "silently break the outlet's p = 0 exactness)")
            if still_level is not None:
                zax = grid.dim - 1
                z = (grid.x_lo[zax]
                     + (jnp.arange(grid.n[zax], dtype=dtype) + 0.5)
                     * grid.dx[zax])
                shape = [1] * grid.dim
                shape[zax] = grid.n[zax]
                phi_still = (z.reshape(shape)
                             - float(still_level)) * jnp.ones(
                    grid.n, dtype=dtype)
                self._rho_still = self.density(phi_still)

    # -- wall helpers --------------------------------------------------------
    def _pin_normal(self, c: jnp.ndarray, d: int) -> jnp.ndarray:
        """Zero the pinned wall-face slot of component d (wall axes)."""
        from ibamr_tpu.integrators.ins_walls import pin_normal

        return pin_normal(c, d, self.wall_axes)

    def _proj_bc(self):
        """Pressure-Poisson BCs: Neumann at walls, periodic elsewhere
        (the discrete counterpart of the masked wall-face gradient)."""
        from ibamr_tpu.bc import AxisBC, DomainBC, neumann_axis

        return DomainBC(axes=tuple(
            neumann_axis() if w else AxisBC() for w in self.wall_axes))

    # -- material fields -----------------------------------------------------
    def density(self, phi: jnp.ndarray) -> jnp.ndarray:
        H = ls.heaviside(phi, self.eps)
        return self.rho[0] + (self.rho[1] - self.rho[0]) * H

    def viscosity(self, phi: jnp.ndarray) -> jnp.ndarray:
        H = ls.heaviside(phi, self.eps)
        return self.mu[0] + (self.mu[1] - self.mu[0]) * H

    # -- variable-density projection -----------------------------------------
    def project_vc(self, u: Vel, rho_cc: jnp.ndarray,
                   dt: float, face_rule: str = "harmonic"
                   ) -> Tuple[Vel, jnp.ndarray]:
        """Solve div((dt/rho) grad p) = div u*, correct
        u <- u* - (dt/rho) grad p. CG with the configured
        preconditioner (VC multigrid V-cycle or FFT).

        ``face_rule``: "harmonic" (arithmetic mean of 1/rho — the
        standard choice for large density jumps, and exactly the rule
        the MG preconditioner's coefficient coarsening uses) or
        "arithmetic" (1 / mean(rho) — the conservative integrator's
        rule, matching its face momentum density so the pressure
        correction's TOTAL momentum telescopes to zero). The velocity
        correction uses the SAME coefficient as the operator either
        way, so div(u_new) = 0 holds discretely."""
        g = self.grid
        dx = g.dx
        if face_rule == "harmonic":
            inv_rho_face = tuple(_cc_to_face(1.0 / rho_cc, d)
                                 for d in range(g.dim))
        elif face_rule == "arithmetic":
            inv_rho_face = tuple(1.0 / _cc_to_face(rho_cc, d)
                                 for d in range(g.dim))
        else:
            raise ValueError(f"unknown face_rule {face_rule!r}")
        # masking the wall-face coefficient makes the operator's wall
        # rows homogeneous-Neumann AND keeps the velocity correction
        # from touching the pinned faces — one mask, both halves of the
        # discrete-exactness argument (see ins_walls module docstring)
        inv_rho_face = tuple(self._pin_normal(c, d)
                             for d, c in enumerate(inv_rho_face))
        if self.open_outlet:
            return self._project_vc_open(u, rho_cc, dt, inv_rho_face)
        div = stencils.divergence(u, dx)
        div = div - jnp.mean(div)
        rho_ref = min(self.rho)

        # cg requires a POSITIVE-definite system; -div((dt/rho) grad .)
        # is SPD on the zero-mean subspace, so solve the negated system
        # (round 2 fix: the unnegated operator tripped cg's pAp>0
        # breakdown guard every iteration and the solve returned 0)
        def A(p):
            gp = stencils.gradient(p, dx)
            flux = tuple(dt * rf * gc
                         for rf, gc in zip(inv_rho_face, gp))
            return -stencils.divergence(flux, dx)

        if self.precond == "mg":
            from ibamr_tpu.bc import DomainBC
            from ibamr_tpu.solvers.multigrid import PoissonMultigrid

            # one V-cycle of the true VC operator div((dt/rho) grad .)
            # — the level hierarchy (coefficient coarsening, diagonals)
            # traces into the step; shapes are static so this compiles
            # once. Note A is the NEGATED operator, so M negates too.
            bc = (self._proj_bc() if any(self.wall_axes)
                  else DomainBC.periodic(g.dim))
            mg = PoissonMultigrid(g.n, bc, dx,
                                  D=dt / rho_cc, dtype=rho_cc.dtype)

            def M(r):
                r = r - jnp.mean(r)
                q = mg.vcycle(jnp.zeros_like(r), r)
                return -(q - jnp.mean(q))
        else:
            def M(r):
                # exact inverse of the constant-coefficient operator
                return -fft.solve_poisson_periodic(r / (dt / rho_ref),
                                                   dx)

        # clamp the tolerance to the dtype's reachable floor: an f32
        # production run configured with the f64 default (1e-8) must
        # iterate to ITS roundoff floor and stop, not chase an
        # unreachable residual past the divergence guard
        eps = float(jnp.finfo(rho_cc.dtype).eps)
        tol_eff = max(self.cg_tol, 20.0 * eps)
        res = krylov.cg(A, -div, M=M, tol=tol_eff,
                        maxiter=self.cg_maxiter)
        p = res.x - jnp.mean(res.x)
        gp = stencils.gradient(p, dx)
        u_new = tuple(self._pin_normal(c - dt * rf * gc, d)
                      for d, (c, rf, gc)
                      in enumerate(zip(u, inv_rho_face, gp)))
        return u_new, p

    def _project_vc_open(self, u: Vel, rho_cc, dt, inv_rho_face):
        """Variable-density projection with axis 0 = wall(lo) ->
        OUTLET(hi): no pressure nullspace (the outlet's homogeneous
        Dirichlet anchors p), the axis-0 operator/divergence/correction
        assembled from the explicit (n+1)-face flux array
        [wall 0, interior, outlet half-cell], and the MG
        preconditioner carries the matching mixed Neumann/Dirichlet
        BCs. The axis-0 wrap slot of u_0 stores the outlet face."""
        from ibamr_tpu.bc import (DIRICHLET, NEUMANN, AxisBC, DomainBC,
                                  SideBC, neumann_axis, periodic_axis)
        from ibamr_tpu.solvers.multigrid import PoissonMultigrid

        g = self.grid
        dx = g.dx
        take = stencils.axis_slice
        n0 = g.n[0]
        # outlet face coefficient: one-sided against cell n0-1
        inv_out = dt * take(1.0 / rho_cc, 0, n0 - 1, n0)

        def axis0_fluxes(p):
            gp_int = (take(p, 0, 1, n0) - take(p, 0, 0, n0 - 1)) / dx[0]
            flux_int = dt * take(inv_rho_face[0], 0, 1, n0) * gp_int
            flux_out = inv_out * (0.0 - take(p, 0, n0 - 1, n0)) \
                / (0.5 * dx[0])
            wall = jnp.zeros_like(flux_out)
            return jnp.concatenate([wall, flux_int, flux_out], axis=0)

        def _gp_t(p, d):
            # transverse face gradient (periodic/wall-pinned axes only
            # — axis 0 has its own explicit face assembly)
            return (p - jnp.roll(p, 1, d)) / dx[d]

        def A(p):
            fx = axis0_fluxes(p)
            div = (take(fx, 0, 1, n0 + 1) - take(fx, 0, 0, n0)) / dx[0]
            for d in range(1, g.dim):
                flux = dt * inv_rho_face[d] * _gp_t(p, d)
                div = div + (jnp.roll(flux, -1, d) - flux) / dx[d]
            return -div

        def div_star(uv):
            # axis 0: [wall 0, interior slots 1.., outlet (slot 0)]
            ux = uv[0]
            faces0 = jnp.concatenate(
                [jnp.zeros_like(take(ux, 0, 0, 1)),
                 take(ux, 0, 1, n0), take(ux, 0, 0, 1)], axis=0)
            div = (take(faces0, 0, 1, n0 + 1)
                   - take(faces0, 0, 0, n0)) / dx[0]
            for d in range(1, g.dim):
                div = div + (jnp.roll(uv[d], -1, d) - uv[d]) / dx[d]
            return div

        axes = [AxisBC(SideBC(NEUMANN), SideBC(DIRICHLET))]
        for d in range(1, g.dim):
            axes.append(neumann_axis() if self.wall_axes[d]
                        else periodic_axis())
        bc = DomainBC(axes=tuple(axes))
        mg = PoissonMultigrid(g.n, bc, dx, D=dt / rho_cc,
                              dtype=rho_cc.dtype)

        def M(r):
            return -mg.vcycle(jnp.zeros_like(r), r)

        eps = float(jnp.finfo(rho_cc.dtype).eps)
        tol_eff = max(self.cg_tol, 20.0 * eps)
        res = krylov.cg(A, -div_star(u), M=M, tol=tol_eff,
                        maxiter=self.cg_maxiter)
        p = res.x
        u_new = []
        for d in range(g.dim):
            if d == 0:
                # slot 0 is the outlet face (half-cell coefficient);
                # interior slots use the standard face correction
                corr_out = inv_out * (0.0 - take(p, 0, n0 - 1, n0)) \
                    / (0.5 * dx[0])
                c = jnp.concatenate(
                    [take(u[0], 0, 0, 1) - corr_out,
                     take(u[0], 0, 1, n0)
                     - dt * take(inv_rho_face[0], 0, 1, n0)
                     * (take(p, 0, 1, n0)
                        - take(p, 0, 0, n0 - 1)) / dx[0]], axis=0)
                u_new.append(c)
            else:
                u_new.append(self._pin_normal(
                    u[d] - dt * inv_rho_face[d] * _gp_t(p, d), d))
        return tuple(u_new), p

    # -- variable-viscosity stress -------------------------------------------
    def _viscous_force(self, u: Vel, mu_cc: jnp.ndarray) -> Vel:
        """div(2 mu D(u)) on the MAC grid (explicit). Diagonal terms use
        cell-centered mu; off-diagonal terms use mu averaged to the
        transverse-face (edge-like) locations.

        Wall axes (pinned-face storage): the DIAGONAL term's rolls stay
        exact (both wall faces carry 0 for the normal component, and
        the wall-face output rows are pinned anyway). The OFF-DIAGONAL
        term for component d across wall axis j needs the true wall
        shear: tau_dj at the wall edge = mu_wall * 2 (u_d - V_wall)/dx_j
        (half-cell one-sided gradient against the prescribed tangential
        velocity; du_j/dx_d vanishes on the wall since u_j = 0 along
        it), with mu_wall the even-reflection (adjacent-cell) viscosity
        — assembled by CONCATENATING [lo-wall edge, interior edges,
        hi-wall edge] along j (n+1 edge planes) and differencing."""
        g = self.grid
        dim = g.dim
        dx = g.dx

        take = stencils.axis_slice

        out = []
        for d in range(dim):
            acc = None
            for j in range(dim):
                if j == d:
                    # tau_dd = 2 mu du_d/dx_d at cell centers
                    dudx = (jnp.roll(u[d], -1, d) - u[d]) / dx[d]
                    tau = 2.0 * mu_cc * dudx
                    term = (tau - jnp.roll(tau, 1, d)) / dx[d]
                else:
                    # tau_dj = mu (du_d/dx_j + du_j/dx_d) at d-j corners
                    dudj = (u[d] - jnp.roll(u[d], 1, j)) / dx[j]
                    dujd = (u[j] - jnp.roll(u[j], 1, d)) / dx[d]
                    mu_e = 0.25 * (mu_cc + jnp.roll(mu_cc, 1, d)
                                   + jnp.roll(mu_cc, 1, j)
                                   + jnp.roll(jnp.roll(mu_cc, 1, d), 1, j))
                    tau = mu_e * (dudj + dujd)
                    if self.wall_axes[j]:
                        nj = u[d].shape[j]
                        # mu averaged along d to the face, one-sided in j
                        mu_d = 0.5 * (mu_cc + jnp.roll(mu_cc, 1, d))
                        v_lo = self.tangential.get((d, j, 0), 0.0)
                        v_hi = self.tangential.get((d, j, 1), 0.0)
                        t_lo = (take(mu_d, j, 0, 1)
                                * 2.0 * (take(u[d], j, 0, 1) - v_lo)
                                / dx[j])
                        t_hi = (take(mu_d, j, nj - 1, nj)
                                * 2.0 * (v_hi - take(u[d], j, nj - 1, nj))
                                / dx[j])
                        tau_full = jnp.concatenate(
                            [t_lo, take(tau, j, 1, nj), t_hi], axis=j)
                        term = (take(tau_full, j, 1, nj + 1)
                                - take(tau_full, j, 0, nj)) / dx[j]
                    else:
                        term = (jnp.roll(tau, -1, j) - tau) / dx[j]
                acc = term if acc is None else acc + term
            out.append(self._pin_normal(acc, d))
        return tuple(out)

    # -- surface tension + gravity -------------------------------------------
    def _interface_forces(self, phi: jnp.ndarray,
                          rho_cc: jnp.ndarray) -> Vel:
        """Interface FORCE densities: CSF surface tension + buoyancy in
        the net-force-free periodic form (rho - mean(rho)) g.

        Why the anomaly form: uniform acceleration g in a periodic box
        is pure free fall (equivalence principle — the projection's
        mean mode is div-free and absorbs nothing), and building rho*g
        with one face rule while dividing by another scales gravity
        O(ratio) wrong at interface faces. The density-ANOMALY force
        yields exact hydrostatic quiescence for flat pools, genuine
        relative buoyancy for drops/bubbles, and injects zero net
        momentum (both regression-tested)."""
        g = self.grid
        dx = g.dx
        out = []
        kap = (ls.curvature(phi, dx, wall_axes=self.wall_axes)
               if self.sigma else None)
        dlt = ls.delta(phi, self.eps) if self.sigma else None
        # open-outlet: reference the STILL hydrostatic profile so the
        # quiescent state has p = 0 (outlet Dirichlet exact); periodic
        # and walled tanks keep the net-force-free mean anomaly
        if self._rho_still is not None:
            drho = rho_cc - self._rho_still
        else:
            drho = rho_cc - jnp.mean(rho_cc)
        for d in range(g.dim):
            f = _cc_to_face(drho, d) * self.gravity[d]
            if self.sigma:
                gphi = (phi - jnp.roll(phi, 1, d)) / dx[d]
                f = f + self.sigma * _cc_to_face(kap * dlt, d) * gphi
            out.append(self._pin_normal(f, d))
        return tuple(out)

    # -- state / stepping ----------------------------------------------------
    def initialize(self, phi0, u0_arrays: Optional[Vel] = None
                   ) -> VCINSState:
        g = self.grid
        phi = jnp.asarray(phi0, dtype=self.dtype)
        if u0_arrays is not None:
            u = tuple(jnp.asarray(c, dtype=self.dtype) for c in u0_arrays)
        else:
            u = tuple(jnp.zeros(g.n, dtype=self.dtype)
                      for _ in range(g.dim))
        return VCINSState(
            u=u, p=jnp.zeros(g.n, dtype=self.dtype), phi=phi,
            n_prev=tuple(jnp.zeros(g.n, dtype=self.dtype)
                         for _ in range(g.dim)),
            t=jnp.zeros((), dtype=self.dtype),
            k=jnp.zeros((), dtype=jnp.int32))

    def step(self, state: VCINSState, dt: float,
             f: Optional[Vel] = None) -> VCINSState:
        g = self.grid
        dx = g.dx
        u, p, phi = state.u, state.p, state.phi

        rho_cc = self.density(phi)
        mu_cc = self.viscosity(phi)
        # harmonic-density face weights: the SAME discrete (1/rho)
        # operator as project_vc, so the accumulated-pressure gradient
        # in the predictor and the increment correction stay consistent
        # (mixing arithmetic/harmonic faces inflates splitting error by
        # the density ratio at interface faces)
        inv_rho_face = tuple(_cc_to_face(1.0 / rho_cc, d)
                             for d in range(g.dim))

        # convection (AB2)
        if self.convective_op_type == "none":
            n_curr = tuple(jnp.zeros_like(c) for c in u)
            n_star = n_curr
        else:
            n_curr = self._convective(u)
            c1 = jnp.where(state.k == 0, 1.0, 1.5).astype(self.dtype)
            c2 = jnp.where(state.k == 0, 0.0, -0.5).astype(self.dtype)
            n_star = tuple(c1 * a + c2 * b
                           for a, b in zip(n_curr, state.n_prev))

        visc = self._viscous_force(u, mu_cc)
        body = self._interface_forces(phi, rho_cc)
        gp = stencils.gradient(p, dx)

        u_star = []
        for d in range(g.dim):
            rhs = (-n_star[d]
                   + (visc[d] + body[d] - gp[d]) * inv_rho_face[d])
            if f is not None:
                rhs = rhs + f[d] * inv_rho_face[d]
            u_star.append(self._pin_normal(u[d] + dt * rhs, d))

        if self.open_outlet:
            # seed the outlet face (axis-0 wrap slot) by zero-gradient
            # outflow extrapolation; the projection then sets it from
            # mass conservation + the outlet pressure condition
            n0 = g.n[0]
            u_star[0] = jnp.concatenate(
                [stencils.axis_slice(u_star[0], 0, n0 - 1, n0),
                 stencils.axis_slice(u_star[0], 0, 1, n0)], axis=0)

        # variable-density pressure-increment projection
        u_new, dp = self.project_vc(tuple(u_star), rho_cc, dt)
        p_new = p + dp

        # advect + periodically reinitialize the level set
        phi_new = self._transport_level_set(phi, u_new, dt, state.k)

        return VCINSState(u=u_new, p=p_new, phi=phi_new, n_prev=n_curr,
                          t=state.t + dt, k=state.k + 1)

    def _convective(self, u: Vel) -> Vel:
        """N(u) — BC-aware ghost-padded path when any axis is walled
        (the wall-edge momentum fluxes vanish and tangential lids enter
        through the Dirichlet ghosts), periodic rolls otherwise."""
        if any(self.wall_axes):
            from ibamr_tpu.ops.convection import convective_rate_bc

            return convective_rate_bc(
                u, self.grid.dx, scheme=self.convective_op_type,
                wall_axes=self.wall_axes,
                wall_tangential=self.tangential)
        return convective_rate(u, self.grid.dx, self.convective_op_type)

    def _transport_level_set(self, phi, u_new: Vel, dt, k):
        """Godunov advection + cadenced reinitialization (shared by the
        non-conservative and conservative steps). Wall axes ride the
        pinned-face convention: wall-face fluxes vanish identically, so
        the advection conserves mass in the walled box too."""
        wa = self.wall_axes if any(self.wall_axes) else None
        phi_new = advect(phi, u_new, self.grid.dx, dt, wall_axes=wa)
        return jax.lax.cond(
            jnp.mod(k + 1, self.reinit_interval) == 0,
            lambda q: ls.reinitialize(q, self.grid.dx, iters=20,
                                      wall_axes=wa),
            lambda q: q, phi_new)

    # -- diagnostics ---------------------------------------------------------
    def max_divergence(self, state: VCINSState) -> jnp.ndarray:
        return jnp.max(jnp.abs(stencils.divergence(state.u, self.grid.dx)))

    def heavy_phase_volume(self, state: VCINSState) -> jnp.ndarray:
        """Volume of the DENSER phase: phi>0 carries rho1 (density()
        blends rho0 -> rho1 with H(phi)), so the heavy phase is phi>0
        when rho1 >= rho0, else phi<0. (Regression: this used to
        return the phi<0 volume unconditionally — normalizing a drop's
        'volume drift' by the ~20x larger ambient volume.)"""
        vol_neg = ls.phase_volume(state.phi, self.grid, self.eps)
        total = float(np.prod(self.grid.n)) * self.grid.cell_volume
        if self.rho[1] >= self.rho[0]:
            return total - vol_neg
        return vol_neg


class VCConsState(NamedTuple):
    u: Vel
    p: jnp.ndarray
    phi: jnp.ndarray
    rho: jnp.ndarray        # conservatively transported density
    t: jnp.ndarray
    k: jnp.ndarray


class INSVCConservativeIntegrator(INSVCStaggeredIntegrator):
    """Conservative-form variable-coefficient INS — the
    ``INSVCStaggeredConservativeHierarchyIntegrator`` half of P22:
    density is a conserved state transported by upwind mass fluxes, and
    momentum is advected with the SAME mass fluxes interpolated to each
    momentum control volume (consistent mass–momentum transport).

    Discrete consistency: the face momentum density is the ARITHMETIC
    mean of the cell densities. Arithmetic means are linear, so the
    face density satisfies its own continuity equation with exactly the
    face-interpolated fluxes the momentum advection uses — which makes
    uniform translation of a density jump an EXACT discrete equilibrium
    (no spurious interface accelerations; tested). The projection uses
    the matching arithmetic face coefficient, so the pressure
    correction's total momentum telescopes to zero and global momentum
    is conserved to roundoff under net-force-free forcing — the
    property the non-conservative velocity form cannot have (both
    pinned by tests). Viscosity stays slaved to the level set;
    ``rho_resync_interval`` optionally re-slaves rho to phi to bound
    drift between the conserved density and the interface geometry."""

    def __init__(self, *args, rho_resync_interval: int = 0, **kw):
        super().__init__(*args, **kw)
        self.rho_resync_interval = int(rho_resync_interval)
        if self.convective_op_type not in ("upwind", "none"):
            raise ValueError(
                "the conservative form advects momentum with upwind "
                "mass fluxes; convective_op_type must be 'upwind' "
                f"(or 'none' for the Stokes limit), got "
                f"{self.convective_op_type!r}")

    # -- conservative transport ----------------------------------------
    def _mass_fluxes(self, u: Vel, rho_cc: jnp.ndarray) -> Vel:
        """Upwind mass flux rho*u through every (lower) cell face."""
        out = []
        for d in range(self.grid.dim):
            rho_up = jnp.where(u[d] > 0, jnp.roll(rho_cc, 1, d), rho_cc)
            out.append(u[d] * rho_up)
        return tuple(out)

    def _momentum_advection(self, u: Vel, F: Vel) -> Vel:
        """div(F u) on each momentum control volume, upwinding u_d by
        the sign of the interpolated mass flux — the consistent pairing
        (same F as the density update)."""
        g = self.grid
        dim = g.dim
        dx = g.dx
        out = []
        for d in range(dim):
            acc = None
            for j in range(dim):
                if j == d:
                    # CV faces at cell centers along d
                    Fc = 0.5 * (F[d] + jnp.roll(F[d], -1, d))
                    u_up = jnp.where(Fc > 0, u[d],
                                     jnp.roll(u[d], -1, d))
                    G = Fc * u_up
                    term = (G - jnp.roll(G, 1, d)) / dx[d]
                else:
                    # CV faces at d-j edges
                    Fe = 0.5 * (F[j] + jnp.roll(F[j], 1, d))
                    u_up = jnp.where(Fe > 0, jnp.roll(u[d], 1, j),
                                     u[d])
                    G = Fe * u_up
                    term = (jnp.roll(G, -1, j) - G) / dx[j]
                acc = term if acc is None else acc + term
            out.append(acc)
        return tuple(out)

    # -- state / stepping ----------------------------------------------
    def initialize(self, phi0, u0_arrays: Optional[Vel] = None
                   ) -> VCConsState:
        base = super().initialize(phi0, u0_arrays=u0_arrays)
        return VCConsState(u=base.u, p=base.p, phi=base.phi,
                           rho=self.density(base.phi),
                           t=base.t, k=base.k)

    def step(self, state: VCConsState, dt: float,
             f: Optional[Vel] = None) -> VCConsState:
        g = self.grid
        dx = g.dx
        u, p, phi, rho = state.u, state.p, state.phi, state.rho
        mu_cc = self.viscosity(phi)

        # 1. mass transport (conservative)
        F = self._mass_fluxes(u, rho)
        div_F = None
        for d in range(g.dim):
            t_ = (jnp.roll(F[d], -1, d) - F[d]) / dx[d]
            div_F = t_ if div_F is None else div_F + t_
        rho_new = rho - dt * div_F

        # 2. momentum update with the SAME fluxes. Arithmetic face
        # densities: linear in the cells, so mean(rho_new) equals the
        # face continuity update with the momentum CV's interpolated
        # fluxes — uniform translation of a jump stays exact, and the
        # arithmetic-rule projection keeps total momentum telescoping.
        if self.convective_op_type == "none":
            adv = tuple(jnp.zeros(g.n, dtype=u[0].dtype)
                        for _ in range(g.dim))
            rho_new = rho          # no transport in the Stokes limit
        else:
            adv = self._momentum_advection(u, F)
        visc = self._viscous_force(u, mu_cc)
        body = self._interface_forces(phi, rho)
        gp = stencils.gradient(p, dx)
        u_star = []
        for d in range(g.dim):
            m = _cc_to_face(rho, d) * u[d]
            rhs = -adv[d] + visc[d] + body[d] - gp[d]
            if f is not None:
                rhs = rhs + f[d]
            u_star.append(self._pin_normal(
                (m + dt * rhs) / _cc_to_face(rho_new, d), d))

        # 3. variable-density pressure-increment projection with the
        # MATCHING arithmetic face coefficient
        u_new, dp = self.project_vc(tuple(u_star), rho_new, dt,
                                    face_rule="arithmetic")
        p_new = p + dp

        # 4. interface transport + optional density re-slaving
        phi_new = self._transport_level_set(phi, u_new, dt, state.k)
        if self.rho_resync_interval:
            rho_new = jax.lax.cond(
                jnp.mod(state.k + 1, self.rho_resync_interval) == 0,
                lambda _: self.density(phi_new),
                lambda r: r, rho_new)

        return VCConsState(u=u_new, p=p_new, phi=phi_new, rho=rho_new,
                           t=state.t + dt, k=state.k + 1)

    # -- diagnostics ----------------------------------------------------
    def total_mass(self, state: VCConsState) -> jnp.ndarray:
        return jnp.sum(state.rho) * self.grid.cell_volume

    def total_momentum(self, state: VCConsState) -> Vel:
        """Arithmetic-face momentum density — the conserved quantity of
        this discretization (matches the step's face rule)."""
        return tuple(
            jnp.sum(_cc_to_face(state.rho, d) * state.u[d])
            * self.grid.cell_volume
            for d in range(self.grid.dim))


# one generic scan advance serves both VC forms (step resolves
# dynamically); the alias keeps the conservative API explicit
def advance_vc_conservative(integ, state, dt: float, num_steps: int):
    return advance_vc(integ, state, dt, num_steps)


def advance_vc(integ: INSVCStaggeredIntegrator, state: VCINSState,
               dt: float, num_steps: int) -> VCINSState:
    def body(s, _):
        return integ.step(s, dt), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return out
