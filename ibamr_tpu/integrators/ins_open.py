"""Navier–Stokes integrator for inflow/outflow (open-boundary) domains.

Reference parity: the ``INSStaggeredHierarchyIntegrator`` configuration
that every non-periodic, non-enclosed acceptance scenario uses — channel
and jet flows with prescribed-velocity inflows and traction-free open
outflows (P2/P3 + ``INSProjectionBcCoef``/``INSIntermediateVelocityBcCoef``
boundary plumbing, SURVEY.md §2.2). The enclosed/no-slip configurations
are served by :mod:`ibamr_tpu.integrators.ins_walls`; the periodic ones
by :mod:`ibamr_tpu.integrators.ins`. This module completes the boundary
menu with the open/traction case, driven by the coupled saddle solver of
:mod:`ibamr_tpu.solvers.stokes`.

Scheme: explicit first-order-upwind convection + backward-Euler viscous
step, coupled velocity–pressure solve each step (the reference's
"stokes solve per timestep" path, not the split projection):

    (1/dt) u^{n+1} - mu lap u^{n+1} + grad p = (1/dt) u^n - N(u^n) + f
    div u^{n+1} = 0

Everything is jit-traceable; the FGMRES saddle solve compiles into the
step function.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.bc import pad_boundary_data
from ibamr_tpu.solvers.stokes import StaggeredStokesSolver, StokesBC

Array = jnp.ndarray
Vel = Tuple[Array, ...]


class OpenINSState(NamedTuple):
    u: Vel
    p: Array
    t: Array


class INSOpenIntegrator:
    """Incompressible NS on a box domain with inflow/wall/open sides.

    ``bdry`` is the boundary-data dict of
    :meth:`StaggeredStokesSolver.make_rhs` — {(d, e, side): value}
    (inflow profiles, moving-wall tangential values), fixed at
    construction so the compiled step is data-free.
    """

    def __init__(self, n, dx, bc: StokesBC, mu: float, dt: float,
                 bdry: Optional[Dict] = None, rho: float = 1.0,
                 tol: float = 1e-8, dtype=jnp.float64):
        self.mu = float(mu)
        self.rho = float(rho)
        self.dt = float(dt)
        self.alpha = self.rho / self.dt
        self.solver = StaggeredStokesSolver(
            n, dx, bc, alpha=self.alpha, mu=self.mu, tol=tol,
            dtype=dtype)
        self.bdry = dict(bdry or {})
        self.n = self.solver.n
        self.dx = self.solver.dx

    # ------------------------------------------------------------------
    def initialize(self, u: Optional[Vel] = None) -> OpenINSState:
        s = self.solver
        if u is None:
            u = tuple(jnp.zeros(sh, dtype=s.dtype) for sh in s.shapes)
        p = jnp.zeros(s.n, dtype=s.dtype)
        return OpenINSState(u=tuple(u), p=p,
                            t=jnp.asarray(0.0, dtype=s.dtype))

    # -- advection helpers ---------------------------------------------
    def _ghost_with_data(self, c: Array, d: int) -> Array:
        """One ghost layer per axis honoring the ACTUAL boundary data
        (unlike the solver's homogeneous pad): prescribed tangential
        sides reflect around the data value; open sides copy; periodic
        wraps; own-axis boundary faces already carry their data (the
        saddle solve's identity rows keep them exact)."""
        s = self.solver
        out = c
        for e in range(c.ndim):
            lo_idx = [slice(None)] * out.ndim
            hi_idx = [slice(None)] * out.ndim
            if s.bc.periodic(e):
                lo_idx[e] = slice(-1, None)
                hi_idx[e] = slice(0, 1)
                lo_g, hi_g = out[tuple(lo_idx)], out[tuple(hi_idx)]
            else:
                lo_idx[e] = slice(0, 1)
                hi_idx[e] = slice(-1, None)
                lo_g, hi_g = out[tuple(lo_idx)], out[tuple(hi_idx)]
                if e != d:
                    if s.bc.side(e, 0).prescribed:
                        v = pad_boundary_data(jnp.asarray(
                            self.bdry.get((d, e, 0), 0.0), c.dtype),
                            out, e)
                        lo_g = 2.0 * v - lo_g
                    if s.bc.side(e, 1).prescribed:
                        v = pad_boundary_data(jnp.asarray(
                            self.bdry.get((d, e, 1), 0.0), c.dtype),
                            out, e)
                        hi_g = 2.0 * v - hi_g
            out = jnp.concatenate([lo_g, out, hi_g], axis=e)
        return out

    def _to_cells(self, u: Vel) -> Vel:
        """Average every MAC component to cell centers (shape n)."""
        s = self.solver
        out = []
        for e, c in enumerate(u):
            if s.bc.periodic(e):
                out.append(0.5 * (c + jnp.roll(c, -1, axis=e)))
            else:
                lo = [slice(None)] * c.ndim
                hi = [slice(None)] * c.ndim
                lo[e] = slice(0, -1)
                hi[e] = slice(1, None)
                out.append(0.5 * (c[tuple(lo)] + c[tuple(hi)]))
        return tuple(out)

    def _advect(self, u: Vel) -> Vel:
        """First-order upwind N(u)_d = sum_e a_e * d(u_d)/dx_e with
        BC-data ghosts; advecting velocities interpolated through cell
        centers (compact, layout-uniform)."""
        s = self.solver
        uc = self._to_cells(u)                   # all at cells, shape n
        out = []
        for d, c in enumerate(u):
            G = self._ghost_with_data(c, d)
            center = tuple(slice(1, -1) for _ in range(c.ndim))
            N = jnp.zeros_like(c)
            for e in range(c.ndim):
                lo = list(center)
                hi = list(center)
                lo[e] = slice(0, -2)
                hi[e] = slice(2, None)
                dm = (c - G[tuple(lo)]) / s.dx[e]
                dp = (G[tuple(hi)] - c) / s.dx[e]
                a = self._advecting(uc, u, d, e)
                N = N + jnp.where(a > 0, a * dm, a * dp)
            out.append(N)
        return tuple(out)

    def _advecting(self, uc: Vel, u: Vel, d: int, e: int) -> Array:
        """Velocity component e evaluated at component d's faces."""
        s = self.solver
        if e == d:
            return u[d]
        ce = uc[e]                      # cell-centered, shape n
        if s.bc.periodic(d):
            return 0.5 * (ce + jnp.roll(ce, 1, axis=d))
        # interior faces: mean of adjacent cells; boundary faces: edge
        pad = [(0, 0)] * ce.ndim
        pad[d] = (1, 1)
        Gp = jnp.pad(ce, pad, mode="edge")
        lo = [slice(None)] * ce.ndim
        hi = [slice(None)] * ce.ndim
        lo[d] = slice(0, -1)
        hi[d] = slice(1, None)
        return 0.5 * (Gp[tuple(lo)] + Gp[tuple(hi)])

    # ------------------------------------------------------------------
    def step(self, state: OpenINSState,
             f: Optional[Vel] = None) -> OpenINSState:
        s = self.solver
        N = self._advect(state.u)
        f_u = []
        for d in range(len(s.n)):
            r = self.alpha * state.u[d] - self.rho * N[d]
            if f is not None:
                r = r + f[d]
            f_u.append(r)
        rhs = s.make_rhs(f_u=tuple(f_u), bdry=self.bdry)
        sol = s.solve(rhs, x0=(state.u, state.p))
        return OpenINSState(u=sol.u, p=sol.p, t=state.t + self.dt)

    def max_divergence(self, state: OpenINSState) -> Array:
        return jnp.max(jnp.abs(self.solver.divergence(state.u)))


def advance(integ: INSOpenIntegrator, state: OpenINSState,
            nsteps: int, f: Optional[Vel] = None) -> OpenINSState:
    """jit/scan-rolled advance of ``nsteps`` steps."""
    def body(st, _):
        return integ.step(st, f=f), None

    out, _ = jax.lax.scan(body, state, None, length=nsteps)
    return out
