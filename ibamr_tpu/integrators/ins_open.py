"""Navier–Stokes integrator for inflow/outflow (open-boundary) domains.

Reference parity: the ``INSStaggeredHierarchyIntegrator`` configuration
that every non-periodic, non-enclosed acceptance scenario uses — channel
and jet flows with prescribed-velocity inflows and traction-free open
outflows (P2/P3 + ``INSProjectionBcCoef``/``INSIntermediateVelocityBcCoef``
boundary plumbing, SURVEY.md §2.2). The enclosed/no-slip configurations
are served by :mod:`ibamr_tpu.integrators.ins_walls`; the periodic ones
by :mod:`ibamr_tpu.integrators.ins`. This module completes the boundary
menu with the open/traction case, driven by the coupled saddle solver of
:mod:`ibamr_tpu.solvers.stokes`.

Scheme: explicit first-order-upwind convection + backward-Euler viscous
step, coupled velocity–pressure solve each step (the reference's
"stokes solve per timestep" path, not the split projection):

    (1/dt) u^{n+1} - mu lap u^{n+1} + grad p = (1/dt) u^n - N(u^n) + f
    div u^{n+1} = 0

Everything is jit-traceable; the FGMRES saddle solve compiles into the
step function.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.bc import pad_boundary_data
from ibamr_tpu.solvers.stokes import StaggeredStokesSolver, StokesBC

Array = jnp.ndarray
Vel = Tuple[Array, ...]


class OpenINSState(NamedTuple):
    u: Vel
    p: Array
    t: Array


class INSOpenIntegrator:
    """Incompressible NS on a box domain with inflow/wall/open sides.

    ``bdry`` is the boundary-data dict of
    :meth:`StaggeredStokesSolver.make_rhs` — {(d, e, side): value}
    (inflow profiles, moving-wall tangential values), fixed at
    construction so the compiled step is data-free.
    """

    def __init__(self, n, dx, bc: StokesBC, mu: float, dt: float,
                 bdry: Optional[Dict] = None, rho: float = 1.0,
                 tol: float = 1e-8, dtype=jnp.float64,
                 convective_op_type: str = "upwind",
                 stab_band: int = 4):
        self.mu = float(mu)
        self.rho = float(rho)
        self.dt = float(dt)
        self.alpha = self.rho / self.dt
        convective_op_type = convective_op_type.lower()
        if convective_op_type not in ("upwind", "stabilized_ppm"):
            raise ValueError(
                f"unknown convective_op_type {convective_op_type!r}")
        self.convective_op_type = convective_op_type
        self.stab_band = int(stab_band)
        self.solver = StaggeredStokesSolver(
            n, dx, bc, alpha=self.alpha, mu=self.mu, tol=tol,
            dtype=dtype)
        self.bdry = dict(bdry or {})
        self.n = self.solver.n
        self.dx = self.solver.dx

    # ------------------------------------------------------------------
    def initialize(self, u: Optional[Vel] = None) -> OpenINSState:
        s = self.solver
        if u is None:
            u = tuple(jnp.zeros(sh, dtype=s.dtype) for sh in s.shapes)
        p = jnp.zeros(s.n, dtype=s.dtype)
        return OpenINSState(u=tuple(u), p=p,
                            t=jnp.asarray(0.0, dtype=s.dtype))

    # -- advection helpers ---------------------------------------------
    def _ghost_with_data(self, c: Array, d: int) -> Array:
        """One ghost layer on EVERY axis honoring the actual boundary
        data (unlike the solver's homogeneous pad) — sequential
        applications of :meth:`_ghost_axis`, the one reflection
        implementation both advection paths share."""
        out = c
        for e in range(c.ndim):
            out = self._ghost_axis(out, d, e, width=1)
        return out

    def _to_cells(self, u: Vel) -> Vel:
        """Average every MAC component to cell centers (shape n)."""
        s = self.solver
        out = []
        for e, c in enumerate(u):
            if s.bc.periodic(e):
                out.append(0.5 * (c + jnp.roll(c, -1, axis=e)))
            else:
                lo = [slice(None)] * c.ndim
                hi = [slice(None)] * c.ndim
                lo[e] = slice(0, -1)
                hi[e] = slice(1, None)
                out.append(0.5 * (c[tuple(lo)] + c[tuple(hi)]))
        return tuple(out)

    def _advect(self, u: Vel) -> Vel:
        """First-order upwind N(u)_d = sum_e a_e * d(u_d)/dx_e with
        BC-data ghosts; advecting velocities interpolated through cell
        centers (compact, layout-uniform)."""
        s = self.solver
        uc = self._to_cells(u)                   # all at cells, shape n
        out = []
        for d, c in enumerate(u):
            G = self._ghost_with_data(c, d)
            center = tuple(slice(1, -1) for _ in range(c.ndim))
            N = jnp.zeros_like(c)
            for e in range(c.ndim):
                lo = list(center)
                hi = list(center)
                lo[e] = slice(0, -2)
                hi[e] = slice(2, None)
                dm = (c - G[tuple(lo)]) / s.dx[e]
                dp = (G[tuple(hi)] - c) / s.dx[e]
                a = self._advecting(uc, u, d, e)
                N = N + jnp.where(a > 0, a * dm, a * dp)
            out.append(N)
        return tuple(out)

    def _ghost_axis(self, c: Array, d: int, e: int, width: int) -> Array:
        """``width`` ghost layers along axis ``e`` only, honoring the
        boundary data (the wide-stencil fill the stabilized-PPM path
        needs; the one-layer all-axes fill above serves upwind)."""
        s = self.solver

        def take(lo, hi):
            sl = [slice(None)] * c.ndim
            sl[e] = slice(lo, hi)
            return c[tuple(sl)]

        n_e = c.shape[e]
        if s.bc.periodic(e):
            return jnp.concatenate(
                [take(n_e - width, n_e), c, take(0, width)], axis=e)
        if e != d:
            # cell-centered along e: odd reflection about prescribed
            # data, constant extrapolation past open sides
            lo_int = jnp.flip(take(0, width), axis=e)
            hi_int = jnp.flip(take(n_e - width, n_e), axis=e)
            if s.bc.side(e, 0).prescribed:
                v = pad_boundary_data(jnp.asarray(
                    self.bdry.get((d, e, 0), 0.0), c.dtype), c, e)
                lo_g = 2.0 * v - lo_int
            else:
                lo_g = jnp.repeat(take(0, 1), width, axis=e)
            if s.bc.side(e, 1).prescribed:
                v = pad_boundary_data(jnp.asarray(
                    self.bdry.get((d, e, 1), 0.0), c.dtype), c, e)
                hi_g = 2.0 * v - hi_int
            else:
                hi_g = jnp.repeat(take(n_e - 1, n_e), width, axis=e)
        else:
            # face-centered along its own axis: the boundary faces ARE
            # slots 0 / -1 (the saddle solve keeps them exact); odd
            # reflection through the boundary node for prescribed
            # sides, constant extrapolation for open ones
            if s.bc.side(e, 0).prescribed:
                lo_g = 2.0 * take(0, 1) - jnp.flip(take(1, width + 1),
                                                   axis=e)
            else:
                lo_g = jnp.repeat(take(0, 1), width, axis=e)
            if s.bc.side(e, 1).prescribed:
                hi_g = 2.0 * take(n_e - 1, n_e) - jnp.flip(
                    take(n_e - 1 - width, n_e - 1), axis=e)
            else:
                hi_g = jnp.repeat(take(n_e - 1, n_e), width, axis=e)
        return jnp.concatenate([lo_g, c, hi_g], axis=e)

    def _stab_mask(self, shape, e: int) -> Array:
        """Upwind-blend weight along flux axis ``e``: 1 at a
        non-periodic boundary, linear ramp to 0 over ``stab_band``
        cells (the reference's stabilized-PPM boundary band)."""
        s = self.solver
        n_e = shape[e]
        # the solver's working dtype, NOT a hard-coded f64: the ramp
        # values are exact in f32, and requesting f64 with x64 disabled
        # warns and silently truncates (graph-audit first-wave finding)
        idx = jnp.arange(n_e, dtype=s.dtype)
        chi = jnp.zeros((n_e,), dtype=s.dtype)
        band = float(max(self.stab_band, 1))
        if not s.bc.periodic(e):
            chi = jnp.maximum(chi, jnp.clip(1.0 - idx / band, 0.0, 1.0))
            chi = jnp.maximum(chi, jnp.clip(
                1.0 - (n_e - 1 - idx) / band, 0.0, 1.0))
        sh = [1] * len(shape)
        sh[e] = n_e
        return chi.reshape(sh)

    def _advect_stabilized(self, u: Vel) -> Vel:
        """PPM-reconstructed advective derivatives in the interior,
        blended to first-order upwind within ``stab_band`` cells of the
        physical boundaries — the
        ``INSStaggeredStabilizedPPMConvectiveOperator`` contract
        (SURVEY.md P4 [U]): high-order transport where the flow is
        smooth, damping at open/inflow boundaries where PPM's wide
        stencil would ring against the boundary model."""
        from ibamr_tpu.ops.convection import _face_value_padded, _sh

        s = self.solver
        g = 3
        uc = self._to_cells(u)
        out = []
        for d, c in enumerate(u):
            N = jnp.zeros_like(c)
            for e in range(c.ndim):
                a = self._advecting(uc, u, d, e)
                # midpoint advecting values between c's sample points
                # (wrap on periodic axes: an edge pad would pick the
                # wrong upwind donor at the seam)
                pad = [(0, 0)] * c.ndim
                pad[e] = (1, 1)
                ap = jnp.pad(a, pad,
                             mode="wrap" if s.bc.periodic(e) else "edge")
                lo_sl = [slice(None)] * c.ndim
                hi_sl = [slice(None)] * c.ndim
                lo_sl[e] = slice(0, -2)
                hi_sl[e] = slice(2, None)
                a_lo = 0.5 * (a + ap[tuple(lo_sl)])
                a_hi = 0.5 * (a + ap[tuple(hi_sl)])

                G = self._ghost_axis(c, d, e, width=g)
                n_e = c.shape[e]
                q_lo = _face_value_padded(G, a_lo, e, n_e, g, "ppm",
                                          shift=0)
                q_hi = _face_value_padded(G, a_hi, e, n_e, g, "ppm",
                                          shift=1)
                ppm_term = a * (q_hi - q_lo) / s.dx[e]

                c_m = _sh(G, e, -1, n_e, g)
                c_p = _sh(G, e, 1, n_e, g)
                up_term = jnp.where(
                    a > 0, a * (c - c_m) / s.dx[e],
                    a * (c_p - c) / s.dx[e])
                chi = self._stab_mask(c.shape, e).astype(c.dtype)
                N = N + chi * up_term + (1.0 - chi) * ppm_term
            out.append(N)
        return tuple(out)

    def _advecting(self, uc: Vel, u: Vel, d: int, e: int) -> Array:
        """Velocity component e evaluated at component d's faces."""
        s = self.solver
        if e == d:
            return u[d]
        ce = uc[e]                      # cell-centered, shape n
        if s.bc.periodic(d):
            return 0.5 * (ce + jnp.roll(ce, 1, axis=d))
        # interior faces: mean of adjacent cells; boundary faces: edge
        pad = [(0, 0)] * ce.ndim
        pad[d] = (1, 1)
        Gp = jnp.pad(ce, pad, mode="edge")
        lo = [slice(None)] * ce.ndim
        hi = [slice(None)] * ce.ndim
        lo[d] = slice(0, -1)
        hi[d] = slice(1, None)
        return 0.5 * (Gp[tuple(lo)] + Gp[tuple(hi)])

    # ------------------------------------------------------------------
    def step(self, state: OpenINSState, dt=None,
             f: Optional[Vel] = None) -> OpenINSState:
        """One step. ``dt`` may be omitted (construction dt — the
        original compiled-in behavior), a Python float, or a TRACED
        scalar: alpha = rho/dt is threaded through the saddle solve
        dynamically, so the CFL-adaptive ``hierarchy_driver`` loop
        drives this integrator without recompilation (VERDICT round 4
        item 6 — dt is no longer baked into the factorization)."""
        s = self.solver
        if dt is None:
            dt, alpha = self.dt, None
            a_expl = self.alpha
        else:
            alpha = self.rho / dt
            a_expl = alpha
        if self.convective_op_type == "stabilized_ppm":
            N = self._advect_stabilized(state.u)
        else:
            N = self._advect(state.u)
        f_u = []
        for d in range(len(s.n)):
            r = a_expl * state.u[d] - self.rho * N[d]
            if f is not None:
                r = r + f[d]
            f_u.append(r)
        rhs = s.make_rhs(f_u=tuple(f_u), bdry=self.bdry)
        sol = s.solve(rhs, x0=(state.u, state.p), alpha=alpha)
        return OpenINSState(u=sol.u, p=sol.p, t=state.t + dt)

    def cfl_dt(self, state: OpenINSState, cfl: float = 0.5) -> float:
        """Largest stable dt by the advective CFL condition (host-side
        global-min reduction, the hierarchy_driver contract)."""
        import math

        umax = max(float(jnp.max(jnp.abs(c))) for c in state.u)
        if umax == 0.0:
            return math.inf
        return cfl * min(self.dx) / umax

    def max_divergence(self, state: OpenINSState) -> Array:
        return jnp.max(jnp.abs(self.solver.divergence(state.u)))


def advance(integ: INSOpenIntegrator, state: OpenINSState,
            nsteps: int, f: Optional[Vel] = None) -> OpenINSState:
    """jit/scan-rolled advance of ``nsteps`` steps."""
    def body(st, _):
        return integ.step(st, f=f), None

    out, _ = jax.lax.scan(body, state, None, length=nsteps)
    return out
