"""Wall-bounded (no-slip) operators for the staggered INS integrator.

Reference parity: the non-periodic half of the staggered Stokes machinery
(P3: StaggeredStokesPhysicalBoundaryHelper, INSProjectionBcCoef,
INSIntermediateVelocityBcCoef; T8's non-periodic solvers; T9 wall fills —
SURVEY.md §2.1/§2.2) for homogeneous no-slip walls, collapsed onto the
fast-diagonalization solver (solvers.fastdiag).

Storage convention for a wall axis (see fastdiag "fc_pinned"): every MAC
component keeps shape ``n`` per axis; for the wall-NORMAL component the
slot at index 0 along that axis is the lo wall face, pinned to 0, and
the hi wall face is the periodic-wrap image of slot 0 — so for
HOMOGENEOUS no-slip both wall faces carry 0 and the periodic roll
stencils for divergence and the normal-axis Laplacian remain EXACT; only
tangential components need explicit odd-reflection ghosts, and the
pressure gradient is masked at pinned faces.

Projection note: with u.n = 0 enforced at walls the pressure Poisson
problem gets homogeneous Neumann BCs; the masked discrete gradient
composed with the roll divergence reproduces the Neumann matrix rows
exactly, so the projection is discretely exact (div u = 0 to roundoff).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

from ibamr_tpu.bc import AxisBC, DomainBC, SideBC, dirichlet_axis, neumann_axis
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils
from ibamr_tpu.solvers.fastdiag import FastDiagSolver

Vel = Tuple[jnp.ndarray, ...]


def _axis_bc(wall: bool, kind_builder) -> AxisBC:
    return kind_builder() if wall else AxisBC()


def pin_normal(c: jnp.ndarray, d: int, wall_axes) -> jnp.ndarray:
    """Zero the pinned wall-face slot of MAC component d (the storage
    convention of this module: slot 0 along a wall axis is the lo wall
    face; the hi wall face is its periodic-wrap image). Shared by every
    wall-bounded integrator so the convention is single-sourced."""
    if not wall_axes[d]:
        return c
    idx = [slice(None)] * c.ndim
    idx[d] = slice(0, 1)
    return c.at[tuple(idx)].set(0.0)


class WallOps:
    """Per-grid wall-aware operators + solvers, built once per config.

    ``tangential[(d, e, side)]`` prescribes component d's tangential
    velocity on the side(0=lo,1=hi) wall of axis e != d (a moving lid,
    e.g. the driven cavity). Inhomogeneous values enter the explicit
    Laplacian through the Dirichlet ghost fill and the implicit
    Helmholtz solve through RHS lifting (the ghost correction is a
    state-independent constant, so the homogeneous fast-diagonalization
    solver stays exact)."""

    def __init__(self, grid: StaggeredGrid, wall_axes: Sequence[bool],
                 tangential=None):
        self.grid = grid
        self.wall_axes = tuple(bool(w) for w in wall_axes)
        self.tangential = dict(tangential or {})
        dim = grid.dim

        # velocity Helmholtz solvers: component d -> per-axis centering
        self.vel_solvers = []
        for d in range(dim):
            axes, cents = [], []
            for e in range(dim):
                if not self.wall_axes[e]:
                    axes.append(AxisBC())
                    cents.append("cc")
                elif e == d:
                    axes.append(dirichlet_axis())
                    cents.append("fc_pinned")
                else:
                    axes.append(dirichlet_axis())
                    cents.append("cc")
            self.vel_solvers.append(
                FastDiagSolver(grid, DomainBC(axes=tuple(axes)),
                               tuple(cents)))

        # pressure Poisson: cc, Neumann at walls
        p_axes = tuple(_axis_bc(w, neumann_axis) for w in self.wall_axes)
        self.p_solver = FastDiagSolver(grid, DomainBC(axes=p_axes),
                                       ("cc",) * dim)

        # ghost-fill BC descriptors for the explicit stencils (shared
        # with bc.laplacian_cc so the ghost arithmetic lives in ONE
        # place). Component d treats its own wall axis as periodic: the
        # pinned-face storage wraps exactly for homogeneous walls.
        self._p_lap_bc = DomainBC(axes=p_axes)
        self._vel_lap_bc = [
            DomainBC(axes=tuple(
                dirichlet_axis(self.tangential.get((d, e, 0), 0.0),
                               self.tangential.get((d, e, 1), 0.0))
                if (self.wall_axes[e] and e != d)
                else AxisBC()
                for e in range(dim)))
            for d in range(dim)]

        # RHS lifting for the implicit solve: L_inhom u = L_hom u + lift,
        # lift = 2*V/dx_e^2 in the cell rows adjacent to a moving wall
        self._lift = []
        for d in range(dim):
            lift = None
            for e in range(dim):
                if not self.wall_axes[e] or e == d:
                    continue
                for side in (0, 1):
                    v = self.tangential.get((d, e, side), 0.0)
                    if v == 0.0:
                        continue
                    if lift is None:
                        lift = jnp.zeros(grid.n)
                    idx = [slice(None)] * dim
                    idx[e] = slice(0, 1) if side == 0 else slice(-1, None)
                    lift = lift.at[tuple(idx)].add(
                        2.0 * v / grid.dx[e] ** 2)
            self._lift.append(lift)

    # -- masks ---------------------------------------------------------------
    def _pin_normal(self, c: jnp.ndarray, d: int) -> jnp.ndarray:
        """Zero the pinned wall-face slot of component d (wall axes only)."""
        return pin_normal(c, d, self.wall_axes)

    # -- operators -----------------------------------------------------------
    def laplacian_vel(self, u: Sequence[jnp.ndarray],
                      dx: Sequence[float]) -> Vel:
        """Component Laplacians with homogeneous no-slip ghosts.

        Per component d, axis e:
        - e periodic, or e == d on a wall axis (pinned storage): the
          periodic wrap is exact (wall nodes carry 0).
        - e != d on a wall axis: tangential no-slip -> homogeneous
          Dirichlet ghosts (odd reflection).
        Ghost arithmetic delegates to bc.laplacian_cc.
        """
        from ibamr_tpu import bc as bc_mod

        return tuple(
            self._pin_normal(bc_mod.laplacian_cc(c, self._vel_lap_bc[d], dx),
                             d)
            for d, c in enumerate(u))

    def pressure_gradient(self, p: jnp.ndarray,
                          dx: Sequence[float]) -> Vel:
        """grad p at faces; zero at pinned wall faces (no normal update —
        the discrete homogeneous-Neumann condition)."""
        g = stencils.gradient(p, dx)
        return tuple(self._pin_normal(c, d) for d, c in enumerate(g))

    def laplacian_cc(self, f: jnp.ndarray, dx: Sequence[float]) -> jnp.ndarray:
        """Cell-centered Laplacian with homogeneous-Neumann wall ghosts
        (for the pressure-increment update); delegates to bc.laplacian_cc."""
        from ibamr_tpu import bc as bc_mod

        return bc_mod.laplacian_cc(f, self._p_lap_bc, dx)

    # -- solver seams (signatures match the periodic fft module) -------------
    def helmholtz_vel(self, rhs: Vel, dx, alpha, beta) -> Vel:
        out = []
        for d, c in enumerate(rhs):
            if self._lift[d] is not None:
                # (alpha + beta L_inhom) u = rhs
                #   <=> (alpha + beta L_hom) u = rhs - beta*lift
                c = c - beta * self._lift[d].astype(c.dtype)
            out.append(self.vel_solvers[d].solve(c, alpha, beta))
        return tuple(out)

    def project(self, u: Vel, dx, q=None) -> Tuple[Vel, jnp.ndarray]:
        """Leray projection with wall BCs: div uses the roll stencil
        (exact — wall faces carry 0), phi solves the Neumann Poisson
        problem, and the correction is masked at pinned faces. ``q`` is
        an optional cell-centered divergence source (P14); the Neumann
        solve's nullspace projection handles any net component."""
        div = stencils.divergence(u, dx)
        if q is not None:
            div = div - q
        phi = self.p_solver.solve(div, 0.0, 1.0, zero_nullspace=True)
        g = self.pressure_gradient(phi, dx)
        u_new = tuple(self._pin_normal(c - gc, d)
                      for d, (c, gc) in enumerate(zip(u, g)))
        return u_new, phi
