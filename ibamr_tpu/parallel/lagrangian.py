"""Lagrangian co-partitioning: shard-owned markers + ppermute halos (S2).

Reference parity: ``LDataManager``'s marker-to-rank co-partitioning
(T1/S2, SURVEY.md §2.3) — each MPI rank owns the markers inside its
patches, PETSc VecScatter builds ghost halos, redistribution follows
regrid. Round 1 replicated markers on every device and let GSPMD
scatter into the sharded grid (flagged by VERDICT round 1 item 3: the
transfers materialize all-gathers and per-device work scales with the
GLOBAL marker count).

TPU-first redesign (the "sort + ppermute" plan of SURVEY.md §2.4
"irregular scatter"):

1. **Owner bucketing (the redistribution step).** Markers are bucketed
   by the mesh block owning their cell — one argsort + scatter of N
   rows (replicated arithmetic, cheap) producing fixed-capacity
   per-shard pools ``(P * cap, ...)`` that are then sharded over the
   mesh, so each device holds exactly its own markers. Re-bucketing
   every call IS the migration strategy ("periodic global re-sort",
   SURVEY.md §2.3 S2) — no incremental ghost bookkeeping to invalidate.
2. **Local transfer + halo exchange.** Inside ``shard_map`` each device
   spreads its ``cap`` markers into its local grid block extended by a
   halo ring of width ``s//2 + 1`` (the delta support radius), then the
   halo slabs are ``lax.ppermute``d to the ring neighbors and
   accumulated — the RefineSchedule ghost-accumulate of SURVEY.md §3.2
   as one explicit ICI neighbor push. Interpolation mirrors it: ghost
   fill by ppermute, then a purely local gather (exact adjoint).
3. **Overflow (fixed-capacity safety).** Markers beyond a shard's
   capacity fall back to the round-1 replicated scatter path through a
   COMPACT index buffer under ``lax.cond`` (same design as
   ops.interaction_fast), so clustering degrades performance, never
   correctness.

Per-device spread/interp work scales with ``cap`` (~N/P * slack), not
N — the S2 scaling contract.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                        # jax >= 0.6 promotes shard_map to the top level
    from jax import shard_map
except ImportError:         # 0.4/0.5: experimental namespace only
    from jax.experimental.shard_map import shard_map

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.delta import Kernel, get_kernel
from ibamr_tpu.ops.interaction import _centering_offsets

Vel = Tuple[jnp.ndarray, ...]


class ShardBuckets(NamedTuple):
    """Owner-bucketed marker layout (all shapes static)."""
    Xb: jnp.ndarray          # (P*cap, dim) positions, sharded rows
    wb: jnp.ndarray          # (P*cap,) weights (0 in pad slots)
    slot_of_marker: jnp.ndarray   # (N,) slot or P*cap (overflowed)
    w_all: jnp.ndarray       # (N,) the caller's weights, global order
    o_idx: jnp.ndarray       # (ocap,) original indices of overflow markers
    o_w: jnp.ndarray         # (ocap,) their weights (0 in pad slots)
    any_overflow: jnp.ndarray     # () bool
    exceeded: jnp.ndarray    # () bool: overflow buffer itself overflowed


class ShardedInteraction:
    """Shard-owned spread/interp engine bound to one (grid, mesh) pair.

    The leading ``len(mesh.axis_names)`` grid axes are sharded by the
    mesh (the same convention as parallel.mesh.grid_pspec). ``cap`` is
    the per-shard marker capacity (static); default ``slack`` x the
    balanced share, rounded up to a multiple of 8.
    """

    def __init__(self, grid: StaggeredGrid, mesh: Mesh,
                 kernel: Kernel = "IB_4", n_markers: Optional[int] = None,
                 cap: Optional[int] = None, slack: float = 2.0,
                 overflow_cap: Optional[int] = None):
        self.grid = grid
        self.mesh = mesh
        self.kernel: Kernel = kernel
        self.axes = tuple(mesh.axis_names)
        self.sizes = tuple(mesh.shape[a] for a in self.axes)
        self.n_sharded = len(self.axes)
        if self.n_sharded > grid.dim:
            raise ValueError("mesh has more axes than the grid")
        self.nloc = []
        for d, p in enumerate(self.sizes):
            if grid.n[d] % p != 0:
                raise ValueError(
                    f"grid axis {d} ({grid.n[d]}) not divisible by mesh "
                    f"axis {self.axes[d]!r} ({p})")
            self.nloc.append(grid.n[d] // p)
        support, _ = get_kernel(kernel)
        self.support = support
        # halo radius: stencil of a cell-owned marker spans at most
        # [c - s//2, c + s//2] across all MAC centerings
        self.w = support // 2 + 1
        for d in range(self.n_sharded):
            if self.nloc[d] < self.w:
                raise ValueError(
                    f"local block ({self.nloc[d]} cells on axis {d}) "
                    f"thinner than the halo ({self.w}); use fewer devices "
                    f"or a bigger grid")
        self.P = int(np.prod(self.sizes))
        if cap is None:
            if n_markers is None:
                raise ValueError("need n_markers or an explicit cap")
            cap = int(math.ceil(n_markers * slack / self.P / 8.0) * 8)
        self.cap = int(cap)
        self.overflow_cap = overflow_cap
        # row sharding of the (P*cap, ...) pools: all mesh axes, in order
        row_axes = tuple(self.axes) if self.n_sharded > 1 else self.axes[0]
        self.row_spec = P(row_axes)                 # (P*cap,)
        self.row_spec2 = P(row_axes, None)          # (P*cap, dim)
        self.grid_spec = P(*self.axes,
                           *([None] * (grid.dim - self.n_sharded)))

    # -- bucketing (replicated arithmetic) -----------------------------------
    def buckets(self, X: jnp.ndarray,
                weights: Optional[jnp.ndarray] = None) -> ShardBuckets:
        grid = self.grid
        N, dim = X.shape
        if weights is None:
            weights = jnp.ones((N,), dtype=X.dtype)
        ocap = self.overflow_cap
        if ocap is None:
            ocap = min(N, max(256, N // 8))

        # inactive (weight-0) markers spread nothing and interpolate to
        # zero, so they must NOT occupy shard capacity: send them to the
        # sentinel owner P (a parked fixed-capacity pool would otherwise
        # evict real markers and force the replicated fallback)
        active = weights != 0
        owner = jnp.zeros((N,), dtype=jnp.int32)
        for d in range(self.n_sharded):
            c = jnp.floor(
                (X[:, d] - grid.x_lo[d]) / grid.dx[d]).astype(jnp.int32)
            c = jnp.mod(c, grid.n[d])
            owner = owner * self.sizes[d] + c // self.nloc[d]
        owner = jnp.where(active, owner, self.P)

        cap = self.cap
        Pn = self.P
        order = jnp.argsort(owner)
        owner_s = owner[order]
        start = jnp.searchsorted(owner_s,
                                 jnp.arange(Pn, dtype=owner_s.dtype))
        rank = (jnp.arange(N, dtype=jnp.int32)
                - start[jnp.minimum(owner_s, Pn - 1)].astype(jnp.int32))
        keep = jnp.logical_and(owner_s < Pn, rank < cap)
        slot_sorted = jnp.where(keep, owner_s * cap + rank, Pn * cap)

        Xb = jnp.zeros((Pn * cap + 1, dim), dtype=X.dtype)
        Xb = Xb.at[slot_sorted].set(X[order])[:-1]
        wb = jnp.zeros((Pn * cap + 1,), dtype=weights.dtype)
        wb = wb.at[slot_sorted].set(
            jnp.where(keep, weights[order], 0.0))[:-1]

        slot_of_marker = jnp.zeros((N,), dtype=jnp.int32)
        slot_of_marker = slot_of_marker.at[order].set(
            slot_sorted.astype(jnp.int32))

        # compact fallback buffer: only ACTIVE unselected markers need
        # it (inactive ones must not crowd out real overflow)
        need = jnp.logical_and(jnp.logical_not(keep), active[order])
        ord2 = jnp.argsort(jnp.logical_not(need))   # stable: needy first
        o_pos = ord2[:ocap]
        o_idx = order[o_pos].astype(jnp.int32)
        o_w = jnp.where(need[o_pos], weights[order[o_pos]], 0.0)
        n_over = jnp.sum(need)

        Xb = lax.with_sharding_constraint(
            Xb, NamedSharding(self.mesh, self.row_spec2))
        wb = lax.with_sharding_constraint(
            wb, NamedSharding(self.mesh, self.row_spec))
        return ShardBuckets(Xb=Xb, wb=wb, slot_of_marker=slot_of_marker,
                            w_all=weights, o_idx=o_idx, o_w=o_w,
                            any_overflow=n_over > 0,
                            exceeded=n_over > ocap)

    # -- local stencil helpers ----------------------------------------------
    def _local_stencil(self, Xl, starts, centering):
        """Per-device flattened stencil indices into the halo-extended
        local buffer + tensor-product weights. Xl: (cap, dim)."""
        grid = self.grid
        support, phi = get_kernel(self.kernel)
        offs = _centering_offsets(grid, centering)
        dim = grid.dim
        w = self.w
        C = Xl.shape[0]
        ext_shape = tuple(
            (self.nloc[d] + 2 * w) if d < self.n_sharded else grid.n[d]
            for d in range(dim))

        idxs, wgts = [], []
        for d in range(dim):
            xi = (Xl[:, d] - grid.x_lo[d]) / grid.dx[d]
            if d < self.n_sharded:
                # wrap into [0, n) by the marker's CELL (keeps the
                # stencil contiguous around the owned cell)
                shift = jnp.mod(jnp.floor(xi), grid.n[d]) - jnp.floor(xi)
                xi = xi + shift
            j, wg = interaction._axis_weights_indices_raw(
                xi - offs[d], support, phi)
            if d < self.n_sharded:
                j = j - starts[d] + w          # local, NO wrap
            else:
                j = jnp.mod(j, grid.n[d])
            idxs.append(j)
            wgts.append(wg)

        lin = idxs[0]
        wgt = wgts[0]
        for d in range(1, dim):
            lin = lin[..., :, None] * ext_shape[d] + idxs[d].reshape(
                (C,) + (1,) * (lin.ndim - 1) + (support,))
            wgt = wgt[..., :, None] * wgts[d].reshape(
                (C,) + (1,) * (wgt.ndim - 1) + (support,))
        return lin.reshape(C, -1), wgt.reshape(C, -1), ext_shape

    def _starts(self):
        return [lax.axis_index(self.axes[d]) * self.nloc[d]
                for d in range(self.n_sharded)]

    def _take(self, a, d, lo, hi):
        idx = [slice(None)] * a.ndim
        idx[d] = slice(lo, hi)
        return a[tuple(idx)]

    def _halo_issue(self, buf, d):
        """Issue the two halo-accumulate ppermutes along local axis d;
        returns the in-flight slabs for :meth:`_halo_retire`. Split
        from the retire half so the fused multi-component kernels can
        interleave another component's purely-local scatter between
        issue and consumption (structural overlap, PR 16)."""
        ax = self.axes[d]
        Pd = self.sizes[d]
        w, nl = self.w, self.nloc[d]
        lo_slab = self._take(buf, d, 0, w)
        hi_slab = self._take(buf, d, nl + w, nl + 2 * w)
        # lo ghost belongs to the PREVIOUS block; receive the next
        # block's lo slab into our interior tail (and mirrored for hi)
        fwd = [(i, (i - 1) % Pd) for i in range(Pd)]
        bwd = [(i, (i + 1) % Pd) for i in range(Pd)]
        # `comm` scope: device profiles classify the halo pushes into
        # the comm_s op-class (obs/deviceprof) instead of anonymous ops
        with jax.named_scope("comm"):
            from_next = lax.ppermute(lo_slab, ax, perm=fwd)
            from_prev = lax.ppermute(hi_slab, ax, perm=bwd)
        return from_next, from_prev

    def _halo_retire(self, buf, d, slabs):
        """Accumulate the in-flight axis-d slabs; returns the axis-d
        interior."""
        from_next, from_prev = slabs
        w, nl = self.w, self.nloc[d]
        interior = self._take(buf, d, w, w + nl)
        idx_hi = [slice(None)] * buf.ndim
        idx_hi[d] = slice(nl - w, nl)
        idx_lo = [slice(None)] * buf.ndim
        idx_lo[d] = slice(0, w)
        interior = interior.at[tuple(idx_hi)].add(from_next)
        interior = interior.at[tuple(idx_lo)].add(from_prev)
        return interior

    def _halo_add(self, buf, d):
        """Push this device's halo slabs along local axis d to the ring
        neighbors and accumulate; returns the axis-d interior."""
        return self._halo_retire(buf, d, self._halo_issue(buf, d))

    def _ghost_issue(self, f, d):
        """Issue the two ghost-fill ppermutes along local axis d;
        returns the in-flight ghost slabs for :meth:`_ghost_retire`."""
        ax = self.axes[d]
        Pd = self.sizes[d]
        w, nl = self.w, self.nloc[d]
        fwd = [(i, (i + 1) % Pd) for i in range(Pd)]
        bwd = [(i, (i - 1) % Pd) for i in range(Pd)]
        with jax.named_scope("comm"):
            lo_ghost = lax.ppermute(self._take(f, d, nl - w, nl), ax,
                                    perm=fwd)
            hi_ghost = lax.ppermute(self._take(f, d, 0, w), ax,
                                    perm=bwd)
        return lo_ghost, hi_ghost

    def _ghost_retire(self, f, d, slabs):
        """Concatenate the in-flight ghost slabs onto local field f."""
        return jnp.concatenate([slabs[0], f, slabs[1]], axis=d)

    def _ghost_fill(self, f, d):
        """Extend local field f with w ghost layers along axis d from
        the ring neighbors."""
        return self._ghost_retire(f, d, self._ghost_issue(f, d))

    # -- public ops ----------------------------------------------------------
    def spread(self, F: jnp.ndarray, X: jnp.ndarray, centering,
               b: ShardBuckets) -> jnp.ndarray:
        """Spread marker values F (N,) -> sharded grid field."""
        grid = self.grid
        inv_vol = 1.0 / math.prod(grid.dx)
        # bucket F with the same layout as Xb
        Fb = jnp.zeros((self.P * self.cap + 1,), dtype=F.dtype)
        Fb = Fb.at[b.slot_of_marker].add(F)[:-1]
        Fb = lax.with_sharding_constraint(
            Fb, NamedSharding(self.mesh, self.row_spec))

        def kernel(Xl, Fl, wl):
            starts = self._starts()
            lin, wgt, ext_shape = self._local_stencil(Xl, starts, centering)
            vals = (Fl * wl * inv_vol)[:, None] * wgt
            buf = jnp.zeros(ext_shape, dtype=vals.dtype)
            buf = buf.reshape(-1).at[lin.reshape(-1)].add(
                vals.reshape(-1)).reshape(ext_shape)
            for d in range(self.n_sharded):
                buf = self._halo_add(buf, d)
            return buf

        out = shard_map(
            kernel, mesh=self.mesh,
            in_specs=(self.row_spec2, self.row_spec, self.row_spec),
            out_specs=self.grid_spec)(b.Xb, Fb, b.wb)
        return self._spread_overflow(out, F, X, centering, b)

    def _spread_overflow(self, out, F, X, centering, b: ShardBuckets):
        """Gated overflow fallbacks on one spread component (shared by
        the per-component and fused paths — identical graphs)."""
        grid = self.grid

        def compact(o):
            return interaction.spread(F[b.o_idx], grid, X[b.o_idx],
                                      centering=centering,
                                      kernel=self.kernel,
                                      weights=b.o_w, out=o)

        def full(o):
            # overflow buffer exceeded: exact full fallback carrying the
            # CALLER's weights for every non-selected marker (masked
            # markers must stay masked here too)
            w_over = jnp.where(b.slot_of_marker < self.P * self.cap,
                               0.0, b.w_all)
            return interaction.spread(F, grid, X, centering=centering,
                                      kernel=self.kernel,
                                      weights=w_over, out=o)

        return lax.cond(
            b.exceeded, full,
            lambda o: lax.cond(b.any_overflow, compact,
                               lambda oo: oo, o), out)

    def interpolate(self, f: jnp.ndarray, X: jnp.ndarray, centering,
                    b: ShardBuckets) -> jnp.ndarray:
        """Interpolate a sharded grid field at the markers -> (N,)."""

        def kernel(fl, Xl, wl):
            for d in range(self.n_sharded):
                fl = self._ghost_fill(fl, d)
            starts = self._starts()
            lin, wgt, _ = self._local_stencil(Xl, starts, centering)
            vals = jnp.take(fl.reshape(-1), lin, axis=0)
            return jnp.sum(vals * wgt, axis=-1) * wl

        Ub = shard_map(
            kernel, mesh=self.mesh,
            in_specs=(self.grid_spec, self.row_spec2, self.row_spec),
            out_specs=self.row_spec)(f, b.Xb, b.wb)
        return self._interp_unbucket(Ub, f, X, centering, b)

    def _interp_unbucket(self, Ub, f, X, centering, b: ShardBuckets):
        """Slot gather back to global marker order + gated overflow
        fallbacks on one interpolated component (shared by the
        per-component and fused paths — identical graphs)."""
        grid = self.grid
        # map back to global marker order (slot gather; the sentinel
        # slot P*cap maps overflowed markers to 0)
        U = jnp.take(Ub, jnp.minimum(b.slot_of_marker, Ub.shape[0] - 1),
                     axis=0)
        U = jnp.where(b.slot_of_marker < Ub.shape[0], U, 0.0)

        def compact(u):
            Uo = interaction.interpolate(f, grid, X[b.o_idx],
                                         centering=centering,
                                         kernel=self.kernel, weights=b.o_w)
            return u.at[b.o_idx].add(Uo)

        def full(u):
            w_over = jnp.where(b.slot_of_marker < self.P * self.cap,
                               0.0, b.w_all)
            return u + interaction.interpolate(
                f, grid, X, centering=centering, kernel=self.kernel,
                weights=w_over)

        return lax.cond(
            b.exceeded, full,
            lambda u: lax.cond(b.any_overflow, compact,
                               lambda uu: uu, u), U)

    # drop-in FastInteraction-shaped surface (IBMethod engine seam).
    # The vector paths run ONE fused shard_map over all dim components
    # and software-pipeline the halo exchange ACROSS components: while
    # component c's ghost slabs ride the ring, component c+1's purely
    # local scatter/stencil/gather executes — every component's own
    # expression tree is untouched (axis order, accumulate order), so
    # the fused result is bitwise identical to the per-component loop
    # (pinned by tests/test_lagrangian_sharded.py).
    def interpolate_vel(self, u: Vel, X: jnp.ndarray,
                        weights: Optional[jnp.ndarray] = None,
                        b: Optional[ShardBuckets] = None) -> jnp.ndarray:
        if b is None:
            b = self.buckets(X, weights)
        C = self.grid.dim
        S = self.n_sharded

        def kernel(Xl, wl, *fls):
            starts = self._starts()
            exts = [None] * C
            stencils = [None] * C
            ready = []
            inflight = []            # [component, axis, field, slabs]

            def advance():
                nxt = []
                for c, d, f, slabs in inflight:
                    fe = self._ghost_retire(f, d, slabs)
                    if d + 1 < S:
                        nxt.append([c, d + 1, fe,
                                    self._ghost_issue(fe, d + 1)])
                    else:
                        exts[c] = fe
                        ready.append(c)
                inflight[:] = nxt

            def gather(c):
                lin, wgt = stencils[c]
                vals = jnp.take(exts[c].reshape(-1), lin, axis=0)
                return jnp.sum(vals * wgt, axis=-1) * wl

            Us = [None] * C
            for c in range(C):
                inflight.append([c, 0, fls[c],
                                 self._ghost_issue(fls[c], 0)])
                # the stencil build is pure marker arithmetic — the
                # compute that hides the ghost slabs just issued
                stencils[c] = self._local_stencil(Xl, starts, c)[:2]
                advance()
            while inflight:
                if ready:            # a gather hides the drain retires
                    c = ready.pop(0)
                    Us[c] = gather(c)
                advance()
            for c in ready:
                Us[c] = gather(c)
            return tuple(Us)

        Ubs = shard_map(
            kernel, mesh=self.mesh,
            in_specs=(self.row_spec2, self.row_spec)
            + (self.grid_spec,) * C,
            out_specs=(self.row_spec,) * C)(b.Xb, b.wb, *u)
        cols = [self._interp_unbucket(Ubs[c], u[c], X, c, b)
                for c in range(C)]
        return jnp.stack(cols, axis=-1)

    def spread_vel(self, F: jnp.ndarray, X: jnp.ndarray,
                   weights: Optional[jnp.ndarray] = None,
                   b: Optional[ShardBuckets] = None) -> Vel:
        if b is None:
            b = self.buckets(X, weights)
        grid = self.grid
        C = grid.dim
        S = self.n_sharded
        inv_vol = 1.0 / math.prod(grid.dx)
        Fbs = []
        for c in range(C):
            Fb = jnp.zeros((self.P * self.cap + 1,), dtype=F.dtype)
            Fb = Fb.at[b.slot_of_marker].add(F[:, c])[:-1]
            Fbs.append(lax.with_sharding_constraint(
                Fb, NamedSharding(self.mesh, self.row_spec)))

        def kernel(Xl, wl, *Fls):
            starts = self._starts()
            outs = [None] * C
            inflight = []            # [component, axis, buffer, slabs]

            def advance():
                nxt = []
                for c, d, buf, slabs in inflight:
                    interior = self._halo_retire(buf, d, slabs)
                    if d + 1 < S:
                        nxt.append([c, d + 1, interior,
                                    self._halo_issue(interior, d + 1)])
                    else:
                        outs[c] = interior
                inflight[:] = nxt

            for c in range(C):
                # the local scatter is the compute that hides the halo
                # slabs issued for the previous component(s)
                lin, wgt, ext_shape = self._local_stencil(Xl, starts, c)
                vals = (Fls[c] * wl * inv_vol)[:, None] * wgt
                buf = jnp.zeros(ext_shape, dtype=vals.dtype)
                buf = buf.reshape(-1).at[lin.reshape(-1)].add(
                    vals.reshape(-1)).reshape(ext_shape)
                advance()
                inflight.append([c, 0, buf, self._halo_issue(buf, 0)])
            while inflight:
                advance()
            return tuple(outs)

        outs = shard_map(
            kernel, mesh=self.mesh,
            in_specs=(self.row_spec2, self.row_spec)
            + (self.row_spec,) * C,
            out_specs=(self.grid_spec,) * C)(b.Xb, b.wb, *Fbs)
        return tuple(self._spread_overflow(outs[c], F[:, c], X, c, b)
                     for c in range(C))
