"""Pencil-decomposed distributed FFT Poisson/Helmholtz solvers.

Reference parity: the spectral replacement for hypre's distributed
multigrid bottom solves (T8) under domain decomposition — SURVEY.md §2.4
row "Reduction"/§5.7: the FFT's transposes are the framework's true
long-range communication, expressed as `lax.all_to_all` inside
`shard_map` so they ride ICI as explicit collectives.

Scheme (classic pencil transpose): FFT the locally-complete trailing
axes, then for each sharded axis all-to-all-transpose it against an
already-transformed axis and FFT it locally; apply the (sliced) discrete
Laplacian symbol; mirror the transposes back. Local FFTs act on
contiguous local blocks (which also sidesteps XLA CPU's layout-restricted
FFT thunk that breaks the naive GSPMD lowering of `rfftn` on a 2D-sharded
2D array).

Supported decompositions (grid axes are sharded left-to-right by mesh
axes): 2D or 3D grid x 1D mesh; 3D grid x 2D mesh (true pencils); 2D
grid x 2D mesh (both mesh axes flattened into one transpose group).

Double-buffered transposes (PR 16): with ``tiles > 1`` the 3-D kernels
split each pencil stage along a BYSTANDER axis (one the stage's
transpose and FFT never touch) and software-pipeline the tiles — tile
``t+1``'s ``all_to_all`` is issued before tile ``t``'s local FFT /
diagonal solve consumes its own, so every transpose but the pipeline
boundary has independent compute inside its issue window
(``analysis.graph_census.structural_overlap_census``) and a
latency-hiding scheduler can keep it in flight behind the k-space
algebra. Bitwise contract: tiling only ever slices a *batch* axis of a
batched 1-D FFT and the pointwise symbol, so every transform and every
symbol element sees exactly the arithmetic of the ``tiles=1`` chain —
pinned in f64 by tests/test_fftpar.py.
"""

from __future__ import annotations

import math
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:                        # jax >= 0.6 promotes shard_map to the top level
    from jax import shard_map
except ImportError:         # 0.4/0.5: experimental namespace only
    from jax.experimental.shard_map import shard_map

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.parallel.mesh import grid_pspec

Vel = Tuple[jnp.ndarray, ...]


def _axis_symbol(n: int, h: float, dtype) -> jnp.ndarray:
    """Eigenvalues of the 1D discrete periodic Laplacian, full-spectrum
    (fft, not rfft) ordering: (2 cos(2 pi k / n) - 2) / h^2."""
    k = jnp.fft.fftfreq(n)
    return ((2.0 * jnp.cos(2.0 * math.pi * k) - 2.0) / (h * h)).astype(dtype)


def _slice_for_shard(l: jnp.ndarray, idx, count: int) -> jnp.ndarray:
    size = l.shape[0] // count
    return lax.dynamic_slice(l, (idx * size,), (size,))


def _transpose(c: jnp.ndarray, axis_name, split_axis: int,
               concat_axis: int) -> jnp.ndarray:
    """One pencil transpose (tiled all_to_all) under the ``comm`` named
    scope, so device profiles attribute the exchange to the comm
    op-class (obs/deviceprof ``comm_s``) instead of anonymous lowered
    ops — the dynamic twin of the static ``collective_census`` pin."""
    with jax.named_scope("comm"):
        return lax.all_to_all(c, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


class PencilFFT:
    """Distributed spectral solver bound to one (grid, mesh) pair.

    ``op(sym, rhat, *scalars)`` runs pointwise in the spectral domain on
    each shard's pencil; scalars (e.g. Helmholtz alpha/beta) pass through
    shard_map as replicated operands so they may be traced values.
    """

    def __init__(self, grid: StaggeredGrid, mesh: Mesh, tiles: int = 2):
        self.grid = grid
        self.mesh = mesh
        if tiles < 1:
            raise ValueError(f"tiles must be >= 1, got {tiles}")
        self.tiles = tiles
        dim = grid.dim
        axes = tuple(mesh.axis_names)
        sizes = tuple(mesh.shape[a] for a in axes)
        if len(axes) > dim:
            raise ValueError("mesh has more axes than the grid")
        n = grid.n
        for d, (name, p) in enumerate(zip(axes, sizes)):
            if n[d] % p != 0:
                raise ValueError(
                    f"grid axis {d} ({n[d]}) not divisible by mesh axis "
                    f"{name!r} ({p})")
        if dim == 2 and len(axes) == 2:
            ptot = sizes[0] * sizes[1]
            if n[0] % ptot or n[1] % ptot:
                raise ValueError("2D grid on 2D mesh needs n % (Px*Py) == 0")
        elif dim == 3 and len(axes) == 2:
            if n[2] % sizes[1] or n[1] % sizes[0]:
                raise ValueError(
                    "3D pencil needs n[2] % Py == 0 and n[1] % Px == 0")
        elif len(axes) == 1 and dim >= 2:
            if n[1] % sizes[0]:
                raise ValueError("1D pencil needs n[1] % P == 0")
        self.axes = axes
        self.sizes = sizes
        self.spec = grid_pspec(mesh, dim)

    # -- spectral core -------------------------------------------------------
    def _make_kernel(self, op: Callable, rdt) -> Callable:
        """Build the per-shard kernel r_local, *scalars -> u_local."""
        dim = self.grid.dim
        axes, sizes = self.axes, self.sizes
        n, dx = self.grid.n, self.grid.dx
        cdt = jnp.complex128 if rdt == jnp.float64 else jnp.complex64
        lam = [_axis_symbol(n[d], dx[d], rdt) for d in range(dim)]

        if len(axes) == 1 and dim == 3:
            ax = axes[0]
            # bystander axis 2 is never touched by the ax transpose or
            # the axis-0/1 FFTs, so the whole solve pipelines along it
            tn = math.gcd(self.tiles, n[2])

            def kernel(r, *scalars):
                c = jnp.fft.fft(r.astype(cdt), axis=2)
                parts = (jnp.split(c, tn, axis=2) if tn > 1 else [c])
                pre = [jnp.fft.fft(parts[0], axis=1)]
                inb = [_transpose(pre[0], ax, 1, 0)]
                i = lax.axis_index(ax)
                # symbol built AFTER the first inbound issue: its adds
                # are the compute that hides tile 0's transpose
                sym = (lam[0][:, None, None]
                       + _slice_for_shard(lam[1], i, sizes[0])[None, :, None]
                       + lam[2][None, None, :])
                w = n[2] // tn
                outb = []
                for t in range(tn):
                    if t + 1 < tn:
                        pre.append(jnp.fft.fft(parts[t + 1], axis=1))
                        inb.append(_transpose(pre[t + 1], ax, 1, 0))
                    y = jnp.fft.fft(inb[t], axis=0)
                    y = op(sym[:, :, t * w:(t + 1) * w], y, *scalars)
                    y = jnp.fft.ifft(y, axis=0)
                    outb.append(_transpose(y, ax, 0, 1))
                res = [jnp.fft.ifft(o, axis=1) for o in outb]
                c = (jnp.concatenate(res, axis=2) if tn > 1 else res[0])
                c = jnp.fft.ifft(c, axis=2)
                return jnp.real(c).astype(rdt)

        elif len(axes) == 1:
            ax = axes[0]

            def kernel(r, *scalars):
                c = r.astype(cdt)
                for d in range(1, dim):
                    c = jnp.fft.fft(c, axis=d)
                c = _transpose(c, ax, 1, 0)
                c = jnp.fft.fft(c, axis=0)
                i = lax.axis_index(ax)
                parts = [lam[0].reshape((-1,) + (1,) * (dim - 1)),
                         _slice_for_shard(lam[1], i, sizes[0]).reshape(
                             (1, -1) + (1,) * (dim - 2))]
                for d in range(2, dim):
                    parts.append(lam[d].reshape(
                        (1,) * d + (-1,) + (1,) * (dim - 1 - d)))
                c = op(sum(parts), c, *scalars)
                c = jnp.fft.ifft(c, axis=0)
                c = _transpose(c, ax, 0, 1)
                for d in range(dim - 1, 0, -1):
                    c = jnp.fft.ifft(c, axis=d)
                return jnp.real(c).astype(rdt)

        elif dim == 3:
            ax, ay = axes
            # stage A/C (ay transposes) pipeline along bystander axis 0
            # (local extent n0/Px); stage B (ax transposes + diagonal
            # solve) along bystander axis 2 (local extent n2/Py)
            ta = math.gcd(self.tiles, n[0] // sizes[0])
            tb = math.gcd(self.tiles, n[2] // sizes[1])

            def kernel(r, *scalars):
                c = r.astype(cdt)
                # stage A: axis-2 FFT per tile, ay transpose prefetched
                # one tile ahead of the axis-1 FFT that consumes it
                parts = (jnp.split(c, ta, axis=0) if ta > 1 else [c])
                pre = [jnp.fft.fft(parts[0], axis=2)]
                moved = [_transpose(pre[0], ay, 2, 1)]
                outs = []
                for t in range(ta):
                    if t + 1 < ta:
                        pre.append(jnp.fft.fft(parts[t + 1], axis=2))
                        moved.append(_transpose(pre[t + 1], ay, 2, 1))
                    outs.append(jnp.fft.fft(moved[t], axis=1))
                c = (jnp.concatenate(outs, axis=0) if ta > 1 else outs[0])
                # stage B: inbound ax transpose for tile t+1 in flight
                # while tile t's axis-0 FFT + diagonal solve runs
                parts = (jnp.split(c, tb, axis=2) if tb > 1 else [c])
                inb = [_transpose(parts[0], ax, 1, 0)]
                ix, iy = lax.axis_index(ax), lax.axis_index(ay)
                # symbol built AFTER the first inbound issue: its adds
                # are the compute that hides tile 0's transpose
                sym = (lam[0][:, None, None]
                       + _slice_for_shard(lam[1], ix, sizes[0])[None, :, None]
                       + _slice_for_shard(lam[2], iy, sizes[1])[None, None, :])
                w = n[2] // sizes[1] // tb
                outb = []
                for t in range(tb):
                    if t + 1 < tb:
                        inb.append(_transpose(parts[t + 1], ax, 1, 0))
                    y = jnp.fft.fft(inb[t], axis=0)
                    y = op(sym[:, :, t * w:(t + 1) * w], y, *scalars)
                    y = jnp.fft.ifft(y, axis=0)
                    outb.append(_transpose(y, ax, 0, 1))
                res = [jnp.fft.ifft(o, axis=1) for o in outb]
                c = (jnp.concatenate(res, axis=2) if tb > 1 else res[0])
                # stage C: ay transpose back, axis-2 IFFT interleaved
                parts = (jnp.split(c, ta, axis=0) if ta > 1 else [c])
                back = [_transpose(parts[0], ay, 1, 2)]
                res2 = []
                for t in range(ta):
                    if t + 1 < ta:
                        back.append(_transpose(parts[t + 1], ay, 1, 2))
                    res2.append(jnp.fft.ifft(back[t], axis=2))
                c = (jnp.concatenate(res2, axis=0) if ta > 1 else res2[0])
                return jnp.real(c).astype(rdt)

        else:  # dim == 2, 2D mesh: flatten both mesh axes into one group
            ax, ay = axes
            ptot = sizes[0] * sizes[1]

            def kernel(r, *scalars):
                c = r.astype(cdt)
                # unshard axis 1 by splitting axis 0 further over ay
                c = _transpose(c, ay, 0, 1)
                c = jnp.fft.fft(c, axis=1)
                c = _transpose(c, (ax, ay), 1, 0)
                c = jnp.fft.fft(c, axis=0)
                i = lax.axis_index((ax, ay))
                sym = (lam[0][:, None]
                       + _slice_for_shard(lam[1], i, ptot)[None, :])
                c = op(sym, c, *scalars)
                c = jnp.fft.ifft(c, axis=0)
                c = _transpose(c, (ax, ay), 0, 1)
                c = jnp.fft.ifft(c, axis=1)
                c = _transpose(c, ay, 1, 0)
                return jnp.real(c).astype(rdt)

        return kernel

    def _spectral_apply(self, rhs: jnp.ndarray, op: Callable,
                        *scalars) -> jnp.ndarray:
        kernel = self._make_kernel(op, rhs.dtype)
        scalars = tuple(jnp.asarray(s, dtype=rhs.dtype) for s in scalars)
        fn = shard_map(
            kernel, mesh=self.mesh,
            in_specs=(self.spec,) + tuple(P() for _ in scalars),
            out_specs=self.spec)
        return fn(rhs, *scalars)

    # -- public solves -------------------------------------------------------
    def poisson(self, rhs: jnp.ndarray) -> jnp.ndarray:
        """Zero-mean solution of lap(p) = rhs (periodic)."""
        def op(sym, rhat):
            safe = jnp.where(sym == 0, 1.0, sym)
            return jnp.where(sym == 0, 0.0, rhat / safe)

        return self._spectral_apply(rhs, op)

    def helmholtz(self, rhs: jnp.ndarray, alpha, beta) -> jnp.ndarray:
        """Solve (alpha + beta lap) u = rhs; alpha/beta may be traced."""
        def op(sym, rhat, a, b):
            return rhat / (a + b * sym)

        return self._spectral_apply(rhs, op, alpha, beta)

    def helmholtz_cc(self, rhs: jnp.ndarray, dx, alpha, beta) -> jnp.ndarray:
        """Drop-in for solvers.fft.solve_helmholtz_periodic (dx carried by
        the bound grid; accepted for signature parity)."""
        return self.helmholtz(rhs, alpha, beta)

    def helmholtz_vel(self, rhs: Vel, dx, alpha, beta) -> Vel:
        """Drop-in for solvers.fft.solve_helmholtz_periodic_vel (dx is
        carried by the bound grid; accepted for signature parity)."""
        return tuple(self.helmholtz(c, alpha, beta) for c in rhs)

    def project_divergence_free(self, u: Vel, dx,
                                q=None) -> Tuple[Vel, jnp.ndarray]:
        """Drop-in for solvers.fft.project_divergence_free."""
        from ibamr_tpu.ops import stencils

        div = stencils.divergence(u, dx)
        if q is not None:
            div = div - q
        phi = self.poisson(div)
        g = stencils.gradient(phi, dx)
        return tuple(c - gc for c, gc in zip(u, g)), phi
