"""Device-mesh construction and GSPMD-sharded simulation steps.

Reference parity: SAMRAI `LoadBalancer` patch->rank assignment (S1,
SURVEY.md §2.3) — here the "patches" are equal blocks of each uniform
level, laid out over a 1D or 2D `jax.sharding.Mesh` so halo traffic rides
ICI neighbor links. Marker POSITIONS and force arithmetic stay
replicated (O(N) elementwise work, negligible next to the grid work),
but the spread/interp TRANSFERS — the actual hot path — run through the
S2 co-partitioned engine (parallel.lagrangian): owner-bucketed per-shard
marker pools, local scatter/gather, ppermute halo accumulation (the
VecScatter analog of §2.4 "irregular scatter").

The GSPMD contract: the step function is the SAME pure function as the
single-device path; only `with_sharding_constraint` pins where arrays
live. XLA then inserts `collective-permute` for the roll-stencil halos and
all-to-all/all-gather for the FFT transposes — the two communication
patterns SURVEY.md §5.7 identifies as nearest-neighbor halos + the FFT's
true long-range exchange.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ibamr_tpu.grid import StaggeredGrid


def factor_devices(n: int, max_axes: int = 2) -> Tuple[int, ...]:
    """Near-square factorization of the device count into mesh axes
    (the analog of choosing a process grid for domain decomposition)."""
    if max_axes == 1 or n == 1:
        return (n,)
    a = int(math.isqrt(n))
    while a > 1 and n % a != 0:
        a -= 1
    if a == 1:
        return (n,)
    return (n // a, a)


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None,
              axis_names: Tuple[str, ...] = ("x", "y"),
              max_axes: int = 2) -> Mesh:
    """Build a 1D/2D spatial mesh over the first ``n_devices`` devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    shape = factor_devices(len(devices), max_axes)
    import numpy as np
    dev_arr = np.array(devices).reshape(shape)
    return Mesh(dev_arr, axis_names[:len(shape)])


def grid_pspec(mesh: Mesh, grid_dim: int) -> P:
    """PartitionSpec sharding the leading grid axes over the mesh axes."""
    names = list(mesh.axis_names)[:grid_dim]
    return P(*names, *([None] * (grid_dim - len(names))))


def _pin(a, sharding):
    """``with_sharding_constraint`` under the ``comm`` named scope: the
    partitioner materializes its resharding collectives at these
    constraint boundaries, and the scope label is what lets
    obs/deviceprof classify that device time into the ``comm_s``
    op-class instead of leaving it anonymous. Every pin site in this
    module routes through here."""
    with jax.named_scope("comm"):
        return jax.lax.with_sharding_constraint(a, sharding)


def shard_state(state, grid: StaggeredGrid, mesh: Mesh):
    """Pin every grid-shaped array in the state pytree to the spatial
    sharding; everything else (markers, scalars) stays replicated."""
    spec = grid_pspec(mesh, grid.dim)
    sharding = NamedSharding(mesh, spec)
    gshape = tuple(grid.n)

    def constrain(a):
        if hasattr(a, "shape") and tuple(a.shape) == gshape:
            return _pin(a, sharding)
        return a

    return jax.tree_util.tree_map(constrain, state)


def _with_pencil_solvers(ins_integ, mesh: Mesh):
    """Shallow-copy an INS integrator with its spectral solves swapped for
    the pencil-decomposed distributed FFT (parallel.fftpar) — the solver
    seam of the north star's StaggeredStokesSolver interface."""
    import copy

    from ibamr_tpu.parallel.fftpar import PencilFFT

    if any(getattr(ins_integ, "wall_axes", ())):
        raise NotImplementedError(
            "sharded stepping currently supports fully periodic INS; "
            "wall-bounded fast-diagonalization solves are not yet "
            "distributed")
    pencil = PencilFFT(ins_integ.grid, mesh)
    integ2 = copy.copy(ins_integ)
    integ2.helmholtz_vel_solve = pencil.helmholtz_vel
    integ2.project = pencil.project_divergence_free
    # the fused single-device spectral path bypasses the seams above;
    # sharded stepping must go through the pencil transposes
    integ2.fused_stokes = None
    return integ2


# ---------------------------------------------------------------------------
# THE sharding seam (round 5, VERDICT item 7): one generic pinned-step
# wrapper + per-family PREPARE hooks + a name-dispatched entry point.
# Each integrator family contributes only what is genuinely its own —
# a solver-seam swap and/or a custom state pinner — and the wrapping,
# argument pinning, and jit live in exactly one place.
# ---------------------------------------------------------------------------

def _prepare_fluid(ins, mesh: Mesh):
    """Solver-seam prepare for a uniform INS integrator: periodic
    domains swap in the pencil-decomposed distributed FFT; wall-bounded
    domains keep their fast-diagonalization solves (dense per-axis
    eigenvector matmuls the SPMD partitioner distributes directly —
    the transform along a sharded axis becomes an MXU matmul with an
    all-gather, exactly a transpose-based distributed transform's
    communication)."""
    if any(getattr(ins, "wall_axes", ())):
        import copy

        ins = copy.copy(ins)
        ins.fused_stokes = None   # defensive: walls never set it
        return ins
    return _with_pencil_solvers(ins, mesh)


def _generic_pinned_step(integ, mesh: Mesh, prepare=None,
                         pin_state=None):
    """The one wrapper every simple (single-level) family uses: pin
    the state and every array argument to the family's sharding,
    call ``integ.step``, pin the result, jit. ``pin_state`` defaults
    to the exact-shape grid pinner (``shard_state``); rank-based
    layouts (face-complete open boundaries) pass ``_pin_rank_dim``."""
    if prepare is not None:
        integ = prepare(integ, mesh)
    if pin_state is None:
        grid = integ.grid

        def pin_state(t):
            return shard_state(t, grid, mesh)

    def step(state, *args, **kwargs):
        args = tuple(pin_state(a) for a in args)
        kwargs = {k: pin_state(v) for k, v in kwargs.items()}
        return pin_state(integ.step(pin_state(state), *args,
                                    **kwargs))

    return jax.jit(step)


def make_sharded_ins_step(integ, mesh: Mesh):
    """Jitted INS step with grid arrays sharded over ``mesh``
    (periodic: pencil-FFT solves; walls: partitioner-distributed
    fastdiag matmuls — see _prepare_fluid)."""
    return _generic_pinned_step(integ, mesh,
                                prepare=_prepare_fluid)


def _prepare_adv_diff(integ, mesh: Mesh):
    # Quantities with wall BCs keep their fast-diagonalization solves;
    # fully-periodic quantities get the pencil-FFT Helmholtz — the
    # integrator consults helmholtz_solve only where _wall_solvers[i]
    # is None, so the pencil plan is built exactly when some quantity
    # needs it (an all-wall integrator must not trip pencil
    # divisibility checks).
    import copy

    from ibamr_tpu.parallel.fftpar import PencilFFT

    integ = copy.copy(integ)
    if any(s is None for s in getattr(integ, '_wall_solvers', (None,))):
        pencil = PencilFFT(integ.grid, mesh)
        integ.helmholtz_solve = pencil.helmholtz_cc
    return integ


def make_sharded_adv_diff_step(integ, mesh: Mesh):
    """Jitted adv-diff step with grid arrays sharded over ``mesh``."""
    return _generic_pinned_step(integ, mesh,
                                prepare=_prepare_adv_diff)


def make_sharded_step(integ, mesh: Mesh, **opts):
    """THE sharding entry point (round 5, VERDICT item 7): dispatch
    any integrator to its family's sharded-step builder by class name.
    ``opts`` forward to the family builder (e.g. ``shard_window=`` for
    the composite families, ``sharded_markers=`` for IB). Integrators
    outside the table that expose ``.grid`` and ``.step`` get the
    generic exact-shape pinned wrapper — a new single-level family
    needs NO factory at all."""
    table = {
        "INSStaggeredIntegrator": make_sharded_ins_step,
        "AdvDiffSemiImplicitIntegrator": make_sharded_adv_diff_step,
        "INSVCStaggeredIntegrator": make_sharded_vc_step,
        "INSVCConservativeIntegrator": make_sharded_vc_step,
        "INSOpenIntegrator": make_sharded_open_ins_step,
        "IBOpenIntegrator": make_sharded_ib_open_step,
        "IBExplicitIntegrator": make_sharded_ib_step,
        "TwoLevelIBINS": make_sharded_two_level_ib_step,
        "MultiLevelAdvDiff": make_sharded_multilevel_step,
        "MultiLevelINS": make_sharded_multilevel_ins_step,
        "MultiLevelIBINS": make_sharded_multilevel_ib_step,
        "MultiBoxDynamicAdvDiff": make_sharded_multibox_step,
        "TwoLevelSmagorinskyINS": make_sharded_les_two_level_step,
        "CIBMethod": make_sharded_cib_constraint,
    }
    # walk the MRO so SUBCLASSES of a registered family inherit its
    # prepare seam (a name-only match would silently drop e.g. the
    # pencil-solver swap for a user's INSStaggeredIntegrator subclass)
    for klass in type(integ).__mro__:
        builder = table.get(klass.__name__)
        if builder is not None:
            return builder(integ, mesh, **opts)
    if hasattr(integ, "grid") and hasattr(integ, "step"):
        return _generic_pinned_step(integ, mesh, **opts)
    raise TypeError(
        f"no sharded-step builder for {type(integ).__name__}; expose "
        f".grid/.step for the generic wrapper or register a family "
        f"builder")


def make_sharded_multilevel_step(ml, mesh: Mesh):
    """Level-by-level AMR parallelism (S4): every level of a
    :class:`~ibamr_tpu.amr_multilevel.MultiLevelAdvDiff` hierarchy is
    sharded over the SAME device mesh (each level is a dense box array,
    so equal-block GSPMD sharding balances each level independently —
    the reference's per-level LoadBalancer pass). Coarse-fine transfer
    (quadratic ghost gathers, restriction, reflux slabs) crosses the
    level shardings as XLA-inserted collectives — the Refine/Coarsen
    schedule analog (SURVEY.md §2.3 S4)."""
    import copy

    dim = len(ml.levels[0].grid.n)
    ml = copy.copy(ml)
    # pin the level-synchronization arrays (CF ghost fills, post-update
    # level states) replicated: these are the hierarchy's boundary
    # exchanges, and leaving their sharding to SPMD propagation
    # miscompiles (wrong values, observed on the CPU mesh); flux and
    # stencil compute between the pins stays sharded
    ml.sync_sharding = NamedSharding(mesh, P(*([None] * dim)))

    shardings = []
    for spec in ml.levels:
        pspec = grid_pspec(mesh, len(spec.grid.n))
        shardings.append(NamedSharding(mesh, pspec))

    def constrain(Qs):
        return tuple(_pin(q, s)
                     for q, s in zip(Qs, shardings))

    def step(Qs, dt):
        return constrain(ml.step(constrain(tuple(Qs)), dt))

    return jax.jit(step)


def _wrap_sharded_markers(base_ib, grid: StaggeredGrid, mesh: Mesh,
                          marker_cap: Optional[int] = None,
                          marker_slack: float = 2.0,
                          warn_strategy: bool = False):
    """Build the S2 facade routing an IBMethod's transfers through the
    co-partitioned engine (parallel.lagrangian) on ``grid`` — markers
    owner-bucketed onto the mesh every step, local scatter/gather,
    ppermute halos. Returns None when the facade cannot engage —
    silently for a non-IBMethod strategy unless ``warn_strategy``
    (GSPMD is the intended route for IBFE/plugin couplings; explicit
    opt-ins pass True to learn their request was not honored), and
    with a warning when the (grid, mesh) geometry fails the engine's
    constraints (axis divisibility, halo >= local block) — callers
    then keep the GSPMD-resolved path. Shared by the uniform
    flagship step and the sharded-window composite step (S2 at the
    FINE level)."""
    from ibamr_tpu.integrators.ib import IBMethod
    from ibamr_tpu.parallel.lagrangian import ShardedInteraction

    if not isinstance(base_ib, IBMethod):
        # the GSPMD-resolved path is the INTENDED route for IBFE
        # quadrature couplings and custom plugins, so the default
        # (make_sharded_ib_step's sharded_markers=True) stays silent;
        # an EXPLICIT opt-in (the composite paths) warns so the user
        # learns their request was not honored
        if warn_strategy:
            import warnings

            warnings.warn(
                "sharded markers disabled: the S2 facade understands "
                f"marker-point IBMethod transfers only (got "
                f"{type(base_ib).__name__}); keeping the "
                "GSPMD-resolved path")
        return None
    try:
        ShardedInteraction(grid, mesh, kernel=base_ib.kernel, cap=8)
    except ValueError as e:
        import warnings

        warnings.warn(
            f"sharded markers disabled for this (grid, mesh): {e}")
        return None

    engines = {}

    def get_engine(N):
        # keyed by marker count: a retrace with a different N
        # must not reuse a capacity sized for the old N
        if N not in engines:
            engines[N] = ShardedInteraction(
                grid, mesh, kernel=base_ib.kernel, n_markers=N,
                cap=marker_cap, slack=marker_slack)
        return engines[N]

    class _ShardedIB:
        """IBMethod facade routing transfers through the S2 engine;
        force evaluation stays with the base method."""

        def __init__(self):
            self.specs = base_ib.specs
            self.kernel = base_ib.kernel

        def compute_force(self, X, U, t):
            return base_ib.compute_force(X, U, t)

        def prepare(self, X, mask):
            return get_engine(X.shape[0]).buckets(X, mask)

        def interpolate_velocity(self, u, g, X, mask, ctx=None):
            eng = get_engine(X.shape[0])
            if ctx is None:
                ctx = eng.buckets(X, mask)
            return eng.interpolate_vel(u, X, weights=mask, b=ctx)

        def spread_force(self, F, g, X, mask, ctx=None):
            eng = get_engine(X.shape[0])
            if ctx is None:
                ctx = eng.buckets(X, mask)
            return eng.spread_vel(F, X, weights=mask, b=ctx)

    return _ShardedIB()


def make_sharded_ib_step(integ, mesh: Mesh,
                         sharded_markers: Optional[bool] = None,
                         marker_cap: Optional[int] = None,
                         marker_slack: float = 2.0):
    """Jitted coupled IB step (interp -> force -> spread -> fluid solve ->
    correct) with the Eulerian state sharded over ``mesh``. This is the
    whole-timestep SPMD program of SURVEY.md §3.2's device-boundary note.

    With ``sharded_markers`` (default), the spread/interp transfers run
    through the S2 co-partitioned engine (parallel.lagrangian): markers
    are owner-bucketed onto the mesh every step and each device scatters
    /gathers only its own ~N/P markers, with ppermute halo exchange —
    instead of replicated markers + GSPMD-resolved transfers (round-1
    behavior, kept via ``sharded_markers=False``). Positions and forces
    stay replicated (O(N) arithmetic is negligible next to the grid
    work; SURVEY.md §2.3 S2)."""
    import copy

    grid = integ.ins.grid
    integ = copy.copy(integ)
    integ.ins = _prepare_fluid(integ.ins, mesh)

    # None = AUTO (default): use the S2 engine when eligible, fall back
    # silently (GSPMD is the intended route for IBFE/plugin strategies).
    # True = EXPLICIT request: warn if it cannot be honored.
    if sharded_markers is None or sharded_markers:
        wrapped = _wrap_sharded_markers(
            integ.ib, grid, mesh, marker_cap, marker_slack,
            warn_strategy=sharded_markers is True)
        if wrapped is not None:
            integ.ib = wrapped

    def pin_ib(st):
        if hasattr(st, "ins"):
            return st._replace(ins=shard_state(st.ins, grid, mesh))
        return st

    return _generic_pinned_step(integ, mesh, pin_state=pin_ib)


def make_sharded_two_level_ib_step(integ, mesh: Mesh,
                                   shard_window: bool = False,
                                   sharded_markers: bool = False,
                                   marker_cap: Optional[int] = None,
                                   marker_slack: float = 2.0):
    """Jitted composite two-level INS/IB step (S4 for the FLAGSHIP
    path) with the COARSE level sharded over ``mesh`` and the fine
    window either replicated (default) or ALSO sharded over the same
    mesh (``shard_window=True``), with explicit pins at every level
    crossing.

    Cost model for the default (window-replication): a SMALL fine
    window — it tracks the immersed structure (box_from_markers), so
    its cell count is O(structure volume), typically 5-25% of the
    coarse level's and often far less — does its per-step work
    (stencils + a fast-diagonalization solve whose dense axis matmuls
    saturate a single chip's MXU at window sizes <= ~128^3) without
    needing the mesh, and sharding it would put a latency-bound
    collective inside EVERY CF crossing (ghost fill, restriction,
    interface flux sync, and each FGMRES iteration's operator+precond
    application — ~m*restarts per projection).

    ``shard_window=True`` is the AT-SCALE mode (S4 depth, VERDICT
    round 3 missing #2): when the refined window carries the majority
    of the FLOPs (a 2x-refined window over a large structure has 2^dim
    times the cell density of the coarse level), replication makes the
    window the serial bottleneck and caps weak scaling. Sharding it
    divides the window stencils, the fastdiag dense axis matmuls
    (distributed by the SPMD partitioner exactly like the wall-bounded
    transforms), and the fine-resolution spread/interp scatter targets
    by the mesh size — the reference's per-level LoadBalancer behavior
    (every level distributed independently, SURVEY.md §2.3 S4). The
    CF crossings then carry the halo/restriction communication XLA
    inserts — O(window surface), the same asymptotics as the
    reference's Refine/Coarsen schedules.

    ``sharded_markers=True`` additionally routes the FINE-level marker
    transfers through the S2 owner-bucketed engine on the fine grid
    (local scatter/gather + ppermute halos instead of GSPMD-resolved
    transfers against the sharded window) — the full 'every level AND
    the transfers distributed' composition; pairs naturally with
    ``shard_window=True``. Ineligible strategies/geometries fall back
    with a warning.

    Either way the pins (CompositeProjection._pin_c/_pin_f) keep the
    SPMD partitioner from mis-propagating through the mixed
    scatter/gather level crossings (the round-2 wrong-values miscompile
    this replaces; same fix pattern as make_sharded_multilevel_step's
    sync pins). Equality with the single-device path at rtol 1e-12 for
    BOTH modes (1e-11 with S2 markers — segment-sum ordering) is
    pinned by tests/test_parallel.py."""
    import copy

    grid = integ.grid
    dim = grid.dim
    spatial = NamedSharding(mesh, grid_pspec(mesh, dim))
    replicated = NamedSharding(mesh, P())
    window_sh = spatial if shard_window else replicated

    integ = copy.copy(integ)
    integ.core = copy.copy(integ.core)
    proj = copy.copy(integ.core.proj)
    proj.level_sharding = spatial
    proj.window_sharding = window_sh
    proj.build_dense_coarse_solver()   # host-side: not legal mid-trace
    integ.core.proj = proj

    if sharded_markers:
        # S2 AT THE FINE LEVEL (the second half of VERDICT round 3
        # missing #2: "fine-level marker transfers over the mesh"):
        # owner-bucket the markers over the mesh against the FINE grid
        # and run local scatter/gather + ppermute halos there, instead
        # of GSPMD-resolved transfers against the sharded window.
        # Composes with shard_window (the natural pairing); ineligible
        # (fine grid, mesh) geometries fall back with a warning.
        wrapped = _wrap_sharded_markers(
            integ.ib, integ.fine_grid, mesh, marker_cap, marker_slack,
            warn_strategy=True)
        if wrapped is not None:
            integ.ib = wrapped

    def pin_state(st):
        # STRUCTURAL classification (coarse level vs everything else):
        # a shape heuristic would misclassify fine-window arrays
        # whenever ratio * box.shape == grid.n
        def pin(a, sh):
            return _pin(a, sh)

        fluid = st.fluid._replace(
            uc=tuple(pin(c, spatial) for c in st.fluid.uc),
            uf=tuple(pin(f, window_sh) for f in st.fluid.uf))
        return st._replace(fluid=fluid,
                           X=pin(st.X, replicated),
                           U=pin(st.U, replicated),
                           mask=pin(st.mask, replicated))

    def step(state, dt):
        return pin_state(integ.step(pin_state(state), dt))

    return jax.jit(step)


def _shard_multilevel_proj(core, mesh: Mesh, shard_boxes: bool = False):
    """Copy an L-level core integrator with its composite projection
    pinned for GSPMD: root level spatially sharded, box levels
    replicated by default (same cost model as
    make_sharded_two_level_ib_step — the boxes are usually the small
    levels) or ALSO sharded (``shard_boxes=True``, the at-scale S4
    depth mode: every level distributed independently, the reference's
    per-level LoadBalancer behavior)."""
    import copy

    core = copy.copy(core)
    proj = copy.copy(core.proj)
    spatial = NamedSharding(mesh, grid_pspec(mesh, core.grid.dim))
    proj.root_sharding = spatial
    proj.box_sharding = spatial if shard_boxes else NamedSharding(mesh,
                                                                  P())
    proj.build_dense_root_solver()    # host-side: not legal mid-trace
    core.proj = proj
    return core


def _pin_multilevel_us(us, spatial, box_sh):
    pin = _pin
    return tuple(
        tuple(pin(c, spatial if l == 0 else box_sh) for c in lev)
        for l, lev in enumerate(us))


def make_sharded_multilevel_ins_step(integ, mesh: Mesh,
                                     shard_boxes: bool = False):
    """Jitted L-level composite INS step
    (:class:`~ibamr_tpu.amr_ins_multilevel.MultiLevelINS`) with the
    root level sharded over ``mesh`` and every box level replicated
    (default) or every level sharded over the same mesh
    (``shard_boxes=True``), with explicit pins at every level crossing
    (S4 for the L-level FLUID hierarchy — the arbitrary-depth
    extension of make_sharded_two_level_ib_step; see its docstring for
    the replicate-vs-shard cost model)."""
    integ = _shard_multilevel_proj(integ, mesh, shard_boxes=shard_boxes)
    spatial = NamedSharding(mesh, grid_pspec(mesh, integ.grid.dim))
    box_sh = spatial if shard_boxes else NamedSharding(mesh, P())

    def pin_state(st):
        return st._replace(us=_pin_multilevel_us(st.us, spatial, box_sh))

    def step(state, dt):
        return pin_state(integ.step(pin_state(state), dt))

    return jax.jit(step)


def make_sharded_multilevel_ib_step(integ, mesh: Mesh,
                                    shard_boxes: bool = False):
    """Jitted L-level composite INS/IB step
    (:class:`~ibamr_tpu.amr_ins_multilevel.MultiLevelIBINS`): root
    level sharded, box levels replicated (default) or sharded
    (``shard_boxes=True`` — every level distributed, the S4-depth
    mode), markers replicated, pins at every level crossing. Removes
    the round-3 scope line "the L-level composite INS/IB runs
    replicated under sharding". Equality with the single-device step
    for both modes is pinned by tests/test_parallel.py."""
    import copy

    integ = copy.copy(integ)
    integ.core = _shard_multilevel_proj(integ.core, mesh,
                                        shard_boxes=shard_boxes)
    spatial = NamedSharding(mesh, grid_pspec(mesh, integ.grid.dim))
    replicated = NamedSharding(mesh, P())
    box_sh = spatial if shard_boxes else replicated
    pin = _pin

    def pin_state(st):
        fluid = st.fluid._replace(
            us=_pin_multilevel_us(st.fluid.us, spatial, box_sh))
        return st._replace(fluid=fluid,
                           X=pin(st.X, replicated),
                           U=pin(st.U, replicated),
                           mask=pin(st.mask, replicated))

    def step(state, dt):
        return pin_state(integ.step(pin_state(state), dt))

    return jax.jit(step)


def place_state(state, grid: StaggeredGrid, mesh: Mesh):
    """Device-put the initial state under the spatial sharding (so the
    first step doesn't start from a single-device layout)."""
    spec = grid_pspec(mesh, grid.dim)
    sharding = NamedSharding(mesh, spec)
    replicated = NamedSharding(mesh, P())
    gshape = tuple(grid.n)

    def put(a):
        a = jnp.asarray(a)
        if tuple(a.shape) == gshape:
            return jax.device_put(a, sharding)
        return jax.device_put(a, replicated)

    return jax.tree_util.tree_map(put, state)


# ---- fleet lane sharding (PR 16) ------------------------------------
# The SECOND scaling axis: where the spatial meshes above split ONE
# simulation's grid over D devices, a lane mesh splits a B-lane fleet
# (utils.lanes stacked state, lane axis ALWAYS axis 0) across devices —
# B/D whole lanes per device, zero cross-device traffic inside a step
# (lanes are independent), so a pod runs B×D-lane ensembles with the
# per-lane quarantine/dt machinery of HierarchyDriver untouched. The
# bitwise contract (sharded fleet == replicated fleet, f64) is pinned
# by tests/test_fleet_mesh.py.

LANE_AXIS = "lanes"


def make_lane_mesh(n_devices: Optional[int] = None,
                   devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the lane (batch) axis of a stacked fleet state."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    import numpy as np
    return Mesh(np.array(devices), (LANE_AXIS,))


def lane_pspec(mesh: Mesh) -> P:
    """PartitionSpec sharding axis 0 (the lane axis) over the lane mesh."""
    return P(mesh.axis_names[0])


def _check_lane_divisible(lanes: int, mesh: Mesh) -> None:
    d = int(mesh.devices.size)
    if lanes % d != 0:
        raise ValueError(
            f"fleet of {lanes} lanes does not divide the {d}-device "
            f"lane mesh evenly (lanes % devices must be 0 so every "
            f"device owns whole lanes)")


def shard_lanes(state, mesh: Mesh):
    """Constraint-pin every leaf's lane axis (axis 0) to the lane mesh.

    ``utils.lanes.stack_lanes`` gives EVERY leaf — scalars included — a
    leading (B,) lane axis, so the pin is unconditional; trailing axes
    stay unsharded (each device owns whole lanes)."""
    sharding = NamedSharding(mesh, lane_pspec(mesh))

    def constrain(a):
        if hasattr(a, "ndim") and a.ndim >= 1:
            return _pin(a, sharding)
        return a

    return jax.tree_util.tree_map(constrain, state)


def place_lanes(state, mesh: Mesh):
    """Device-put a lane-stacked fleet state under the lane sharding
    (so the first chunk doesn't start from a single-device layout, and
    so sharded checkpoints record the lane-sharded layout)."""
    sharding = NamedSharding(mesh, lane_pspec(mesh))
    replicated = NamedSharding(mesh, P())
    leaves = [l for l in jax.tree_util.tree_leaves(state)
              if hasattr(l, "shape") and getattr(l, "ndim", 0) >= 1]
    if leaves:
        _check_lane_divisible(int(leaves[0].shape[0]), mesh)

    def put(a):
        a = jnp.asarray(a)
        if a.ndim >= 1:
            return jax.device_put(a, sharding)
        return jax.device_put(a, replicated)

    return jax.tree_util.tree_map(put, state)


def make_sharded_vc_step(integ, mesh: Mesh):
    """Jitted variable-coefficient (multiphase) INS step with every
    grid field sharded over ``mesh`` — S1 for the P22 multiphase
    integrators (`INSVCStaggeredIntegrator` incl. the open-outlet
    tank / conservative form, walls or periodic). Everything inside
    the step is roll-stencil, CG (psum reductions), multigrid V-cycle,
    Godunov advection, and level-set reinitialization — all
    GSPMD-compatible. Equality pinned by tests/test_parallel.py."""
    return _generic_pinned_step(integ, mesh)


def _pin_rank_dim(mesh: Mesh, dim: int):
    """Pin every rank-``dim`` array of a pytree to the spatial sharding
    (the face-COMPLETE open-boundary layouts have +1 extents, so an
    exact-shape match cannot classify them; rank works because these
    states carry only grid-shaped fields at that rank)."""
    sharding = NamedSharding(mesh, grid_pspec(mesh, dim))

    def pin(a):
        if hasattr(a, "ndim") and a.ndim == dim:
            return _pin(a, sharding)
        return a

    def pin_state(st):
        return jax.tree_util.tree_map(pin, st)

    return pin_state


def make_sharded_multibox_step(mb, mesh: Mesh,
                               costs=None,
                               X=None, w_marker: float = 4.0):
    """Workload-BALANCED box->device placement for the K-window
    multi-box hierarchy (round 5, VERDICT item 4 — the real
    ``LoadBalancer::loadBalanceBoxLevel`` analog [U], closing S3):

    - per-window costs from the S3 cost model (fine cells +
      w_marker x markers, ``parallel.workload.box_costs``) unless
      given explicitly;
    - greedy LPT bin-packing assigns boxes to devices UNEVENLY
      (``parallel.workload.lpt_assign``) — a hot window (marker
      cluster) gets a device to itself while cold windows share;
    - the jitted step gathers the boxes into a device-major padded
      slot pool sharded over the mesh, runs all fine-window substeps
      (the dominant work) device-parallel via vmap against the
      pristine coarse predictor, then applies the cheap coarse
      restriction/reflux writebacks sequentially in box order — the
      SAME read-then-write (Jacobi) ordering the plain step uses, so
      1-vs-8 equality holds at stencil tolerance at EVERY window
      separation (tests/test_workload.py).

    Returns the jitted ``step(state, dt)``; ``step.placement()``
    yields the assignment/per-device loads for work-spread checks and
    ``step.rebuild(state)`` re-places after a host-side regrid moved
    the windows (placement is never checked on the hot path — no
    device sync per step).

    ``costs`` overrides the cost model for the INITIAL layout only; a
    ``rebuild`` after a regrid always re-derives costs from the new
    origins (an explicit stale-cost placement would silently defeat
    the balancing the rebuild exists to restore).
    """
    import numpy as _np

    from ibamr_tpu.parallel.workload import box_costs, lpt_assign

    D = int(_np.prod(mesh.devices.shape))
    K = mb.K
    win = mb.win
    state_holder = {"explicit_costs": costs}

    def build(lo_np):
        c = state_holder.pop("explicit_costs", None)
        if c is None:
            c = box_costs(lo_np, mb.win.box_shape, mb.grid,
                          ratio=mb.ratio, X=X, w_marker=w_marker)
        device_of_box, load = lpt_assign(c, D)
        M = int(max(1, _np.bincount(device_of_box,
                                    minlength=D).max()))
        slot_box = _np.zeros(D * M, dtype=_np.int64)   # pad: box 0
        slot_of_box = _np.zeros(K, dtype=_np.int64)
        fill = _np.zeros(D, dtype=_np.int64)
        for k in range(K):
            d = int(device_of_box[k])
            s = d * M + int(fill[d])
            fill[d] += 1
            slot_box[s] = k
            slot_of_box[k] = s
        return c, device_of_box, load, M, slot_box, slot_of_box

    placement = None

    def make(lo_np):
        nonlocal placement
        c, device_of_box, load, M, slot_box, slot_of_box = build(lo_np)
        placement = {
            "costs": c, "device_of_box": device_of_box,
            "load": load, "slots_per_device": M,
        }
        slot_box_j = jnp.asarray(slot_box)
        slot_of_box_j = jnp.asarray(slot_of_box)
        pool_sh = NamedSharding(mesh, P(mesh.axis_names[0]
                                        if len(mesh.axis_names) == 1
                                        else mesh.axis_names))
        replicated = NamedSharding(mesh, P())
        pin = _pin

        def step(state, dt):
            Qc = pin(state.Qc, replicated)
            Qf = pin(state.Qf, replicated)
            lo = pin(state.lo, replicated)
            Fc, Qc_new = win._coarse_advance(Qc, dt)
            Qf_slots = pin(jnp.take(Qf, slot_box_j, axis=0), pool_sh)
            lo_slots = jnp.take(lo, slot_box_j, axis=0)
            sub = jax.vmap(
                lambda qf, l: win._fine_substeps(Qc, Qc_new, qf, l,
                                                 dt))
            Qf_new_s, acc_lo_s, acc_hi_s = sub(Qf_slots, lo_slots)
            Qf_new_s = pin(Qf_new_s, pool_sh)
            for k in range(K):            # cheap, exact, box order
                s = int(slot_of_box[k])
                Qc_new = win._restrict_and_reflux(
                    Qc_new, Qf_new_s[s], lo[k], Fc,
                    [a[s] for a in acc_lo_s],
                    [a[s] for a in acc_hi_s], dt)
            Qf_new = pin(jnp.take(Qf_new_s, slot_of_box_j, axis=0),
                         replicated)
            from ibamr_tpu.amr_multibox import MultiBoxState

            return MultiBoxState(Qc=pin(Qc_new, replicated),
                                 Qf=Qf_new, lo=lo)

        return jax.jit(step)

    _compiled = [None]

    def stepper(state, dt):
        # placement built lazily on FIRST call; never re-checked on
        # the hot path (np.asarray(state.lo) would force a device
        # sync per step). Regrid callers invalidate via rebuild().
        if _compiled[0] is None:
            _compiled[0] = make(_np.asarray(state.lo))
        return _compiled[0](state, dt)

    def rebuild(state):
        """Re-place after a host-side regrid moved the windows."""
        _compiled[0] = make(_np.asarray(state.lo))

    def get_placement():
        return placement

    stepper.placement = get_placement
    stepper.rebuild = rebuild
    return stepper


def make_sharded_les_two_level_step(les, mesh: Mesh):
    """Jitted composite-window LES step (round 5, VERDICT item 3b
    sharded): the coarse level sharded over ``mesh``, the refined
    window replicated (the default cost model of
    make_sharded_two_level_ib_step), with the composite projection's
    level-crossing pins installed. The per-level eddy-stress forces
    are pure stencil work and follow their level's sharding."""
    import copy

    grid = les.grid
    spatial = NamedSharding(mesh, grid_pspec(mesh, grid.dim))
    replicated = NamedSharding(mesh, P())

    les = copy.copy(les)
    les.core = copy.copy(les.core)
    proj = copy.copy(les.core.proj)
    proj.level_sharding = spatial
    proj.window_sharding = replicated
    proj.build_dense_coarse_solver()   # host-side: not legal mid-trace
    les.core.proj = proj

    pin = _pin

    def pin_state(st):
        return st._replace(
            uc=tuple(pin(c, spatial) for c in st.uc),
            uf=tuple(pin(f, replicated) for f in st.uf))

    def step(state, dt):
        return pin_state(les.step(pin_state(state), dt))

    return jax.jit(step)


def make_sharded_cib_constraint(cibm, mesh: Mesh):
    """Jitted CIB prescribed-kinematics solve with the Eulerian fields
    of every nested mobility application (spread force, Stokes
    velocity) sharded over ``mesh`` and the marker arrays replicated —
    S1 through the CIB composition (round 5, VERDICT item 3c sharded;
    works for both the periodic and the WALLED domain, whose saddle
    FGMRES smoothers/reductions are the same GSPMD-compatible ops as
    the open-boundary path's)."""
    import copy

    spatial = NamedSharding(mesh, grid_pspec(mesh, cibm.grid.dim))
    replicated = NamedSharding(mesh, P())
    pin = _pin

    cibm = copy.copy(cibm)
    cibm.field_pin = lambda a: pin(a, spatial)

    def solve(X, U):
        X = pin(X, replicated)
        U = pin(U, replicated)
        lam, FT, info = cibm.solve_constraint(X, U)
        return pin(lam, replicated), pin(FT, replicated), info

    return jax.jit(solve)


def make_sharded_open_ins_step(integ, mesh: Mesh):
    """Jitted inflow/outflow (open-boundary) INS step sharded over
    ``mesh`` — S1 for the external-flow configuration: the coupled
    saddle solve's red-black smoothers are masked elementwise ops and
    its FGMRES reductions are psums, all GSPMD-compatible. Equality
    with the single-device step is pinned by tests/test_parallel.py."""
    return _generic_pinned_step(
        integ, mesh, pin_state=_pin_rank_dim(mesh, len(integ.n)))


def make_sharded_ib_open_step(integ, mesh: Mesh):
    """Jitted coupled IB step over the OPEN-BOUNDARY fluid
    (integrators.ib_open) with the Eulerian state sharded over
    ``mesh`` and markers replicated — flow past an immersed structure
    on the device mesh."""
    pin_fluid = _pin_rank_dim(mesh, len(integ.ins.n))
    replicated = NamedSharding(mesh, P())
    pin = _pin

    def pin_all(st):
        if hasattr(st, "fluid"):
            return st._replace(fluid=pin_fluid(st.fluid),
                               X=pin(st.X, replicated),
                               U=pin(st.U, replicated),
                               mask=pin(st.mask, replicated))
        return st        # scalars/aux passed through step args

    return _generic_pinned_step(integ, mesh, pin_state=pin_all)
