"""Multi-device parallelism: spatial domain decomposition over a TPU mesh.

Reference parity (SURVEY.md §2.3/§2.4): SAMRAI's MPI domain decomposition
(LoadBalancer patch->rank assignment, RefineSchedule halo exchange,
SAMRAI_MPI/PETSc reductions) becomes a `jax.sharding.Mesh` with
XLA collectives over ICI. Two execution paths are provided:

- `mesh.py` — GSPMD path: jit the single-device step with
  `with_sharding_constraint` on all grid arrays; XLA's SPMD partitioner
  lowers roll-stencils to neighbor collective-permutes and FFTs to
  all-to-all/all-gather transposes automatically.
- `halo.py` / `fftpar.py` — explicit `shard_map` path: hand-written
  ppermute halo exchange and pencil-decomposed distributed FFT, the
  controlled analog of the reference's precomputed RefineSchedules.
"""

from ibamr_tpu.parallel.lagrangian import ShardedInteraction  # noqa: F401
from ibamr_tpu.parallel.mesh import (  # noqa: F401
    factor_devices,
    grid_pspec,
    make_mesh,
    make_sharded_ib_step,
    make_sharded_ins_step,
    make_sharded_step,
    shard_state,
)
