"""Workload-balanced partitioning (S3): the Lagrangian cost model.

Reference parity: ``IBStrategy::updateWorkloadEstimates`` +
``LoadBalancer`` (SURVEY.md §2.3 S3, §3.4) — the reference adds a
marker-count weight to each cell so the box partitioner equalizes
Eulerian + Lagrangian cost per rank.

TPU-first reinterpretation: under GSPMD the grid is sharded in EQUAL
blocks (XLA's partitioner does not support weighted splits), so the
balancing levers are different but real:

1. **Mesh-axis selection.** For a P-device mesh there are several ways
   to factor P over the grid axes (8 = 8x1 = 4x2 = 2x4 = 1x8 ...);
   clustered structures (a shell mid-domain, a falling drop) produce
   very different per-shard marker maxima under each. ``choose_mesh``
   evaluates the cost model over the candidate factorizations against
   the actual marker histogram and returns the best — the partitioner
   decision, made once per regrid cadence on the host (cheap: a few
   histograms over N integers).
2. **Capacity sizing.** The sharded transfer engine
   (:class:`~ibamr_tpu.parallel.lagrangian.ShardedInteraction`) uses
   fixed per-shard pools; ``recommended_capacity`` sizes them from the
   measured histogram (instead of the uniform N/P * slack guess) so the
   fast path holds exactly when the cost model says it can.
3. **Rebalance cadence.** ``needs_rebalance`` is the host-side check
   (the analog of the reference's regrid-triggered load balancing):
   markers drifted enough that the current capacity would overflow, or
   a different factorization now wins by more than ``hysteresis``.
"""

from __future__ import annotations

import math
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ibamr_tpu.grid import StaggeredGrid

__all__ = ["shard_marker_counts", "workload_estimate", "choose_mesh",
           "recommended_capacity", "needs_rebalance", "WorkloadReport",
           "box_costs", "lpt_assign"]


def _factorizations(P: int, naxes: int) -> List[Tuple[int, ...]]:
    """All ordered factorizations of P into ``naxes`` factors."""
    if naxes == 1:
        return [(P,)]
    out = []
    for f in range(1, P + 1):
        if P % f == 0:
            for rest in _factorizations(P // f, naxes - 1):
                out.append((f,) + rest)
    return out


def shard_marker_counts(X: np.ndarray, grid: StaggeredGrid,
                        sizes: Sequence[int],
                        mask: Optional[np.ndarray] = None) -> np.ndarray:
    """Markers owned by each shard of a ``sizes`` block partition
    (same owner math as ShardedInteraction.buckets), shape ``sizes``."""
    X = np.asarray(X)
    sizes = tuple(int(s) for s in sizes)
    for d, p in enumerate(sizes):
        if grid.n[d] % p != 0:
            raise ValueError(
                f"sizes[{d}]={p} does not divide grid axis "
                f"{grid.n[d]} — not a GSPMD partition")
    if mask is not None:
        X = X[np.asarray(mask) != 0]
    idx = []
    for d, p in enumerate(sizes):
        nloc = grid.n[d] // p
        c = np.floor((X[:, d] - grid.x_lo[d]) / grid.dx[d]).astype(int)
        c = np.mod(c, grid.n[d])
        idx.append(np.clip(c // nloc, 0, p - 1))
    flat = np.zeros(int(np.prod(sizes)), dtype=np.int64)
    lin = idx[0]
    for d in range(1, len(sizes)):
        lin = lin * sizes[d] + idx[d]
    np.add.at(flat, lin, 1)
    return flat.reshape(sizes)


class WorkloadReport(NamedTuple):
    sizes: Tuple[int, ...]       # chosen mesh factorization
    cost_per_shard: np.ndarray   # estimated cost per shard
    imbalance: float             # max/mean cost ratio
    max_markers: int             # largest per-shard marker count
    capacity: int                # recommended per-shard pool capacity


def workload_estimate(counts: np.ndarray, grid: StaggeredGrid,
                      w_marker: float = 4.0) -> np.ndarray:
    """Per-shard cost: local grid cells + w_marker * local markers.
    ``w_marker`` is the relative cost of one marker's spread+interp
    stencils vs one grid cell's stencil updates (the reference's
    default workload weight is O(1); delta-kernel transfers touch
    s^dim cells per marker, so the default leans higher)."""
    cells = np.prod(grid.n) / counts.size
    return cells + w_marker * counts.astype(np.float64)


def recommended_capacity(counts: np.ndarray, slack: float = 1.5,
                         quantum: int = 8) -> int:
    """Per-shard pool capacity covering the measured maximum with
    headroom, rounded up to the allocation quantum."""
    peak = int(counts.max()) if counts.size else 0
    return int(math.ceil(max(peak, 1) * slack / quantum) * quantum)


def choose_mesh(X: np.ndarray, grid: StaggeredGrid, n_devices: int,
                max_axes: int = 2, w_marker: float = 4.0,
                min_block: Optional[int] = None,
                mask: Optional[np.ndarray] = None) -> WorkloadReport:
    """Evaluate every mesh factorization of ``n_devices`` over at most
    ``max_axes`` leading grid axes against the marker histogram; return
    the factorization minimizing the maximum per-shard cost. Ties keep
    the earliest candidate — fewer sharded axes first (mean cost is
    factorization-invariant, so equal max cost implies equal
    imbalance). ``min_block`` rejects factorizations whose local blocks
    are thinner than the transfer halo."""
    best: Optional[WorkloadReport] = None
    naxes = min(max_axes, grid.dim)
    for k in range(1, naxes + 1):
        for sizes in _factorizations(n_devices, k):
            ok = True
            for d, p in enumerate(sizes):
                if grid.n[d] % p != 0:
                    ok = False
                    break
                if min_block is not None and grid.n[d] // p < min_block:
                    ok = False
                    break
            if not ok:
                continue
            counts = shard_marker_counts(X, grid, sizes, mask=mask)
            cost = workload_estimate(counts, grid, w_marker=w_marker)
            rep = WorkloadReport(
                sizes=sizes,
                cost_per_shard=cost,
                imbalance=float(cost.max() / cost.mean()),
                max_markers=int(counts.max()),
                capacity=recommended_capacity(counts))
            if best is None or cost.max() < best.cost_per_shard.max() \
                    - 1e-9:
                best = rep
    if best is None:
        raise ValueError(
            f"no valid factorization of {n_devices} devices for grid "
            f"{grid.n} (min_block={min_block})")
    return best


def box_costs(lo: np.ndarray, box_shape: Sequence[int],
              grid: StaggeredGrid, ratio: int = 2,
              X: Optional[np.ndarray] = None,
              w_marker: float = 4.0) -> np.ndarray:
    """Per-window workload of a K-box fine level: fine cells +
    ``w_marker`` x markers inside each window (the same cost model as
    :func:`workload_estimate`, per box instead of per shard — the
    SAMRAI ``LoadBalancer`` weights patches exactly this way before
    bin-packing them onto ranks [U])."""
    lo = np.asarray(lo)
    K = lo.shape[0]
    cells = float(np.prod([s * ratio for s in box_shape]))
    costs = np.full(K, cells, dtype=np.float64)
    if X is not None and len(X):
        Xi = np.asarray(X)
        for k in range(K):
            inside = np.ones(len(Xi), dtype=bool)
            for d in range(grid.dim):
                x0 = grid.x_lo[d] + lo[k, d] * grid.dx[d]
                x1 = x0 + box_shape[d] * grid.dx[d]
                inside &= (Xi[:, d] >= x0) & (Xi[:, d] < x1)
            costs[k] += w_marker * int(inside.sum())
    return costs


def lpt_assign(costs: np.ndarray, n_devices: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Greedy LPT (longest-processing-time) bin-packing: sort items by
    descending cost, always assign to the least-loaded device — the
    classic 4/3-approximation the reference's greedy
    ``LoadBalancer::loadBalanceBoxLevel`` uses [U]. Returns
    (device_of_item (K,), per-device load (n_devices,))."""
    costs = np.asarray(costs, dtype=np.float64)
    order = np.argsort(-costs)
    load = np.zeros(n_devices, dtype=np.float64)
    device = np.zeros(costs.size, dtype=np.int64)
    for k in order:
        d = int(np.argmin(load))
        device[k] = d
        load[d] += costs[k]
    return device, load


def needs_rebalance(X: np.ndarray, grid: StaggeredGrid,
                    sizes: Sequence[int], capacity: int,
                    n_devices: Optional[int] = None,
                    hysteresis: float = 1.3,
                    mask: Optional[np.ndarray] = None,
                    min_block: Optional[int] = None,
                    max_axes: int = 2, w_marker: float = 4.0) -> bool:
    """Host-side regrid-cadence check: True when the current partition
    would overflow its pools, or another factorization beats the
    current maximum cost by more than ``hysteresis``. Pass the SAME
    ``w_marker``/``max_axes`` used when the current partition was
    chosen, so both sides of the comparison share one cost model."""
    counts = shard_marker_counts(X, grid, sizes, mask=mask)
    if int(counts.max()) > capacity:
        return True
    if n_devices is None:
        n_devices = int(np.prod(tuple(sizes)))
    cur_cost = workload_estimate(counts, grid, w_marker=w_marker).max()
    best = choose_mesh(X, grid, n_devices, max_axes=max_axes,
                       w_marker=w_marker, mask=mask,
                       min_block=min_block)
    return bool(cur_cost > hysteresis * best.cost_per_shard.max())
