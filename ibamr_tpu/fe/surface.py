"""Codim-1 FE structures: membranes/shells (the IBFESurfaceMethod half
of P17, SURVEY.md §2.2).

Reference parity: the reference's ``IBFESurfaceMethod`` couples a
surface (codimension-1) finite-element mesh to the fluid: EDGE2 curves
in 2D, TRI3 facets in 3D, with in-plane membrane elasticity evaluated
from the surface deformation gradient and forces spread from surface
quadrature points with AREA weights.

TPU-first redesign mirrors ``fe/fem.py``: all reference tables (shape
values, parametric gradients, reference metric and area measure) are
host-precomputed; the total membrane energy

    E(x) = sum_e sum_q wdA_eq * W_s(M_eq),   M = G_ref^{-1} C(x),
    C_ij = t_i . t_j,  t_i = sum_a dN_a/dxi_i x_a   (current tangents)

is a pure jitted function of nodal positions and the nodal force is
``-jax.grad(E)`` — the weak form falls out of the chain rule, for any
invariant-based membrane energy. ``M`` (the mixed Cauchy--Green strain)
is frame-indifferent by construction: rigid motions give C == G_ref,
M == I, zero force.

``neo_hookean_membrane``: W_s = mu/2 (tr M - rdim - ln det M)
+ kappa/2 (sqrt(det M) - 1)^2 — shear stiffness mu, area-dilatation
stiffness kappa (kappa with mu=0 is a surface-tension-like area
penalty).
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Vel = Tuple[jnp.ndarray, ...]


class SurfaceMesh(NamedTuple):
    """Codim-1 mesh: EDGE2 (2D ambient) or TRI3 facets (3D ambient)."""
    nodes: np.ndarray      # (n_nodes, dim)
    elems: np.ndarray      # (E, nen): nen=2 (EDGE2) or 3 (TRI3)
    elem_type: str         # "EDGE2" | "TRI3S"

    @property
    def dim(self) -> int:
        return self.nodes.shape[1]

    @property
    def n_nodes(self) -> int:
        return self.nodes.shape[0]


def _surf_shape_table(elem_type: str):
    if elem_type == "EDGE2":
        g = 1.0 / math.sqrt(3.0)
        qp = np.array([[(1.0 - g) / 2.0], [(1.0 + g) / 2.0]])
        qw = np.array([0.5, 0.5])
        N = np.stack([1.0 - qp[:, 0], qp[:, 0]], axis=1)
        dN = np.broadcast_to(np.array([[-1.0], [1.0]]),
                             (2, 2, 1)).copy()
    elif elem_type == "TRI3S":
        qp = np.array([[1 / 6, 1 / 6], [2 / 3, 1 / 6], [1 / 6, 2 / 3]])
        qw = np.array([1 / 6, 1 / 6, 1 / 6])
        N = np.stack([1.0 - qp[:, 0] - qp[:, 1], qp[:, 0], qp[:, 1]],
                     axis=1)
        dN = np.broadcast_to(
            np.array([[-1.0, -1.0], [1.0, 0.0], [0.0, 1.0]]),
            (3, 3, 2)).copy()
    else:
        raise ValueError(f"unknown surface element {elem_type!r}")
    return N, dN, qw


class SurfaceAssembly(NamedTuple):
    elems: jnp.ndarray       # (E, nen)
    shape: jnp.ndarray       # (nq, nen)
    dN: jnp.ndarray          # (nq, nen, rdim) parametric gradients
    Ginv: jnp.ndarray        # (E, nq, rdim, rdim) reference metric inv
    wdA: jnp.ndarray         # (E, nq) reference area measure * weight
    lumped_mass: jnp.ndarray  # (n_nodes,) HRZ-lumped surface mass
    n_nodes: int
    dim: int                 # ambient dimension
    rdim: int                # reference (surface) dimension


def build_surface_assembly(mesh: SurfaceMesh,
                           dtype=jnp.float32) -> SurfaceAssembly:
    N, dN, qw = _surf_shape_table(mesh.elem_type)
    rdim = dN.shape[2]
    Xe = mesh.nodes[mesh.elems]                      # (E, nen, dim)
    T = np.einsum("qar,eai->eqir", dN, Xe)           # (E, nq, dim, rdim)
    G = np.einsum("eqir,eqis->eqrs", T, T)           # reference metric
    detG = np.linalg.det(G)
    wdA = np.sqrt(np.abs(detG)) * qw[None, :]
    Ginv = np.linalg.inv(G)

    from ibamr_tpu.fe.fem import hrz_lumped_mass
    mass = hrz_lumped_mass(mesh.elems, N, wdA, mesh.n_nodes)

    return SurfaceAssembly(
        elems=jnp.asarray(mesh.elems, dtype=jnp.int32),
        shape=jnp.asarray(N, dtype=dtype),
        dN=jnp.asarray(dN, dtype=dtype),
        Ginv=jnp.asarray(Ginv, dtype=dtype),
        wdA=jnp.asarray(wdA, dtype=dtype),
        lumped_mass=jnp.asarray(mass, dtype=dtype),
        n_nodes=mesh.n_nodes, dim=mesh.dim, rdim=rdim)


def surface_strain(asm: SurfaceAssembly, x: jnp.ndarray) -> jnp.ndarray:
    """Mixed Cauchy--Green strain M = G_ref^{-1} C(x) at every surface
    quadrature point -> (E, nq, rdim, rdim); M == I under rigid motion."""
    xe = x[asm.elems]                                # (E, nen, dim)
    T = jnp.einsum("qar,eai->eqir", asm.dN, xe)      # current tangents
    C = jnp.einsum("eqir,eqis->eqrs", T, T)
    return jnp.einsum("eqrt,eqts->eqrs", asm.Ginv, C)


def neo_hookean_membrane(mu: float, kappa: float) -> Callable:
    """W_s(M) = mu/2 (tr M - rdim - ln det M) + kappa/2 (J_s - 1)^2,
    J_s = sqrt(det M) (relative area/length change)."""
    def W(M):
        rdim = M.shape[-1]
        detM = jnp.linalg.det(M) if rdim > 1 else M[..., 0, 0]
        trM = jnp.trace(M, axis1=-2, axis2=-1) if rdim > 1 \
            else M[..., 0, 0]
        Js = jnp.sqrt(jnp.maximum(detM, 1e-12))
        return (0.5 * mu * (trM - rdim - jnp.log(
            jnp.maximum(detM, 1e-12)))
            + 0.5 * kappa * (Js - 1.0) ** 2)
    return W


def membrane_energy(asm: SurfaceAssembly, W: Callable, x: jnp.ndarray):
    M = surface_strain(asm, x)
    return jnp.sum(W(M) * asm.wdA)


def membrane_forces(asm: SurfaceAssembly, W: Callable,
                    x: jnp.ndarray) -> jnp.ndarray:
    """Weak-form nodal membrane force -dE/dx -> (n_nodes, dim)."""
    return -jax.grad(lambda xx: membrane_energy(asm, W, xx))(x)


def surface_quad_positions(asm: SurfaceAssembly,
                           x: jnp.ndarray) -> jnp.ndarray:
    xe = x[asm.elems]
    return jnp.einsum("qa,eai->eqi", asm.shape, xe).reshape(-1, asm.dim)


def current_area(asm: SurfaceAssembly, x: jnp.ndarray):
    """Deformed surface measure (perimeter in 2D, area in 3D)."""
    M = surface_strain(asm, x)
    rdim = asm.rdim
    detM = jnp.linalg.det(M) if rdim > 1 else M[..., 0, 0]
    return jnp.sum(jnp.sqrt(jnp.maximum(detM, 0.0)) * asm.wdA)


# -- mesh builders -----------------------------------------------------------

def surface_mesh_from_fe(mesh) -> SurfaceMesh:
    """Adopt a codim-1 :class:`~ibamr_tpu.fe.mesh.FEMesh` — e.g. a
    Gmsh-loaded TRI3 shell embedded in 3D (``read_gmsh`` keeps all
    three coordinate columns for such meshes) or an EDGE2 curve — as a
    :class:`SurfaceMesh` for the codim-1 IBFE machinery. Higher-order
    surface families (TRI6) are adopted by their corner nodes."""
    et, nodes, elems = mesh.elem_type, mesh.nodes, mesh.elems
    if et in ("TRI3", "TRI6") and nodes.shape[1] == 3:
        corners, out_type = elems[:, :3], "TRI3S"
    elif et == "EDGE2" and nodes.shape[1] == 2:
        corners, out_type = elems[:, :2], "EDGE2"
    else:
        raise ValueError(
            f"not a codim-1 configuration: {et} with "
            f"{nodes.shape[1]}-column nodes (need TRI3/TRI6 in 3D or "
            "EDGE2 in 2D)")
    # corner-only adoption can orphan nodes (TRI6 midsides): drop and
    # remap densely so no inert markers ride along in the IB coupling
    used = np.unique(corners)
    remap = -np.ones(nodes.shape[0], dtype=np.int64)
    remap[used] = np.arange(used.size)
    return SurfaceMesh(nodes=np.asarray(nodes[used], dtype=float),
                       elems=np.asarray(remap[corners],
                                        dtype=np.int64),
                       elem_type=out_type)


def ring_mesh(center=(0.5, 0.5), radius: float = 0.25, n: int = 64,
              aspect: float = 1.0) -> SurfaceMesh:
    """Closed EDGE2 ring (optionally elliptic: semi-axes r*aspect, r)."""
    th = 2.0 * np.pi * np.arange(n) / n
    nodes = np.stack([center[0] + radius * aspect * np.cos(th),
                      center[1] + radius * np.sin(th)], axis=1)
    elems = np.stack([np.arange(n), (np.arange(n) + 1) % n], axis=1)
    return SurfaceMesh(nodes=nodes, elems=elems.astype(np.int64),
                       elem_type="EDGE2")


def sphere_surface_mesh(center=(0.5, 0.5, 0.5), radius: float = 0.25,
                        n_subdiv: int = 2) -> SurfaceMesh:
    """Geodesic TRI3 sphere: subdivided octahedron projected to the
    sphere (watertight, near-uniform facets)."""
    verts = np.array([[1, 0, 0], [-1, 0, 0], [0, 1, 0],
                      [0, -1, 0], [0, 0, 1], [0, 0, -1]], dtype=float)
    faces = [(0, 2, 4), (2, 1, 4), (1, 3, 4), (3, 0, 4),
             (2, 0, 5), (1, 2, 5), (3, 1, 5), (0, 3, 5)]
    verts = [v for v in verts]
    for _ in range(n_subdiv):
        new_faces = []
        midcache = {}

        def mid(i, j):
            key = (min(i, j), max(i, j))
            if key not in midcache:
                m = verts[i] + verts[j]
                m = m / np.linalg.norm(m)
                midcache[key] = len(verts)
                verts.append(m)
            return midcache[key]

        for (a, b, c) in faces:
            ab, bc, ca = mid(a, b), mid(b, c), mid(c, a)
            new_faces += [(a, ab, ca), (b, bc, ab), (c, ca, bc),
                          (ab, bc, ca)]
        faces = new_faces
    nodes = np.asarray(verts) * radius + np.asarray(center)
    return SurfaceMesh(nodes=nodes,
                       elems=np.asarray(faces, dtype=np.int64),
                       elem_type="TRI3S")
