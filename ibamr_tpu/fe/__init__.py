"""Finite-element structure layer for IBFE (P17/T16 parity)."""

from ibamr_tpu.fe.fem import (FEAssembly, build_assembly,
                              deformation_gradients, elastic_energy,
                              l2_project_from_quads, neo_hookean,
                              nodal_forces, nodal_forces_pk1, pk1,
                              project_to_quads, quad_positions, stvk)
from ibamr_tpu.fe.mesh import (FEMesh, block_mesh_tet, block_mesh_tri,
                               box_hex_mesh, disc_mesh, read_triangle,
                               rect_quad_mesh, to_quadratic)

__all__ = [
    "FEAssembly", "FEMesh", "block_mesh_tet", "block_mesh_tri",
    "box_hex_mesh", "build_assembly", "deformation_gradients",
    "disc_mesh", "elastic_energy", "l2_project_from_quads",
    "neo_hookean", "nodal_forces", "nodal_forces_pk1", "pk1",
    "project_to_quads", "quad_positions", "read_triangle",
    "rect_quad_mesh", "stvk", "to_quadratic",
]
