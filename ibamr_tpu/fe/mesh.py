"""Finite-element meshes for the IBFE structure path.

Reference parity: the *used surface* of libMesh in ``IBFEMethod`` /
``FEDataManager`` (P17/T16, SURVEY.md §2) — a nodal mesh of linear
simplex elements carrying the Lagrangian solid. The reference links
libMesh; the rebuild keeps the mesh as plain arrays (nodes, connectivity)
built host-side with NumPy, because everything the device ever touches is
the precomputed quadrature tables in :mod:`ibamr_tpu.fe.fem`
(SURVEY.md §7.3 hard-part #6: FE reference-configuration quantities are
host precompute, only per-step kinematics run on TPU).

Element types: TRI3 (2D solids) and TET4 (3D solids), both linear
simplices — the element family the IBFE acceptance config uses.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np


@dataclass
class FEMesh:
    """Nodal mesh of one linear-simplex element type.

    nodes: (n_nodes, dim) float reference coordinates
    elems: (n_elems, nen) int connectivity (nen = dim + 1)
    elem_type: "TRI3" | "TET4" | "TRI6" | "TET10" | "QUAD4" | "HEX8"
    """
    nodes: np.ndarray
    elems: np.ndarray
    elem_type: str

    @property
    def dim(self) -> int:
        return self.nodes.shape[1]

    @property
    def n_nodes(self) -> int:
        return self.nodes.shape[0]

    @property
    def n_elems(self) -> int:
        return self.elems.shape[0]

    def volume(self) -> float:
        """Total reference measure (area in 2D, volume in 3D), by the
        element family's own quadrature — exact for every type in the
        menu."""
        from ibamr_tpu.fe.fem import _shape_table
        _, dN, qw = _shape_table(self.elem_type)
        X = self.nodes[self.elems]                    # (E, nen, dim)
        J = np.einsum("qad,eai->eqid", dN, X)
        return float(np.sum(np.abs(np.linalg.det(J)) * qw[None, :]))


def disc_mesh(radius: float = 0.25,
              center: Tuple[float, float] = (0.5, 0.5),
              n_rings: int = 8) -> FEMesh:
    """Unstructured TRI3 disc: a center node plus ``n_rings`` concentric
    rings; ring r holds ``6r`` nodes (hex-like layout keeps triangles
    well-shaped). The standard IBFE-ex0-style solid body."""
    nodes = [np.array(center, dtype=np.float64)]
    ring_start = [0]
    for r in range(1, n_rings + 1):
        ring_start.append(len(nodes))
        m = 6 * r
        th = 2.0 * np.pi * np.arange(m) / m
        rr = radius * r / n_rings
        for t in th:
            nodes.append(np.array([center[0] + rr * np.cos(t),
                                   center[1] + rr * np.sin(t)]))
    nodes = np.stack(nodes, axis=0)

    elems = []
    # inner fan: center to ring 1 (6 nodes)
    s1 = ring_start[1]
    for k in range(6):
        elems.append([0, s1 + k, s1 + (k + 1) % 6])
    # strips between ring r (6r nodes) and ring r+1 (6(r+1) nodes)
    for r in range(1, n_rings):
        si, mi = ring_start[r], 6 * r
        so, mo = ring_start[r + 1], 6 * (r + 1)
        # walk the outer ring; connect each outer edge to the nearest
        # inner node, and fill the leftover wedges
        inner_of = [int(np.floor(k * mi / mo + 0.5)) % mi
                    for k in range(mo)]
        for k in range(mo):
            k1 = (k + 1) % mo
            a, b = inner_of[k], inner_of[k1]
            elems.append([so + k, so + k1, si + a])
            if a != b:
                elems.append([so + k1, si + b, si + a])
    return FEMesh(nodes=nodes, elems=np.asarray(elems, dtype=np.int32),
                  elem_type="TRI3")


def block_mesh_tri(nx: int, ny: int,
                   x_lo: Tuple[float, float] = (0.0, 0.0),
                   x_up: Tuple[float, float] = (1.0, 1.0)) -> FEMesh:
    """Structured TRI3 rectangle: (nx x ny) quads split into 2 triangles."""
    xs = np.linspace(x_lo[0], x_up[0], nx + 1)
    ys = np.linspace(x_lo[1], x_up[1], ny + 1)
    gx, gy = np.meshgrid(xs, ys, indexing="ij")
    nodes = np.stack([gx.ravel(), gy.ravel()], axis=1)

    def nid(i, j):
        return i * (ny + 1) + j

    elems = []
    for i in range(nx):
        for j in range(ny):
            a, b = nid(i, j), nid(i + 1, j)
            c, d = nid(i + 1, j + 1), nid(i, j + 1)
            elems.append([a, b, c])
            elems.append([a, c, d])
    return FEMesh(nodes=nodes, elems=np.asarray(elems, dtype=np.int32),
                  elem_type="TRI3")


def block_mesh_tet(nx: int, ny: int, nz: int,
                   x_lo=(0.0, 0.0, 0.0), x_up=(1.0, 1.0, 1.0)) -> FEMesh:
    """Structured TET4 box: each hex cell split into 6 tetrahedra."""
    xs = np.linspace(x_lo[0], x_up[0], nx + 1)
    ys = np.linspace(x_lo[1], x_up[1], ny + 1)
    zs = np.linspace(x_lo[2], x_up[2], nz + 1)
    gx, gy, gz = np.meshgrid(xs, ys, zs, indexing="ij")
    nodes = np.stack([gx.ravel(), gy.ravel(), gz.ravel()], axis=1)

    def nid(i, j, k):
        return (i * (ny + 1) + j) * (nz + 1) + k

    # 6-tet (Kuhn) subdivision of the unit cube
    kuhn = [(0, 1, 3, 7), (0, 1, 5, 7), (0, 2, 3, 7),
            (0, 2, 6, 7), (0, 4, 5, 7), (0, 4, 6, 7)]
    elems = []
    for i in range(nx):
        for j in range(ny):
            for k in range(nz):
                corner = [nid(i + a, j + b, k + c)
                          for a in (0, 1) for b in (0, 1) for c in (0, 1)]
                # corner index bit order: a*4 + b*2 + c
                for t in kuhn:
                    elems.append([corner[v] for v in t])
    return FEMesh(nodes=nodes, elems=np.asarray(elems, dtype=np.int32),
                  elem_type="TET4")


def _read_tokens(path: str):
    """Whitespace tokens with Triangle-format '#' comments stripped."""
    with open(path) as f:
        return [t for line in f
                for t in line.split("#", 1)[0].split()]


def read_triangle(node_path: str, ele_path: str) -> FEMesh:
    """Read a mesh in the public Triangle ``.node``/``.ele`` ASCII format
    (the rebuild's analog of the reference's libMesh file readers)."""
    toks = _read_tokens(node_path)
    n_nodes, dim = int(toks[0]), int(toks[1])
    n_attr, n_bdry = int(toks[2]), int(toks[3])
    stride = 1 + dim + n_attr + n_bdry
    body = toks[4:4 + n_nodes * stride]
    first_idx = int(body[0])
    nodes = np.array(
        [[float(body[r * stride + 1 + d]) for d in range(dim)]
         for r in range(n_nodes)])
    toks = _read_tokens(ele_path)
    n_elems, nen = int(toks[0]), int(toks[1])
    n_attr = int(toks[2])
    stride = 1 + nen + n_attr
    body = toks[3:3 + n_elems * stride]
    elems = np.array(
        [[int(body[r * stride + 1 + a]) - first_idx for a in range(nen)]
         for r in range(n_elems)], dtype=np.int32)
    etype = "TRI3" if nen == 3 else "TET4"
    return FEMesh(nodes=nodes, elems=elems, elem_type=etype)


def to_quadratic(mesh: FEMesh) -> FEMesh:
    """Convert a linear simplex mesh to its quadratic family member
    (TRI3 -> TRI6, TET4 -> TET10) by inserting midside nodes — the
    higher-order path of the reference's general element support
    (T16/P17). Shared edges share one midside node."""
    if mesh.elem_type == "TRI3":
        edges = [(0, 1), (1, 2), (2, 0)]
        new_type = "TRI6"
    elif mesh.elem_type == "TET4":
        edges = [(0, 1), (1, 2), (0, 2), (0, 3), (1, 3), (2, 3)]
        new_type = "TET10"
    else:
        raise ValueError(f"to_quadratic: {mesh.elem_type} is not a "
                         "linear simplex type")
    edge_id = {}
    E = mesh.n_elems
    mids = np.zeros((E, len(edges)), dtype=mesh.elems.dtype)
    next_id = mesh.n_nodes
    new_pts = []
    for e in range(E):
        conn = mesh.elems[e]
        for m, (i, j) in enumerate(edges):
            key = (min(conn[i], conn[j]), max(conn[i], conn[j]))
            if key not in edge_id:
                edge_id[key] = next_id
                new_pts.append(0.5 * (mesh.nodes[conn[i]]
                                      + mesh.nodes[conn[j]]))
                next_id += 1
            mids[e, m] = edge_id[key]
    all_nodes = np.concatenate([mesh.nodes, np.asarray(new_pts)], axis=0)
    elems = np.concatenate([mesh.elems, mids], axis=1)
    return FEMesh(nodes=all_nodes, elems=elems, elem_type=new_type)


def to_quadratic_tensor(mesh: FEMesh, serendipity: bool = False
                        ) -> FEMesh:
    """Convert a tensor mesh to its quadratic family member
    (QUAD4 -> QUAD9/QUAD8, HEX8 -> HEX27/HEX20) by inserting edge
    midpoints (shared), plus face centers and the cell center for the
    full (non-serendipity) families — node order matching
    fe.fem's libMesh-convention shape tables (corners, edges[,
    faces, center])."""
    if mesh.elem_type == "QUAD4":
        edges = [(0, 1), (1, 2), (2, 3), (3, 0)]
        faces = []
        new_type = "QUAD8" if serendipity else "QUAD9"
        center_nodes = not serendipity
    elif mesh.elem_type == "HEX8":
        edges = [(0, 1), (1, 2), (2, 3), (3, 0),
                 (0, 4), (1, 5), (2, 6), (3, 7),
                 (4, 5), (5, 6), (6, 7), (7, 4)]
        faces = [(0, 1, 2, 3), (0, 1, 5, 4), (1, 2, 6, 5),
                 (2, 3, 7, 6), (3, 0, 4, 7), (4, 5, 6, 7)]
        new_type = "HEX20" if serendipity else "HEX27"
        center_nodes = not serendipity
        if serendipity:
            faces = []
    else:
        raise ValueError(f"to_quadratic_tensor: {mesh.elem_type} is "
                         "not a linear tensor type")
    E = mesh.n_elems
    next_id = mesh.n_nodes
    new_pts = []
    edge_id = {}
    mids = np.zeros((E, len(edges)), dtype=mesh.elems.dtype)
    for e in range(E):
        conn = mesh.elems[e]
        for m, (i, j) in enumerate(edges):
            key = (min(conn[i], conn[j]), max(conn[i], conn[j]))
            if key not in edge_id:
                edge_id[key] = next_id
                new_pts.append(0.5 * (mesh.nodes[conn[i]]
                                      + mesh.nodes[conn[j]]))
                next_id += 1
            mids[e, m] = edge_id[key]
    cols = [mesh.elems, mids]
    if faces:
        face_id = {}
        fmids = np.zeros((E, len(faces)), dtype=mesh.elems.dtype)
        for e in range(E):
            conn = mesh.elems[e]
            for m, idx in enumerate(faces):
                key = tuple(sorted(int(conn[i]) for i in idx))
                if key not in face_id:
                    face_id[key] = next_id
                    new_pts.append(np.mean(
                        [mesh.nodes[conn[i]] for i in idx], axis=0))
                    next_id += 1
                fmids[e, m] = face_id[key]
        cols.append(fmids)
    if center_nodes:
        centers = np.arange(next_id, next_id + E,
                            dtype=mesh.elems.dtype)[:, None]
        new_pts.extend(np.mean(mesh.nodes[mesh.elems[e]], axis=0)
                       for e in range(E))
        next_id += E
        cols.append(centers)
    all_nodes = np.concatenate([mesh.nodes, np.asarray(new_pts)],
                               axis=0)
    return FEMesh(nodes=all_nodes,
                  elems=np.concatenate(cols, axis=1),
                  elem_type=new_type)


def rect_quad_mesh(nx: int, ny: int,
                   x_lo=(0.0, 0.0), x_up=(1.0, 1.0)) -> FEMesh:
    """Structured QUAD4 mesh of a rectangle."""
    xs = np.linspace(x_lo[0], x_up[0], nx + 1)
    ys = np.linspace(x_lo[1], x_up[1], ny + 1)
    X, Y = np.meshgrid(xs, ys, indexing="ij")
    nodes = np.stack([X.reshape(-1), Y.reshape(-1)], axis=1)
    nid = np.arange((nx + 1) * (ny + 1)).reshape(nx + 1, ny + 1)
    elems = np.stack([nid[:-1, :-1], nid[1:, :-1],
                      nid[1:, 1:], nid[:-1, 1:]],
                     axis=-1).reshape(-1, 4)
    return FEMesh(nodes=nodes, elems=elems.astype(np.int64),
                  elem_type="QUAD4")


def box_hex_mesh(nx: int, ny: int, nz: int,
                 x_lo=(0.0, 0.0, 0.0), x_up=(1.0, 1.0, 1.0)) -> FEMesh:
    """Structured HEX8 mesh of a box."""
    axes = [np.linspace(x_lo[d], x_up[d], n + 1)
            for d, n in enumerate((nx, ny, nz))]
    X, Y, Z = np.meshgrid(*axes, indexing="ij")
    nodes = np.stack([X.reshape(-1), Y.reshape(-1), Z.reshape(-1)],
                     axis=1)
    nid = np.arange(nodes.shape[0]).reshape(nx + 1, ny + 1, nz + 1)
    c = nid[:-1, :-1, :-1]
    elems = np.stack([
        c, nid[1:, :-1, :-1], nid[1:, 1:, :-1], nid[:-1, 1:, :-1],
        nid[:-1, :-1, 1:], nid[1:, :-1, 1:], nid[1:, 1:, 1:],
        nid[:-1, 1:, 1:]], axis=-1).reshape(-1, 8)
    return FEMesh(nodes=nodes, elems=elems.astype(np.int64),
                  elem_type="HEX8")


# --------------------------------------------------------------------------
# Gmsh MSH v2 ASCII import/export (T16 external-geometry path)
# --------------------------------------------------------------------------

# Gmsh element-type id -> (elem_type, nodes-per-element, topological dim).
# Node orderings (Gmsh reference manual §9.3) match this module's
# conventions directly for TRI3/TRI6/TET4/QUAD4/HEX8; TET10 differs in
# the last two midside nodes — Gmsh stores e(2,3) at slot 8 and e(1,3)
# at slot 9, while fem._shape_table's TET10 (libMesh order) wants
# e(1,3) then e(2,3) — so slots 8 and 9 are swapped on read/write.
_GMSH_TYPES = {
    2: ("TRI3", 3, 2),
    3: ("QUAD4", 4, 2),
    4: ("TET4", 4, 3),
    5: ("HEX8", 8, 3),
    9: ("TRI6", 6, 2),
    11: ("TET10", 10, 3),
}
_GMSH_IDS = {v[0]: (k, v[1], v[2]) for k, v in _GMSH_TYPES.items()}
_TET10_GMSH_TO_LIBMESH = [0, 1, 2, 3, 4, 5, 6, 7, 9, 8]


def read_gmsh(path: str, elem_type: str = None) -> FEMesh:
    """Read a Gmsh ``.msh`` v2 ASCII file into an :class:`FEMesh` —
    the rebuild's analog of the reference's libMesh mesh readers
    (``FEDataManager`` geometry input via ``libMesh::ExodusII_IO`` /
    ``GmshIO``, SURVEY.md T16 [U]): user geometries enter the IBFE
    path from a file instead of the programmatic generators.

    Supports the full element menu of :mod:`ibamr_tpu.fe.fem`
    (TRI3/TRI6/QUAD4/TET4/TET10/HEX8). A file may carry several
    element types (boundary lines/faces alongside the solid): the
    reader keeps ``elem_type`` if given, else the highest-dimension
    supported type present (the solid body). Node ids may be
    non-contiguous (Gmsh never guarantees contiguity); they are
    remapped densely and unreferenced nodes are dropped. For 2D
    element types the z column is discarded only when degenerate
    (all ~0); a surface mesh embedded in 3D keeps all three columns
    (spatial dim independent of element dim, as in libMesh).
    """
    with open(path) as f:
        lines = [ln.strip() for ln in f]

    def section(name):
        try:
            a = lines.index(f"${name}") + 1
            b = lines.index(f"$End{name}")
        except ValueError:
            raise ValueError(f"{path}: missing ${name} section "
                             "(is this MSH v2 ASCII?)")
        return lines[a:b]

    fmt = section("MeshFormat")[0].split()
    if not fmt[0].startswith("2"):
        raise ValueError(
            f"{path}: MSH version {fmt[0]} unsupported (need v2 ASCII; "
            "export with `gmsh -format msh2`)")
    if int(fmt[1]) != 0:
        raise ValueError(f"{path}: binary MSH unsupported")

    node_lines = section("Nodes")
    n_nodes = int(node_lines[0])
    ids = np.empty(n_nodes, dtype=np.int64)
    xyz = np.empty((n_nodes, 3), dtype=np.float64)
    for r, ln in enumerate(node_lines[1:1 + n_nodes]):
        t = ln.split()
        ids[r] = int(t[0])
        xyz[r] = [float(t[1]), float(t[2]), float(t[3])]
    id2row = {int(i): r for r, i in enumerate(ids)}

    elem_lines = section("Elements")
    n_elems = int(elem_lines[0])
    by_type = {}
    for ln in elem_lines[1:1 + n_elems]:
        t = ln.split()
        gtype = int(t[1])
        if gtype not in _GMSH_TYPES:
            continue                      # points/lines/unsupported
        name, nen, _ = _GMSH_TYPES[gtype]
        ntags = int(t[2])
        conn = [id2row[int(v)] for v in t[3 + ntags:3 + ntags + nen]]
        by_type.setdefault(name, []).append(conn)
    if not by_type:
        raise ValueError(f"{path}: no supported volume/surface elements")

    if elem_type is None:
        elem_type = max(by_type, key=lambda k: (_GMSH_IDS[k][2],
                                                len(by_type[k])))
    if elem_type not in by_type:
        raise ValueError(f"{path}: no {elem_type} elements "
                         f"(found {sorted(by_type)})")
    elems = np.asarray(by_type[elem_type], dtype=np.int64)
    if elem_type == "TET10":
        elems = elems[:, _TET10_GMSH_TO_LIBMESH]

    # drop nodes not referenced by the kept element block (the file may
    # carry boundary-only nodes or other-dimension blocks); remap
    # connectivity densely
    used = np.unique(elems)
    dim = _GMSH_IDS[elem_type][2]
    # Spatial dim is independent of element dim (libMesh semantics): a
    # TRI3/TRI6 shell CURVED through 3D (codim-1 IBFE surface) must
    # keep its z column. A planar sheet — z constant across the nodes
    # this block references, whether at z=0, an offset plane, or with
    # CAD-transform roundoff — stays a 2D solid (the volumetric FE
    # path needs square Jacobians). Spread is measured against the
    # mesh extent so roundoff-level z noise never promotes.
    if dim == 2:
        zs = xyz[used, 2]
        extent = max(1.0, float(np.ptp(xyz[used], axis=0).max()))
        if float(np.ptp(zs)) > 1e-9 * extent:
            dim = 3
    nodes = xyz[:, :dim]
    remap = -np.ones(nodes.shape[0], dtype=np.int64)
    remap[used] = np.arange(used.size)
    return FEMesh(nodes=nodes[used], elems=remap[elems],
                  elem_type=elem_type)


def write_gmsh(mesh: FEMesh, path: str) -> None:
    """Write an :class:`FEMesh` as Gmsh ``.msh`` v2 ASCII (round-trip
    partner of :func:`read_gmsh`; also lets generated meshes feed any
    external Gmsh-reading tool)."""
    gtype, nen, _ = _GMSH_IDS[mesh.elem_type]
    elems = mesh.elems
    if mesh.elem_type == "TET10":
        inv = np.argsort(_TET10_GMSH_TO_LIBMESH)
        elems = elems[:, inv]
    with open(path, "w") as f:
        f.write("$MeshFormat\n2.2 0 8\n$EndMeshFormat\n")
        f.write(f"$Nodes\n{mesh.n_nodes}\n")
        for i, p in enumerate(mesh.nodes):
            x, y = p[0], p[1]
            z = p[2] if mesh.dim == 3 else 0.0
            f.write(f"{i + 1} {x:.17g} {y:.17g} {z:.17g}\n")
        f.write("$EndNodes\n")
        f.write(f"$Elements\n{mesh.n_elems}\n")
        for e, conn in enumerate(elems):
            nodes = " ".join(str(int(v) + 1) for v in conn)
            f.write(f"{e + 1} {gtype} 2 0 0 {nodes}\n")
        f.write("$EndElements\n")
