"""Finite-element meshes for the IBFE structure path.

Reference parity: the *used surface* of libMesh in ``IBFEMethod`` /
``FEDataManager`` (P17/T16, SURVEY.md §2) — a nodal mesh of linear
simplex elements carrying the Lagrangian solid. The reference links
libMesh; the rebuild keeps the mesh as plain arrays (nodes, connectivity)
built host-side with NumPy, because everything the device ever touches is
the precomputed quadrature tables in :mod:`ibamr_tpu.fe.fem`
(SURVEY.md §7.3 hard-part #6: FE reference-configuration quantities are
host precompute, only per-step kinematics run on TPU).

Element types: TRI3 (2D solids) and TET4 (3D solids), both linear
simplices — the element family the IBFE acceptance config uses.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np


@dataclass
class FEMesh:
    """Nodal mesh of one linear-simplex element type.

    nodes: (n_nodes, dim) float reference coordinates
    elems: (n_elems, nen) int connectivity (nen = dim + 1)
    elem_type: "TRI3" | "TET4"
    """
    nodes: np.ndarray
    elems: np.ndarray
    elem_type: str

    @property
    def dim(self) -> int:
        return self.nodes.shape[1]

    @property
    def n_nodes(self) -> int:
        return self.nodes.shape[0]

    @property
    def n_elems(self) -> int:
        return self.elems.shape[0]

    def volume(self) -> float:
        """Total reference measure (area in 2D, volume in 3D)."""
        X = self.nodes[self.elems]          # (E, nen, dim)
        edges = X[:, 1:, :] - X[:, :1, :]   # (E, dim, dim)
        det = np.linalg.det(edges)
        fact = 2.0 if self.elem_type == "TRI3" else 6.0
        return float(np.sum(np.abs(det)) / fact)


def disc_mesh(radius: float = 0.25,
              center: Tuple[float, float] = (0.5, 0.5),
              n_rings: int = 8) -> FEMesh:
    """Unstructured TRI3 disc: a center node plus ``n_rings`` concentric
    rings; ring r holds ``6r`` nodes (hex-like layout keeps triangles
    well-shaped). The standard IBFE-ex0-style solid body."""
    nodes = [np.array(center, dtype=np.float64)]
    ring_start = [0]
    for r in range(1, n_rings + 1):
        ring_start.append(len(nodes))
        m = 6 * r
        th = 2.0 * np.pi * np.arange(m) / m
        rr = radius * r / n_rings
        for t in th:
            nodes.append(np.array([center[0] + rr * np.cos(t),
                                   center[1] + rr * np.sin(t)]))
    nodes = np.stack(nodes, axis=0)

    elems = []
    # inner fan: center to ring 1 (6 nodes)
    s1 = ring_start[1]
    for k in range(6):
        elems.append([0, s1 + k, s1 + (k + 1) % 6])
    # strips between ring r (6r nodes) and ring r+1 (6(r+1) nodes)
    for r in range(1, n_rings):
        si, mi = ring_start[r], 6 * r
        so, mo = ring_start[r + 1], 6 * (r + 1)
        # walk the outer ring; connect each outer edge to the nearest
        # inner node, and fill the leftover wedges
        inner_of = [int(np.floor(k * mi / mo + 0.5)) % mi
                    for k in range(mo)]
        for k in range(mo):
            k1 = (k + 1) % mo
            a, b = inner_of[k], inner_of[k1]
            elems.append([so + k, so + k1, si + a])
            if a != b:
                elems.append([so + k1, si + b, si + a])
    return FEMesh(nodes=nodes, elems=np.asarray(elems, dtype=np.int32),
                  elem_type="TRI3")


def block_mesh_tri(nx: int, ny: int,
                   x_lo: Tuple[float, float] = (0.0, 0.0),
                   x_up: Tuple[float, float] = (1.0, 1.0)) -> FEMesh:
    """Structured TRI3 rectangle: (nx x ny) quads split into 2 triangles."""
    xs = np.linspace(x_lo[0], x_up[0], nx + 1)
    ys = np.linspace(x_lo[1], x_up[1], ny + 1)
    gx, gy = np.meshgrid(xs, ys, indexing="ij")
    nodes = np.stack([gx.ravel(), gy.ravel()], axis=1)

    def nid(i, j):
        return i * (ny + 1) + j

    elems = []
    for i in range(nx):
        for j in range(ny):
            a, b = nid(i, j), nid(i + 1, j)
            c, d = nid(i + 1, j + 1), nid(i, j + 1)
            elems.append([a, b, c])
            elems.append([a, c, d])
    return FEMesh(nodes=nodes, elems=np.asarray(elems, dtype=np.int32),
                  elem_type="TRI3")


def block_mesh_tet(nx: int, ny: int, nz: int,
                   x_lo=(0.0, 0.0, 0.0), x_up=(1.0, 1.0, 1.0)) -> FEMesh:
    """Structured TET4 box: each hex cell split into 6 tetrahedra."""
    xs = np.linspace(x_lo[0], x_up[0], nx + 1)
    ys = np.linspace(x_lo[1], x_up[1], ny + 1)
    zs = np.linspace(x_lo[2], x_up[2], nz + 1)
    gx, gy, gz = np.meshgrid(xs, ys, zs, indexing="ij")
    nodes = np.stack([gx.ravel(), gy.ravel(), gz.ravel()], axis=1)

    def nid(i, j, k):
        return (i * (ny + 1) + j) * (nz + 1) + k

    # 6-tet (Kuhn) subdivision of the unit cube
    kuhn = [(0, 1, 3, 7), (0, 1, 5, 7), (0, 2, 3, 7),
            (0, 2, 6, 7), (0, 4, 5, 7), (0, 4, 6, 7)]
    elems = []
    for i in range(nx):
        for j in range(ny):
            for k in range(nz):
                corner = [nid(i + a, j + b, k + c)
                          for a in (0, 1) for b in (0, 1) for c in (0, 1)]
                # corner index bit order: a*4 + b*2 + c
                for t in kuhn:
                    elems.append([corner[v] for v in t])
    return FEMesh(nodes=nodes, elems=np.asarray(elems, dtype=np.int32),
                  elem_type="TET4")


def _read_tokens(path: str):
    """Whitespace tokens with Triangle-format '#' comments stripped."""
    with open(path) as f:
        return [t for line in f
                for t in line.split("#", 1)[0].split()]


def read_triangle(node_path: str, ele_path: str) -> FEMesh:
    """Read a mesh in the public Triangle ``.node``/``.ele`` ASCII format
    (the rebuild's analog of the reference's libMesh file readers)."""
    toks = _read_tokens(node_path)
    n_nodes, dim = int(toks[0]), int(toks[1])
    n_attr, n_bdry = int(toks[2]), int(toks[3])
    stride = 1 + dim + n_attr + n_bdry
    body = toks[4:4 + n_nodes * stride]
    first_idx = int(body[0])
    nodes = np.array(
        [[float(body[r * stride + 1 + d]) for d in range(dim)]
         for r in range(n_nodes)])
    toks = _read_tokens(ele_path)
    n_elems, nen = int(toks[0]), int(toks[1])
    n_attr = int(toks[2])
    stride = 1 + nen + n_attr
    body = toks[3:3 + n_elems * stride]
    elems = np.array(
        [[int(body[r * stride + 1 + a]) - first_idx for a in range(nen)]
         for r in range(n_elems)], dtype=np.int32)
    etype = "TRI3" if nen == 3 else "TET4"
    return FEMesh(nodes=nodes, elems=elems, elem_type=etype)
