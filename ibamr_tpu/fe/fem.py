"""FE kinematics, hyperelastic energies, and nodal force assembly.

Reference parity: the mechanics core of ``IBFEMethod`` (P17) +
``FEDataManager`` (T16): deformation gradient at quadrature points, a
first-Piola-Kirchhoff (PK1) stress from a strain-energy density, and the
weak-form nodal force  F_a = -sum_q w_q P(FF_q) dN_a/dX(q).

TPU-first redesign: the reference assembles PK1 element loops in C++ and
projects through libMesh; here the total elastic energy

    E(x) = sum_elems sum_q  w_q * W(FF(x))

is a pure jitted function of the nodal positions and the nodal force is
literally ``-jax.grad(E)`` — exactly the weak-form assembly (PK1 = dW/dFF
falls out of the chain rule), with consistency guaranteed by construction
and the whole thing fused by XLA into the coupled IB step. An explicit
PK1-assembly path is kept for parity and as a cross-check oracle.

All reference-configuration tables (shape gradients dN/dX, quadrature
measures w*dV, lumped mass) are host-precomputed once per mesh
(SURVEY.md §7.3 hard-part #6); only current-configuration kinematics run
per step.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.fe.mesh import FEMesh

# -- reference elements (linear simplices) ----------------------------------

# shape functions at barycentric-style local coords; rows = quad points
_TRI3_QP = np.array([[1 / 6, 1 / 6], [2 / 3, 1 / 6], [1 / 6, 2 / 3]])
_TRI3_QW = np.array([1 / 6, 1 / 6, 1 / 6])          # ref-triangle area 1/2
_TET4_A, _TET4_B = 0.5854101966249685, 0.1381966011250105
_TET4_QP = np.array([[_TET4_B, _TET4_B, _TET4_B],
                     [_TET4_A, _TET4_B, _TET4_B],
                     [_TET4_B, _TET4_A, _TET4_B],
                     [_TET4_B, _TET4_B, _TET4_A]])
_TET4_QW = np.array([1 / 24] * 4)                   # ref-tet volume 1/6


def _shape_table(elem_type: str):
    """(N(q,a), dN/dxi(a,d), qp weights) for the reference element."""
    if elem_type == "TRI3":
        qp, qw = _TRI3_QP, _TRI3_QW
        N = np.stack([1.0 - qp[:, 0] - qp[:, 1], qp[:, 0], qp[:, 1]], axis=1)
        dN = np.array([[-1.0, -1.0], [1.0, 0.0], [0.0, 1.0]])
    elif elem_type == "TET4":
        qp, qw = _TET4_QP, _TET4_QW
        N = np.stack([1.0 - qp.sum(axis=1), qp[:, 0], qp[:, 1], qp[:, 2]],
                     axis=1)
        dN = np.array([[-1.0, -1.0, -1.0], [1.0, 0.0, 0.0],
                       [0.0, 1.0, 0.0], [0.0, 0.0, 1.0]])
    else:
        raise ValueError(f"unknown element type {elem_type!r}")
    return N, dN, qw


class FEAssembly(NamedTuple):
    """Device-resident reference-configuration tables for one mesh."""
    elems: jnp.ndarray     # (E, nen) int32 connectivity
    shape: jnp.ndarray     # (nq, nen) shape values at quad points
    dNdX: jnp.ndarray      # (E, nen, dim) reference shape gradients
    wdV: jnp.ndarray       # (E, nq) quadrature weight * |detJ|
    lumped_mass: jnp.ndarray  # (n_nodes,) sum_q wdV * N_a  (unit density)
    n_nodes: int
    dim: int


def build_assembly(mesh: FEMesh, dtype=jnp.float32) -> FEAssembly:
    N, dN, qw = _shape_table(mesh.elem_type)
    Xe = mesh.nodes[mesh.elems]                      # (E, nen, dim)
    # J_ij = dX_i/dxi_j  (constant per linear simplex)
    J = np.einsum("ad,eai->eid", dN, Xe)             # (E, dim, dim)
    detJ = np.linalg.det(J)
    Jinv = np.linalg.inv(J)
    dNdX = np.einsum("ad,edi->eai", dN, Jinv)        # (E, nen, dim)
    wdV = np.abs(detJ)[:, None] * qw[None, :]        # (E, nq)

    n_nodes = mesh.n_nodes
    mass = np.zeros(n_nodes)
    contrib = np.einsum("eq,qa->ea", wdV, N)         # (E, nen)
    np.add.at(mass, mesh.elems, contrib)

    return FEAssembly(
        elems=jnp.asarray(mesh.elems, dtype=jnp.int32),
        shape=jnp.asarray(N, dtype=dtype),
        dNdX=jnp.asarray(dNdX, dtype=dtype),
        wdV=jnp.asarray(wdV, dtype=dtype),
        lumped_mass=jnp.asarray(mass, dtype=dtype),
        n_nodes=n_nodes, dim=mesh.dim)


# -- kinematics --------------------------------------------------------------

def deformation_gradients(asm: FEAssembly, x: jnp.ndarray) -> jnp.ndarray:
    """FF_e = dx/dX per element (constant for linear simplices) -> (E, dim, dim)."""
    xe = x[asm.elems]                                # (E, nen, dim)
    return jnp.einsum("eai,eaj->eij", xe, asm.dNdX)


# -- strain-energy densities (W: FF -> scalar) -------------------------------

def _log_ext(J, eps: float = 1e-4):
    """log(J) with a C1 linear extension below ``eps``: near/through
    element inversion the volumetric terms keep a large (1/eps-slope)
    restoring force instead of a clamped-to-zero gradient."""
    return jnp.where(J > eps, jnp.log(jnp.maximum(J, eps)),
                     jnp.log(eps) + (J - eps) / eps)


def neo_hookean(mu: float, lam: float) -> Callable:
    """Compressible neo-Hookean, the IBFE-ex0-style material:
    W = mu/2 (I1 - d) - mu ln J + lam/2 (ln J)^2."""
    def W(FF):
        d = FF.shape[-1]
        J = jnp.linalg.det(FF)
        logJ = _log_ext(J)
        I1 = jnp.einsum("...ij,...ij->...", FF, FF)
        return 0.5 * mu * (I1 - d) - mu * logJ + 0.5 * lam * logJ ** 2
    return W


def stvk(mu: float, lam: float) -> Callable:
    """St. Venant-Kirchhoff: W = mu tr(EE^2) + lam/2 (tr EE)^2,
    EE = (FF^T FF - I)/2."""
    def W(FF):
        d = FF.shape[-1]
        C = jnp.einsum("...ki,...kj->...ij", FF, FF)
        E = 0.5 * (C - jnp.eye(d, dtype=FF.dtype))
        trE = jnp.trace(E, axis1=-2, axis2=-1)
        return mu * jnp.einsum("...ij,...ij->...", E, E) + 0.5 * lam * trE ** 2
    return W


def pk1(W: Callable) -> Callable:
    """PK1 stress P = dW/dFF (vectorized over leading axes)."""
    return jax.grad(lambda FF: jnp.sum(W(FF)))


# -- force assembly ----------------------------------------------------------

def elastic_energy(asm: FEAssembly, W: Callable, x: jnp.ndarray):
    """E(x) = sum_e sum_q wdV * W(FF_e). Linear simplices: FF constant per
    element, so per-element energy is W(FF_e) * sum_q wdV."""
    FF = deformation_gradients(asm, x)
    return jnp.sum(W(FF) * jnp.sum(asm.wdV, axis=1))


def nodal_forces(asm: FEAssembly, W: Callable, x: jnp.ndarray) -> jnp.ndarray:
    """Weak-form nodal elastic force F = -dE/dx -> (n_nodes, dim)."""
    return -jax.grad(lambda xx: elastic_energy(asm, W, xx))(x)


def nodal_forces_pk1(asm: FEAssembly, W: Callable,
                     x: jnp.ndarray) -> jnp.ndarray:
    """Explicit PK1 assembly F_a = -sum_e sum_q wdV P(FF) dN_a/dX — the
    reference's element-loop form; must equal :func:`nodal_forces`."""
    FF = deformation_gradients(asm, x)
    P = pk1(W)(FF)                                   # (E, dim, dim)
    vol = jnp.sum(asm.wdV, axis=1)                   # (E,)
    Fe = -jnp.einsum("e,eij,eaj->eai", vol, P, asm.dNdX)  # (E, nen, dim)
    out = jnp.zeros((asm.n_nodes, asm.dim), dtype=x.dtype)
    return out.at[asm.elems.reshape(-1)].add(
        Fe.reshape(-1, asm.dim))


# -- quadrature-point utilities (the "unified" coupling scheme) --------------

def quad_positions(asm: FEAssembly, x: jnp.ndarray) -> jnp.ndarray:
    """Current positions of all quadrature points -> (E*nq, dim)."""
    xe = x[asm.elems]                                # (E, nen, dim)
    xq = jnp.einsum("qa,eai->eqi", asm.shape, xe)
    return xq.reshape(-1, asm.dim)

def project_to_quads(asm: FEAssembly, nodal: jnp.ndarray) -> jnp.ndarray:
    """Evaluate a nodal field at quadrature points -> (E*nq, ...)."""
    ne = nodal[asm.elems]                            # (E, nen, ...)
    nq = jnp.einsum("qa,ea...->eq...", asm.shape, ne)
    return nq.reshape((-1,) + nodal.shape[1:])


def l2_project_from_quads(asm: FEAssembly, vals: jnp.ndarray) -> jnp.ndarray:
    """Lumped-mass L2 projection of quad-point values to nodes:
    N_a-weighted quadrature sum divided by the lumped mass — the rebuild's
    ``FEDataManager::buildL2ProjectionSolver`` (T16) with mass lumping."""
    E, nq = asm.wdV.shape
    v = vals.reshape((E, nq) + vals.shape[1:])
    contrib = jnp.einsum("eq,qa,eq...->ea...", asm.wdV, asm.shape, v)
    out = jnp.zeros((asm.n_nodes,) + vals.shape[1:], dtype=vals.dtype)
    out = out.at[asm.elems.reshape(-1)].add(
        contrib.reshape((-1,) + vals.shape[1:]))
    shape = (asm.n_nodes,) + (1,) * (vals.ndim - 1)
    return out / safe_lumped_mass(asm).reshape(shape)


def safe_lumped_mass(asm: FEAssembly) -> jnp.ndarray:
    """Lumped mass with zeros (nodes unreferenced by any element — legal
    in external Triangle meshes) replaced by 1 so divisions stay finite;
    such nodes carry no load either way."""
    return jnp.where(asm.lumped_mass > 0, asm.lumped_mass,
                     jnp.ones_like(asm.lumped_mass))
