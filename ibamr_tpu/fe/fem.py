"""FE kinematics, hyperelastic energies, and nodal force assembly.

Reference parity: the mechanics core of ``IBFEMethod`` (P17) +
``FEDataManager`` (T16): deformation gradient at quadrature points, a
first-Piola-Kirchhoff (PK1) stress from a strain-energy density, and the
weak-form nodal force  F_a = -sum_q w_q P(FF_q) dN_a/dX(q).

TPU-first redesign: the reference assembles PK1 element loops in C++ and
projects through libMesh; here the total elastic energy

    E(x) = sum_elems sum_q  w_q * W(FF(x))

is a pure jitted function of the nodal positions and the nodal force is
literally ``-jax.grad(E)`` — exactly the weak-form assembly (PK1 = dW/dFF
falls out of the chain rule), with consistency guaranteed by construction
and the whole thing fused by XLA into the coupled IB step. An explicit
PK1-assembly path is kept for parity and as a cross-check oracle.

All reference-configuration tables (shape gradients dN/dX, quadrature
measures w*dV, lumped mass) are host-precomputed once per mesh
(SURVEY.md §7.3 hard-part #6); only current-configuration kinematics run
per step.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import math

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.fe.mesh import FEMesh

# -- reference elements (linear simplices) ----------------------------------

# shape functions at barycentric-style local coords; rows = quad points
_TRI3_QP = np.array([[1 / 6, 1 / 6], [2 / 3, 1 / 6], [1 / 6, 2 / 3]])
_TRI3_QW = np.array([1 / 6, 1 / 6, 1 / 6])          # ref-triangle area 1/2
_TET4_A, _TET4_B = 0.5854101966249685, 0.1381966011250105
_TET4_QP = np.array([[_TET4_B, _TET4_B, _TET4_B],
                     [_TET4_A, _TET4_B, _TET4_B],
                     [_TET4_B, _TET4_A, _TET4_B],
                     [_TET4_B, _TET4_B, _TET4_A]])
_TET4_QW = np.array([1 / 24] * 4)                   # ref-tet volume 1/6


def _tri6_shapes(qp):
    """Quadratic triangle (libMesh TRI6 edge order 3:(0,1) 4:(1,2)
    5:(2,0)); barycentric L = (1-xi-eta, xi, eta)."""
    xi, eta = qp[:, 0], qp[:, 1]
    L = np.stack([1.0 - xi - eta, xi, eta], axis=1)          # (nq, 3)
    N = np.concatenate([L * (2.0 * L - 1.0),
                        np.stack([4 * L[:, 0] * L[:, 1],
                                  4 * L[:, 1] * L[:, 2],
                                  4 * L[:, 2] * L[:, 0]], axis=1)],
                       axis=1)                               # (nq, 6)
    dL = np.array([[-1.0, -1.0], [1.0, 0.0], [0.0, 1.0]])   # (3, 2)
    dN = np.zeros((qp.shape[0], 6, 2))
    for a in range(3):
        dN[:, a, :] = (4.0 * L[:, a, None] - 1.0) * dL[a]
    edges = [(0, 1), (1, 2), (2, 0)]
    for m, (i, j) in enumerate(edges):
        dN[:, 3 + m, :] = 4.0 * (L[:, i, None] * dL[j]
                                 + L[:, j, None] * dL[i])
    return N, dN


def _tet10_shapes(qp):
    """Quadratic tetrahedron (libMesh TET10 edge order 4:(0,1) 5:(1,2)
    6:(0,2) 7:(0,3) 8:(1,3) 9:(2,3))."""
    xi, eta, ze = qp[:, 0], qp[:, 1], qp[:, 2]
    L = np.stack([1.0 - xi - eta - ze, xi, eta, ze], axis=1)
    edges = [(0, 1), (1, 2), (0, 2), (0, 3), (1, 3), (2, 3)]
    N = np.concatenate(
        [L * (2.0 * L - 1.0),
         np.stack([4 * L[:, i] * L[:, j] for i, j in edges], axis=1)],
        axis=1)                                              # (nq, 10)
    dL = np.array([[-1.0, -1.0, -1.0], [1.0, 0.0, 0.0],
                   [0.0, 1.0, 0.0], [0.0, 0.0, 1.0]])
    dN = np.zeros((qp.shape[0], 10, 3))
    for a in range(4):
        dN[:, a, :] = (4.0 * L[:, a, None] - 1.0) * dL[a]
    for m, (i, j) in enumerate(edges):
        dN[:, 4 + m, :] = 4.0 * (L[:, i, None] * dL[j]
                                 + L[:, j, None] * dL[i])
    return N, dN


def _tensor_shapes(qp, dim):
    """Bi/tri-linear tensor element (QUAD4 / HEX8), nodes in the
    standard counterclockwise / bottom-then-top order on [-1, 1]^dim."""
    if dim == 2:
        corners = np.array([[-1, -1], [1, -1], [1, 1], [-1, 1]])
    else:
        corners = np.array([[-1, -1, -1], [1, -1, -1], [1, 1, -1],
                            [-1, 1, -1], [-1, -1, 1], [1, -1, 1],
                            [1, 1, 1], [-1, 1, 1]])
    nq, nen = qp.shape[0], corners.shape[0]
    N = np.ones((nq, nen))
    dN = np.zeros((nq, nen, dim))
    for a in range(nen):
        facs = [(1.0 + corners[a, d] * qp[:, d]) / 2.0
                for d in range(dim)]
        for d in range(dim):
            N[:, a] *= facs[d]
            dfac = corners[a, d] / 2.0 * np.ones(nq)
            dN[:, a, d] = dfac
            for d2 in range(dim):
                if d2 != d:
                    dN[:, a, d] *= facs[d2]
    return N, dN


def _gauss_1d():
    g = 1.0 / math.sqrt(3.0)
    return np.array([-g, g]), np.array([1.0, 1.0])


def _gauss_1d_n(n: int):
    """n-point Gauss-Legendre rule on [-1, 1]."""
    return np.polynomial.legendre.leggauss(n)


# node coordinate tables for the quadratic tensor families, libMesh
# ordering: corners, then edge midpoints, then (QUAD9/HEX27) face
# centers and the cell center. HEX edges: 4 bottom, 4 vertical, 4 top;
# HEX27 faces: bottom, front, right, back, left, top.
_QUAD_CORNERS = [(-1, -1), (1, -1), (1, 1), (-1, 1)]
_QUAD_EDGES = [(0, -1), (1, 0), (0, 1), (-1, 0)]
_QUAD9_NODES = _QUAD_CORNERS + _QUAD_EDGES + [(0, 0)]
_HEX_CORNERS = [(-1, -1, -1), (1, -1, -1), (1, 1, -1), (-1, 1, -1),
                (-1, -1, 1), (1, -1, 1), (1, 1, 1), (-1, 1, 1)]
_HEX_EDGES = [(0, -1, -1), (1, 0, -1), (0, 1, -1), (-1, 0, -1),
              (-1, -1, 0), (1, -1, 0), (1, 1, 0), (-1, 1, 0),
              (0, -1, 1), (1, 0, 1), (0, 1, 1), (-1, 0, 1)]
_HEX_FACES = [(0, 0, -1), (0, -1, 0), (1, 0, 0), (0, 1, 0),
              (-1, 0, 0), (0, 0, 1)]
_HEX27_NODES = _HEX_CORNERS + _HEX_EDGES + _HEX_FACES + [(0, 0, 0)]


def _lagrange3(c, t):
    """Quadratic 1D Lagrange basis value/derivative for node c in
    {-1, 0, 1} at coordinates t."""
    if c == -1:
        return 0.5 * t * (t - 1.0), t - 0.5
    if c == 0:
        return 1.0 - t * t, -2.0 * t
    return 0.5 * t * (t + 1.0), t + 0.5


def _tensor_quadratic_shapes(qp, nodes):
    """Full quadratic tensor element (QUAD9 / HEX27): N_a = prod_d
    L_{c_a[d]}(xi_d) with quadratic 1D Lagrange factors."""
    nq = qp.shape[0]
    dim = qp.shape[1]
    nen = len(nodes)
    N = np.ones((nq, nen))
    dN = np.zeros((nq, nen, dim))
    for a, cs in enumerate(nodes):
        vals, ders = zip(*[_lagrange3(cs[d], qp[:, d])
                           for d in range(dim)])
        for d in range(dim):
            N[:, a] *= vals[d]
            g = ders[d].copy()
            for d2 in range(dim):
                if d2 != d:
                    g = g * vals[d2]
            dN[:, a, d] = g
    return N, dN


def _serendipity_shapes(qp, dim):
    """Serendipity quadratic element (QUAD8 / HEX20): corner + edge
    midside nodes only (the classic 8/20-node formulas)."""
    nq = qp.shape[0]
    if dim == 2:
        xi, eta = qp[:, 0], qp[:, 1]
        N = np.zeros((nq, 8))
        dN = np.zeros((nq, 8, 2))
        for a, (xa, ya) in enumerate(_QUAD_CORNERS):
            f, g = 1.0 + xa * xi, 1.0 + ya * eta
            h = xa * xi + ya * eta - 1.0
            N[:, a] = f * g * h / 4.0
            dN[:, a, 0] = xa * g * (h + f) / 4.0
            dN[:, a, 1] = ya * f * (h + g) / 4.0
        for m, (xa, ya) in enumerate(_QUAD_EDGES):
            a = 4 + m
            if xa == 0:
                g = 1.0 + ya * eta
                N[:, a] = (1.0 - xi * xi) * g / 2.0
                dN[:, a, 0] = -xi * g
                dN[:, a, 1] = (1.0 - xi * xi) * ya / 2.0
            else:
                f = 1.0 + xa * xi
                N[:, a] = f * (1.0 - eta * eta) / 2.0
                dN[:, a, 0] = xa * (1.0 - eta * eta) / 2.0
                dN[:, a, 1] = -eta * f
        return N, dN
    xi, eta, ze = qp[:, 0], qp[:, 1], qp[:, 2]
    N = np.zeros((nq, 20))
    dN = np.zeros((nq, 20, 3))
    for a, (xa, ya, za) in enumerate(_HEX_CORNERS):
        f, g, e = 1.0 + xa * xi, 1.0 + ya * eta, 1.0 + za * ze
        h = xa * xi + ya * eta + za * ze - 2.0
        N[:, a] = f * g * e * h / 8.0
        dN[:, a, 0] = xa * g * e * (h + f) / 8.0
        dN[:, a, 1] = ya * f * e * (h + g) / 8.0
        dN[:, a, 2] = za * f * g * (h + e) / 8.0
    for m, (xa, ya, za) in enumerate(_HEX_EDGES):
        a = 8 + m
        if xa == 0:
            g, e = 1.0 + ya * eta, 1.0 + za * ze
            N[:, a] = (1.0 - xi * xi) * g * e / 4.0
            dN[:, a, 0] = -2.0 * xi * g * e / 4.0
            dN[:, a, 1] = (1.0 - xi * xi) * ya * e / 4.0
            dN[:, a, 2] = (1.0 - xi * xi) * g * za / 4.0
        elif ya == 0:
            f, e = 1.0 + xa * xi, 1.0 + za * ze
            N[:, a] = f * (1.0 - eta * eta) * e / 4.0
            dN[:, a, 0] = xa * (1.0 - eta * eta) * e / 4.0
            dN[:, a, 1] = -2.0 * eta * f * e / 4.0
            dN[:, a, 2] = f * (1.0 - eta * eta) * za / 4.0
        else:
            f, g = 1.0 + xa * xi, 1.0 + ya * eta
            N[:, a] = f * g * (1.0 - ze * ze) / 4.0
            dN[:, a, 0] = xa * g * (1.0 - ze * ze) / 4.0
            dN[:, a, 1] = f * ya * (1.0 - ze * ze) / 4.0
            dN[:, a, 2] = -2.0 * ze * f * g / 4.0
    return N, dN


def _tensor_gauss(dim: int, npts: int):
    g, w = _gauss_1d_n(npts)
    grids = np.meshgrid(*([g] * dim), indexing="ij")
    qp = np.stack([c.reshape(-1) for c in grids], axis=1)
    wgrids = np.meshgrid(*([w] * dim), indexing="ij")
    qw = np.ones(qp.shape[0])
    for c in wgrids:
        qw = qw * c.reshape(-1)
    return qp, qw


def _rule_weights(elem_type: str):
    """Quadrature weights of the standard (stiffness) rule."""
    if elem_type in ("TRI3", "TRI6"):
        return _TRI3_QW
    if elem_type in ("TET4", "TET10"):
        return _TET4_QW
    dim = 2 if elem_type.startswith("QUAD") else 3
    n = 2 if elem_type in ("QUAD4", "HEX8") else 3
    return _tensor_gauss(dim, n)[1]


def _shape_table(elem_type: str):
    """(N (nq, nen), dN/dxi (nq, nen, dim), qp weights (nq,)) for the
    reference element at the standard stiffness rule — one dispatch
    (:func:`_shapes_at`) serves both this and the adaptive transfer
    rules, so a family's formulas exist exactly once."""
    qp = _rule_points(elem_type)
    N, dN = _shapes_at(elem_type, qp)
    return N, dN, _rule_weights(elem_type)


def _shapes_at(elem_type: str, qp: "np.ndarray"):
    """(N, dN/dxi) of any family at ARBITRARY reference points — the
    generalization of :func:`_shape_table` the adaptive transfer
    quadrature needs (evaluate the element anywhere, not only at the
    stiffness rule's points)."""
    if elem_type == "TRI3":
        N = np.stack([1.0 - qp[:, 0] - qp[:, 1], qp[:, 0], qp[:, 1]],
                     axis=1)
        dN1 = np.array([[-1.0, -1.0], [1.0, 0.0], [0.0, 1.0]])
        return N, np.broadcast_to(dN1, (qp.shape[0],)
                                  + dN1.shape).copy()
    if elem_type == "TET4":
        N = np.stack([1.0 - qp.sum(axis=1), qp[:, 0], qp[:, 1],
                      qp[:, 2]], axis=1)
        dN1 = np.array([[-1.0, -1.0, -1.0], [1.0, 0.0, 0.0],
                        [0.0, 1.0, 0.0], [0.0, 0.0, 1.0]])
        return N, np.broadcast_to(dN1, (qp.shape[0],)
                                  + dN1.shape).copy()
    if elem_type == "TRI6":
        return _tri6_shapes(qp)
    if elem_type == "TET10":
        return _tet10_shapes(qp)
    if elem_type in ("QUAD4", "HEX8"):
        return _tensor_shapes(qp, 2 if elem_type == "QUAD4" else 3)
    if elem_type == "QUAD9":
        return _tensor_quadratic_shapes(qp, _QUAD9_NODES)
    if elem_type == "HEX27":
        return _tensor_quadratic_shapes(qp, _HEX27_NODES)
    if elem_type in ("QUAD8", "HEX20"):
        return _serendipity_shapes(qp, 2 if elem_type == "QUAD8"
                                   else 3)
    raise ValueError(f"unknown element type {elem_type!r}")


def _subdivide_simplex(verts, level: int):
    """Uniform midpoint subdivision of a reference simplex, returning
    the list of sub-simplex vertex arrays (4^level triangles /
    8^level tets, all of equal measure)."""
    sims = [np.asarray(verts, dtype=float)]
    dim = sims[0].shape[1]
    for _ in range(level):
        nxt = []
        for s in sims:
            if dim == 2:
                a, b, c = s
                ab, bc, ca = (a + b) / 2, (b + c) / 2, (c + a) / 2
                nxt += [np.stack(t) for t in
                        ((a, ab, ca), (ab, b, bc), (ca, bc, c),
                         (ab, bc, ca))]
            else:
                a, b, c, d = s
                ab, ac, ad = (a + b) / 2, (a + c) / 2, (a + d) / 2
                bc, bd, cd = (b + c) / 2, (b + d) / 2, (c + d) / 2
                nxt += [np.stack(t) for t in
                        ((a, ab, ac, ad), (ab, b, bc, bd),
                         (ac, bc, c, cd), (ad, bd, cd, d),
                         (ab, ac, ad, bd), (ab, ac, bc, bd),
                         (ac, ad, bd, cd), (ac, bc, bd, cd))]
        sims = nxt
    return sims


def transfer_quadrature(elem_type: str, level: int = 0):
    """Reference points/weights for the Eulerian<->Lagrangian TRANSFER
    at adjustable density (round 5, VERDICT item 8 — the
    ``FEDataManager::updateQuadratureRule`` analog [U]: the reference
    adapts the IB quadrature rule to the deformed element so spread
    points stay denser than the grid). ``level`` 0 = the stiffness
    rule; each level adds one Gauss point per axis (tensor families)
    or one midpoint subdivision with centroid points (simplices).
    Returns (qp, qw) with sum(qw) = reference measure."""
    if level <= 0:
        _, _, qw = _shape_table(elem_type)
        qp = _rule_points(elem_type)
        return qp, qw
    if elem_type in ("QUAD4", "HEX8", "QUAD8", "QUAD9", "HEX20",
                     "HEX27"):
        dim = 2 if elem_type.startswith("QUAD") else 3
        base = 2 if elem_type in ("QUAD4", "HEX8") else 3
        return _tensor_gauss(dim, base + int(level))
    if elem_type in ("TRI3", "TRI6"):
        verts = np.array([[0.0, 0.0], [1.0, 0.0], [0.0, 1.0]])
        measure = 0.5
    elif elem_type in ("TET4", "TET10"):
        verts = np.array([[0.0, 0.0, 0.0], [1.0, 0.0, 0.0],
                          [0.0, 1.0, 0.0], [0.0, 0.0, 1.0]])
        measure = 1.0 / 6.0
    else:
        raise ValueError(f"unknown element type {elem_type!r}")
    sims = _subdivide_simplex(verts, int(level))
    qp = np.stack([s.mean(axis=0) for s in sims])
    qw = np.full(len(sims), measure / len(sims))
    return qp, qw


def _rule_points(elem_type: str):
    """Reference points of the standard (stiffness) rule."""
    if elem_type in ("TRI3", "TRI6"):
        return _TRI3_QP
    if elem_type in ("TET4", "TET10"):
        return _TET4_QP
    dim = 2 if elem_type.startswith("QUAD") else 3
    n = 2 if elem_type in ("QUAD4", "HEX8") else 3
    return _tensor_gauss(dim, n)[0]


def suggest_transfer_level(mesh: FEMesh, x, h: float,
                           target: float = 0.5,
                           max_level: int = 4) -> int:
    """Host-side density decision (the per-regrid analog of the
    reference's per-step updateQuadratureRule): smallest ``level``
    whose transfer-point spacing stays below ``target * h`` for the
    DEFORMED configuration ``x`` (nodal positions). Spacing estimate:
    max deformed edge length / points-per-axis of the rule."""
    xn = np.asarray(x)
    et = mesh.elem_type
    # corner connectivity edges per family (corners bound the element)
    ncorner = {"TRI3": 3, "TRI6": 3, "TET4": 4, "TET10": 4,
               "QUAD4": 4, "QUAD8": 4, "QUAD9": 4,
               "HEX8": 8, "HEX20": 8, "HEX27": 8}[et]
    corners = np.asarray(mesh.elems)[:, :ncorner]
    lmax = 0.0
    for i in range(ncorner):
        for j in range(i + 1, ncorner):
            d = np.linalg.norm(xn[corners[:, i]] - xn[corners[:, j]],
                               axis=1)
            lmax = max(lmax, float(d.max()))
    for level in range(max_level + 1):
        qp, _ = transfer_quadrature(et, level)
        if et.startswith(("QUAD", "HEX")):
            npts_axis = round(len(qp) ** (1.0 / (2 if et.startswith(
                "QUAD") else 3)))
        else:
            npts_axis = 2 ** level
        if lmax / max(npts_axis, 1) <= target * h:
            return level
    return max_level


class FEAssembly(NamedTuple):
    """Device-resident reference-configuration tables for one mesh."""
    elems: jnp.ndarray     # (E, nen) int32 connectivity
    shape: jnp.ndarray     # (nq, nen) shape values at quad points
    dNdX: jnp.ndarray      # (E, nq, nen, dim) reference shape gradients
    wdV: jnp.ndarray       # (E, nq) quadrature weight * |detJ|
    lumped_mass: jnp.ndarray  # (n_nodes,) sum_q wdV * N_a  (unit density)
    n_nodes: int
    dim: int


def _assemble_tables(mesh: FEMesh, N, dN, qw, dtype) -> FEAssembly:
    """THE geometry/assembly kernel shared by the stiffness and
    transfer rules (one place for the Jacobian math)."""
    Xe = mesh.nodes[mesh.elems]                      # (E, nen, dim)
    # per-quadrature-point Jacobian J_ij = dX_i/dxi_j (varies within
    # quadratic/tensor elements)
    J = np.einsum("qad,eai->eqid", dN, Xe)           # (E, nq, dim, dim)
    detJ = np.linalg.det(J)                          # (E, nq)
    Jinv = np.linalg.inv(J)
    dNdX = np.einsum("qad,eqdi->eqai", dN, Jinv)     # (E, nq, nen, dim)
    wdV = np.abs(detJ) * qw[None, :]                 # (E, nq)

    n_nodes = mesh.n_nodes
    mass = hrz_lumped_mass(mesh.elems, N, wdV, n_nodes)

    return FEAssembly(
        elems=jnp.asarray(mesh.elems, dtype=jnp.int32),
        shape=jnp.asarray(N, dtype=dtype),
        dNdX=jnp.asarray(dNdX, dtype=dtype),
        wdV=jnp.asarray(wdV, dtype=dtype),
        lumped_mass=jnp.asarray(mass, dtype=dtype),
        n_nodes=n_nodes, dim=mesh.dim)


def build_assembly(mesh: FEMesh, dtype=jnp.float32) -> FEAssembly:
    N, dN, qw = _shape_table(mesh.elem_type)
    return _assemble_tables(mesh, N, dN, qw, dtype)


def build_transfer_assembly(mesh: FEMesh, level: int = 0,
                            dtype=jnp.float32) -> FEAssembly:
    """A shadow assembly at TRANSFER quadrature density ``level``
    (:func:`transfer_quadrature`) — same connectivity, denser
    points/weights — for the Eulerian<->Lagrangian coupling while the
    weak-form assembly keeps the stiffness rule (the reference's
    FEDataManager holds exactly this pair of rules [U])."""
    if level <= 0:
        return build_assembly(mesh, dtype=dtype)
    qp, qw = transfer_quadrature(mesh.elem_type, level)
    N, dN = _shapes_at(mesh.elem_type, qp)
    return _assemble_tables(mesh, N, dN, qw, dtype)


# -- kinematics --------------------------------------------------------------

def deformation_gradients(asm: FEAssembly, x: jnp.ndarray) -> jnp.ndarray:
    """FF = dx/dX at every quadrature point -> (E, nq, dim, dim)."""
    xe = x[asm.elems]                                # (E, nen, dim)
    return jnp.einsum("eai,eqaj->eqij", xe, asm.dNdX)


# -- strain-energy densities (W: FF -> scalar) -------------------------------

def _log_ext(J, eps: float = 1e-4):
    """log(J) with a C1 linear extension below ``eps``: near/through
    element inversion the volumetric terms keep a large (1/eps-slope)
    restoring force instead of a clamped-to-zero gradient."""
    return jnp.where(J > eps, jnp.log(jnp.maximum(J, eps)),
                     jnp.log(eps) + (J - eps) / eps)


def neo_hookean(mu: float, lam: float) -> Callable:
    """Compressible neo-Hookean, the IBFE-ex0-style material:
    W = mu/2 (I1 - d) - mu ln J + lam/2 (ln J)^2."""
    def W(FF):
        d = FF.shape[-1]
        J = jnp.linalg.det(FF)
        logJ = _log_ext(J)
        I1 = jnp.einsum("...ij,...ij->...", FF, FF)
        return 0.5 * mu * (I1 - d) - mu * logJ + 0.5 * lam * logJ ** 2
    return W


def stvk(mu: float, lam: float) -> Callable:
    """St. Venant-Kirchhoff: W = mu tr(EE^2) + lam/2 (tr EE)^2,
    EE = (FF^T FF - I)/2."""
    def W(FF):
        d = FF.shape[-1]
        C = jnp.einsum("...ki,...kj->...ij", FF, FF)
        E = 0.5 * (C - jnp.eye(d, dtype=FF.dtype))
        trE = jnp.trace(E, axis1=-2, axis2=-1)
        return mu * jnp.einsum("...ij,...ij->...", E, E) + 0.5 * lam * trE ** 2
    return W


def pk1(W: Callable) -> Callable:
    """PK1 stress P = dW/dFF (vectorized over leading axes)."""
    return jax.grad(lambda FF: jnp.sum(W(FF)))


# -- force assembly ----------------------------------------------------------

def elastic_energy(asm: FEAssembly, W: Callable, x: jnp.ndarray):
    """E(x) = sum_e sum_q wdV_eq * W(FF_eq)."""
    FF = deformation_gradients(asm, x)               # (E, nq, d, d)
    return jnp.sum(W(FF) * asm.wdV)


def nodal_forces(asm: FEAssembly, W: Callable, x: jnp.ndarray) -> jnp.ndarray:
    """Weak-form nodal elastic force F = -dE/dx -> (n_nodes, dim)."""
    return -jax.grad(lambda xx: elastic_energy(asm, W, xx))(x)


def nodal_forces_pk1(asm: FEAssembly, W: Callable,
                     x: jnp.ndarray) -> jnp.ndarray:
    """Explicit PK1 assembly F_a = -sum_e sum_q wdV P(FF) dN_a/dX — the
    reference's element-loop form; must equal :func:`nodal_forces`."""
    FF = deformation_gradients(asm, x)
    P = pk1(W)(FF)                                   # (E, nq, dim, dim)
    Fe = -jnp.einsum("eq,eqij,eqaj->eai", asm.wdV, P,
                     asm.dNdX)                       # (E, nen, dim)
    out = jnp.zeros((asm.n_nodes, asm.dim), dtype=x.dtype)
    return out.at[asm.elems.reshape(-1)].add(
        Fe.reshape(-1, asm.dim))


# -- quadrature-point utilities (the "unified" coupling scheme) --------------

def quad_positions(asm: FEAssembly, x: jnp.ndarray) -> jnp.ndarray:
    """Current positions of all quadrature points -> (E*nq, dim)."""
    xe = x[asm.elems]                                # (E, nen, dim)
    xq = jnp.einsum("qa,eai->eqi", asm.shape, xe)
    return xq.reshape(-1, asm.dim)

def project_to_quads(asm: FEAssembly, nodal: jnp.ndarray) -> jnp.ndarray:
    """Evaluate a nodal field at quadrature points -> (E*nq, ...)."""
    ne = nodal[asm.elems]                            # (E, nen, ...)
    nq = jnp.einsum("qa,ea...->eq...", asm.shape, ne)
    return nq.reshape((-1,) + nodal.shape[1:])


def hrz_lumped_mass(elems, N, w, n_nodes) -> "np.ndarray":
    """HRZ diagonal mass lumping (host-side, numpy): m_a ~ integral
    N_a^2, normalized per element to the element mass (weights ``w`` =
    wdV volumetric or wdA surface) — positive for EVERY family (plain
    row-sum lumping goes negative at quadratic-simplex vertices).
    Shared by the volumetric and codim-1 assemblies."""
    mass = np.zeros(n_nodes)
    n2 = np.einsum("eq,qa->ea", w, N * N)            # (E, nen)
    emass = w.sum(axis=1)                            # (E,)
    contrib = n2 * (emass / np.maximum(n2.sum(axis=1), 1e-300))[:, None]
    np.add.at(mass, elems, contrib)
    return mass


def _node_qp_weights(elems, shape, w, n_nodes):
    """Positive node<->quad-point transfer weights omega_eqa = w_eq *
    N_a(q)^2 and their per-node totals. N^2 keeps every weight
    POSITIVE for every element family (plain N goes negative at
    quadratic-simplex vertices, where sum_q w N_a is exactly zero —
    round-3 review finding: the old N-weighted projection returned 0 at
    TRI6/TET10 vertices)."""
    ww = w[:, :, None] * (shape ** 2)[None, :, :]    # (E, nq, nen)
    den = jnp.zeros(n_nodes, dtype=w.dtype)
    den = den.at[elems.reshape(-1)].add(
        jnp.sum(ww, axis=1).reshape(-1))
    den = jnp.where(den > 0, den, jnp.ones_like(den))
    return ww, den


def nodal_average_from_quads(elems, shape, w, n_nodes,
                             vals: jnp.ndarray,
                             ww_den=None) -> jnp.ndarray:
    """Node-normalized weighted average of quad-point values: exact for
    constants on EVERY family (numerator and denominator carry the same
    weights). The rebuild's FEDataManager L2-projection role (T16),
    shared by the volumetric and surface paths. ``ww_den`` takes a
    precomputed ``_node_qp_weights`` pair (it depends only on the
    static assembly, so per-step callers hoist it out of the hot
    loop — round-3 review finding)."""
    E, nq = w.shape
    v = vals.reshape((E, nq) + vals.shape[1:])
    ww, den = (ww_den if ww_den is not None
               else _node_qp_weights(elems, shape, w, n_nodes))
    contrib = jnp.einsum("eqa,eq...->ea...", ww, v)
    out = jnp.zeros((n_nodes,) + vals.shape[1:], dtype=vals.dtype)
    out = out.at[elems.reshape(-1)].add(
        contrib.reshape((-1,) + vals.shape[1:]))
    shp = (n_nodes,) + (1,) * (vals.ndim - 1)
    return out / den.reshape(shp)


def distribute_to_quads(elems, shape, w, n_nodes,
                        F: jnp.ndarray, ww_den=None) -> jnp.ndarray:
    """Adjoint transfer: split each NODAL value over its quadrature
    points with per-node-normalized shares, so sum_q out_q == sum_a F_a
    EXACTLY (the force-conservation contract of the unified coupling),
    for every element family. ``ww_den``: see nodal_average_from_quads."""
    ww, den = (ww_den if ww_den is not None
               else _node_qp_weights(elems, shape, w, n_nodes))
    Fa = (F / den.reshape((n_nodes,) + (1,) * (F.ndim - 1)))[elems]
    out = jnp.einsum("eqa,ea...->eq...", ww, Fa)
    return out.reshape((-1,) + F.shape[1:])


def l2_project_from_quads(asm: FEAssembly, vals: jnp.ndarray) -> jnp.ndarray:
    """Quad-point values -> nodes (see nodal_average_from_quads)."""
    return nodal_average_from_quads(asm.elems, asm.shape, asm.wdV,
                                    asm.n_nodes, vals)


def safe_lumped_mass(asm: FEAssembly) -> jnp.ndarray:
    """Lumped mass with zeros (nodes unreferenced by any element — legal
    in external Triangle meshes) replaced by 1 so divisions stay finite;
    such nodes carry no load either way."""
    return jnp.where(asm.lumped_mass > 0, asm.lumped_mass,
                     jnp.ones_like(asm.lumped_mass))
