"""Physical boundary conditions on the uniform MAC grid.

Reference parity: the Robin BC machinery of IBTK (T9, SURVEY.md §2.1) —
``RobinBcCoefStrategy`` / ``muParserRobinBcCoefs`` semantics: each domain
side prescribes a * Q + b * dQ/dn = g. The common named cases:

- ``periodic``  — both sides of the axis wrap (the default everywhere).
- ``dirichlet`` — Q = g at the boundary face      (a=1, b=0).
- ``neumann``   — dQ/dn = g at the boundary face  (a=0, b=1).

TPU-first design: BCs are static metadata (hashable dataclasses) baked
into jitted step functions; ghost filling is array padding + arithmetic
(no indirection), so XLA fuses the fills into the stencils that consume
them — the analog of SAMRAI's physical-boundary RefinePatchStrategy fill
pass collapsing into the compute kernel.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp

PERIODIC = "periodic"
DIRICHLET = "dirichlet"
NEUMANN = "neumann"
ROBIN = "robin"
_KINDS = (PERIODIC, DIRICHLET, NEUMANN, ROBIN)


@dataclasses.dataclass(frozen=True)
class SideBC:
    """One side's condition a*Q + b*dQ/dn = g (the full Robin form of
    the reference's RobinBcCoefStrategy). ``kind`` names the common
    cases; ``robin`` uses the explicit (a, b). ``value`` is the
    CONSTANT boundary datum g; spatially-varying g arrives at fill time
    through the ``bdry_data`` argument of the ghost-fill/Laplacian
    functions (the muParserRobinBcCoefs analog), keeping this dataclass
    hashable static metadata."""
    kind: str = PERIODIC
    value: float = 0.0
    a: float = 1.0             # robin coefficient on Q
    b: float = 0.0             # robin coefficient on dQ/dn

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown BC kind {self.kind!r}")
        if self.kind == ROBIN and self.a == 0.0 and self.b == 0.0:
            raise ValueError("robin BC needs a != 0 or b != 0")

    def coeffs(self):
        """(a, b) of a*Q + b*dQ/dn = g for any non-periodic kind."""
        if self.kind == DIRICHLET:
            return 1.0, 0.0
        if self.kind == NEUMANN:
            return 0.0, 1.0
        if self.kind == ROBIN:
            return self.a, self.b
        raise ValueError("periodic side has no Robin coefficients")


def ghost_reflect_coeff(side: SideBC, h: float) -> float:
    """ghost = c * interior under the HOMOGENEOUS condition
    a*Q + b*dQ/dn = 0 discretized at the face (see _ghost_layers_cc):
    c = -(a/2 - b/h) / (a/2 + b/h). Shared by the ghost fill, the
    fast-diagonalization 1D matrices, and the multigrid diagonals so
    the smoothers always match the operator discretization."""
    a, b = side.coeffs()
    denom = 0.5 * a + b / h
    if denom == 0.0:
        raise ValueError(f"ill-posed ghost fill: a/2 + b/h == 0 for {side}")
    return -(0.5 * a - b / h) / denom


@dataclasses.dataclass(frozen=True)
class AxisBC:
    lo: SideBC = SideBC()
    hi: SideBC = SideBC()

    def __post_init__(self):
        if (self.lo.kind == PERIODIC) != (self.hi.kind == PERIODIC):
            raise ValueError("periodic must be set on both sides of an axis")

    @property
    def periodic(self) -> bool:
        return self.lo.kind == PERIODIC


def periodic_axis() -> AxisBC:
    return AxisBC()


def dirichlet_axis(lo: float = 0.0, hi: float = 0.0) -> AxisBC:
    return AxisBC(SideBC(DIRICHLET, lo), SideBC(DIRICHLET, hi))


def neumann_axis(lo: float = 0.0, hi: float = 0.0) -> AxisBC:
    return AxisBC(SideBC(NEUMANN, lo), SideBC(NEUMANN, hi))


def robin_axis(a: float, b: float, lo: float = 0.0,
               hi: float = 0.0) -> AxisBC:
    """a*Q + b*dQ/dn = g on both sides (g = lo/hi constants)."""
    return AxisBC(SideBC(ROBIN, lo, a=a, b=b),
                  SideBC(ROBIN, hi, a=a, b=b))


@dataclasses.dataclass(frozen=True)
class DomainBC:
    """Per-axis BCs for one scalar (cell-centered) field, or one velocity
    component's wall behavior when used by the INS machinery."""
    axes: Tuple[AxisBC, ...]

    @property
    def all_periodic(self) -> bool:
        return all(a.periodic for a in self.axes)

    @classmethod
    def periodic(cls, dim: int) -> "DomainBC":
        return cls(axes=(AxisBC(),) * dim)


# ---------------------------------------------------------------------------
# Ghost filling for cell-centered fields
# ---------------------------------------------------------------------------

def _ghost_layers_cc(Q: jnp.ndarray, axis: int, side: SideBC, h: float,
                     lo_side: bool, width: int, g=None) -> jnp.ndarray:
    """``width`` ghost layers beyond a wall from the Robin condition,
    reflecting each (ghost_k, interior_k) pair symmetrically about the
    boundary face:  a*(ghost+int)/2 + b*(ghost-int)/((2k-1)h) = g
    (reduces to odd reflection 2g - int_k for Dirichlet and the mirrored
    int_k + (2k-1)h*g for Neumann — the reference's multi-width
    RobinBcCoefStrategy fill, T5/T9). Layers are returned in array
    order (outermost first on the lo side)."""
    a, b = side.coeffs()
    if g is None:
        g = side.value
    layers = []
    for k in range(1, width + 1):
        idx = [slice(None)] * Q.ndim
        idx[axis] = slice(k - 1, k) if lo_side else \
            slice(Q.shape[axis] - k, Q.shape[axis] - k + 1)
        interior = Q[tuple(idx)]
        heff = (2 * k - 1) * h
        denom = 0.5 * a + b / heff
        if denom == 0.0:
            raise ValueError(
                f"ill-posed ghost fill: a/2 + b/h == 0 for {side}")
        layers.append((g - interior * (0.5 * a - b / heff)) / denom)
    if lo_side:
        layers = layers[::-1]
    return jnp.concatenate(layers, axis=axis) if width > 1 else layers[0]


def fill_ghosts_cc(Q: jnp.ndarray, bc: DomainBC,
                   dx: Sequence[float],
                   bdry_data: Optional[dict] = None,
                   width: int = 1) -> jnp.ndarray:
    """Pad a cell-centered field with ``width`` ghost layers per side
    honoring the BCs (periodic wrap or Robin wall extrapolation).
    Output shape n + 2*width per axis; stencil consumers slice the
    interior back out. Multi-width fills serve the wide-stencil
    consumers (PPM/Godunov predictors) the way the reference's
    variable-ghost-width RefineSchedules do (T5).

    ``bdry_data``: optional {(axis, side0or1): array} of
    spatially-varying boundary data g (each broadcastable to the face
    slab of that side), overriding the per-side constants."""
    if width < 1:
        raise ValueError(f"ghost width must be >= 1, got {width}")
    if any(width > s for s in Q.shape):
        raise ValueError(
            f"ghost width {width} exceeds field extent {Q.shape}")
    out = Q
    for d, axbc in enumerate(bc.axes):
        if axbc.periodic:
            lo_idx = [slice(None)] * out.ndim
            hi_idx = [slice(None)] * out.ndim
            lo_idx[d] = slice(-width, None)
            hi_idx[d] = slice(0, width)
            lo_ghost, hi_ghost = out[tuple(lo_idx)], out[tuple(hi_idx)]
        else:
            g_lo = g_hi = None
            if bdry_data is not None:
                g_lo = bdry_data.get((d, 0))
                g_hi = bdry_data.get((d, 1))
            lo_ghost = _ghost_layers_cc(out, d, axbc.lo, dx[d], True,
                                        width,
                                        g=pad_boundary_data(g_lo, out, d, width))
            hi_ghost = _ghost_layers_cc(out, d, axbc.hi, dx[d], False,
                                        width,
                                        g=pad_boundary_data(g_hi, out, d, width))
        out = jnp.concatenate([lo_ghost, out, hi_ghost], axis=d)
    return out


def pad_boundary_data(g, out, d, width: int = 1):
    """Boundary-data arrays are sized for the UNPADDED grid; make them
    broadcast against the partially-padded array: align axes the numpy
    way (prepend singleton axes up to full rank), let extent-1 axes
    broadcast, and edge-pad true-extent axes that earlier loop
    iterations already grew by exactly 2*width ghost layers (any other
    size mismatch is a caller bug and raises)."""
    if g is None or not hasattr(g, "ndim") or g.ndim == 0:
        return g
    if g.ndim > out.ndim:
        raise ValueError(
            f"boundary data has rank {g.ndim} > field rank {out.ndim}")
    g = jnp.reshape(g, (1,) * (out.ndim - g.ndim) + tuple(g.shape))
    target = list(out.shape)
    target[d] = 1
    if list(g.shape) == target:
        return g
    pads = []
    for gs, ts in zip(g.shape, target):
        if gs == ts or gs == 1:
            pads.append((0, 0))
        elif gs == ts - 2 * width:
            pads.append((width, width))
        else:
            raise ValueError(
                f"boundary data shape {g.shape} incompatible with face "
                f"slab {tuple(target)} (ghost width {width})")
    return jnp.pad(g, pads, mode="edge")


def laplacian_cc(Q: jnp.ndarray, bc: DomainBC,
                 dx: Sequence[float],
                 bdry_data: Optional[dict] = None) -> jnp.ndarray:
    """BC-aware 2d+1-point Laplacian of a cell-centered field (ghost-fill
    then difference; XLA fuses the pad into the stencil)."""
    G = fill_ghosts_cc(Q, bc, dx, bdry_data=bdry_data)
    dim = Q.ndim
    center = tuple(slice(1, -1) for _ in range(dim))
    out = jnp.zeros_like(Q)
    for d in range(dim):
        lo = list(center)
        hi = list(center)
        lo[d] = slice(0, -2)
        hi[d] = slice(2, None)
        out = out + (G[tuple(lo)] - 2.0 * Q + G[tuple(hi)]) / dx[d] ** 2
    return out
