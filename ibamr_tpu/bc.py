"""Physical boundary conditions on the uniform MAC grid.

Reference parity: the Robin BC machinery of IBTK (T9, SURVEY.md §2.1) —
``RobinBcCoefStrategy`` / ``muParserRobinBcCoefs`` semantics: each domain
side prescribes a * Q + b * dQ/dn = g. The common named cases:

- ``periodic``  — both sides of the axis wrap (the default everywhere).
- ``dirichlet`` — Q = g at the boundary face      (a=1, b=0).
- ``neumann``   — dQ/dn = g at the boundary face  (a=0, b=1).

TPU-first design: BCs are static metadata (hashable dataclasses) baked
into jitted step functions; ghost filling is array padding + arithmetic
(no indirection), so XLA fuses the fills into the stencils that consume
them — the analog of SAMRAI's physical-boundary RefinePatchStrategy fill
pass collapsing into the compute kernel.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp

PERIODIC = "periodic"
DIRICHLET = "dirichlet"
NEUMANN = "neumann"
_KINDS = (PERIODIC, DIRICHLET, NEUMANN)


@dataclasses.dataclass(frozen=True)
class SideBC:
    """One side's condition. ``value`` is the (constant) boundary datum g;
    spatially-varying data enters via the solvers' RHS lifting hooks."""
    kind: str = PERIODIC
    value: float = 0.0

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown BC kind {self.kind!r}")


@dataclasses.dataclass(frozen=True)
class AxisBC:
    lo: SideBC = SideBC()
    hi: SideBC = SideBC()

    def __post_init__(self):
        if (self.lo.kind == PERIODIC) != (self.hi.kind == PERIODIC):
            raise ValueError("periodic must be set on both sides of an axis")

    @property
    def periodic(self) -> bool:
        return self.lo.kind == PERIODIC


def periodic_axis() -> AxisBC:
    return AxisBC()


def dirichlet_axis(lo: float = 0.0, hi: float = 0.0) -> AxisBC:
    return AxisBC(SideBC(DIRICHLET, lo), SideBC(DIRICHLET, hi))


def neumann_axis(lo: float = 0.0, hi: float = 0.0) -> AxisBC:
    return AxisBC(SideBC(NEUMANN, lo), SideBC(NEUMANN, hi))


@dataclasses.dataclass(frozen=True)
class DomainBC:
    """Per-axis BCs for one scalar (cell-centered) field, or one velocity
    component's wall behavior when used by the INS machinery."""
    axes: Tuple[AxisBC, ...]

    @property
    def all_periodic(self) -> bool:
        return all(a.periodic for a in self.axes)

    @classmethod
    def periodic(cls, dim: int) -> "DomainBC":
        return cls(axes=(AxisBC(),) * dim)


# ---------------------------------------------------------------------------
# Ghost filling for cell-centered fields
# ---------------------------------------------------------------------------

def _ghost_values_cc(Q: jnp.ndarray, axis: int, side: SideBC, h: float,
                     lo_side: bool) -> jnp.ndarray:
    """One ghost layer for a cell-centered field beyond a wall: linear
    extrapolation through the boundary-face value (dirichlet) or slope
    (neumann). Outward normal points lo-ward on the lo side."""
    idx = [slice(None)] * Q.ndim
    idx[axis] = slice(0, 1) if lo_side else slice(-1, None)
    interior = Q[tuple(idx)]
    if side.kind == DIRICHLET:
        return 2.0 * side.value - interior
    if side.kind == NEUMANN:
        # dQ/dn = g with n the OUTWARD normal: on either side the ghost
        # lies outward of the interior cell, so (ghost - interior)/h = g.
        return interior + h * side.value
    raise ValueError(side.kind)


def fill_ghosts_cc(Q: jnp.ndarray, bc: DomainBC,
                   dx: Sequence[float]) -> jnp.ndarray:
    """Pad a cell-centered field with ONE ghost layer per side honoring
    the BCs (periodic wrap or wall extrapolation). Output shape n+2 per
    axis; stencil consumers slice the interior back out."""
    out = Q
    for d, axbc in enumerate(bc.axes):
        if axbc.periodic:
            lo_idx = [slice(None)] * out.ndim
            hi_idx = [slice(None)] * out.ndim
            lo_idx[d] = slice(-1, None)
            hi_idx[d] = slice(0, 1)
            lo_ghost, hi_ghost = out[tuple(lo_idx)], out[tuple(hi_idx)]
        else:
            lo_ghost = _ghost_values_cc(out, d, axbc.lo, dx[d], True)
            hi_ghost = _ghost_values_cc(out, d, axbc.hi, dx[d], False)
        out = jnp.concatenate([lo_ghost, out, hi_ghost], axis=d)
    return out


def laplacian_cc(Q: jnp.ndarray, bc: DomainBC,
                 dx: Sequence[float]) -> jnp.ndarray:
    """BC-aware 2d+1-point Laplacian of a cell-centered field (ghost-fill
    then difference; XLA fuses the pad into the stencil)."""
    G = fill_ghosts_cc(Q, bc, dx)
    dim = Q.ndim
    center = tuple(slice(1, -1) for _ in range(dim))
    out = jnp.zeros_like(Q)
    for d in range(dim):
        lo = list(center)
        hi = list(center)
        lo[d] = slice(0, -2)
        hi[d] = slice(2, None)
        out = out + (G[tuple(lo)] - 2.0 * Q + G[tuple(hi)]) / dx[d] ** 2
    return out
