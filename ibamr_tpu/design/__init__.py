"""Inverse design: optimize THROUGH the solver (PR 19, ROADMAP item 4).

The C++ reference can only simulate; this package cashes in every perf
lever the rebuild chose with the gradient path in mind — the fused
``SpectralPlan`` substep (custom VJP: cotangents ride the SAME plan),
the packed transfers (d(spread) is an interp through the SAME buckets,
zero scatter primitives), ``RunConfig(remat=)`` checkpointed chunks,
and the PR-11 ``ExecutableCache`` (gradient executables keyed as
``kind: grad_chunk`` so a design iteration after the first pays zero
compiles). A design loop is a warm-pool tenant.
"""

from ibamr_tpu.design.loop import (AdamState, DesignIter, DesignLoop,
                                   DesignResult, adam_init, adam_update,
                                   global_norm)
from ibamr_tpu.design.eel_gait import build_eel, build_eel_gait_problem
from ibamr_tpu.design.cantilever import build_cantilever_problem

__all__ = [
    "AdamState", "DesignIter", "DesignLoop", "DesignResult",
    "adam_init", "adam_update", "global_norm",
    "build_eel", "build_eel_gait_problem", "build_cantilever_problem",
]
