"""IBFE cantilever stiffness/shape optimization: match a target tip
deflection by differentiating through the FE coupling.

A neo-Hookean QUAD4 beam is anchored along its left edge (stiff tether
to the reference positions) and loaded by a distributed transverse body
force; after a short rollout the tip sags by an amount set by the
material stiffness and the beam thickness. The design parameters —
``log_mu`` (log shear modulus, log-space so Adam steps are
multiplicative and positivity is free) and ``log_thick`` (log
thickness scale applied to the undeformed section) — are traced through
``neo_hookean`` and the initial geometry: ``IBFEMethod`` is built
INSIDE the objective, so ``nodal_forces`` (itself a ``jax.grad`` of the
strain energy) differentiates correctly w.r.t. the material constants
(grad-of-grad), and the spread/interp transfers ride the same adjoint
path the classic IB method uses.

Objective: ``(tip_deflection - target)^2`` — a calibration problem: find
the stiffness/section that produces a prescribed compliance.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ibamr_tpu.fe.fem import neo_hookean
from ibamr_tpu.fe.mesh import rect_quad_mesh
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ib import IBExplicitIntegrator
from ibamr_tpu.integrators.ibfe import IBFEMethod
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.utils.hierarchy_driver import checkpointed_step


def build_cantilever_problem(n: int = 32, nx: int = 8, ny: int = 2,
                             num_steps: int = 10, dt: float = 2e-3,
                             mu: float = 0.05,
                             load: float = -4.0,
                             k_anchor: float = 2e3,
                             target_tip: float = -0.02,
                             dtype=jnp.float32,
                             remat: Optional[str] = "full",
                             ) -> Tuple[Callable, dict]:
    """``(objective, params0)`` for a :class:`~ibamr_tpu.design.loop.
    DesignLoop`. The beam spans x ∈ [0.3, 0.7] at mid-channel; its left
    edge is anchored, every other node carries the transverse ``load``
    per unit mass; ``objective(params)`` returns the squared mismatch
    between the rolled-out mean tip deflection and ``target_tip``."""
    grid = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(grid, rho=1.0, mu=mu, dtype=dtype)
    mesh = rect_quad_mesh(nx, ny, x_lo=(0.30, 0.46), x_up=(0.70, 0.54))
    nodes = mesh.nodes
    base = nodes[:, 0] <= nodes[:, 0].min() + 1e-12
    tip = nodes[:, 0] >= nodes[:, 0].max() - 1e-12
    # python float, not np.float64: a weak scalar keeps the scaled
    # section in X_ref's dtype even when x64 is globally enabled
    y_mid = float(0.5 * (nodes[:, 1].min() + nodes[:, 1].max()))
    base_w = jnp.asarray(base.astype(np.float64), dtype)[:, None]
    free_w = 1.0 - base_w
    tip_idx = jnp.asarray(np.nonzero(tip)[0])
    X_ref = jnp.asarray(nodes, dtype)

    def objective(params):
        mu_s = jnp.exp(params["log_mu"])
        lam_s = 4.0 * mu_s                     # fixed compressibility ratio
        thick = jnp.exp(params["log_thick"])
        # shape parameter: scale the undeformed SECTION about the beam
        # axis (the anchor tether below targets the same scaled
        # reference, so the anchored edge is consistent)
        X0 = X_ref.at[:, 1].set(y_mid + thick * (X_ref[:, 1] - y_mid))

        def body_force(x, t):
            tether = -k_anchor * (x - X0) * base_w
            pull = jnp.stack([jnp.zeros_like(x[:, 0]),
                              jnp.full_like(x[:, 0], load)], axis=1)
            return tether + pull * free_w

        # built INSIDE the trace: mu_s/lam_s live in the neo-Hookean
        # closure, so the weak-form force (a jax.grad of the energy)
        # carries the design tracers — grad-of-grad, handled natively
        fe = IBFEMethod(mesh, neo_hookean(mu_s, lam_s),
                        body_force=body_force, dtype=dtype)
        integ = IBExplicitIntegrator(ins, fe)
        st = integ.initialize(X0)
        step = integ.step if remat is None \
            else checkpointed_step(integ.step, remat)

        def body(carry, _):
            return step(carry, dt), None

        out, _ = jax.lax.scan(body, st, None, length=num_steps)
        defl = jnp.mean(out.X[tip_idx, 1]) - y_mid
        return (defl - jnp.asarray(target_tip, dtype)) ** 2

    params0 = {"log_mu": jnp.asarray(0.0, dtype),
               "log_thick": jnp.asarray(0.0, dtype)}
    return objective, params0
