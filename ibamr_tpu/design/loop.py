"""The design loop: AOT-cached gradient executables + hand-rolled Adam.

One design iteration is ONE executable call: ``value_and_grad`` of the
rollout objective FUSED with the Adam update, AOT-compiled once
(``jax.jit(...).lower(...).compile()``) and keyed through the PR-11
:class:`~ibamr_tpu.serve.aot_cache.ExecutableCache` as
``kind: grad_chunk``. Iteration 1 pays the single compile (a cache
MISS); every later iteration — and every later loop over the same
scenario family — is a cache HIT calling a ``jax.stages.Compiled``,
which structurally cannot retrace or recompile. That is the
"adjoint at primal cost" operational contract:

  * cost:   the VJP inside the executable is the custom-VJP path the
            graph budgets pin (``grad_substep``: batched FFTs ≤ 2×
            primal; ``grad_spread``/``grad_interp``: zero scatter
            primitives; zero f64 widenings) — not whatever reverse-mode
            autodiff happens to emit;
  * compiles: per-iteration cache-stat deltas are RECORDED in each
            :class:`DesignIter` and emitted as ``design_iter`` ledger
            records, so "iteration 2+ pays zero compiles" is a number
            the drill (``fault_injection --design-smoke``) and
            ``obs.py summary`` can check, not a slogan.

The optimizer is a self-contained Adam (no optax dependency — the
container pins its package set); its state is an ordinary pytree so it
lives INSIDE the compiled iterate. L-BFGS-style quasi-Newton loops can
wrap :meth:`DesignLoop.value_and_grad_fn` with their own line search;
the flagship demos (``eel_gait``, ``cantilever``) use Adam because a
fixed-arity update fuses into one cacheable executable.
"""

from __future__ import annotations

import time
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu import obs as _obs
from ibamr_tpu.serve.aot_cache import (ExecutableCache, aot_compile,
                                       arg_signature, get_cache)

Params = Any  # any pytree of inexact arrays


# -- Adam --------------------------------------------------------------------

class AdamState(NamedTuple):
    """Optimizer state, shaped like the params pytree (scan/jit safe)."""
    step: jnp.ndarray   # () int32 — update count (bias correction)
    m: Params           # first moments
    v: Params           # second moments


def adam_init(params: Params) -> AdamState:
    zeros = lambda p: jnp.zeros_like(p)
    return AdamState(step=jnp.zeros((), jnp.int32),
                     m=jax.tree_util.tree_map(zeros, params),
                     v=jax.tree_util.tree_map(zeros, params))


def adam_update(params: Params, grads: Params, opt: AdamState, lr,
                b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8) -> Tuple[Params, AdamState]:
    """One Adam step (Kingma & Ba 2015, bias-corrected)."""
    tmap = jax.tree_util.tree_map
    t = opt.step + 1
    m = tmap(lambda mm, g: b1 * mm + (1.0 - b1) * g, opt.m, grads)
    v = tmap(lambda vv, g: b2 * vv + (1.0 - b2) * g * g, opt.v, grads)

    def upd(p, mm, vv):
        tf = t.astype(p.dtype)
        mhat = mm / (1.0 - jnp.asarray(b1, p.dtype) ** tf)
        vhat = vv / (1.0 - jnp.asarray(b2, p.dtype) ** tf)
        return p - jnp.asarray(lr, p.dtype) * mhat \
            / (jnp.sqrt(vhat) + jnp.asarray(eps, p.dtype))

    return tmap(upd, params, m, v), AdamState(step=t, m=m, v=v)


def global_norm(grads: Params) -> jnp.ndarray:
    """sqrt(sum of squares) over every leaf — the logged grad scale."""
    leaves = jax.tree_util.tree_leaves(grads)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))


# -- per-iteration record ----------------------------------------------------

class DesignIter(NamedTuple):
    iteration: int
    objective: float      # f(params) BEFORE this iteration's update
    grad_norm: float
    wall_s: float         # full iteration wall (lookup + exec + sync)
    cache_hits: int       # executable-cache hit delta this iteration
    cache_misses: int     # compiles paid this iteration (0 when warm)


class DesignResult(NamedTuple):
    params: Params
    history: Tuple[DesignIter, ...]
    objective: float      # last recorded objective value


# -- the loop ----------------------------------------------------------------

def _default_fingerprint(label: str) -> dict:
    """Cache-key material for an objective with no integrator behind a
    flight recorder: the same :data:`~ibamr_tpu.serve.aot_cache.
    KEY_FIELDS` vocabulary, with the design label as the config digest
    (two different objectives never share an executable)."""
    return {
        "config_digest": f"design:{label}",
        "integrator": "design_loop",
        "engine": None,
        "spectral_dtype": None,
        "mesh": None,
        "mesh_shape": None,
        "x64": bool(jax.config.jax_enable_x64),
        "platform": jax.default_backend(),
        "device_count": jax.device_count(),
        "jax_version": jax.__version__,
    }


class DesignLoop:
    """Gradient-based optimization of a differentiable rollout objective.

    ``objective(params) -> scalar`` must be pure traced JAX — build the
    coupled method INSIDE it so design parameters flow into the physics
    (see ``design.eel_gait`` / ``design.cantilever``), advance with
    ``lax.scan`` over a :func:`~ibamr_tpu.utils.hierarchy_driver.
    checkpointed_step`-wrapped step when the rollout is long, and never
    request buffer donation (``jitted_step(donate=True)`` REFUSES under
    a cotangent trace for exactly this use).
    """

    def __init__(self, objective: Callable[[Params], jnp.ndarray],
                 params0: Params, *, lr: float = 1e-2,
                 b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                 remat: Optional[str] = None,
                 cache: Optional[ExecutableCache] = None,
                 label: str = "design",
                 fingerprint: Optional[dict] = None):
        if remat is not None:
            # early, loud validation (same vocabulary as RunConfig)
            from ibamr_tpu.utils.hierarchy_driver import REMAT_POLICIES
            if remat not in REMAT_POLICIES:
                raise ValueError(
                    f"DesignLoop remat must be one of "
                    f"{sorted(REMAT_POLICIES)} or None, got {remat!r}")
        self.objective = objective
        self.params0 = params0
        self.lr = float(lr)
        self.b1, self.b2, self.eps = float(b1), float(b2), float(eps)
        self.remat = remat
        self.cache = cache if cache is not None else get_cache()
        self.label = label
        self._fp = dict(fingerprint) if fingerprint is not None \
            else _default_fingerprint(label)

    # -- pieces ----------------------------------------------------------
    def value_and_grad_fn(self) -> Callable:
        """``params -> (value, grads)`` — the raw adjoint pass, for
        external optimizers (L-BFGS line searches) and FD checks. With
        ``remat`` set the whole objective is checkpointed under that
        policy (coarse-grained; rollouts get finer control by wrapping
        their scan body via ``checkpointed_step`` themselves)."""
        obj = self.objective
        if self.remat is not None:
            from ibamr_tpu.utils.hierarchy_driver import checkpointed_step
            obj = checkpointed_step(obj, self.remat)
        return jax.value_and_grad(obj)

    def iterate_fn(self) -> Callable:
        """The fused ``(params, opt, lr) -> (params', opt', value,
        grad_norm)`` python callable the cache lowers — value_and_grad
        plus the Adam update in ONE executable."""
        vg = self.value_and_grad_fn()
        b1, b2, eps = self.b1, self.b2, self.eps

        def iterate(params, opt, lr):
            value, grads = vg(params)
            new_params, new_opt = adam_update(params, grads, opt, lr,
                                              b1=b1, b2=b2, eps=eps)
            return new_params, new_opt, value, global_norm(grads)

        return iterate

    def executable(self, params: Params, opt: AdamState, lr):
        """Get-or-AOT-compile the fused iterate for this aval family
        through the executable cache as ``kind: grad_chunk`` (the seam
        PR 11 reserved). Returns ``(callable, entry)`` exactly like
        ``cached_step``."""
        args = (params, opt, lr)
        extra = {"kind": "grad_chunk", "label": self.label,
                 "args": arg_signature(args)}
        entry = self.cache.get_or_compile(
            self._fp, lambda: aot_compile(self.iterate_fn(), args),
            extra=extra, label=f"design/{self.label}")
        return entry.executable, entry

    # -- run -------------------------------------------------------------
    def run(self, num_iters: int, params: Optional[Params] = None,
            opt: Optional[AdamState] = None) -> DesignResult:
        """``num_iters`` Adam iterations; per-iteration wall and
        cache-stat deltas recorded in the history and emitted as
        ``design_iter`` ledger records (``obs.py summary`` renders
        them). ``history[i].objective`` is f(params) BEFORE update i —
        strict decrease across entries means every update helped."""
        params = self.params0 if params is None else params
        lr = jnp.asarray(
            self.lr,
            jax.tree_util.tree_leaves(params)[0].dtype)
        opt = adam_init(params) if opt is None else opt
        history = []
        for i in range(int(num_iters)):
            s0 = self.cache.stats()
            t0 = time.perf_counter()
            # the lookup is INSIDE the timed region on purpose: a warm
            # iteration's wall includes proving the cache serves it
            exe, _entry = self.executable(params, opt, lr)
            params, opt, value, gnorm = exe(params, opt, lr)
            jax.block_until_ready(value)
            wall = time.perf_counter() - t0
            s1 = self.cache.stats()
            it = DesignIter(
                iteration=i, objective=float(value),
                grad_norm=float(gnorm), wall_s=wall,
                cache_hits=int(s1["hits"] - s0["hits"]),
                cache_misses=int(s1["misses"] - s0["misses"]))
            history.append(it)
            _obs.emit("design_iter", label=self.label,
                      iteration=it.iteration, objective=it.objective,
                      grad_norm=it.grad_norm, wall_s=it.wall_s,
                      cache_hits=it.cache_hits,
                      cache_misses=it.cache_misses)
        return DesignResult(params=params, history=tuple(history),
                            objective=history[-1].objective
                            if history else float("nan"))
