"""Eel2d gait optimization: differentiate swim distance through the solver.

An anguilliform swimmer as a ConstraintIB body (momentum-projection
coupling, P16): the gait is a PRESCRIBED deformational velocity — a
traveling wave of lateral motion whose amplitude grows toward the tail
— and the body's rigid motion is left entirely free, so any net
displacement is hydrodynamic thrust recovered by the momentum
projection, not kinematic bookkeeping. The design parameters
(amplitude, frequency, wavenumber) are traced THROUGH the rollout:
``ConstraintIBMethod`` is constructed inside the objective so the gait
closure carries tracers into every spread/interp/FFT of every step.

Objective: the swim displacement ``mean_x(X_T) - mean_x(X_0)``. The
wave travels head→tail (+x), so thrust drives the body toward -x;
MINIMIZING the objective means swimming farther. Three Adam iterations
on the tiny config strictly decrease it (pinned by the design-smoke
drill, dryrun path 23).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.cib import RigidBodies
from ibamr_tpu.integrators.constraint_ib import ConstraintIBMethod
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.utils.hierarchy_driver import checkpointed_step


def build_eel(ns: int = 33, L: float = 0.5,
              center=(0.55, 0.5), dtype=jnp.float32
              ) -> Tuple[jnp.ndarray, jnp.ndarray, float]:
    """Straight horizontal filament of ``ns`` markers: head at
    ``center[0] - L/2``, tail at ``+L/2``. Returns ``(X0, s, L)`` with
    ``s`` the head-to-tail arclength coordinate."""
    s = jnp.linspace(0.0, L, ns, dtype=dtype)
    X0 = jnp.stack([center[0] - L / 2 + s,
                    jnp.full((ns,), center[1], dtype=dtype)], axis=1)
    return X0, s, float(L)


def build_eel_gait_problem(n: int = 32, ns: int = 33,
                           num_steps: int = 20, dt: float = 2e-3,
                           mu: float = 0.01, L: float = 0.5,
                           dtype=jnp.float32,
                           remat: Optional[str] = "full",
                           ) -> Tuple[Callable, dict]:
    """``(objective, params0)`` for a :class:`~ibamr_tpu.design.loop.
    DesignLoop`. ``objective(params)`` rolls the swimmer ``num_steps``
    forward under the gait ``params`` and returns the (signed) swim
    displacement; ``remat`` checkpoints the per-step body so the
    reverse pass stores one state per step instead of every
    intermediate field."""
    grid = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(grid, rho=1.0, mu=mu, dtype=dtype)
    X0, s, L = build_eel(ns=ns, L=L, dtype=dtype)
    bodies = RigidBodies(body_id=jnp.zeros((ns,), jnp.int32), n_bodies=1)

    def objective(params):
        A0, omega, k = params["A0"], params["omega"], params["k"]

        def gait(t, X):
            # traveling-wave lateral VELOCITY with a tail-growing
            # amplitude envelope: y(s,t) = A0 (s/L) sin(k s - omega t)
            # differentiated in t (the method projects out any rigid
            # component automatically)
            phase = k * s - omega * t
            uy = -(A0 * s / L) * omega * jnp.cos(phase)
            return jnp.stack([jnp.zeros_like(uy), uy], axis=1)

        # constructed INSIDE the trace: the gait closure carries the
        # design tracers into the physics of every step
        method = ConstraintIBMethod(ins, bodies, deformation_fn=gait)
        st = method.initialize(X0)
        com0 = jnp.mean(st.X[:, 0])
        step = method.step if remat is None \
            else checkpointed_step(method.step, remat)

        def body(carry, _):
            return step(carry, dt), None

        out, _ = jax.lax.scan(body, st, None, length=num_steps)
        return jnp.mean(out.X[:, 0]) - com0

    params0 = {"A0": jnp.asarray(0.08, dtype),
               "omega": jnp.asarray(8.0, dtype),
               "k": jnp.asarray(2.0 * jnp.pi / L, dtype)}
    return objective, params0
