"""AST-level jit-safety linter for ``ibamr_tpu/``.

The graph censuses (:mod:`~ibamr_tpu.analysis.graph_census`) audit the
artifacts we KNOW to lower; this linter audits the source for the
mistakes that prevent lowering or silently poison it — the classic
jit-unsafety patterns:

- ``traced-branch``: Python ``if``/``while`` on a traced value inside
  a known-traced scope (a ``TracerBoolConversionError`` at best, a
  trace-time-frozen branch at worst). Structural tests (``is None``,
  ``isinstance``, ``hasattr``, ``callable``, ``len``, ``.shape`` /
  ``.ndim`` / ``.dtype`` access) are trace-time-static and exempt.
- ``tracer-cast``: ``float()`` / ``int()`` / ``bool()`` / ``.item()``
  / ``.tolist()`` / ``np.asarray()`` / ``np.array()`` on a traced
  value — a forced host sync (or a trace error) in the hot path.
- ``time-capture``: ``time.*`` / ``random.*`` / ``np.random.*`` calls
  inside a traced scope — the value freezes at trace time and silently
  replays from the executable cache forever after.
- ``mutable-default``: mutable default argument on a traced function —
  the default is evaluated once and shared across every trace.

A *known-traced scope* is a function that is (a) decorated with
``jit`` / ``jax.jit`` / ``partial(jax.jit, ...)``, (b) passed by name
or as a lambda to a tracing entry point (``jax.jit``, ``vmap``,
``pmap``, ``grad``, ``checkpoint`` / ``remat``, ``lax.scan`` /
``while_loop`` / ``cond`` / ``switch`` / ``fori_loop`` / ``map``,
``custom_vjp``) within its enclosing function, or (c) nested inside a
traced scope (it runs at trace time). Method references like
``jax.jit(self.step)`` are intentionally out of scope for the AST pass
— the graph censuses cover those paths at lowering time.

Waiver syntax (inline, same line or the line directly above)::

    x = float(eps)  # jitlint: ok(tracer-cast): eps is a static config scalar

The justification after the colon is REQUIRED — a bare waiver is
itself reported (``bad-waiver``) and cannot be waived. The report
carries a waiver inventory so every exemption stays auditable.

CLI: ``python -m ibamr_tpu.analysis.jit_lint [paths...] [--json]``.
Exit 0 when no unwaived findings, 1 otherwise.
"""

from __future__ import annotations

import argparse
import ast
import io
import json
import os
import re
import sys
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

RULES = ("traced-branch", "tracer-cast", "time-capture",
         "mutable-default", "bad-waiver")

# decorators that make the decorated def a traced scope
_JIT_DECOS = {"jit", "filter_jit"}
# call targets whose function-valued args become traced scopes
_TRACE_ENTRY = {"jit", "vmap", "pmap", "grad", "value_and_grad",
                "checkpoint", "remat", "custom_vjp", "custom_jvp",
                "scan", "while_loop", "cond", "switch", "fori_loop",
                "map", "associated_scan", "associative_scan",
                # gradient entry points (PR 19): functions handed to
                # these are traced scopes exactly like jit/grad ones
                "vjp", "linearize", "jacfwd", "jacrev"}
# attribute / call results that are trace-time STATIC even on a tracer
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                 "itemsize", "weak_type"}
_STATIC_CALLS = {"isinstance", "hasattr", "callable", "len", "getattr",
                 "type", "str", "repr", "id", "format"}
_CAST_CALLS = {"float", "int", "bool", "complex"}
_CAST_METHODS = {"item", "tolist", "__float__", "__int__", "__bool__"}
_NUMPY_ALIASES = {"np", "numpy", "onp"}
_CLOCK_FUNCS = {"time", "perf_counter", "monotonic", "process_time",
                "time_ns", "perf_counter_ns", "monotonic_ns"}

_WAIVER_RE = re.compile(
    r"#\s*jitlint:\s*ok\(([a-z-]+)\)(?::\s*(\S.*))?")


@dataclass
class Finding:
    path: str
    line: int
    rule: str
    message: str
    waived: bool = False

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line,
                "rule": self.rule, "message": self.message,
                "waived": self.waived}


@dataclass
class Waiver:
    path: str
    line: int
    rule: str
    reason: Optional[str]
    used: bool = False

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line,
                "rule": self.rule, "reason": self.reason,
                "used": self.used}


def _dotted(node) -> str:
    """``a.b.c`` for Name/Attribute chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _call_basename(call: ast.Call) -> str:
    """Last path component of a call target (``jax.lax.scan``->scan)."""
    d = _dotted(call.func)
    return d.rsplit(".", 1)[-1] if d else ""


def _stmt_exprs(st):
    """A statement's OWN expressions (not those of nested statements)."""
    for name, value in ast.iter_fields(st):
        if name in ("body", "orelse", "finalbody", "handlers"):
            continue
        for v in (value if isinstance(value, list) else [value]):
            if isinstance(v, ast.expr):
                yield v


class _TaintNames(ast.NodeVisitor):
    """Names referenced by an expression, minus trace-time-static
    subexpressions (``x.shape``, ``isinstance(x, ...)``, ...)."""

    def __init__(self):
        self.names: Set[str] = set()

    def visit_Name(self, node):
        self.names.add(node.id)

    def visit_Attribute(self, node):
        if node.attr in _STATIC_ATTRS:
            return                      # x.shape is static: stop here
        self.generic_visit(node)

    def visit_Call(self, node):
        if _call_basename(node) in _STATIC_CALLS:
            return                      # len(x)/isinstance(x,..) static
        self.generic_visit(node)

    def visit_Compare(self, node):
        # `x is None` / `x is not None` are structural, not value tests
        if (len(node.ops) == 1
                and isinstance(node.ops[0], (ast.Is, ast.IsNot))
                and isinstance(node.comparators[0], ast.Constant)
                and node.comparators[0].value is None):
            return
        self.generic_visit(node)


def _expr_taint(expr, tainted: Set[str]) -> bool:
    v = _TaintNames()
    v.visit(expr)
    return bool(v.names & tainted)


class _FnScope:
    """One function-ish scope (FunctionDef / AsyncFunctionDef / Lambda)
    with its parent link and the set of callee names it passes into
    tracing entry points."""

    def __init__(self, node, parent: Optional["_FnScope"]):
        self.node = node
        self.parent = parent
        self.traced_callees: Set[str] = set()
        self.traced_lambdas: Set[int] = set()   # id() of Lambda nodes
        self.jit_decorated = False
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                base = deco.func if isinstance(deco, ast.Call) else deco
                name = _dotted(base).rsplit(".", 1)[-1]
                if name in _JIT_DECOS:
                    self.jit_decorated = True
                if (isinstance(deco, ast.Call)
                        and name in ("partial", "wraps")):
                    for a in deco.args:
                        if _dotted(a).rsplit(".", 1)[-1] in _JIT_DECOS:
                            self.jit_decorated = True

    def is_traced(self) -> bool:
        if self.jit_decorated:
            return True
        p = self.parent
        if p is None:
            return False
        if isinstance(self.node, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)) \
                and self.node.name in p.traced_callees:
            return True
        if isinstance(self.node, ast.Lambda) \
                and id(self.node) in p.traced_lambdas:
            return True
        return p.is_traced()            # trace-time nested scope


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, relpath: str):
        self.path = path
        self.relpath = relpath
        self.findings: List[Finding] = []
        self.scopes: Dict[int, _FnScope] = {}
        self.stack: List[_FnScope] = []

    # -- pass 1: scope graph + traced-callee marking -------------------
    def _enter(self, node):
        parent = self.stack[-1] if self.stack else None
        sc = _FnScope(node, parent)
        self.scopes[id(node)] = sc
        self.stack.append(sc)

    def visit_FunctionDef(self, node):
        self._enter(node)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._enter(node)
        self.generic_visit(node)
        self.stack.pop()

    def visit_Call(self, node):
        if self.stack and _call_basename(node) in _TRACE_ENTRY:
            sc = self.stack[-1]
            for a in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(a, ast.Name):
                    sc.traced_callees.add(a.id)
                elif isinstance(a, ast.Lambda):
                    sc.traced_lambdas.add(id(a))
        self.generic_visit(node)

    # -- pass 2 driver -------------------------------------------------
    def lint(self, tree):
        self.visit(tree)                # pass 1
        for sc in self.scopes.values():
            if sc.is_traced():
                self._lint_traced_scope(sc)
            if isinstance(sc.node, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                self._check_mutable_defaults(sc)

    def _emit(self, line, rule, msg):
        self.findings.append(Finding(self.relpath, line, rule, msg))

    # -- rules ---------------------------------------------------------
    def _check_mutable_defaults(self, sc):
        if not sc.is_traced():
            return
        node = sc.node
        defaults = list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None]
        for d in defaults:
            mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call)
                and _call_basename(d) in ("list", "dict", "set"))
            if mutable:
                self._emit(
                    d.lineno, "mutable-default",
                    f"traced function '{node.name}' has a mutable "
                    f"default argument — evaluated once, shared by "
                    f"every trace")

    def _params(self, node) -> Set[str]:
        a = node.args
        names = [p.arg for p in
                 a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return {n for n in names if n not in ("self", "cls")}

    def _lint_traced_scope(self, sc):
        node = sc.node
        tainted = self._params(node)
        if isinstance(node, ast.Lambda):
            self._check_exprs(node.body, tainted)
            return
        self._walk_stmts(node.body, tainted)

    def _walk_stmts(self, stmts, tainted):
        # statement-order taint propagation + rule checks, without
        # descending into nested defs/lambdas (they are linted as
        # their own scopes — their params shadow the outer taint)
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.If, ast.While)) \
                    and _expr_taint(st.test, tainted):
                kw = "if" if isinstance(st, ast.If) else "while"
                self._emit(
                    st.lineno, "traced-branch",
                    f"Python `{kw}` on a traced value in a traced "
                    f"scope — use lax.cond/select or hoist the test "
                    f"to trace time")
            for expr in _stmt_exprs(st):
                self._check_exprs(expr, tainted)
            # propagate taint through simple assignments / for targets
            if isinstance(st, (ast.Assign, ast.AugAssign,
                               ast.AnnAssign)) \
                    and getattr(st, "value", None) is not None \
                    and _expr_taint(st.value, tainted):
                targets = (st.targets if isinstance(st, ast.Assign)
                           else [st.target])
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
            elif isinstance(st, ast.For) \
                    and _expr_taint(st.iter, tainted):
                for n in ast.walk(st.target):
                    if isinstance(n, ast.Name):
                        tainted.add(n.id)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(st, attr, None)
                if isinstance(sub, list):
                    self._walk_stmts(
                        [s for s in sub if isinstance(s, ast.stmt)],
                        tainted)
            for h in getattr(st, "handlers", []) or []:
                self._walk_stmts(h.body, tainted)

    def _check_exprs(self, expr, tainted):
        # walk one expression, skipping Lambda subtrees (own scopes)
        stack = [expr]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Call):
                self._check_call(n, tainted)
            for c in ast.iter_child_nodes(n):
                if not isinstance(c, ast.Lambda):
                    stack.append(c)

    def _check_call(self, call, tainted):
        base = _call_basename(call)
        dotted = _dotted(call.func)
        root = dotted.split(".", 1)[0] if dotted else ""

        # tracer-cast: float(x)/int(x)/bool(x) on a tainted expr
        if base in _CAST_CALLS and dotted == base and call.args:
            if _expr_taint(call.args[0], tainted):
                self._emit(call.lineno, "tracer-cast",
                           f"`{base}()` on a traced value forces a "
                           f"host sync (or a TracerConversionError)")
        # tracer-cast: np.asarray/np.array on a tainted expr
        if root in _NUMPY_ALIASES and base in ("asarray", "array") \
                and call.args and _expr_taint(call.args[0], tainted):
            self._emit(call.lineno, "tracer-cast",
                       f"`{dotted}()` on a traced value pulls the "
                       f"buffer to host inside the traced scope")
        # tracer-cast: x.item()/x.tolist() on a tainted receiver
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _CAST_METHODS \
                and _expr_taint(call.func.value, tainted):
            self._emit(call.lineno, "tracer-cast",
                       f"`.{call.func.attr}()` on a traced value "
                       f"forces a host sync inside the traced scope")
        # time-capture: wall clock / host RNG frozen at trace time
        if root == "time" and base in _CLOCK_FUNCS:
            self._emit(call.lineno, "time-capture",
                       f"`{dotted}()` in a traced scope freezes at "
                       f"trace time and replays from the executable "
                       f"cache")
        if (root == "random"
                or dotted.startswith(tuple(
                    f"{a}.random." for a in _NUMPY_ALIASES))):
            self._emit(call.lineno, "time-capture",
                       f"`{dotted}()` host RNG in a traced scope "
                       f"freezes at trace time — use jax.random with "
                       f"an explicit key")


def _collect_waivers(relpath: str, source: str) -> List[Waiver]:
    # scan COMMENT tokens, not raw lines: a waiver shown inside a
    # docstring (e.g. this module's own syntax example) must stay inert
    out = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [(t.start[0], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError):
        comments = [(i, line) for i, line in
                    enumerate(source.splitlines(), start=1)
                    if line.lstrip().startswith("#")]
    for lineno, text in comments:
        m = _WAIVER_RE.search(text)
        if m:
            out.append(Waiver(relpath, lineno, m.group(1),
                              (m.group(2) or "").strip() or None))
    return out


def lint_file(path: str, relpath: Optional[str] = None) -> Tuple[
        List[Finding], List[Waiver]]:
    relpath = relpath or path
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return ([Finding(relpath, e.lineno or 0, "bad-waiver",
                         f"file does not parse: {e.msg}")], [])
    linter = _Linter(path, relpath)
    linter.lint(tree)
    waivers = _collect_waivers(relpath, source)

    # bad-waiver: missing justification or unknown rule name
    findings = linter.findings
    for w in waivers:
        if w.rule not in RULES:
            findings.append(Finding(
                relpath, w.line, "bad-waiver",
                f"waiver names unknown rule '{w.rule}'"))
        elif not w.reason:
            findings.append(Finding(
                relpath, w.line, "bad-waiver",
                "waiver carries no justification — write "
                "`# jitlint: ok(<rule>): <why this is safe>`"))

    # apply waivers (same line or the line directly above the finding)
    by_key = {}
    for w in waivers:
        if w.rule in RULES and w.reason:
            by_key.setdefault((w.rule, w.line), w)
    for f in findings:
        if f.rule == "bad-waiver":
            continue                    # not waivable
        w = by_key.get((f.rule, f.line)) or by_key.get(
            (f.rule, f.line - 1))
        if w is not None:
            f.waived = True
            w.used = True
    return findings, waivers


def lint_paths(paths) -> dict:
    """Lint every ``.py`` under ``paths``; returns the report dict."""
    files = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__",)]
                files.extend(os.path.join(dirpath, fn)
                             for fn in sorted(filenames)
                             if fn.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    findings: List[Finding] = []
    waivers: List[Waiver] = []
    root = os.getcwd()
    for path in files:
        rel = os.path.relpath(path, root)
        fs, ws = lint_file(path, rel)
        findings.extend(fs)
        waivers.extend(ws)
    active = [f for f in findings if not f.waived]
    return {
        "files_scanned": len(files),
        "findings": [f.to_dict() for f in findings],
        "active_findings": len(active),
        "waived_findings": len(findings) - len(active),
        "waivers": [w.to_dict() for w in waivers],
    }


def format_report(report: dict) -> str:
    lines = [f"jit-lint: {report['files_scanned']} files, "
             f"{report['active_findings']} finding(s), "
             f"{report['waived_findings']} waived"]
    for f in report["findings"]:
        if f["waived"]:
            continue
        lines.append(f"  {f['path']}:{f['line']}: [{f['rule']}] "
                     f"{f['message']}")
    ws = [w for w in report["waivers"] if w["used"]]
    if ws:
        lines.append("waiver inventory:")
        for w in ws:
            lines.append(f"  {w['path']}:{w['line']}: ok({w['rule']}) "
                         f"— {w['reason']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="jit-safety linter for ibamr_tpu")
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(
                        os.path.dirname(os.path.dirname(
                            os.path.dirname(os.path.abspath(
                                __file__)))), "ibamr_tpu")])
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    report = lint_paths(args.paths)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(format_report(report))
    return 1 if report["active_findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
