"""Census primitives over lowered artifacts (jaxpr + compiled HLO).

These generalize the one-off censuses that grew inside
``tools/hlo_cost_audit.py`` (scatter census, FFT-primitive census,
dot-operand census) into reusable pure functions, and add the three the
contract gate needs that the bench artifact never measured:

- :func:`convert_census` — dtype-promotion census: every
  ``convert_element_type`` by (src -> dst) pair, with the two smells
  flagged explicitly: *f64 widenings* (a narrower float silently
  upcast to f64 — the classic x64-leak that doubles HBM traffic on
  chip) and *round-trip chains* (x -> wider -> x, two converts that
  compute nothing; the deliberate mixed-precision rounding pattern
  f32 -> bf16 -> f32 goes through a NARROWER dtype and is not
  flagged);
- :func:`host_transfer_census` — callback/infeed/outfeed primitives,
  split by whether they sit inside a ``scan``/``while`` body, where
  each one forces a per-iteration device->host round trip that
  serializes the whole chunk;
- :func:`donation_census` — parses the compiled module's
  ``input_output_alias`` table, so ``donate_argnums`` stops being a
  *request* and becomes a *verified* property of the executable.

Everything here is backend-independent and pure: callers hand in a
jaxpr (``jax.make_jaxpr``) or optimized-HLO text
(``compiled.as_text()``); nothing in this module forces a backend,
spawns processes, or touches the registry. ``tools/hlo_cost_audit.py``
(the bench artifact) and ``tools/graph_audit.py`` (the CI gate) both
consume these functions, so the two can never disagree on counting
rules.
"""

from __future__ import annotations

import re
from typing import Iterator, Optional, Tuple

__all__ = [
    "iter_eqns", "fft_census", "dot_census", "convert_census",
    "host_transfer_census", "collective_census", "overlap_census",
    "structural_overlap_census",
    "hlo_op_counts", "op_class_counts",
    "donation_census", "graph_census", "budget_metrics",
]


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

# primitives whose sub-jaxpr executes per loop iteration: anything
# found inside counts as "inside a scan body" for the host-transfer
# budget (a callback there fires every step, not once per chunk)
_LOOP_PRIMS = {"scan", "while"}

# callback-family primitives: each is a host round trip at run time
# (debug_callback covers jax.debug.print too; infeed/outfeed are the
# raw transfer prims some jax versions lower callbacks to)
_HOST_PRIMS = {"debug_callback", "pure_callback", "io_callback",
               "callback", "outside_call", "infeed", "outfeed"}


def _sub_jaxprs(params) -> Iterator[Tuple[str, object]]:
    """(param_name, jaxpr) for every sub-jaxpr in an eqn's params —
    ClosedJaxpr, raw Jaxpr, or tuples/lists of either (cond branches)."""
    for name, v in params.items():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for w in vs:
            if hasattr(w, "jaxpr"):          # ClosedJaxpr
                yield name, w.jaxpr
            elif hasattr(w, "eqns"):         # raw Jaxpr
                yield name, w


def iter_eqns(jaxpr, in_loop: bool = False):
    """Yield ``(eqn, in_loop)`` for every equation reachable from
    ``jaxpr``, recursing into sub-jaxprs. ``in_loop`` is True once the
    walk has entered the body of a ``scan``/``while`` (the body runs
    per iteration; a ``cond`` branch or inner ``pjit`` inherits its
    enclosing context)."""
    for eqn in jaxpr.eqns:
        yield eqn, in_loop
        child_in_loop = in_loop or eqn.primitive.name in _LOOP_PRIMS
        for _, sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub, child_in_loop)


# ---------------------------------------------------------------------------
# jaxpr censuses
# ---------------------------------------------------------------------------

def fft_census(jaxpr, max_transforms: int = 32) -> dict:
    """Batched-FFT call count + operand bytes at the jaxpr primitive
    level. Primitive-level on purpose: the CPU backend lowers
    ``lax.fft`` to a ducc custom-call an HLO-text census cannot see,
    while the primitive count is exactly the number of batched FFT
    calls the TPU backend will also issue."""
    out = {"fft_ops": 0, "fft_bytes": 0, "fft_transforms": []}
    for eqn, _ in iter_eqns(jaxpr):
        if eqn.primitive.name != "fft":
            continue
        iv, ov = eqn.invars[0].aval, eqn.outvars[0].aval
        ib, ob = (iv.size * iv.dtype.itemsize,
                  ov.size * ov.dtype.itemsize)
        out["fft_ops"] += 1
        out["fft_bytes"] += ib + ob
        if len(out["fft_transforms"]) < max_transforms:
            out["fft_transforms"].append({
                "kind": str(eqn.params.get("fft_type")),
                "in_shape": list(iv.shape),
                "in_bytes": ib, "out_bytes": ob})
    return out


def dot_census(jaxpr) -> dict:
    """Operand/output bytes + FLOPs of every ``dot_general`` — the
    (B,cap,P)/(B,cap,nz) contraction operands are the transfer engines'
    claimed dominant traffic, and their traced dtypes/shapes show
    exactly what occupancy packing and bf16 compression do to them."""
    out = {"dot_lhs_bytes": 0, "dot_rhs_bytes": 0, "dot_out_bytes": 0,
           "dot_count": 0, "dot_flops": 0}
    for eqn, _ in iter_eqns(jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
        outv = eqn.outvars[0].aval
        out["dot_lhs_bytes"] += lhs.size * lhs.dtype.itemsize
        out["dot_rhs_bytes"] += rhs.size * rhs.dtype.itemsize
        out["dot_out_bytes"] += outv.size * outv.dtype.itemsize
        contracted = 1
        for ax in eqn.params["dimension_numbers"][0][0]:
            contracted *= lhs.shape[ax]
        out["dot_flops"] += 2 * outv.size * contracted
        out["dot_count"] += 1
    return out


def scatter_gather_census(jaxpr) -> dict:
    """Scatter/gather counts at the jaxpr PRIMITIVE level.

    Primitive-level on purpose (like :func:`fft_census`): the XLA CPU
    scatter expander rewrites small scatters into while-loops of
    dynamic-update-slices BEFORE the optimized HLO, so an HLO-text
    scatter budget audited on the CPU backend would be vacuously zero.
    The primitive count is what the TPU backend's serial scatter
    penalty is charged on — the observable the zero-scatter engines
    exist to eliminate."""
    out = {"scatter_prims": 0, "gather_prims": 0}
    for eqn, _ in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name.startswith("scatter"):
            out["scatter_prims"] += 1
        elif name == "gather":
            out["gather_prims"] += 1
    return out


def _is_float(dtype) -> bool:
    return dtype.kind == "f" or dtype.name == "bfloat16"


def _width(dtype) -> int:
    return int(dtype.itemsize)


def convert_census(jaxpr) -> dict:
    """Dtype-promotion census over every ``convert_element_type``.

    Returns::

        {"convert_ops": total count,
         "convert_pairs": {"f32->f64": n, ...},
         "f64_widenings": count of float converts widening INTO f64,
         "weak_widenings": of those, the weak-typed ones (a Python
                           scalar/np default leaked into the graph),
         "roundtrip_chains": count of x -> wider -> x chains,
         "widening_sites": [up to 16 {src, dst, shape} records]}

    The deliberate mixed-precision *rounding* pattern
    (``x.astype(bf16).astype(f32)`` — through a NARROWER dtype) is not
    flagged; ``bf16 -> f32 -> bf16`` (through a WIDER dtype, two
    converts that compute nothing) is.
    """
    pairs: dict = {}
    f64_widenings = 0
    weak_widenings = 0
    roundtrips = 0
    sites = []
    # var id -> source dtype of the convert that produced it (chain
    # detection: convert(convert(x)) landing back on x's dtype through
    # a wider intermediate)
    produced_from: dict = {}
    for eqn, _ in iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = eqn.invars[0].aval.dtype
        dst = eqn.outvars[0].aval.dtype
        key = f"{src.name}->{dst.name}"
        pairs[key] = pairs.get(key, 0) + 1
        if _is_float(src) and _is_float(dst) and _width(dst) > _width(src):
            if dst.name == "float64":
                f64_widenings += 1
                if bool(eqn.params.get("weak_type", False)):
                    weak_widenings += 1
                if len(sites) < 16:
                    sites.append({"src": src.name, "dst": dst.name,
                                  "shape": list(eqn.invars[0].aval.shape)})
        grand_src = produced_from.get(id(eqn.invars[0]))
        if (grand_src is not None and grand_src == dst
                and _width(src) > _width(dst)):
            # x -> wider -> x: the wider hop computed nothing
            roundtrips += 1
        produced_from[id(eqn.outvars[0])] = src
    return {"convert_ops": sum(pairs.values()),
            "convert_pairs": pairs,
            "f64_widenings": f64_widenings,
            "weak_widenings": weak_widenings,
            "roundtrip_chains": roundtrips,
            "widening_sites": sites}


def host_transfer_census(jaxpr) -> dict:
    """Callback/infeed/outfeed census, split by loop context.

    ``in_scan`` is the budgeted number: a callback inside a
    ``scan``/``while`` body fires once per ITERATION — a per-step
    device->host sync that serializes the chunk the driver exists to
    keep device-resident. Gated debug paths (pad-inertness checks,
    ``record_stats=True`` solve taps) are trace-time gated, so they
    contribute zero here unless someone turns them on in the artifact
    being audited."""
    out = {"host_transfers": 0, "host_transfers_in_scan": 0,
           "host_transfer_prims": {}}
    for eqn, in_loop in iter_eqns(jaxpr):
        if eqn.primitive.name not in _HOST_PRIMS:
            continue
        out["host_transfers"] += 1
        if in_loop:
            out["host_transfers_in_scan"] += 1
        k = eqn.primitive.name + (":scan" if in_loop else "")
        out["host_transfer_prims"][k] = \
            out["host_transfer_prims"].get(k, 0) + 1
    return out


# the explicit cross-device primitives jax traces into a jaxpr.
# psum appears only where the program ASKS for it (shard_map bodies,
# pmapped code); the psums GSPMD inserts to implement a sharded jnp
# reduction materialize at partitioning time and are visible only in
# HLO (the collective_ops op-class and :func:`overlap_census`).
_COLLECTIVE_PRIMS = ("ppermute", "psum", "all_gather", "all_to_all",
                     "pbroadcast")


def collective_census(jaxpr) -> dict:
    """Per-primitive count + bytes census of the explicit collectives.

    Primitive-level on purpose (the :func:`scatter_gather_census`
    argument): backend partitioners rewrite, fuse, and batch
    collectives before optimized HLO — CPU lowers them synchronously,
    TPU splits them into start/done pairs — while the jaxpr primitive
    count is exactly the number of cross-device exchanges the program
    *asked* for, identical on every backend.

    Bytes are the sum of each collective's OUTPUT aval sizes — the
    per-shard payload a device materializes from its peers per
    execution (for ``psum``/``ppermute``/``pbroadcast`` this equals
    the input payload; for ``all_gather`` it is the gathered result,
    ``axis_size`` times the input). Inside a ``shard_map`` body avals
    are per-shard, so the numbers read as per-device traffic — the
    operand the roofline join divides by ``comm_s``.
    """
    out = {"collective_prims": 0, "collective_bytes": 0}
    for p in _COLLECTIVE_PRIMS:
        out[f"{p}_prims"] = 0
        out[f"{p}_bytes"] = 0
    for eqn, _ in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name not in _COLLECTIVE_PRIMS:
            continue
        nbytes = sum(v.aval.size * v.aval.dtype.itemsize
                     for v in eqn.outvars)
        out[f"{name}_prims"] += 1
        out[f"{name}_bytes"] += nbytes
        out["collective_prims"] += 1
        out["collective_bytes"] += nbytes
    return out


# the data-MOVING collectives for the structural pipeline census.
# pbroadcast is excluded on purpose: it is shard_map's replication
# annotation, lowered to nothing on device — its hundreds of sites
# would swamp the fraction the pipelined exchanges actually move.
_MOVING_COLLECTIVE_PRIMS = ("ppermute", "psum", "all_gather",
                            "all_to_all")

# primitives that are pure layout/bookkeeping, not schedulable
# compute: a window containing only these hides no link latency
_LAYOUT_PRIMS = {"reshape", "broadcast_in_dim", "squeeze", "transpose",
                 "convert_element_type", "copy", "slice",
                 "sharding_constraint", "pbroadcast"}


def structural_overlap_census(jaxpr, max_sites: int = 16) -> dict:
    """Structural hidden/unhidden census at the jaxpr level.

    The HLO :func:`overlap_census` sees only what one backend's
    scheduler DID (the CPU backend lowers every collective
    synchronously, so it reports zero pairs on the CI mesh); this
    census measures what the traced program makes POSSIBLE, identically
    on every backend: a data-moving collective (`ppermute`/`psum`/
    `all_gather`/`all_to_all` — NOT `pbroadcast`, a no-traffic
    replication annotation) counts as **hidden** when at least one
    independent schedulable compute equation sits between its issue
    site and its first consumer in trace order. Such a window is
    exactly what lets a latency-hiding scheduler keep the transfer in
    flight behind real work; an empty (or layout/collective-only)
    window pins the exchange to the critical path on every backend.

    Windows are computed per jaxpr body (trace order within a body is
    the schedulable order; a collective whose result is a body OUTPUT
    gets the remainder of the body as its window). Returns::

        {"structural_collectives": data-moving collectives seen,
         "hidden_collectives": with >=1 compute eqn in the window,
         "unhidden_collectives": with an empty/bookkeeping-only window,
         "hidden_fraction": int percent (100 when no collectives),
         "unhidden_sites": [up to max_sites {prim, window_eqns}]}
    """
    out = {"structural_collectives": 0, "hidden_collectives": 0,
           "unhidden_collectives": 0, "unhidden_sites": []}

    def walk(jx):
        eqns = list(jx.eqns)
        for i, eqn in enumerate(eqns):
            for _, sub in _sub_jaxprs(eqn.params):
                walk(sub)
            name = eqn.primitive.name
            if name not in _MOVING_COLLECTIVE_PRIMS:
                continue
            produced = {id(v) for v in eqn.outvars}
            first_use = len(eqns)
            for j in range(i + 1, len(eqns)):
                if any(id(v) in produced for v in eqns[j].invars):
                    first_use = j
                    break
            compute = 0
            for k in range(i + 1, first_use):
                kn = eqns[k].primitive.name
                if (kn in _LAYOUT_PRIMS
                        or kn in _MOVING_COLLECTIVE_PRIMS):
                    continue
                compute += 1
            out["structural_collectives"] += 1
            if compute:
                out["hidden_collectives"] += 1
            else:
                out["unhidden_collectives"] += 1
                if len(out["unhidden_sites"]) < max_sites:
                    out["unhidden_sites"].append(
                        {"prim": name,
                         "window_eqns": first_use - i - 1})

    walk(jaxpr)
    tot = out["structural_collectives"]
    out["hidden_fraction"] = (
        100 * out["hidden_collectives"] // tot if tot else 100)
    return out


# ---------------------------------------------------------------------------
# HLO-text censuses
# ---------------------------------------------------------------------------

def hlo_op_counts(text: str) -> dict:
    """Opcode census of an optimized-HLO dump (``compiled.as_text()``).

    Quoted metadata (op_name/source strings) can contain anything,
    including op-like tokens — strip quoted spans per line BEFORE
    matching, then take the first ``opcode(`` token on the RHS of each
    ``=`` assignment. Backend-independent: the census runs on whatever
    module the caller compiled. tests/test_forces_hlo.py uses it to pin
    the zero-scatter force-assembly guarantee.
    """
    counts: dict = {}
    for line in text.splitlines():
        if "=" not in line:
            continue
        rhs = re.sub(r'"[^"]*"', '""', line.split("=", 1)[1])
        m = re.search(r"\b([a-z][a-z0-9_.-]*)\s*\(", rhs)
        if m:
            counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    return counts


# opcode prefix -> budget class. ``fusion``/arithmetic opcodes are
# deliberately unclassified: their counts are backend fusion decisions,
# not graph contracts.
_OP_CLASSES = (
    ("scatter", "scatter_ops"),
    ("gather", "gather_ops"),
    ("all-gather", "collective_ops"),
    ("all-reduce", "collective_ops"),
    ("all-to-all", "collective_ops"),
    ("collective-permute", "collective_ops"),
    ("custom-call", "custom_calls"),
    ("convert", "convert_hlo_ops"),
    ("fft", "fft_hlo_ops"),
)


def op_class_counts(ops) -> dict:
    """Bucket an opcode census (:func:`hlo_op_counts` output, or raw
    HLO text) into the contract classes. ``gather`` excludes
    ``all-gather`` (a collective, not an addressing op)."""
    if isinstance(ops, str):
        ops = hlo_op_counts(ops)
    out = {cls: 0 for _, cls in _OP_CLASSES}
    for op, n in ops.items():
        # longest-prefix match so "all-gather" never lands in gather_ops
        best = None
        for prefix, cls in _OP_CLASSES:
            if op.startswith(prefix):
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, cls)
        if best is not None:
            out[best[1]] += n
    return out


# async collective machinery in optimized HLO: `<op>-start` issues the
# transfer, the matching `<op>-done` blocks on it; XLA also wraps some
# collectives in generic `async-start`/`async-done` pairs.
_ASYNC_START_RE = re.compile(
    r"^(all-gather|all-reduce|all-to-all|collective-permute|"
    r"reduce-scatter|collective-broadcast|copy|send|recv|async)-start$")
_SYNC_COLLECTIVE_RE = re.compile(
    r"^(all-gather|all-reduce|all-to-all|collective-permute|"
    r"reduce-scatter|collective-broadcast)(\.|$)")
# opcodes that are bookkeeping, not schedulable compute: having only
# these between a start and its done hides nothing
_STRUCTURAL_OPS = {"parameter", "constant", "tuple", "get-tuple-element",
                   "bitcast", "bitcast-convert", "after-all", "domain"}

_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.-]+)\s*=")


def overlap_census(hlo_text: str) -> dict:
    """Census of collective/compute overlap in optimized HLO.

    Pairs every ``<collective>-start`` with the ``*-done`` that
    consumes it and counts the schedulable compute ops the scheduler
    placed BETWEEN them — the structural observable for async halo
    exchange: a pair with zero compute in the window (``unhidden``)
    pays its full link latency on the critical path. Synchronous
    collective ops (how the CPU backend — and an unscheduled TPU
    module — emit them) can never overlap and are counted separately
    as ``collective_sync_ops``.

    Returns::

        {"overlap_pairs": start/done pairs found,
         "overlap_hidden": pairs with >=1 compute op in the window,
         "overlap_unhidden": pairs with an empty window,
         "collective_sync_ops": synchronous collective ops,
         "overlap_sites": [up to 16 {op, compute_between}]}
    """
    # (line_idx, def_name, opcode) for every op-defining line, in
    # program order (HLO text lists each computation's ops in order)
    defs = []
    for idx, line in enumerate(hlo_text.splitlines()):
        if "=" not in line:
            continue
        dm = _DEF_RE.match(line)
        rhs = re.sub(r'"[^"]*"', '""', line.split("=", 1)[1])
        om = re.search(r"\b([a-z][a-z0-9_.-]*)\s*\(", rhs)
        if not (dm and om):
            continue
        defs.append((idx, dm.group(1), om.group(1), rhs))

    out = {"overlap_pairs": 0, "overlap_hidden": 0,
           "overlap_unhidden": 0, "collective_sync_ops": 0,
           "overlap_sites": []}
    # strip the .N instance suffix HLO appends to repeated opcodes
    base = lambda op: re.sub(r"\.\d+$", "", op)  # noqa: E731
    starts = {}          # def name -> (position in defs, opcode)
    for pos, (idx, name, op, rhs) in enumerate(defs):
        b = base(op)
        if _ASYNC_START_RE.match(b):
            starts[name] = (pos, op)
        elif b.endswith("-done"):
            # which start does this done consume?
            used = [s for s in starts
                    if re.search(r"%" + re.escape(s) + r"\b", rhs)]
            if not used:
                continue
            sname = used[0]
            spos, sop = starts.pop(sname)
            compute = 0
            for _, _, iop, _ in defs[spos + 1:pos]:
                ib = base(iop)
                if (ib in _STRUCTURAL_OPS or ib.endswith("-start")
                        or ib.endswith("-done")):
                    continue
                compute += 1
            out["overlap_pairs"] += 1
            if compute:
                out["overlap_hidden"] += 1
            else:
                out["overlap_unhidden"] += 1
            if len(out["overlap_sites"]) < 16:
                out["overlap_sites"].append(
                    {"op": sop, "compute_between": compute})
        elif _SYNC_COLLECTIVE_RE.match(b):
            out["collective_sync_ops"] += 1
    return out


_ALIAS_RE = re.compile(r"input_output_alias=\{((?:[^{}]|\{[^{}]*\})*)\}")
_ALIAS_ENTRY_RE = re.compile(
    r"\{[^{}]*\}\s*:\s*\(\s*(\d+)\s*,\s*\{[^{}]*\}\s*,\s*"
    r"(may-alias|must-alias)\s*\)")


def donation_census(hlo_text: str) -> dict:
    """Parse the compiled module's ``input_output_alias`` table.

    ``jax.jit(..., donate_argnums=...)`` is a *request*; whether XLA
    actually aliased each donated buffer to an output is recorded in
    the module header. Returns ``{"donated_args": <distinct aliased
    parameter count>, "donation_entries": <alias-table entries>}`` —
    the verified-donation observable the budgets pin (before this
    census, donation was requested everywhere and verified nowhere)."""
    m = _ALIAS_RE.search(hlo_text)
    if not m:
        return {"donated_args": 0, "donation_entries": 0}
    entries = _ALIAS_ENTRY_RE.findall(m.group(1))
    return {"donated_args": len({int(p) for p, _ in entries}),
            "donation_entries": len(entries)}


# ---------------------------------------------------------------------------
# the one-call composite census
# ---------------------------------------------------------------------------

def graph_census(fn, args, donate_argnums=()) -> dict:
    """Full census of one artifact: trace (jaxpr censuses) + compile on
    the CURRENT backend (HLO censuses + donation audit). Pure apart
    from the compile itself; callers choose the backend (the CI gate
    runs under ``JAX_PLATFORMS=cpu`` child processes — same HLO module
    structure as TPU, per tools/hlo_cost_audit.py)."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args)
    jfn = jax.jit(fn, donate_argnums=tuple(donate_argnums)) \
        if donate_argnums else jax.jit(fn)
    compiled = jfn.lower(*args).compile()
    text = compiled.as_text()
    ops = hlo_op_counts(text)
    out = {}
    out.update(op_class_counts(ops))
    out.update(scatter_gather_census(jaxpr.jaxpr))
    out.update(fft_census(jaxpr.jaxpr))
    out.update(dot_census(jaxpr.jaxpr))
    out.update(convert_census(jaxpr.jaxpr))
    out.update(host_transfer_census(jaxpr.jaxpr))
    out.update(collective_census(jaxpr.jaxpr))
    out.update(structural_overlap_census(jaxpr.jaxpr))
    out.update(overlap_census(text))
    out.update(donation_census(text))
    out["hlo_ops_total"] = sum(ops.values())
    return out


# the flat metrics a budget may pin. "max" metrics regress UP;
# "donated_args" is the one "min" metric (regresses DOWN — donation
# silently dropped by a refactor)
BUDGET_MAX_METRICS = (
    "scatter_ops", "scatter_prims", "fft_ops",
    "host_transfers_in_scan", "host_transfers", "f64_widenings",
    "weak_widenings", "roundtrip_chains", "convert_ops", "gather_ops",
    "custom_calls", "collective_ops", "dot_count",
    # PR 15: the comm layer. Per-primitive collective counts + bytes
    # (jaxpr level, backend-independent) and the HLO overlap census —
    # `overlap_unhidden` is the structural pin for async halo
    # exchange: an unhidden start/done pair pays full link latency.
    "collective_prims", "collective_bytes",
    "ppermute_prims", "ppermute_bytes", "psum_prims", "psum_bytes",
    "all_gather_prims", "all_gather_bytes",
    "all_to_all_prims", "all_to_all_bytes",
    "pbroadcast_prims", "pbroadcast_bytes",
    "overlap_pairs", "overlap_unhidden", "collective_sync_ops",
    # PR 16: the structural pipeline census — an unhidden data-moving
    # collective (empty issue->first-consumer window) serializes on
    # every backend, so its count is a ceiling.
    "unhidden_collectives",
)
# "min" metrics regress DOWN: donation silently dropped by a refactor,
# or a double-buffered pipeline collapsing back to a sync chain
# (hidden_fraction is the int percent of data-moving collectives with
# compute in their issue window — see structural_overlap_census).
BUDGET_MIN_METRICS = ("donated_args", "hidden_fraction")


def budget_metrics(census: dict) -> dict:
    """The budget-comparable slice of a :func:`graph_census` result."""
    keys = BUDGET_MAX_METRICS + BUDGET_MIN_METRICS
    return {k: int(census[k]) for k in keys if k in census}
